"""BASELINE config 2: GPT-2 data-parallel training (4-way dp mesh).

Reference equivalent: TorchTrainer + NCCL DDP over 4 GPU workers. Here the
data parallelism is a mesh axis: one jitted train step whose gradients
all-reduce over ICI (XLA-inserted), not a wrapper.

Run (CPU demo): JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=4 \
  python examples/train_gpt2_dp.py --debug
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import GPT2Config, GPT2Model
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.train.spmd import make_train_step, shard_batch


def main(debug: bool = True, steps: int = 5):
    n = len(jax.devices())
    mesh = build_mesh(MeshSpec.auto(n))           # all-dp mesh
    cfg = GPT2Config.debug() if debug else GPT2Config.gpt2_125m()
    model = GPT2Model(cfg, mesh=mesh)
    ts = make_train_step(model, mesh=mesh)
    params, opt = ts.init_fn(jax.random.key(0))

    rng = np.random.default_rng(0)
    B, S = max(4, n), min(128, cfg.max_seq_len)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32)
    batch = shard_batch((toks, jnp.roll(toks, -1, 1)), ts)

    for step in range(steps):
        params, opt, m = ts.step_fn(params, opt, batch)
        print(f"step {step}: loss={float(m['loss']):.4f}")
    return float(m["loss"])


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--debug", action="store_true", default=True)
    p.add_argument("--full", dest="debug", action="store_false")
    args = p.parse_args()
    main(debug=args.debug)
