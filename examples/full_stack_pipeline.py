"""End-to-end: Data -> Train -> Tune -> Serve on one runtime.

The canonical "AI libraries compose over the core" walkthrough
(reference capability: the Ray AIR examples — dataset ingest feeding a
trainer, a small HPO sweep, then serving the tuned model):

 1. ray_tpu.data builds a streaming dataset of (x, noisy 3x+1) pairs.
 2. JaxTrainer fits a linear model with a jitted SPMD train step,
    ingesting via iter_batches.
 3. Tuner sweeps the learning rate with the native TPE searcher.
 4. The best weights deploy as a Serve application; predictions flow
    through the asyncio HTTP ingress.

Run: python examples/full_stack_pipeline.py
"""

from __future__ import annotations


def main(samples: int = 512, trials: int = 4):
    import json
    import urllib.request

    import numpy as np

    import ray_tpu
    from ray_tpu import data, serve, train, tune
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    from ray_tpu.tune import TPESearcher, TuneConfig, Tuner

    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8})

    # 1. dataset: y = 3x + 1 (+ noise)
    rng = np.random.default_rng(0)
    xs = rng.uniform(-1, 1, samples).astype("float32")
    ys = 3.0 * xs + 1.0 + rng.normal(0, 0.05, samples).astype("float32")
    ds = data.from_items([{"x": float(a), "y": float(b)}
                          for a, b in zip(xs, ys)])

    # 2-3. trainer inside a Tune sweep over the learning rate
    def train_loop(config):
        import jax
        import jax.numpy as jnp

        lr = config["lr"]
        w = jnp.zeros(()), jnp.zeros(())

        @jax.jit
        def step(w, batch_x, batch_y):
            def loss_fn(wb):
                pred = wb[0] * batch_x + wb[1]
                return jnp.mean((pred - batch_y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(w)
            return ((w[0] - lr * grads[0], w[1] - lr * grads[1]),
                    loss)

        shard = train.get_dataset_shard("train")
        loss = None
        for _ in range(3):
            for batch in shard.iter_batches(batch_size=64,
                                            batch_format="numpy"):
                w, loss = step(w, jnp.asarray(batch["x"]),
                               jnp.asarray(batch["y"]))
        train.report({"loss": float(loss),
                      "w": float(w[0]), "b": float(w[1])})

    trainer = JaxTrainer(
        train_loop, train_loop_config={"lr": 0.1},
        scaling_config=ScalingConfig(num_workers=1),
        datasets={"train": ds},
        run_config=RunConfig(name="fullstack"))
    results = Tuner(
        trainer,
        param_space={"train_loop_config": {
            "lr": tune.loguniform(1e-2, 1.0)}},
        tune_config=TuneConfig(metric="loss", mode="min",
                               num_samples=trials,
                               max_concurrent_trials=2,
                               search_alg=TPESearcher(n_startup=2,
                                                      seed=0))).fit()
    if results.errors:
        raise RuntimeError(f"sweep trials failed: {results.errors}")
    best = results.get_best_result()
    best_lr = best.config["lr"]    # train_loop_config is flattened
    # re-fit at the tuned lr to obtain the weights: trial metrics
    # surface the tuned objective, so the production parameters come
    # from one direct fit (also the natural place to train longer than
    # the sweep's per-trial budget)
    final = JaxTrainer(
        train_loop, train_loop_config={"lr": best_lr},
        scaling_config=ScalingConfig(num_workers=1),
        datasets={"train": ds},
        run_config=RunConfig(name="fullstack-final")).fit()
    if final.error:
        raise RuntimeError(f"final fit failed: {final.error}")
    w, b = final.metrics["w"], final.metrics["b"]

    # 4. serve the tuned model over HTTP; the finally guarantees the
    # module-global proxy state is torn down even when the request
    # fails (a leaked proxy would poison later serve use in-process)
    @serve.deployment
    def predict(payload):
        x = float(payload["x"])
        return {"y": w * x + b}

    try:
        serve.run(predict.bind())
        port = serve.start_http_proxy(port=0)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/",
            data=json.dumps({"x": 0.5}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            served = json.loads(resp.read())["y"]
    finally:
        serve.shutdown()
        if own:
            ray_tpu.shutdown()
    return {"w": w, "b": b, "loss": final.metrics["loss"],
            "best_lr": best_lr, "served_prediction": served}


if __name__ == "__main__":
    out = main()
    print(out)
    assert abs(out["w"] - 3.0) < 0.5 and abs(out["b"] - 1.0) < 0.5
