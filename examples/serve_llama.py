"""BASELINE config 5: Llama continuous-batching serving (v5e-8 target;
debug model for the demo).

Reference equivalent: `ray.llm build_openai_app` wrapping vLLM. Here the
continuous-batching engine is in-tree (ray_tpu.llm.engine): slot-major
HBM KV cache, bucketed prefill, batched decode. Also demonstrates the
HTTP proxy plane.

Run: python examples/serve_llama.py
"""

import json
import urllib.request

import ray_tpu
from ray_tpu import serve
from ray_tpu.llm import LLMConfig, build_llm_app


def main():
    ray_tpu.init(num_nodes=1, ignore_reinit_error=True)
    app = build_llm_app(LLMConfig(model_id="llama-demo", max_slots=4,
                                  max_seq=256))
    handle = serve.run(app)

    # direct handle path
    out = handle.remote({"prompt": "hello tpu", "max_tokens": 8}).result()
    print("handle:", {k: out[k] for k in ("text", "finish_reason",
                                          "ttft_s")})

    # HTTP path
    port = serve.start_http_proxy(port=0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"prompt": "hi", "max_tokens": 4}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        print("http:", json.loads(resp.read())["finish_reason"])

    serve.shutdown()
    return out


if __name__ == "__main__":
    main()
