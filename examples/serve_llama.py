"""BASELINE config 5: Llama continuous-batching serving (v5e-8 target;
debug model for the demo).

Reference equivalent: `ray.llm build_openai_app` wrapping vLLM. Here the
continuous-batching engine is in-tree (ray_tpu.llm.engine): slot-major
HBM KV cache, bucketed prefill, batched decode. Also demonstrates the
HTTP proxy plane.

Run: python examples/serve_llama.py
     python examples/serve_llama.py --load        # open-loop burst +
                                                  # SLO report (loadgen)
"""

import argparse
import json
import urllib.request

import ray_tpu
from ray_tpu import serve
from ray_tpu.llm import LLMConfig, build_llm_app


def demo(handle):
    """Single-request demo: handle path + HTTP path."""
    out = handle.remote({"prompt": "hello tpu", "max_tokens": 8}).result()
    print("handle:", {k: out[k] for k in ("text", "finish_reason",
                                          "ttft_s")})

    port = serve.start_http_proxy(port=0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"prompt": "hi", "max_tokens": 4}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        print("http:", json.loads(resp.read())["finish_reason"])
    return out


def load(handle, rate: float, duration: float, clients: int):
    """Open-loop burst through the loadgen subsystem: offered-rate
    requests/s, TTFT/E2E percentiles, and goodput under an SLO
    (docs/serving.md)."""
    from ray_tpu.loadgen import (SLO, HandleTarget, LoadSpec,
                                 format_report, run_load)

    # warm the engine so the first TTFTs measure serving, not XLA
    handle.remote({"prompt": [1] * 8, "max_tokens": 2}).result()
    spec = LoadSpec(rate=rate, duration_s=duration, clients=clients,
                    prompt_len="uniform:8:24", output_len=8, seed=0,
                    slo=SLO(ttft_s=1.0, e2e_s=5.0))
    report = run_load(HandleTarget(handle, stream=True), spec)
    print(format_report(report))
    return report


def main(argv=()):
    # default (): callable from tests without swallowing pytest's argv
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--load", action="store_true",
                        help="run a short open-loop burst and print "
                             "the SLO report (keeps the single-request "
                             "demo as default)")
    parser.add_argument("--rate", type=float, default=10.0)
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--clients", type=int, default=8)
    args = parser.parse_args(list(argv))

    ray_tpu.init(num_nodes=1, ignore_reinit_error=True)
    app = build_llm_app(LLMConfig(model_id="llama-demo", max_slots=4,
                                  max_seq=256))
    handle = serve.run(app)

    if args.load:
        out = load(handle, args.rate, args.duration, args.clients)
    else:
        out = demo(handle)

    serve.shutdown()
    return out


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
