"""Compiled-DAG inference pipeline over cross-process shm channels.

Three process-worker actors form a preprocess -> embed -> score
pipeline; after ``experimental_compile()`` every ``execute()`` flows
through pre-allocated shared-memory channels with ZERO RPCs — the
TPU-native shape of the reference's accelerated DAGs
(`python/ray/dag/compiled_dag_node.py`).

Run: python examples/compiled_dag_pipeline.py
"""

from __future__ import annotations

import time


def main(rounds: int = 100):
    import numpy as np

    import ray_tpu
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Preprocess:
        def run(self, text):
            return np.asarray([ord(c) % 97 for c in text], np.float32)

    @ray_tpu.remote
    class Embed:
        def __init__(self, dim):
            rng = np.random.default_rng(0)
            self.table = rng.normal(size=(97, dim)).astype(np.float32)

        def run(self, ids):
            return self.table[ids.astype(np.int64) % 97].mean(axis=0)

    @ray_tpu.remote
    class Score:
        def __init__(self):
            rng = np.random.default_rng(1)
            self.w = rng.normal(size=(16,)).astype(np.float32)

        def run(self, emb):
            return float(emb @ self.w)

    pre, emb, score = Preprocess.remote(), Embed.remote(16), Score.remote()
    with InputNode() as inp:
        dag = score.run.bind(emb.run.bind(pre.run.bind(inp)))
    compiled = dag.experimental_compile()
    assert compiled._proc is not None, "shm-channel mode expected"

    t0 = time.perf_counter()
    refs = [compiled.execute(f"request number {i}")
            for i in range(rounds)]
    outs = [ray_tpu.get(r, timeout=60) for r in refs]
    dt = time.perf_counter() - t0
    compiled.teardown()
    print(f"{rounds} pipelined rounds in {dt:.3f}s "
          f"({rounds / dt:.0f} exec/s), sample score {outs[0]:.4f}")
    return outs


if __name__ == "__main__":
    import ray_tpu

    ray_tpu.init(num_nodes=1, resources={"CPU": 4})
    try:
        main()
    finally:
        ray_tpu.shutdown()
