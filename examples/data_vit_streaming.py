"""BASELINE config 4: ImageNet-style streaming → ViT training with HBM
prefetch.

Reference equivalent: Ray Data streaming ingest feeding TorchTrainer
(`release/train_tests/benchmark` image configs). Here:
Dataset.streaming_split iterators → to_jax device double-buffering →
jitted ViT train step. Synthetic images stand in for ImageNet.

Run: python examples/data_vit_streaming.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu import data as rdata, train
from ray_tpu.models import ViTConfig, ViTModel
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
from ray_tpu.train.spmd import make_train_step


def make_dataset(n=256, img=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, img, img, 3)).astype(np.float32)
    y = rng.integers(0, 10, (n,)).astype(np.int32)
    return rdata.from_numpy(x, "image").zip(
        rdata.from_numpy(y, "label")).repartition(8)


def train_fn(config):
    model = ViTModel(ViTConfig.debug())
    ts = make_train_step(model, optimizer=optax.adam(1e-3))
    params, opt = ts.init_fn(jax.random.key(0))
    shard = train.get_dataset_shard("train")
    # HBM double-buffering: batch N+1 transfers while N computes
    last = None
    for batch in shard.to_jax(batch_size=config["batch_size"],
                              prefetch=2, drop_last=True):
        params, opt, last = ts.step_fn(
            params, opt, (batch["image"], batch["label"]))
    train.report({"loss": float(last["loss"])})


def main():
    ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                 ignore_reinit_error=True)
    result = JaxTrainer(
        train_fn, train_loop_config={"batch_size": 16},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="vit_streaming"),
        datasets={"train": make_dataset()},
    ).fit()
    print("final:", result.metrics)
    assert result.error is None
    return result


if __name__ == "__main__":
    main()
