"""BASELINE config 3: Llama-3-8B sharded training → XLA SPMD (v5p-64
target; CPU-simulated mesh for the demo).

Reference equivalent: TorchTrainer + FSDP wrappers
(`release/train_tests/benchmark/train_benchmark.py`). Here FSDP *is* the
sharding: params carry fsdp/tp logical axes, gradients reduce-scatter and
params all-gather over ICI, ring attention handles the sp axis.

Run (CPU demo): JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/train_llama_fsdp.py --debug
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import LlamaConfig, LlamaModel
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.train.spmd import make_train_step, shard_batch


def main(debug: bool = True, steps: int = 3):
    n = len(jax.devices())
    # v5p-64 target shape: fsdp=16, tp=4; demo shape: fsdp=2, tp=2, sp=2.
    if n % 8 == 0:
        spec = MeshSpec.auto(n, fsdp=2, tp=2, sp=2)
    else:
        spec = MeshSpec.auto(n)
    mesh = build_mesh(spec, jax.devices()[:spec.num_devices])
    cfg = (LlamaConfig.debug(vocab_size=512, max_seq_len=128) if debug
           else LlamaConfig.llama3_8b())
    model = LlamaModel(cfg, mesh=mesh)
    ts = make_train_step(model, mesh=mesh)
    params, opt = ts.init_fn(jax.random.key(0))

    rng = np.random.default_rng(0)
    B, S = 4, min(128, cfg.max_seq_len)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32)
    batch = shard_batch((toks, jnp.roll(toks, -1, 1)), ts)

    for step in range(steps):
        params, opt, m = ts.step_fn(params, opt, batch)
        print(f"step {step}: loss={float(m['loss']):.4f} "
              f"mesh={dict(mesh.shape)}")
    return float(m["loss"])


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--debug", action="store_true", default=True)
    p.add_argument("--full", dest="debug", action="store_false")
    main(debug=p.parse_args().debug)
