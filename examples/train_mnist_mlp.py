"""BASELINE config 1: Fashion-MNIST-style MLP, 2 workers (DDP baseline).

Reference equivalent: TorchTrainer + gloo on 2 CPU workers
(`python/ray/train/examples/pytorch/torch_fashion_mnist_example.py`).
Here: JaxTrainer worker group; the train step is a jitted program; data
ingest via ray_tpu.data streaming_split. Synthetic data stands in for the
dataset download (zero-egress environment).

Run: python examples/train_mnist_mlp.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu import data as rdata, train
from ray_tpu.models import MLPConfig, MLPModel
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
from ray_tpu.train.spmd import make_train_step


def make_dataset(n=2048, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 784)).astype(np.float32)
    w = rng.normal(size=(784, 10)).astype(np.float32)
    y = (x @ w).argmax(1).astype(np.int32)       # learnable labels
    return rdata.from_numpy(x, "x").zip(rdata.from_numpy(y, "y")) \
        .repartition(8)


def train_fn(config):
    ctx = train.get_context()
    model = MLPModel(MLPConfig())
    ts = make_train_step(model, optimizer=optax.adam(config["lr"]))
    params, opt = ts.init_fn(jax.random.key(0))
    shard = train.get_dataset_shard("train")
    for epoch in range(config["epochs"]):
        last = None
        for batch in shard.iter_batches(batch_size=config["batch_size"]):
            xb = jnp.asarray(batch["x"])
            yb = jnp.asarray(batch["y"])
            params, opt, m = ts.step_fn(params, opt, (xb, yb))
            last = m
        train.report({"epoch": epoch, "loss": float(last["loss"]),
                      "rank": ctx.get_world_rank()})


def main():
    ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                 ignore_reinit_error=True)
    result = JaxTrainer(
        train_fn,
        train_loop_config={"lr": 1e-3, "epochs": 2, "batch_size": 128},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="mnist_mlp"),
        datasets={"train": make_dataset()},
    ).fit()
    print("final:", result.metrics)
    assert result.error is None
    return result


if __name__ == "__main__":
    main()
