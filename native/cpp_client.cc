// C++ client for the ray_tpu control/object wire protocol.
//
// Reference capability: the C++ API tier (`cpp/` — ray::Init/Put/Get over
// the core-worker runtime, 9.2k LoC) and the raylet client
// (`src/ray/raylet_client/raylet_client.h`). This build's wire is typed
// msgpack frames over TCP (`ray_tpu/_private/rpc.py`: u32 BE length +
// msgpack map, "m"=method, "i"=request id), so a native client needs no
// Python at all: it speaks to the head (KV) and node daemons (object
// plane, ping) directly.
//
// Exposed as a C ABI (ctypes-consumable, same pattern as shm_store.cc):
//   rtc_connect / rtc_close
//   rtc_kv_put / rtc_kv_get            (head InternalKV)
//   rtc_put_object / rtc_get_object    (daemon object table)
//   rtc_ping                           (daemon_ping -> pid)
//   rtc_submit_task                    (cross-language task by name)
//   rtc_create_actor / rtc_call_actor  (cross-language Python actors)
//   rtc_free                           (free buffers returned by _get)
//
// Cross-language calls reference functions/classes EXPORTED BY NAME from
// Python (`ray_tpu.xlang.export_task` / `export_actor_class` -> head KV,
// reference `cpp/include/ray/api.h` + `python/ray/cross_language.py`);
// args and results are msgpack-plain values, no Python pickles.
//
// Build: `make` in native/ produces libray_tpu_cpp_client.so.

#include <arpa/inet.h>
#include <netdb.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// minimal msgpack (the subset the wire uses)
// ---------------------------------------------------------------------------

struct Value {
  enum Kind { NIL, BOOL, INT, DBL, STR, BIN, ARR, MAP } kind = NIL;
  bool b = false;
  int64_t i = 0;
  double d = 0;
  std::string s;                       // STR and BIN both land here
  std::vector<Value> arr;
  std::map<std::string, Value> map;    // string-keyed maps only

  const Value* get(const std::string& key) const {
    auto it = map.find(key);
    return it == map.end() ? nullptr : &it->second;
  }
};

void pack_uint(std::string& out, uint64_t v) {
  if (v < 128) {
    out.push_back(static_cast<char>(v));
  } else if (v <= 0xff) {
    out.push_back(static_cast<char>(0xcc));
    out.push_back(static_cast<char>(v));
  } else if (v <= 0xffff) {
    out.push_back(static_cast<char>(0xcd));
    out.push_back(static_cast<char>(v >> 8));
    out.push_back(static_cast<char>(v));
  } else if (v <= 0xffffffffULL) {
    out.push_back(static_cast<char>(0xce));
    for (int s = 24; s >= 0; s -= 8)
      out.push_back(static_cast<char>(v >> s));
  } else {
    out.push_back(static_cast<char>(0xcf));
    for (int s = 56; s >= 0; s -= 8)
      out.push_back(static_cast<char>(v >> s));
  }
}

void pack_str(std::string& out, const std::string& s) {
  size_t n = s.size();
  if (n < 32) {
    out.push_back(static_cast<char>(0xa0 | n));
  } else if (n <= 0xff) {
    out.push_back(static_cast<char>(0xd9));
    out.push_back(static_cast<char>(n));
  } else if (n <= 0xffff) {
    out.push_back(static_cast<char>(0xda));
    out.push_back(static_cast<char>(n >> 8));
    out.push_back(static_cast<char>(n));
  } else {
    out.push_back(static_cast<char>(0xdb));
    for (int s = 24; s >= 0; s -= 8)
      out.push_back(static_cast<char>(n >> s));
  }
  out += s;
}

void pack_bin(std::string& out, const uint8_t* data, size_t n) {
  if (n <= 0xff) {
    out.push_back(static_cast<char>(0xc4));
    out.push_back(static_cast<char>(n));
  } else if (n <= 0xffff) {
    out.push_back(static_cast<char>(0xc5));
    out.push_back(static_cast<char>(n >> 8));
    out.push_back(static_cast<char>(n));
  } else {
    out.push_back(static_cast<char>(0xc6));
    for (int s = 24; s >= 0; s -= 8)
      out.push_back(static_cast<char>(n >> s));
  }
  out.append(reinterpret_cast<const char*>(data), n);
}

void pack_bool(std::string& out, bool v) {
  out.push_back(static_cast<char>(v ? 0xc3 : 0xc2));
}

void pack_map_header(std::string& out, size_t n) {
  if (n < 16) {
    out.push_back(static_cast<char>(0x80 | n));
  } else if (n <= 0xffff) {
    out.push_back(static_cast<char>(0xde));
    out.push_back(static_cast<char>(n >> 8));
    out.push_back(static_cast<char>(n));
  } else {
    out.push_back(static_cast<char>(0xdf));
    for (int s = 24; s >= 0; s -= 8)
      out.push_back(static_cast<char>(n >> s));
  }
}

// Value -> msgpack bytes (results crossing back to the C caller).
void pack_value(std::string& out, const Value& v) {
  switch (v.kind) {
    case Value::NIL: out.push_back(static_cast<char>(0xc0)); break;
    case Value::BOOL: pack_bool(out, v.b); break;
    case Value::INT:
      if (v.i >= 0) {
        pack_uint(out, static_cast<uint64_t>(v.i));
      } else {
        out.push_back(static_cast<char>(0xd3));
        uint64_t u = static_cast<uint64_t>(v.i);
        for (int s = 56; s >= 0; s -= 8)
          out.push_back(static_cast<char>(u >> s));
      }
      break;
    case Value::DBL: {
      out.push_back(static_cast<char>(0xcb));
      uint64_t u;
      memcpy(&u, &v.d, 8);
      for (int s = 56; s >= 0; s -= 8)
        out.push_back(static_cast<char>(u >> s));
      break;
    }
    case Value::STR: pack_str(out, v.s); break;
    case Value::BIN:
      pack_bin(out, reinterpret_cast<const uint8_t*>(v.s.data()),
               v.s.size());
      break;
    case Value::ARR: {
      size_t n = v.arr.size();
      if (n < 16) {
        out.push_back(static_cast<char>(0x90 | n));
      } else if (n <= 0xffff) {
        out.push_back(static_cast<char>(0xdc));
        out.push_back(static_cast<char>(n >> 8));
        out.push_back(static_cast<char>(n));
      } else {
        out.push_back(static_cast<char>(0xdd));
        for (int s = 24; s >= 0; s -= 8)
          out.push_back(static_cast<char>(n >> s));
      }
      for (const Value& e : v.arr) pack_value(out, e);
      break;
    }
    case Value::MAP: {
      pack_map_header(out, v.map.size());
      for (const auto& kv : v.map) {
        pack_str(out, kv.first);
        pack_value(out, kv.second);
      }
      break;
    }
  }
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t be(int n) {
    if (end - p < n) { ok = false; return 0; }
    uint64_t v = 0;
    for (int k = 0; k < n; ++k) v = (v << 8) | *p++;
    return v;
  }

  std::string take(size_t n) {
    if (static_cast<size_t>(end - p) < n) { ok = false; return {}; }
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }

  Value parse() {
    Value v;
    if (p >= end) { ok = false; return v; }
    uint8_t t = *p++;
    if (t < 0x80) { v.kind = Value::INT; v.i = t; return v; }
    if (t >= 0xe0) { v.kind = Value::INT;
                     v.i = static_cast<int8_t>(t); return v; }
    if ((t & 0xf0) == 0x80) return parse_map(t & 0x0f);
    if ((t & 0xf0) == 0x90) return parse_arr(t & 0x0f);
    if ((t & 0xe0) == 0xa0) { v.kind = Value::STR;
                              v.s = take(t & 0x1f); return v; }
    switch (t) {
      case 0xc0: return v;                              // nil
      case 0xc2: v.kind = Value::BOOL; v.b = false; return v;
      case 0xc3: v.kind = Value::BOOL; v.b = true; return v;
      case 0xc4: v.kind = Value::BIN; v.s = take(be(1)); return v;
      case 0xc5: v.kind = Value::BIN; v.s = take(be(2)); return v;
      case 0xc6: v.kind = Value::BIN; v.s = take(be(4)); return v;
      case 0xca: { v.kind = Value::DBL; uint32_t r = be(4); float f;
                   memcpy(&f, &r, 4); v.d = f; return v; }
      case 0xcb: { v.kind = Value::DBL; uint64_t r = be(8);
                   memcpy(&v.d, &r, 8); return v; }
      case 0xcc: v.kind = Value::INT; v.i = be(1); return v;
      case 0xcd: v.kind = Value::INT; v.i = be(2); return v;
      case 0xce: v.kind = Value::INT; v.i = be(4); return v;
      case 0xcf: v.kind = Value::INT;
                 v.i = static_cast<int64_t>(be(8)); return v;
      case 0xd0: v.kind = Value::INT;
                 v.i = static_cast<int8_t>(be(1)); return v;
      case 0xd1: v.kind = Value::INT;
                 v.i = static_cast<int16_t>(be(2)); return v;
      case 0xd2: v.kind = Value::INT;
                 v.i = static_cast<int32_t>(be(4)); return v;
      case 0xd3: v.kind = Value::INT;
                 v.i = static_cast<int64_t>(be(8)); return v;
      case 0xd9: v.kind = Value::STR; v.s = take(be(1)); return v;
      case 0xda: v.kind = Value::STR; v.s = take(be(2)); return v;
      case 0xdb: v.kind = Value::STR; v.s = take(be(4)); return v;
      case 0xdc: return parse_arr(be(2));
      case 0xdd: return parse_arr(be(4));
      case 0xde: return parse_map(be(2));
      case 0xdf: return parse_map(be(4));
      default: ok = false; return v;                    // unsupported
    }
  }

  Value parse_arr(size_t n) {
    Value v;
    v.kind = Value::ARR;
    for (size_t k = 0; k < n && ok; ++k) v.arr.push_back(parse());
    return v;
  }

  Value parse_map(size_t n) {
    Value v;
    v.kind = Value::MAP;
    for (size_t k = 0; k < n && ok; ++k) {
      Value key = parse();
      Value val = parse();
      if (key.kind == Value::STR) v.map.emplace(key.s, std::move(val));
    }
    return v;
  }
};

// ---------------------------------------------------------------------------
// connection
// ---------------------------------------------------------------------------

struct Client {
  int fd = -1;
  uint64_t next_id = 0;
  std::mutex mu;
  std::string last_error;

  bool send_all(const std::string& buf) {
    size_t off = 0;
    while (off < buf.size()) {
      ssize_t n = ::send(fd, buf.data() + off, buf.size() - off, 0);
      if (n <= 0) return false;
      off += static_cast<size_t>(n);
    }
    return true;
  }

  bool recv_all(uint8_t* out, size_t n) {
    size_t off = 0;
    while (off < n) {
      ssize_t r = ::recv(fd, out + off, n - off, 0);
      if (r <= 0) return false;
      off += static_cast<size_t>(r);
    }
    return true;
  }

  // One request/reply round trip. Body packs the extra fields.
  bool call(const std::string& method,
            const std::string& packed_fields, size_t n_fields,
            Value* reply) {
    std::lock_guard<std::mutex> lock(mu);
    uint64_t rid = ++next_id;
    std::string body;
    pack_map_header(body, n_fields + 2);
    pack_str(body, "m");
    pack_str(body, method);
    pack_str(body, "i");
    pack_uint(body, rid);
    body += packed_fields;

    std::string frame;
    uint32_t len = htonl(static_cast<uint32_t>(body.size()));
    frame.append(reinterpret_cast<const char*>(&len), 4);
    frame += body;
    if (!send_all(frame)) { last_error = "send failed"; return false; }

    // read frames until the one carrying our id (pushes have no "i")
    while (true) {
      uint8_t hdr[4];
      if (!recv_all(hdr, 4)) { last_error = "recv failed"; return false; }
      uint32_t blen = (uint32_t(hdr[0]) << 24) | (uint32_t(hdr[1]) << 16) |
                      (uint32_t(hdr[2]) << 8) | uint32_t(hdr[3]);
      std::vector<uint8_t> buf(blen);
      if (!recv_all(buf.data(), blen)) {
        last_error = "recv failed";
        return false;
      }
      Reader r{buf.data(), buf.data() + blen};
      Value msg = r.parse();
      if (!r.ok || msg.kind != Value::MAP) {
        last_error = "bad frame";
        return false;
      }
      const Value* id = msg.get("i");
      if (id == nullptr || static_cast<uint64_t>(id->i) != rid) {
        continue;  // server push or stale frame: skip
      }
      const Value* err = msg.get("e");
      if (err != nullptr && err->kind == Value::STR) {
        last_error = err->s;
        return false;
      }
      *reply = std::move(msg);
      return true;
    }
  }
};

uint8_t* dup_buffer(const std::string& s, int64_t* out_len) {
  auto* out = static_cast<uint8_t*>(malloc(s.size() ? s.size() : 1));
  memcpy(out, s.data(), s.size());
  *out_len = static_cast<int64_t>(s.size());
  return out;
}

}  // namespace

extern "C" {

void* rtc_connect(const char* host, int port) {
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (getaddrinfo(host, port_s.c_str(), &hints, &res) != 0 || !res) {
    return nullptr;
  }
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    if (fd >= 0) close(fd);
    freeaddrinfo(res);
    return nullptr;
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));
  // Bound every send/recv (the Python wire client defaults to 30s):
  // a wedged peer returns an error instead of hanging the caller.
  struct timeval tv;
  tv.tv_sec = 30;
  tv.tv_usec = 0;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

void rtc_close(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (c == nullptr) return;
  if (c->fd >= 0) close(c->fd);
  delete c;
}

void rtc_free(void* p) { free(p); }

// -- head InternalKV --------------------------------------------------------

int rtc_kv_put(void* handle, const uint8_t* key, int klen,
               const uint8_t* value, int vlen) {
  auto* c = static_cast<Client*>(handle);
  std::string fields;
  pack_str(fields, "key");
  pack_bin(fields, key, static_cast<size_t>(klen));
  pack_str(fields, "value");
  pack_bin(fields, value, static_cast<size_t>(vlen));
  pack_str(fields, "overwrite");
  pack_bool(fields, true);
  pack_str(fields, "ns");
  pack_bin(fields, nullptr, 0);
  Value reply;
  if (!c->call("kv_put", fields, 4, &reply)) return -1;
  return 0;
}

// Returns 0 + *out on hit, 1 on miss, -1 on transport error.
int rtc_kv_get(void* handle, const uint8_t* key, int klen,
               uint8_t** out, int64_t* out_len) {
  auto* c = static_cast<Client*>(handle);
  std::string fields;
  pack_str(fields, "key");
  pack_bin(fields, key, static_cast<size_t>(klen));
  pack_str(fields, "ns");
  pack_bin(fields, nullptr, 0);
  Value reply;
  if (!c->call("kv_get", fields, 2, &reply)) return -1;
  const Value* v = reply.get("value");
  if (v == nullptr || v->kind == Value::NIL) return 1;
  *out = dup_buffer(v->s, out_len);
  return 0;
}

// -- daemon object plane ----------------------------------------------------

int rtc_put_object(void* handle, const uint8_t* oid, int oid_len,
                   const uint8_t* blob, int64_t blob_len) {
  auto* c = static_cast<Client*>(handle);
  std::string fields;
  pack_str(fields, "oid");
  pack_bin(fields, oid, static_cast<size_t>(oid_len));
  pack_str(fields, "blob");
  pack_bin(fields, blob, static_cast<size_t>(blob_len));
  Value reply;
  return c->call("put_object", fields, 2, &reply) ? 0 : -1;
}

// Returns 0 + *out on hit, 1 on miss, -1 on transport error.
int rtc_get_object(void* handle, const uint8_t* oid, int oid_len,
                   uint8_t** out, int64_t* out_len) {
  auto* c = static_cast<Client*>(handle);
  std::string fields;
  pack_str(fields, "oid");
  pack_bin(fields, oid, static_cast<size_t>(oid_len));
  pack_str(fields, "prefer_shm");
  pack_bool(fields, false);
  Value reply;
  if (!c->call("get_object", fields, 2, &reply)) return -1;
  const Value* missing = reply.get("missing");
  if (missing != nullptr && missing->kind == Value::BOOL && missing->b) {
    return 1;
  }
  const Value* blob = reply.get("blob");
  if (blob == nullptr || blob->kind == Value::NIL) return 1;
  *out = dup_buffer(blob->s, out_len);
  return 0;
}

// -- cross-language tasks/actors (daemon) -----------------------------------

namespace {  // shared reply handling for the xlang calls

// 0 + *out(result msgpack) on ok; 1 + *out(error UTF-8) on app error;
// -1 on transport error.
int xlang_finish(Client* c, bool sent, const Value& reply,
                 uint8_t** out, int64_t* out_len) {
  if (!sent) return -1;
  const Value* outcome = reply.get("outcome");
  if (outcome != nullptr && outcome->s == "ok") {
    const Value* result = reply.get("result");
    std::string packed;
    if (result != nullptr) {
      pack_value(packed, *result);
    } else {
      packed.push_back(static_cast<char>(0xc0));  // nil
    }
    if (out != nullptr) *out = dup_buffer(packed, out_len);
    return 0;
  }
  const Value* err = reply.get("error");
  std::string e = err != nullptr ? err->s : "unknown xlang error";
  c->last_error = e;
  if (out != nullptr) *out = dup_buffer(e, out_len);
  return 1;
}

}  // namespace

int rtc_submit_task(void* handle, const char* name, const uint8_t* args,
                    int args_len, uint8_t** out, int64_t* out_len) {
  auto* c = static_cast<Client*>(handle);
  std::string fields;
  pack_str(fields, "name");
  pack_str(fields, name);
  pack_str(fields, "args");
  fields.append(reinterpret_cast<const char*>(args),
                static_cast<size_t>(args_len));  // pre-packed msgpack arr
  Value reply;
  bool sent = c->call("xlang_submit", fields, 2, &reply);
  return xlang_finish(c, sent, reply, out, out_len);
}

int rtc_create_actor(void* handle, const char* cls, const char* name,
                     const uint8_t* args, int args_len) {
  auto* c = static_cast<Client*>(handle);
  std::string fields;
  pack_str(fields, "cls");
  pack_str(fields, cls);
  pack_str(fields, "name");
  pack_str(fields, name);
  pack_str(fields, "args");
  fields.append(reinterpret_cast<const char*>(args),
                static_cast<size_t>(args_len));
  Value reply;
  bool sent = c->call("xlang_create_actor", fields, 3, &reply);
  return xlang_finish(c, sent, reply, nullptr, nullptr);
}

int rtc_call_actor(void* handle, const char* name, const char* method,
                   const uint8_t* args, int args_len, uint8_t** out,
                   int64_t* out_len) {
  auto* c = static_cast<Client*>(handle);
  std::string fields;
  pack_str(fields, "name");
  pack_str(fields, name);
  pack_str(fields, "method");
  pack_str(fields, method);
  pack_str(fields, "args");
  fields.append(reinterpret_cast<const char*>(args),
                static_cast<size_t>(args_len));
  Value reply;
  bool sent = c->call("xlang_call_actor", fields, 3, &reply);
  return xlang_finish(c, sent, reply, out, out_len);
}

// -- daemon ping ------------------------------------------------------------

// Returns the daemon's pid, or -1.
long rtc_ping(void* handle) {
  auto* c = static_cast<Client*>(handle);
  Value reply;
  if (!c->call("daemon_ping", "", 0, &reply)) return -1;
  const Value* pid = reply.get("pid");
  return pid != nullptr ? static_cast<long>(pid->i) : -1;
}

const char* rtc_last_error(void* handle) {
  auto* c = static_cast<Client*>(handle);
  return c->last_error.c_str();
}

}  // extern "C"
