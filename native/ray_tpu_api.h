// ray_tpu embedded C++ API (header-only facade).
//
// Reference capability: the C++ front end (`cpp/include/ray/api.h` —
// ray::Init / ray::Put / ray::Get / ray::Task(...).Remote() over the
// embedded CoreWorker). Here the embedded runtime is the native wire
// client (libray_tpu_cpp_client.so, C ABI `rtc_*`); this header gives
// a C++ program the same ergonomics with RAII handles and typed
// argument/result marshalling — no Python anywhere in the process.
//
// Usage:
//   ray_tpu_api::Runtime rt;
//   rt.Init(head_host, head_port, daemon_host, daemon_port);
//   rt.KvPut("k", "v");
//   auto r = rt.SubmitTask("add", ray_tpu_api::Args().I(2).I(3));
//   int64_t five = r.AsInt();
//   rt.CreateActor("Counter", "c1", ray_tpu_api::Args());
//   rt.CallActor("c1", "inc", ray_tpu_api::Args()).AsInt();
//
// Values cross the boundary as msgpack (the cross-language contract:
// plain ints/floats/strings/bools/bytes — never language pickles).

#pragma once

#include <dlfcn.h>
#include <stdint.h>

#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace ray_tpu_api {

// -- minimal msgpack (what the xlang value contract allows) -----------------

class Args {
 public:
  Args &I(int64_t v) {
    count_++;
    if (v >= 0 && v < 128) {
      buf_.push_back(char(v));
    } else {
      buf_.push_back(char(0xd3));
      for (int i = 7; i >= 0; i--) buf_.push_back(char((v >> (8 * i)) & 0xff));
    }
    return *this;
  }
  Args &D(double v) {
    count_++;
    buf_.push_back(char(0xcb));
    uint64_t bits;
    memcpy(&bits, &v, 8);
    for (int i = 7; i >= 0; i--)
      buf_.push_back(char((bits >> (8 * i)) & 0xff));
    return *this;
  }
  Args &B(bool v) {
    count_++;
    buf_.push_back(char(v ? 0xc3 : 0xc2));
    return *this;
  }
  Args &S(const std::string &s) {
    count_++;
    append_str(s);
    return *this;
  }
  Args &Bin(const std::string &b) {
    count_++;
    buf_.push_back(char(0xc6));
    uint32_t n = uint32_t(b.size());
    for (int i = 3; i >= 0; i--) buf_.push_back(char((n >> (8 * i)) & 0xff));
    buf_.append(b);
    return *this;
  }

  // packed msgpack ARRAY of the accumulated values
  std::string Packed() const {
    std::string out;
    if (count_ < 16) {
      out.push_back(char(0x90 | count_));
    } else {
      out.push_back(char(0xdc));
      out.push_back(char((count_ >> 8) & 0xff));
      out.push_back(char(count_ & 0xff));
    }
    out.append(buf_);
    return out;
  }

 private:
  void append_str(const std::string &s) {
    size_t n = s.size();
    if (n < 32) {
      buf_.push_back(char(0xa0 | n));
    } else {
      buf_.push_back(char(0xdb));
      for (int i = 3; i >= 0; i--) buf_.push_back(char((n >> (8 * i)) & 0xff));
    }
    buf_.append(s);
  }
  std::string buf_;
  size_t count_ = 0;
};

// -- result decoding --------------------------------------------------------

struct Result {
  bool ok = false;
  std::string raw;     // msgpack on ok; UTF-8 error text otherwise
  std::string Error() const { return ok ? "" : raw; }

  int64_t AsInt() const {
    const uint8_t *p = Bytes();
    uint8_t t = p[0];
    if (t < 0x80) return t;
    if (t >= 0xe0) return int8_t(t);
    // signed (0xd0-0xd3) sign-extend; unsigned (0xcc-0xcf) must NOT —
    // msgpack-python packs 128..255 as 0xcc, 40000 as 0xcd, etc.
    if (t == 0xd3 || t == 0xcf) return ReadBE(p + 1, 8);
    if (t == 0xd2) return int32_t(ReadBE(p + 1, 4));
    if (t == 0xce) return ReadBE(p + 1, 4);
    if (t == 0xd1) return int16_t(ReadBE(p + 1, 2));
    if (t == 0xcd) return ReadBE(p + 1, 2);
    if (t == 0xd0) return int8_t(ReadBE(p + 1, 1));
    if (t == 0xcc) return ReadBE(p + 1, 1);
    throw std::runtime_error("result is not an int");
  }
  double AsDouble() const {
    const uint8_t *p = Bytes();
    if (p[0] == 0xcb) {
      uint64_t bits = uint64_t(ReadBE(p + 1, 8));
      double v;
      memcpy(&v, &bits, 8);
      return v;
    }
    return double(AsInt());
  }
  std::string AsString() const {
    const uint8_t *p = Bytes();
    uint8_t t = p[0];
    size_t n, off;
    if ((t & 0xe0) == 0xa0) {
      n = t & 0x1f;
      off = 1;
    } else if (t == 0xd9) {
      n = p[1];
      off = 2;
    } else if (t == 0xda) {
      n = size_t(ReadBE(p + 1, 2));
      off = 3;
    } else if (t == 0xdb) {
      n = size_t(ReadBE(p + 1, 4));
      off = 5;
    } else {
      throw std::runtime_error("result is not a string");
    }
    return std::string(reinterpret_cast<const char *>(p + off), n);
  }
  bool AsBool() const { return Bytes()[0] == 0xc3; }
  bool IsNil() const { return !raw.empty() && Bytes()[0] == 0xc0; }

 private:
  const uint8_t *Bytes() const {
    if (raw.empty()) throw std::runtime_error("empty result");
    return reinterpret_cast<const uint8_t *>(raw.data());
  }
  static int64_t ReadBE(const uint8_t *p, int n) {
    int64_t v = 0;
    for (int i = 0; i < n; i++) v = (v << 8) | p[i];
    return v;
  }
};

// -- the embedded runtime ---------------------------------------------------

class Runtime {
 public:
  Runtime() = default;
  ~Runtime() { Shutdown(); }
  Runtime(const Runtime &) = delete;
  Runtime &operator=(const Runtime &) = delete;

  // Connect to a running cluster: the head (KV plane) and one node
  // daemon (task/actor/object planes). lib_path defaults to the .so
  // next to this header's repo layout or LD_LIBRARY_PATH.
  void Init(const std::string &head_host, int head_port,
            const std::string &daemon_host, int daemon_port,
            const std::string &lib_path = "libray_tpu_cpp_client.so") {
    lib_ = dlopen(lib_path.c_str(), RTLD_NOW);
    if (!lib_) throw std::runtime_error(std::string("dlopen: ") + dlerror());
    Load();
    head_ = connect_(head_host.c_str(), head_port);
    if (!head_) throw std::runtime_error("cannot reach head");
    daemon_ = connect_(daemon_host.c_str(), daemon_port);
    if (!daemon_) throw std::runtime_error("cannot reach daemon");
  }

  void Shutdown() {
    if (head_) close_(head_), head_ = nullptr;
    if (daemon_) close_(daemon_), daemon_ = nullptr;
    if (lib_) dlclose(lib_), lib_ = nullptr;
  }

  // -- KV plane (head InternalKV) --------------------------------------
  bool KvPut(const std::string &key, const std::string &value) {
    return kv_put_(head_, U(key), int(key.size()), U(value),
                   int(value.size())) == 0;
  }
  bool KvGet(const std::string &key, std::string *out) {
    uint8_t *buf = nullptr;
    int64_t n = 0;
    int rc = kv_get_(head_, U(key), int(key.size()), &buf, &n);
    if (rc != 0) return false;
    out->assign(reinterpret_cast<char *>(buf), size_t(n));
    free_(buf);
    return true;
  }

  // -- object plane (daemon object table / shm arena) ------------------
  bool PutObject(const std::string &oid, const std::string &blob) {
    return put_object_(daemon_, U(oid), int(oid.size()), U(blob),
                       int64_t(blob.size())) == 0;
  }
  bool GetObject(const std::string &oid, std::string *out) {
    uint8_t *buf = nullptr;
    int64_t n = 0;
    int rc = get_object_(daemon_, U(oid), int(oid.size()), &buf, &n);
    if (rc != 0) return false;
    out->assign(reinterpret_cast<char *>(buf), size_t(n));
    free_(buf);
    return true;
  }

  long Ping() { return ping_(daemon_); }

  // -- tasks / actors (cross-language by NAME) -------------------------
  Result SubmitTask(const std::string &name, const Args &args) {
    uint8_t *buf = nullptr;
    int64_t n = 0;
    std::string packed = args.Packed();
    int rc = submit_(daemon_, name.c_str(), U(packed),
                     int(packed.size()), &buf, &n);
    return Finish(rc, buf, n);
  }
  Result CreateActor(const std::string &cls, const std::string &name,
                     const Args &args) {
    std::string packed = args.Packed();
    int rc = create_actor_(daemon_, cls.c_str(), name.c_str(), U(packed),
                           int(packed.size()));
    Result r;
    r.ok = rc == 0;
    if (!r.ok) r.raw = last_error_(daemon_);
    return r;
  }
  Result CallActor(const std::string &name, const std::string &method,
                   const Args &args) {
    uint8_t *buf = nullptr;
    int64_t n = 0;
    std::string packed = args.Packed();
    int rc = call_actor_(daemon_, name.c_str(), method.c_str(), U(packed),
                         int(packed.size()), &buf, &n);
    return Finish(rc, buf, n);
  }

 private:
  Result Finish(int rc, uint8_t *buf, int64_t n) {
    Result r;
    r.ok = rc == 0;
    if (buf) {
      r.raw.assign(reinterpret_cast<char *>(buf), size_t(n));
      free_(buf);
    } else if (rc < 0) {
      r.raw = "transport error";
    }
    return r;
  }
  static const uint8_t *U(const std::string &s) {
    return reinterpret_cast<const uint8_t *>(s.data());
  }
  template <typename T>
  void Sym(T &fn, const char *name) {
    fn = reinterpret_cast<T>(dlsym(lib_, name));
    if (!fn) throw std::runtime_error(std::string("missing symbol ") + name);
  }
  void Load() {
    Sym(connect_, "rtc_connect");
    Sym(close_, "rtc_close");
    Sym(free_, "rtc_free");
    Sym(kv_put_, "rtc_kv_put");
    Sym(kv_get_, "rtc_kv_get");
    Sym(put_object_, "rtc_put_object");
    Sym(get_object_, "rtc_get_object");
    Sym(ping_, "rtc_ping");
    Sym(submit_, "rtc_submit_task");
    Sym(create_actor_, "rtc_create_actor");
    Sym(call_actor_, "rtc_call_actor");
    Sym(last_error_, "rtc_last_error");
  }

  void *lib_ = nullptr;
  void *head_ = nullptr;
  void *daemon_ = nullptr;
  void *(*connect_)(const char *, int) = nullptr;
  void (*close_)(void *) = nullptr;
  void (*free_)(void *) = nullptr;
  int (*kv_put_)(void *, const uint8_t *, int, const uint8_t *, int) = nullptr;
  int (*kv_get_)(void *, const uint8_t *, int, uint8_t **, int64_t *) = nullptr;
  int (*put_object_)(void *, const uint8_t *, int, const uint8_t *,
                     int64_t) = nullptr;
  int (*get_object_)(void *, const uint8_t *, int, uint8_t **,
                     int64_t *) = nullptr;
  long (*ping_)(void *) = nullptr;
  int (*submit_)(void *, const char *, const uint8_t *, int, uint8_t **,
                 int64_t *) = nullptr;
  int (*create_actor_)(void *, const char *, const char *, const uint8_t *,
                       int) = nullptr;
  int (*call_actor_)(void *, const char *, const char *, const uint8_t *, int,
                     uint8_t **, int64_t *) = nullptr;
  const char *(*last_error_)(void *) = nullptr;
};

}  // namespace ray_tpu_api
