// Embedded C++ program driving a ray_tpu cluster through the header
// API (native/ray_tpu_api.h) — the `cpp/` front-end role: no Python in
// THIS process; tasks/actors execute on the cluster's pooled workers.
// Invoked by tests/test_cpp_client.py with the cluster's addresses;
// prints one KEY=value line per check for the test to assert.

#include <cstdio>
#include <cstdlib>

#include "ray_tpu_api.h"

int main(int argc, char **argv) {
  if (argc < 6) {
    fprintf(stderr,
            "usage: api_demo HEAD_HOST HEAD_PORT DAEMON_HOST "
            "DAEMON_PORT LIB_PATH\n");
    return 2;
  }
  try {
    ray_tpu_api::Runtime rt;
    rt.Init(argv[1], atoi(argv[2]), argv[3], atoi(argv[4]), argv[5]);

    rt.KvPut("embedded-key", "embedded-value");
    std::string v;
    printf("KV=%s\n", rt.KvGet("embedded-key", &v) ? v.c_str() : "MISS");

    printf("PING=%ld\n", rt.Ping());

    rt.PutObject("embedded-oid", std::string(300000, 'z'));
    std::string blob;
    bool got = rt.GetObject("embedded-oid", &blob);
    printf("OBJ=%zu\n", got ? blob.size() : 0);

    auto add = rt.SubmitTask("add",
                             ray_tpu_api::Args().I(20).I(22));
    if (!add.ok) {
      printf("ADD_ERR=%s\n", add.Error().c_str());
      return 1;
    }
    printf("ADD=%lld\n", static_cast<long long>(add.AsInt()));

    auto greet = rt.SubmitTask("greet",
                               ray_tpu_api::Args().S("embedded"));
    printf("GREET=%s\n",
           greet.ok ? greet.AsString().c_str() : greet.Error().c_str());

    auto mk = rt.CreateActor("Counter", "embedded-counter",
                             ray_tpu_api::Args().I(100));
    if (!mk.ok) {
      printf("ACTOR_ERR=%s\n", mk.Error().c_str());
      return 1;
    }
    auto c1 = rt.CallActor("embedded-counter", "inc",
                           ray_tpu_api::Args().I(1));
    auto c2 = rt.CallActor("embedded-counter", "inc",
                           ray_tpu_api::Args().I(5));
    printf("COUNT1=%lld\n", static_cast<long long>(c1.AsInt()));
    printf("COUNT2=%lld\n", static_cast<long long>(c2.AsInt()));

    // app errors surface as error text, not crashes
    auto bad = rt.SubmitTask("no-such-task", ray_tpu_api::Args());
    printf("MISSING_OK=%d\n", bad.ok ? 1 : 0);
    rt.Shutdown();
    printf("DONE=1\n");
    return 0;
  } catch (const std::exception &e) {
    fprintf(stderr, "exception: %s\n", e.what());
    return 1;
  }
}
