// Native daemon core: the hot task-routing loop of the node daemon.
//
// Reference capability: the C++ raylet's lease-grant + task-dispatch
// path (src/ray/raylet/node_manager.cc HandleRequestWorkerLease,
// raylet/local_task_manager.h) — the per-node engine that assigns
// queued tasks to pooled workers and pumps results back, without the
// policy layer in the loop. Here the Python daemon stays the policy
// shell (actors, placement groups, runtime envs, object pulls); this
// C++ event loop owns the per-task fast path: accept driver
// submissions, lease a free worker, forward the payload, route the
// outcome back — zero Python (and zero GIL) per task.
//
// Wire protocol (little-endian, TCP):
//   frame := u32 body_len | body
//   body  := u8 op | rest
// ops from peers:
//   0x01 HELLO_WORKER  {}                        worker registers, free
//   0x02 SUBMIT        u64 rid | payload         driver submits a task
//   0x03 RESULT        u64 tid | u8 kind | blob  worker finished tid
//   0x04 CANCEL        u64 rid                   driver cancels
//   0x05 PING          u64 rid                   health/stats probe
// ops to peers:
//   0x06 EXEC          u64 tid | payload         core -> worker
//   0x07 REPLY         u64 rid | u8 kind | blob  core -> driver
//   0x08 CANCEL_EXEC   u64 tid                   core -> worker
// kinds are opaque passthrough except core-generated:
//   0x63 CRASHED (payload = error text), 0x64 CANCELLED, 0x65 PONG.
// The task payload itself (msgpack map built by the driver, decoded by
// the worker) is never parsed here — the core routes bytes.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>
#include <deque>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint8_t OP_HELLO_WORKER = 0x01;
constexpr uint8_t OP_SUBMIT = 0x02;
constexpr uint8_t OP_RESULT = 0x03;
constexpr uint8_t OP_CANCEL = 0x04;
constexpr uint8_t OP_PING = 0x05;
constexpr uint8_t OP_EXEC = 0x06;
constexpr uint8_t OP_REPLY = 0x07;
constexpr uint8_t OP_CANCEL_EXEC = 0x08;
// targeted lane (actors): a TAGGED worker serves exactly the submits
// addressed to its tag, strictly FIFO — the per-actor ordering the
// reference enforces via actor_scheduling_queue.h
constexpr uint8_t OP_HELLO_TAGGED = 0x09;
constexpr uint8_t OP_SUBMIT_TARGETED = 0x0a;
// core -> tagged worker: registration is LIVE. The worker reports its
// join complete only after this ack, so the daemon's create-actor
// reply (and thus the driver's first targeted submit) cannot race the
// hello bytes through the event loop.
constexpr uint8_t OP_HELLO_ACK = 0x0b;

constexpr uint8_t KIND_CRASHED = 0x63;
constexpr uint8_t KIND_CANCELLED = 0x64;
constexpr uint8_t KIND_PONG = 0x65;

constexpr size_t MAX_FRAME = size_t(1) << 31;

struct Pending {
  uint64_t rid;
  int driver_fd;
  uint64_t driver_gen;
  std::vector<uint8_t> payload;
};

struct Conn {
  int fd = -1;
  uint64_t gen = 0;          // guards against fd reuse
  bool is_worker = false;
  bool writable = true;
  bool dirty = false;        // queued frames await the end-of-iteration flush
  std::vector<uint8_t> rbuf;
  std::deque<std::vector<uint8_t>> wq;
  size_t wq_off = 0;         // bytes of wq.front() already written
  uint64_t inflight_tid = 0; // worker: task currently executing (0 = idle)
  uint64_t tag = 0;          // targeted worker: its address (0 = pool)
  // targeted worker: submits waiting for it, strictly FIFO
  std::deque<Pending> own_queue;
  // driver: rid -> tid for its in-flight tasks. Per-connection, because
  // every driver numbers its rids independently from 1 — a global map
  // would collide across drivers.
  std::unordered_map<uint64_t, uint64_t> rid_tid;
};

struct Inflight {
  uint64_t rid;
  int driver_fd;
  uint64_t driver_gen;
  int worker_fd;
};

struct Core {
  int epfd = -1;
  int listen_fd = -1;
  int stop_fd = -1;
  uint64_t next_gen = 1;
  uint64_t next_tid = 1;
  std::unordered_map<int, Conn> conns;
  std::vector<int> dirty_fds;  // conns with frames queued this iteration
  std::deque<int> free_workers;
  std::unordered_map<uint64_t, int> tagged;   // tag -> worker fd
  std::deque<Pending> queue;
  std::unordered_map<uint64_t, Inflight> inflight;

  std::atomic<uint64_t> stat_submitted{0};
  std::atomic<uint64_t> stat_completed{0};
  // Gauges mirrored from the loop-thread-owned containers so
  // rtdc_stats (called from arbitrary Python threads) never reads
  // queue/inflight/free_workers cross-thread — container size reads
  // race with the loop thread's mutations (UB, and a deque mid-resize
  // can return garbage). Refreshed by the loop thread each iteration,
  // the same ownership discipline as stat_submitted/stat_completed.
  std::atomic<uint64_t> stat_queue_depth{0};
  std::atomic<uint64_t> stat_inflight{0};
  std::atomic<uint64_t> stat_free{0};
};

Core *g_core = nullptr;
pthread_t g_thread;
std::atomic<bool> g_running{false};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void put_u32(std::vector<uint8_t> &v, uint32_t x) {
  v.push_back(x & 0xff);
  v.push_back((x >> 8) & 0xff);
  v.push_back((x >> 16) & 0xff);
  v.push_back((x >> 24) & 0xff);
}

void put_u64(std::vector<uint8_t> &v, uint64_t x) {
  for (int i = 0; i < 8; i++) v.push_back((x >> (8 * i)) & 0xff);
}

uint32_t get_u32(const uint8_t *p) {
  return uint32_t(p[0]) | uint32_t(p[1]) << 8 | uint32_t(p[2]) << 16 |
         uint32_t(p[3]) << 24;
}

uint64_t get_u64(const uint8_t *p) {
  uint64_t x = 0;
  for (int i = 0; i < 8; i++) x |= uint64_t(p[i]) << (8 * i);
  return x;
}

void epoll_mod(Core &c, int fd, bool want_write) {
  epoll_event ev{};
  ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0);
  ev.data.fd = fd;
  epoll_ctl(c.epfd, EPOLL_CTL_MOD, fd, &ev);
}

// Queue a frame (header built here around op+body parts) on a conn.
// Writes are DEFERRED to the end of the event-loop iteration
// (flush_dirty): a burst handled in one iteration — several worker
// RESULTs, a driver read full of SUBMITs — leaves per peer as ONE
// scatter-gather syscall instead of one send per frame. Same-iteration
// flushing keeps single-round-trip latency unchanged.
void send_frame(Core &c, Conn &conn, uint8_t op,
                const uint8_t *h, size_t hlen,
                const uint8_t *body, size_t blen) {
  std::vector<uint8_t> f;
  f.reserve(4 + 1 + hlen + blen);
  put_u32(f, uint32_t(1 + hlen + blen));
  f.push_back(op);
  f.insert(f.end(), h, h + hlen);
  if (blen) f.insert(f.end(), body, body + blen);
  conn.wq.emplace_back(std::move(f));
  if (!conn.dirty) {
    conn.dirty = true;
    c.dirty_fds.push_back(conn.fd);
  }
}

// Drain a conn's write queue with as few syscalls as the kernel allows
// (sendmsg over up to 64 queued frames); registers EPOLLOUT on a
// short write.
void flush_conn(Core &c, Conn &conn) {
  while (!conn.wq.empty()) {
    iovec iov[64];
    int cnt = 0;
    size_t off = conn.wq_off;
    for (auto &buf : conn.wq) {
      if (cnt == 64) break;
      iov[cnt].iov_base = const_cast<uint8_t *>(buf.data()) + off;
      iov[cnt].iov_len = buf.size() - off;
      off = 0;
      cnt++;
    }
    msghdr mh{};
    mh.msg_iov = iov;
    mh.msg_iovlen = cnt;
    ssize_t n = ::sendmsg(conn.fd, &mh, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        epoll_mod(c, conn.fd, true);
        return;
      }
      return;  // peer dead; EPOLLHUP will clean up
    }
    size_t left = size_t(n);
    while (left) {
      auto &front = conn.wq.front();
      size_t avail = front.size() - conn.wq_off;
      if (left >= avail) {
        left -= avail;
        conn.wq.pop_front();
        conn.wq_off = 0;
      } else {
        conn.wq_off += left;
        left = 0;
      }
    }
  }
}

void flush_dirty(Core &c) {
  if (c.dirty_fds.empty()) return;
  std::vector<int> fds;
  fds.swap(c.dirty_fds);
  for (int fd : fds) {
    auto it = c.conns.find(fd);
    if (it == c.conns.end()) continue;  // closed this iteration
    Conn &conn = it->second;
    if (!conn.dirty) continue;          // fd reuse duplicate entry
    conn.dirty = false;
    flush_conn(c, conn);
  }
}

void reply_driver(Core &c, int fd, uint64_t gen, uint64_t rid, uint8_t kind,
                  const uint8_t *blob, size_t blen) {
  auto it = c.conns.find(fd);
  if (it == c.conns.end() || it->second.gen != gen) return;  // driver gone
  uint8_t h[9];
  memcpy(h, &rid, 8);
  h[8] = kind;
  send_frame(c, it->second, OP_REPLY, h, 9, blob, blen);
}

void dispatch(Core &c);

void complete(Core &c, uint64_t tid, uint8_t kind, const uint8_t *blob,
              size_t blen) {
  auto it = c.inflight.find(tid);
  if (it == c.inflight.end()) return;
  Inflight inf = it->second;
  c.inflight.erase(it);
  auto dit = c.conns.find(inf.driver_fd);
  if (dit != c.conns.end() && dit->second.gen == inf.driver_gen)
    dit->second.rid_tid.erase(inf.rid);
  c.stat_completed.fetch_add(1, std::memory_order_relaxed);
  reply_driver(c, inf.driver_fd, inf.driver_gen, inf.rid, kind, blob, blen);
}

void exec_on(Core &c, Conn &worker, Pending &&p) {
  uint64_t tid = c.next_tid++;
  c.inflight[tid] = Inflight{p.rid, p.driver_fd, p.driver_gen,
                             worker.fd};
  auto dit = c.conns.find(p.driver_fd);
  if (dit != c.conns.end() && dit->second.gen == p.driver_gen)
    dit->second.rid_tid[p.rid] = tid;
  worker.inflight_tid = tid;
  uint8_t h[8];
  memcpy(h, &tid, 8);
  send_frame(c, worker, OP_EXEC, h, 8, p.payload.data(),
             p.payload.size());
}

void dispatch(Core &c) {
  while (!c.queue.empty() && !c.free_workers.empty()) {
    int wfd = c.free_workers.front();
    c.free_workers.pop_front();
    auto wit = c.conns.find(wfd);
    if (wit == c.conns.end()) continue;  // stale free-list entry
    Pending p = std::move(c.queue.front());
    c.queue.pop_front();
    exec_on(c, wit->second, std::move(p));
  }
}

void close_conn(Core &c, int fd) {
  auto it = c.conns.find(fd);
  if (it == c.conns.end()) return;
  Conn &conn = it->second;
  if (conn.is_worker) {
    // crash any task it was executing AND everything queued on it
    static const char err[] = "worker process died (fast lane)";
    if (conn.inflight_tid) {
      complete(c, conn.inflight_tid, KIND_CRASHED,
               reinterpret_cast<const uint8_t *>(err), sizeof(err) - 1);
    }
    for (auto &p : conn.own_queue)
      reply_driver(c, p.driver_fd, p.driver_gen, p.rid, KIND_CRASHED,
                   reinterpret_cast<const uint8_t *>(err),
                   sizeof(err) - 1);
    conn.own_queue.clear();
    if (conn.tag) c.tagged.erase(conn.tag);
    for (auto fit = c.free_workers.begin(); fit != c.free_workers.end();)
      fit = (*fit == fd) ? c.free_workers.erase(fit) : fit + 1;
  } else {
    // driver: purge its queued submits and orphan its in-flights
    for (auto qit = c.queue.begin(); qit != c.queue.end();)
      qit = (qit->driver_fd == fd && qit->driver_gen == conn.gen)
                ? c.queue.erase(qit)
                : qit + 1;
    for (auto &kv : c.conns) {
      if (!kv.second.is_worker) continue;
      auto &oq = kv.second.own_queue;
      for (auto qit = oq.begin(); qit != oq.end();)
        qit = (qit->driver_fd == fd && qit->driver_gen == conn.gen)
                  ? oq.erase(qit)
                  : qit + 1;
    }
    for (auto &kv : c.inflight)
      if (kv.second.driver_fd == fd && kv.second.driver_gen == conn.gen)
        kv.second.driver_fd = -1;  // result will be discarded
  }
  epoll_ctl(c.epfd, EPOLL_CTL_DEL, fd, nullptr);
  ::close(fd);
  c.conns.erase(it);
  dispatch(c);
}

// Handle one complete frame from a peer.
void on_frame(Core &c, int fd, const uint8_t *body, size_t len) {
  auto it = c.conns.find(fd);
  if (it == c.conns.end() || len < 1) return;
  Conn &conn = it->second;
  uint8_t op = body[0];
  const uint8_t *p = body + 1;
  size_t n = len - 1;
  switch (op) {
    case OP_HELLO_WORKER: {
      conn.is_worker = true;
      conn.inflight_tid = 0;
      c.free_workers.push_back(fd);
      dispatch(c);
      break;
    }
    case OP_HELLO_TAGGED: {
      if (n < 8) return;
      conn.is_worker = true;
      conn.inflight_tid = 0;
      conn.tag = get_u64(p);
      c.tagged[conn.tag] = fd;      // NOT in free_workers
      send_frame(c, conn, OP_HELLO_ACK, nullptr, 0, nullptr, 0);
      break;
    }
    case OP_SUBMIT_TARGETED: {
      if (n < 16) return;
      uint64_t rid = get_u64(p);
      uint64_t tag = get_u64(p + 8);
      c.stat_submitted.fetch_add(1, std::memory_order_relaxed);
      auto tit = c.tagged.find(tag);
      if (tit == c.tagged.end()) {
        static const char err[] = "no such targeted worker";
        reply_driver(c, fd, conn.gen, rid, KIND_CRASHED,
                     reinterpret_cast<const uint8_t *>(err),
                     sizeof(err) - 1);
        return;
      }
      auto wit = c.conns.find(tit->second);
      if (wit == c.conns.end()) return;
      Pending pend{rid, fd, conn.gen,
                   std::vector<uint8_t>(p + 16, p + n)};
      if (wit->second.inflight_tid == 0 && wit->second.own_queue.empty())
        exec_on(c, wit->second, std::move(pend));
      else
        wit->second.own_queue.emplace_back(std::move(pend));
      break;
    }
    case OP_SUBMIT: {
      if (n < 8) return;
      uint64_t rid = get_u64(p);
      c.stat_submitted.fetch_add(1, std::memory_order_relaxed);
      Pending pend{rid, fd, conn.gen,
                   std::vector<uint8_t>(p + 8, p + n)};
      c.queue.emplace_back(std::move(pend));
      dispatch(c);
      break;
    }
    case OP_RESULT: {
      if (n < 9 || !conn.is_worker) return;
      uint64_t tid = get_u64(p);
      uint8_t kind = p[8];
      // require the worker's current assignment: a duplicate/stale
      // RESULT must not double-free-list the worker (two concurrent
      // EXECs on one worker would interleave)
      if (conn.inflight_tid != tid) return;
      conn.inflight_tid = 0;
      complete(c, tid, kind, p + 9, n - 9);
      if (conn.tag) {
        // targeted worker: strictly its own FIFO, never the pool
        if (!conn.own_queue.empty()) {
          Pending pend = std::move(conn.own_queue.front());
          conn.own_queue.pop_front();
          exec_on(c, conn, std::move(pend));
        }
        break;
      }
      // pool worker is free again
      c.free_workers.push_back(fd);
      dispatch(c);
      break;
    }
    case OP_CANCEL: {
      if (n < 8) return;
      uint64_t rid = get_u64(p);
      uint8_t force = (n >= 9) ? p[8] : 0;
      // still queued? drop + CANCELLED (pool queue, then per-worker
      // targeted queues)
      for (auto qit = c.queue.begin(); qit != c.queue.end(); ++qit) {
        if (qit->rid == rid && qit->driver_fd == fd &&
            qit->driver_gen == conn.gen) {
          Pending pend = std::move(*qit);
          c.queue.erase(qit);
          reply_driver(c, pend.driver_fd, pend.driver_gen, rid,
                       KIND_CANCELLED, nullptr, 0);
          return;
        }
      }
      for (auto &kv : c.conns) {
        if (!kv.second.is_worker) continue;
        auto &oq = kv.second.own_queue;
        for (auto qit = oq.begin(); qit != oq.end(); ++qit) {
          if (qit->rid == rid && qit->driver_fd == fd &&
              qit->driver_gen == conn.gen) {
            oq.erase(qit);
            reply_driver(c, fd, conn.gen, rid, KIND_CANCELLED,
                         nullptr, 0);
            return;
          }
        }
      }
      // in flight? forward to the executing worker — soft interrupt,
      // or force (worker exits, surfacing as CRASHED, the classic
      // force-kill contract); the outcome flows back normally
      auto rit = conn.rid_tid.find(rid);
      if (rit != conn.rid_tid.end()) {
        auto iit = c.inflight.find(rit->second);
        if (iit != c.inflight.end() && iit->second.driver_fd == fd) {
          auto wit = c.conns.find(iit->second.worker_fd);
          if (wit != c.conns.end()) {
            uint8_t h[9];
            uint64_t tid = rit->second;
            memcpy(h, &tid, 8);
            h[8] = force;
            send_frame(c, wit->second, OP_CANCEL_EXEC, h, 9, nullptr, 0);
          }
        }
      }
      break;
    }
    case OP_PING: {
      if (n < 8) return;
      uint64_t rid = get_u64(p);
      // stats blob: 4 x u64 (queued, inflight, workers, completed)
      std::vector<uint8_t> s;
      put_u64(s, c.queue.size());
      put_u64(s, c.inflight.size());
      uint64_t nworkers = 0;
      for (auto &kv : c.conns)
        if (kv.second.is_worker) nworkers++;
      put_u64(s, nworkers);
      put_u64(s, c.stat_completed.load(std::memory_order_relaxed));
      reply_driver(c, fd, conn.gen, rid, KIND_PONG, s.data(), s.size());
      break;
    }
    default:
      break;
  }
}

void on_readable(Core &c, int fd) {
  auto it = c.conns.find(fd);
  if (it == c.conns.end()) return;
  Conn &conn = it->second;
  uint8_t tmp[64 * 1024];
  for (;;) {
    ssize_t r = ::recv(fd, tmp, sizeof(tmp), 0);
    if (r > 0) {
      conn.rbuf.insert(conn.rbuf.end(), tmp, tmp + r);
      continue;
    }
    if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    close_conn(c, fd);
    return;
  }
  size_t off = 0;
  for (;;) {
    if (conn.rbuf.size() - off < 4) break;
    uint32_t blen = get_u32(conn.rbuf.data() + off);
    if (blen > MAX_FRAME) {
      close_conn(c, fd);
      return;
    }
    if (conn.rbuf.size() - off < 4 + size_t(blen)) break;
    on_frame(c, fd, conn.rbuf.data() + off + 4, blen);
    // on_frame may have closed the conn (protocol error)
    auto again = c.conns.find(fd);
    if (again == c.conns.end() || &again->second != &conn) return;
    off += 4 + blen;
  }
  if (off) conn.rbuf.erase(conn.rbuf.begin(), conn.rbuf.begin() + off);
}

void on_writable(Core &c, int fd) {
  auto it = c.conns.find(fd);
  if (it == c.conns.end()) return;
  Conn &conn = it->second;
  flush_conn(c, conn);
  if (conn.wq.empty()) epoll_mod(c, fd, false);
}

void *loop_main(void *) {
  Core &c = *g_core;
  epoll_event evs[64];
  while (g_running.load(std::memory_order_acquire)) {
    int n = epoll_wait(c.epfd, evs, 64, 500);
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      if (fd == c.stop_fd) {
        uint64_t x;
        (void)!read(c.stop_fd, &x, 8);
        continue;
      }
      if (fd == c.listen_fd) {
        for (;;) {
          int cfd = ::accept(c.listen_fd, nullptr, nullptr);
          if (cfd < 0) break;
          set_nonblock(cfd);
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          Conn conn;
          conn.fd = cfd;
          conn.gen = c.next_gen++;
          c.conns.emplace(cfd, std::move(conn));
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.fd = cfd;
          epoll_ctl(c.epfd, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        close_conn(c, fd);
        continue;
      }
      if (evs[i].events & EPOLLIN) on_readable(c, fd);
      if (evs[i].events & EPOLLOUT) on_writable(c, fd);
    }
    // one coalesced write per peer for everything this iteration queued
    flush_dirty(c);
    // publish the stats gauges from the loop thread (sole owner of the
    // containers); cross-thread rtdc_stats reads only these atomics
    c.stat_queue_depth.store(c.queue.size(), std::memory_order_relaxed);
    c.stat_inflight.store(c.inflight.size(), std::memory_order_relaxed);
    c.stat_free.store(c.free_workers.size(), std::memory_order_relaxed);
  }
  return nullptr;
}

}  // namespace

extern "C" {

// Start the core; returns the bound port, or -1. host must be an IPv4
// literal (e.g. "0.0.0.0" or "127.0.0.1").
int rtdc_start(const char *host, int port) {
  if (g_running.load()) return -1;
  g_core = new Core();
  Core &c = *g_core;
  c.epfd = epoll_create1(0);
  c.listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(c.listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return -1;
  if (bind(c.listen_fd, reinterpret_cast<sockaddr *>(&addr),
           sizeof(addr)) != 0)
    return -1;
  if (listen(c.listen_fd, 256) != 0) return -1;
  socklen_t alen = sizeof(addr);
  getsockname(c.listen_fd, reinterpret_cast<sockaddr *>(&addr), &alen);
  set_nonblock(c.listen_fd);
  c.stop_fd = eventfd(0, EFD_NONBLOCK);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = c.listen_fd;
  epoll_ctl(c.epfd, EPOLL_CTL_ADD, c.listen_fd, &ev);
  ev.data.fd = c.stop_fd;
  epoll_ctl(c.epfd, EPOLL_CTL_ADD, c.stop_fd, &ev);
  g_running.store(true, std::memory_order_release);
  if (pthread_create(&g_thread, nullptr, loop_main, nullptr) != 0) {
    g_running.store(false);
    return -1;
  }
  return int(ntohs(addr.sin_port));
}

void rtdc_stop(void) {
  if (!g_running.load()) return;
  g_running.store(false, std::memory_order_release);
  uint64_t one = 1;
  (void)!write(g_core->stop_fd, &one, 8);
  pthread_join(g_thread, nullptr);
  Core &c = *g_core;
  for (auto &kv : c.conns) ::close(kv.first);
  ::close(c.listen_fd);
  ::close(c.stop_fd);
  ::close(c.epfd);
  delete g_core;
  g_core = nullptr;
}

// out[0..3] = queued, inflight, workers(free), submitted
void rtdc_stats(uint64_t *out) {
  if (!g_running.load() || !g_core) {
    out[0] = out[1] = out[2] = out[3] = 0;
    return;
  }
  out[0] = g_core->stat_queue_depth.load(std::memory_order_relaxed);
  out[1] = g_core->stat_inflight.load(std::memory_order_relaxed);
  out[2] = g_core->stat_free.load(std::memory_order_relaxed);
  out[3] = g_core->stat_submitted.load(std::memory_order_relaxed);
}

}  // extern "C"
