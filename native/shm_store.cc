// Shared-memory object store: the native host-buffer tier.
//
// Reference capability (NOT a port): plasma, src/ray/object_manager/plasma/
//   - store.h / object_store.h  : object table, create/seal/get/release
//   - plasma_allocator.cc       : allocator over shared memory (dlmalloc
//                                 there; first-fit coalescing free list here)
//   - eviction_policy.h         : LRU eviction of sealed, unreferenced
//   - create_request_queue.h    : create backpressure (here: create fails
//                                 with RTPU_ERR_FULL after eviction fails;
//                                 the Python layer queues/spills)
//
// Objects are immutable after seal. Clients map the same shm segment and
// read payloads zero-copy (numpy frombuffer on the offset). A pthread
// mutex (process-shared when needed) guards the metadata.

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <map>
#include <string>
#include <vector>

namespace {

constexpr int RTPU_OK = 0;
constexpr int RTPU_ERR_FULL = -1;
constexpr int RTPU_ERR_NOT_FOUND = -2;
constexpr int RTPU_ERR_EXISTS = -3;
constexpr int RTPU_ERR_NOT_SEALED = -4;
constexpr int RTPU_ERR_BAD = -5;

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
};

struct ObjectEntry {
  uint64_t offset = 0;
  uint64_t size = 0;
  bool sealed = false;
  bool deleted = false;  // delete requested while refs outstanding
  bool pinned = false;   // creator ref retained; delete() consumes it
  int64_t refcount = 0;
  uint64_t lru_tick = 0;  // last release time; eviction order
};

struct Store {
  void* base = nullptr;
  uint64_t capacity = 0;
  uint64_t used = 0;
  uint64_t tick = 0;
  int shm_fd = -1;
  std::string shm_name;
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  std::map<std::string, ObjectEntry> objects;
  std::vector<FreeBlock> free_list;  // sorted by offset, coalesced

  uint64_t allocate(uint64_t size) {
    // first fit
    for (size_t i = 0; i < free_list.size(); ++i) {
      if (free_list[i].size >= size) {
        uint64_t off = free_list[i].offset;
        free_list[i].offset += size;
        free_list[i].size -= size;
        if (free_list[i].size == 0) free_list.erase(free_list.begin() + i);
        used += size;
        return off;
      }
    }
    return UINT64_MAX;
  }

  void deallocate(uint64_t offset, uint64_t size) {
    used -= size;
    // insert sorted, coalesce neighbours
    size_t i = 0;
    while (i < free_list.size() && free_list[i].offset < offset) ++i;
    free_list.insert(free_list.begin() + i, FreeBlock{offset, size});
    // coalesce right
    if (i + 1 < free_list.size() &&
        free_list[i].offset + free_list[i].size == free_list[i + 1].offset) {
      free_list[i].size += free_list[i + 1].size;
      free_list.erase(free_list.begin() + i + 1);
    }
    // coalesce left
    if (i > 0 &&
        free_list[i - 1].offset + free_list[i - 1].size ==
            free_list[i].offset) {
      free_list[i - 1].size += free_list[i].size;
      free_list.erase(free_list.begin() + i);
    }
  }

  // Evict sealed refcount-0 objects (oldest release first) until
  // `needed` bytes could be contiguously available or nothing evictable.
  uint64_t evict(uint64_t needed) {
    uint64_t freed = 0;
    while (true) {
      if (allocatable(needed)) return freed;
      const std::string* victim = nullptr;
      uint64_t best_tick = UINT64_MAX;
      for (auto& kv : objects) {
        if (kv.second.sealed && kv.second.refcount == 0 &&
            !kv.second.deleted && kv.second.lru_tick < best_tick) {
          best_tick = kv.second.lru_tick;
          victim = &kv.first;
        }
      }
      if (victim == nullptr) return freed;
      auto it = objects.find(*victim);
      deallocate(it->second.offset, it->second.size);
      freed += it->second.size;
      objects.erase(it);
    }
  }

  bool allocatable(uint64_t size) const {
    for (const auto& b : free_list)
      if (b.size >= size) return true;
    return false;
  }
};

}  // namespace

extern "C" {

Store* rtpu_store_open(const char* name, uint64_t capacity) {
  std::string shm_name = std::string("/") + name;
  int fd = shm_open(shm_name.c_str(), O_CREAT | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)capacity) != 0) {
    close(fd);
    shm_unlink(shm_name.c_str());
    return nullptr;
  }
  void* base =
      mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(shm_name.c_str());
    return nullptr;
  }
  Store* s = new Store();
  s->base = base;
  s->capacity = capacity;
  s->shm_fd = fd;
  s->shm_name = shm_name;
  s->free_list.push_back(FreeBlock{0, capacity});
  return s;
}

void rtpu_store_close(Store* s, int unlink) {
  if (s == nullptr) return;
  munmap(s->base, s->capacity);
  close(s->shm_fd);
  if (unlink) shm_unlink(s->shm_name.c_str());
  delete s;
}

// Unlink the segment name WITHOUT unmapping: used at shutdown while
// zero-copy views into the arena are still alive in user code. The
// mapping (and Store) are deliberately leaked until process exit so
// those views stay valid; the name is removed so /dev/shm doesn't leak.
void rtpu_store_unlink(Store* s) {
  if (s == nullptr) return;
  shm_unlink(s->shm_name.c_str());
}

void* rtpu_store_base(Store* s) { return s->base; }
uint64_t rtpu_store_capacity(Store* s) { return s->capacity; }

uint64_t rtpu_store_used(Store* s) {
  pthread_mutex_lock(&s->mu);
  uint64_t u = s->used;
  pthread_mutex_unlock(&s->mu);
  return u;
}

uint64_t rtpu_store_num_objects(Store* s) {
  pthread_mutex_lock(&s->mu);
  uint64_t n = s->objects.size();
  pthread_mutex_unlock(&s->mu);
  return n;
}

// Reserve an unsealed buffer. Returns RTPU_OK and sets *offset, or error.
int rtpu_create(Store* s, const char* id, uint64_t size, uint64_t* offset) {
  pthread_mutex_lock(&s->mu);
  if (s->objects.count(id)) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_EXISTS;
  }
  if (size == 0 || size > s->capacity) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_BAD;
  }
  uint64_t off = s->allocate(size);
  if (off == UINT64_MAX) {
    s->evict(size);
    off = s->allocate(size);
  }
  if (off == UINT64_MAX) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_FULL;  // create backpressure: caller queues or spills
  }
  ObjectEntry e;
  e.offset = off;
  e.size = size;
  e.sealed = false;
  e.refcount = 1;  // creator holds a ref until seal+release
  s->objects[id] = e;
  *offset = off;
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

int rtpu_seal(Store* s, const char* id) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end()) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  it->second.sealed = true;
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

// Mark the object pinned: the creator keeps its create-time ref (does not
// release after seal) and rtpu_delete consumes it. Pinned objects are
// immune to LRU eviction (their refcount stays >= 1).
int rtpu_pin(Store* s, const char* id) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end()) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  it->second.pinned = true;
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

// Get a sealed object: increfs and returns offset+size.
int rtpu_get(Store* s, const char* id, uint64_t* offset, uint64_t* size) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end() || it->second.deleted) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  if (!it->second.sealed) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_SEALED;
  }
  it->second.refcount++;
  *offset = it->second.offset;
  *size = it->second.size;
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

int rtpu_release(Store* s, const char* id) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end()) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  if (it->second.refcount > 0) it->second.refcount--;
  it->second.lru_tick = ++s->tick;
  if (it->second.deleted && it->second.refcount == 0) {
    // Deferred delete: last outstanding reader is gone, free now.
    s->deallocate(it->second.offset, it->second.size);
    s->objects.erase(it);
  }
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

int rtpu_contains(Store* s, const char* id) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  int out = (it != s->objects.end() && it->second.sealed &&
             !it->second.deleted) ? 1 : 0;
  pthread_mutex_unlock(&s->mu);
  return out;
}

// Delete: the owner decided the object is dead. If readers still hold
// refs the buffer is only MARKED deleted and the deallocation happens at
// the last release (plasma semantics: clients' zero-copy buffers stay
// valid for their lifetime; the object just becomes unreachable for new
// gets).
int rtpu_delete(Store* s, const char* id) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end()) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  if (it->second.pinned) {
    // Consume the creator's retained ref — otherwise pinned objects
    // (the normal host-store path) would leak as permanent zombies.
    it->second.pinned = false;
    if (it->second.refcount > 0) it->second.refcount--;
  }
  if (it->second.refcount > 0) {
    it->second.deleted = true;
  } else {
    s->deallocate(it->second.offset, it->second.size);
    s->objects.erase(it);
  }
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

uint64_t rtpu_evict_bytes(Store* s, uint64_t needed) {
  pthread_mutex_lock(&s->mu);
  uint64_t freed = s->evict(needed);
  pthread_mutex_unlock(&s->mu);
  return freed;
}

}  // extern "C"
