// Shared-memory object store: the native host-buffer tier.
//
// Reference capability (NOT a port): plasma, src/ray/object_manager/plasma/
//   - store.h / object_store.h  : object table, create/seal/get/release
//   - plasma_allocator.cc       : allocator over shared memory (dlmalloc
//                                 there; first-fit coalescing free list here)
//   - eviction_policy.h         : LRU eviction of sealed, unreferenced
//   - create_request_queue.h    : create backpressure (here: create fails
//                                 with RTPU_ERR_FULL after eviction fails;
//                                 the Python layer queues/spills)
//   - client.cc / fling.cc      : cross-process clients; here the segment
//                                 is attached BY NAME (rtpu_store_attach)
//                                 and per-object refcounts live in a slot
//                                 table INSIDE the segment, so any attached
//                                 process can pin a buffer against eviction
//                                 without a store round trip.
//
// Segment layout:
//
//   [ ArenaHeader: magic | nslots | ExtSlot[NSLOTS] ]  (page aligned)
//   [ object data region: first-fit allocations ]
//
// Each object entry owns one ExtSlot for the lifetime of the entry. The
// slot's `refs` field is a PROCESS-SHARED refcount mutated with atomic
// builtins from any process that mapped the segment; `gen` bumps when
// the slot is recycled so a stale (slot, gen) pair is detectable. The
// store owner (the daemon) only frees/evicts a buffer when BOTH its
// in-process refcount and the slot's external refcount are zero — LRU
// eviction can never unmap bytes a worker still views.
//
// Objects are immutable after seal. Clients map the same shm segment and
// read payloads zero-copy (numpy frombuffer on the offset). A pthread
// mutex guards the owner's metadata; attached handles touch only the
// slot table (atomics) and raw ranges.

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <map>
#include <string>
#include <vector>

namespace {

constexpr int RTPU_OK = 0;
constexpr int RTPU_ERR_FULL = -1;
constexpr int RTPU_ERR_NOT_FOUND = -2;
constexpr int RTPU_ERR_EXISTS = -3;
constexpr int RTPU_ERR_NOT_SEALED = -4;
constexpr int RTPU_ERR_BAD = -5;

constexpr uint64_t RTPU_MAGIC = 0x314d485355505452ULL;  // "RTPUSHM1"
constexpr uint32_t RTPU_NSLOTS = 4096;
constexpr uint32_t RTPU_NO_SLOT = UINT32_MAX;

struct ExtSlot {
  uint32_t refs;  // process-shared refcount (atomic builtins only)
  uint32_t gen;   // bumped on slot recycle (forensics; the grant
                  // protocol never hands out a slot across a recycle:
                  // the owner only recycles at refs==0 under its
                  // mutex, and grants ref-before-reply)
};

struct ArenaHeader {
  uint64_t magic;
  uint32_t nslots;
  uint32_t reserved;
  ExtSlot slots[RTPU_NSLOTS];
};

// data region starts page-aligned past the header
constexpr uint64_t RTPU_DATA_OFF =
    ((sizeof(ArenaHeader) + 4095) / 4096) * 4096;

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
};

struct ObjectEntry {
  uint64_t offset = 0;
  uint64_t size = 0;
  bool sealed = false;
  bool deleted = false;  // delete requested while refs outstanding
  bool pinned = false;   // creator ref retained; delete() consumes it
  int64_t refcount = 0;
  uint32_t slot = RTPU_NO_SLOT;  // ext-refcount slot in the header
  uint64_t lru_tick = 0;  // last release time; eviction order
};

struct Store {
  void* base = nullptr;
  uint64_t capacity = 0;
  uint64_t used = 0;
  uint64_t tick = 0;
  int shm_fd = -1;
  bool attached = false;  // attach-only handle: no metadata, no unlink
  std::string shm_name;
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  std::map<std::string, ObjectEntry> objects;
  std::vector<FreeBlock> free_list;  // sorted by offset, coalesced
  std::vector<uint8_t> slot_used;    // owner-side slot allocation map
  uint32_t next_slot = 0;

  ArenaHeader* hdr() const { return reinterpret_cast<ArenaHeader*>(base); }

  uint32_t ext_refs(uint32_t slot) const {
    if (slot >= RTPU_NSLOTS) return 0;
    return __atomic_load_n(&hdr()->slots[slot].refs, __ATOMIC_ACQUIRE);
  }

  uint32_t alloc_slot() {
    for (uint32_t i = 0; i < RTPU_NSLOTS; ++i) {
      uint32_t cand = (next_slot + i) % RTPU_NSLOTS;
      if (!slot_used[cand]) {
        slot_used[cand] = 1;
        next_slot = (cand + 1) % RTPU_NSLOTS;
        return cand;
      }
    }
    return RTPU_NO_SLOT;  // table full: entry gets no ext slot
  }

  void free_slot(uint32_t slot) {
    if (slot >= RTPU_NSLOTS) return;
    // recycle: bump gen (a forensic marker for debugging recycled
    // slots), then clear usage. refs is 0 by the caller's contract.
    __atomic_add_fetch(&hdr()->slots[slot].gen, 1, __ATOMIC_RELEASE);
    slot_used[slot] = 0;
  }

  uint64_t allocate(uint64_t size) {
    // first fit
    for (size_t i = 0; i < free_list.size(); ++i) {
      if (free_list[i].size >= size) {
        uint64_t off = free_list[i].offset;
        free_list[i].offset += size;
        free_list[i].size -= size;
        if (free_list[i].size == 0) free_list.erase(free_list.begin() + i);
        used += size;
        return off;
      }
    }
    return UINT64_MAX;
  }

  void deallocate(uint64_t offset, uint64_t size) {
    used -= size;
    // insert sorted, coalesce neighbours
    size_t i = 0;
    while (i < free_list.size() && free_list[i].offset < offset) ++i;
    free_list.insert(free_list.begin() + i, FreeBlock{offset, size});
    // coalesce right
    if (i + 1 < free_list.size() &&
        free_list[i].offset + free_list[i].size == free_list[i + 1].offset) {
      free_list[i].size += free_list[i + 1].size;
      free_list.erase(free_list.begin() + i + 1);
    }
    // coalesce left
    if (i > 0 &&
        free_list[i - 1].offset + free_list[i - 1].size ==
            free_list[i].offset) {
      free_list[i - 1].size += free_list[i].size;
      free_list.erase(free_list.begin() + i);
    }
  }

  void free_entry(std::map<std::string, ObjectEntry>::iterator it) {
    deallocate(it->second.offset, it->second.size);
    free_slot(it->second.slot);
    objects.erase(it);
  }

  // Reap deleted entries whose last reference (in-process AND external)
  // is gone: external releases are silent atomic decrements from other
  // processes, so deferred deletes need this sweep to complete.
  uint64_t reap() {
    uint64_t freed = 0;
    for (auto it = objects.begin(); it != objects.end();) {
      auto cur = it++;
      if (cur->second.deleted && cur->second.refcount == 0 &&
          ext_refs(cur->second.slot) == 0) {
        freed += cur->second.size;
        free_entry(cur);
      }
    }
    return freed;
  }

  // Evict sealed refcount-0 ext-0 objects (oldest release first) until
  // `needed` bytes could be contiguously available or nothing evictable.
  uint64_t evict(uint64_t needed) {
    uint64_t freed = reap();  // deferred deletes first: already dead
    while (true) {
      if (allocatable(needed)) return freed;
      const std::string* victim = nullptr;
      uint64_t best_tick = UINT64_MAX;
      for (auto& kv : objects) {
        if (kv.second.sealed && kv.second.refcount == 0 &&
            !kv.second.deleted && ext_refs(kv.second.slot) == 0 &&
            kv.second.lru_tick < best_tick) {
          best_tick = kv.second.lru_tick;
          victim = &kv.first;
        }
      }
      if (victim == nullptr) return freed;
      auto it = objects.find(*victim);
      freed += it->second.size;
      free_entry(it);
    }
  }

  bool allocatable(uint64_t size) const {
    for (const auto& b : free_list)
      if (b.size >= size) return true;
    return false;
  }
};

}  // namespace

extern "C" {

// Create (or re-initialize) the segment as its OWNER: the header is
// reset and allocations start fresh. `capacity` is the DATA capacity —
// the segment is sized capacity + header so callers keep their byte
// accounting. Attach to a live store with rtpu_store_attach instead —
// opening an existing arena here would wipe its slot table under the
// owner.
Store* rtpu_store_open(const char* name, uint64_t capacity) {
  if (capacity == 0) return nullptr;
  uint64_t segment = capacity + RTPU_DATA_OFF;
  std::string shm_name = std::string("/") + name;
  int fd = shm_open(shm_name.c_str(), O_CREAT | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)segment) != 0) {
    close(fd);
    shm_unlink(shm_name.c_str());
    return nullptr;
  }
  void* base =
      mmap(nullptr, segment, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(shm_name.c_str());
    return nullptr;
  }
  Store* s = new Store();
  s->base = base;
  s->capacity = segment;
  s->shm_fd = fd;
  s->shm_name = shm_name;
  s->slot_used.assign(RTPU_NSLOTS, 0);
  s->free_list.push_back(FreeBlock{RTPU_DATA_OFF, capacity});
  // header init: zero the slot table, publish the magic LAST (release)
  // so an attacher that sees the magic sees an initialized table
  ArenaHeader* h = s->hdr();
  std::memset(h, 0, sizeof(ArenaHeader));
  h->nslots = RTPU_NSLOTS;
  __atomic_store_n(&h->magic, RTPU_MAGIC, __ATOMIC_RELEASE);
  return s;
}

// Attach an EXISTING segment by name (the fd-passing role of plasma's
// fling.cc, done via shm_open-by-name): maps read-write (direct puts
// write payload bytes), touches only raw ranges + the slot table.
// Returns nullptr when the segment is absent or not an arena.
Store* rtpu_store_attach(const char* name) {
  std::string shm_name = std::string("/") + name;
  int fd = shm_open(shm_name.c_str(), O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (uint64_t)st.st_size <= RTPU_DATA_OFF) {
    close(fd);
    return nullptr;
  }
  uint64_t capacity = (uint64_t)st.st_size;
  void* base =
      mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Store* s = new Store();
  s->base = base;
  s->capacity = capacity;
  s->shm_fd = fd;
  s->attached = true;
  s->shm_name = shm_name;
  if (__atomic_load_n(&s->hdr()->magic, __ATOMIC_ACQUIRE) != RTPU_MAGIC) {
    munmap(base, capacity);
    close(fd);
    delete s;
    return nullptr;
  }
  return s;
}

int rtpu_store_is_attached(Store* s) { return s->attached ? 1 : 0; }

void rtpu_store_close(Store* s, int unlink) {
  if (s == nullptr) return;
  munmap(s->base, s->capacity);
  close(s->shm_fd);
  if (unlink && !s->attached) shm_unlink(s->shm_name.c_str());
  delete s;
}

// Unlink the segment name WITHOUT unmapping: used at shutdown while
// zero-copy views into the arena are still alive in user code. The
// mapping (and Store) are deliberately leaked until process exit so
// those views stay valid; the name is removed so /dev/shm doesn't leak.
void rtpu_store_unlink(Store* s) {
  if (s == nullptr || s->attached) return;
  shm_unlink(s->shm_name.c_str());
}

void* rtpu_store_base(Store* s) { return s->base; }
uint64_t rtpu_store_capacity(Store* s) { return s->capacity; }
uint64_t rtpu_store_data_off(void) { return RTPU_DATA_OFF; }

uint64_t rtpu_store_used(Store* s) {
  pthread_mutex_lock(&s->mu);
  uint64_t u = s->used;
  pthread_mutex_unlock(&s->mu);
  return u;
}

uint64_t rtpu_store_num_objects(Store* s) {
  pthread_mutex_lock(&s->mu);
  uint64_t n = s->objects.size();
  pthread_mutex_unlock(&s->mu);
  return n;
}

// Reserve an unsealed buffer. Returns RTPU_OK and sets *offset, or error.
int rtpu_create(Store* s, const char* id, uint64_t size, uint64_t* offset) {
  pthread_mutex_lock(&s->mu);
  if (s->objects.count(id)) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_EXISTS;
  }
  if (size == 0 || size > s->capacity - RTPU_DATA_OFF) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_BAD;
  }
  uint64_t off = s->allocate(size);
  if (off == UINT64_MAX) {
    s->evict(size);
    off = s->allocate(size);
  }
  if (off == UINT64_MAX) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_FULL;  // create backpressure: caller queues or spills
  }
  ObjectEntry e;
  e.offset = off;
  e.size = size;
  e.sealed = false;
  e.refcount = 1;  // creator holds a ref until seal+release
  e.slot = s->alloc_slot();
  s->objects[id] = e;
  *offset = off;
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

int rtpu_seal(Store* s, const char* id) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end()) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  it->second.sealed = true;
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

// Entry metadata regardless of seal state (idempotent reserve support:
// a retried create finds the EXISTS entry and re-reads its offset).
int rtpu_stat(Store* s, const char* id, uint64_t* offset, uint64_t* size,
              int* sealed) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end() || it->second.deleted) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  *offset = it->second.offset;
  *size = it->second.size;
  *sealed = it->second.sealed ? 1 : 0;
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

// Mark the object pinned: the creator keeps its create-time ref (does not
// release after seal) and rtpu_delete consumes it. Pinned objects are
// immune to LRU eviction (their refcount stays >= 1).
int rtpu_pin(Store* s, const char* id) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end()) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  it->second.pinned = true;
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

// Get a sealed object: increfs (in-process) and returns offset+size.
int rtpu_get(Store* s, const char* id, uint64_t* offset, uint64_t* size) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end() || it->second.deleted) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  if (!it->second.sealed) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_SEALED;
  }
  it->second.refcount++;
  *offset = it->second.offset;
  *size = it->second.size;
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

// Get a sealed object for an EXTERNAL (attached-process) reader: the
// owner increments the object's process-shared slot refcount on the
// client's behalf and hands back (offset, size, slot). The client reads
// the range through its own mapping and releases with
// rtpu_ext_release(slot) — no store round trip on release, and eviction
// is blocked until the slot count drops to zero.
int rtpu_ext_get(Store* s, const char* id, uint64_t* offset,
                 uint64_t* size, uint32_t* slot) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end() || it->second.deleted) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  if (!it->second.sealed) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_SEALED;
  }
  if (it->second.slot == RTPU_NO_SLOT) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_BAD;  // slot table exhausted: caller takes blob path
  }
  __atomic_add_fetch(&s->hdr()->slots[it->second.slot].refs, 1,
                     __ATOMIC_ACQ_REL);
  *offset = it->second.offset;
  *size = it->second.size;
  *slot = it->second.slot;
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

// Release an external slot ref; valid from ANY handle (owner or
// attached) mapping the segment. CAS loop floors at zero so a buggy
// double-release cannot wrap the count and pin the slot forever.
void rtpu_ext_release(Store* s, uint32_t slot) {
  if (slot >= RTPU_NSLOTS) return;
  uint32_t* p = &s->hdr()->slots[slot].refs;
  uint32_t cur = __atomic_load_n(p, __ATOMIC_ACQUIRE);
  while (cur > 0) {
    if (__atomic_compare_exchange_n(p, &cur, cur - 1, false,
                                    __ATOMIC_ACQ_REL, __ATOMIC_ACQUIRE))
      return;
  }
}

// Bulk slot decrement for crash reclamation: drop up to n refs in one
// CAS (same floor-at-zero discipline as rtpu_ext_release) and return
// how many were actually dropped, so the caller's grant ledger can
// account for refs the dead client had already released locally.
uint32_t rtpu_ext_release_n(Store* s, uint32_t slot, uint32_t n) {
  if (slot >= RTPU_NSLOTS || n == 0) return 0;
  uint32_t* p = &s->hdr()->slots[slot].refs;
  uint32_t cur = __atomic_load_n(p, __ATOMIC_ACQUIRE);
  while (cur > 0) {
    uint32_t drop = cur < n ? cur : n;
    if (__atomic_compare_exchange_n(p, &cur, cur - drop, false,
                                    __ATOMIC_ACQ_REL, __ATOMIC_ACQUIRE))
      return drop;
  }
  return 0;
}

uint32_t rtpu_ext_refs(Store* s, uint32_t slot) {
  if (slot >= RTPU_NSLOTS) return 0;
  return __atomic_load_n(&s->hdr()->slots[slot].refs, __ATOMIC_ACQUIRE);
}

int rtpu_release(Store* s, const char* id) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end()) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  if (it->second.refcount > 0) it->second.refcount--;
  it->second.lru_tick = ++s->tick;
  if (it->second.deleted && it->second.refcount == 0 &&
      s->ext_refs(it->second.slot) == 0) {
    // Deferred delete: last outstanding reader is gone, free now.
    // (An external ref still held leaves it for reap()/evict().)
    s->free_entry(it);
  }
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

int rtpu_contains(Store* s, const char* id) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  int out = (it != s->objects.end() && it->second.sealed &&
             !it->second.deleted) ? 1 : 0;
  pthread_mutex_unlock(&s->mu);
  return out;
}

// Delete: the owner decided the object is dead. If readers still hold
// refs (in-process or external) the buffer is only MARKED deleted and
// the deallocation happens at the last release / next reap (plasma
// semantics: clients' zero-copy buffers stay valid for their lifetime;
// the object just becomes unreachable for new gets).
int rtpu_delete(Store* s, const char* id) {
  pthread_mutex_lock(&s->mu);
  auto it = s->objects.find(id);
  if (it == s->objects.end()) {
    pthread_mutex_unlock(&s->mu);
    return RTPU_ERR_NOT_FOUND;
  }
  if (it->second.pinned) {
    // Consume the creator's retained ref — otherwise pinned objects
    // (the normal host-store path) would leak as permanent zombies.
    it->second.pinned = false;
    if (it->second.refcount > 0) it->second.refcount--;
  }
  if (it->second.refcount > 0 || s->ext_refs(it->second.slot) > 0) {
    it->second.deleted = true;
  } else {
    s->free_entry(it);
  }
  pthread_mutex_unlock(&s->mu);
  return RTPU_OK;
}

uint64_t rtpu_evict_bytes(Store* s, uint64_t needed) {
  pthread_mutex_lock(&s->mu);
  uint64_t freed = s->evict(needed);
  pthread_mutex_unlock(&s->mu);
  return freed;
}

uint64_t rtpu_reap(Store* s) {
  pthread_mutex_lock(&s->mu);
  uint64_t freed = s->reap();
  pthread_mutex_unlock(&s->mu);
  return freed;
}

}  // extern "C"
