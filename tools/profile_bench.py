"""Component-level timing of the bench_400m train step on the live chip.

Answers, in order: (1) what bf16 matmul TFLOP/s can this chip actually
deliver through the tunnel (roofline sanity), (2) how step time splits
across forward / backward / optimizer, (3) what the flash-attention
kernel costs vs the XLA fallback, (4) whether per-dispatch tunnel
latency is material (time vs batch scaling).
"""
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=5, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
        jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    dev = jax.devices()[0]
    print("device:", dev.device_kind, dev.platform)

    # 1. raw matmul roofline
    for m, k, n in ((8192, 8192, 8192), (16384, 1024, 4096)):
        a = jnp.ones((m, k), jnp.bfloat16)
        b = jnp.ones((k, n), jnp.bfloat16)
        f = jax.jit(lambda a, b: a @ b)
        dt = timeit(f, a, b, n=10)
        tflops = 2 * m * k * n / dt / 1e12
        print(f"matmul {m}x{k}x{n}: {dt*1e3:.2f} ms = {tflops:.1f} TFLOP/s")

    # 2. dispatch latency: tiny op round-trip
    tiny = jnp.ones((8, 8), jnp.float32)
    f = jax.jit(lambda x: x + 1)
    dt = timeit(f, tiny, n=20)
    print(f"tiny-op dispatch: {dt*1e3:.3f} ms")

    from ray_tpu.models.llama import LlamaConfig, LlamaModel
    from ray_tpu.train.spmd import make_train_step

    cfg = LlamaConfig.bench_400m()
    batch, seq = 8, 2048
    model = LlamaModel(cfg)
    ts = make_train_step(model)
    params, opt_state = ts.init_fn(jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)

    # 3. forward-only loss
    loss_fn = jax.jit(lambda p, t, g: model.loss(p, t, g))
    dt_fwd = timeit(loss_fn, params, tokens, targets, n=5)
    print(f"forward loss: {dt_fwd*1e3:.1f} ms")

    # forward without remat
    cfg_nr = LlamaConfig.bench_400m()
    object.__setattr__(cfg_nr, "remat", False)
    model_nr = LlamaModel(cfg_nr)
    loss_nr = jax.jit(lambda p, t, g: model_nr.loss(p, t, g))
    dt_fwd_nr = timeit(loss_nr, params, tokens, targets, n=5)
    print(f"forward loss (no remat flag): {dt_fwd_nr*1e3:.1f} ms")

    # 4. grad step (no optimizer)
    grad_fn = jax.jit(jax.value_and_grad(
        lambda p: model.loss(p, tokens, targets)))
    dt_grad = timeit(grad_fn, params, n=5)
    print(f"value_and_grad: {dt_grad*1e3:.1f} ms")

    # full step
    def run_step(p, o):
        return ts.step_fn(p, o, (tokens, targets))
    p2, o2 = params, opt_state
    for _ in range(2):
        p2, o2, m = run_step(p2, o2)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(5):
        p2, o2, m = run_step(p2, o2)
    jax.block_until_ready(m["loss"])
    dt_step = (time.perf_counter() - t0) / 5
    print(f"full train step: {dt_step*1e3:.1f} ms")

    # 5. attention kernel alone: flash vs xla
    from ray_tpu.ops.attention import attention
    B, S, H, D = 8, 2048, 8, 128
    q = jnp.ones((B, S, H, D), jnp.bfloat16)
    k = jnp.ones((B, S, cfg.n_kv_heads, D), jnp.bfloat16)
    v = k
    fl = jax.jit(lambda q, k, v: attention(q, k, v, causal=True,
                                           use_flash=True))
    xl = jax.jit(lambda q, k, v: attention(q, k, v, causal=True,
                                           use_flash=False))
    print(f"flash attn fwd: {timeit(fl, q, k, v, n=10)*1e3:.2f} ms")
    print(f"xla attn fwd:   {timeit(xl, q, k, v, n=10)*1e3:.2f} ms")

    gfl = jax.jit(jax.grad(lambda q: fl(q, k, v).sum()))
    gxl = jax.jit(jax.grad(lambda q: xl(q, k, v).sum()))
    print(f"flash attn grad: {timeit(gfl, q, n=5)*1e3:.2f} ms")
    print(f"xla attn grad:   {timeit(gxl, q, n=5)*1e3:.2f} ms")


if __name__ == "__main__":
    main()
