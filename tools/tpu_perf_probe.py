"""Staged TPU perf probes: ONE measurement per process, flap-resilient.

Usage: python tools/tpu_perf_probe.py <stage>

Each stage opens the backend, runs one tightly-scoped measurement (1-3
compiles max), prints one "PROBE <stage> <json>" line, and exits — so a
flapping tunnel window can be milked stage by stage (driven by
tools/tpu_profile_all.sh; results land in tools/evidence/).

  matmul     raw bf16 matmul TFLOP/s (roofline sanity, 2 shapes)
  dispatch   tiny-op round-trip latency
  attn       flash vs xla attention forward at bench shapes
  attn_bwd   flash-VJP (blockwise) vs xla attention backward
  fwd        bench_400m forward loss
  step       full train step (the bench measurement)
  step_nr    train step with remat DISABLED (memory permitting)
  step_xla   train step with attention_impl="xla"
  step_b16   train step at batch 16 (remat on)

Interpret against the v5e roofline: 394 bf16 TFLOP/s, 819 GB/s HBM.
"""

from __future__ import annotations

import faulthandler
import json
import sys
import time

faulthandler.dump_traceback_later(270, exit=True)


def timeit(fn, *args, n=5, warm=2):
    import jax
    for _ in range(warm):
        out = fn(*args)
        jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def emit(stage, **kw):
    print(f"PROBE {stage} {json.dumps(kw)}", flush=True)


def stage_matmul():
    import jax
    import jax.numpy as jnp
    out = {}
    for m, k, n in ((8192, 8192, 8192), (16384, 1024, 4096)):
        a = jnp.ones((m, k), jnp.bfloat16)
        b = jnp.ones((k, n), jnp.bfloat16)
        f = jax.jit(lambda a, b: a @ b)
        dt = timeit(f, a, b, n=10)
        out[f"{m}x{k}x{n}"] = round(2 * m * k * n / dt / 1e12, 1)
    emit("matmul", tflops=out)


def stage_dispatch():
    import jax
    import jax.numpy as jnp
    tiny = jnp.ones((8, 8), jnp.float32)
    f = jax.jit(lambda x: x + 1)
    dt = timeit(f, tiny, n=20)
    emit("dispatch", roundtrip_ms=round(dt * 1e3, 3))


def _attn_inputs():
    import jax.numpy as jnp
    B, S, H, Hkv, D = 8, 2048, 8, 4, 128
    q = jnp.ones((B, S, H, D), jnp.bfloat16)
    k = jnp.ones((B, S, Hkv, D), jnp.bfloat16)
    return q, k, k


def stage_attn():
    import jax
    from ray_tpu.ops.attention import attention
    q, k, v = _attn_inputs()
    fl = jax.jit(lambda q, k, v: attention(q, k, v, causal=True,
                                           use_flash=True))
    xl = jax.jit(lambda q, k, v: attention(q, k, v, causal=True,
                                           use_flash=False))
    emit("attn",
         flash_ms=round(timeit(fl, q, k, v, n=10) * 1e3, 2),
         xla_ms=round(timeit(xl, q, k, v, n=10) * 1e3, 2))


def stage_attn_bwd():
    import jax
    from ray_tpu.ops.attention import attention
    q, k, v = _attn_inputs()
    gfl = jax.jit(jax.grad(lambda q: attention(
        q, k, v, causal=True, use_flash=True).astype('float32').sum()))
    gxl = jax.jit(jax.grad(lambda q: attention(
        q, k, v, causal=True, use_flash=False).astype('float32').sum()))
    emit("attn_bwd",
         flash_ms=round(timeit(gfl, q, n=5) * 1e3, 2),
         xla_ms=round(timeit(gxl, q, n=5) * 1e3, 2))


def _bench_model(remat=True, attn="flash", batch=8, fb=None,
                 remat_policy="full"):
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.models.llama import LlamaConfig, LlamaModel
    cfg = LlamaConfig.bench_400m()
    cfg = dataclasses.replace(cfg, remat=remat, attention_impl=attn,
                              remat_policy=remat_policy)
    if fb:
        cfg = dataclasses.replace(cfg, flash_block_q=fb,
                                  flash_block_k=fb)
    model = LlamaModel(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, 2048)),
                         jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    return cfg, model, tokens, targets


def stage_fwd():
    import jax
    cfg, model, tokens, targets = _bench_model()
    params = model.init(jax.random.key(0))
    loss_fn = jax.jit(lambda p: model.loss(p, tokens, targets))
    dt = timeit(loss_fn, params, n=5)
    flops = 2 * cfg.num_params() * tokens.size
    emit("fwd", ms=round(dt * 1e3, 1),
         fwd_tflops=round(flops / dt / 1e12, 1))


def run_step(stage, remat=True, attn="flash", batch=8, fb=None,
             remat_policy="full"):
    import jax
    from ray_tpu.train.spmd import make_train_step
    cfg, model, tokens, targets = _bench_model(remat, attn, batch, fb,
                                               remat_policy)
    ts = make_train_step(model)
    p, o = ts.init_fn(jax.random.key(0))
    bt = (tokens, targets)
    for _ in range(2):
        p, o, m = ts.step_fn(p, o, bt)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(5):
        p, o, m = ts.step_fn(p, o, bt)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / 5
    n = cfg.num_params()
    mfu = (tokens.size / dt) * 6 * n / 394e12
    emit(stage, step_ms=round(dt * 1e3, 1),
         tok_s=round(tokens.size / dt, 1), mfu=round(mfu, 4))


STAGES = {
    "matmul": stage_matmul,
    "dispatch": stage_dispatch,
    "attn": stage_attn,
    "attn_bwd": stage_attn_bwd,
    "fwd": stage_fwd,
    "step": lambda: run_step("step"),
    "step_nr": lambda: run_step("step_nr", remat=False),
    "step_xla": lambda: run_step("step_xla", attn="xla"),
    "step_b16": lambda: run_step("step_b16", batch=16),
    "step_fb256": lambda: run_step("step_fb256", fb=256),
    "step_fb512": lambda: run_step("step_fb512", fb=512),
    "step_dots": lambda: run_step("step_dots", remat_policy="dots"),
}


def main():
    stage = sys.argv[1] if len(sys.argv) > 1 else "matmul"
    if stage not in STAGES:
        raise SystemExit(f"unknown stage {stage}; have {list(STAGES)}")
    import jax
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        emit(stage, error="cpu backend, no TPU")
        return 1
    STAGES[stage]()
    return 0


if __name__ == "__main__":
    sys.exit(main())
