#!/usr/bin/env bash
# Chaos tier: replay the seeded failpoint schedules across BOTH
# execution topologies (in-process virtual nodes and
# RAY_TPU_CLUSTER=daemons head+daemon OS processes).
#
# The schedules themselves are deterministic per seed (see
# tests/test_chaos.py and docs/fault_tolerance.md); this script sweeps
# the topologies — the daemons-specific tests boot their own cluster
# regardless of the env var, the topology-agnostic tests (the rpc-drop
# replays, stream kill) run under whichever topology the env selects.
#
# Drain-under-chaos rides this sweep: test_chaos_drain_* races a
# graceful node drain against failpoint-injected migration faults,
# worker crashes, and deadline escalation, across both topologies.
#
# Usage: tools/run_chaos.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "=== chaos tier: in-process topology ==="
RAY_TPU_CLUSTER= python -m pytest tests/test_chaos.py -q -m chaos \
    -p no:cacheprovider -p no:randomly "$@"

echo "=== chaos tier: daemons topology ==="
RAY_TPU_CLUSTER=daemons python -m pytest tests/test_chaos.py -q -m chaos \
    -p no:cacheprovider -p no:randomly "$@"

echo "chaos tier: OK (both topologies)"
