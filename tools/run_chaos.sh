#!/usr/bin/env bash
# Chaos tier: replay the seeded failpoint schedules across BOTH
# execution topologies (in-process virtual nodes and
# RAY_TPU_CLUSTER=daemons head+daemon OS processes).
#
# The schedules themselves are deterministic per seed (see
# tests/test_chaos.py and docs/fault_tolerance.md); this script sweeps
# the topologies — the daemons-specific tests boot their own cluster
# regardless of the env var, the topology-agnostic tests (the rpc-drop
# replays, stream kill) run under whichever topology the env selects.
#
# Drain-under-chaos rides this sweep: test_chaos_drain_* races a
# graceful node drain against failpoint-injected migration faults,
# worker crashes, and deadline escalation, across both topologies.
#
# The process-kill tier (last stage) SIGKILLs real object-plane
# clients — a worker mid-zero-copy-view, a writer between reserve and
# seal, an external attacher holding live grants — and fails on a
# nonzero end-of-run leak gauge: every scenario asserts
# ray_tpu_arena_slot_refs{state=refs} returns to zero and the evicted
# bytes are re-allocatable, with no daemon restart.
#
# Usage: tools/run_chaos.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# the reclamation, network-partition and memory-pressure scenarios get
# their own stages
PROCKILL="sigkill or sweep_backstop"
NETWORK="netchaos"
MEMORY="chaos_memory"

echo "=== chaos tier: in-process topology ==="
RAY_TPU_CLUSTER= python -m pytest tests/test_chaos.py -q -m chaos \
    -k "not ($PROCKILL) and not ($NETWORK) and not ($MEMORY)" \
    -p no:cacheprovider -p no:randomly "$@"

echo "=== chaos tier: daemons topology ==="
RAY_TPU_CLUSTER=daemons python -m pytest tests/test_chaos.py -q -m chaos \
    -k "not ($PROCKILL) and not ($NETWORK) and not ($MEMORY)" \
    -p no:cacheprovider -p no:randomly "$@"

echo "=== chaos tier: lock-sanitizer seed (in-process topology) ==="
# One seeded replay with the runtime lock-order sanitizer armed: the
# tracked_lock classes (cluster/daemon/head/node/worker/fast_lane —
# the same names tools/raylint's static lock-order pass reports on)
# build the live acquired-before graph while faults fire. -W error
# escalates main-thread inversions eagerly; inversions recorded on
# runtime (daemon) threads fail the session via the conftest
# pytest_sessionfinish gate on GRAPH.violations — a warning raised on
# a background thread would otherwise die with that thread.
RAY_TPU_LOCK_SANITIZER=1 RAY_TPU_CLUSTER= python -m pytest \
    tests/test_chaos.py -q -m chaos -k "101" \
    -W "error::ray_tpu._private.lock_sanitizer.LockOrderViolation" \
    -p no:cacheprovider -p no:randomly "$@"

echo "=== chaos tier: process-kill reclamation (both topologies) ==="
# SIGKILL campaign over every seed, swept over both topology env
# settings (the scenarios boot their own daemons cluster either way —
# the sweep varies the surrounding driver runtime, matching the other
# stages). A leaked grant, a stranded reservation, or a daemon restart
# fails the run inside the tests themselves.
RAY_TPU_CLUSTER= python -m pytest tests/test_chaos.py -q -m chaos \
    -k "$PROCKILL" -p no:cacheprovider -p no:randomly "$@"
RAY_TPU_CLUSTER=daemons python -m pytest tests/test_chaos.py -q -m chaos \
    -k "$PROCKILL" -p no:cacheprovider -p no:randomly "$@"

echo "=== chaos tier: network partitions (both topologies) ==="
# Deterministic network-chaos campaign (tests/test_netchaos.py has the
# tier-1 units; this is the cluster-level replay): one-way
# driver->daemon split mid-burst, daemon<->head partition across a
# death-mark + heal (fenced-result counter must move), a flapping link
# under a queued drain, and a partition racing a graceful drain —
# every seed, swept over both topology env settings (the scenarios
# boot their own daemons cluster either way, like the kill tier).
RAY_TPU_CLUSTER= python -m pytest tests/test_chaos.py -q -m chaos \
    -k "$NETWORK" -p no:cacheprovider -p no:randomly "$@"
RAY_TPU_CLUSTER=daemons python -m pytest tests/test_chaos.py -q -m chaos \
    -k "$NETWORK" -p no:cacheprovider -p no:randomly "$@"

echo "=== chaos tier: memory pressure (both topologies) ==="
# OOM ballast campaign (docs/fault_tolerance.md "Memory pressure &
# graceful degradation"): worker host-memory ballast under the memory
# monitor's preemption policy, arena overfill through the spill tier
# with a pinned zero-copy view held across the storm, and a forced
# hard-pressure window (pressure.level failpoint armed per-node via the
# fail_points RPC) over an arena overfill — every seed, swept over both
# topology env settings (the scenarios boot their own daemons cluster
# either way). Lost tasks, a spilled pinned entry, leaked slot refs, or
# a node stuck off level ok fail the run inside the tests themselves.
RAY_TPU_CLUSTER= python -m pytest tests/test_chaos.py -q -m chaos \
    -k "$MEMORY" -p no:cacheprovider -p no:randomly "$@"
RAY_TPU_CLUSTER=daemons python -m pytest tests/test_chaos.py -q -m chaos \
    -k "$MEMORY" -p no:cacheprovider -p no:randomly "$@"

echo "chaos tier: OK (both topologies + sanitized seed + process-kill + network + memory)"
