"""Generate PERF.md: the committed core-op perf envelope (VERDICT r2 #7).

Runs `_private/perf.py` in both execution modes (in-process virtual
nodes, and real head+daemon OS processes) in fresh subprocesses and
renders one markdown table. Usage: `python tools/gen_perf.py > PERF.md`.
"""

from __future__ import annotations

import json
import os
import platform
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_mode(mode: str, envelope: bool = False) -> list:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env["JAX_PLATFORMS"] = "cpu"
    env["RAY_TPU_LOG_TO_DRIVER"] = "0"
    if envelope:
        env["PERF_ENVELOPE"] = "1"
    else:
        # the degraded retry must not re-inherit an exported
        # PERF_ENVELOPE=1 from the caller's environment
        env.pop("PERF_ENVELOPE", None)
    if mode == "daemons":
        env["RAY_TPU_CLUSTER"] = "daemons"
    else:
        env.pop("RAY_TPU_CLUSTER", None)
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu._private.perf"],
        capture_output=True, text=True, env=env,
        timeout=(3600 if envelope else 900))
    if out.returncode != 0:
        if envelope:
            # The envelope slices (100k drain / 5000 actors) exceed some
            # sandboxes' thread/PID limits and get SIGKILLed; degrade to
            # the core rows — the envelope section simply drops out of
            # PERF.md on hosts that cannot hold it.
            print(f"# {mode} envelope run failed (rc={out.returncode}); "
                  f"retrying without envelope", file=sys.stderr)
            return run_mode(mode, envelope=False)
        raise RuntimeError(f"{mode} perf run failed:\n{out.stderr[-2000:]}")
    return [json.loads(line) for line in out.stdout.splitlines()
            if line.strip().startswith("{")]


def main() -> int:
    envelope = os.environ.get("PERF_ENVELOPE", "1") == "1"
    rows = {}
    for mode in ("in-process", "daemons"):
        rows[mode] = {r["name"]: r
                      for r in run_mode(
                          mode, envelope=(envelope
                                          and mode == "in-process"))}

    def is_envelope(name: str) -> bool:
        if name in ("actors_5000_create_and_call",
                    "spread_256_tasks_64_nodes"):
            return True
        # the queued-drain ladder emits one row per rung that held
        # (queued_100000/300000/1000000_task_drain), so match by size
        m = re.match(r"queued_(\d+)_task_drain$", name)
        return bool(m) and int(m.group(1)) >= 100_000

    env_names = [n for n in rows["in-process"] if is_envelope(n)]
    names = [n for n in rows["in-process"] if n not in env_names]
    print("# PERF — core-op envelope (committed record)")
    print()
    print(f"Recorded {time.strftime('%Y-%m-%d')} on "
          f"{os.cpu_count()} CPUs ({platform.machine()}), "
          f"Python {platform.python_version()}, CPU jax backend. "
          f"Harness: `ray_tpu/_private/perf.py` "
          f"(reference: `python/ray/_private/ray_perf.py:95`, envelope "
          f"targets `release/benchmarks/README.md:25-31`). Regenerate "
          f"with `python tools/gen_perf.py > PERF.md`.")
    print()
    print("| benchmark | in-process | daemons (wire protocol) |")
    print("|---|---|---|")
    for name in names:
        a = rows["in-process"].get(name, {})
        b = rows["daemons"].get(name, {})

        def fmt(r):
            if "throughput_per_s" in r:
                return f"{r['throughput_per_s']:,.0f}/s"
            if "drain_per_s" in r:
                return (f"submit {r['submit_per_s']:,.0f}/s, "
                        f"drain {r['drain_per_s']:,.0f}/s "
                        f"({r['total_seconds']}s total)")
            return "—"
        print(f"| {name} | {fmt(a)} | {fmt(b)} |")
    env_rows = [rows["in-process"][n] for n in env_names
                if n in rows["in-process"]]
    if env_rows:
        print()
        print("## Scale envelope (single-host slices of "
              "`release/benchmarks/README.md:5-31`)")
        print()
        print("| envelope probe | result |")
        print("|---|---|")
        for r in env_rows:
            if "drain_per_s" in r:
                print(f"| {r['name']} | submit {r['submit_per_s']:,.0f}/s, "
                      f"drain {r['drain_per_s']:,.0f}/s "
                      f"({r['total_seconds']}s total) |")
            elif r["name"] == "spread_256_tasks_64_nodes":
                print(f"| {r['name']} | {r['count']} distinct nodes hit, "
                      f"{r['throughput_per_s']:,.0f} tasks/s |")
            else:
                print(f"| {r['name']} | {r['throughput_per_s']:,.0f}/s "
                      f"({r['seconds']}s for {r['count']}) |")
    print()
    print("Notes: daemons mode pays the full serialization + RPC + "
          "process boundary on every op — the honest cost of the "
          "reference's default topology. The queued-drain rows probe "
          "the single-node scheduler backlog (reference envelope: "
          "1M+ queued; this record uses 10k and 30k per run to stay "
          "CI-sized; the 3x row shows the drain rate HOLDS as the "
          "backlog grows — no superlinear degradation). "
          "`burst_submit_batched` bursts two-return tasks — off the "
          "native fast lane — so the daemons column measures the "
          "batched push_task_batch wire path end to end "
          "(docs/performance.md). The envelope section appears only "
          "on hosts whose thread/PID limits can hold the 5000-actor "
          "slice; the queued-drain LADDER (100k -> 300k -> 1M) "
          "commits every rung the box held and stops at the first "
          "rung it could not — the largest committed rung is this "
          "box's backlog envelope, degrading gracefully on small "
          "hosts. Numbers are only comparable within one "
          "host generation: see tools/evidence/batching_ab_r6.md "
          "(control-plane submit 4.4-6.5x) and "
          "tools/evidence/drain_ab_r10.md (drain-side result "
          "pipeline: queued-drain rows within 2x of submit — ratio "
          "~0.5 — with no round-trip/submit regression) for the "
          "same-box A/Bs that isolate code changes from hardware "
          "changes.")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
