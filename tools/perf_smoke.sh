#!/usr/bin/env bash
# Control-plane perf smoke: a ~10-second mini envelope (tasks/s + a
# queued-submit drain) compared against the committed floor. Fails
# (exit 1) when any probe regresses more than 30% below its floor —
# wire it into CI next to the tier-1 tests (see docs/performance.md).
#
# Usage:
#   tools/perf_smoke.sh                      # in-process topology
#   tools/perf_smoke.sh daemons              # head+daemon wire topology
#   tools/perf_smoke.sh [daemons] --rebaseline   # rewrite the floor
#
# Floors live per topology: tools/perf_floor.json (in-process) and
# tools/perf_floor_daemons.json (daemons).
set -euo pipefail
cd "$(dirname "$0")/.."

REBASE=""
FLOOR="tools/perf_floor.json"
for arg in "$@"; do
    case "$arg" in
        --rebaseline) REBASE="--rebaseline" ;;
        daemons)
            export RAY_TPU_CLUSTER=daemons
            FLOOR="tools/perf_floor_daemons.json"
            ;;
        local|in-process) ;;
        *) echo "unknown argument: $arg" >&2; exit 2 ;;
    esac
done
export JAX_PLATFORMS=cpu
export RAY_TPU_LOG_TO_DRIVER=0
export PERF_SMOKE_FLOOR="$FLOOR"
# NOTE: probe 9's lag sampler reads the gauge at the DEFAULT 250ms
# probe interval on purpose. Arming a faster probe (50ms) to get more
# samples measurably perturbs the paired overhead probes on a
# GIL-saturated burst — three consecutive runs tripped the tracing
# gate with a fast probe armed, none with the default.

python - $REBASE <<'EOF'
import json
import os
import sys
import time

FLOOR_PATH = os.environ["PERF_SMOKE_FLOOR"]
TOLERANCE = 0.30    # fail on >30% regression vs the committed floor
rebaseline = "--rebaseline" in sys.argv

import ray_tpu  # noqa: E402

ray_tpu.init(num_nodes=1, resources={"CPU": 8})


@ray_tpu.remote
def noop():
    return None


@ray_tpu.remote(num_returns=2)
def duo():
    return None, None


results = {}

# probe 1: round-trip tasks/s (~4s)
ray_tpu.get([noop.remote() for _ in range(100)])    # warm
t0 = time.perf_counter()
count = 0
while time.perf_counter() - t0 < 4.0:
    ray_tpu.get([noop.remote() for _ in range(100)])
    count += 100
results["tasks_per_second"] = round(count / (time.perf_counter() - t0), 1)

# probe 2: queued burst submit rate + drain ratio (3k tasks, ~2-6s).
# queued_drain_ratio = drain rate ÷ submit rate — the drain-side
# result-pipeline metric (ROADMAP item 4: drain within 2x of submit
# means ratio >= 0.5). Ratio of the SAME burst, so box speed cancels.
t0 = time.perf_counter()
refs = [noop.remote() for _ in range(3000)]
t_submit = time.perf_counter() - t0
results["queued_submit_per_s"] = round(3000 / t_submit, 1)
ray_tpu.get(refs)
t_drain = time.perf_counter() - t0 - t_submit
# drain rate / submit rate = (N/t_drain) / (N/t_submit) = t_submit/t_drain
results["queued_drain_ratio"] = round(t_submit / t_drain, 3)

# probe 3: batched classic-path burst — exercises the submit coalescer
# wire path when this script is invoked with the `daemons` mode
# (process workers in-process otherwise). Tracing is ON by default, so
# this row measures the traced rate.


def burst_batched(n=600) -> float:
    t0 = time.perf_counter()
    refs = [duo.remote() for _ in range(n)]
    ray_tpu.get([r for ab in refs for r in ab])
    return n / (time.perf_counter() - t0)


burst_batched()     # warm the classic path
results["burst_batched_per_s"] = round(burst_batched(), 1)

# probe 4: tracing overhead — the same burst with spans ON vs OFF.
# MUST run before the object-plane probe: a put/get phase leaves the
# driver-side object bookkeeping in a state where traced bursts pay a
# consistent ~20% (measured on BOTH wire cores, so it predates the
# async rebuild — see ROADMAP). This row measures the documented ~1%
# steady-state tracing tax on the burst path, not that interaction.
# Methodology: 5 PAIRED bursts in one cluster with BALANCED ordering
# (on-first on even rounds, off-first on odd) and the MEDIAN of the
# per-pair ratios; the raw per-pair ratios are printed with the verdict
# so a trip is diagnosable from the CI log. Anything weaker is a noise
# lottery on shared hardware: single-burst scatter here is +-25%, the
# real overhead ~1% (docs/observability.md). 3 pairs of 200-task
# bursts false-tripped repeatedly under correlated box load — one in
# eight even under pure coin-flip noise; 5 pairs of 300 needs 4/5
# slower AND an over-budget median. Budget: <= 5% on
# burst_submit_batched.
import statistics  # noqa: E402

from ray_tpu._private.config import apply_system_config  # noqa: E402


def traced_burst(on: bool) -> float:
    apply_system_config({"task_trace": on})
    return burst_batched(300)


ratios = []
for i in range(5):
    if i % 2 == 0:
        r_on = traced_burst(True)
        r_off = traced_burst(False)
    else:
        r_off = traced_burst(False)
        r_on = traced_burst(True)
    ratios.append(r_on / r_off)
apply_system_config(None)   # restore env/default flag resolution

# probe 7: continuous-sampler overhead — the same burst with the
# driver-process stack sampler ON (25 Hz, well above the suggested
# production 5-10 Hz) vs OFF, same interleaved-median methodology as
# the tracing row. Budget: <= 3% (docs/observability.md).
from ray_tpu.util import profiling as _profiling  # noqa: E402


def profiled_burst(on: bool) -> float:
    if on:
        _profiling.start_process_sampler("driver", hz=25.0)
    else:
        _profiling.stop_process_sampler()
    return burst_batched(300)


p_ratios = []
for i in range(5):
    if i % 2 == 0:
        p_on = profiled_burst(True)
        p_off = profiled_burst(False)
    else:
        p_off = profiled_burst(False)
        p_on = profiled_burst(True)
    p_ratios.append(p_on / p_off)
_profiling.stop_process_sampler()

# probe 6: object plane — worker-side 1MiB put+get round trips, in
# MiB/s moved (put and get each move the payload). In daemons mode
# this is the zero-copy arena path (direct put + frombuffer get); in
# the in-process topology it measures the worker-pipe round trip
# (docs/object_plane.md). Runs AFTER the paired overhead probes — see
# the probe 4 comment for the interaction this ordering avoids.
# The committed floor is the PRESSURE-DISARMED baseline: the
# memory_pressure subsystem (spill tier + PressureController) defaults
# off and this script never arms it, so the row doubles as the
# zero-overhead-when-disarmed gate for that subsystem
# (docs/fault_tolerance.md "Memory pressure & graceful degradation").


@ray_tpu.remote
def _put_get_1mib(seconds=1.5):
    import time as _time

    import numpy as _np

    import ray_tpu as _rt
    a = _np.ones((1 << 20) // 4, dtype=_np.float32)
    r = _rt.put(a)
    _rt.get([r])        # warm
    n = 0
    t0 = _time.perf_counter()
    while _time.perf_counter() - t0 < seconds:
        r = _rt.put(a)
        b = _rt.get([r])[0]
        assert b.nbytes == 1 << 20
        del b, r
        n += 1
    return n, _time.perf_counter() - t0


n_pg, dt_pg = ray_tpu.get(_put_get_1mib.remote(), timeout=60.0)
results["put_get_1MiB_mbps"] = round(n_pg * 2 / dt_pg, 1)

# probe 5: serving data plane — a small OPEN-LOOP burst through a
# 2-replica deployment via ray_tpu.loadgen (handle -> depth-aware P2C
# router -> replica), the row every serving-perf PR is gated against
# (docs/serving.md). Constant arrivals + fixed seed keep it stable.
from ray_tpu import serve  # noqa: E402
from ray_tpu.loadgen import (HandleTarget, LoadSpec,  # noqa: E402
                             SLO, run_load)


@serve.deployment(num_replicas=2, max_ongoing_requests=32)
def _smoke_echo(payload):
    return {"ok": True}


handle = serve.run(_smoke_echo.bind())
handle.remote({}).result(timeout=30)    # warm the route
spec = LoadSpec(rate=150.0, duration_s=2.0, clients=16,
                arrival="constant", stream=False, seed=0,
                prompt_len=4, output_len=1, slo=SLO(e2e_s=1.0),
                timeout_s=30, drain_timeout_s=60)
serving = run_load(HandleTarget(handle, stream=False, timeout_s=30),
                   spec)
results["serving_requests_per_s"] = serving["requests_per_second"]
if serving["requests"]["errors"]:
    print(f"serving probe errors: {serving['error_samples']}",
          file=sys.stderr)
    results["serving_requests_per_s"] = 0.0
serve.shutdown()
overhead = max(0.0, (1.0 - statistics.median(ratios)) * 100.0)
# Single-burst scatter on shared hardware is +-30-70%, far above the 5%
# budget, so the gate demands a CONSISTENT regression: a real overhead
# shows tracing slower in (nearly) every pair; noise flips signs. A
# median above budget with mixed signs reports but does not fail.
slower = sum(1 for r in ratios if r < 1.0)
consistent = slower >= len(ratios) - 1
results["tracing_overhead_pct"] = round(overhead, 1)
results["tracing_overhead_consistent"] = bool(consistent)
p_overhead = max(0.0, (1.0 - statistics.median(p_ratios)) * 100.0)
p_slower = sum(1 for r in p_ratios if r < 1.0)
p_consistent = p_slower >= len(p_ratios) - 1
results["profiling_overhead_pct"] = round(p_overhead, 1)
results["profiling_overhead_consistent"] = bool(p_consistent)

# probe 8: fair-share overhead on the queued-drain path — the SAME
# submit-then-drain burst with the tenancy subsystem disabled
# (`fairshare` off, the default — one enabled-flag check per submit)
# vs fairshare ON (verdicts + ledger ordering + quota gates + per-task
# accounting on the drain side). Both arms run on a FRESH cluster with
# an identical warm-up so neither inherits the long-warmed state of
# probes 1-7 (an asymmetric warm reads as fair-share overhead), and
# the arms alternate to spread box drift evenly.
# Budget: the ON path costs <= 3% drain rate vs OFF — which bounds the
# off-path tax at strictly less (docs/multitenancy.md).


def drain_rate(fairshare: bool, n=1500) -> float:
    kw = {"_system_config": {"fairshare": True}} if fairshare else {}
    ray_tpu.init(num_nodes=1, resources={"CPU": 8}, **kw)
    ray_tpu.get([noop.remote() for _ in range(300)])    # warm pools
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    t_submit = time.perf_counter() - t0
    ray_tpu.get(refs)
    rate = n / (time.perf_counter() - t0 - t_submit)
    ray_tpu.shutdown()
    return rate


ray_tpu.shutdown()
off_rates, on_rates = [], []
for _ in range(3):
    off_rates.append(drain_rate(False))
    on_rates.append(drain_rate(True))
fs_overhead = max(0.0, (1.0 - statistics.median(on_rates)
                        / statistics.median(off_rates)) * 100.0)
# cross-cluster rounds are noisier than same-cluster pairs: only a
# separation of the full samples counts as a consistent regression
fs_consistent = max(on_rates) < min(off_rates)
results["fairshare_overhead_pct"] = round(fs_overhead, 1)
results["fairshare_overhead_consistent"] = bool(fs_consistent)

# probe 9: async-core A/B on the queued submit→drain burst — the
# event-loop core's headline row (docs/performance.md "Asyncio core").
# Same fresh-cluster alternating methodology as probe 8: each arm gets
# its own cluster and warm-up; the env var carries the core choice
# into daemon processes (daemons mode) while apply_system_config pins
# the driver side. async_submit_drain_ratio is the end-to-end burst
# rate (submit + drain of the SAME n tasks) async ÷ threaded, so box
# speed cancels; its floor lives in tools/perf_floor*.json. While the
# arms run, a sampler thread records the PEAK driver-loop lag gauge —
# loop_lag_max_s is a budget row (lower is better) gated as a ceiling.
import threading  # noqa: E402

from ray_tpu.util import metrics as _metrics  # noqa: E402


def core_burst(async_on: bool, n=2000) -> float:
    os.environ["RAY_TPU_ASYNC_CORE"] = "1" if async_on else "0"
    apply_system_config({"async_core": async_on})
    ray_tpu.init(num_nodes=1, resources={"CPU": 8})
    try:
        ray_tpu.get([noop.remote() for _ in range(300)])    # warm pools
        t0 = time.perf_counter()
        refs = [noop.remote() for _ in range(n)]
        ray_tpu.get(refs)
        return n / (time.perf_counter() - t0)
    finally:
        ray_tpu.shutdown()


_lag_peak = [0.0]
_lag_stop = threading.Event()


def _sample_lag() -> None:
    # every gauge sample in this registry is this process's loop; max
    # over tags keys the row to the worst moment, not the last probe
    while not _lag_stop.wait(0.02):
        m = _metrics.registry().get("ray_tpu_event_loop_lag_seconds")
        if m is None:
            continue
        for _key, v in m.samples():
            if v > _lag_peak[0]:
                _lag_peak[0] = v


_sampler = threading.Thread(target=_sample_lag, daemon=True,
                            name="perf-smoke-lag-sampler")
_sampler.start()
ab_on, ab_off = [], []
for i in range(3):
    if i % 2 == 0:
        ab_on.append(core_burst(True))
        ab_off.append(core_burst(False))
    else:
        ab_off.append(core_burst(False))
        ab_on.append(core_burst(True))
_lag_stop.set()
_sampler.join(timeout=5.0)
os.environ.pop("RAY_TPU_ASYNC_CORE", None)
apply_system_config(None)
results["async_submit_drain_ratio"] = round(
    statistics.median(ab_on) / statistics.median(ab_off), 3)
results["loop_lag_max_s"] = round(_lag_peak[0], 3)

print(json.dumps(results, indent=2))

# tracing_overhead_pct / profiling_overhead_pct are BUDGET rows (lower
# is better), checked against fixed ceilings below — never against the
# rate floors.
TRACING_OVERHEAD_MAX = 5.0
PROFILING_OVERHEAD_MAX = 3.0
FAIRSHARE_OVERHEAD_MAX = 3.0

if rebaseline:
    floors = {k: v for k, v in results.items()
              if not k.startswith(("tracing_overhead",
                                   "profiling_overhead",
                                   "fairshare_overhead"))}
    with open(FLOOR_PATH, "w") as fh:
        json.dump(floors, fh, indent=2)
        fh.write("\n")
    print(f"wrote {FLOOR_PATH}")
    sys.exit(0)

try:
    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
except FileNotFoundError:
    print(f"no {FLOOR_PATH}; run tools/perf_smoke.sh "
          f"[daemons] --rebaseline")
    sys.exit(1)

# The object-plane row's daemons floor assumes the native shm arena;
# a no-compiler box runs the classic RPC path by design (graceful
# fallback) and must not fail the gate for missing g++.
try:
    from ray_tpu.native_store import available as _native_available
    _have_native = _native_available()
except Exception:
    _have_native = False
if not _have_native and "put_get_1MiB_mbps" in floors:
    print("put_get_1MiB_mbps: skipped (no native arena on this box; "
          "classic path is ungated)")
    floors.pop("put_get_1MiB_mbps")

failed = False
for name, floor in floors.items():
    if name.startswith(("tracing_overhead", "profiling_overhead",
                        "fairshare_overhead")):
        continue    # legacy floor entry: budget-checked below instead
    got = results.get(name, 0.0)
    if name.startswith("loop_lag"):
        # budget row, lower is better: the committed value is a
        # CEILING. The absolute slack is one probe interval — a real
        # blocking-callback regression shows sustained lag comparable
        # to the interval or worse, while a near-zero committed
        # baseline must not trip on one scheduler hiccup.
        limit = max(floor * (1.0 + TOLERANCE), floor + 0.25)
        verdict = "ok" if got <= limit else "REGRESSION"
        print(f"{name}: {got:.3f}s vs budget {floor:.3f}s "
              f"(max {limit:.3f}s) {verdict}")
        if got > limit:
            failed = True
        continue
    limit = floor * (1.0 - TOLERANCE)
    verdict = "ok" if got >= limit else "REGRESSION"
    if name.endswith("_ratio"):     # dimensionless rows (drain÷submit)
        print(f"{name}: {got:.2f} vs floor {floor:.2f} "
              f"(min {limit:.2f}) {verdict}")
    else:
        print(f"{name}: {got:,.0f}/s vs floor {floor:,.0f}/s "
              f"(min {limit:,.0f}/s) {verdict}")
    if got < limit:
        failed = True
trip = overhead > TRACING_OVERHEAD_MAX and consistent
verdict = ("REGRESSION" if trip else
           "ok" if overhead <= TRACING_OVERHEAD_MAX else
           "ok (noise: mixed-sign pairs)")
raw = "[" + ", ".join(f"{r:.3f}" for r in ratios) + "]"
print(f"tracing_overhead_pct: {overhead:.1f}% vs budget "
      f"{TRACING_OVERHEAD_MAX:.0f}% "
      f"({slower}/{len(ratios)} pairs slower, on/off ratios {raw}) "
      f"{verdict}")
if trip:
    failed = True
p_trip = p_overhead > PROFILING_OVERHEAD_MAX and p_consistent
p_verdict = ("REGRESSION" if p_trip else
             "ok" if p_overhead <= PROFILING_OVERHEAD_MAX else
             "ok (noise: mixed-sign pairs)")
p_raw = "[" + ", ".join(f"{r:.3f}" for r in p_ratios) + "]"
print(f"profiling_overhead_pct: {p_overhead:.1f}% vs budget "
      f"{PROFILING_OVERHEAD_MAX:.0f}% "
      f"({p_slower}/{len(p_ratios)} pairs slower, on/off ratios "
      f"{p_raw}) {p_verdict}")
if p_trip:
    failed = True
fs_trip = fs_overhead > FAIRSHARE_OVERHEAD_MAX and fs_consistent
fs_verdict = ("REGRESSION" if fs_trip else
              "ok" if fs_overhead <= FAIRSHARE_OVERHEAD_MAX else
              "ok (noise: overlapping samples)")
fs_raw = ("on=[" + ", ".join(f"{r:,.0f}" for r in on_rates)
          + "] off=[" + ", ".join(f"{r:,.0f}" for r in off_rates) + "]")
print(f"fairshare_overhead_pct: {fs_overhead:.1f}% vs budget "
      f"{FAIRSHARE_OVERHEAD_MAX:.0f}% (queued-drain rates/s {fs_raw}) "
      f"{fs_verdict}")
if fs_trip:
    failed = True
sys.exit(1 if failed else 0)
EOF
