#!/usr/bin/env bash
# Control-plane perf smoke: a ~10-second mini envelope (tasks/s + a
# queued-submit drain) compared against the committed floor. Fails
# (exit 1) when any probe regresses more than 30% below its floor —
# wire it into CI next to the tier-1 tests (see docs/performance.md).
#
# Usage:
#   tools/perf_smoke.sh                      # in-process topology
#   tools/perf_smoke.sh daemons              # head+daemon wire topology
#   tools/perf_smoke.sh [daemons] --rebaseline   # rewrite the floor
#
# Floors live per topology: tools/perf_floor.json (in-process) and
# tools/perf_floor_daemons.json (daemons).
set -euo pipefail
cd "$(dirname "$0")/.."

REBASE=""
FLOOR="tools/perf_floor.json"
for arg in "$@"; do
    case "$arg" in
        --rebaseline) REBASE="--rebaseline" ;;
        daemons)
            export RAY_TPU_CLUSTER=daemons
            FLOOR="tools/perf_floor_daemons.json"
            ;;
        local|in-process) ;;
        *) echo "unknown argument: $arg" >&2; exit 2 ;;
    esac
done
export JAX_PLATFORMS=cpu
export RAY_TPU_LOG_TO_DRIVER=0
export PERF_SMOKE_FLOOR="$FLOOR"

python - $REBASE <<'EOF'
import json
import os
import sys
import time

FLOOR_PATH = os.environ["PERF_SMOKE_FLOOR"]
TOLERANCE = 0.30    # fail on >30% regression vs the committed floor
rebaseline = "--rebaseline" in sys.argv

import ray_tpu  # noqa: E402

ray_tpu.init(num_nodes=1, resources={"CPU": 8})


@ray_tpu.remote
def noop():
    return None


@ray_tpu.remote(num_returns=2)
def duo():
    return None, None


results = {}

# probe 1: round-trip tasks/s (~4s)
ray_tpu.get([noop.remote() for _ in range(100)])    # warm
t0 = time.perf_counter()
count = 0
while time.perf_counter() - t0 < 4.0:
    ray_tpu.get([noop.remote() for _ in range(100)])
    count += 100
results["tasks_per_second"] = round(count / (time.perf_counter() - t0), 1)

# probe 2: queued burst submit rate (3k tasks, ~2-4s)
t0 = time.perf_counter()
refs = [noop.remote() for _ in range(3000)]
results["queued_submit_per_s"] = round(3000 / (time.perf_counter() - t0), 1)
ray_tpu.get(refs)

# probe 3: batched classic-path burst — exercises the submit coalescer
# wire path when this script is invoked with the `daemons` mode
# (process workers in-process otherwise)
t0 = time.perf_counter()
refs = [duo.remote() for _ in range(600)]
ray_tpu.get([r for ab in refs for r in ab])
results["burst_batched_per_s"] = round(600 / (time.perf_counter() - t0), 1)

ray_tpu.shutdown()
print(json.dumps(results, indent=2))

if rebaseline:
    with open(FLOOR_PATH, "w") as fh:
        json.dump(results, fh, indent=2)
        fh.write("\n")
    print(f"wrote {FLOOR_PATH}")
    sys.exit(0)

try:
    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
except FileNotFoundError:
    print(f"no {FLOOR_PATH}; run tools/perf_smoke.sh "
          f"[daemons] --rebaseline")
    sys.exit(1)

failed = False
for name, floor in floors.items():
    got = results.get(name, 0.0)
    limit = floor * (1.0 - TOLERANCE)
    verdict = "ok" if got >= limit else "REGRESSION"
    print(f"{name}: {got:,.0f}/s vs floor {floor:,.0f}/s "
          f"(min {limit:,.0f}/s) {verdict}")
    if got < limit:
        failed = True
sys.exit(1 if failed else 0)
EOF
