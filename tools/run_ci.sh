#!/usr/bin/env bash
# CI entry: the full suite in the default (in-process) topology, then the
# protocol-sensitive suites again over REAL head+daemon OS processes
# (reference: the default topology there IS processes — VERDICT r2 weak
# #3 asks both paths to stay covered).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== full suite (in-process topology) ==="
python -m pytest tests/ -x -q

echo "=== wire-protocol topology (RAY_TPU_CLUSTER=daemons) ==="
RAY_TPU_CLUSTER=daemons python -m pytest \
    tests/test_core_tasks.py tests/test_actors.py \
    tests/test_placement_group.py tests/test_serve.py \
    tests/test_train.py tests/test_data.py \
    tests/test_hash_shuffle.py tests/test_train_elastic.py -q
# daemon-dependent suites manage their own clusters (xlang C++ tier,
# sharded device objects across real processes)
python -m pytest tests/test_cpp_client.py tests/test_device_objects.py -q
