#!/usr/bin/env bash
# CI entry, three stages (reference: the default topology there IS real
# processes — python/ray/tests/conftest.py:588 — so the WHOLE suite must
# hold over the wire, not just protocol-picked files):
#   1. full suite, in-process topology (the fast path)
#   2. full suite again over REAL head+daemon OS processes
#      (RAY_TPU_CLUSTER=daemons) — suites that manage their own
#      clusters/processes (multihost, cluster_daemons, fast_lane) simply
#      ignore the env and run identically
#   3. the scale-envelope tier (100k drain / 5k actors / 64 nodes),
#      excluded from stages 1-2 by pytest.ini's `-m "not envelope"`
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== stage 0: observability + async core (fail-fast) ==="
# cheap fail-fast pass over the dashboard/trace/federation/profiling
# tests plus the asyncio-core suite (loop affinity, coalesced writes,
# failpoint/netchaos parity, mixed-cluster hello bit — the wire every
# other suite rides on). They also run inside stages 1-2; this
# surfaces wire/observability breakage in seconds instead of after
# the full sweep.
python -m pytest tests/test_observability.py tests/test_profiling.py \
    tests/test_async_core.py -x -q

echo "=== stage 0.5: raylint (static concurrency/protocol analysis) ==="
# fail-fast AST passes: guarded-by, lock-order, blocking-under-lock,
# rpc-drift, failpoint-registry, async-discipline, loop-affinity,
# capability-drift, frame-schema (+ the metric-registry mini-pass) —
# see docs/static_analysis.md. Exit 1 = NEW findings (baseline-covered
# ones pass); runs in ~3s so protocol, lock-discipline, or asyncio-
# readiness drift surfaces before any suite boots a cluster.
python -m tools.raylint ray_tpu/

echo "=== stage 1: full suite (in-process topology) ==="
python -m pytest tests/ -x -q

echo "=== stage 2: full suite (RAY_TPU_CLUSTER=daemons wire topology) ==="
RAY_TPU_CLUSTER=daemons python -m pytest tests/ -q

echo "=== stage 3: scale-envelope tier ==="
python -m pytest tests/ -m envelope -q
