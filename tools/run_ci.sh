#!/usr/bin/env bash
# CI entry: the full suite in the default (in-process) topology, then the
# protocol-sensitive suites again over REAL head+daemon OS processes
# (reference: the default topology there IS processes — VERDICT r2 weak
# #3 asks both paths to stay covered).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== full suite (in-process topology) ==="
python -m pytest tests/ -x -q

echo "=== wire-protocol topology (RAY_TPU_CLUSTER=daemons) ==="
RAY_TPU_CLUSTER=daemons python -m pytest \
    tests/test_core_tasks.py tests/test_actors.py \
    tests/test_placement_group.py tests/test_serve.py \
    tests/test_train.py tests/test_data.py -q
