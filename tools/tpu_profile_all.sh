#!/bin/bash
# Milk a TPU tunnel window: run every perf probe stage, each in its own
# process under a timeout, appending results to tools/evidence/.
# A stage that hangs (tunnel flap) is killed and the next one still runs
# (the tunnel may come back). Exit code 0 if ANY stage produced data.
cd "$(dirname "$0")/.."
OUT=tools/evidence/tpu_perf_probes.log
mkdir -p tools/evidence
echo "=== $(date '+%F %T') profile run ===" >> "$OUT"
got=1
# MFU localizers first: a tunnel window may be short (the 03:18 window
# lasted ~25 min) — matmul roofline, attention split, and the three
# biggest step A/Bs must land before the nice-to-haves.
for stage in matmul attn attn_bwd step step_fb512 step_xla step_fb256 step_dots fwd dispatch step_nr step_b16; do
  echo "--- $stage $(date '+%T')" >> "$OUT"
  if timeout -k 5 300 python tools/tpu_perf_probe.py "$stage" >> "$OUT" 2>&1; then
    got=0
  else
    echo "(stage $stage failed/timed out rc=$?)" >> "$OUT"
  fi
done
tail -40 "$OUT"
exit $got
