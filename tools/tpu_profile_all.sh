#!/bin/bash
# Milk a TPU tunnel window: run every perf probe stage, each in its own
# process under a timeout, appending results to tools/evidence/.
# A stage that hangs (tunnel flap) is killed and the next one still runs
# (the tunnel may come back). Exit code 0 if ANY stage produced data.
cd "$(dirname "$0")/.."
OUT=tools/evidence/tpu_perf_probes.log
mkdir -p tools/evidence
echo "=== $(date '+%F %T') profile run ===" >> "$OUT"
got=1
for stage in matmul dispatch attn attn_bwd fwd step step_xla step_fb256 step_fb512 step_dots step_nr step_b16; do
  echo "--- $stage $(date '+%T')" >> "$OUT"
  if timeout -k 5 300 python tools/tpu_perf_probe.py "$stage" >> "$OUT" 2>&1; then
    got=0
  else
    echo "(stage $stage failed/timed out rc=$?)" >> "$OUT"
  fi
done
tail -40 "$OUT"
exit $got
