"""TPU backend-init probe with hang diagnostics (VERDICT r3 #1).

Three rounds of bench runs hung at "importing jax backend" with no
traceback.  This probe captures WHERE: ``faulthandler.dump_traceback_later``
fires every 30 s into stderr, TPU plugin logging is forced on, and each
stage heartbeats.  Run under a timeout; the dumped stacks survive the kill.

Usage: timeout -k 5 300 python tools/tpu_probe.py 2>&1 | tee probe.log
"""
from __future__ import annotations

import faulthandler
import os
import sys
import time

T0 = time.time()


def hb(msg: str) -> None:
    print(f"[probe +{time.time() - T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


def main() -> int:
    # Maximum plugin verbosity.
    os.environ.setdefault("TPU_STDERR_LOG_LEVEL", "0")
    os.environ.setdefault("TPU_MIN_LOG_LEVEL", "0")
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "0")
    os.environ.setdefault("TPU_VMODULE", "tpu_driver=2")
    os.environ.setdefault("JAX_DEBUG_LOG_MODULES", "jax._src.xla_bridge")
    # Periodic stack dumps: if anything below blocks, we learn the frame.
    faulthandler.enable()
    faulthandler.dump_traceback_later(30, repeat=True, file=sys.stderr)

    hb("importing jax")
    import jax

    hb(f"jax {jax.__version__} imported; calling jax.devices()")
    devs = jax.devices()
    hb(f"devices: {devs}")

    d = devs[0]
    hb(f"platform={d.platform} kind={getattr(d, 'device_kind', '?')}")

    import jax.numpy as jnp

    hb("running tiny matmul")
    x = jnp.ones((256, 256), jnp.bfloat16)
    y = (x @ x).block_until_ready()
    hb(f"matmul ok: {float(y[0, 0])}")

    hb("running jitted matmul")
    f = jax.jit(lambda a: a @ a)
    z = f(x).block_until_ready()
    hb(f"jit ok: {float(z[0, 0])}")
    print("PROBE_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
