"""raylint: AST-based concurrency & wire-protocol static analysis.

The reactive half of this repo's correctness tooling is the runtime
``lock_sanitizer`` (it observes the locks tests happen to exercise) and
the chaos tier (it injects the faults a schedule happens to contain).
raylint is the proactive half: whole-package static passes over the
recurring bug classes this codebase has actually shipped —

- ``guarded-by``        annotated shared state touched outside its lock
- ``lock-order``        cycles in the static acquired-before graph
- ``blocking-under-lock``  wire I/O / sleeps / RPC inside a held lock
- ``rpc-drift``         client method literals vs server dispatch tables
- ``failpoint-registry``  fire() names unique + documented + tested
- ``async-discipline``  no blocking calls / dropped coroutines on the loop
- ``loop-affinity``     ``#: loop-only`` defs reached only from the loop
- ``capability-drift``  hello capability flags advertised, gated, honored
- ``frame-schema``      payload keys at send sites match consumer reads
- ``metric-registry``   ray_tpu_* metric names documented in the catalogue

Run: ``python -m tools.raylint ray_tpu/`` (CI stage 0.5, fail-fast).
Docs: ``docs/static_analysis.md``. No ``--fix`` by design: every fix is
a semantic change a human (or a baseline justification) must own.
"""

from tools.raylint import (async_discipline, blocking,  # noqa: F401
                           capability_drift, failpoints_pass,
                           frame_schema, guarded_by, lock_order,
                           loop_affinity, metric_registry, rpc_drift)
from tools.raylint.core import (Baseline, Context, Finding,  # noqa: F401
                                Module, REGISTRY, collect_py_files,
                                load_modules)

__all__ = ["Baseline", "Context", "Finding", "Module", "REGISTRY",
           "collect_py_files", "load_modules", "run_passes"]

# passes whose findings for module M depend ONLY on module M (the
# whole-program context adds nothing): under --changed these scan just
# the changed modules, keeping the pre-commit path inside its ~2s
# budget. Everything else is whole-program (call graphs, registries,
# send/consume matching) and always sees the full module set.
LOCAL_PASSES = {"guarded-by", "blocking-under-lock", "loop-affinity"}


def run_passes(ctx: Context, only=None, changed=None):
    """Run registered passes (all, or the ids in ``only``) and return
    the combined findings sorted by location. ``changed`` (a set of
    repo-relative paths, or None) restricts the per-module-only passes
    in ``LOCAL_PASSES`` to those modules — their findings cannot land
    anywhere else, so the skipped work is pure waste."""
    findings = []
    local_ctx = None
    for pass_id, fn in sorted(REGISTRY.items()):
        if only and pass_id not in only:
            continue
        use = ctx
        if changed is not None and pass_id in LOCAL_PASSES:
            if local_ctx is None:
                local_ctx = Context(
                    modules=[m for m in ctx.modules
                             if m.relpath in changed],
                    repo_root=ctx.repo_root,
                    docs_fault_tolerance=ctx.docs_fault_tolerance,
                    docs_observability=ctx.docs_observability,
                    tests_sources=ctx.tests_sources)
            use = local_ctx
        findings.extend(fn(use))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return findings
