"""raylint: AST-based concurrency & wire-protocol static analysis.

The reactive half of this repo's correctness tooling is the runtime
``lock_sanitizer`` (it observes the locks tests happen to exercise) and
the chaos tier (it injects the faults a schedule happens to contain).
raylint is the proactive half: whole-package static passes over the
recurring bug classes this codebase has actually shipped —

- ``guarded-by``        annotated shared state touched outside its lock
- ``lock-order``        cycles in the static acquired-before graph
- ``blocking-under-lock``  wire I/O / sleeps / RPC inside a held lock
- ``rpc-drift``         client method literals vs server dispatch tables
- ``failpoint-registry``  fire() names unique + documented + tested

Run: ``python -m tools.raylint ray_tpu/`` (CI stage 0.5, fail-fast).
Docs: ``docs/static_analysis.md``. No ``--fix`` by design: every fix is
a semantic change a human (or a baseline justification) must own.
"""

from tools.raylint import (blocking, failpoints_pass,  # noqa: F401
                           guarded_by, lock_order, rpc_drift)
from tools.raylint.core import (Baseline, Context, Finding,  # noqa: F401
                                Module, REGISTRY, collect_py_files,
                                load_modules)

__all__ = ["Baseline", "Context", "Finding", "Module", "REGISTRY",
           "collect_py_files", "load_modules", "run_passes"]


def run_passes(ctx: Context, only=None):
    """Run registered passes (all, or the ids in ``only``) and return
    the combined findings sorted by location."""
    findings = []
    for pass_id, fn in sorted(REGISTRY.items()):
        if only and pass_id not in only:
            continue
        findings.extend(fn(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return findings
