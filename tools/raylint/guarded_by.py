"""guarded-by pass: annotated shared state is only touched under its lock.

Annotation syntax — a comment on the attribute's initialization line::

    self._streams = {}   #: guarded by self._slock

Every other access to ``self._streams`` anywhere in the class must then
sit lexically inside a ``with self._slock:`` block (or between a manual
``self._slock.acquire()`` / ``.release()`` pair). ``__init__`` and
``__del__`` are exempt (single-threaded construction/teardown), as is
the annotated line itself. Re-entrant acquisition of the same lock and
conditional locking are handled by the shared held-lock scanner;
deliberate lock-free reads get a ``# raylint: disable=guarded-by``
or a baseline entry with justification.

Limitation (by design): the check is lexical and per-function. A helper
that requires the lock held by its caller needs its own ``with`` (use
an RLock) or a suppression comment.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from tools.raylint.core import (Context, Finding, FuncScanner, Module,
                                expr_name, register)

PASS_ID = "guarded-by"


def _annotations(module: Module) -> Dict[str, Dict[str, Tuple[str, int]]]:
    """ClassName -> {attr: (lock_expr, line)} from annotated assigns."""
    out: Dict[str, Dict[str, Tuple[str, int]]] = {}
    if not module.guarded_lines:
        return out      # nothing annotated: skip the per-class walks
    for node in module.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        attrs: Dict[str, Tuple[str, int]] = {}
        assigns = [stmt for stmt in ast.walk(node)
                   if isinstance(stmt, (ast.Assign, ast.AnnAssign))]
        # same-line annotations bind first; an annotation on its OWN
        # line (long assignment lines) then binds to the next
        # assignment below it — never to one whose line already holds
        # an annotation of its own
        same_line = {stmt.lineno for stmt in assigns
                     if stmt.lineno in module.guarded_lines}
        for stmt in assigns:
            lock = module.guarded_lines.get(stmt.lineno)
            if lock is None and (stmt.lineno - 1) not in same_line:
                lock = module.guarded_lines.get(stmt.lineno - 1)
            if lock is None:
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for target in targets:
                name = expr_name(target)
                if name and name.startswith("self."):
                    attrs[name[len("self."):]] = (lock, stmt.lineno)
        if attrs:
            out[node.name] = attrs
    return out


@register(PASS_ID)
def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for module in ctx.modules:
        per_class = _annotations(module)
        if not per_class:
            continue
        for node in module.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            attrs = per_class.get(node.name)
            if not attrs:
                continue
            for fn in node.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name in ("__init__", "__del__"):
                    continue
                findings.extend(
                    _check_method(module, node.name, fn, attrs))
    return findings


def _check_method(module: Module, cls: str, fn: ast.AST,
                  attrs: Dict[str, Tuple[str, int]]) -> List[Finding]:
    findings: List[Finding] = []
    reported = set()

    def on_node(node: ast.AST, held: List[str]) -> None:
        if not isinstance(node, ast.Attribute):
            return
        name = expr_name(node)
        if not name or not name.startswith("self."):
            return
        attr = name[len("self."):]
        entry = attrs.get(attr)
        if entry is None:
            return
        lock, decl_line = entry
        if node.lineno == decl_line:
            return
        if lock in held:
            return
        if module.suppressed(PASS_ID, node.lineno):
            return
        key = f"{cls}.{getattr(fn, 'name', '?')}:{attr}"
        if key in reported:
            return      # one finding per (method, attr)
        reported.add(key)
        findings.append(Finding(
            PASS_ID, module.relpath, node.lineno, key,
            f"{cls}.{attr} is annotated 'guarded by {lock}' but "
            f"accessed in {fn.name}() without holding it"))

    FuncScanner(on_node).scan(fn)
    return findings
