"""raylint CLI.

Usage::

    python -m tools.raylint ray_tpu/ [more paths...]
        [--baseline tools/raylint/baseline.txt] [--changed]
        [--passes guarded-by,rpc-drift] [--list-passes]

Exit codes (CI contract):

- 0  clean, or every finding is baseline-covered (count printed)
- 1  NEW findings (not in the baseline)
- 2  usage / internal error

``--changed`` restricts *reporting* to files in ``git diff
--name-only HEAD`` (plus staged) — whole-package analysis still runs,
so cross-file passes (lock-order, rpc-drift, failpoint-registry) see
the full graph; the pre-commit path stays under ~2s because parsing is
the only cost.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from tools.raylint import (Baseline, Context, REGISTRY, collect_py_files,
                           load_modules, run_passes)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.txt")


def _changed_files(repo_root: str) -> set:
    try:
        # one invocation: diff vs HEAD covers staged AND unstaged edits
        out = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=repo_root, capture_output=True, text=True, timeout=10)
        return set(out.stdout.split())
    except (OSError, subprocess.SubprocessError):
        return set()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="raylint")
    parser.add_argument("paths", nargs="*", default=[],
                        help="files or directories to lint")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument("--no-baseline", action="store_true",
                        help="report baseline-covered findings too")
    parser.add_argument("--changed", action="store_true",
                        help="report only on git-changed files")
    parser.add_argument("--passes", default="",
                        help="comma-separated pass ids to run")
    parser.add_argument("--list-passes", action="store_true")
    args = parser.parse_args(argv)

    if args.list_passes:
        for pass_id, fn in sorted(REGISTRY.items()):
            doc = (sys.modules[fn.__module__].__doc__ or "").strip()
            first = doc.splitlines()[0] if doc else ""
            print(f"{pass_id:22s} {first}")
        return 0
    if not args.paths:
        parser.error("no paths given (try: python -m tools.raylint "
                     "ray_tpu/)")

    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    files = collect_py_files(args.paths)
    if not files:
        print("raylint: no python files found", file=sys.stderr)
        return 2
    changed = _changed_files(repo_root) if args.changed else None
    if changed is not None:
        linted = {os.path.relpath(os.path.abspath(f), repo_root)
                  for f in files}
        if not (linted & changed):
            # nothing reportable can surface: skip parsing entirely
            print("raylint: clean (no linted files changed)",
                  file=sys.stderr)
            return 0
    modules = load_modules(files, repo_root)
    ctx = Context(modules=modules, repo_root=repo_root)
    only = ({p.strip() for p in args.passes.split(",") if p.strip()}
            or None)
    if only and not only <= set(REGISTRY):
        print(f"raylint: unknown passes {sorted(only - set(REGISTRY))}"
              f" (known: {sorted(REGISTRY)})", file=sys.stderr)
        return 2
    findings = run_passes(ctx, only=only, changed=changed)

    if changed is not None:
        findings = [f for f in findings if f.path in changed]

    baseline = (Baseline() if args.no_baseline
                else Baseline.load(args.baseline))
    new = [f for f in findings if not baseline.covers(f)]
    covered = len(findings) - len(new)
    for f in new:
        print(f.render())
    # stale detection is only meaningful on a FULL run: a --changed or
    # --passes subset (or partial path args) simply didn't execute the
    # checks behind most baseline entries
    stale = (baseline.unused(findings)
             if not args.changed and only is None else [])
    for key in stale:
        print(f"raylint: stale baseline entry (no longer fires): {key}",
              file=sys.stderr)
    n_files = len(modules)
    if new:
        print(f"raylint: {len(new)} new finding(s), {covered} "
              f"baseline-covered, {n_files} files", file=sys.stderr)
        return 1
    print(f"raylint: clean ({covered} baseline-covered, {n_files} "
          f"files)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
