"""loop-affinity pass: ``#: loop-only`` functions stay on the loop.

Annotation syntax — a comment on the ``def`` line (or the line above)::

    def _wake(self):   #: loop-only
        ...

A loop-only function mutates event-loop state (handles, transports,
pending-task sets) and must ONLY execute on the loop thread. Callers
in thread context reach it via ``loop.call_soon_threadsafe(self._wake)``
— a *reference* hand-off, never a direct call.

A direct call site is fine when the calling context is itself
loop-affine:

- the caller is an ``async def`` (coroutines only run on the loop);
- the caller is itself annotated ``#: loop-only``;
- the caller is a nested def whose NAME is handed to a loop-scheduling
  API (``call_soon_threadsafe``/``call_soon``/``call_later``/
  ``call_at``/``run_coroutine_threadsafe``/``create_task``/
  ``ensure_future``/``add_done_callback``) somewhere in its enclosing
  function — the loop-spawned-callback idiom.

Everything else is a violation: a plain sync function (thread context
until proven otherwise) calling a loop-only function directly races
the loop. Lambda bodies are skipped entirely (deferred, context
unknowable — prefer a named nested def, which IS checked).

Scope: per module. Loop-only helpers are private by convention, so
cross-module direct calls do not arise; matching bare attribute names
package-wide would trade that for name-collision false positives.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.raylint.core import Context, Finding, Module, register

PASS_ID = "loop-affinity"

LOOP_SCHEDULE_ATTRS = {"call_soon_threadsafe", "call_soon", "call_later",
                       "call_at", "run_coroutine_threadsafe",
                       "create_task", "ensure_future",
                       "add_done_callback"}


def _loop_only_names(module: Module) -> Dict[str, str]:
    """name -> "method" (defined in a class body, reached via
    ``obj.name()``) or "function" (plain/nested def, reached via a bare
    ``name()``). The call-shape split keeps an UNRELATED attribute that
    happens to share a nested def's name (``self._pool.shutdown()`` vs
    a loop-only ``def shutdown()``) from matching."""
    names: Dict[str, str] = {}
    methods: Set[str] = set()
    for node in module.tree.body:
        if isinstance(node, ast.ClassDef):
            methods.update(sub.name for sub in node.body
                           if isinstance(sub, (ast.FunctionDef,
                                               ast.AsyncFunctionDef)))
    for node in module.walk():
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and module.loop_only(node)):
            names[node.name] = ("method" if node.name in methods
                                else "function")
    return names


def _scheduled_names(fn: ast.AST) -> Set[str]:
    """Names passed by reference to a loop-scheduling API anywhere in
    this function (NOT descending into nested defs — a hand-off in a
    sibling scope proves nothing about this one)."""
    out: Set[str] = set()
    for node in _walk_same_scope(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in LOOP_SCHEDULE_ATTRS):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name):
                out.add(arg.id)
            elif isinstance(arg, ast.Attribute):
                out.add(arg.attr)
    return out


def _walk_same_scope(fn: ast.AST):
    """Walk a function body without entering nested function bodies
    (the nested def NODE itself is yielded so callers can recurse)."""
    todo = list(ast.iter_child_nodes(fn))
    while todo:
        node = todo.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        todo.extend(ast.iter_child_nodes(node))


def _check_fn(module: Module, fn: ast.AST, loop_ctx: bool,
              loop_names: Dict[str, str], where: str,
              findings: List[Finding]) -> None:
    scheduled = _scheduled_names(fn)
    for node in _walk_same_scope(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested_ctx = (loop_ctx
                          or isinstance(node, ast.AsyncFunctionDef)
                          or module.loop_only(node)
                          or node.name in scheduled)
            _check_fn(module, node, nested_ctx, loop_names,
                      f"{where}.{node.name}", findings)
            continue
        if loop_ctx or not isinstance(node, ast.Call):
            continue
        callee = None
        if isinstance(node.func, ast.Name):
            callee = node.func.id
            if loop_names.get(callee) != "function":
                continue
        elif isinstance(node.func, ast.Attribute):
            callee = node.func.attr
            if loop_names.get(callee) != "method":
                continue
        else:
            continue
        if module.suppressed(PASS_ID, node.lineno):
            continue
        findings.append(Finding(
            PASS_ID, module.relpath, node.lineno,
            f"{where}->{callee}",
            f"{callee}() is '#: loop-only' but {where}() calls it from "
            f"thread context — hand it to the loop via "
            f"call_soon_threadsafe instead"))


@register(PASS_ID)
def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for module in ctx.modules:
        if not module.loop_only_lines:
            continue
        loop_names = _loop_only_names(module)
        if not loop_names:
            continue
        for node in module.tree.body:
            tops: List[Tuple[Optional[str], ast.AST]] = []
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                tops.append((None, node))
            elif isinstance(node, ast.ClassDef):
                tops.extend((node.name, sub) for sub in node.body
                            if isinstance(sub, (ast.FunctionDef,
                                                ast.AsyncFunctionDef)))
            for cls, fn in tops:
                loop_ctx = (isinstance(fn, ast.AsyncFunctionDef)
                            or module.loop_only(fn))
                where = f"{cls}.{fn.name}" if cls else fn.name
                _check_fn(module, fn, loop_ctx, loop_names, where,
                          findings)
    return findings
