"""static lock-order pass: no cycles in the acquired-before graph.

Extracts every lexically nested lock acquisition (``with a: ... with
b:`` and manual acquire/release pairs) across the whole package and
builds the class-level acquired-before graph — the same model the
runtime ``lock_sanitizer._Graph`` builds from live executions (Linux
lockdep's class-level discipline), but over ALL code paths instead of
only the ones tests happen to drive.

Lock class naming matches the runtime sanitizer: a lock created via
``tracked_lock("name")`` is class ``name``; plain locks are
``module.Class.attr`` (or ``module.NAME`` at module scope). Same-class
nested acquisition is skipped, exactly like the runtime graph — it
would need lockdep-style nesting annotations to express.

A cycle is reported once per participating edge, keyed by the edge pair
so the baseline survives line churn.
"""

from __future__ import annotations

import ast
from bisect import bisect_left
from typing import Dict, List, Optional, Set, Tuple

from tools.raylint.core import (Context, Finding, FuncScanner, Module,
                                class_lock_names, expr_name,
                                has_locky_source, is_locky, register,
                                tracked_lock_name)

PASS_ID = "lock-order"


def _module_lock_names(module: Module) -> Dict[str, str]:
    """Module-level lock assignments: NAME -> stable class name."""
    out: Dict[str, str] = {}
    for stmt in module.tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for target in stmt.targets:
            if isinstance(target, ast.Name) and is_locky(target.id):
                tname = tracked_lock_name(stmt.value)
                out[target.id] = tname or f"{module.name}.{target.id}"
    return out


def _canon(dotted: str, cls: Optional[str],
           class_names: Dict[Tuple[str, str], str],
           mod_names: Dict[str, str], module: Module) -> str:
    """Map a dotted lock expression at a use site to its lock class."""
    if dotted.startswith("self.") and cls is not None:
        attr = dotted[len("self."):]
        return class_names.get((cls, attr),
                               f"{module.name}.{cls}.{attr}")
    if "." not in dotted:
        return mod_names.get(dotted, f"{module.name}.{dotted}")
    # foreign attribute (e.g. wp._POOL_LOCK, router._lock): name by the
    # final component — cross-module canonical names need the runtime
    # tracked_lock() registry, which class_lock_names of the defining
    # module provides when linted together
    return dotted.rsplit(".", 1)[-1]


@register(PASS_ID)
def run(ctx: Context) -> List[Finding]:
    # edge -> first-seen (path, line)
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    all_class_names: Dict[Tuple[str, str], str] = {}
    for module in ctx.modules:
        all_class_names.update(class_lock_names(module))

    for module in ctx.modules:
        if not has_locky_source(module):
            continue        # no lock-like name can appear: no edges
        # an edge needs a with-block or a manual .acquire(); skip the
        # (many) functions containing neither, found by line range
        # against one sorted lineno list from the shared node cache
        lock_lines: List[int] = []
        for node in module.walk():
            k = node.__class__
            if k is ast.With or k is ast.AsyncWith:
                lock_lines.append(node.lineno)
            elif (k is ast.Call
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "acquire"):
                lock_lines.append(node.lineno)
        if not lock_lines:
            continue
        lock_lines.sort()
        mod_names = _module_lock_names(module)
        for cls, fn in module.functions():
            lo, hi = fn.lineno, fn.end_lineno or fn.lineno
            i = bisect_left(lock_lines, lo)
            if i >= len(lock_lines) or lock_lines[i] > hi:
                continue    # no acquisition site anywhere in this def
            _record_edges(module, cls, fn, all_class_names, mod_names,
                          edges)

    findings: List[Finding] = []
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    for (a, b), (path, line) in sorted(edges.items(),
                                       key=lambda kv: kv[1]):
        if _reaches(graph, b, a):
            findings.append(Finding(
                PASS_ID, path, line, f"{a}->{b}",
                f"lock-order cycle: acquiring {b!r} while holding "
                f"{a!r}, but a {b!r} -> ... -> {a!r} path exists "
                f"elsewhere in the package"))
    return findings


def _record_edges(module: Module, cls: Optional[str], fn: ast.AST,
                  class_names: Dict[Tuple[str, str], str],
                  mod_names: Dict[str, str],
                  edges: Dict[Tuple[str, str], Tuple[str, int]]) -> None:
    """Collect held->acquiring edges from one function."""

    def canon(dotted: str) -> str:
        return _canon(dotted, cls, class_names, mod_names, module)

    class Recorder(FuncScanner):
        def _scan_stmt(self, stmt, held):
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in stmt.items:
                    name = expr_name(item.context_expr)
                    if name and is_locky(name):
                        self._edge(self._eff(held) + acquired, name,
                                   stmt.lineno)
                        acquired.append(name)
                self._scan_block(stmt.body, held + acquired)
                return
            name = self._manual_acquire(stmt)
            if name is not None:
                self._edge(self._eff(held), name, stmt.lineno)
            super()._scan_stmt(stmt, held)

        def _edge(self, held: List[str], acquiring: str,
                  line: int) -> None:
            acq = canon(acquiring)
            for h in held:
                hc = canon(h)
                if hc == acq:
                    continue    # re-entrant same class: runtime model
                if module.suppressed(PASS_ID, line):
                    continue
                edges.setdefault((hc, acq), (module.relpath, line))

    Recorder(lambda node, held: None, visit_unheld=False).scan(fn)


def _reaches(graph: Dict[str, Set[str]], src: str, dst: str) -> bool:
    seen: Set[str] = set()
    stack = [src]
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(graph.get(cur, ()))
    return False
