"""frame-schema pass: payload keys written at send sites match reads.

rpc-drift checks that method NAMES line up; this pass extends the
check to payload SHAPE. For every declared method, the string-literal
keys written at each send site (``client.call("x", k=...)`` keywords,
plus ``**kw`` splats resolved against a same-function ``kw = {...}``
dict literal and ``kw["k"] = ...`` stores) are diffed against the keys
the matching consumer reads — ``handle_x(self, conn, rid, msg)`` for
calls, the ``method == "x"`` branch of an ``_on_push`` demux for
pushes. Two drift directions:

- ``missing-key``: the consumer does ``msg["k"]`` (a REQUIRED read —
  ``msg.get("k")`` is optional by construction) but no send site ever
  writes ``k``. Flagged at the handler, only when every send site's
  key set resolved fully (an opaque splat means we cannot prove
  absence).
- ``dead-key``: a send site writes ``k`` but no consumer ever reads it
  and the consumer does not forward ``msg`` onward (a handler that
  hands ``msg`` to a helper — or aliases/splats/iterates it — may
  read anything, so dead-key is skipped for it). Flagged at the send
  site.

Keys the consumer itself stores into ``msg`` (``msg["_t0"] = ...``)
are locally materialized, not wire keys, and are excluded from both
directions.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.raylint.core import Context, Finding, Module, register
from tools.raylint.rpc_drift import _first_str_arg, _imports_rpc

PASS_ID = "frame-schema"

CALL_ATTRS = {"call", "_call", "notify"}
PUSH_ATTRS = {"push", "notify_driver"}
# msg methods that read a single string-keyed entry
READ_METHODS = {"get", "pop"}
# named parameters of the wire API itself (Client.call(method,
# timeout=None, **kw)): consumed by the transport, never in the payload
TRANSPORT_KWARGS = {"timeout"}


class _SendSite:
    def __init__(self, module: Module, line: int, where: str,
                 kind: str) -> None:
        self.module = module
        self.line = line
        self.where = where
        self.kind = kind            # "call" | "push"
        self.keys: Set[str] = set()
        self.resolved = True        # False when a splat defeats us


class _Consumer:
    def __init__(self) -> None:
        self.required: Set[str] = set()     # msg["k"] loads
        self.optional: Set[str] = set()     # msg.get/pop/"k" in msg
        self.local: Set[str] = set()        # msg["k"] = ... stores
        self.forwards = False               # msg escapes whole

    def merge(self, other: "_Consumer") -> None:
        self.required |= other.required
        self.optional |= other.optional
        self.local |= other.local
        self.forwards = self.forwards or other.forwards

    def reads(self) -> Set[str]:
        return self.required | self.optional


def _scan_msg_uses(body: List[ast.stmt], msg_name: str) -> _Consumer:
    """Collect how ``msg_name`` is used in a statement list. Any use
    that is not a recognized per-key read marks the consumer as
    forwarding (conservative)."""
    out = _Consumer()
    consumed: Set[int] = set()      # id() of Name nodes used structurally
    names: List[ast.Name] = []
    todo: List[ast.AST] = list(body)
    while todo:
        node = todo.pop()
        todo.extend(ast.iter_child_nodes(node))
        if isinstance(node, ast.Name) and node.id == msg_name:
            names.append(node)
            continue
        if (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == msg_name
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            consumed.add(id(node.value))
            key = node.slice.value
            if isinstance(node.ctx, ast.Load):
                out.required.add(key)
            else:
                out.local.add(key)
            continue
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == msg_name
                and node.func.attr in READ_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            consumed.add(id(node.func.value))
            out.optional.add(node.args[0].value)
            continue
        if (isinstance(node, ast.Compare)
                and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and isinstance(node.left, ast.Constant)
                and isinstance(node.left.value, str)
                and len(node.comparators) == 1
                and isinstance(node.comparators[0], ast.Name)
                and node.comparators[0].id == msg_name):
            consumed.add(id(node.comparators[0]))
            out.optional.add(node.left.value)
            continue
    if any(id(n) not in consumed for n in names):
        out.forwards = True         # bare msg escaped somewhere
    return out


def _arg_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [x.arg for x in a.posonlyargs + a.args]


def _handler_msg_param(fn: ast.AST) -> Optional[str]:
    names = _arg_names(fn)
    if "msg" in names:
        return "msg"
    # handle_x(self, conn, rid, msg): the 4th positional
    return names[3] if len(names) >= 4 else None


def _demux_params(fn: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    names = _arg_names(fn)
    method = "method" if "method" in names else None
    msg = "msg" if "msg" in names else None
    if method is None and len(names) >= 2:
        method = names[1] if names[0] == "self" else names[0]
    if msg is None and len(names) >= 3:
        msg = names[2] if names[0] == "self" else names[1]
    return method, msg


def _branch_methods(test: ast.AST, method_param: str) -> Set[str]:
    """``method == "x"`` / ``method in ("x", "y")`` -> {"x", "y"}."""
    out: Set[str] = set()
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.left, ast.Name)
            and test.left.id == method_param):
        return out
    comp = test.comparators[0]
    if isinstance(test.ops[0], ast.Eq):
        if (isinstance(comp, ast.Constant)
                and isinstance(comp.value, str)):
            out.add(comp.value)
    elif isinstance(test.ops[0], ast.In):
        if isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
            for el in comp.elts:
                if (isinstance(el, ast.Constant)
                        and isinstance(el.value, str)):
                    out.add(el.value)
    return out


def _resolve_splat(fn: ast.AST, splat: ast.AST) -> Optional[Set[str]]:
    """Keys of a ``**kw`` splat when ``kw`` is a same-function dict
    literal (all-string keys) plus ``kw["k"] = ...`` stores."""
    if not isinstance(splat, ast.Name):
        return None
    keys: Optional[Set[str]] = None
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == splat.id
                        for t in node.targets)):
            if not isinstance(node.value, ast.Dict):
                return None
            ks: Set[str] = set()
            for k in node.value.keys:
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    return None     # computed key / nested splat
                ks.add(k.value)
            keys = ks if keys is None else keys | ks
        elif (isinstance(node, ast.Subscript)
              and isinstance(node.ctx, ast.Store)
              and isinstance(node.value, ast.Name)
              and node.value.id == splat.id
              and isinstance(node.slice, ast.Constant)
              and isinstance(node.slice.value, str)):
            if keys is not None:
                keys.add(node.slice.value)
    return keys


@register(PASS_ID)
def run(ctx: Context) -> List[Finding]:
    sends: Dict[Tuple[str, str], List[_SendSite]] = {}
    consumers: Dict[Tuple[str, str], _Consumer] = {}
    declared: Set[str] = set()

    for module in ctx.modules:
        if not _imports_rpc(module):
            continue
        for node in module.calls():
            if (isinstance(node.func, ast.Name)
                    and node.func.id == "declare"):
                name = _first_str_arg(node)
                if name:
                    declared.add(name)
        # consumers: handlers and push-demux branches
        for fn_node in module.defs():
            if fn_node.name.startswith("handle_"):
                msg_p = _handler_msg_param(fn_node)
                if msg_p is not None:
                    key = ("call", fn_node.name[len("handle_"):])
                    consumers.setdefault(key, _Consumer()).merge(
                        _scan_msg_uses(fn_node.body, msg_p))
            if fn_node.name in ("_on_push", "on_push"):
                _scan_demux(fn_node, consumers)
        _scan_sends(module, declared, sends)

    findings: List[Finding] = []
    for (kind, name), sites in sorted(sends.items()):
        consumer = consumers.get((kind, name))
        if consumer is None:
            continue        # rpc-drift already owns missing handlers
        sent_union: Set[str] = set()
        all_resolved = True
        for site in sites:
            sent_union |= site.keys
            all_resolved = all_resolved and site.resolved
        # dead keys: per site, skippable when the consumer forwards
        if not consumer.forwards:
            reads = consumer.reads()
            for site in sites:
                if site.module.suppressed(PASS_ID, site.line):
                    continue
                for k in sorted(site.keys - reads):
                    findings.append(Finding(
                        PASS_ID, site.module.relpath, site.line,
                        f"dead-key:{name}:{k}",
                        f"{site.where} sends {name!r} key {k!r} that "
                        f"no consumer of {name!r} ever reads"))
        # missing keys: only when every site resolved fully
        if all_resolved and sites:
            missing = (consumer.required - sent_union
                       - consumer.local)
            site = sites[0]
            for k in sorted(missing):
                findings.append(Finding(
                    PASS_ID, site.module.relpath, site.line,
                    f"missing-key:{name}:{k}",
                    f"consumer of {name!r} requires msg[{k!r}] but no "
                    f"send site ever writes it"))
    return findings


def _scan_demux(fn: ast.AST, consumers: Dict[Tuple[str, str],
                                             _Consumer]) -> None:
    method_p, msg_p = _demux_params(fn)
    if method_p is None or msg_p is None:
        return
    # pre-branch reads apply to every method this demux consumes
    shared = _Consumer()

    def walk_branches(stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                methods = _branch_methods(stmt.test, method_p)
                if methods:
                    branch = _scan_msg_uses(stmt.body, msg_p)
                    for m in methods:
                        c = consumers.setdefault(("push", m),
                                                 _Consumer())
                        c.merge(branch)
                        c.merge(shared)
                    walk_branches(stmt.orelse)
                    continue
                walk_branches(stmt.body)
                walk_branches(stmt.orelse)
                continue
            # non-demux statement at demux level: its reads are shared
            shared.merge(_scan_msg_uses([stmt], msg_p))

    # shared reads must not mark every branch as forwarding just
    # because a helper takes msg at the top level — track it separately
    walk_branches(fn.body)
    if shared.required or shared.optional or shared.forwards:
        for (kind, _m), c in consumers.items():
            if kind == "push":
                c.merge(shared)


def _scan_sends(module: Module, declared: Set[str],
                sends: Dict[Tuple[str, str], List[_SendSite]]) -> None:
    for node in module.calls():
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in CALL_ATTRS | PUSH_ATTRS):
            continue
        name = _first_str_arg(node)
        if name is None:
            continue
        kind = ("push" if node.func.attr in PUSH_ATTRS else "call")
        if kind == "call" and name not in declared:
            continue        # rpc-drift owns undeclared methods
        # splat resolution and the ``where`` label both need the
        # innermost enclosing def; module-level sends are out of scope
        fn = module.enclosing_def(node.lineno)
        if fn is None:
            continue
        site = _SendSite(module, node.lineno, fn.name, kind)
        for kwarg in node.keywords:
            if kwarg.arg is not None:
                if kwarg.arg not in TRANSPORT_KWARGS:
                    site.keys.add(kwarg.arg)
                continue
            resolved = _resolve_splat(fn, kwarg.value)
            if resolved is None:
                site.resolved = False
            else:
                site.keys |= resolved
        sends.setdefault((kind, name), []).append(site)
