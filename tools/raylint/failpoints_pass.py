"""failpoint-registry pass: fire() names are unique, documented, tested.

Every ``_fp.fire("name")`` seam in the runtime is part of the fault-
injection contract (docs/fault_tolerance.md's failpoint table, the
chaos tier's schedules). This pass keeps the three views in sync:

- **unique**: one failpoint name = one seam. The same name fired from
  two call sites makes hit counts and chaos schedules ambiguous (a
  deliberately shared seam — e.g. ``trace.flush`` on both the daemon
  and driver flushers — goes in the baseline with its justification).
- **documented**: the name appears in ``docs/fault_tolerance.md``.
- **tested**: the name appears in at least one file under ``tests/``
  (a failpoint no test can trigger is dead chaos surface).

The definition module (``failpoints.py`` itself) and test files are
not fire *seams* and are excluded from collection.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from tools.raylint.core import Context, Finding, register

PASS_ID = "failpoint-registry"


def _fire_sites(ctx: Context) -> Dict[str, List[Tuple[str, int]]]:
    sites: Dict[str, List[Tuple[str, int]]] = {}
    for module in ctx.modules:
        if module.name == "failpoints":
            continue        # the registry itself, not a seam
        if "fire(" not in module.source:
            continue        # no call site can match
        for node in module.calls():
            fname = None
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            if fname != "fire":
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            if module.suppressed(PASS_ID, node.lineno):
                continue
            sites.setdefault(node.args[0].value, []).append(
                (module.relpath, node.lineno))
    return sites


@register(PASS_ID)
def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    sites = _fire_sites(ctx)
    doc = ctx.fault_tolerance_doc()
    tests = ctx.test_sources()
    for name in sorted(sites):
        locs = sites[name]
        if len(locs) > 1:
            path, line = locs[1]
            others = ", ".join(f"{p}:{ln}" for p, ln in locs[:1])
            # the site COUNT is part of the key: a baselined 2-site
            # seam must not silently grandfather a third site
            findings.append(Finding(
                PASS_ID, path, line, f"dup:{name}:{len(locs)}",
                f"failpoint {name!r} fired from {len(locs)} call sites "
                f"(also at {others}); one name = one seam"))
        path, line = locs[0]
        if f"`{name}`" not in doc and name not in doc:
            findings.append(Finding(
                PASS_ID, path, line, f"undocumented:{name}",
                f"failpoint {name!r} missing from "
                f"docs/fault_tolerance.md's failpoint table"))
        if not any(name in src for src in tests.values()):
            findings.append(Finding(
                PASS_ID, path, line, f"untested:{name}",
                f"failpoint {name!r} is not exercised by any test "
                f"under tests/"))
    return findings
