"""async-discipline pass: no blocking work / lost coroutines on the loop.

The asyncio control plane (ROADMAP item 4) is only safe if the event
loop never runs blocking code and never drops a coroutine. Flags:

- **blocking-call**: inside any ``async def`` body (nested sync defs
  and lambdas excluded — they run in executors or threads), a call
  that blocks the loop: ``time.sleep``, ``subprocess.*``, socket
  send/recv attributes, rpc round trips (``.call``/``._call``/
  ``.notify``/``.notify_driver``) and data-plane submissions
  (``.remote``), and ``.acquire()``/``.wait()`` on a threading
  lock/cv/event. A call directly wrapped in ``await`` is the async
  flavor (``await loop.run_in_executor(...)``) and is exempt.
- **unawaited-coroutine**: a bare expression statement calling a
  function every linted definition of which is ``async def`` — the
  coroutine object is created and garbage-collected unrun. Names that
  have both sync and async definitions anywhere in the package are
  skipped (conservative).
- **await-under-lock**: ``await`` while lexically inside a *sync*
  ``with <lock>:`` block (or a manual ``.acquire()`` region). The loop
  parks on the await with the threading lock held — every other
  thread contending the lock stalls the whole control plane.
  ``async with`` is the correct form and is not flagged.
- **fire-and-forget**: ``create_task(...)``/``ensure_future(...)`` as
  a bare expression statement. asyncio holds only a weak reference to
  tasks, so an unretained task can be garbage-collected mid-flight;
  assign it, append it to a collection, or pass it onward.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from tools.raylint.core import (Context, Finding, expr_name, is_locky,
                                register)
from tools.raylint.blocking import (RPC_ATTRS, SOCKET_ATTRS,
                                    SUBPROCESS_ATTRS)

PASS_ID = "async-discipline"

SPAWN_NAMES = {"create_task", "ensure_future"}
# threading primitives whose acquire/wait park the calling THREAD —
# fatal on the loop thread (asyncio's own Lock/Event are awaited, so
# the un-awaited call shape below never matches them)
THREAD_WAIT_ATTRS = {"acquire", "wait"}


_DEF_RE = re.compile(r"\bdef[ \t]+(\w+)")


def _async_defs(ctx: Context) -> Dict[str, Set[bool]]:
    """name -> set of is-async flags across every linted definition.

    Regex-collected rather than AST-walked: this is the only part of
    the pass that must see EVERY module (a sync def anywhere vetoes
    the unawaited-coroutine check for that name), and walking 240k
    nodes to find def statements is the wrong tool. Over-matching a
    ``def`` inside a string skews toward the mixed-kinds veto, i.e.
    toward silence — the conservative direction for this check."""
    kinds: Dict[str, Set[bool]] = {}
    for module in ctx.modules:
        src = module.source
        for m in _DEF_RE.finditer(src):
            start = m.start()
            is_async = src[max(0, start - 6):start].rstrip() \
                .endswith("async")
            kinds.setdefault(m.group(1), set()).add(is_async)
    return kinds


def _blocking_in_async(node: ast.Call) -> Optional[str]:
    """Describe why this call blocks the event loop, or None."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    recv = expr_name(func.value)
    attr = func.attr
    if recv == "time" and attr == "sleep":
        return "time.sleep()"
    if recv == "subprocess" and attr in SUBPROCESS_ATTRS:
        return f"subprocess.{attr}()"
    if attr in THREAD_WAIT_ATTRS and recv is not None and is_locky(recv):
        return f"threading {attr}() on {recv}"
    if attr in SOCKET_ATTRS:
        return f"socket {attr}() on {recv or '<expr>'}"
    if attr in RPC_ATTRS:
        return f"RPC {attr}() on {recv or '<expr>'}"
    if attr == "remote":
        # data-plane submission: a full rpc round trip under the hood
        return f"task submission .remote() on {recv or '<expr>'}"
    return None


def _is_spawn_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id in SPAWN_NAMES
    if isinstance(func, ast.Attribute):
        return func.attr in SPAWN_NAMES
    return False


class _AsyncBodyScan:
    """One async def body: blocking calls + awaits under sync locks.

    Recursive statement walk tracking (a) whether the current Call is
    directly under an ``await`` (exempt from blocking-call) and (b)
    the sync-``with``-held lock names (await-under-lock). Nested defs
    and lambdas are pruned — they execute in their own context and are
    scanned (or deliberately skipped) on their own.
    """

    def __init__(self, module, where: str, findings: List[Finding],
                 async_names: Dict[str, Set[bool]]):
        self.module = module
        self.where = where
        self.findings = findings
        self.async_names = async_names
        self.reported: Set[str] = set()

    def _emit(self, line: int, kind: str, detail: str, msg: str) -> None:
        if self.module.suppressed(PASS_ID, line):
            return
        key = f"{kind}:{self.where}:{detail}"
        if key in self.reported:
            return
        self.reported.add(key)
        self.findings.append(Finding(
            PASS_ID, self.module.relpath, line, key, msg))

    def scan(self, fn: ast.AsyncFunctionDef) -> None:
        self._block(fn.body, [])

    def _block(self, stmts: List[ast.stmt], held: List[str]) -> None:
        for stmt in stmts:
            self._stmt(stmt, held)

    def _stmt(self, stmt: ast.stmt, held: List[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return      # runs in its own context (executor, later task)
        if isinstance(stmt, ast.With):
            acquired = []
            for item in stmt.items:
                self._expr(item.context_expr, held)
                name = expr_name(item.context_expr)
                if name and is_locky(name):
                    acquired.append(name)
            self._block(stmt.body, held + acquired)
            return
        if isinstance(stmt, ast.AsyncWith):
            # async with asyncio.Lock(): awaits are the design
            for item in stmt.items:
                self._expr(item.context_expr, held)
            self._block(stmt.body, held)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test, held)
            self._block(stmt.body, held)
            self._block(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, held)
            self._block(stmt.body, held)
            self._block(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            self._block(stmt.body, held)
            for handler in stmt.handlers:
                self._block(handler.body, held)
            self._block(stmt.orelse, held)
            self._block(stmt.finalbody, held)
            return
        # bare-expression call: the one statement shape that can drop
        # a coroutine unrun
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)):
            callee = _callee_name(stmt.value)
            if (callee is not None and callee not in SPAWN_NAMES
                    and self.async_names.get(callee) == {True}):
                self._emit(
                    stmt.value.lineno, "unawaited", callee,
                    f"{callee}() is async everywhere it is defined but "
                    f"called without await in {self.where}() — the "
                    f"coroutine is created and dropped unrun")
        # leaf statement: scan every expression inside it
        for child in ast.iter_child_nodes(stmt):
            self._expr(child, held)

    def _expr(self, expr: ast.AST, held: List[str],
              under_await: bool = False) -> None:
        if isinstance(expr, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return      # deferred body: runs elsewhere
        if isinstance(expr, ast.Await):
            if held:
                self._emit(
                    expr.lineno, "await-under-lock",
                    ",".join(sorted(set(held))),
                    f"await while holding sync lock "
                    f"{', '.join(sorted(set(held)))} in {self.where}() "
                    f"— the loop parks with the lock held (use async "
                    f"with / release before awaiting)")
            self._expr(expr.value, held, under_await=True)
            return
        if isinstance(expr, ast.Call):
            if not under_await:
                why = _blocking_in_async(expr)
                if why is not None:
                    self._emit(
                        expr.lineno, "blocking", why,
                        f"{why} inside async def {self.where}() blocks "
                        f"the event loop (offload via run_in_executor)")
            for child in ast.iter_child_nodes(expr):
                self._expr(child, held)
            return
        for child in ast.iter_child_nodes(expr):
            self._expr(child, held)


@register(PASS_ID)
def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    async_names = _async_defs(ctx)
    for module in ctx.modules:
        # substring gate: skip the ~90% of modules with no async code
        # (create_task/ensure_future always arrive via an asyncio or
        # loop attribute, but check the names anyway for safety)
        if ("async" not in module.source
                and "create_task" not in module.source
                and "ensure_future" not in module.source):
            continue
        for node in module.walk():
            # fire-and-forget spawn: anywhere, any function kind — the
            # weak-reference hazard does not care who called it
            if (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)
                    and _is_spawn_call(node.value)):
                line = node.value.lineno
                if not module.suppressed(PASS_ID, line):
                    findings.append(Finding(
                        PASS_ID, module.relpath, line,
                        f"fire-and-forget:{_spawn_detail(node.value)}",
                        f"fire-and-forget {_spawn_detail(node.value)} — "
                        f"asyncio keeps only a weak reference to tasks; "
                        f"retain the task or it can be GC'd mid-flight"))
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            _AsyncBodyScan(module, node.name, findings,
                           async_names).scan(node)
    return findings


def _spawn_detail(call: ast.Call) -> str:
    """``create_task(pump())`` -> "create_task(pump)" — the argument
    callee keeps two same-file spawn sites apart in the stable key."""
    fname = (call.func.attr if isinstance(call.func, ast.Attribute)
             else getattr(call.func, "id", "?"))
    arg = ""
    if call.args:
        arg = (_callee_name(call.args[0])
               if isinstance(call.args[0], ast.Call)
               else expr_name(call.args[0])) or ""
    return f"{fname}({arg})"


def _callee_name(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None
