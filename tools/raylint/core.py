"""raylint core: module loading, pass registry, findings, baseline.

The framework is deliberately small: a *module* is one parsed Python
file plus the line-level metadata every pass needs (``#: guarded by``
annotations, ``# raylint: disable=...`` suppressions); a *pass* is a
callable over the whole module set returning :class:`Finding`\\ s. All
passes see all modules — the interesting checks here (lock ordering,
RPC drift, failpoint registry) are whole-program properties, so there
is no per-file pass API to outgrow.

Findings carry a *stable key* (no line numbers) so the checked-in
baseline survives unrelated edits. The baseline is append-only by
convention: new code must come up clean, grandfathered findings carry a
one-line justification.
"""

from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

# annotation comment marking an attribute as lock-guarded, e.g.
#   self._streams = {}   #: guarded by self._slock
GUARDED_RE = re.compile(r"#:\s*guarded by\s+(?P<lock>[A-Za-z_][\w.]*)")
# annotation comment marking a function as event-loop-affine, e.g.
#   def _wake(self):   #: loop-only
LOOP_ONLY_RE = re.compile(r"#:\s*loop-only\b")
# inline suppression:  # raylint: disable=guarded-by,blocking-under-lock
DISABLE_RE = re.compile(r"#\s*raylint:\s*disable=(?P<ids>[\w,-]+)")
# with <expr> acquiring a lock whose attribute/name looks lock-like
LOCKY_NAME_RE = re.compile(
    r"(^|_)(lock|locks|cv|cond|mutex)($|_)|_lock$|lock$", re.IGNORECASE)
# write-serialization locks: their entire purpose is holding a lock
# across a wire write, so blocking-under-lock exempts them by name
WIRE_LOCK_RE = re.compile(r"(^|_)(wlock|wire|send_lock)($|_)|wlock$",
                          re.IGNORECASE)


@dataclass(frozen=True)
class Finding:
    pass_id: str
    path: str          # repo-relative path
    line: int
    key: str           # stable identity — never includes line numbers
    message: str

    def baseline_key(self) -> str:
        return f"{self.pass_id}|{self.path}|{self.key}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


class Module:
    """One parsed source file + per-line lint metadata."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.name = os.path.splitext(os.path.basename(path))[0]
        # line -> lock expression text from "#: guarded by <lock>"
        self._guarded_lines: Dict[int, str] = {}
        # lines carrying a "#: loop-only" affinity annotation
        self._loop_only_lines: Set[int] = set()
        # line -> set of disabled pass ids
        self._suppressions: Dict[int, Set[str]] = {}
        # comment metadata is tokenize-extracted LAZILY: tokenizing is
        # the second-largest per-file cost after ast.parse, and under
        # --changed most modules' annotations are never consulted
        self._comments_scanned = False
        # flat node list from ONE ast.walk, shared by every pass that
        # scans whole modules (nine passes re-walking 170+ trees is the
        # difference between the <5s budget and blowing it)
        self._nodes: Optional[List[ast.AST]] = None
        self._functions: Optional[
            List[Tuple[Optional[str], ast.AST]]] = None
        self._calls: Optional[List[ast.Call]] = None
        self._defs: Optional[List[ast.AST]] = None
        self._cls_ranges: Optional[
            List[Tuple[int, int, ast.ClassDef]]] = None
        self._def_ranges: Optional[
            List[Tuple[int, int, ast.AST]]] = None

    @property
    def guarded_lines(self) -> Dict[int, str]:
        if not self._comments_scanned:
            self._scan_comments()
        return self._guarded_lines

    @property
    def loop_only_lines(self) -> Set[int]:
        if not self._comments_scanned:
            self._scan_comments()
        return self._loop_only_lines

    @property
    def suppressions(self) -> Dict[int, Set[str]]:
        if not self._comments_scanned:
            self._scan_comments()
        return self._suppressions

    def _scan_comments(self) -> None:
        self._comments_scanned = True
        # most files carry neither annotation: the substring gate skips
        # the tokenizer for them outright
        if "#:" not in self.source and "raylint:" not in self.source:
            return
        try:
            tokens = tokenize.generate_tokens(StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = GUARDED_RE.search(tok.string)
                if m:
                    self._guarded_lines[tok.start[0]] = m.group("lock")
                if LOOP_ONLY_RE.search(tok.string):
                    self._loop_only_lines.add(tok.start[0])
                m = DISABLE_RE.search(tok.string)
                if m:
                    ids = {s.strip() for s in m.group("ids").split(",")}
                    self._suppressions.setdefault(
                        tok.start[0], set()).update(ids)
        except tokenize.TokenError:
            pass    # unterminated string etc.: annotations best-effort

    def suppressed(self, pass_id: str, line: int) -> bool:
        return pass_id in self.suppressions.get(line, ())

    def walk(self) -> List[ast.AST]:
        """Every node in the tree, cached. Hand-rolled traversal —
        ``list(ast.walk(...))`` pays a generator frame plus an
        ``iter_fields`` generator per node, which at 240k+ nodes is a
        measurable slice of the --changed budget."""
        if self._nodes is None:
            nodes = [self.tree]
            append = nodes.append
            AST, lst = ast.AST, list
            i = 0
            while i < len(nodes):
                node = nodes[i]
                i += 1
                for name in node._fields:
                    value = getattr(node, name, None)
                    if isinstance(value, AST):
                        append(value)
                    elif isinstance(value, lst):
                        for item in value:
                            if isinstance(item, AST):
                                append(item)
            self._nodes = nodes
        return self._nodes

    def functions(self) -> List[Tuple[Optional[str], ast.AST]]:
        """Cached ``iter_functions`` result (top-level + method defs)."""
        if self._functions is None:
            self._functions = list(iter_functions(self.tree))
        return self._functions

    def calls(self) -> List[ast.Call]:
        """Every Call node, cached. Most whole-program passes key on
        call shapes — iterating this list beats re-filtering the full
        node list (Calls are ~10% of nodes) in every one of them."""
        if self._calls is None:
            self._calls = [n for n in self.walk()
                           if n.__class__ is ast.Call]
        return self._calls

    def defs(self) -> List[ast.AST]:
        """Every (async) function def node at any nesting, cached."""
        if self._defs is None:
            self._defs = [n for n in self.walk()
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))]
        return self._defs

    def enclosing_class(self, line: int) -> Optional[str]:
        """Name of the innermost class whose body spans ``line``."""
        node = self.enclosing_class_node(line)
        return node.name if node is not None else None

    def enclosing_class_node(self, line: int) -> Optional[ast.ClassDef]:
        """The innermost ClassDef whose body spans ``line``."""
        if self._cls_ranges is None:
            self._cls_ranges = [
                (n.lineno, n.end_lineno or n.lineno, n)
                for n in self.walk() if n.__class__ is ast.ClassDef]
        best = None
        for lo, hi, node in self._cls_ranges:
            if lo <= line <= hi and (best is None or lo > best[0]):
                best = (lo, node)
        return best[1] if best else None

    def enclosing_def(self, line: int) -> Optional[ast.AST]:
        """The innermost (async) def whose body spans ``line``, or
        None at module level. Range-based — resolving scope for the
        handful of interesting nodes a pass finds is far cheaper than
        threading scope through a full traversal."""
        if self._def_ranges is None:
            self._def_ranges = [
                (n.lineno, n.end_lineno or n.lineno, n)
                for n in self.defs()]
        best = None
        for lo, hi, node in self._def_ranges:
            if lo <= line <= hi and (best is None or lo > best[0]):
                best = (lo, node)
        return best[1] if best else None

    def loop_only(self, fn: ast.AST) -> bool:
        """Is this def annotated ``#: loop-only``? The annotation sits
        on the ``def`` line itself or on the line directly above it
        (mirroring guarded-by's own-line binding rule)."""
        line = getattr(fn, "lineno", None)
        if line is None:
            return False
        return (line in self.loop_only_lines
                or (line - 1) in self.loop_only_lines)


@dataclass
class Context:
    """Whole-run inputs shared by every pass."""
    modules: List[Module]
    repo_root: str
    # docs/tests content for cross-artifact passes; None -> read from
    # repo_root lazily (tests inject synthetic content here)
    docs_fault_tolerance: Optional[str] = None
    docs_observability: Optional[str] = None
    tests_sources: Optional[Dict[str, str]] = None

    def fault_tolerance_doc(self) -> str:
        if self.docs_fault_tolerance is None:
            self.docs_fault_tolerance = self._read_doc(
                "fault_tolerance.md")
        return self.docs_fault_tolerance

    def observability_doc(self) -> str:
        if self.docs_observability is None:
            self.docs_observability = self._read_doc("observability.md")
        return self.docs_observability

    def _read_doc(self, name: str) -> str:
        p = os.path.join(self.repo_root, "docs", name)
        try:
            with open(p, "r", encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""

    def test_sources(self) -> Dict[str, str]:
        if self.tests_sources is None:
            self.tests_sources = {}
            tdir = os.path.join(self.repo_root, "tests")
            if os.path.isdir(tdir):
                for name in sorted(os.listdir(tdir)):
                    if not name.endswith(".py"):
                        continue
                    try:
                        with open(os.path.join(tdir, name), "r",
                                  encoding="utf-8") as f:
                            self.tests_sources[name] = f.read()
                    except OSError:
                        pass
        return self.tests_sources


PassFn = Callable[[Context], List[Finding]]

REGISTRY: Dict[str, PassFn] = {}


def register(pass_id: str) -> Callable[[PassFn], PassFn]:
    def deco(fn: PassFn) -> PassFn:
        REGISTRY[pass_id] = fn
        return fn
    return deco


# ---------------------------------------------------------------------------
# shared AST helpers (held-lock tracking used by three passes)
# ---------------------------------------------------------------------------

def expr_name(node: ast.AST) -> Optional[str]:
    """Dotted name of a simple expression: ``self._lock`` ->
    "self._lock", ``wp._POOL_LOCK`` -> "wp._POOL_LOCK"."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = expr_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def is_locky(name: str) -> bool:
    """Does a dotted expression look like a lock (by last component)?"""
    return bool(LOCKY_NAME_RE.search(name.rsplit(".", 1)[-1]))


def is_wire_lock(name: str) -> bool:
    return bool(WIRE_LOCK_RE.search(name.rsplit(".", 1)[-1]))


class FuncScanner:
    """Statement-ordered walk of ONE function body tracking the set of
    lexically held locks. Handles:

    - ``with <lock>:`` (multiple items, nested)
    - manual ``<lock>.acquire()`` ... ``try/finally: <lock>.release()``
      (held region = acquire statement to release statement, any
      control flow between them — conservative, per function)
    - conditional acquisition: a lock acquired inside an ``if`` arm is
      held only within that arm's lexical extent

    ``on_node(node, held)`` fires for EVERY visited node with the
    currently-held dotted lock names (a multiset via list).
    ``visit_unheld=False`` skips descending into statements while no
    lock is held — passes that only care about held regions (blocking,
    lock-order) avoid walking ~95% of the package."""

    def __init__(self, on_node, visit_unheld: bool = True):
        self.on_node = on_node
        self.visit_unheld = visit_unheld
        # manually-acquired locks, FUNCTION-wide: acquire/release are
        # control-flow (not lexically scoped like `with`), so a
        # release() inside a nested block — the try/finally idiom —
        # must end the held region for everything after it
        self._manual: List[str] = []

    def scan(self, func: ast.AST) -> None:
        body = getattr(func, "body", [])
        self._manual = []
        self._scan_block(body, [])

    def _eff(self, held: List[str]) -> List[str]:
        """Effective held set at a visit point: lexical with-held plus
        the current manually-acquired multiset."""
        return held + self._manual if self._manual else held

    def _scan_block(self, stmts: List[ast.stmt], held: List[str]) -> None:
        for stmt in stmts:
            self._scan_stmt(stmt, held)
            name = self._manual_acquire(stmt)
            if name is not None:
                self._manual.append(name)
            name = self._manual_release(stmt)
            if name is not None and name in self._manual:
                self._manual.remove(name)

    def _manual_acquire(self, stmt: ast.stmt) -> Optional[str]:
        call = self._lock_method_call(stmt, "acquire")
        return call

    def _manual_release(self, stmt: ast.stmt) -> Optional[str]:
        return self._lock_method_call(stmt, "release")

    @staticmethod
    def _lock_method_call(stmt: ast.stmt, method: str) -> Optional[str]:
        if not isinstance(stmt, ast.Expr):
            return None
        call = stmt.value
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == method):
            return None
        name = expr_name(call.func.value)
        if name and is_locky(name):
            return name
        return None

    def _scan_stmt(self, stmt: ast.stmt, held: List[str]) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in stmt.items:
                self._visit_expr(item.context_expr, held + acquired)
                name = expr_name(item.context_expr)
                if name and is_locky(name):
                    acquired.append(name)
                if item.optional_vars is not None:
                    self._visit_expr(item.optional_vars, held + acquired)
            self._scan_block(stmt.body, held + acquired)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested function: runs later (often on another thread) —
            # its body starts with NO held locks, lexical OR manual
            self.on_node(stmt, self._eff(held))
            for deco in stmt.decorator_list:
                self._visit_expr(deco, held)
            outer_manual, self._manual = self._manual, []
            try:
                self._scan_block(stmt.body, [])
            finally:
                self._manual = outer_manual
            return
        if isinstance(stmt, ast.ClassDef):
            self.on_node(stmt, self._eff(held))
            outer_manual, self._manual = self._manual, []
            try:
                for sub in stmt.body:
                    self._scan_stmt(sub, [])
            finally:
                self._manual = outer_manual
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._visit_expr(stmt.test, held)
            self._scan_block(stmt.body, held)
            self._scan_block(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.target, held)
            self._visit_expr(stmt.iter, held)
            self._scan_block(stmt.body, held)
            self._scan_block(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            self._scan_block(stmt.body, held)
            for handler in stmt.handlers:
                self._scan_block(handler.body, held)
            self._scan_block(stmt.orelse, held)
            self._scan_block(stmt.finalbody, held)
            return
        # leaf statement: visit all expressions inside it
        eff = self._eff(held)
        if not eff and not self.visit_unheld:
            return
        self.on_node(stmt, eff)
        for node in _walk_skip_lambda(stmt):
            if node is not stmt:
                self.on_node(node, eff)

    def _visit_expr(self, expr: ast.AST, held: List[str]) -> None:
        eff = self._eff(held)
        if not eff and not self.visit_unheld:
            return
        for node in _walk_skip_lambda(expr):
            self.on_node(node, eff)


def _walk_skip_lambda(root: ast.AST):
    """ast.walk, but PRUNE lambda subtrees entirely: a lambda body runs
    later (often on another thread or never), so neither a held-lock
    claim nor a blocking-call finding inside it is sound. The deferred
    body is deliberately not re-scanned unheld either — a conservative
    blind spot the nested-``def`` path does cover (prefer a def for
    thread targets)."""
    from collections import deque
    todo = deque([root])
    while todo:
        node = todo.popleft()
        if isinstance(node, ast.Lambda):
            continue
        todo.extend(ast.iter_child_nodes(node))
        yield node


def iter_functions(tree: ast.Module
                   ) -> Iterable[Tuple[Optional[str], ast.AST]]:
    """Yield (enclosing class name or None, function node) for every
    top-level and method-level function in a module (nested functions
    are reached by FuncScanner itself)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    yield node.name, sub


def tracked_lock_name(value: ast.AST) -> Optional[str]:
    """If ``value`` is a ``tracked_lock("name", ...)`` call, return the
    stable runtime lock-class name — the static passes share the lock
    sanitizer's naming so the two views agree."""
    if (isinstance(value, ast.Call)
            and ((isinstance(value.func, ast.Name)
                  and value.func.id == "tracked_lock")
                 or (isinstance(value.func, ast.Attribute)
                     and value.func.attr == "tracked_lock"))
            and value.args
            and isinstance(value.args[0], ast.Constant)
            and isinstance(value.args[0].value, str)):
        return value.args[0].value
    return None


def has_locky_source(module: Module) -> bool:
    """Cheap substring gate: can this module mention a lock-like name
    at all? ("ock" covers Lock/RLock/_lock/wlock; cv/cond/mutex the
    rest.) Conservative — matching is cheap, missing is not allowed."""
    s = module.source
    return ("ock" in s or "cv" in s or "cond" in s or "mutex" in s)


def class_lock_names(module: Module) -> Dict[Tuple[str, str], str]:
    """(ClassName, attr) -> stable lock-class name for every lock-like
    attribute assigned in a class body. tracked_lock("x") names win;
    plain locks fall back to ``module.Class.attr``."""
    out: Dict[Tuple[str, str], str] = {}
    if not has_locky_source(module):
        return out
    for node in module.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for fn in node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.Assign):
                    continue
                for target in stmt.targets:
                    name = expr_name(target)
                    if (not name or not name.startswith("self.")
                            or not is_locky(name)):
                        continue
                    attr = name[len("self."):]
                    tname = tracked_lock_name(stmt.value)
                    out[(node.name, attr)] = (
                        tname if tname
                        else f"{module.name}.{node.name}.{attr}")
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

@dataclass
class Baseline:
    entries: Dict[str, str] = field(default_factory=dict)  # key -> note

    @classmethod
    def load(cls, path: str) -> "Baseline":
        bl = cls()
        if not os.path.exists(path):
            return bl
        with open(path, "r", encoding="utf-8") as f:
            for raw in f:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                # keys never contain whitespace, so split on the first
                # space-then-# — tolerant of one OR two spaces before
                # the justification comment
                key, _, note = line.partition(" #")
                bl.entries[key.strip()] = note.strip()
        return bl

    def covers(self, finding: Finding) -> bool:
        return finding.baseline_key() in self.entries

    def unused(self, findings: List[Finding]) -> List[str]:
        seen = {f.baseline_key() for f in findings}
        return [k for k in self.entries if k not in seen]


def load_modules(paths: Iterable[str], repo_root: str) -> List[Module]:
    modules: List[Module] = []
    for path in sorted(set(paths)):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, repo_root)
        try:
            modules.append(Module(path, rel, source))
        except SyntaxError as e:
            raise SystemExit(f"raylint: cannot parse {rel}: {e}")
    return modules


def collect_py_files(args: Iterable[str]) -> List[str]:
    files: List[str] = []
    for arg in args:
        if os.path.isdir(arg):
            for dirpath, dirnames, filenames in os.walk(arg):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in filenames:
                    if fn.endswith(".py"):
                        files.append(os.path.join(dirpath, fn))
        elif arg.endswith(".py"):
            files.append(arg)
    return files
