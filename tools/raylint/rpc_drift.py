"""rpc-drift pass: client method-name literals match server dispatch.

The wire protocol is stringly typed: ``client.call("push_task", ...)``
dispatches to ``handle_push_task`` on whichever service the server
wraps, with the message schema declared via ``declare("push_task",
...)``. Rename a handler (or typo a call site) and nothing fails until
the call 404s at runtime — on the wire, under load.

Checks, across the whole linted package:

- every string-literal method name at a ``.call("x")`` /
  ``.notify("x")`` / ``._call("x")`` client site is ``declare()``\\ d
  AND has a ``handle_x`` method on some service class;
- every server-initiated push (``conn.push("x")`` /
  ``notify_driver("x")``) has a consumer: the literal ``"x"`` appears
  inside some ``_on_push`` demux function;
- every ``declare()``\\ d method has a ``handle_x`` somewhere (a dead
  declare is protocol drift in the other direction).

False-positive guards: a ``self.method("x")`` call where the enclosing
class itself defines ``method`` is an ordinary method call, not an RPC
(e.g. ``PreemptionWatcher.notify("sigterm")``); modules that never
import the rpc layer are skipped entirely (``util/client`` speaks its
own protocol).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.raylint.core import Context, Finding, Module, register

PASS_ID = "rpc-drift"

CALL_ATTRS = {"call", "notify", "_call"}
PUSH_ATTRS = {"push", "notify_driver"}


def _imports_rpc(module: Module) -> bool:
    if "rpc" not in module.source:
        return False    # no import statement can name it
    for node in module.walk():
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.endswith("rpc") or any(
                    a.name == "rpc" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any(a.name.endswith(".rpc") or a.name == "rpc"
                   for a in node.names):
                return True
    return False


def _first_str_arg(call: ast.Call) -> Optional[str]:
    if (call.args and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)):
        return call.args[0].value
    return None


def _class_methods(module: Module) -> Dict[str, Set[str]]:
    out: Dict[str, Set[str]] = {}
    for node in module.tree.body:
        if isinstance(node, ast.ClassDef):
            out[node.name] = {
                sub.name for sub in node.body
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))}
    return out


@register(PASS_ID)
def run(ctx: Context) -> List[Finding]:
    declared: Dict[str, Tuple[str, int]] = {}
    handlers: Set[str] = set()
    push_consumers: Set[str] = set()
    # (module, cls, method, name, line, kind) call/push sites
    sites: List[Tuple[Module, Optional[str], str, str, int, str]] = []

    for module in ctx.modules:
        if not _imports_rpc(module):
            continue
        methods = _class_methods(module)
        # handler tables + push-demux literals
        for node in module.defs():
            if node.name.startswith("handle_"):
                handlers.add(node.name[len("handle_"):])
            if node.name in ("_on_push", "on_push"):
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Constant)
                            and isinstance(sub.value, str)):
                        push_consumers.add(sub.value)
        # declare() schema table + client/push sites, with class context
        for scope in module.calls():
            fname = None
            if isinstance(scope.func, ast.Name):
                fname = scope.func.id
            elif isinstance(scope.func, ast.Attribute):
                fname = scope.func.attr
            name = _first_str_arg(scope)
            if name is None:
                continue
            if fname == "declare":
                declared.setdefault(name, (module.relpath, scope.lineno))
                continue
            if fname in CALL_ATTRS | PUSH_ATTRS:
                cls = module.enclosing_class(scope.lineno)
                if (isinstance(scope.func, ast.Attribute)
                        and isinstance(scope.func.value, ast.Name)
                        and scope.func.value.id == "self"
                        and cls is not None
                        and fname in methods.get(cls, ())):
                    continue    # intra-class method call, not an RPC
                if isinstance(scope.func, ast.Name):
                    continue    # bare function named call/push: not rpc
                kind = "push" if fname in PUSH_ATTRS else "call"
                sites.append((module, cls, fname, name,
                              scope.lineno, kind))

    findings: List[Finding] = []
    for module, cls, fname, name, line, kind in sites:
        if module.suppressed(PASS_ID, line):
            continue
        where = f"{cls}." if cls else ""
        if kind == "call":
            if name not in declared:
                findings.append(Finding(
                    PASS_ID, module.relpath, line, f"undeclared:{name}",
                    f"{where}{fname}({name!r}) has no declare() schema "
                    f"— undeclared rpc method"))
            elif name not in handlers:
                findings.append(Finding(
                    PASS_ID, module.relpath, line, f"unhandled:{name}",
                    f"{where}{fname}({name!r}) has no handle_{name} on "
                    f"any linted service class"))
        else:
            if name not in push_consumers:
                findings.append(Finding(
                    PASS_ID, module.relpath, line, f"unconsumed:{name}",
                    f"{where}{fname}({name!r}) push has no _on_push "
                    f"consumer for {name!r}"))
    # dead declares (drift in the other direction)
    for name, (path, line) in sorted(declared.items()):
        if name not in handlers:
            findings.append(Finding(
                PASS_ID, path, line, f"dead-declare:{name}",
                f"declare({name!r}) has no handle_{name} on any linted "
                f"service class"))
    return findings
