"""capability-drift pass: hello flags advertised, gated, and honored.

The registry is ``ray_tpu/_private/capabilities.py`` (a pure-literal
``CAPABILITY_FLAGS`` dict; see its docstring for the protocol). Three
legs per flag, all whole-program:

- ``no-advertiser``: a ``hello`` flag missing from every
  ``handle_hello*`` reply dict, or a ``frame`` flag no wire send site
  ever writes;
- ``dead-flag``: a ``hello`` flag whose guard attribute is never read
  (nothing consults the advertisement), or a ``frame`` flag no
  receiver ever gates on (``msg.get("flag")`` / ``msg["flag"]``);
- ``unguarded-send``: a send site writing a ``frame`` flag whose
  ``requires`` guards are referenced neither by the sending function,
  nor by a direct same-class caller, nor by a same-class helper that
  caller consults — the hoisted-check idiom (``execute_task`` asks
  ``_submit_coalescer``, which reads ``_batch_supported``, before
  calling ``_submit_batched``) is recognized one hop deep.

Send sites are: keywords on rpc wire calls (``.call``/``._call``/
``.notify``/``.notify_driver``/``.push``), string-literal dict keys,
and ``kw["flag"] = ...`` subscript stores — the three shapes frame
payloads are built from. Constructor keywords and ``declare()`` field
lists are NOT send sites.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.raylint.core import Context, Finding, Module, register

PASS_ID = "capability-drift"

WIRE_SEND_ATTRS = {"call", "_call", "notify", "notify_driver", "push"}


def _load_registry(ctx: Context) -> Optional[Dict[str, dict]]:
    for module in ctx.modules:
        if module.name != "capabilities":
            continue
        for node in module.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if any(isinstance(t, ast.Name)
                   and t.id == "CAPABILITY_FLAGS"
                   for t in node.targets):
                try:
                    reg = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return None
                return reg if isinstance(reg, dict) else None
    return None


class _ClassIndex:
    """Per (module, class): attr reads, self-call edges, per-method."""

    def __init__(self) -> None:
        self.refs: Dict[str, Set[str]] = {}      # method -> attrs read
        self.calls: Dict[str, Set[str]] = {}     # method -> self.X()


def _index_class(cls: ast.ClassDef) -> _ClassIndex:
    idx = _ClassIndex()
    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        refs: Set[str] = set()
        calls: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute):
                if isinstance(node.ctx, ast.Load):
                    refs.add(node.attr)
                if (isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    # self.X(...) call edge (parent Call not needed:
                    # reading self.X at all implies consulting it)
                    calls.add(node.attr)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name)
                  and node.func.id == "getattr"
                  and len(node.args) >= 2
                  and isinstance(node.args[1], ast.Constant)
                  and isinstance(node.args[1].value, str)):
                refs.add(node.args[1].value)
        idx.refs[fn.name] = refs
        idx.calls[fn.name] = calls
    return idx


def _guard_dominates(idx: _ClassIndex, fn_name: str,
                     requires: List[str]) -> bool:
    req = set(requires)
    if idx.refs.get(fn_name, set()) & req:
        return True
    for caller, callees in idx.calls.items():
        if fn_name not in callees:
            continue
        if idx.refs.get(caller, set()) & req:
            return True
        # one hop into the caller's helpers: the hoisted-check idiom
        for helper in callees:
            if helper != fn_name and idx.refs.get(helper, set()) & req:
                return True
    return False


@register(PASS_ID)
def run(ctx: Context) -> List[Finding]:
    registry = _load_registry(ctx)
    if not registry:
        return []
    hello = {n: s for n, s in registry.items()
             if s.get("kind") == "hello"}
    frame = {n: s for n, s in registry.items()
             if s.get("kind") == "frame"}
    reg_path = next((m.relpath for m in ctx.modules
                     if m.name == "capabilities"), "capabilities.py")

    advertised: Set[str] = set()        # hello keys seen in a reply
    guard_reads: Set[str] = set()       # attribute names read anywhere
    frame_sends: Dict[str, List[Tuple[Module, Optional[str],
                                      Optional[str], int]]] = {}
    frame_gates: Set[str] = set()       # flags read via .get()/[...]
    frame_names = set(frame)

    class_idx: Dict[Tuple[str, str], _ClassIndex] = {}

    # every leg keys on a SPECIFIC textual form of a registry string:
    # flags appear quoted (dict key, .get arg, subscript) or as a
    # kwarg (`flag=`); guards appear as an attribute (`.guard`) or a
    # getattr string. Bare-word matches ("batch" inside "batching")
    # would drag half the package through the scoped walk below.
    tokens: Set[str] = set()
    for flag in registry:
        tokens.update((f'"{flag}"', f"'{flag}'", f"{flag}="))
    guards = ({s.get("guard", "") for s in hello.values()} - {""})
    for spec in frame.values():
        guards.update(spec.get("requires", []))
    for g in guards:
        tokens.update((f".{g}", f'"{g}"', f"'{g}'"))

    def _site_scope(module: Module,
                    line: int) -> Tuple[Optional[str], Optional[str]]:
        """(class, function) names for one send site, resolved by line
        range — scope is only needed for the handful of sites found,
        so threading it through the whole traversal is wasted work."""
        cls_node = module.enclosing_class_node(line)
        fn_node = module.enclosing_def(line)
        cls = cls_node.name if cls_node is not None else None
        if cls_node is not None:
            key = (module.relpath, cls)
            if key not in class_idx:
                class_idx[key] = _index_class(cls_node)
        return cls, (fn_node.name if fn_node is not None else None)

    for module in ctx.modules:
        if module.name == "capabilities":
            continue
        src = module.source
        if ("handle_hello" not in src
                and not any(t in src for t in tokens)):
            continue
        # hello advertisers: dict keys inside handle_hello* bodies
        # (the innermost def must BE the advertiser — a dict built in
        # a nested helper def is that helper's, not the reply's)
        for fn_node in module.defs():
            if not fn_node.name.startswith("handle_hello"):
                continue
            for node in ast.walk(fn_node):
                if node.__class__ is ast.Dict:
                    fn = module.enclosing_def(node.lineno)
                    if (fn is not None
                            and not fn.name.startswith("handle_hello")):
                        continue
                    for k in node.keys:
                        if (isinstance(k, ast.Constant)
                                and isinstance(k.value, str)):
                            advertised.add(k.value)
        # one flat dispatch over the shared node cache
        for node in module.walk():
            kind = node.__class__
            if kind is ast.Attribute:
                if isinstance(node.ctx, ast.Load):
                    guard_reads.add(node.attr)      # hello gate leg
            elif kind is ast.Call:
                func = node.func
                if (isinstance(func, ast.Name)
                        and func.id == "getattr"
                        and len(node.args) >= 2
                        and isinstance(node.args[1], ast.Constant)
                        and isinstance(node.args[1].value, str)):
                    guard_reads.add(node.args[1].value)
                elif isinstance(func, ast.Attribute):
                    # frame-flag receive gate: x.get("flag")
                    if (func.attr == "get" and node.args
                            and isinstance(node.args[0], ast.Constant)
                            and node.args[0].value in frame_names):
                        frame_gates.add(node.args[0].value)
                    # frame-flag send site: wire-call keyword
                    elif func.attr in WIRE_SEND_ATTRS:
                        for kwarg in node.keywords:
                            if kwarg.arg in frame_names:
                                cls, fn = _site_scope(
                                    module, node.lineno)
                                frame_sends.setdefault(
                                    kwarg.arg, []).append(
                                    (module, cls, fn, node.lineno))
            elif kind is ast.Subscript:
                slc = node.slice
                if (isinstance(slc, ast.Constant)
                        and slc.value in frame_names):
                    if isinstance(node.ctx, ast.Load):
                        frame_gates.add(slc.value)  # x["flag"] gate
                    elif isinstance(node.ctx, ast.Store):
                        cls, fn = _site_scope(module, node.lineno)
                        frame_sends.setdefault(slc.value, []).append(
                            (module, cls, fn, node.lineno))
            elif kind is ast.Dict:
                for k in node.keys:
                    if (isinstance(k, ast.Constant)
                            and k.value in frame_names):
                        cls, fn = _site_scope(module, node.lineno)
                        frame_sends.setdefault(k.value, []).append(
                            (module, cls, fn, node.lineno))

    findings: List[Finding] = []
    for flag, spec in sorted(hello.items()):
        guard = spec.get("guard", "")
        if flag not in advertised:
            findings.append(Finding(
                PASS_ID, reg_path, 1, f"no-advertiser:{flag}",
                f"hello capability {flag!r} is registered but no "
                f"handle_hello* reply advertises it"))
        if guard and guard not in guard_reads:
            findings.append(Finding(
                PASS_ID, reg_path, 1, f"dead-flag:{flag}",
                f"hello capability {flag!r} guard .{guard} is never "
                f"read — the advertisement gates nothing"))
    for flag, spec in sorted(frame.items()):
        sends = frame_sends.get(flag, [])
        if not sends:
            findings.append(Finding(
                PASS_ID, reg_path, 1, f"no-advertiser:{flag}",
                f"frame capability flag {flag!r} is registered but no "
                f"wire send site ever writes it"))
        if flag not in frame_gates:
            findings.append(Finding(
                PASS_ID, reg_path, 1, f"dead-flag:{flag}",
                f"frame capability flag {flag!r} is never gated on by "
                f"any receiver (msg.get/msg[...])"))
        requires = list(spec.get("requires", []))
        if not requires:
            continue
        for module, cls, fn, line in sends:
            if module.suppressed(PASS_ID, line):
                continue
            idx = (class_idx.get((module.relpath, cls))
                   if cls is not None else None)
            ok = (idx is not None and fn is not None
                  and _guard_dominates(idx, fn, requires))
            if ok:
                continue
            where = (f"{cls}.{fn}" if cls and fn else fn or "<module>")
            findings.append(Finding(
                PASS_ID, module.relpath, line,
                f"unguarded-send:{flag}:{where}",
                f"{where}() writes capability-gated key {flag!r} "
                f"without a dominating check of "
                f"{' or '.join('.' + r for r in requires)} — peer may "
                f"not have advertised it"))
    return findings
