"""metric-registry pass: every ray_tpu_* metric is in the catalogue.

Same machine-check discipline as failpoint-registry, applied to the
metrics surface: any ``ray_tpu_*`` series name the runtime can emit
must appear in ``docs/observability.md``'s metric catalogue — an
operator alerting off the docs must never meet an undocumented series
(or grep for one that was renamed). Collected creation shapes:

- registry constructors: ``Counter("ray_tpu_x", ...)`` /
  ``Gauge(...)`` / ``Histogram(...)`` and the module-local
  ``_counter``/``_gauge``/``_histogram`` helpers;
- wire-entry dict literals: ``{"name": "ray_tpu_x", "kind": ...}``
  (the cross-process delta format merged by ``merge_deltas``).

Dynamically formatted names (f-strings) are not string constants and
are skipped — document the PREFIX family in the catalogue and keep a
``# raylint: disable=metric-registry`` near deliberate dynamic names
if one ever needs the reminder.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from tools.raylint.core import Context, Finding, register

PASS_ID = "metric-registry"

CTOR_NAMES = {"Counter", "Gauge", "Histogram",
              "_counter", "_gauge", "_histogram"}
PREFIX = "ray_tpu_"


def _metric_sites(ctx: Context) -> Dict[str, Tuple[str, int]]:
    sites: Dict[str, Tuple[str, int]] = {}
    for module in ctx.modules:
        if PREFIX not in module.source:
            continue
        for node in module.walk():
            name = None
            if isinstance(node, ast.Call):
                fname = None
                if isinstance(node.func, ast.Name):
                    fname = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    fname = node.func.attr
                if (fname in CTOR_NAMES and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                        and node.args[0].value.startswith(PREFIX)):
                    name = node.args[0].value
            elif isinstance(node, ast.Dict):
                keys = {k.value: v for k, v in zip(node.keys,
                                                   node.values)
                        if isinstance(k, ast.Constant)}
                v = keys.get("name")
                if ("kind" in keys and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                        and v.value.startswith(PREFIX)):
                    name = v.value
            if name is None:
                continue
            if module.suppressed(PASS_ID, node.lineno):
                continue
            sites.setdefault(name, (module.relpath, node.lineno))
    return sites


@register(PASS_ID)
def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    doc = ctx.observability_doc()
    for name, (path, line) in sorted(_metric_sites(ctx).items()):
        if f"`{name}`" in doc or name in doc:
            continue
        findings.append(Finding(
            PASS_ID, path, line, f"undocumented:{name}",
            f"metric {name!r} missing from docs/observability.md's "
            f"metric catalogue"))
    return findings
