"""blocking-under-lock pass: no wire I/O / sleeps / RPC inside a lock.

The exact shape of the PR-4 futex-convoy bug: a shared lock held across
a syscall (or a whole RPC round trip) turns every contending thread
into a convoy. Flags, inside any lexically held lock region:

- ``time.sleep(...)``
- socket sends/receives (``send``/``sendall``/``recv``/``recv_into``/
  ``recv_exact``/``connect``/``accept`` attribute calls)
- RPC round trips: ``.call(...)`` / ``._call(...)`` /
  ``.notify_driver(...)`` attribute calls
- ``subprocess.*`` invocations (``Popen``/``run``/``check_call``/
  ``check_output``/``call``)

Deliberate exemptions:

- condition-variable methods on the held lock itself (``cv.wait()``
  releases it; ``notify``/``notify_all`` are cheap)
- *wire-write locks* (attribute name matching ``wlock``/``send_lock``/
  ``wire``): their entire purpose is serializing a socket write, so a
  send under one is the design, not a bug
- anything marked ``# raylint: disable=blocking-under-lock`` or
  baselined with a justification
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.raylint.core import (Context, Finding, FuncScanner,
                                expr_name, is_locky, is_wire_lock,
                                iter_functions, register)

PASS_ID = "blocking-under-lock"

SOCKET_ATTRS = {"send", "sendall", "sendmsg", "recv", "recv_into",
                "recvmsg", "recv_exact", "connect", "accept",
                # Connection reply/push wrappers: one frame send each
                "reply", "reply_error", "push"}
RPC_ATTRS = {"call", "_call", "notify", "notify_driver"}
SUBPROCESS_ATTRS = {"Popen", "run", "check_call", "check_output", "call"}
# lock-protocol methods that are NOT blocking work — exempt only when
# the RECEIVER itself looks like a lock/cv: `self._cv.notify()` is
# protocol, `client.notify(...)` is a wire frame (rpc.Client.notify)
LOCK_PROTOCOL = {"acquire", "release", "wait", "wait_for", "notify",
                 "notify_all", "locked"}


def _blocking_call(node: ast.Call) -> Optional[str]:
    """Describe why this call is blocking, or None."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    recv = expr_name(func.value)
    attr = func.attr
    if recv == "time" and attr == "sleep":
        return "time.sleep()"
    if recv == "subprocess" and attr in SUBPROCESS_ATTRS:
        return f"subprocess.{attr}()"
    if recv is not None and attr in LOCK_PROTOCOL and (
            is_locky(recv) or attr not in RPC_ATTRS):
        return None     # cv.wait()/lock.acquire(): lock protocol
    if attr in SOCKET_ATTRS:
        return f"socket {attr}() on {recv or '<expr>'}"
    if attr in RPC_ATTRS:
        # skip subprocess-style receivers handled above, and method
        # calls on self when the enclosing class defines them is the
        # rpc-drift pass's concern; here any .call() round trip counts
        return f"RPC {attr}() on {recv or '<expr>'}"
    return None


@register(PASS_ID)
def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for module in ctx.modules:
        for cls, fn in iter_functions(module.tree):
            reported = set()

            def on_node(node: ast.AST, held: List[str],
                        _cls=cls, _fn=fn, _reported=reported) -> None:
                if not held or not isinstance(node, ast.Call):
                    return
                # ignore wire-write locks: everything held is exempt
                # only if ALL held locks are wire locks
                effective = [h for h in held if not is_wire_lock(h)]
                if not effective:
                    return
                why = _blocking_call(node)
                if why is None:
                    return
                if module.suppressed(PASS_ID, node.lineno):
                    return
                where = f"{_cls}.{_fn.name}" if _cls else _fn.name
                key = f"{where}:{why}"
                if key in _reported:
                    return
                _reported.add(key)
                findings.append(Finding(
                    PASS_ID, module.relpath, node.lineno, key,
                    f"{why} while holding {', '.join(effective)} "
                    f"in {where}()"))

            FuncScanner(on_node, visit_unheld=False).scan(fn)
    return findings
