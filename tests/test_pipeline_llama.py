"""Pipeline-parallel Llama: parity with the un-pipelined model.

Reference capability under test: pipeline schedules as compiled actor
DAGs (``python/ray/dag/compiled_dag_node.py:809``); here the schedule is
a single SPMD program (models/llama_pp.py) and the contract is numeric
parity with pp=1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _cfg(**kw):
    from ray_tpu.models.llama import LlamaConfig
    base = dict(vocab_size=128, dim=32, n_layers=4, n_heads=4,
                n_kv_heads=2, ffn_dim=64, max_seq_len=32, remat=False,
                dtype=jnp.float32)
    base.update(kw)
    return LlamaConfig(**base)


def _mesh(**axes):
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    spec = MeshSpec(**axes)
    return build_mesh(spec, jax.devices()[:spec.num_devices])


def _data(cfg, batch=4, seed=0):
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq_len)),
        jnp.int32)
    return tokens, jnp.roll(tokens, -1, axis=1)


def test_stack_unstack_roundtrip():
    from ray_tpu.models.llama import LlamaModel
    from ray_tpu.models.llama_pp import stack_stages, unstack_stages

    cfg = _cfg()
    params = LlamaModel(cfg).init(jax.random.key(0))
    stacked = stack_stages(params, 2)
    assert stacked["layers"]["wq"].shape[:2] == (2, 2)
    back = unstack_stages(stacked)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipelined_loss_matches_pp1():
    """pp=2 x dp=2 x tp=2 over 8 devices reproduces the single-device
    loss exactly (f32): the GPipe schedule + Megatron psums are the same
    math, just scheduled."""
    from ray_tpu.models.llama import LlamaModel
    from ray_tpu.models.llama_pp import PipelinedLlama, stack_stages

    cfg = _cfg()
    base = LlamaModel(cfg)
    params = base.init(jax.random.key(0))
    tokens, targets = _data(cfg)
    l_ref = float(base.loss(params, tokens, targets))

    mesh = _mesh(pp=2, dp=2, tp=2)
    model = PipelinedLlama(cfg, mesh, num_microbatches=2)
    l_pp = float(model.loss(stack_stages(params, 2), tokens, targets))
    assert np.isfinite(l_pp)
    np.testing.assert_allclose(l_ref, l_pp, rtol=2e-5, atol=2e-5)


def test_pipelined_four_stages_four_microbatches():
    """pp=4 with M=4 microbatches (deeper fill/drain) still matches."""
    from ray_tpu.models.llama import LlamaModel
    from ray_tpu.models.llama_pp import PipelinedLlama, stack_stages

    cfg = _cfg()
    base = LlamaModel(cfg)
    params = base.init(jax.random.key(1))
    tokens, targets = _data(cfg, batch=8, seed=1)
    l_ref = float(base.loss(params, tokens, targets))

    mesh = _mesh(pp=4, dp=2)
    model = PipelinedLlama(cfg, mesh, num_microbatches=4)
    l_pp = float(model.loss(stack_stages(params, 4), tokens, targets))
    np.testing.assert_allclose(l_ref, l_pp, rtol=2e-5, atol=2e-5)


def test_pipelined_train_step_matches_pp1():
    """One optimizer step through make_train_step: grads flow through the
    scan/ppermute schedule (autodiff IS the backward pipeline) and the
    updated params match the single-device step."""
    import optax

    from ray_tpu.models.llama import LlamaModel
    from ray_tpu.models.llama_pp import (PipelinedLlama, stack_stages,
                                         unstack_stages)
    from ray_tpu.train.spmd import make_train_step, shard_batch

    cfg = _cfg()
    tokens, targets = _data(cfg)

    base = LlamaModel(cfg)
    ts0 = make_train_step(base, optax.sgd(1e-2), donate=False)
    p0, o0 = ts0.init_fn(jax.random.key(0))
    p0_after, _, m0 = ts0.step_fn(p0, o0, (tokens, targets))

    mesh = _mesh(pp=2, dp=2, tp=2)
    model = PipelinedLlama(cfg, mesh, num_microbatches=2)
    ts1 = make_train_step(model, optax.sgd(1e-2), mesh=mesh, donate=False)
    p1, o1 = ts1.init_fn(jax.random.key(0))
    batch = shard_batch((tokens, targets), ts1)
    p1_after, _, m1 = ts1.step_fn(p1, o1, batch)

    np.testing.assert_allclose(float(m0["loss"]), float(m1["loss"]),
                               rtol=2e-5, atol=2e-5)
    flat = unstack_stages(jax.device_get(p1_after))
    for a, b in zip(jax.tree.leaves(jax.device_get(p0_after)),
                    jax.tree.leaves(flat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_jax_trainer_drives_pipelined_llama(tmp_path):
    """The flagship pipeline model runs through JaxTrainer end-to-end:
    the trainer's worker executes pp=2 train steps and the loss drops."""
    import ray_tpu
    from ray_tpu.train import (Checkpoint, JaxTrainer, RunConfig,
                               ScalingConfig)
    from ray_tpu.train import session as train_session

    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 8},
                      ignore_reinit_error=True)

    def train_fn(config):
        import optax

        from ray_tpu.models.llama_pp import PipelinedLlama
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        from ray_tpu.train.spmd import make_train_step, shard_batch

        cfg = _cfg()
        mesh = build_mesh(MeshSpec(pp=2, dp=2, tp=2),
                          jax.devices()[:8])
        model = PipelinedLlama(cfg, mesh, num_microbatches=2)
        ts = make_train_step(model, optax.adam(1e-2), mesh=mesh)
        params, opt = ts.init_fn(jax.random.key(0))
        tokens, targets = _data(cfg)
        batch = shard_batch((tokens, targets), ts)
        first = None
        for _ in range(8):
            params, opt, m = ts.step_fn(params, opt, batch)
            if first is None:
                first = float(m["loss"])
        train_session.report(
            {"first_loss": first, "last_loss": float(m["loss"])},
            checkpoint=Checkpoint.from_pytree(
                {"loss": m["loss"]}))

    result = JaxTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="pp_llama",
                             storage_path=str(tmp_path))).fit()
    ray_tpu.shutdown()
    assert result.error is None
    assert result.metrics["last_loss"] < result.metrics["first_loss"]


def test_pipelined_validates_mesh_and_config():
    from ray_tpu.models.llama_pp import PipelinedLlama

    with pytest.raises(ValueError, match="pp>=2"):
        PipelinedLlama(_cfg(), _mesh(dp=2))
    with pytest.raises(ValueError, match="not divisible"):
        PipelinedLlama(_cfg(n_layers=3), _mesh(pp=2))
    with pytest.raises(ValueError, match="sp/ep"):
        PipelinedLlama(_cfg(), _mesh(pp=2, sp=2))
