"""ICI-topology-aware gang scheduling: placement groups claim
contiguous sub-slices.

Reference capability under test: bundle placement policy
(``src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h``) —
upgraded TPU-first: bundles land on the hosts of one axis-aligned torus
sub-slice (``parallel/topology.py``) instead of by resource count.
"""

import pytest

import ray_tpu
from ray_tpu.util.placement_group import (placement_group,
                                          placement_group_table,
                                          remove_placement_group)


@pytest.fixture
def tpu_cluster():
    """4 virtual hosts of a v5p 2x2x4 slice (16 chips, 4 chips/host)."""
    rt = ray_tpu.init(
        num_nodes=4, resources={"CPU": 4, "TPU": 4},
        _system_config={"tpu_topology": "v5p:2x2x4"})
    yield rt
    ray_tpu.shutdown()


def _entry(pg):
    return placement_group_table()[pg.id.hex()]


def _is_contiguous_box(chips):
    """The claimed chip coords form exactly one axis-aligned box."""
    lo = tuple(min(c[i] for c in chips) for i in range(len(chips[0])))
    hi = tuple(max(c[i] for c in chips) for i in range(len(chips[0])))
    volume = 1
    for a, b in zip(lo, hi):
        volume *= (b - a + 1)
    return volume == len(chips) and len(set(chips)) == len(chips)


def test_pg_claims_contiguous_subslice(tpu_cluster):
    pg = placement_group([{"TPU": 4}, {"TPU": 4}], strategy="PACK")
    assert pg.wait(10)
    e = _entry(pg)
    assert "subslice" in e, e
    all_chips = [tuple(c) for chips in e["bundle_chips"] for c in chips]
    assert len(all_chips) == 8
    assert _is_contiguous_box(all_chips)
    # 8 chips at 4/host -> exactly two distinct hosts/nodes
    assert len(set(e["bundle_nodes"])) == 2
    remove_placement_group(pg)
    assert tpu_cluster.tpu_topology.topology._allocated == []


def test_strict_pack_stays_inside_one_host_block(tpu_cluster):
    pg = placement_group([{"TPU": 2}, {"TPU": 2}], strategy="STRICT_PACK")
    assert pg.wait(10)
    e = _entry(pg)
    nodes = e["bundle_nodes"]
    assert len(set(nodes)) == 1          # one node...
    topo = tpu_cluster.tpu_topology.topology
    hosts = topo.hosts_of_subslice(pg.subslice)
    assert len(hosts) == 1               # ...backed by one torus host block
    remove_placement_group(pg)


def test_strict_spread_uses_distinct_hosts(tpu_cluster):
    pg = placement_group([{"TPU": 2}] * 3, strategy="STRICT_SPREAD")
    assert pg.wait(10)
    e = _entry(pg)
    assert len(set(e["bundle_nodes"])) == 3


def test_spread_prefers_distinct_hosts(tpu_cluster):
    """SPREAD must not collapse onto one host just because the cube-like
    box fits a single host block (fault isolation is the point)."""
    pg = placement_group([{"TPU": 2}, {"TPU": 2}], strategy="SPREAD")
    assert pg.wait(10)
    assert len(set(_entry(pg)["bundle_nodes"])) == 2
    remove_placement_group(pg)


def test_mixed_cpu_bundle_not_forced_onto_subslice_hosts():
    """A chip-less bundle in a TPU group places by generic semantics:
    a big CPU bundle lands on the fat CPU node, not a 4-CPU TPU host."""
    rt = ray_tpu.init(
        num_nodes=1, resources={"CPU": 4, "TPU": 4},
        _system_config={"tpu_topology": "v5e:2x2"})
    try:
        cpu_node = rt.add_node({"CPU": 64})
        pg = placement_group([{"TPU": 4}, {"CPU": 16}], strategy="PACK")
        assert pg.wait(10)
        e = _entry(pg)
        assert e["bundle_nodes"][1] == cpu_node.node_id.hex()
        assert e["bundle_chips"][0] is not None
        assert e["bundle_chips"][1] is None
    finally:
        ray_tpu.shutdown()


def test_fragmentation_blocks_then_release_unblocks(tpu_cluster):
    """A full slice leaves a later group pending; freeing a sub-slice
    lets it place (the allocator is consulted, not just chip counts)."""
    pg_a = placement_group([{"TPU": 4}, {"TPU": 4}], strategy="PACK")
    pg_b = placement_group([{"TPU": 4}, {"TPU": 4}], strategy="PACK")
    assert pg_a.wait(10) and pg_b.wait(10)

    pg_c = placement_group([{"TPU": 4}], strategy="PACK")
    assert not pg_c.wait(1.5)            # 16/16 chips claimed
    assert pg_c.state in ("PENDING", "RESCHEDULING")

    remove_placement_group(pg_a)
    assert pg_c.wait(10)
    chips = [tuple(c) for c in _entry(pg_c)["bundle_chips"][0]]
    assert _is_contiguous_box(chips)
    remove_placement_group(pg_b)
    remove_placement_group(pg_c)


def test_oversized_bundle_rejected_up_front(tpu_cluster):
    with pytest.raises(ValueError, match="split it across bundles"):
        placement_group([{"TPU": 8}])
    with pytest.raises(ValueError, match="fractional"):
        placement_group([{"TPU": 0.5}])


def test_node_death_frees_and_replaces_subslice(tpu_cluster):
    rt = tpu_cluster
    pg = placement_group([{"TPU": 4}, {"TPU": 4}], strategy="SPREAD")
    assert pg.wait(10)
    first_slice = pg.subslice
    victim = pg.bundles[0].node_id
    rt.remove_node(rt.get_node(victim))
    assert pg.wait(15)                   # re-placed on surviving hosts
    e = _entry(pg)
    assert victim.hex() not in e["bundle_nodes"]
    all_chips = [tuple(c) for chips in e["bundle_chips"] for c in chips]
    assert _is_contiguous_box(all_chips)
    # the first claim was released: allocator holds exactly one slice
    assert len(rt.tpu_topology.topology._allocated) == 1
    assert rt.tpu_topology.topology._allocated[0] is not first_slice


def test_tasks_schedule_into_topology_bundles(tpu_cluster):
    """PG-scheduled work runs on the bundle's sub-slice node (the scoped
    resource rewrite rides the existing ledger machinery)."""
    pg = placement_group([{"CPU": 1, "TPU": 4}], strategy="PACK")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    def where():
        import ray_tpu
        return ray_tpu.get_runtime_context().get_node_id()

    strat = ray_tpu.PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=0)
    node_hex = ray_tpu.get(
        where.options(scheduling_strategy=strat).remote(), timeout=30)
    assert node_hex == pg.bundles[0].node_id.hex()
    remove_placement_group(pg)
