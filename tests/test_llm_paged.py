"""Paged KV cache: block pool, prefix reuse, preemption, paged kernel.

Reference capability: vLLM's BlockSpaceManager/prefix caching behind
`ray.llm` (`python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_models.py:126-207`); PAPERS.md paged attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import ContinuousBatchingEngine, SamplingParams
from ray_tpu.llm.paged_cache import (BlockPool, allocate_slot,
                                     ensure_capacity, seal_prompt_blocks)
from ray_tpu.models.llama import LlamaConfig, LlamaModel


# ---------------------------------------------------------------------------
# BlockPool host logic (no device work)
# ---------------------------------------------------------------------------

def test_pool_alloc_free_refcount():
    pool = BlockPool(4, 16)
    a = pool.alloc(3)
    assert len(a) == 3 and pool.num_free == 1
    assert pool.alloc(2) is None          # over-ask fails atomically
    assert pool.num_free == 1
    pool.ref(a[0])                        # second reference
    pool.unref(a[0])
    assert pool.num_free == 1             # still held once
    pool.unref_all(a)
    assert pool.num_free == 4
    with pytest.raises(ValueError):
        pool.unref(a[0])


def test_chain_hashes_full_blocks_only():
    h = BlockPool.chain_hashes([1, 2, 3, 4, 5], 2)
    assert len(h) == 2                    # 5 tokens -> 2 full blocks
    # chain: same prefix -> same hashes; divergence changes the tail
    h2 = BlockPool.chain_hashes([1, 2, 3, 9], 2)
    assert h2[0] == h[0] and h2[1] != h[1]


def test_prefix_match_and_resurrection():
    pool = BlockPool(4, 4)
    prompt = list(range(9))               # 2 full blocks + partial tail
    alloc, shared = allocate_slot(pool, prompt, 10)
    assert shared == 0 and len(alloc.blocks) == 3
    seal_prompt_blocks(pool, alloc, prompt)
    pool.unref_all(alloc.blocks)          # request finished
    assert pool.num_free == 4
    assert pool.cached_free_blocks() == 2
    # identical prompt: both full blocks resurrect from the free list
    alloc2, shared2 = allocate_slot(pool, prompt, 10)
    assert shared2 == 8
    assert alloc2.blocks[:2] == alloc.blocks[:2]
    assert pool.stats["prefix_hits"] >= 1


def test_block_aligned_prompt_never_shares_last_block():
    pool = BlockPool(8, 4)
    prompt = list(range(8))               # exactly 2 blocks
    alloc, _ = allocate_slot(pool, prompt, len(prompt))
    seal_prompt_blocks(pool, alloc, prompt)
    pool.unref_all(alloc.blocks)
    # full-prompt hit would skip prefill entirely; the last block must
    # re-prefill so the engine gets last-token logits
    _, shared = allocate_slot(pool, prompt, len(prompt))
    assert shared == 4


def test_eviction_drops_prefix_entry():
    pool = BlockPool(2, 4)
    alloc, _ = allocate_slot(pool, [1, 2, 3, 4], 8)
    seal_prompt_blocks(pool, alloc, [1, 2, 3, 4])
    pool.unref_all(alloc.blocks)
    assert pool.cached_free_blocks() == 1
    pool.alloc(2)                         # forces reuse of the cached block
    assert pool.cached_free_blocks() == 0
    assert pool.stats["evictions"] == 1
    assert pool.match_prefix(BlockPool.chain_hashes([1, 2, 3, 4], 4)) == []


def test_ensure_capacity_growth_and_exhaustion():
    pool = BlockPool(3, 4)
    alloc, _ = allocate_slot(pool, [1, 2, 3], 4)
    assert len(alloc.blocks) == 1
    assert ensure_capacity(pool, alloc, 9)
    assert len(alloc.blocks) == 3
    assert not ensure_capacity(pool, alloc, 13)   # pool exhausted
    assert len(alloc.blocks) == 3


# ---------------------------------------------------------------------------
# Engine end-to-end on the debug model
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.debug(vocab_size=512, max_seq_len=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.key(0))
    return model, params


def _greedy(model, params, prompt, n):
    """Uncached greedy reference."""
    seq = list(prompt)
    out = []
    for _ in range(n):
        logits = model.apply(params, jnp.asarray([seq], jnp.int32))
        tok = int(jnp.argmax(logits[0, -1]))
        out.append(tok)
        seq.append(tok)
    return out


def test_block_reclaim_after_finish(tiny_model):
    model, params = tiny_model
    eng = ContinuousBatchingEngine(model, params, max_slots=2, max_seq=64,
                                   prefill_buckets=(8, 16), block_size=8)
    free0 = eng.pool.num_free
    reqs = eng.generate([[1, 2, 3], [4, 5, 6, 7, 8, 9]],
                        SamplingParams(max_tokens=6))
    assert all(len(r.output) == 6 for r in reqs)
    assert eng.pool.num_free == free0          # every block reclaimed
    assert all(r == 0 for r in eng.pool.refcount)


def test_prefix_reuse_cross_request_correctness(tiny_model):
    """Second request sharing a long prefix must reuse blocks AND
    produce exactly the no-sharing greedy output."""
    model, params = tiny_model
    prefix = [(7 * i + 3) % 500 for i in range(16)]   # 2 full blocks @ 8
    p1 = prefix + [100, 101]
    p2 = prefix + [200, 201, 202]
    eng = ContinuousBatchingEngine(model, params, max_slots=2, max_seq=64,
                                   prefill_buckets=(8, 16, 32),
                                   block_size=8)
    r1 = eng.generate([p1], SamplingParams(max_tokens=4))[0]
    assert eng.stats["prefix_prefills"] == 0
    r2 = eng.generate([p2], SamplingParams(max_tokens=4))[0]
    assert eng.stats["prefix_prefills"] == 1
    assert eng.stats["prefix_tokens_reused"] == 16
    assert r1.output == _greedy(model, params, p1, 4)
    assert r2.output == _greedy(model, params, p2, 4)


def test_prefix_reuse_concurrent_requests(tiny_model):
    """Same-prefix requests running TOGETHER share physical blocks
    (refcount > 1 on the prefix while all are active). Sealing happens
    at admission, so the sharers arrive one step after the first."""
    model, params = tiny_model
    prefix = [(11 * i + 5) % 500 for i in range(8)]   # 1 full block @ 8
    eng = ContinuousBatchingEngine(model, params, max_slots=4, max_seq=64,
                                   prefill_buckets=(8, 16), block_size=8)
    prompts = [prefix + [100 + i] for i in range(3)]
    r0 = eng.submit(prompts[0], SamplingParams(max_tokens=8))
    eng.step()                       # prefill + seal the prefix block
    r1 = eng.submit(prompts[1], SamplingParams(max_tokens=8))
    r2 = eng.submit(prompts[2], SamplingParams(max_tokens=8))
    eng.step()                       # admits both; r0 still active
    prefix_block = eng.allocs[0].blocks[0]
    assert eng.pool.refcount[prefix_block] == 3   # shared by all three
    while eng.has_work():
        eng.step()
    reqs = [r0, r1, r2]
    assert all(len(r.output) == 8 for r in reqs)
    assert eng.stats["prefix_prefills"] == 2
    assert eng.stats["prefix_tokens_reused"] == 16
    for p, r in zip(prompts, reqs):
        assert r.output == _greedy(model, params, p, 8)


def test_preemption_by_recompute(tiny_model):
    """A pool too small for both requests' full generations must
    preempt (recompute) yet still finish both with correct outputs."""
    model, params = tiny_model
    p1, p2 = [1, 2, 3, 4, 5], [9, 8, 7]
    # each request needs up to ceil((5+20)/8)=4 blocks; 5 blocks total
    # forces at least one preemption while both are active
    eng = ContinuousBatchingEngine(model, params, max_slots=2, max_seq=64,
                                   prefill_buckets=(8, 16), block_size=8,
                                   num_blocks=5)
    reqs = eng.generate([p1, p2], SamplingParams(max_tokens=20))
    assert all(len(r.output) == 20 for r in reqs)
    assert eng.stats["preemptions"] >= 1
    assert sum(r.preemptions for r in reqs) >= 1
    assert reqs[0].output == _greedy(model, params, p1, 20)
    assert reqs[1].output == _greedy(model, params, p2, 20)
    assert eng.pool.num_free == 5


def test_oversubscribed_pool_many_requests(tiny_model):
    """More concurrent demand than the pool can hold: FIFO admission +
    preemption must drain everything."""
    model, params = tiny_model
    eng = ContinuousBatchingEngine(model, params, max_slots=4, max_seq=64,
                                   prefill_buckets=(8, 16, 32),
                                   block_size=8, num_blocks=6)
    prompts = [[10 + i, 20 + i, 30 + i] for i in range(8)]
    reqs = eng.generate(prompts, SamplingParams(max_tokens=10))
    assert all(len(r.output) == 10 for r in reqs)
    assert all(r.finish_reason == "length" for r in reqs)
    assert eng.pool.num_free == 6


def test_chunked_prefill_long_prompt(tiny_model):
    """A prompt LONGER than the largest prefill bucket admits via
    chunked prefill (each chunk attends over the prior chunks' blocks)
    and still matches the uncached greedy reference."""
    model, params = tiny_model
    prompt = [(13 * i + 11) % 500 for i in range(21)]  # > bucket 16
    eng = ContinuousBatchingEngine(model, params, max_slots=2, max_seq=64,
                                   prefill_buckets=(8, 16), block_size=8)
    req = eng.generate([prompt], SamplingParams(max_tokens=5))[0]
    assert len(req.output) == 5
    assert req.output == _greedy(model, params, prompt, 5)


# ---------------------------------------------------------------------------
# Paged attention kernel (interpret mode)
# ---------------------------------------------------------------------------

def test_paged_kernel_matches_reference():
    from ray_tpu.ops.paged_attention import (
        paged_decode_attention_pallas, paged_decode_attention_reference)
    rng = np.random.default_rng(0)
    B, H, Hkv, D, bs, NB, maxb = 4, 8, 4, 128, 16, 32, 6
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(NB, bs, Hkv, D)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(NB, bs, Hkv, D)), jnp.float32)
    tables = jnp.asarray(
        rng.permutation(NB)[:B * maxb].reshape(B, maxb), jnp.int32)
    lengths = jnp.asarray([1, 16, 37, 96], jnp.int32)
    ref = paged_decode_attention_reference(q, kp, vp, tables, lengths)
    out = paged_decode_attention_pallas(q, kp, vp, tables, lengths,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_paged_reference_gather_equals_dense():
    """The paged XLA fallback must equal ragged attention on the dense
    equivalent of the same block layout."""
    from ray_tpu.ops.decode_attention import \
        ragged_decode_attention_reference
    from ray_tpu.ops.paged_attention import paged_decode_attention_reference
    rng = np.random.default_rng(1)
    B, H, Hkv, D, bs, NB, maxb = 2, 4, 2, 16, 8, 16, 4
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(NB, bs, Hkv, D)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(NB, bs, Hkv, D)), jnp.float32)
    tables = jnp.asarray(rng.permutation(NB)[:B * maxb].reshape(B, maxb),
                         jnp.int32)
    lengths = jnp.asarray([13, 27], jnp.int32)
    paged = paged_decode_attention_reference(q, kp, vp, tables, lengths)
    k_dense = kp[tables].reshape(B, maxb * bs, Hkv, D)
    v_dense = vp[tables].reshape(B, maxb * bs, Hkv, D)
    dense = ragged_decode_attention_reference(q, k_dense, v_dense, lengths)
    np.testing.assert_allclose(np.asarray(paged), np.asarray(dense),
                               atol=1e-6)


def test_paged_decode_step_pallas_matches_xla(tiny_model):
    """model.decode_step_paged over the PALLAS paged kernel (interpret
    off-TPU) must match the XLA fallback's logits on identical pool
    state — logits, not greedy tokens: bf16 rounding can legally flip
    near-tied argmaxes on a 512-vocab debug model."""
    import dataclasses

    from ray_tpu.models.llama import LlamaConfig, LlamaModel
    model, params = tiny_model
    cfg_p = dataclasses.replace(model.cfg, decode_attention="pallas")
    model_p = LlamaModel(cfg_p)

    # build LIVE pool state: submit long generations and stop mid-run so
    # slots still own real multi-block tables (a finished slot's table
    # resets to scratch and would compare degenerate inputs)
    eng = ContinuousBatchingEngine(model, params, max_slots=2, max_seq=64,
                                   prefill_buckets=(8, 16), block_size=8)
    eng.submit([3, 1, 4, 1, 5], SamplingParams(max_tokens=40))
    eng.submit([2, 7, 2, 7, 2, 7, 2, 7, 2], SamplingParams(max_tokens=40))
    for _ in range(12):                     # grow past one block each
        eng.step()
    assert all(r is not None for r in eng.slots[:2])
    tables_np = np.array(eng._tables[:2])
    offsets_np = np.array(eng.offsets[:2])
    assert (offsets_np > 8).all()           # >1 live block per slot
    assert len({int(x) for x in tables_np[:, :2].ravel()}) > 2
    pool = {"k": eng.kv["k"], "v": eng.kv["v"]}
    tokens = jnp.asarray([9, 11], jnp.int32)
    tables = jnp.asarray(tables_np, jnp.int32)
    offsets = jnp.asarray(offsets_np, jnp.int32)
    lx, _ = model.decode_step_paged(params, tokens, pool, tables, offsets)
    lp, _ = model_p.decode_step_paged(params, tokens, pool, tables,
                                      offsets)
    np.testing.assert_allclose(np.asarray(lx), np.asarray(lp),
                               atol=0.15, rtol=0.05)   # bf16 K/V path


def test_paged_engine_soak_no_leaks(tiny_model):
    """Sustained mixed load (prefix sharing, varied lengths, slot churn)
    must reclaim every block and leave zero refcounts."""
    model, params = tiny_model
    rng = np.random.default_rng(0)
    eng = ContinuousBatchingEngine(model, params, max_slots=6, max_seq=64,
                                   prefill_buckets=(8, 16, 32),
                                   block_size=8, num_blocks=40)
    prefixes = [list(rng.integers(1, 500, 8)) for _ in range(3)]
    reqs = []
    for i in range(120):
        if i % 2 == 0:
            p = list(prefixes[i % 3]) + list(rng.integers(1, 500, 3))
        else:
            p = list(rng.integers(1, 500, int(rng.integers(2, 20))))
        reqs.append(eng.submit(
            p, SamplingParams(max_tokens=int(rng.integers(1, 8)))))
    while eng.has_work():
        eng.step()
    assert all(r.done.is_set() for r in reqs)
    assert all(r.finish_reason is not None for r in reqs)
    assert eng.pool.num_free == 40            # fully reclaimed
    assert all(c == 0 for c in eng.pool.refcount)
    assert eng.stats["prefix_prefills"] > 0   # sharing actually happened
