"""tools/raylint unit tier: each pass catches its bug class on small
synthetic modules, and stays quiet on the known-tricky non-bugs
(re-entrant same-instance acquisition, try/finally manual
acquire/release, conditional locking, intra-class ``notify`` calls).

These are tier-1: pure AST analysis, no cluster, no sockets.
"""

import os
import textwrap

import pytest

from tools.raylint import REGISTRY, run_passes
from tools.raylint.core import Baseline, Context, Finding, Module


def _module(source: str, name: str = "mod.py") -> Module:
    return Module(name, name, textwrap.dedent(source))


def _ctx(*sources, docs: str = "", tests: dict = None,
         obs: str = "") -> Context:
    modules = [_module(src, f"m{i}.py") for i, src in enumerate(sources)]
    return Context(modules=modules, repo_root=os.getcwd(),
                   docs_fault_tolerance=docs,
                   docs_observability=obs,
                   tests_sources=tests if tests is not None else {})


def _run(pass_id: str, ctx: Context):
    return REGISTRY[pass_id](ctx)


# ---------------------------------------------------------------------------
# guarded-by
# ---------------------------------------------------------------------------

GUARDED_BAD = """
    import threading

    class Ledger:
        def __init__(self):
            self._items = {}   #: guarded by self._lock
            self._lock = threading.Lock()

        def ok(self):
            with self._lock:
                return len(self._items)

        def racy(self):
            return self._items.get("k")      # <-- unguarded access
"""


def test_guarded_by_true_positive():
    findings = _run("guarded-by", _ctx(GUARDED_BAD))
    assert len(findings) == 1
    f = findings[0]
    assert f.key == "Ledger.racy:_items"
    assert "without holding" in f.message


def test_guarded_by_ok_inside_with():
    ok = GUARDED_BAD.replace(
        'return self._items.get("k")      # <-- unguarded access',
        'with self._lock:\n                return self._items.get("k")')
    assert _run("guarded-by", _ctx(ok)) == []


def test_guarded_by_manual_acquire_release_no_false_positive():
    src = """
        import threading

        class Ledger:
            def __init__(self):
                self._items = {}   #: guarded by self._lock
                self._lock = threading.Lock()

            def manual(self):
                self._lock.acquire()
                try:
                    return len(self._items)   # held: acquired above
                finally:
                    self._lock.release()

            def after_release(self):
                self._lock.acquire()
                self._lock.release()
                return len(self._items)       # NOT held anymore
    """
    findings = _run("guarded-by", _ctx(src))
    assert [f.key for f in findings] == ["Ledger.after_release:_items"]


def test_guarded_by_conditional_locking_scoped_to_arm():
    src = """
        import threading

        class Ledger:
            def __init__(self):
                self._items = {}   #: guarded by self._lock
                self._lock = threading.Lock()

            def cond(self, fast):
                if fast:
                    with self._lock:
                        return len(self._items)    # guarded arm: fine
                return len(self._items)            # unguarded arm: flagged
    """
    findings = _run("guarded-by", _ctx(src))
    assert [f.key for f in findings] == ["Ledger.cond:_items"]


def test_guarded_by_init_exempt_and_suppression():
    src = """
        import threading

        class Ledger:
            def __init__(self):
                self._items = {}   #: guarded by self._lock
                self._lock = threading.Lock()
                self._items["warm"] = 1            # __init__: exempt

            def deliberate(self):
                return bool(self._items)  # raylint: disable=guarded-by
    """
    assert _run("guarded-by", _ctx(src)) == []


def test_guarded_by_nested_thread_closure_is_unheld():
    src = """
        import threading

        class Ledger:
            def __init__(self):
                self._items = {}   #: guarded by self._lock
                self._lock = threading.Lock()

            def spawn(self):
                with self._lock:
                    def worker():
                        return len(self._items)    # runs LATER, unlocked
                    threading.Thread(target=worker).start()
    """
    findings = _run("guarded-by", _ctx(src))
    assert [f.key for f in findings] == ["Ledger.spawn:_items"]


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

def test_lock_order_cycle_detected():
    src = """
        import threading

        class A:
            def __init__(self):
                self._alock = threading.Lock()
                self._block = threading.Lock()

            def fwd(self):
                with self._alock:
                    with self._block:
                        pass

            def rev(self):
                with self._block:
                    with self._alock:
                        pass
    """
    findings = _run("lock-order", _ctx(src))
    keys = sorted(f.key for f in findings)
    assert keys == ["m0.A._alock->m0.A._block",
                    "m0.A._block->m0.A._alock"]


def test_lock_order_reentrant_same_instance_skipped():
    src = """
        import threading

        class A:
            def __init__(self):
                self._alock = threading.RLock()

            def reenter(self):
                with self._alock:
                    with self._alock:      # same class: no edge
                        pass
    """
    assert _run("lock-order", _ctx(src)) == []


def test_lock_order_consistent_nesting_is_clean():
    src = """
        import threading

        class A:
            def __init__(self):
                self._alock = threading.Lock()
                self._block = threading.Lock()

            def one(self):
                with self._alock:
                    with self._block:
                        pass

            def two(self):
                with self._alock:
                    with self._block:
                        pass
    """
    assert _run("lock-order", _ctx(src)) == []


def test_lock_order_manual_acquire_builds_edges():
    src = """
        import threading

        class A:
            def __init__(self):
                self._alock = threading.Lock()
                self._block = threading.Lock()

            def fwd(self):
                self._alock.acquire()
                try:
                    self._block.acquire()
                    self._block.release()
                finally:
                    self._alock.release()

            def rev(self):
                with self._block:
                    with self._alock:
                        pass
    """
    findings = _run("lock-order", _ctx(src))
    assert len(findings) == 2       # both directions of the cycle


def test_lock_order_tracked_lock_names_match_runtime():
    src = """
        from ray_tpu._private.lock_sanitizer import tracked_lock

        class A:
            def __init__(self):
                self._alock = tracked_lock("alpha")
                self._block = tracked_lock("beta")

            def fwd(self):
                with self._alock:
                    with self._block:
                        pass

            def rev(self):
                with self._block:
                    with self._alock:
                        pass
    """
    keys = sorted(f.key for f in _run("lock-order", _ctx(src)))
    assert keys == ["alpha->beta", "beta->alpha"]


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------

def test_blocking_flags_sleep_socket_rpc_subprocess():
    src = """
        import subprocess
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def bad_sleep(self):
                with self._lock:
                    time.sleep(1)

            def bad_wire(self, sock):
                with self._lock:
                    sock.sendall(b"x")

            def bad_rpc(self, client):
                with self._lock:
                    client.call("ping")

            def bad_proc(self):
                with self._lock:
                    subprocess.run(["true"])
    """
    kinds = sorted(f.key for f in _run("blocking-under-lock", _ctx(src)))
    assert kinds == [
        "S.bad_proc:subprocess.run()",
        "S.bad_rpc:RPC call() on client",
        "S.bad_sleep:time.sleep()",
        "S.bad_wire:socket sendall() on sock",
    ]


def test_blocking_cv_wait_and_wire_lock_exempt():
    src = """
        import threading

        class S:
            def __init__(self):
                self._cv = threading.Condition()
                self._wlock = threading.Lock()

            def fine_wait(self):
                with self._cv:
                    self._cv.wait(0.1)      # releases the lock: exempt

            def fine_wire(self, sock):
                with self._wlock:           # wire-write lock: exempt
                    sock.sendall(b"frame")

            def fine_outside(self, sock):
                with self._cv:
                    pass
                sock.sendall(b"after")      # not under the lock
    """
    assert _run("blocking-under-lock", _ctx(src)) == []


def test_blocking_manual_release_ends_the_region():
    src = """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def ok(self):
                self._lock.acquire()
                self._lock.release()
                time.sleep(0.01)            # after release: fine
    """
    assert _run("blocking-under-lock", _ctx(src)) == []


# ---------------------------------------------------------------------------
# rpc-drift
# ---------------------------------------------------------------------------

RPC_OK = """
    from ray_tpu._private import rpc
    from ray_tpu._private.rpc import declare

    declare("echo", "v")

    class Svc:
        def handle_echo(self, conn, rid, msg):
            return {"v": msg["v"]}

    class Caller:
        def ask(self, client):
            return client.call("echo", v=1)
"""


def test_rpc_drift_clean_roundtrip():
    assert _run("rpc-drift", _ctx(RPC_OK)) == []


def test_rpc_drift_renamed_handler_detected():
    src = RPC_OK.replace("def handle_echo", "def handle_echo2")
    keys = {f.key for f in _run("rpc-drift", _ctx(src))}
    assert keys == {"unhandled:echo", "dead-declare:echo"}


def test_rpc_drift_undeclared_call_site():
    src = RPC_OK.replace('client.call("echo", v=1)',
                         'client.call("ecoh", v=1)')
    keys = {f.key for f in _run("rpc-drift", _ctx(src))}
    assert "undeclared:ecoh" in keys


def test_rpc_drift_intra_class_notify_is_not_rpc():
    src = """
        from ray_tpu._private import rpc

        class Watcher:
            def notify(self, reason):
                self.reason = reason

            def on_sigterm(self):
                self.notify("sigterm")      # method call, not a frame
    """
    assert _run("rpc-drift", _ctx(src)) == []


def test_rpc_drift_module_without_rpc_import_skipped():
    src = """
        class OtherProtocol:
            def go(self, client):
                client.call("own_wire_thing")
    """
    assert _run("rpc-drift", _ctx(src)) == []


def test_rpc_drift_push_needs_consumer():
    src = """
        from ray_tpu._private import rpc

        class Svc:
            def done(self, conn):
                conn.push("task_done", ok=True)
    """
    keys = {f.key for f in _run("rpc-drift", _ctx(src))}
    assert keys == {"unconsumed:task_done"}
    consumer = """
        from ray_tpu._private import rpc

        class Handle:
            def _on_push(self, method, msg):
                if method == "task_done":
                    pass
    """
    assert _run("rpc-drift", _ctx(src, consumer)) == []


# ---------------------------------------------------------------------------
# failpoint-registry
# ---------------------------------------------------------------------------

FP_SRC = """
    from ray_tpu._private import failpoints as _fp

    def seam_a():
        if _fp.ENABLED:
            _fp.fire("mod.seam_a")

    def seam_b():
        if _fp.ENABLED:
            _fp.fire("mod.seam_b")
"""


def test_failpoint_registry_all_green():
    ctx = _ctx(FP_SRC, docs="| `mod.seam_a` | x |\n| `mod.seam_b` | y |",
               tests={"test_x.py": 'fire("mod.seam_a"); "mod.seam_b"'})
    assert _run("failpoint-registry", ctx) == []


def test_failpoint_registry_undocumented_and_untested():
    ctx = _ctx(FP_SRC, docs="| `mod.seam_a` | x |",
               tests={"test_x.py": '"mod.seam_a"'})
    keys = sorted(f.key for f in _run("failpoint-registry", ctx))
    assert keys == ["undocumented:mod.seam_b", "untested:mod.seam_b"]


def test_failpoint_registry_duplicate_seam():
    dup = FP_SRC.replace('_fp.fire("mod.seam_b")',
                         '_fp.fire("mod.seam_a")')
    ctx = _ctx(dup, docs="| `mod.seam_a` | x |",
               tests={"test_x.py": '"mod.seam_a"'})
    keys = [f.key for f in _run("failpoint-registry", ctx)]
    # the site count rides in the key: baselining a 2-site seam must
    # not grandfather a future third site
    assert keys == ["dup:mod.seam_a:2"]


# ---------------------------------------------------------------------------
# baseline + runner plumbing
# ---------------------------------------------------------------------------

def test_baseline_covers_and_reports_stale(tmp_path):
    bl_path = tmp_path / "baseline.txt"
    bl_path.write_text(
        "guarded-by|m0.py|Ledger.racy:_items  # known racy read\n"
        "guarded-by|m0.py|Gone.method:_x  # stale entry\n"
        "# comment line\n\n")
    baseline = Baseline.load(str(bl_path))
    findings = _run("guarded-by", _ctx(GUARDED_BAD))
    assert len(findings) == 1 and baseline.covers(findings[0])
    assert baseline.unused(findings) == ["guarded-by|m0.py|Gone.method:_x"]


def test_finding_keys_are_line_stable():
    shifted = "\n\n\n" + textwrap.dedent(GUARDED_BAD)
    a = _run("guarded-by", _ctx(GUARDED_BAD))
    b = REGISTRY["guarded-by"](Context(
        modules=[Module("m0.py", "m0.py", shifted)],
        repo_root=os.getcwd(), docs_fault_tolerance="",
        tests_sources={}))
    assert [f.baseline_key() for f in a] == [f.baseline_key() for f in b]
    assert a[0].line != b[0].line


def test_run_passes_sorts_and_filters():
    ctx = _ctx(GUARDED_BAD, docs="", tests={})
    all_findings = run_passes(ctx)
    only = run_passes(ctx, only={"guarded-by"})
    assert [f.key for f in only] == ["Ledger.racy:_items"]
    assert {f.pass_id for f in all_findings} >= {"guarded-by"}


def test_cli_on_real_package_is_clean():
    """The CI contract itself: the shipped package + shipped baseline
    lint clean (exit 0), and the baseline stays within budget."""
    from tools.raylint.__main__ import main
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rc = main([os.path.join(repo, "ray_tpu")])
    assert rc == 0
    with open(os.path.join(repo, "tools", "raylint",
                           "baseline.txt")) as f:
        entries = [ln for ln in f
                   if ln.strip() and not ln.startswith("#")]
    assert len(entries) <= 15


def test_cli_exit_codes(tmp_path):
    from tools.raylint.__main__ import main
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(GUARDED_BAD))
    empty_baseline = tmp_path / "empty.txt"
    empty_baseline.write_text("")
    # exit 1: a NEW finding against an empty baseline
    assert main([str(bad), "--baseline", str(empty_baseline)]) == 1
    # exit 0: the same finding, baseline-covered (CI contract: the exit
    # code distinguishes new findings from grandfathered ones)
    covering = tmp_path / "covering.txt"
    rel = os.path.relpath(str(bad), repo)   # the CLI keys on repo-rel
    covering.write_text(
        f"guarded-by|{rel}|Ledger.racy:_items  # known racy read\n")
    assert main([str(bad), "--baseline", str(covering)]) == 0


def test_guarded_by_release_in_finally_ends_region():
    """Regression: a release() inside try/finally lives in a NESTED
    block — it must still end the held region for code after the try,
    both for guarded-by (access after = flagged) and for
    blocking-under-lock (no false positive on a sleep after)."""
    src = """
        import threading
        import time

        class Ledger:
            def __init__(self):
                self._items = {}   #: guarded by self._lock
                self._lock = threading.Lock()

            def acquire_try_finally(self):
                self._lock.acquire()
                try:
                    n = len(self._items)       # held
                finally:
                    self._lock.release()
                return self._items.get("k")    # NOT held: flagged

            def sleep_after_finally(self):
                self._lock.acquire()
                try:
                    pass
                finally:
                    self._lock.release()
                time.sleep(0.01)               # NOT held: no finding
    """
    guarded = _run("guarded-by", _ctx(src))
    assert [f.key for f in guarded] == \
        ["Ledger.acquire_try_finally:_items"]
    assert _run("blocking-under-lock", _ctx(src)) == []


def test_guarded_by_manual_region_spans_nested_blocks():
    """A manual acquire held ACROSS nested control flow (the region is
    function-flow, not lexical) keeps covering accesses inside it."""
    src = """
        import threading

        class Ledger:
            def __init__(self):
                self._items = {}   #: guarded by self._lock
                self._lock = threading.Lock()

            def held_through_if(self, flag):
                self._lock.acquire()
                try:
                    if flag:
                        return len(self._items)    # held
                    return bool(self._items)       # held
                finally:
                    self._lock.release()
    """
    assert _run("guarded-by", _ctx(src)) == []


def test_async_with_holds_the_lock():
    """Regression: `async with self._lock:` must count as a held
    region for all three lock passes (the async control-plane core is
    exactly where this tool needs to see)."""
    src = """
        import asyncio
        import time

        class A:
            def __init__(self):
                self._items = {}   #: guarded by self._lock
                self._lock = asyncio.Lock()
                self._block = asyncio.Lock()

            async def ok(self):
                async with self._lock:
                    return self._items.get("k")

            async def blocking(self):
                async with self._lock:
                    time.sleep(1)          # held: flagged

            async def fwd(self):
                async with self._lock:
                    async with self._block:
                        pass

            async def rev(self):
                async with self._block:
                    async with self._lock:
                        pass
    """
    assert _run("guarded-by", _ctx(src)) == []
    blocked = _run("blocking-under-lock", _ctx(src))
    assert [f.key for f in blocked] == ["A.blocking:time.sleep()"]
    assert len(_run("lock-order", _ctx(src))) == 2


def test_lambda_bodies_are_not_held_regions():
    """Regression: a lambda defers execution — a blocking call inside
    one under a lock is NOT blocking-under-lock (it runs later,
    unlocked). The symmetric guarded-by blind spot (deferred unguarded
    access inside a lambda) is a documented non-goal; thread targets
    written as nested defs ARE caught (see
    test_guarded_by_nested_thread_closure_is_unheld)."""
    src = """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def deferred(self):
                with self._lock:
                    cb = lambda: time.sleep(1)     # runs later
                return cb
    """
    assert _run("blocking-under-lock", _ctx(src)) == []


def test_blocking_rpc_notify_not_exempt():
    """Regression: `.notify()` is cv-protocol ONLY on a lock-like
    receiver; rpc.Client.notify sends a wire frame and must be flagged
    under a held lock."""
    src = """
        import threading

        class S:
            def __init__(self):
                self._cv = threading.Condition()
                self._lock = threading.Lock()

            def fine(self):
                with self._cv:
                    self._cv.notify()              # lock protocol

            def convoy(self, client):
                with self._lock:
                    client.notify("report", x=1)   # wire frame: flagged
    """
    keys = [f.key for f in _run("blocking-under-lock", _ctx(src))]
    assert keys == ["S.convoy:RPC notify() on client"]


def test_baseline_single_space_comment_parses(tmp_path):
    """Regression: a justification typed with ONE space before the #
    must still parse (key side never contains whitespace)."""
    bl = tmp_path / "b.txt"
    bl.write_text("guarded-by|m0.py|Ledger.racy:_items # known racy\n")
    baseline = Baseline.load(str(bl))
    findings = _run("guarded-by", _ctx(GUARDED_BAD))
    assert len(findings) == 1 and baseline.covers(findings[0])


# ---------------------------------------------------------------------------
# async-discipline
# ---------------------------------------------------------------------------

def test_async_blocking_call_true_positives():
    src = """
        import time

        class Plane:
            async def tick(self):
                time.sleep(0.1)

            async def submit(self, handle, payload):
                return handle.remote(payload)
    """
    keys = sorted(f.key for f in _run("async-discipline", _ctx(src)))
    assert keys == ["blocking:submit:task submission .remote() on handle",
                    "blocking:tick:time.sleep()"]


def test_async_unawaited_coroutine_true_positive():
    src = """
        async def pump():
            return 1

        async def main():
            pump()
    """
    keys = [f.key for f in _run("async-discipline", _ctx(src))]
    assert keys == ["unawaited:main:pump"]


def test_async_await_under_sync_lock_true_positive():
    src = """
        import threading

        class Plane:
            def __init__(self):
                self._lock = threading.Lock()

            async def drain(self, fut):
                with self._lock:
                    await fut
    """
    keys = [f.key for f in _run("async-discipline", _ctx(src))]
    assert keys == ["await-under-lock:drain:self._lock"]


def test_async_fire_and_forget_true_positive():
    src = """
        async def pump():
            pass

        def boot(loop):
            loop.create_task(pump())
    """
    keys = [f.key for f in _run("async-discipline", _ctx(src))]
    assert keys == ["fire-and-forget:create_task(pump)"]


def test_async_awaited_rpc_and_executor_not_flagged():
    """FP guard: the await-wrapped flavors of otherwise-blocking
    shapes are the DESIGN (run_in_executor offload, awaited call)."""
    src = """
        import time

        class Plane:
            async def ok(self, loop, sock):
                await loop.run_in_executor(None, time.sleep, 1)
                data = await self._client.call("ping")
                return data
    """
    assert _run("async-discipline", _ctx(src)) == []


def test_async_task_stored_then_gathered_not_flagged():
    """FP guard (the retained-handle idiom): a task assigned to a
    name or appended to a set is NOT fire-and-forget."""
    src = """
        import asyncio

        async def pump():
            pass

        async def main(loop):
            inflight = set()
            t = loop.create_task(pump())
            inflight.add(loop.create_task(pump()))
            await asyncio.gather(t, *inflight)
    """
    assert _run("async-discipline", _ctx(src)) == []


def test_async_nested_sync_def_and_async_with_not_flagged():
    """FP guard: a nested sync def runs in an executor (its blocking
    body is the point), and `async with lock:` is the async flavor."""
    src = """
        import time
        import asyncio

        class Plane:
            def __init__(self):
                self._alock = asyncio.Lock()

            async def stream(self, loop):
                def next_chunk():
                    time.sleep(0.1)
                    return self._client.call("pull")
                async with self._alock:
                    return await loop.run_in_executor(None, next_chunk)
    """
    assert _run("async-discipline", _ctx(src)) == []


def test_async_mixed_sync_async_name_not_flagged():
    """FP guard: a name with BOTH sync and async definitions anywhere
    in the package is skipped by unawaited-coroutine (conservative)."""
    src_a = """
        async def flush():
            pass
    """
    src_b = """
        def flush():
            pass

        async def main():
            flush()
    """
    assert _run("async-discipline", _ctx(src_a, src_b)) == []


# ---------------------------------------------------------------------------
# loop-affinity
# ---------------------------------------------------------------------------

def test_loop_affinity_direct_call_from_thread_context():
    src = """
        class Node:
            def _wake(self):   #: loop-only
                pass

            def from_thread(self):
                self._wake()
    """
    findings = _run("loop-affinity", _ctx(src))
    assert [f.key for f in findings] == ["Node.from_thread->_wake"]
    assert "call_soon_threadsafe" in findings[0].message


def test_loop_affinity_annotation_on_line_above():
    src = """
        class Node:
            #: loop-only
            def _wake(self):
                pass

            def from_thread(self):
                self._wake()
    """
    keys = [f.key for f in _run("loop-affinity", _ctx(src))]
    assert keys == ["Node.from_thread->_wake"]


def test_loop_affinity_reference_handoff_not_flagged():
    """FP guard: passing the loop-only function BY REFERENCE to
    call_soon_threadsafe is the prescribed fix, not a violation."""
    src = """
        class Node:
            def _wake(self):   #: loop-only
                pass

            def from_thread(self, loop):
                loop.call_soon_threadsafe(self._wake)
    """
    assert _run("loop-affinity", _ctx(src)) == []


def test_loop_affinity_loop_spawned_callback_not_flagged():
    """FP guard: a nested def whose NAME is handed to a loop-scheduling
    API runs on the loop — its direct call of a loop-only def is fine."""
    src = """
        class Node:
            def _wake(self):   #: loop-only
                pass

            def start(self, loop):
                def cb():
                    self._wake()
                loop.call_soon_threadsafe(cb)
    """
    assert _run("loop-affinity", _ctx(src)) == []


def test_loop_affinity_async_and_loop_only_callers_not_flagged():
    """FP guard: async defs and loop-only defs are already on the
    loop; their direct calls are the normal case."""
    src = """
        class Node:
            def _wake(self):   #: loop-only
                pass

            async def handler(self):
                self._wake()

            def _pump(self):   #: loop-only
                self._wake()
    """
    assert _run("loop-affinity", _ctx(src)) == []


def test_loop_affinity_unrelated_attribute_same_name_not_flagged():
    """Regression (http_proxy.stop): `self._pool.shutdown()` must not
    match a nested loop-only `def shutdown()` — call shape (bare name
    vs attribute) disambiguates."""
    src = """
        class Proxy:
            def stop(self, loop):
                def shutdown():   #: loop-only
                    loop.stop()
                loop.call_soon_threadsafe(shutdown)
                self._pool.shutdown(wait=False)
    """
    assert _run("loop-affinity", _ctx(src)) == []


# ---------------------------------------------------------------------------
# capability-drift
# ---------------------------------------------------------------------------

CAP_REGISTRY = """
    CAPABILITY_FLAGS = {
        "batch": {"kind": "hello", "guard": "_batch_ok"},
        "via_pump": {"kind": "frame", "requires": ["_batch_ok"]},
    }
"""


def _cap_ctx(*sources) -> Context:
    modules = [_module(CAP_REGISTRY, "capabilities.py")]
    modules += [_module(src, f"m{i}.py")
                for i, src in enumerate(sources)]
    return Context(modules=modules, repo_root=os.getcwd(),
                   docs_fault_tolerance="", docs_observability="",
                   tests_sources={})


CAP_PEER = """
    class Daemon:
        def handle_hello_driver(self, conn, rid, msg):
            return {"batch": True}

        def handle_submit(self, conn, rid, msg):
            if msg.get("via_pump"):
                pass
"""


def test_capability_unguarded_send_true_positive():
    src = """
        class Driver:
            def check(self):
                return self._batch_ok

            def execute(self, spec):
                self._client.call("submit", via_pump=True)
    """
    keys = [f.key for f in _run("capability-drift",
                                _cap_ctx(CAP_PEER, src))]
    assert keys == ["unguarded-send:via_pump:Driver.execute"]


def test_capability_dead_and_unadvertised_flags():
    """A registry entry nothing advertises, gates, or sends drifts in
    both directions at once."""
    keys = sorted(f.key for f in _run("capability-drift",
                                      _cap_ctx("x = 1\n")))
    assert keys == ["dead-flag:batch", "dead-flag:via_pump",
                    "no-advertiser:batch", "no-advertiser:via_pump"]


def test_capability_guard_in_direct_caller_not_flagged():
    """FP guard: the guard checked by the CALLER dominates the send."""
    src = """
        class Driver:
            def execute(self, spec):
                if self._batch_ok:
                    self._send(spec)

            def _send(self, spec):
                self._client.call("submit", via_pump=True)
    """
    assert _run("capability-drift", _cap_ctx(CAP_PEER, src)) == []


def test_capability_check_hoisted_into_helper_not_flagged():
    """FP guard (the _submit_coalescer idiom): the caller consults a
    HELPER that reads the guard, then calls the send function."""
    src = """
        class Driver:
            def execute(self, spec):
                if self._coalescer():
                    self._send(spec)

            def _coalescer(self):
                return self._batch_ok

            def _send(self, spec):
                self._client.call("submit", via_pump=True)
    """
    assert _run("capability-drift", _cap_ctx(CAP_PEER, src)) == []


def test_capability_pass_inert_without_registry():
    assert _run("capability-drift", _ctx(CAP_PEER)) == []


# ---------------------------------------------------------------------------
# frame-schema
# ---------------------------------------------------------------------------

def test_frame_schema_dead_key_true_positive():
    src = """
        from ray_tpu._private import rpc

        declare("submit", "fn")

        class Server:
            def handle_submit(self, conn, rid, msg):
                return msg["fn"]

        class Client:
            def send(self):
                self._client.call("submit", fn=1, extra=2)
    """
    findings = _run("frame-schema", _ctx(src))
    assert [f.key for f in findings] == ["dead-key:submit:extra"]
    assert "no consumer" in findings[0].message


def test_frame_schema_missing_key_true_positive():
    src = """
        from ray_tpu._private import rpc

        declare("submit", "fn")

        class Server:
            def handle_submit(self, conn, rid, msg):
                return msg["fn"], msg["deadline"]

        class Client:
            def send(self):
                self._client.call("submit", fn=1)
    """
    keys = [f.key for f in _run("frame-schema", _ctx(src))]
    assert keys == ["missing-key:submit:deadline"]


def test_frame_schema_push_demux_dead_key():
    src = """
        from ray_tpu._private import rpc

        class Driver:
            def _on_push(self, method, msg):
                if method == "report":
                    return msg["x"]

        class Worker:
            def emit(self, conn):
                conn.push("report", x=1, y=2)
    """
    keys = [f.key for f in _run("frame-schema", _ctx(src))]
    assert keys == ["dead-key:report:y"]


def test_frame_schema_resolved_splat_not_flagged():
    """FP guard: a **kw splat built from a same-function dict literal
    plus kw["k"] = ... stores resolves to its keys."""
    src = """
        from ray_tpu._private import rpc

        declare("submit", "fn")

        class Server:
            def handle_submit(self, conn, rid, msg):
                return msg["fn"], msg["deadline"]

        class Client:
            def send(self):
                kw = {"fn": 1}
                kw["deadline"] = 5
                self._client.call("submit", **kw)
    """
    assert _run("frame-schema", _ctx(src)) == []


def test_frame_schema_forwarding_handler_suppresses_dead_key():
    """FP guard: a handler that hands msg onward whole may read any
    key downstream — dead-key must stay quiet."""
    src = """
        from ray_tpu._private import rpc

        declare("submit", "fn")

        class Server:
            def handle_submit(self, conn, rid, msg):
                self._exec(msg)

        class Client:
            def send(self):
                self._client.call("submit", fn=1, extra=2)
    """
    assert _run("frame-schema", _ctx(src)) == []


def test_frame_schema_opaque_splat_blocks_missing_key():
    """FP guard: an unresolvable **kw means absence cannot be proven —
    no missing-key. Transport-level kwargs (timeout) are not payload."""
    src = """
        from ray_tpu._private import rpc

        declare("submit", "fn")

        class Server:
            def handle_submit(self, conn, rid, msg):
                return msg["fn"]

        class Client:
            def send(self, kw):
                self._client.call("submit", timeout=5.0, **kw)
    """
    assert _run("frame-schema", _ctx(src)) == []


def test_frame_schema_local_store_not_a_wire_key():
    """FP guard: msg["k"] = ... inside the handler materializes the
    key locally — later msg["k"] loads are not wire requirements."""
    src = """
        from ray_tpu._private import rpc

        declare("submit", "fn")

        class Server:
            def handle_submit(self, conn, rid, msg):
                msg["_t0"] = 1
                return msg["fn"], msg["_t0"]

        class Client:
            def send(self):
                self._client.call("submit", fn=1)
    """
    assert _run("frame-schema", _ctx(src)) == []


# ---------------------------------------------------------------------------
# metric-registry
# ---------------------------------------------------------------------------

def test_metric_registry_undocumented_true_positive():
    src = """
        from ray_tpu._private.metrics import Counter

        _hits = Counter("ray_tpu_cache_hits_total", "cache hits")
    """
    findings = _run("metric-registry",
                    _ctx(src, obs="## Metrics\\n- `ray_tpu_other`\\n"))
    assert [f.key for f in findings] == [
        "undocumented:ray_tpu_cache_hits_total"]


def test_metric_registry_documented_not_flagged():
    src = """
        from ray_tpu._private.metrics import Counter

        _hits = Counter("ray_tpu_cache_hits_total", "cache hits")
    """
    obs = "- `ray_tpu_cache_hits_total` cache hits by tier\\n"
    assert _run("metric-registry", _ctx(src, obs=obs)) == []


def test_metric_registry_ignores_foreign_names_and_plain_dicts():
    """FP guard: non-prefix metric names belong to other systems, and
    a dict with a name key but no kind key is not a wire entry."""
    src = """
        from ray_tpu._private.metrics import Counter

        _x = Counter("process_cpu_seconds_total", "not ours")
        spec = {"name": "ray_tpu_not_a_metric"}
    """
    assert _run("metric-registry", _ctx(src, obs="")) == []


def test_metric_registry_wire_entry_dict_collected():
    src = """
        def delta():
            return {"name": "ray_tpu_queue_depth", "kind": "gauge",
                    "value": 3}
    """
    keys = [f.key for f in _run("metric-registry", _ctx(src, obs=""))]
    assert keys == ["undocumented:ray_tpu_queue_depth"]
    ok = "| `ray_tpu_queue_depth` | gauge |"
    assert _run("metric-registry", _ctx(src, obs=ok)) == []


# ---------------------------------------------------------------------------
# CLI surface + perf budget
# ---------------------------------------------------------------------------

def test_list_passes_catalogue_complete():
    expected = {"guarded-by", "blocking-under-lock", "lock-order",
                "rpc-drift", "failpoint-registry", "async-discipline",
                "loop-affinity", "capability-drift", "frame-schema",
                "metric-registry"}
    assert expected <= set(REGISTRY)


def _parse_calibration(repo: str) -> float:
    """CPU seconds to raw-ast.parse every file raylint analyzes, in the
    interpreter's CURRENT state. Late in a full pytest sweep the whole
    interpreter runs several times slower for allocation-heavy work
    than in a fresh process (hundreds of live threads, a large live
    heap steering the allocator and GC) — measured 8-10x on the raylint
    CLI with identical inputs and 128GB free RAM, so neither wall clock
    nor absolute process CPU is a stable budget. Parsing the same
    corpus is the dominant, linear part of raylint's cost and slows
    down by the same interpreter-state factor, so budgets expressed as
    a MULTIPLE of this calibration stay meaningful in both states:
    an accidental O(n^2) pass blows the ratio either way."""
    import ast as _ast
    import gc
    import time as _time
    files = []
    for base, _dirs, names in os.walk(os.path.join(repo, "ray_tpu")):
        files.extend(os.path.join(base, n) for n in names
                     if n.endswith(".py"))
    srcs = []
    for path in sorted(files):
        with open(path, "r", encoding="utf-8") as fh:
            srcs.append((path, fh.read()))
    gc.collect()
    t0 = _time.process_time()
    for path, src in srcs:
        _ast.parse(src, filename=path)
    return _time.process_time() - t0


def test_full_run_meets_time_budget():
    """CI stage-0.5 contract: all passes over the whole package stay
    within a small multiple of the cost of just PARSING the package
    (what keeps raylint in the default CI path). The self-calibrating
    ratio is what makes this load- and state-tolerant — see
    _parse_calibration; in a fresh process the full run costs ~3x the
    parse-only baseline, so 10x headroom catches an accidental
    O(n^2) pass, not a slow interpreter state. An absolute floor keeps
    the gate sane if calibration itself measures near zero."""
    import gc
    import time as _time
    from tools.raylint.__main__ import main
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    budget = max(10.0 * _parse_calibration(repo), 6.0)
    gc.collect()
    t0 = _time.process_time()
    rc = main([os.path.join(repo, "ray_tpu")])
    elapsed = _time.process_time() - t0
    assert rc == 0
    assert elapsed < budget, (
        f"full raylint run took {elapsed:.2f}s CPU "
        f"(self-calibrated budget {budget:.2f}s)")


def test_changed_run_meets_time_budget():
    """Pre-commit contract: --changed costs no more than the full run
    (whole-program passes still execute; the per-module-only passes
    scan just the changed files, and reporting filters to the git
    diff). Same self-calibrated budget as the full-run gate above —
    the old absolute budgets (wall first, then fixed CPU) both flaked
    under full-suite interpreter state."""
    import gc
    import time as _time
    from tools.raylint.__main__ import main
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    budget = max(10.0 * _parse_calibration(repo), 6.0)
    gc.collect()
    t0 = _time.process_time()
    rc = main([os.path.join(repo, "ray_tpu"), "--changed"])
    elapsed = _time.process_time() - t0
    assert rc == 0
    assert elapsed < budget, (
        f"--changed raylint run took {elapsed:.2f}s CPU "
        f"(self-calibrated budget {budget:.2f}s)")
