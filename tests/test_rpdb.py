"""Distributed debugger: socket pdb sessions + post-mortem attach.

Reference capability: `python/ray/util/rpdb.py:282` + the `ray debug`
CLI.
"""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import rpdb


def _attach_when_advertised(commands, out, timeout=30.0):
    """Poll the session registry, then drive the session."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        sessions = rpdb.active_sessions()
        if sessions:
            s = sessions[0]
            out.append(rpdb.connect(s["host"], s["port"],
                                    commands=commands))
            return
        time.sleep(0.1)
    out.append("")


def test_set_trace_breakpoint(ray_start_regular):
    """A task blocks at set_trace(); an attached client inspects locals
    and continues it."""
    @ray_tpu.remote
    def task():
        secret = 41 + 1
        rpdb.set_trace()
        return secret

    ref = task.remote()
    out = []
    t = threading.Thread(target=_attach_when_advertised,
                         args=(["p secret", "c"], out))
    t.start()
    result = ray_tpu.get(ref, timeout=60)
    t.join(timeout=30)
    assert result == 42
    assert "42" in out[0]
    assert rpdb.active_sessions() == []        # deregistered on detach


def test_post_mortem_attach(ray_start_regular):
    """A crashing task holds its frame for post-mortem inspection, then
    the error still propagates to the caller. The flag rides the task's
    runtime_env so it reaches pooled workers forked before the test."""
    @ray_tpu.remote(runtime_env={"env_vars": {"RAY_TPU_POST_MORTEM": "1"}})
    def boom():
        clue = "the-clue"
        raise ValueError(f"exploded with {clue}")

    ref = boom.remote()
    out = []
    t = threading.Thread(target=_attach_when_advertised,
                         args=(["p clue", "q"], out))
    t.start()
    with pytest.raises(Exception, match="exploded"):
        ray_tpu.get(ref, timeout=60)
    t.join(timeout=30)
    assert "the-clue" in out[0]


def test_disabled_and_timeout_paths(monkeypatch):
    monkeypatch.setenv("RAY_TPU_DEBUGGER_DISABLED", "1")
    rpdb.set_trace()                           # no-op, returns
    monkeypatch.delenv("RAY_TPU_DEBUGGER_DISABLED")
    monkeypatch.setenv("RAY_TPU_DEBUGGER_TIMEOUT_S", "0.2")
    t0 = time.time()
    rpdb.set_trace()                           # nobody attaches
    assert time.time() - t0 < 5


def test_cli_lists_sessions(ray_start_regular, capsys):
    from ray_tpu.scripts.cli import cmd_debug

    class A:
        session = ""
        cluster = ""
        num_nodes = 1
    rc = cmd_debug(A())
    assert rc == 0
    assert "no active debugger sessions" in capsys.readouterr().out
