"""Tune: search spaces, Tuner, ASHA early stopping, PBT."""

import numpy as np

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (AsyncHyperBandScheduler, PopulationBasedTraining,
                          TuneConfig, Tuner)
from ray_tpu.tune.search_space import generate_variants


def test_generate_variants_grid_and_random():
    space = {"lr": tune.grid_search([0.1, 0.01]),
             "wd": tune.uniform(0, 1), "fixed": 7}
    variants = generate_variants(space, num_samples=3, seed=0)
    assert len(variants) == 6
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    assert all(0 <= v["wd"] <= 1 and v["fixed"] == 7 for v in variants)


def test_tuner_finds_minimum(ray_start_regular):
    def objective(config):
        x = config["x"]
        return {"loss": (x - 3.0) ** 2}

    grid = Tuner(objective,
                 param_space={"x": tune.grid_search(
                     [0.0, 1.0, 2.0, 3.0, 4.0])},
                 tune_config=TuneConfig(metric="loss", mode="min"))
    results = grid.fit()
    best = results.get_best_result()
    assert best.config["x"] == 3.0
    assert best.metrics["loss"] == 0.0
    assert len(results) == 5


def test_tuner_intermediate_reports(ray_start_regular):
    def trainable(config):
        for i in range(1, 4):
            tune.report({"training_iteration": i, "score": i * config["m"]})

    results = Tuner(trainable,
                    param_space={"m": tune.grid_search([1, 2])},
                    tune_config=TuneConfig(metric="score",
                                           mode="max")).fit()
    best = results.get_best_result()
    assert best.config["m"] == 2
    assert best.metrics["score"] == 6


def test_asha_early_stops_bad_trials(ray_start_regular):
    def trainable(config):
        import time
        for i in range(1, 17):
            tune.report({"training_iteration": i,
                         "loss": config["q"] + 1.0 / i})
            time.sleep(0.02)  # give the controller a chance to intervene

    sched = AsyncHyperBandScheduler(metric="loss", mode="min", max_t=16,
                                    grace_period=2, reduction_factor=2)
    results = Tuner(
        trainable,
        param_space={"q": tune.grid_search([0.0, 5.0, 10.0, 20.0])},
        tune_config=TuneConfig(metric="loss", mode="min",
                               scheduler=sched,
                               max_concurrent_trials=1)).fit()
    best = results.get_best_result()
    assert best.config["q"] == 0.0
    trials = {r.config["q"]: r.trial for r in results}
    # the worst trial must have been stopped before finishing 16 iters
    assert len(trials[20.0].results) < 16
    assert results.errors == []


def test_pbt_exploits(ray_start_regular):
    def trainable(config):
        for i in range(1, 9):
            tune.report({"training_iteration": i,
                         "score": config["lr"] * i})

    sched = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_mutations={"lr": [0.1, 1.0, 10.0]})
    results = Tuner(
        trainable, param_space={"lr": tune.grid_search([0.1, 10.0])},
        tune_config=TuneConfig(metric="score", mode="max",
                               scheduler=sched)).fit()
    assert len(results) == 2
    assert results.errors == []


def test_trial_error_captured(ray_start_regular):
    def bad(config):
        raise RuntimeError("boom")

    results = Tuner(bad, param_space={},
                    tune_config=TuneConfig(num_samples=1)).fit()
    assert len(results.errors) == 1


def test_tuner_over_jax_trainer(ray_start_regular, tmp_path):
    """Train-on-Tune: HPO over a JaxTrainer's train_loop_config."""
    from ray_tpu import train, tune as rtune
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def train_fn(config):
        # pretend loss depends on lr quadratically
        loss = (config["lr"] - 0.3) ** 2
        train.report({"loss": loss})

    trainer = JaxTrainer(
        train_fn, train_loop_config={"lr": 0.1},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="hpo", storage_path=str(tmp_path)))
    results = Tuner(
        trainer,
        param_space={"train_loop_config": {
            "lr": rtune.grid_search([0.1, 0.3, 0.9])}},
        tune_config=TuneConfig(metric="loss", mode="min")).fit()
    best = results.get_best_result()
    assert best.config["lr"] == 0.3
    assert best.metrics["loss"] == 0.0


def test_tpe_searcher_converges(ray_start_regular):
    """TPE beats pure exploration on a smooth 1-d objective: after the
    startup phase, suggestions concentrate near the optimum."""
    from ray_tpu import tune as rtune
    from ray_tpu.tune.search import TPESearcher

    def objective(config):
        rtune.report({"loss": (config["x"] - 0.7) ** 2})

    results = Tuner(
        objective,
        param_space={"x": rtune.uniform(0.0, 1.0)},
        tune_config=TuneConfig(metric="loss", mode="min", num_samples=24,
                               max_concurrent_trials=2,
                               search_alg=TPESearcher(n_startup=6,
                                                      seed=0))).fit()
    best = results.get_best_result()
    assert abs(best.config["x"] - 0.7) < 0.15
    # later trials should cluster nearer the optimum than startup ones
    xs = [t.config["x"] for t in results.trials]
    startup_err = sum(abs(x - 0.7) for x in xs[:6]) / 6
    later_err = sum(abs(x - 0.7) for x in xs[-6:]) / 6
    assert later_err <= startup_err + 0.05


def test_halton_searcher_covers_space(ray_start_regular):
    from ray_tpu import tune as rtune
    from ray_tpu.tune.search import HaltonSearcher

    def objective(config):
        rtune.report({"loss": config["x"]})

    results = Tuner(
        objective, param_space={"x": rtune.uniform(0.0, 1.0)},
        tune_config=TuneConfig(num_samples=8, max_concurrent_trials=1,
                               search_alg=HaltonSearcher())).fit()
    # low-discrepancy: all four quartiles visited within 8 points
    quartiles = {min(int(t.config["x"] * 4), 3) for t in results.trials}
    assert {0, 1, 2, 3} <= quartiles


def _restorable_trainable(config):
    import os
    import time

    time.sleep(config.get("sleep", 0.4))
    return {"score": config["x"], "run_pid": os.getpid()}


def test_tuner_experiment_restore(tmp_path):
    """Kill an experiment mid-flight; Tuner.restore resumes it with the
    trial count conserved and finished results preserved (reference:
    Tuner.restore + experiment state snapshots)."""
    import os
    import signal
    import subprocess
    import sys
    import time

    import cloudpickle

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    exp_base = str(tmp_path)
    state_file = os.path.join(exp_base, "exp", "tuner_state.pkl")
    script = f"""
import sys
sys.path.insert(0, {repo!r})
from ray_tpu._private.platform import force_cpu_platform
force_cpu_platform(8)
import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.train.config import RunConfig
from tests.test_tune import _restorable_trainable
ray_tpu.init(num_nodes=1, resources={{"CPU": 4}})
Tuner(_restorable_trainable,
      param_space={{"x": tune.grid_search([1, 2, 3, 4])}},
      tune_config=TuneConfig(metric="score", mode="max",
                             max_concurrent_trials=1),
      run_config=RunConfig(name="exp", storage_path={exp_base!r})).fit()
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    proc = subprocess.Popen([sys.executable, "-c", script], env=env,
                            start_new_session=True)

    # wait until >=1 trial TERMINATED but the experiment is not done
    def load_state():
        try:
            with open(state_file, "rb") as f:
                return cloudpickle.loads(f.read())
        except Exception:
            return None

    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        state = load_state()
        if state is not None:
            done = [t for t in state["trials"]
                    if t["status"] == "TERMINATED"]
            if 1 <= len(done) < 4:
                break
        if proc.poll() is not None:
            break
        time.sleep(0.05)
    assert proc.poll() is None, "experiment finished before the kill"
    os.killpg(proc.pid, signal.SIGKILL)
    proc.wait(timeout=10)

    state = load_state()
    finished_before = {t["id"] for t in state["trials"]
                       if t["status"] == "TERMINATED"}
    assert finished_before, "no finished trial before the kill"

    # restore in THIS process and finish the experiment
    from ray_tpu.tune import Tuner
    exp_path = os.path.join(exp_base, "exp")
    assert Tuner.can_restore(exp_path)
    ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                 ignore_reinit_error=True)
    try:
        results = Tuner.restore(exp_path, _restorable_trainable).fit()
        # trial count conserved: 4 grid points, no duplicates
        assert len(results) == 4
        scores = sorted(r.metrics["score"] for r in results)
        assert scores == [1, 2, 3, 4]
        assert not results.errors
        # trials finished before the kill kept their ORIGINAL results
        # (run in the killed subprocess, not re-run here)
        by_id = {r.trial.id: r for r in results}
        for tid in finished_before:
            assert by_id[tid].metrics["run_pid"] != os.getpid()
    finally:
        ray_tpu.shutdown()


def test_bohb_multi_fidelity_model(ray_start_regular):
    """BOHB = ASHA culling + a TPE whose Parzen model fits per-rung
    (multi-fidelity) observations; intermediate reports alone must be
    enough to steer suggestions toward the optimum (reference:
    tune/search/bohb + schedulers/hb_bohb pairing)."""
    from ray_tpu import tune as rtune
    from ray_tpu.tune.schedulers import AsyncHyperBandScheduler
    from ray_tpu.tune.search import BOHBSearcher

    def objective(config):
        # multi-fidelity surrogate: the loss ordering is visible from
        # iteration 1, so rung-level observations carry real signal
        for it in range(1, 5):
            loss = (config["x"] - 0.6) ** 2 + 0.5 / it
            rtune.report({"loss": loss, "training_iteration": it})

    searcher = BOHBSearcher(n_startup=6, seed=0)
    results = Tuner(
        objective,
        param_space={"x": rtune.uniform(0.0, 1.0)},
        tune_config=TuneConfig(
            metric="loss", mode="min", num_samples=20,
            max_concurrent_trials=2,
            search_alg=searcher,
            scheduler=AsyncHyperBandScheduler(
                metric="loss", mode="min", max_t=4, grace_period=1,
                reduction_factor=2))).fit()
    best = results.get_best_result()
    assert abs(best.config["x"] - 0.6) < 0.3
    # the mechanical multi-fidelity contract (the part that must hold
    # on EVERY run): rung-level observations reached per-budget pools
    # at more than one fidelity, and the model phase consumed them.
    # (Per-run optimizer-quality deltas like "later trials cluster
    # nearer" are order-sensitive with 2 concurrent trials — under the
    # wire topology completion order varies, so that claim is asserted
    # for TPE in test_tpe_searcher_converges, not here.)
    assert len(searcher._by_budget) >= 2, searcher._by_budget.keys()
    assert any(len(pool) >= searcher.n_startup
               for pool in searcher._by_budget.values())
