"""Ops layer: norms, rope, attention, ring attention (8 virtual devices)."""

import dataclasses
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import (apply_rope, attention, layer_norm, ring_attention,
                         rms_norm, rope_frequencies)
from ray_tpu.ops.attention import reference_attention
from ray_tpu.ops.ring_attention import ring_attention_sharded


def test_rms_norm_matches_numpy():
    x = np.random.default_rng(0).normal(size=(2, 8, 16)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(16,)).astype(np.float32)
    out = rms_norm(jnp.asarray(x), jnp.asarray(w), eps=1e-5)
    expect = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-5) * w
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_layer_norm_zero_mean_unit_var():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 32)), jnp.float32)
    out = layer_norm(x, jnp.ones((32,)), jnp.zeros((32,)))
    np.testing.assert_allclose(np.mean(np.asarray(out), -1), 0, atol=1e-5)
    np.testing.assert_allclose(np.var(np.asarray(out), -1), 1, atol=1e-3)


def test_rope_preserves_norm_and_relative_property():
    angles = rope_frequencies(8, 64, theta=10_000.0)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, 16, 2, 8)),
                    jnp.float32)
    out = apply_rope(x, angles)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)
    # explicit positions == default positions
    pos = jnp.arange(16)[None, :]
    out2 = apply_rope(x, angles, pos)
    np.testing.assert_allclose(out, out2, rtol=1e-6)


def _naive_attention(q, k, v, causal=True):
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    rep = H // Hkv
    k = np.repeat(k, rep, axis=2)
    v = np.repeat(v, rep, axis=2)
    out = np.zeros_like(q)
    for b in range(B):
        for h in range(H):
            s = q[b, :, h] @ k[b, :, h].T / np.sqrt(D)
            if causal:
                mask = np.tril(np.ones((S, S), bool))
                s = np.where(mask, s, -1e30)
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            out[b, :, h] = p @ v[b, :, h]
    return out


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("kv_heads", [4, 2])
def test_reference_attention_vs_naive(causal, kv_heads):
    rng = np.random.default_rng(0)
    q = rng.normal(size=(2, 16, 4, 8)).astype(np.float32)
    k = rng.normal(size=(2, 16, kv_heads, 8)).astype(np.float32)
    v = rng.normal(size=(2, 16, kv_heads, 8)).astype(np.float32)
    out = attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                    causal=causal, use_flash=False)
    np.testing.assert_allclose(out, _naive_attention(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


def test_attention_grad_finite():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 8, 2, 4)), jnp.float32)

    def f(q):
        return attention(q, q, q, causal=True, use_flash=False).sum()

    g = jax.grad(f)(q)
    assert np.all(np.isfinite(np.asarray(g)))


def test_ring_attention_single_axis_matches_reference():
    """shard_map ring over sp=4 must equal full attention."""
    from jax.sharding import Mesh, PartitionSpec as P
    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    rng = np.random.default_rng(0)
    B, S, H, D = 2, 32, 4, 8
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, 2, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, 2, D)), jnp.float32)

    out = ring_attention_sharded(q, k, v, mesh, batch_axes=(), head_axis=None)
    expect = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_noncausal():
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:2]).reshape(2)
    mesh = Mesh(devs, ("sp",))
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 16, 2, 4)), jnp.float32)
    out = ring_attention_sharded(q, q, q, mesh, causal=False,
                                 batch_axes=(), head_axis=None)
    expect = reference_attention(q, q, q, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients_match_reference():
    """Regression: stop_gradient on the online-softmax max broke grads."""
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(1, 32, 2, 4)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 32, 2, 4)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 32, 2, 4)), jnp.float32)
    cot = jnp.asarray(rng.normal(size=(1, 32, 2, 4)), jnp.float32)

    def f_ring(q, k, v):
        return (ring_attention_sharded(q, k, v, mesh, batch_axes=(),
                                       head_axis=None) * cot).sum()

    def f_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) * cot).sum()

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_blockwise_attention_matches_reference_fwd_and_grad():
    from ray_tpu.ops.attention import blockwise_attention
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(2, 40, 4, 8)), jnp.float32)  # 40 % 16 != 0
    k = jnp.asarray(rng.normal(size=(2, 40, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 40, 2, 8)), jnp.float32)
    for causal in (True, False):
        out = blockwise_attention(q, k, v, causal=causal, block_k=16)
        expect = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def f_blk(q):
        return blockwise_attention(q, k, v, block_k=16).sum()

    def f_ref(q):
        return reference_attention(q, k, v).sum()

    np.testing.assert_allclose(np.asarray(jax.grad(f_blk)(q)),
                               np.asarray(jax.grad(f_ref)(q)),
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Ulysses all-to-all context parallelism (SURVEY §5.7 requires both schemes)
# ---------------------------------------------------------------------------

def test_ulysses_attention_matches_reference():
    """shard_map Ulysses over sp=4 must equal full attention."""
    from jax.sharding import Mesh
    from ray_tpu.ops.ulysses import ulysses_attention_sharded
    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    rng = np.random.default_rng(3)
    B, S, H, D = 2, 32, 4, 8
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, 2, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, 2, D)), jnp.float32)

    out = ulysses_attention_sharded(q, k, v, mesh, batch_axes=(),
                                    head_axis=None)
    expect = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_matches_ring():
    from jax.sharding import Mesh
    from ray_tpu.ops.ulysses import ulysses_attention_sharded
    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.normal(size=(1, 64, 4, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 64, 4, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 64, 4, 8)), jnp.float32)
    u = ulysses_attention_sharded(q, k, v, mesh, batch_axes=(),
                                  head_axis=None)
    r = ring_attention_sharded(q, k, v, mesh, batch_axes=(),
                               head_axis=None)
    np.testing.assert_allclose(np.asarray(u), np.asarray(r),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_gradients_match_reference():
    from jax.sharding import Mesh
    from ray_tpu.ops.ulysses import ulysses_attention_sharded
    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(1, 32, 4, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 32, 4, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 32, 4, 8)), jnp.float32)
    cot = jnp.asarray(rng.normal(size=(1, 32, 4, 8)), jnp.float32)

    def f_uly(q, k, v):
        return (ulysses_attention_sharded(q, k, v, mesh, batch_axes=(),
                                          head_axis=None) * cot).sum()

    def f_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) * cot).sum()

    gu = jax.grad(f_uly, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_ulysses_rejects_indivisible_heads():
    from jax.sharding import Mesh
    from ray_tpu.ops.ulysses import ulysses_attention_sharded
    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    q = jnp.zeros((1, 32, 3, 8), jnp.float32)  # 3 heads, sp=4
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(q, q, q, mesh, batch_axes=(),
                                  head_axis=None)


# ---------------------------------------------------------------------------
# Explicit MoE expert all-to-all dispatch (VERDICT r1 #7)
# ---------------------------------------------------------------------------

def test_moe_alltoall_matches_einsum_dispatch():
    """The explicit all-to-all scheme must agree with the dense einsum
    scheme when capacity is ample (no drops on either side)."""
    from ray_tpu.models.moe import MoEConfig, MoEModel

    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    mesh = build_mesh(MeshSpec.auto(8, sp=2, ep=2))
    cfg_a = MoEConfig.debug_moe(num_experts=4)
    cfg_a = dataclasses.replace(cfg_a, capacity_factor=4.0,
                                dtype=jnp.float32)
    cfg_b = dataclasses.replace(cfg_a, moe_dispatch="alltoall")

    model_a = MoEModel(cfg_a, mesh=mesh)
    model_b = MoEModel(cfg_b, mesh=mesh)
    params = model_a.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg_a.vocab_size, (2, 32)))

    with mesh:
        la, aux_a = model_a.apply_with_aux(params, tokens)
        lb, aux_b = model_b.apply_with_aux(params, tokens)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                               rtol=5e-3, atol=5e-4)
    np.testing.assert_allclose(float(aux_a), float(aux_b),
                               rtol=5e-2, atol=1e-3)


# ---------------------------------------------------------------------------
# Ragged decode attention (paged-attention role for KV-cache serving)
# ---------------------------------------------------------------------------

def test_ragged_decode_attention_matches_reference():
    from ray_tpu.ops.decode_attention import (
        ragged_decode_attention_pallas, ragged_decode_attention_reference)

    rng = np.random.default_rng(7)
    B, S, H, Hkv, D = 4, 256, 8, 2, 32
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
    lengths = jnp.asarray([1, 100, 200, 256], jnp.int32)
    ref = ragged_decode_attention_reference(q, k, v, lengths)
    out = ragged_decode_attention_pallas(q, k, v, lengths, block_k=64,
                                         interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_ragged_decode_attention_unpadded_lengths():
    from ray_tpu.ops.decode_attention import (
        ragged_decode_attention_pallas, ragged_decode_attention_reference)

    rng = np.random.default_rng(8)
    B, S, H, D = 2, 96, 4, 16   # S not a multiple of block_k
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    lengths = jnp.asarray([37, 96], jnp.int32)
    ref = ragged_decode_attention_reference(q, k, v, lengths)
    out = ragged_decode_attention_pallas(q, k, v, lengths, block_k=64,
                                         interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


# ---------------------------------------------------------------------------
# Pallas flash attention (interpret mode: real kernel logic on CPU)
# ---------------------------------------------------------------------------

def test_flash_attention_matches_reference():
    from ray_tpu.ops.attention import flash_attention

    rng = np.random.default_rng(0)
    B, S, H, Hkv, D = 2, 256, 4, 2, 32
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
    for causal in (True, False):
        out = flash_attention(q, k, v, causal, 128, 128, True)
        expect = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)


def test_flash_attention_ragged_seq():
    """Sequence not a multiple of the k block: tail-block masking."""
    from ray_tpu.ops.attention import flash_attention

    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 192, 2, 16)), jnp.float32)
    out = flash_attention(q, q, q, True, 128, 128, True)
    expect = reference_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_gradients_match_reference():
    from ray_tpu.ops.attention import flash_attention

    rng = np.random.default_rng(2)
    B, S, H, D = 1, 128, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, True, 64, 64, True) ** 2).sum()

    def f_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4)


def test_llama_flash_impl_matches_ring_default():
    """attention_impl='flash' (sp==1) must produce the same loss as the
    reference/blockwise path the other impls use on one device."""
    from ray_tpu.models.llama import LlamaConfig, LlamaModel

    cfg_kw = dict(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                  n_kv_heads=2, ffn_dim=128, max_seq_len=128, remat=False)
    m_ring = LlamaModel(LlamaConfig(attention_impl="ring", **cfg_kw))
    m_flash = LlamaModel(LlamaConfig(attention_impl="flash", **cfg_kw))
    params = m_ring.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 128, (2, 128)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    l_ring = m_ring.loss(params, tokens, targets)
    l_flash = m_flash.loss(params, tokens, targets)
    np.testing.assert_allclose(float(l_ring), float(l_flash),
                               rtol=1e-3, atol=1e-4)


def test_llama_flash_rejects_sp_mesh():
    from jax.sharding import Mesh
    from ray_tpu.models.llama import LlamaConfig, LlamaModel

    devs = np.array(jax.devices()[:2]).reshape(2)
    mesh = Mesh(devs, ("sp",))
    cfg = LlamaConfig.debug()
    cfg = dataclasses.replace(cfg, attention_impl="flash")
    with pytest.raises(ValueError, match="flash"):
        LlamaModel(cfg, mesh=mesh)
