"""RL layer: env dynamics, PPO/DQN learning on CartPole."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import Algorithm, AlgorithmConfig, CartPoleEnv


def test_cartpole_dynamics():
    env = CartPoleEnv(seed=0)
    obs, _ = env.reset()
    assert obs.shape == (4,)
    total = 0
    for _ in range(600):
        obs, rew, term, trunc, _ = env.step(1)
        total += rew
        if term or trunc:
            break
    assert term  # always pushing right must topple the pole
    assert 5 < total < 200


def test_ppo_improves_on_cartpole(ray_start_regular):
    algo = (AlgorithmConfig(algo="PPO")
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=256)
            .training(lr=1e-3, epochs=4, minibatch_size=128)
            .build())
    try:
        first = None
        last = None
        for i in range(12):
            m = algo.train()
            if first is None and np.isfinite(m["episode_return_mean"]):
                first = m["episode_return_mean"]
            if np.isfinite(m["episode_return_mean"]):
                last = m["episode_return_mean"]
        assert first is not None and last is not None
        assert last > first  # learning happened
        assert last > 40     # clearly better than random (~20)
    finally:
        algo.stop()


def test_dqn_runs_and_decays_epsilon(ray_start_regular):
    algo = (AlgorithmConfig(algo="DQN")
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=128)
            .training(lr=1e-3, updates_per_iter=16)
            .build())
    try:
        eps = []
        for _ in range(4):
            m = algo.train()
            eps.append(m["epsilon"])
        assert eps[-1] < eps[0]
        assert np.isfinite(m["td_loss"])
    finally:
        algo.stop()


def test_custom_env_registry(ray_start_regular):
    from ray_tpu.rl import register_env

    class ConstEnv:
        n_actions = 2
        obs_dim = 2

        def __init__(self, seed=0):
            self.t = 0

        def reset(self, seed=None):
            self.t = 0
            return np.zeros(2, np.float32), {}

        def step(self, a):
            self.t += 1
            return (np.zeros(2, np.float32), float(a), False,
                    self.t >= 10, {})

    register_env("Const-v0", lambda seed=0: ConstEnv(seed))
    algo = (AlgorithmConfig(algo="PPO").environment("Const-v0")
            .env_runners(1, rollout_fragment_length=64).build())
    try:
        m = algo.train()
        assert m["num_episodes"] > 0
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# Connectors + offline RL (reference: rllib/connectors/, rllib/offline/)
# ---------------------------------------------------------------------------

def test_connector_pipeline_shapes_and_state():
    from ray_tpu.rl.connectors import (ConnectorPipeline, FrameStack,
                                       MeanStdObservationNormalizer,
                                       ObservationClipper)

    pipe = ConnectorPipeline([MeanStdObservationNormalizer(),
                              FrameStack(3), ObservationClipper()])
    out1 = pipe(np.ones(4, np.float32))
    assert out1.shape == (12,)              # 3x frame stack
    assert pipe.output_multiplier == 3
    pipe(np.ones(4) * 2)
    pipe.reset()                            # episode boundary clears stack
    out2 = pipe(np.zeros(4, np.float32))
    assert out2.shape == (12,)
    assert np.all(out2[:8] == 0)            # stack restarted with zeros


def test_algorithm_with_connectors(ray_start_regular):
    from ray_tpu.rl import AlgorithmConfig
    from ray_tpu.rl.connectors import (ConnectorPipeline, FrameStack,
                                       MeanStdObservationNormalizer)

    algo = (AlgorithmConfig()
            .environment("CartPole-v1")
            .env_runners(1, rollout_fragment_length=64)
            .connectors(env_to_module=lambda: ConnectorPipeline(
                [MeanStdObservationNormalizer(), FrameStack(2)]))
            ).build()
    metrics = algo.train()
    loss_keys = [k for k in metrics if "loss" in k]
    assert loss_keys and all(np.isfinite(metrics[k]) for k in loss_keys)
    algo.stop()


def test_offline_bc_and_cql(ray_start_regular, tmp_path):
    """Collect experiences online, write to a dataset, train BC and
    offline (CQL) DQN from the dataset with no env in the loop."""
    from ray_tpu.rl.env import CartPoleEnv, EnvRunner
    from ray_tpu.rl.offline import (BCLearner, OfflineDQNLearner,
                                    read_experiences, train_offline,
                                    write_experiences)
    from ray_tpu.rl.ppo import ActorCriticPolicy

    runner = EnvRunner(CartPoleEnv,
                       lambda: ActorCriticPolicy(4, 2, seed=0), seed=0)
    batches = [runner.sample(128) for _ in range(2)]
    path = str(tmp_path / "exp.parquet")
    rows = write_experiences(batches, path)
    assert rows == 256

    ds = read_experiences(path)
    assert ds.count() == 256

    bc = BCLearner(4, 2, seed=0, lr=3e-3)
    first = next(iter(ds.iter_batches(batch_size=256)))
    before = bc.evaluate_accuracy(first)
    metrics = train_offline(ds, bc, batch_size=64, epochs=10)
    assert np.isfinite(metrics["bc_loss"])
    after = bc.evaluate_accuracy(first)
    # training fits the logged behavior better than the init did
    assert after >= before and after > 0.45

    cql = OfflineDQNLearner(4, 2, seed=0, cql_alpha=1.0)
    metrics = train_offline(ds, cql, batch_size=64, epochs=2)
    assert np.isfinite(metrics["loss"])
    assert metrics["cql_penalty"] >= 0.0
    assert cql.act(np.zeros(4, np.float32)) in (0, 1)
