"""RL layer: env dynamics, PPO/DQN learning on CartPole."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import Algorithm, AlgorithmConfig, CartPoleEnv


def test_cartpole_dynamics():
    env = CartPoleEnv(seed=0)
    obs, _ = env.reset()
    assert obs.shape == (4,)
    total = 0
    for _ in range(600):
        obs, rew, term, trunc, _ = env.step(1)
        total += rew
        if term or trunc:
            break
    assert term  # always pushing right must topple the pole
    assert 5 < total < 200


def test_ppo_improves_on_cartpole(ray_start_regular):
    algo = (AlgorithmConfig(algo="PPO")
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=256)
            .training(lr=1e-3, epochs=4, minibatch_size=128)
            .build())
    try:
        first = None
        last = None
        for i in range(12):
            m = algo.train()
            if first is None and np.isfinite(m["episode_return_mean"]):
                first = m["episode_return_mean"]
            if np.isfinite(m["episode_return_mean"]):
                last = m["episode_return_mean"]
        assert first is not None and last is not None
        assert last > first  # learning happened
        assert last > 40     # clearly better than random (~20)
    finally:
        algo.stop()


def test_dqn_runs_and_decays_epsilon(ray_start_regular):
    algo = (AlgorithmConfig(algo="DQN")
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=128)
            .training(lr=1e-3, updates_per_iter=16)
            .build())
    try:
        eps = []
        for _ in range(4):
            m = algo.train()
            eps.append(m["epsilon"])
        assert eps[-1] < eps[0]
        assert np.isfinite(m["td_loss"])
    finally:
        algo.stop()


def test_custom_env_registry(ray_start_regular):
    from ray_tpu.rl import register_env

    class ConstEnv:
        n_actions = 2
        obs_dim = 2

        def __init__(self, seed=0):
            self.t = 0

        def reset(self, seed=None):
            self.t = 0
            return np.zeros(2, np.float32), {}

        def step(self, a):
            self.t += 1
            return (np.zeros(2, np.float32), float(a), False,
                    self.t >= 10, {})

    register_env("Const-v0", lambda seed=0: ConstEnv(seed))
    algo = (AlgorithmConfig(algo="PPO").environment("Const-v0")
            .env_runners(1, rollout_fragment_length=64).build())
    try:
        m = algo.train()
        assert m["num_episodes"] > 0
    finally:
        algo.stop()
