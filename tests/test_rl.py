"""RL layer: env dynamics, PPO/DQN learning on CartPole."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import Algorithm, AlgorithmConfig, CartPoleEnv


def test_cartpole_dynamics():
    env = CartPoleEnv(seed=0)
    obs, _ = env.reset()
    assert obs.shape == (4,)
    total = 0
    for _ in range(600):
        obs, rew, term, trunc, _ = env.step(1)
        total += rew
        if term or trunc:
            break
    assert term  # always pushing right must topple the pole
    assert 5 < total < 200


def test_ppo_improves_on_cartpole(ray_start_regular):
    algo = (AlgorithmConfig(algo="PPO")
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=256)
            .training(lr=1e-3, epochs=4, minibatch_size=128)
            .build())
    try:
        first = None
        last = None
        for i in range(12):
            m = algo.train()
            if first is None and np.isfinite(m["episode_return_mean"]):
                first = m["episode_return_mean"]
            if np.isfinite(m["episode_return_mean"]):
                last = m["episode_return_mean"]
        assert first is not None and last is not None
        assert last > first  # learning happened
        assert last > 40     # clearly better than random (~20)
    finally:
        algo.stop()


def test_dqn_runs_and_decays_epsilon(ray_start_regular):
    algo = (AlgorithmConfig(algo="DQN")
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=128)
            .training(lr=1e-3, updates_per_iter=16)
            .build())
    try:
        eps = []
        for _ in range(4):
            m = algo.train()
            eps.append(m["epsilon"])
        assert eps[-1] < eps[0]
        assert np.isfinite(m["td_loss"])
    finally:
        algo.stop()


def test_custom_env_registry(ray_start_regular):
    from ray_tpu.rl import register_env

    class ConstEnv:
        n_actions = 2
        obs_dim = 2

        def __init__(self, seed=0):
            self.t = 0

        def reset(self, seed=None):
            self.t = 0
            return np.zeros(2, np.float32), {}

        def step(self, a):
            self.t += 1
            return (np.zeros(2, np.float32), float(a), False,
                    self.t >= 10, {})

    register_env("Const-v0", lambda seed=0: ConstEnv(seed))
    algo = (AlgorithmConfig(algo="PPO").environment("Const-v0")
            .env_runners(1, rollout_fragment_length=64).build())
    try:
        m = algo.train()
        assert m["num_episodes"] > 0
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# Connectors + offline RL (reference: rllib/connectors/, rllib/offline/)
# ---------------------------------------------------------------------------

def test_connector_pipeline_shapes_and_state():
    from ray_tpu.rl.connectors import (ConnectorPipeline, FrameStack,
                                       MeanStdObservationNormalizer,
                                       ObservationClipper)

    pipe = ConnectorPipeline([MeanStdObservationNormalizer(),
                              FrameStack(3), ObservationClipper()])
    out1 = pipe(np.ones(4, np.float32))
    assert out1.shape == (12,)              # 3x frame stack
    assert pipe.output_multiplier == 3
    pipe(np.ones(4) * 2)
    pipe.reset()                            # episode boundary clears stack
    out2 = pipe(np.zeros(4, np.float32))
    assert out2.shape == (12,)
    assert np.all(out2[:8] == 0)            # stack restarted with zeros


def test_algorithm_with_connectors(ray_start_regular):
    from ray_tpu.rl import AlgorithmConfig
    from ray_tpu.rl.connectors import (ConnectorPipeline, FrameStack,
                                       MeanStdObservationNormalizer)

    algo = (AlgorithmConfig()
            .environment("CartPole-v1")
            .env_runners(1, rollout_fragment_length=64)
            .connectors(env_to_module=lambda: ConnectorPipeline(
                [MeanStdObservationNormalizer(), FrameStack(2)]))
            ).build()
    metrics = algo.train()
    loss_keys = [k for k in metrics if "loss" in k]
    assert loss_keys and all(np.isfinite(metrics[k]) for k in loss_keys)
    algo.stop()


def test_offline_bc_and_cql(ray_start_regular, tmp_path):
    """Collect experiences online, write to a dataset, train BC and
    offline (CQL) DQN from the dataset with no env in the loop."""
    from ray_tpu.rl.env import CartPoleEnv, EnvRunner
    from ray_tpu.rl.offline import (BCLearner, OfflineDQNLearner,
                                    read_experiences, train_offline,
                                    write_experiences)
    from ray_tpu.rl.ppo import ActorCriticPolicy

    runner = EnvRunner(CartPoleEnv,
                       lambda: ActorCriticPolicy(4, 2, seed=0), seed=0)
    batches = [runner.sample(128) for _ in range(2)]
    path = str(tmp_path / "exp.parquet")
    rows = write_experiences(batches, path)
    assert rows == 256

    ds = read_experiences(path)
    assert ds.count() == 256

    bc = BCLearner(4, 2, seed=0, lr=3e-3)
    first = next(iter(ds.iter_batches(batch_size=256)))
    before = bc.evaluate_accuracy(first)
    metrics = train_offline(ds, bc, batch_size=64, epochs=10)
    assert np.isfinite(metrics["bc_loss"])
    after = bc.evaluate_accuracy(first)
    # training fits the logged behavior better than the init did
    assert after >= before and after > 0.45

    cql = OfflineDQNLearner(4, 2, seed=0, cql_alpha=1.0)
    metrics = train_offline(ds, cql, batch_size=64, epochs=2)
    assert np.isfinite(metrics["loss"])
    assert metrics["cql_penalty"] >= 0.0
    assert cql.act(np.zeros(4, np.float32)) in (0, 1)


def test_vtrace_reduces_to_gae_like_targets_on_policy():
    """With target==behavior (rho==c==1) V-trace vs equals the n-step
    lambda=1 return bootstrapped from the value trail (paper identity)."""
    import jax.numpy as jnp
    from ray_tpu.rl.impala import vtrace

    T = 6
    rng = np.random.default_rng(0)
    rewards = jnp.asarray(rng.normal(size=T), jnp.float32)
    values = jnp.asarray(rng.normal(size=T), jnp.float32)
    bootstrap = jnp.asarray(0.7, jnp.float32)
    discounts = jnp.full((T,), 0.9, jnp.float32)
    logp = jnp.zeros(T)
    vs, pg_adv = vtrace(logp, logp, rewards, discounts, values, bootstrap)
    # manual on-policy recursion
    expect = np.zeros(T, np.float32)
    acc = 0.0
    vals = np.asarray(values)
    rews = np.asarray(rewards)
    for t in range(T - 1, -1, -1):
        next_v = 0.7 if t == T - 1 else vals[t + 1]
        delta = rews[t] + 0.9 * next_v - vals[t]
        acc = delta + 0.9 * acc
        expect[t] = acc + vals[t]
    np.testing.assert_allclose(np.asarray(vs), expect, rtol=1e-5)
    assert np.all(np.isfinite(np.asarray(pg_adv)))


def test_impala_improves_on_cartpole(ray_start_regular):
    from ray_tpu.rl import AlgorithmConfig

    algo = (AlgorithmConfig(algo="IMPALA")
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=256)
            .training(lr=1e-3, ent_coef=0.01)
            .build())
    returns = []
    for _ in range(12):
        m = algo.train()
        if not np.isnan(m["episode_return_mean"]):
            returns.append(m["episode_return_mean"])
    algo.stop()
    assert returns, "no completed episodes"
    assert m["num_learner_updates"] >= 24   # async updates really ran
    # learning signal without single-sample flakiness: the trailing
    # window clearly beats a random policy (~20) — and does not sit
    # below the early window by more than noise
    trailing = float(np.mean(returns[-3:]))
    leading = float(np.mean(returns[:3]))
    assert trailing > 35, (leading, trailing, returns)
    assert trailing > leading * 0.7, (leading, trailing)


def test_sac_runs_and_tunes_temperature(ray_start_regular):
    from ray_tpu.rl import AlgorithmConfig

    algo = (AlgorithmConfig(algo="SAC")
            .environment("CartPole-v1")
            .env_runners(1, rollout_fragment_length=256)
            .training(batch_size=128, updates_per_call=8)
            .build())
    metrics = {}
    for _ in range(4):
        metrics = algo.train()
    algo.stop()
    assert metrics["num_learner_updates"] >= 16
    assert np.isfinite(metrics["q_loss"])
    assert metrics["alpha"] > 0       # temperature stayed positive
    assert 0 < metrics["entropy"] <= np.log(2) + 1e-5


def test_learner_group_spmd_matches_single_device(ray_start_regular):
    """Data-parallel learner group (learner_group.py:234 role): the dp-
    sharded SPMD update must produce the same parameters as the single-
    device learner given identical rollouts (XLA's psum IS the DDP
    all-reduce)."""
    import jax
    from ray_tpu.rl.env import CartPoleEnv, EnvRunner
    from ray_tpu.rl.learner_group import LearnerGroup
    from ray_tpu.rl.ppo import ActorCriticPolicy, PPOLearner

    runner = EnvRunner(CartPoleEnv,
                       lambda: ActorCriticPolicy(4, 2, seed=0), seed=0)
    rollouts = [runner.sample(256)]

    single = PPOLearner(4, 2, seed=0, epochs=1, minibatch_size=128)
    grouped = PPOLearner(4, 2, seed=0, epochs=1, minibatch_size=128)
    group = LearnerGroup(grouped, num_learners=8)
    assert group.num_learners == 8

    m1 = single.update(rollouts)
    m2 = group.update(rollouts)
    assert np.isfinite(m2["total_loss"])
    # identical data + identical rng -> identical trajectories
    for a, b in zip(jax.tree.leaves(single.get_weights()),
                    jax.tree.leaves(group.get_weights())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
    assert abs(m1["total_loss"] - m2["total_loss"]) < 1e-3


def test_learner_group_wraps_impala(ray_start_regular):
    """IMPALA's batch has a non-batch-major leaf (the bootstrap
    observation): the group must replicate it and still match the
    single-device update."""
    import jax
    from ray_tpu.rl.env import CartPoleEnv, EnvRunner
    from ray_tpu.rl.impala import ImpalaLearner
    from ray_tpu.rl.learner_group import LearnerGroup
    from ray_tpu.rl.ppo import ActorCriticPolicy

    runner = EnvRunner(CartPoleEnv,
                       lambda: ActorCriticPolicy(4, 2, seed=0), seed=0)
    rollouts = [runner.sample(256)]
    single = ImpalaLearner(4, 2, seed=0)
    grouped = ImpalaLearner(4, 2, seed=0)
    LearnerGroup(grouped, num_learners=8)
    m1 = single.update(rollouts)
    m2 = grouped.update(rollouts)
    assert np.isfinite(m2["loss"])
    for a, b in zip(jax.tree.leaves(single.get_weights()),
                    jax.tree.leaves(grouped.get_weights())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
    assert abs(m1["loss"] - m2["loss"]) < 1e-3


def test_learner_group_ragged_impala_matches_single_device(
        ray_start_regular):
    """A time-major fragment whose length is NOT a dp multiple must not
    be truncated (the bootstrap obs belongs to the step after the last
    row; dropping tail steps biases V-trace targets). The group falls
    back to the replicated path and matches single-device exactly."""
    import jax
    from ray_tpu.rl.env import CartPoleEnv, EnvRunner
    from ray_tpu.rl.impala import ImpalaLearner
    from ray_tpu.rl.learner_group import LearnerGroup
    from ray_tpu.rl.ppo import ActorCriticPolicy

    runner = EnvRunner(CartPoleEnv,
                       lambda: ActorCriticPolicy(4, 2, seed=0), seed=0)
    rollouts = [runner.sample(250)]       # 250 % 8 != 0
    single = ImpalaLearner(4, 2, seed=0)
    grouped = ImpalaLearner(4, 2, seed=0)
    LearnerGroup(grouped, num_learners=8)
    m1 = single.update(rollouts)
    m2 = grouped.update(rollouts)
    for a, b in zip(jax.tree.leaves(single.get_weights()),
                    jax.tree.leaves(grouped.get_weights())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
    assert abs(m1["loss"] - m2["loss"]) < 1e-3


def test_appo_runs_async_with_clipped_vtrace(ray_start_regular):
    """APPO = IMPALA architecture + PPO clip on V-trace advantages."""
    from ray_tpu.rl import AlgorithmConfig

    algo = (AlgorithmConfig(algo="APPO")
            .environment("CartPole-v1")
            .env_runners(2, rollout_fragment_length=128)
            .training(lr=1e-3, clip=0.2)
            .build())
    m = {}
    for _ in range(3):
        m = algo.train()
    algo.stop()
    assert m["num_learner_updates"] >= 6   # async per-fragment updates
    assert np.isfinite(m["pg_loss"]) and np.isfinite(m["vf_loss"])


def test_gridworld_and_mountaincar_dynamics():
    """Native classic-suite envs beyond CartPole (reference:
    rllib/tuned_examples env breadth): GridWorld reaches its goal under
    the optimal policy; MountainCar's flag is reachable by energy
    pumping and the shaped reward pays for velocity."""
    from ray_tpu.rl.env import GridWorldEnv, MountainCarEnv

    env = GridWorldEnv(seed=0, size=5)
    obs, _ = env.reset()
    assert obs.shape == (2,)
    total = 0.0
    for action in [0] * 4 + [2] * 4:       # right x4, down x4
        obs, r, term, trunc, _ = env.step(action)
        total += r
    assert term and total == 10.0 - 0.1 * 7

    env = MountainCarEnv(seed=0, shaped=True)
    obs, _ = env.reset(seed=0)
    done = False
    # bang-bang energy pumping: push in the direction of motion
    for _ in range(200):
        action = 2 if obs[1] >= 0 else 0
        obs, r, done, trunc, _ = env.step(action)
        if done:
            break
    assert done, "energy pumping must reach the flag"


def test_tuned_gridworld_contract(ray_start_regular):
    """A sparse-reward tuned contract converges: PPO on 5x5 GridWorld
    reaches a learned-policy return within the budget."""
    from ray_tpu.rl.tuned_examples import run
    m = run("ppo-gridworld", max_iterations=20)
    assert m["best_return"] > 0.0, m["best_return"]
