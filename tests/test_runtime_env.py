"""Runtime-env materialization (reference: _private/runtime_env/ plugins)."""

import ray_tpu

# ---------------------------------------------------------------------------
# working_dir / py_modules packaging (reference:
# _private/runtime_env/packaging.py — upload-to-GCS + per-node cache)
# ---------------------------------------------------------------------------

def test_working_dir_packaged_to_worker_process(ray_start_regular,
                                                tmp_path):
    """A module in working_dir imports inside a WORKER PROCESS that never
    saw the original path (content-addressed pkg:// materialization)."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "my_helper_mod.py").write_text(
        "MAGIC = 'packaged-and-shipped'\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(proj)})
    def use_helper():
        import os
        import sys
        import my_helper_mod
        # the import came from the extracted package cache, not the
        # original path
        loaded_from = my_helper_mod.__file__
        return my_helper_mod.MAGIC, loaded_from, os.getpid()

    magic, loaded_from, pid = ray_tpu.get(use_helper.remote())
    assert magic == "packaged-and-shipped"
    assert "pkg_cache" in loaded_from
    import os as _os
    assert pid != _os.getpid()   # really ran in a worker process


def test_py_modules_packaged(ray_start_regular, tmp_path):
    mod_dir = tmp_path / "libs"
    pkg = mod_dir / "shipped_pkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("VALUE = 41\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod_dir)]})
    def use_pkg():
        import shipped_pkg
        return shipped_pkg.VALUE + 1

    assert ray_tpu.get(use_pkg.remote()) == 42


def test_package_content_addressing(tmp_path):
    from ray_tpu._private.runtime_env_packaging import (
        fetch_pkg_blob, package_directory)

    d = tmp_path / "d"
    d.mkdir()
    (d / "f.txt").write_text("hello")
    uri1 = package_directory(str(d))
    uri2 = package_directory(str(d))
    assert uri1 == uri2             # unchanged dir -> same uri, no rezip
    assert fetch_pkg_blob(uri1)
    import time
    time.sleep(0.02)
    (d / "f.txt").write_text("changed")
    uri3 = package_directory(str(d))
    assert uri3 != uri1             # content change -> new uri
