"""One 'host' of a two-process multi-host training test.

Spawned twice by ``tests/test_multihost.py`` — each process owns 4 virtual
CPU devices and joins one 8-device global mesh through the jax
coordination service (the single-machine stand-in for a v5p pod the
environment allows; reference capability: multi-node fixture
``python/ray/cluster_utils.py:135`` + Train rendezvous
``train/torch/config.py:66``).

Rendezvous resolution exercised end to end: the coordinator address is
elected through the cluster HEAD's KV (``rendezvous_via_kv`` — the
internal-KV NCCLUniqueID-exchange role), not passed on the command line.
Each host then runs JaxTrainer.fit with the SAME SPMD train loop over the
global mesh; the per-step collectives cross the process boundary.
"""

import argparse
import json
import sys


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--process-id", type=int, required=True)
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--head", required=True, help="host:port of head")
    parser.add_argument("--coordinator-port", type=int, required=True)
    parser.add_argument("--out", required=True)
    # elastic re-form (VERDICT r4 weak #4): generation 2 re-runs the
    # rendezvous under a NEW run id at SURVIVING capacity and resumes
    # from generation 1's checkpoint
    parser.add_argument("--run-id", default="mh-test")
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--restore", action="store_true")
    parser.add_argument("--steps", type=int, default=2)
    args = parser.parse_args()

    from ray_tpu._private.platform import force_cpu_platform
    force_cpu_platform(n_devices=4)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu._private.head import HeadClient
    from ray_tpu.parallel import multihost

    # Elect the coordinator through the head KV (resolution path 2).
    multihost.COORDINATOR_PORT = args.coordinator_port
    host, port = args.head.rsplit(":", 1)
    kv = HeadClient((host, int(port)))
    coord, nprocs, pid = multihost.rendezvous_via_kv(
        kv, args.num_processes, args.process_id, run_id=args.run_id)
    ok = multihost.initialize_multihost(coord, nprocs, pid)
    # a re-formed single-survivor generation needs no coordination
    # service: initialize_multihost correctly reports False
    assert ok or args.num_processes == 1

    assert jax.process_count() == args.num_processes
    assert jax.local_device_count() == 4
    assert jax.device_count() == 4 * args.num_processes

    import ray_tpu
    ray_tpu.init(num_nodes=1, resources={"CPU": 4})
    try:
        from ray_tpu.train import JaxTrainer, ScalingConfig, RunConfig
        from ray_tpu.train import session

        def train_loop(config):
            from ray_tpu.models.llama import LlamaConfig, LlamaModel
            from ray_tpu.parallel.mesh import MeshSpec, build_mesh
            from ray_tpu.train.spmd import make_train_step

            # world-size-aware sharding: 2 hosts -> dp=2 x fsdp=4 over
            # 8 devices; the re-formed single-host generation shards
            # the SAME model dp=1 x fsdp=4 over its 4 local devices
            n_dev = jax.device_count()
            mesh = build_mesh(MeshSpec(dp=n_dev // 4, fsdp=4),
                              jax.devices())
            cfg = LlamaConfig.debug(vocab_size=128, max_seq_len=64)
            model = LlamaModel(cfg, mesh=mesh)
            ts = make_train_step(model, mesh=mesh)
            params, opt = ts.init_fn(jax.random.key(0))
            if args.restore:
                import pickle
                with open(f"{args.checkpoint_dir}/params.pkl",
                          "rb") as f:
                    host_params, host_opt = pickle.load(f)

                def put_like(host_tree, ref_tree):
                    return jax.tree.map(
                        lambda arr, ref: jax.device_put(
                            jnp.asarray(arr), ref.sharding),
                        host_tree, ref_tree)

                # BOTH trees: params alone would reset Adam moments and
                # break the resume contract (first post-restore step
                # re-runs bias correction and can spike the loss)
                params = put_like(host_params, params)
                opt = put_like(host_opt, opt)
            rng = np.random.default_rng(0)   # same data on every host
            tokens = jnp.asarray(
                rng.integers(0, 128, (4, 64)), jnp.int32)
            targets = jnp.roll(tokens, -1, axis=1)
            loss = None
            for _ in range(args.steps):
                params, opt, metrics = ts.step_fn(params, opt,
                                                  (tokens, targets))
                loss = float(metrics["loss"])
            if args.checkpoint_dir and not args.restore:
                # collective gather on EVERY host (a global array is
                # not fully addressable from one process); only host 0
                # writes
                import pickle

                from jax.experimental import multihost_utils
                # tiled=True: concatenate shards into the GLOBAL value
                # (required for non-fully-addressable arrays)
                host_params = multihost_utils.process_allgather(
                    params, tiled=True)
                host_opt = multihost_utils.process_allgather(
                    opt, tiled=True)
                if pid == 0:
                    with open(f"{args.checkpoint_dir}/params.pkl",
                              "wb") as f:
                        pickle.dump((host_params, host_opt), f)
            session.report({"loss": loss})

        result = JaxTrainer(
            train_loop,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name=f"mh-host{args.process_id}")).fit()
        if result.error:
            raise RuntimeError(result.error)
        with open(args.out, "w") as f:
            json.dump({"process_id": args.process_id,
                       "global_devices": jax.device_count(),
                       "loss": result.metrics["loss"]}, f)
    finally:
        ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
