"""One 'host' of a two-process multi-host training test.

Spawned twice by ``tests/test_multihost.py`` — each process owns 4 virtual
CPU devices and joins one 8-device global mesh through the jax
coordination service (the single-machine stand-in for a v5p pod the
environment allows; reference capability: multi-node fixture
``python/ray/cluster_utils.py:135`` + Train rendezvous
``train/torch/config.py:66``).

Rendezvous resolution exercised end to end: the coordinator address is
elected through the cluster HEAD's KV (``rendezvous_via_kv`` — the
internal-KV NCCLUniqueID-exchange role), not passed on the command line.
Each host then runs JaxTrainer.fit with the SAME SPMD train loop over the
global mesh; the per-step collectives cross the process boundary.
"""

import argparse
import json
import sys


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--process-id", type=int, required=True)
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--head", required=True, help="host:port of head")
    parser.add_argument("--coordinator-port", type=int, required=True)
    parser.add_argument("--out", required=True)
    args = parser.parse_args()

    from ray_tpu._private.platform import force_cpu_platform
    force_cpu_platform(n_devices=4)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu._private.head import HeadClient
    from ray_tpu.parallel import multihost

    # Elect the coordinator through the head KV (resolution path 2).
    multihost.COORDINATOR_PORT = args.coordinator_port
    host, port = args.head.rsplit(":", 1)
    kv = HeadClient((host, int(port)))
    coord, nprocs, pid = multihost.rendezvous_via_kv(
        kv, args.num_processes, args.process_id, run_id="mh-test")
    assert multihost.initialize_multihost(coord, nprocs, pid)

    assert jax.process_count() == args.num_processes
    assert jax.local_device_count() == 4
    assert jax.device_count() == 4 * args.num_processes

    import ray_tpu
    ray_tpu.init(num_nodes=1, resources={"CPU": 4})
    try:
        from ray_tpu.train import JaxTrainer, ScalingConfig, RunConfig
        from ray_tpu.train import session

        def train_loop(config):
            from ray_tpu.models.llama import LlamaConfig, LlamaModel
            from ray_tpu.parallel.mesh import MeshSpec, build_mesh
            from ray_tpu.train.spmd import make_train_step

            mesh = build_mesh(MeshSpec(dp=2, fsdp=4), jax.devices())
            cfg = LlamaConfig.debug(vocab_size=128, max_seq_len=64)
            model = LlamaModel(cfg, mesh=mesh)
            ts = make_train_step(model, mesh=mesh)
            params, opt = ts.init_fn(jax.random.key(0))
            rng = np.random.default_rng(0)   # same data on every host
            tokens = jnp.asarray(
                rng.integers(0, 128, (4, 64)), jnp.int32)
            targets = jnp.roll(tokens, -1, axis=1)
            loss = None
            for _ in range(2):
                params, opt, metrics = ts.step_fn(params, opt,
                                                  (tokens, targets))
                loss = float(metrics["loss"])
            session.report({"loss": loss})

        result = JaxTrainer(
            train_loop,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name=f"mh-host{args.process_id}")).fit()
        if result.error:
            raise RuntimeError(result.error)
        with open(args.out, "w") as f:
            json.dump({"process_id": args.process_id,
                       "global_devices": jax.device_count(),
                       "loss": result.metrics["loss"]}, f)
    finally:
        ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
