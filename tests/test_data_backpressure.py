"""Data plane depth: backpressure policies, elastic actor pools,
non-blocking limit, filesystem-seamed datasources.

Reference: ``data/_internal/execution/backpressure_policy/`` and
``data/tests/test_backpressure_e2e.py`` (small store, big dataset);
``actor_pool_map_operator.py`` autoscaling; 41-datasource read_api
behind one path/filesystem seam.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture
def ray_small_store():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 8},
                      object_store_memory=24 * 1024 * 1024)
    yield rt
    ray_tpu.shutdown()


def test_backpressure_e2e_small_store(ray_small_store):
    """A dataset far larger than the object store streams through to
    completion — the store-usage policy throttles upstream reads so the
    pipeline drains instead of dying on allocation."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    old = ctx.object_store_backpressure_threshold
    ctx.object_store_backpressure_threshold = 0.5
    try:
        n_blocks, rows = 24, 40_000  # ~24 x 320KB of int64 ≈ 7.7MB live

        def make(i):
            def read():
                import pyarrow as pa
                return pa.table(
                    {"x": np.full(rows, i, np.int64)})
            return read

        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data import logical as L

        ds = Dataset(L.Read("read_big", [],
                            read_tasks=[make(i) for i in range(n_blocks)]))
        total = 0
        for batch in ds.iter_batches(batch_size=None):
            total += int(len(batch["x"]))
        assert total == n_blocks * rows
    finally:
        ctx.object_store_backpressure_threshold = old


def test_object_store_policy_throttles_upstream(ray_small_store):
    from ray_tpu.data.backpressure_policy import \
        ObjectStoreMemoryBackpressurePolicy

    class _Op:
        def __init__(self, inq, active, cap):
            self.inqueue = inq
            self.active = active
            self.max_in_flight = cap

    class _Exec:
        pass

    up, down = _Op([1], [], 4), _Op([1], [], 4)
    ex = _Exec()
    ex.ops = [up, down]
    policy = ObjectStoreMemoryBackpressurePolicy(threshold=0.5)
    policy._store_pressure = lambda: 0.9  # force pressure
    assert policy.can_launch(down, ex)      # downstream-most may drain
    assert not policy.can_launch(up, ex)    # upstream throttled
    policy._store_pressure = lambda: 0.1
    assert policy.can_launch(up, ex)


def test_actor_pool_autoscales(ray_start_regular):
    """concurrency=(1, 3): the pool grows under backlog and shrinks back
    toward min when input dries up."""
    from ray_tpu.data import logical as L
    from ray_tpu.data.execution import ActorPoolMapOperator

    class Slow:
        def __call__(self, b):
            import time
            time.sleep(0.05)
            return b

    op = L.MapBatches("mb", [], fn=None, fn_constructor=Slow,
                      batch_format="numpy", concurrency=(1, 3))
    phys = ActorPoolMapOperator("pool", op)
    assert len(phys.workers) == 1
    # grow ONLY when the pool is the binding constraint: all workers
    # busy AND input queued
    phys.inqueue.extend([object(), object(), object(), object()])
    phys.maybe_autoscale()
    assert len(phys.workers) == 1  # nothing busy: no growth
    phys.active["ref-a"] = phys.workers[0]
    phys.maybe_autoscale()
    assert len(phys.workers) == 2
    phys.active["ref-b"] = phys.workers[1]
    phys.maybe_autoscale()
    assert len(phys.workers) == 3  # grew to max
    # shrink skips BUSY workers: with workers 0 and 1 busy, the idle
    # worker 2 is reclaimed after the idle window
    phys.inqueue.clear()
    for _ in range(phys._IDLE_TICKS_BEFORE_SHRINK + 1):
        phys.maybe_autoscale()
    assert len(phys.workers) == 2
    assert all(id(w) in {id(x) for x in phys.active.values()}
               for w in phys.workers)
    phys.shutdown()


def test_actor_pool_e2e_with_range(ray_start_regular):
    class Add(object):
        def __init__(self):
            self.c = 5

        def __call__(self, b):
            return {"id": b["id"] + self.c}

    ds = rdata.range(30, parallelism=6).map_batches(
        Add, concurrency=(1, 2))
    assert sorted(r["id"] for r in ds.take_all()) == list(
        np.arange(5, 35))


def test_limit_is_nonblocking_and_exact(ray_start_regular):
    ds = rdata.range(1000, parallelism=10).limit(123)
    rows = ds.take_all()
    # exactly 123 distinct rows (blocks stream in completion order)
    ids = [r["id"] for r in rows]
    assert len(ids) == 123
    assert len(set(ids)) == 123


def test_read_images_roundtrip(ray_start_regular, tmp_path):
    from PIL import Image

    for i in range(3):
        arr = np.full((8, 6, 3), i * 40, np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img{i}.png")
    ds = rdata.read_images(str(tmp_path), size=(4, 3))
    batches = list(ds.iter_batches(batch_size=None))
    imgs = np.concatenate([b["image"] for b in batches])
    assert imgs.shape == (3, 4, 3, 3)
    assert sorted(int(im[0, 0, 0]) for im in imgs) == [0, 40, 80]


def test_read_numpy(ray_start_regular, tmp_path):
    np.save(tmp_path / "a.npy", np.arange(5))
    np.save(tmp_path / "b.npy", np.arange(5, 10))
    ds = rdata.read_numpy(str(tmp_path))
    vals = sorted(v for b in ds.iter_batches(batch_size=None)
                  for v in b["data"].tolist())
    assert vals == list(range(10))


def test_filesystem_scheme_errors(ray_start_regular):
    from ray_tpu.data.filesystem import resolve_filesystem

    with pytest.raises(NotImplementedError, match="S3"):
        resolve_filesystem("s3://bucket/key")
    fs, path = resolve_filesystem("/tmp/x")
    assert path == "/tmp/x"


def test_filesystem_registration(ray_start_regular, tmp_path):
    from ray_tpu.data.filesystem import (LocalFileSystem,
                                         register_filesystem,
                                         resolve_filesystem)

    class Prefixed(LocalFileSystem):
        def open_input(self, path):
            return super().open_input(str(tmp_path / path))

    register_filesystem("mem", Prefixed())
    (tmp_path / "f.txt").write_text("hello")
    fs, rest = resolve_filesystem("mem://f.txt")
    assert fs.open_input(rest).read() == b"hello"
