"""Process-worker execution path: isolation, recycling, crash handling.

Reference capabilities exercised here: worker-pool process isolation
(``src/ray/raylet/worker_pool.h``), serialization boundary on the task
path (``python/ray/_private/serialization.py``), kill -9 of a worker
process triggering retry/actor restart (``GcsActorManager`` worker-failure
path, ``python/ray/tests`` ResourceKiller idea).
"""

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc


def test_tasks_run_in_separate_process(ray_start_regular):
    @ray_tpu.remote
    def worker_pid():
        return os.getpid()

    pid = ray_tpu.get(worker_pid.remote())
    assert pid != os.getpid()


def test_result_immutability_across_consumers(ray_start_regular):
    """A consumer mutating a task result must not affect other consumers
    (reference: every value crosses a serialization boundary)."""

    @ray_tpu.remote
    def make():
        return {"xs": [1, 2, 3]}

    @ray_tpu.remote
    def mutate(d):
        d["xs"].append(99)
        return len(d["xs"])

    ref = make.remote()
    assert ray_tpu.get(mutate.remote(ref)) == 4
    assert ray_tpu.get(ref)["xs"] == [1, 2, 3]
    # numpy results come back read-only in the driver
    @ray_tpu.remote
    def make_arr():
        return np.arange(10)

    arr = ray_tpu.get(make_arr.remote())
    with pytest.raises(ValueError):
        arr[0] = 5


def test_driver_closure_mutation_does_not_leak(ray_start_regular):
    state = {"n": 0}

    @ray_tpu.remote
    def bump():
        state["n"] += 1
        return state["n"]

    ray_tpu.get(bump.remote())
    assert state["n"] == 0  # driver copy untouched


def test_worker_sigkill_triggers_task_retry(ray_start_regular, tmp_path):
    """kill -9 of the worker process mid-task → retried on a new process."""
    marker = str(tmp_path / "attempt")

    @ray_tpu.remote(max_retries=2)
    def slow():
        import os as _os
        import time as _time
        n = len(_os.listdir(_os.path.dirname(marker)))
        # the attempt file carries this worker's pid so the test can
        # SIGKILL it in EITHER topology (daemons-mode workers are not
        # in the driver's router)
        with open(f"{marker}.{n}", "w") as f:
            f.write(str(_os.getpid()))
        if n == 0:
            _time.sleep(30)  # first attempt: get killed mid-flight
        return _os.getpid()

    rt = ray_tpu._private.worker.global_runtime()
    ref = slow.remote()
    # find the worker pid (from the attempt-0 marker) and SIGKILL it
    deadline = time.monotonic() + 10
    pid = None
    while pid is None and time.monotonic() < deadline:
        try:
            with open(f"{marker}.0") as f:
                content = f.read().strip()
            if content:
                pid = int(content)
        except (FileNotFoundError, ValueError):
            pass
        time.sleep(0.05)
    assert pid is not None, "task never landed on a worker process"
    os.kill(pid, signal.SIGKILL)
    out = ray_tpu.get(ref, timeout=30)
    assert out != pid  # retried on a different process
    assert len(os.listdir(tmp_path)) == 2


def test_actor_sigkill_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1)
    class P:
        def __init__(self):
            self.boot = time.monotonic()

        def pid(self):
            return os.getpid()

    a = P.remote()
    rt = ray_tpu._private.worker.global_runtime()
    pid1 = ray_tpu.get(a.pid.remote())
    os.kill(pid1, signal.SIGKILL)
    deadline = time.monotonic() + 20
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2 = ray_tpu.get(a.pid.remote(), timeout=10)
            break
        except (exc.ActorError, exc.ActorUnavailableError, exc.TaskError,
                exc.GetTimeoutError):
            time.sleep(0.2)
    assert pid2 is not None and pid2 != pid1


def test_dirty_actor_worker_not_recycled(ray_start_regular):
    """An actor that leaks a reference to itself (background thread) must
    not poison the worker pool: its process is killed on actor death, and
    subsequent tasks run correctly on fresh/clean workers."""

    @ray_tpu.remote
    class Leaky:
        def __init__(self):
            import threading

            self.stop = False

            def loop(me=self):
                while not me.stop:
                    time.sleep(0.01)

            self.t = threading.Thread(target=loop, daemon=True)
            self.t.start()

        def pid(self):
            return os.getpid()

    a = Leaky.remote()
    leaky_pid = ray_tpu.get(a.pid.remote())
    ray_tpu.kill(a)
    # the leaky process must eventually be gone (killed, not recycled)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            os.kill(leaky_pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.1)
    else:
        pytest.fail(f"dirty actor worker {leaky_pid} still alive")

    @ray_tpu.remote
    def ok():
        return "fine"

    assert ray_tpu.get(ok.remote()) == "fine"


def test_function_table_ships_code_once(ray_start_regular):
    """The same remote function reuses its exported blob (function table,
    reference: function_manager.py export/fetch_and_register)."""
    from ray_tpu._private.worker_process import _FN_TABLE

    @ray_tpu.remote
    def fn(x):
        return x + 1

    before = len(_FN_TABLE)
    ray_tpu.get([fn.remote(i) for i in range(5)])
    added = len(_FN_TABLE) - before
    assert added <= 1


def test_nested_submission_from_worker(ray_start_regular):
    """Tasks submitted from inside a worker process work transparently."""

    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(xs):
        return sum(ray_tpu.get([inner.remote(x) for x in xs]))

    assert ray_tpu.get(outer.remote([1, 2, 3])) == 12


def test_placement_group_from_worker(ray_start_cluster):
    """Workers can create PGs and schedule into them (pg proxy ops)."""

    @ray_tpu.remote
    def with_pg():
        from ray_tpu.util.placement_group import (placement_group,
                                                  remove_placement_group)
        from ray_tpu._private.task_spec import \
            PlacementGroupSchedulingStrategy

        pg = placement_group([{"CPU": 1}], strategy="PACK")
        assert pg.wait(15)

        @ray_tpu.remote(num_cpus=1, scheduling_strategy=
                        PlacementGroupSchedulingStrategy(
                            placement_group=pg,
                            placement_group_bundle_index=0))
        def inside():
            return "placed"

        out = ray_tpu.get(inside.remote(), timeout=20)
        remove_placement_group(pg)
        return out

    assert ray_tpu.get(with_pg.remote(), timeout=60) == "placed"
