"""Fast lane: driver -> native C++ core -> dedicated worker task path.

Reference capability: the raylet's C++ lease/dispatch hot loop
(``src/ray/raylet/node_manager.cc`` HandleRequestWorkerLease,
``raylet/local_task_manager.h``) — plain tasks route through the native
daemon core (``native/daemon_core.cc``) with zero daemon-Python per
task. These tests run the REAL daemons topology (head + daemon OS
processes) and assert both behavior and that the lane actually carried
the tasks (core stats), so a silent classic-path fallback fails loudly.
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc


@pytest.fixture
def daemon_cluster():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


def _lane(rt):
    """The (single) daemon's fast-lane client; skip if the native core
    is unavailable (no g++ in the environment)."""
    (handle,) = rt.cluster_backend.daemons.values()
    if handle.fast_port is None:
        pytest.skip("native daemon core unavailable")
    fl = handle._fast_client()
    assert fl is not None
    return handle, fl


def test_plain_tasks_ride_the_lane(daemon_cluster):
    rt = daemon_cluster
    handle, fl = _lane(rt)
    before = fl.ping()["completed"]

    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get([add.remote(i, 1) for i in range(20)]) == [
        i + 1 for i in range(20)]
    after = fl.ping()["completed"]
    assert after - before >= 20


def test_errors_and_ref_args(daemon_cluster):
    rt = daemon_cluster
    _lane(rt)

    @ray_tpu.remote
    def boom():
        raise ValueError("expected")

    @ray_tpu.remote
    def add(a, b):
        return a + b

    with pytest.raises(exc.TaskError):
        ray_tpu.get(boom.remote())
    # ObjectRef args resolve through the owner from a lane worker
    r = add.remote(1, 1)
    assert ray_tpu.get(add.remote(r, 10)) == 12


def test_nested_tasks_from_lane_worker(daemon_cluster):
    """A lane task submitting child tasks exercises the worker's host
    channel (core ops) while dedicated to the lane."""
    rt = daemon_cluster
    _lane(rt)

    @ray_tpu.remote
    def child(x):
        return x * 2

    @ray_tpu.remote
    def parent():
        return ray_tpu.get([child.remote(i) for i in range(3)])

    assert ray_tpu.get(parent.remote()) == [0, 2, 4]


def test_big_results_inline(daemon_cluster):
    """Lane results come back inline regardless of size; the driver
    (owner) stores them."""
    rt = daemon_cluster
    _lane(rt)
    import numpy as np

    @ray_tpu.remote
    def big():
        return np.arange(200_000)

    out = ray_tpu.get(big.remote())
    assert out.shape == (200_000,) and int(out[-1]) == 199_999


def test_cancel_running_lane_task(daemon_cluster):
    rt = daemon_cluster
    _lane(rt)

    @ray_tpu.remote
    def sleeper():
        # interruptible sleep: async cancel (KeyboardInterrupt) only
        # fires between bytecodes, not inside a blocking C sleep
        for _ in range(300):
            time.sleep(0.1)
        return "done"

    ref = sleeper.remote()
    time.sleep(1.0)        # let it reach the lane worker
    ray_tpu.cancel(ref)
    with pytest.raises(exc.TaskError) as ei:
        ray_tpu.get(ref, timeout=15)
    assert isinstance(ei.value.cause, exc.TaskCancelledError)


def test_actor_and_generator_tasks_stay_classic(daemon_cluster):
    """Non-plain work keeps the classic daemon path and still works
    alongside the lane."""
    rt = daemon_cluster
    _lane(rt)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get([c.inc.remote() for _ in range(3)]) == [1, 2, 3]

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        yield from range(4)

    assert [ray_tpu.get(r) for r in gen.remote()] == [0, 1, 2, 3]


def test_lane_worker_crash_retries(daemon_cluster):
    """A lane worker dying mid-task surfaces as a crash and the driver's
    retry machinery re-runs the task (reference: RetryTaskIfPossible)."""
    rt = daemon_cluster
    _lane(rt)

    @ray_tpu.remote(max_retries=2)
    def die_once():
        import os
        marker = "/tmp/rtpu_fastlane_die_once"
        if not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write("x")
            os._exit(1)
        os.remove(marker)
        return "recovered"

    assert ray_tpu.get(die_once.remote(), timeout=60) == "recovered"


def test_core_stats_shape(daemon_cluster):
    rt = daemon_cluster
    _, fl = _lane(rt)
    stats = fl.ping()
    assert set(stats) == {"queued", "inflight", "workers", "completed"}
    assert stats["workers"] >= 0


def _instance(rt, actor_id, timeout=15.0):
    """Actor creation is async: wait for the driver-side executor."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ex = rt._actor_executors.get(actor_id)
        if ex is not None and ex.instance is not None:
            return ex.instance
        time.sleep(0.05)
    raise AssertionError("actor executor never appeared")


def test_targeted_actor_lane(daemon_cluster):
    """Default (serialized) actors get a per-actor tag in the native
    core; method calls ride the targeted lane with strict FIFO ordering
    (reference: actor_scheduling_queue.h), and the core's submit
    counter proves the calls actually took the lane."""
    rt = daemon_cluster
    handle, fl = _lane(rt)
    before = fl.ping()["completed"]

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote()
    vals = ray_tpu.get([c.inc.remote() for _ in range(20)])
    inst = _instance(rt, c._actor_id)
    assert getattr(inst, "fast_tag", None), "actor not lane-bound"
    assert vals == list(range(1, 21))        # strict ordering
    assert fl.ping()["completed"] - before >= 20

    # generator-returning method: items drained worker-side, replayed
    # as a stream (the method must run exactly ONCE)
    @ray_tpu.remote
    class Gen:
        def __init__(self):
            self.calls = 0

        def stream3(self):
            self.calls += 1
            yield from ("a", "b", "c")

        def count(self):
            return self.calls

    g = Gen.remote()
    out = ray_tpu.get(g.stream3.remote())
    # PARITY with the classic path: a generator-returning method under
    # num_returns=1 resolves to the streaming sentinel (use
    # num_returns="streaming" for item-wise consumption); the items
    # were drained worker-side and the body ran exactly ONCE
    assert type(out).__name__ == "_StreamingGeneratorSentinel"
    assert ray_tpu.get(g.count.remote()) == 1


def test_concurrent_actors_keep_classic_path(daemon_cluster):
    """max_concurrency>1 actors are NOT lane-bound (serialization would
    break their concurrency contract) and still work."""
    rt = daemon_cluster

    @ray_tpu.remote(max_concurrency=4)
    class Par:
        def ping(self):
            return "pong"

    p = Par.remote()
    assert ray_tpu.get(p.ping.remote()) == "pong"
    inst = _instance(rt, p._actor_id)
    assert getattr(inst, "fast_tag", None) is None


def test_lane_actor_worker_sigkill_restarts(daemon_cluster):
    """Killing a lane-bound actor's worker restarts the actor; the new
    incarnation gets a FRESH tag and keeps serving."""
    import os as _os
    import signal as _signal

    rt = daemon_cluster

    @ray_tpu.remote(max_restarts=1)
    class P:
        def pid(self):
            import os
            return os.getpid()

    a = P.remote()
    pid1 = ray_tpu.get(a.pid.remote())
    tag1 = _instance(rt, a._actor_id).fast_tag
    _os.kill(pid1, _signal.SIGKILL)
    deadline = time.monotonic() + 30
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2 = ray_tpu.get(a.pid.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.3)
    assert pid2 is not None and pid2 != pid1
    tag2 = _instance(rt, a._actor_id).fast_tag
    assert tag2 and tag2 != tag1
