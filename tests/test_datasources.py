"""Datasource breadth: SQL, WebDataset, JSON/numpy/webdataset writers.

Reference capability: `python/ray/data/read_api.py` (read_sql,
read_webdataset, Dataset.write_json/write_numpy/write_webdataset).
"""

import json
import sqlite3

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data


def test_read_sql_sqlite(ray_start_regular, tmp_path):
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE users (id INTEGER, name TEXT, score REAL)")
    conn.executemany("INSERT INTO users VALUES (?, ?, ?)",
                     [(i, f"u{i}", i * 1.5) for i in range(20)])
    conn.commit()
    conn.close()

    ds = data.read_sql("SELECT * FROM users WHERE id < 10",
                       lambda: sqlite3.connect(db))
    rows = ds.take_all()
    assert len(rows) == 10
    assert rows[3] == {"id": 3, "name": "u3", "score": 4.5}

    # paged: 3 tasks cover the full result set exactly once
    ds = data.read_sql("SELECT * FROM users", lambda: sqlite3.connect(db),
                       parallelism=3)
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(20))


def test_webdataset_roundtrip(ray_start_regular, tmp_path):
    out = str(tmp_path / "shards")
    ds = data.from_items([
        {"__key__": f"s{i:03d}", "img": bytes([i] * 4),
         "cls": str(i % 3), "meta": {"n": i}}
        for i in range(6)])
    ds.write_webdataset(out)

    back = data.read_webdataset(out + "/*.tar").take_all()
    assert len(back) == 6
    by_key = {r["__key__"]: r for r in back}
    assert by_key["s002"]["img"] == bytes([2] * 4)
    assert by_key["s002"]["cls"] == b"2"            # str columns -> raw
    assert json.loads(by_key["s004"]["meta"]) == {"n": 4}


def test_webdataset_ragged_and_multipart_extensions(ray_start_regular,
                                                    tmp_path):
    """First-dot key splitting (000.seg.png stays with 000) and samples
    missing an extension get None instead of silently dropping data."""
    import io
    import tarfile

    shard = tmp_path / "w"
    shard.mkdir()
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        for name, payload in [("000.jpg", b"img0"),
                              ("000.seg.png", b"mask0"),
                              ("001.jpg", b"img1"),
                              ("001.json", b"{}")]:
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    (shard / "s.tar").write_bytes(buf.getvalue())

    rows = {r["__key__"]: r
            for r in data.read_webdataset(str(shard / "s.tar")).take_all()}
    assert set(rows) == {"000", "001"}
    assert rows["000"]["seg.png"] == b"mask0"      # multi-part extension
    assert rows["000"]["json"] is None             # ragged -> None
    assert rows["001"]["json"] == b"{}"
    assert rows["001"]["seg.png"] is None


def test_write_json_roundtrip(ray_start_regular, tmp_path):
    out = str(tmp_path / "j")
    data.from_items([{"a": i, "b": f"x{i}"} for i in range(7)]
                    ).write_json(out)
    back = data.read_json(out + "/*.json").take_all()
    assert sorted(r["a"] for r in back) == list(range(7))
    assert {r["b"] for r in back} == {f"x{i}" for i in range(7)}


def test_write_numpy_roundtrip(ray_start_regular, tmp_path):
    out = str(tmp_path / "n")
    arr = np.arange(12.0)
    data.from_numpy(arr, column="v").write_numpy(out, "v")
    back = data.read_numpy(out + "/*.npy", column="v").take_all()
    got = np.sort(np.asarray([r["v"] for r in back]))
    np.testing.assert_array_equal(got, arr)


def test_tfrecords_roundtrip(ray_start_regular, tmp_path):
    """TFRecord write/read without TensorFlow: bytes/float/int features,
    scalars and lists, crc-framed."""
    out = str(tmp_path / "tfr")
    rows = [{"name": f"r{i}", "img": bytes([i, i + 1]),
             "score": i * 0.5, "labels": [i, i * 2], "neg": -i}
            for i in range(8)]
    data.from_items(rows).write_tfrecords(out)
    back = sorted(data.read_tfrecords(out + "/*.tfrecord").take_all(),
                  key=lambda r: r["name"])
    assert len(back) == 8
    r3 = back[3]
    # features are ALWAYS lists (proto semantics, shard-consistent)
    assert r3["name"] == [b"r3"]          # strings round-trip as bytes
    assert r3["img"] == [bytes([3, 4])]
    assert abs(r3["score"][0] - 1.5) < 1e-6
    assert r3["labels"] == [3, 6]
    assert r3["neg"] == [-3]               # negative int64 varint


def test_tfrecords_crc_detects_corruption(tmp_path):
    from ray_tpu.data.tfrecords import (decode_example, encode_example,
                                        read_tfrecord_frames,
                                        write_tfrecord_frame)
    payload = encode_example({"a": 1})
    frame = bytearray(write_tfrecord_frame(payload))
    assert decode_example(next(read_tfrecord_frames(bytes(frame)))) == \
        {"a": [1]}                         # decode keeps proto lists
    frame[14] ^= 0xFF                      # flip a payload byte
    with pytest.raises(ValueError, match="crc"):
        list(read_tfrecord_frames(bytes(frame)))
    # truncation raises the same error family, not struct.error
    with pytest.raises(ValueError, match="truncated"):
        list(read_tfrecord_frames(bytes(
            write_tfrecord_frame(payload))[:-2]))
    # out-of-int64-range values are rejected, not silently wrapped
    with pytest.raises(ValueError, match="int64"):
        encode_example({"x": 2 ** 63})


def test_tfrecords_ragged_list_column(ray_start_regular, tmp_path):
    """A column mixing 1-element and longer lists stays ALL lists
    (per-row unwrapping would crash Arrow on mixed types)."""
    out = str(tmp_path / "ragged")
    data.from_items([{"labels": [5]}, {"labels": [1, 2]}]
                    ).write_tfrecords(out)
    back = data.read_tfrecords(out + "/*.tfrecord").take_all()
    assert sorted(back, key=lambda r: len(r["labels"])) == \
        [{"labels": [5]}, {"labels": [1, 2]}]


def test_avro_round_trip(ray_start_regular, tmp_path):
    """write_avro -> read_avro round trip through the native container
    codec (deflate blocks), nullable columns included."""
    from ray_tpu import data

    ds = data.from_items([
        {"a": 1, "b": "x", "c": 1.5, "ok": True, "raw": b"p"},
        {"a": 2, "b": "y", "c": 2.5, "ok": False, "raw": b"q"},
        {"a": 3, "b": None, "c": 3.5, "ok": True, "raw": b"r"},
    ])
    out = str(tmp_path / "avro_out")
    ds.write_avro(out)
    back = data.read_avro(out)
    rows = sorted(back.take_all(), key=lambda r: r["a"])
    assert [r["a"] for r in rows] == [1, 2, 3]
    assert rows[0]["b"] == "x" and rows[2]["b"] is None
    assert rows[1]["c"] == 2.5 and rows[0]["ok"] is True
    assert rows[2]["raw"] == b"r"


def test_avro_codec_spec_shapes(tmp_path):
    """The codec handles spec shapes beyond what write_avro emits:
    unions, enums, arrays, maps, fixed, nested records, both codecs."""
    from ray_tpu.data.avro import read_container, write_container

    schema = {
        "type": "record", "name": "outer", "fields": [
            {"name": "u", "type": ["null", "string", "long"]},
            {"name": "e", "type": {"type": "enum", "name": "col",
                                   "symbols": ["RED", "BLUE"]}},
            {"name": "xs", "type": {"type": "array", "items": "long"}},
            {"name": "m", "type": {"type": "map", "values": "double"}},
            {"name": "fx", "type": {"type": "fixed", "name": "f4",
                                    "size": 4}},
            {"name": "inner", "type": {
                "type": "record", "name": "pt", "fields": [
                    {"name": "x", "type": "double"},
                    {"name": "y", "type": "double"}]}},
        ]}
    records = [
        {"u": None, "e": "RED", "xs": [1, 2, 3], "m": {"a": 0.5},
         "fx": b"abcd", "inner": {"x": 1.0, "y": 2.0}},
        {"u": "s", "e": "BLUE", "xs": [], "m": {},
         "fx": b"wxyz", "inner": {"x": -1.0, "y": 0.25}},
        {"u": 7, "e": "RED", "xs": [10], "m": {"k": 2.0, "j": 3.0},
         "fx": b"0000", "inner": {"x": 0.0, "y": 0.0}},
    ]
    for codec in ("null", "deflate"):
        blob = write_container(schema, records, codec=codec)
        got_schema, got = read_container(blob)
        assert got == records
        assert got_schema["name"] == "outer"


def test_delta_lake_read(ray_start_regular, tmp_path):
    """Native Delta transaction-log replay: add/remove actions resolve
    to the live parquet files; time travel via version=N."""
    import json as _json

    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu import data

    table_dir = tmp_path / "dtable"
    log = table_dir / "_delta_log"
    log.mkdir(parents=True)

    def write_part(name, ids):
        pq.write_table(pa.table({"id": pa.array(ids)}),
                       table_dir / name)

    def commit(version, actions):
        with open(log / f"{version:020d}.json", "w") as f:
            for a in actions:
                f.write(_json.dumps(a) + "\n")

    write_part("part-0.parquet", [1, 2])
    write_part("part-1.parquet", [3, 4])
    write_part("part-2.parquet", [5, 6])
    commit(0, [{"metaData": {"id": "t"}},
               {"add": {"path": "part-0.parquet"}}])
    commit(1, [{"add": {"path": "part-1.parquet"}}])
    # version 2 compacts part-0+1 away into part-2
    commit(2, [{"remove": {"path": "part-0.parquet"}},
               {"remove": {"path": "part-1.parquet"}},
               {"add": {"path": "part-2.parquet"}}])

    latest = sorted(r["id"] for r in
                    data.read_delta(str(table_dir)).take_all())
    assert latest == [5, 6]
    v1 = sorted(r["id"] for r in
                data.read_delta(str(table_dir), version=1).take_all())
    assert v1 == [1, 2, 3, 4]


def test_orc_round_trip(ray_start_regular, tmp_path):
    from ray_tpu import data

    ds = data.from_items([{"a": i, "b": f"s{i}"} for i in range(12)])
    out = str(tmp_path / "orc_out")
    ds.write_orc(out)
    back = sorted(data.read_orc(out).take_all(), key=lambda r: r["a"])
    assert [r["a"] for r in back] == list(range(12))
    assert back[3]["b"] == "s3"


def test_from_torch(ray_start_regular):
    import torch
    from torch.utils.data import Dataset as TorchDataset

    from ray_tpu import data

    class Squares(TorchDataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return torch.tensor([i, i * i])

    rows = data.from_torch(Squares()).take_all()
    assert len(rows) == 8
    assert list(rows[3]["item"]) == [3, 9]


def test_delta_lake_checkpoint_parts(ray_start_regular, tmp_path):
    """Multi-part checkpoints: EVERY part of the newest checkpoint
    version feeds the replay base (one part alone drops files)."""
    import json as _json

    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu import data

    table_dir = tmp_path / "dtable"
    log = table_dir / "_delta_log"
    log.mkdir(parents=True)
    pq.write_table(pa.table({"id": pa.array([1, 2])}),
                   table_dir / "part-a.parquet")
    pq.write_table(pa.table({"id": pa.array([3, 4])}),
                   table_dir / "part-b.parquet")
    pq.write_table(pa.table({"id": pa.array([5])}),
                   table_dir / "part-c.parquet")

    def ckpt_rows(rows, name):
        pq.write_table(pa.table({
            "add": pa.array(rows, type=pa.struct(
                [("path", pa.string())])),
            "remove": pa.array([None] * len(rows), type=pa.struct(
                [("path", pa.string())])),
        }), log / name)

    # checkpoint v1 in two parts covering part-a and part-b
    ckpt_rows([{"path": "part-a.parquet"}],
              "00000000000000000001.checkpoint.0000000001.0000000002"
              ".parquet")
    ckpt_rows([{"path": "part-b.parquet"}],
              "00000000000000000001.checkpoint.0000000002.0000000002"
              ".parquet")
    # commit 2 after the checkpoint adds part-c
    with open(log / f"{2:020d}.json", "w") as f:
        f.write(_json.dumps({"add": {"path": "part-c.parquet"}}) + "\n")

    got = sorted(r["id"] for r in
                 data.read_delta(str(table_dir)).take_all())
    assert got == [1, 2, 3, 4, 5]
