"""Run callbacks (reference: air integration callbacks + tune logger
callbacks — json/csv loggers functional, tracking libs import-gated)."""

import json
import os

import pytest

import ray_tpu
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig, session
from ray_tpu.train.callbacks import (Callback, CSVLoggerCallback,
                                     JsonLoggerCallback,
                                     MLflowLoggerCallback,
                                     WandbLoggerCallback)


def test_json_and_csv_loggers_on_trainer(ray_start_regular, tmp_path):
    events = []

    class Recorder(Callback):
        def on_run_start(self, run_name, config=None):
            events.append(("start", run_name))

        def on_report(self, metrics, iteration, rank=0, trial_id=""):
            events.append(("report", metrics["step"]))

        def on_run_end(self, result=None, error=None):
            events.append(("end", error))

    def loop(config):
        for step in range(3):
            session.report({"step": step, "loss": 1.0 / (step + 1)})

    result = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="cbrun", storage_path=str(tmp_path),
            callbacks=[Recorder(),
                       JsonLoggerCallback(str(tmp_path / "logs")),
                       CSVLoggerCallback(str(tmp_path / "logs"))])).fit()
    assert result.error is None
    assert events[0] == ("start", "cbrun")
    assert ("report", 0) in events and ("report", 2) in events
    assert events[-1] == ("end", None)

    lines = [json.loads(ln) for ln in
             open(tmp_path / "logs" / "result.json")]
    assert [ln["step"] for ln in lines] == [0, 1, 2]
    csv = open(tmp_path / "logs" / "progress.csv").read().splitlines()
    assert csv[0].startswith("iteration,")
    assert len(csv) == 4


def test_callbacks_on_tune_trials(ray_start_regular, tmp_path):
    from ray_tpu import tune
    from ray_tpu.tune import TuneConfig, Tuner

    reports = []

    class Rec(Callback):
        def on_report(self, metrics, iteration, rank=0, trial_id=""):
            reports.append((trial_id, metrics["v"]))

    def obj(config):
        return {"v": config["x"]}

    Tuner(obj, param_space={"x": tune.grid_search([1, 2, 3])},
          tune_config=TuneConfig(metric="v", mode="max"),
          run_config=RunConfig(name="t", storage_path=str(tmp_path),
                               callbacks=[Rec()])).fit()
    assert sorted(v for _, v in reports) == [1, 2, 3]
    assert len({tid for tid, _ in reports}) == 3   # distinct trial ids


def test_tracking_integrations_import_gated():
    with pytest.raises(ImportError, match="wandb"):
        WandbLoggerCallback(project="x")
    with pytest.raises(ImportError, match="mlflow"):
        MLflowLoggerCallback()


def test_broken_callback_does_not_kill_run(ray_start_regular, tmp_path):
    class Broken(Callback):
        def on_report(self, *a, **k):
            raise RuntimeError("logging exploded")

    def loop(config):
        session.report({"ok": 1})

    result = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="b", storage_path=str(tmp_path),
                             callbacks=[Broken()])).fit()
    assert result.error is None
