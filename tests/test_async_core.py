"""Asyncio control-plane core (cfg().async_core): tier-1 units.

Pins the contracts the async rewrite introduced:

- loop-affinity sanitizer: ``eventloop.assert_loop`` is armed by
  ``lock_sanitizer`` and catches loop-only code running on a plain
  thread (the runtime leg of raylint's static loop-affinity pass);
- coalesced writes: a burst of frames staged on the loop leaves in ONE
  ``transport.write`` (the ``daemon_core.cc`` one-sendmsg-per-peer
  model), with large payloads skipping the join copy;
- failpoint + netchaos parity: the async wire honors the SAME seam
  names and frame-level chaos semantics as the threaded core, so chaos
  schedules and fault-injection tests are core-agnostic;
- mixed-cluster interop: daemons advertise their core in the hello
  ``async_core`` bit; frames are byte-identical so a threaded daemon
  under an async driver (and vice versa) just works;
- loop-lag watchdog: a blocked loop shows up in
  ``ray_tpu_event_loop_lag_seconds`` and the slow-callback counter;
- metric-registry pollution pin: a ``clear_registry()`` in one test
  must not silently eat metric writes from instances other modules
  cached (the order-dependent tenancy failures this PR fixed).
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from ray_tpu._private import eventloop
from ray_tpu._private import failpoints as fp
from ray_tpu._private import netchaos as nc
from ray_tpu._private import rpc
from ray_tpu._private.aio import AsyncClient, AsyncServer, _WriteBatcher


@pytest.fixture(autouse=True)
def _reset_chaos():
    yield
    nc.reset()
    fp.reset()


# ---------------------------------------------------------------------------
# loop-affinity sanitizer (runtime leg of raylint's loop-affinity pass)
# ---------------------------------------------------------------------------

def test_assert_loop_sanitizer(monkeypatch):
    """Armed by lock_sanitizer: loop-only code on a plain thread raises;
    the same check ON the loop passes; disarmed it is a no-op."""
    from ray_tpu._private import config
    monkeypatch.setenv("RAY_TPU_LOCK_SANITIZER", "1")
    config.reset()
    try:
        eventloop.get_loop()    # loop thread must exist to compare to
        with pytest.raises(RuntimeError, match="call_soon_threadsafe"):
            eventloop.assert_loop("test handler")

        async def on_loop_ok():
            eventloop.assert_loop("test handler")
            return True

        assert eventloop.run_coro(on_loop_ok(), timeout=5.0)
    finally:
        monkeypatch.delenv("RAY_TPU_LOCK_SANITIZER")
        config.reset()
    eventloop.assert_loop("disarmed")   # sanitizer off: no raise


# ---------------------------------------------------------------------------
# coalesced writes
# ---------------------------------------------------------------------------

class _FakeLoop:
    """Synchronous stand-in: callbacks run when the test drains them."""

    def __init__(self):
        self.pending = []

    def call_soon(self, fn, *args):
        self.pending.append((fn, args))

    def call_later(self, delay, fn, *args):
        self.pending.append((fn, args))

    def time(self):
        return 0.0

    def drain(self):
        while self.pending:
            fn, args = self.pending.pop(0)
            fn(*args)


class _FakeTransport:
    def __init__(self):
        self.chunks = []

    def is_closing(self):
        return False

    def write(self, data):
        self.chunks.append(bytes(data))


def test_write_batcher_coalesces_small_frames():
    """N frames staged in one loop iteration leave in ONE write."""
    loop, transport = _FakeLoop(), _FakeTransport()
    b = _WriteBatcher(loop, transport, object())
    blobs = [bytes([i]) * (i + 1) for i in range(5)]
    for blob in blobs:
        b.send(blob)
    assert transport.chunks == []       # nothing written until flush
    loop.drain()
    assert b.frames == 5
    assert b.writes == 1
    assert transport.chunks == [
        b"".join(rpc._LEN.pack(len(x)) + x for x in blobs)]


def test_write_batcher_big_payload_skips_join_copy():
    """A frame over SEND_CONCAT_MAX never rides the join: the pending
    small run flushes first (stream order holds), then header and
    payload go as their own writes — no multi-MB concat copy."""
    loop, transport = _FakeLoop(), _FakeTransport()
    b = _WriteBatcher(loop, transport, object())
    big = b"B" * (rpc.SEND_CONCAT_MAX + 1)
    b.send(b"s1")
    b.send(big)
    b.send(b"s2")
    loop.drain()
    assert b.frames == 3
    assert transport.chunks == [
        rpc._LEN.pack(2) + b"s1",       # small run before the big frame
        rpc._LEN.pack(len(big)),        # big header, own write
        big,                            # big payload, no copy-join
        rpc._LEN.pack(2) + b"s2"]       # trailing small run


# ---------------------------------------------------------------------------
# failpoint parity on the async wire (same seam names as the threaded core)
# ---------------------------------------------------------------------------

class _EchoSvc:
    def __init__(self):
        self.calls = 0

    def handle_ac_echo(self, conn, rid, msg):
        self.calls += 1
        return {"v": msg["v"]}


rpc.declare("ac_echo", "v")


def _async_pair(svc, timeout=0.5, chaos_roles=None):
    server = AsyncServer(svc).start()
    client = AsyncClient(server.addr, timeout=timeout)
    if chaos_roles:
        local_role, peer_role = chaos_roles
        nc.register_link(client._sock, peer_role, local_role=local_role)
    return server, client


def test_failpoint_server_recv_drop_parity():
    svc = _EchoSvc()
    server, client = _async_pair(svc, timeout=0.3)
    try:
        assert client.call("ac_echo", v=1)["v"] == 1
        fp.activate("rpc.server.recv=drop:max=1")
        with pytest.raises(rpc.RpcError):
            client.call("ac_echo", v=2)
        assert client.call("ac_echo", v=3)["v"] == 3
        assert fp.fire_count("rpc.server.recv") == 1
    finally:
        client.close()
        server.stop()


def test_failpoint_client_send_drop_parity():
    svc = _EchoSvc()
    server, client = _async_pair(svc, timeout=0.2)
    try:
        fp.activate("rpc.client.send=drop:max=1")
        with pytest.raises(rpc.RpcError):
            client.call("ac_echo", v=1)
        assert client.call("ac_echo", v=2)["v"] == 2
        assert fp.fire_count("rpc.client.send") == 1
    finally:
        client.close()
        server.stop()


# ---------------------------------------------------------------------------
# netchaos parity on the async wire (below the frame layer, loop never
# sleeps — delays ride call_later chains)
# ---------------------------------------------------------------------------

def test_netchaos_partition_and_heal_parity():
    svc = _EchoSvc()
    server, client = _async_pair(svc, chaos_roles=("t", "svc"))
    try:
        assert client.call("ac_echo", v=1)["v"] == 1
        nc.activate("t>svc=partition")
        with pytest.raises(rpc.RpcError):
            client.call("ac_echo", v=2)
        assert svc.calls == 1           # request never arrived
        assert nc.injected_count("drop") >= 1
        nc.reset()
        assert client.call("ac_echo", v=3)["v"] == 3    # link healed
    finally:
        client.close()
        server.stop()


def test_netchaos_duplicate_suppressed_at_caller_parity():
    svc = _EchoSvc()
    server, client = _async_pair(svc, timeout=2.0,
                                 chaos_roles=("t", "svc"))
    try:
        nc.activate("t>svc=dup=1.0")
        assert client.call("ac_echo", v=7)["v"] == 7
        deadline = time.monotonic() + 2.0
        while svc.calls < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert svc.calls == 2           # the wire really duplicated
        assert nc.injected_count("dup") >= 1
    finally:
        client.close()
        server.stop()


def test_netchaos_latency_delays_without_blocking_loop():
    """lat=60 delays the round trip — but a SECOND connection's traffic
    must not stall behind it: the delay is a call_later chain on the
    chaotic link, not a sleep on the shared loop."""
    svc = _EchoSvc()
    server, client = _async_pair(svc, timeout=5.0,
                                 chaos_roles=("t", "svc"))
    clean = AsyncClient(server.addr, timeout=5.0)   # no chaos role
    try:
        nc.activate("t>svc=lat=120")
        done = {}

        def slow():
            t0 = time.monotonic()
            out = client.call("ac_echo", v=1)
            done["slow"] = (time.monotonic() - t0, out["v"])

        th = threading.Thread(target=slow)
        th.start()
        time.sleep(0.01)                # slow call is now in flight
        t0 = time.monotonic()
        assert clean.call("ac_echo", v=2)["v"] == 2
        clean_elapsed = time.monotonic() - t0
        th.join(timeout=5.0)
        assert done["slow"][0] >= 0.110 and done["slow"][1] == 1
        # the clean link did not pay the chaotic link's delay
        assert clean_elapsed < 0.110
    finally:
        client.close()
        clean.close()
        server.stop()


# ---------------------------------------------------------------------------
# mixed-cluster interop via the hello async_core capability bit
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("daemon_core", ["0", "1"])
def test_mixed_cluster_hello_bit(monkeypatch, daemon_core):
    """Daemon processes inherit RAY_TPU_ASYNC_CORE from the driver's
    environment; the driver's own core is pinned the opposite way via
    _system_config (which wins locally but is NOT inherited). Both
    mixes must execute tasks — frames are byte-identical across cores —
    and the hello bit must report the daemon's actual core."""
    import ray_tpu
    monkeypatch.setenv("RAY_TPU_ASYNC_CORE", daemon_core)
    driver_async = daemon_core == "0"   # always the opposite core
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      cluster="daemons",
                      _system_config={"async_core": driver_async})
    try:
        handles = list(rt.cluster_backend.daemons.values())
        assert len(handles) == 1
        assert handles[0]._async_core_remote is (daemon_core == "1")
        want = "async" if daemon_core == "1" else "threaded"
        peers = rt.cluster_backend.describe_peers()
        assert any(f"core={want}" in line for line in peers)

        @ray_tpu.remote
        def f(x):
            return x + 1

        assert ray_tpu.get([f.remote(i) for i in range(8)],
                           timeout=60) == list(range(1, 9))
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# loop-lag gauge + slow-callback watchdog
# ---------------------------------------------------------------------------

def test_loop_lag_gauge_and_watchdog(monkeypatch):
    """Blocking the loop past loop_slow_callback_s must surface in the
    lag gauge and bump the slow-callback counter — even without asyncio
    debug mode (the always-on probe leg of the watchdog)."""
    from ray_tpu._private import config
    from ray_tpu.util import metrics
    monkeypatch.setenv("RAY_TPU_LOOP_LAG_PROBE_S", "0.02")
    monkeypatch.setenv("RAY_TPU_LOOP_SLOW_CALLBACK_S", "0.01")
    config.reset()
    eventloop.shutdown_for_tests()      # fresh loop with probe config
    try:
        eventloop.set_proc_label("lagtest")
        loop = eventloop.get_loop()
        loop.call_soon_threadsafe(time.sleep, 0.1)  # stall the loop
        deadline = time.monotonic() + 5.0
        hits = 0.0
        while time.monotonic() < deadline:
            counter = metrics.registry().get(
                "ray_tpu_event_loop_slow_callbacks_total")
            if counter is not None:
                hits = sum(v for k, v in counter.samples()
                           if ("proc", "lagtest") in k)
                if hits >= 1:
                    break
            time.sleep(0.02)
        assert hits >= 1, "stalled loop never hit the watchdog counter"
        gauge = metrics.registry().get("ray_tpu_event_loop_lag_seconds")
        assert gauge is not None and any(
            ("proc", "lagtest") in k for k, _ in gauge.samples())
    finally:
        eventloop.shutdown_for_tests()  # next get_loop: default config
        monkeypatch.delenv("RAY_TPU_LOOP_LAG_PROBE_S")
        monkeypatch.delenv("RAY_TPU_LOOP_SLOW_CALLBACK_S")
        config.reset()
        eventloop.set_proc_label("")


# ---------------------------------------------------------------------------
# metric-registry pollution pin (the order-dependent tenancy failures)
# ---------------------------------------------------------------------------

def test_metric_write_survives_registry_clear():
    """A module that cached a Metric instance before some test called
    clear_registry() must not write into the void: the next write
    re-attaches the instance to the live registry (Metric._reattach).
    This was the root cause of the order-dependent tenancy failures —
    tenancy's cached admission counter went dark after an
    observability test cleared the registry."""
    from ray_tpu.util import metrics
    c = metrics.Counter("pollution_pin_total", "pin")
    c.inc(1)
    metrics.clear_registry()            # orphans the cached instance
    try:
        c.inc(2)                        # must re-attach, not vanish
        assert "pollution_pin_total 3.0" in metrics.prometheus_text()
    finally:
        metrics.clear_registry()


@pytest.mark.slow
def test_polluting_pair_back_to_back():
    """The original failure order, pinned end to end: an observability
    test that clears the registry, then the tenancy test asserting
    ray_tpu_admission_total appears in the exposition — back to back
    in one fresh interpreter, no other tests in between."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "pytest", "-q",
         "-p", "no:cacheprovider", "-p", "no:randomly",
         "tests/test_observability.py::test_prometheus_label_escaping",
         "tests/test_tenancy.py::"
         "test_queued_is_delayed_never_lost_and_resumes"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
