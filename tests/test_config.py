"""Central config/flag system (reference: ray_config_def.h RAY_CONFIG
table + _system_config plumbing via ray.init)."""

import pytest

import ray_tpu
from ray_tpu._private.config import FLAGS, Config, cfg


def test_flag_table_defaults():
    c = Config()
    assert c.pull_chunk == 4 << 20
    assert c.memory_monitor is True
    assert c.worker_killing_policy == "retriable_fifo"
    d = c.describe()
    assert set(d) == set(FLAGS)
    assert all(v["source"] == "default" or v["source"].startswith("env:")
               for v in d.values())


def test_env_override_and_bool_parsing(monkeypatch):
    monkeypatch.setenv("RAY_TPU_PULL_CHUNK", str(1 << 20))
    monkeypatch.setenv("RAY_TPU_MEMORY_MONITOR", "false")
    c = Config()
    assert c.pull_chunk == 1 << 20
    assert c.memory_monitor is False
    assert c.describe()["pull_chunk"]["source"] == "env:RAY_TPU_PULL_CHUNK"


def test_system_config_wins_over_env(monkeypatch):
    monkeypatch.setenv("RAY_TPU_PULL_CHUNK", str(1 << 20))
    c = Config({"pull_chunk": 2 << 20})
    assert c.pull_chunk == 2 << 20
    assert c.describe()["pull_chunk"]["source"] == "_system_config"


def test_unknown_flag_rejected():
    with pytest.raises(ValueError, match="unknown _system_config"):
        Config({"not_a_flag": 1})


def test_init_applies_and_shutdown_resets():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      _system_config={"pull_chunk": 1 << 20})
    try:
        assert cfg().pull_chunk == 1 << 20
    finally:
        ray_tpu.shutdown()
    assert cfg().pull_chunk == 4 << 20


def test_unknown_attr_raises():
    with pytest.raises(AttributeError):
        Config().definitely_not_a_flag
