"""multiprocessing Pool shim, serve multiplexing, check_serialize."""

import pytest

import ray_tpu
from ray_tpu.util.multiprocessing import Pool


def _sq(x):
    return x * x


def test_pool_map(ray_start_regular):
    with Pool() as p:
        assert p.map(_sq, range(6)) == [0, 1, 4, 9, 16, 25]


def test_pool_starmap_apply_imap(ray_start_regular):
    with Pool() as p:
        assert p.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
        assert p.apply(_sq, (7,)) == 49
        r = p.apply_async(_sq, (8,))
        assert r.get(timeout=10) == 64
        assert list(p.imap(_sq, [1, 2, 3])) == [1, 4, 9]
        assert sorted(p.imap_unordered(_sq, [1, 2, 3])) == [1, 4, 9]


def test_serve_multiplexed(ray_start_regular):
    from ray_tpu import serve

    loads = []

    @serve.deployment
    class MuxModel:
        def __init__(self):
            self.get_model = serve.multiplexed(
                max_num_models_per_replica=2)(self._load)

        def _load(self, model_id: str):
            loads.append(model_id)
            return {"id": model_id}

        def __call__(self, model_id: str):
            model = self.get_model(model_id)
            return model["id"]

    try:
        handle = serve.run(MuxModel.bind())
        assert handle.remote("m1").result() == "m1"
        assert handle.remote("m2").result() == "m2"
        assert handle.remote("m1").result() == "m1"   # cached
        n_loads_before = handle.remote("m3").result()  # evicts LRU (m2)
        assert handle.remote("m2").result() == "m2"    # reloaded
    finally:
        serve.shutdown()


def test_inspect_serializability():
    from ray_tpu.util.check_serialize import inspect_serializability
    import threading

    ok, failures = inspect_serializability(lambda x: x + 1)
    assert ok and not failures

    lock = threading.Lock()

    def bad():
        return lock

    ok, failures = inspect_serializability(bad, name="bad")
    assert not ok
    assert any("lock" in f.name for f in failures)


def test_accelerator_detection_env(monkeypatch):
    from ray_tpu._private import accelerators as acc

    monkeypatch.setenv(acc.TPU_VISIBLE_CHIPS_ENV, "0,1,2,3")
    monkeypatch.setenv(acc.TPU_ACCELERATOR_TYPE_ENV, "v5p-8")
    assert acc.detect_tpu_chips() == 4
    res = acc.accelerator_resources()
    assert res["TPU"] == 4.0
    assert "accelerator_type:v5p-8" in res


def test_joblib_backend(ray_start_regular):
    from joblib import Parallel, delayed
    from ray_tpu.util.joblib_backend import register_ray

    register_ray()
    from joblib import parallel_backend
    with parallel_backend("ray_tpu"):
        out = Parallel(n_jobs=4)(delayed(lambda x: x * x)(i)
                                 for i in range(8))
    assert out == [i * i for i in range(8)]


def test_export_events(ray_start_regular):
    from ray_tpu._private.export_events import (get_export_logger,
                                                reset_export_logger)

    reset_export_logger()
    logger = get_export_logger()
    logger.emit("JOB", {"job_id": "j1", "state": "RUNNING"})
    logger.emit("JOB", {"job_id": "j1", "state": "FINISHED"})
    events = logger.read("JOB")
    assert [e["state"] for e in events] == ["RUNNING", "FINISHED"]
    assert all(e["event_type"] == "JOB" for e in events)
