"""Model layer: Llama forward/loss/sharded training, MLP."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import LlamaConfig, LlamaModel, MLPConfig, MLPModel
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.train.spmd import make_train_step, shard_batch


def _tiny():
    return LlamaConfig.debug(vocab_size=128, max_seq_len=64)


def test_llama_forward_shape_and_finite():
    cfg = _tiny()
    model = LlamaModel(cfg)
    params = model.init(jax.random.key(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_llama_loss_decreases_under_training():
    cfg = _tiny()
    model = LlamaModel(cfg)
    ts = make_train_step(model)
    params, opt = ts.init_fn(jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)
    targets = jnp.roll(tokens, -1, 1)
    losses = []
    for _ in range(10):
        params, opt, m = ts.step_fn(params, opt, (tokens, targets))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_llama_causality():
    """Changing a future token must not change past logits."""
    cfg = _tiny()
    model = LlamaModel(cfg)
    params = model.init(jax.random.key(0))
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(5)
    l1 = model.apply(params, t1)
    l2 = model.apply(params, t2)
    np.testing.assert_allclose(np.asarray(l1[0, :10]),
                               np.asarray(l2[0, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_llama_num_params_matches_tree():
    cfg = _tiny()
    model = LlamaModel(cfg)
    params = model.init(jax.random.key(0))
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert actual == cfg.num_params()


def test_llama_sharded_train_step_tp_sp_fsdp():
    """Full 8-device sharded step: tp=2, sp=2, fsdp=2."""
    spec = MeshSpec.auto(8, tp=2, sp=2, fsdp=2)
    mesh = build_mesh(spec, jax.devices()[:8])
    cfg = LlamaConfig(vocab_size=128, dim=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, ffn_dim=64, max_seq_len=64, remat=False)
    model = LlamaModel(cfg, mesh=mesh)
    ts = make_train_step(model, mesh=mesh)
    params, opt = ts.init_fn(jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 64)), jnp.int32)
    batch = shard_batch((tokens, jnp.roll(tokens, -1, 1)), ts)
    params, opt, m = ts.step_fn(params, opt, batch)
    assert np.isfinite(float(m["loss"]))
    # sharded == unsharded result (same seed, single step)
    model0 = LlamaModel(cfg)
    ts0 = make_train_step(model0)
    p0, o0 = ts0.init_fn(jax.random.key(0))
    _, _, m0 = ts0.step_fn(p0, o0, (tokens, jnp.roll(tokens, -1, 1)))
    np.testing.assert_allclose(float(m["loss"]), float(m0["loss"]),
                               rtol=2e-3)


def test_mlp_trains_to_fit_random_data():
    import optax
    model = MLPModel(MLPConfig(in_dim=16, hidden=(32,), num_classes=4))
    ts = make_train_step(model, optimizer=optax.adam(1e-2))
    params, opt = ts.init_fn(jax.random.key(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (64,)), jnp.int32)
    for _ in range(150):
        params, opt, m = ts.step_fn(params, opt, (x, y))
    assert float(model.accuracy(params, x, y)) > 0.9


def test_graft_entry_contract():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert np.all(np.isfinite(np.asarray(out)))
    ge.dryrun_multichip(8)


def test_llama_remat_policy_dots_matches_full():
    """remat_policy='dots' (save matmul outputs) must be numerically
    identical to full remat — it only changes what is recomputed."""
    import dataclasses

    import numpy as np

    from ray_tpu.models.llama import LlamaConfig, LlamaModel
    cfg_full = LlamaConfig.debug(vocab_size=128, max_seq_len=32)
    cfg_full = dataclasses.replace(cfg_full, remat=True)
    cfg_dots = dataclasses.replace(cfg_full, remat_policy="dots")
    m_full, m_dots = LlamaModel(cfg_full), LlamaModel(cfg_dots)
    params = m_full.init(jax.random.key(0))
    toks = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    tgts = jnp.roll(toks, -1, axis=1)
    gf = jax.grad(lambda p: m_full.loss(p, toks, tgts))(params)
    gd = jax.grad(lambda p: m_dots.loss(p, toks, tgts))(params)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
    with pytest.raises(ValueError, match="remat_policy"):
        dataclasses.replace(cfg_full, remat_policy="bogus")


def test_llama_attention_impl_parity():
    """ring / xla-blockwise / flash (custom tile sizes) must agree on
    the loss (f32 so near-ties cannot hide real divergence)."""
    import dataclasses

    from ray_tpu.models.llama import LlamaConfig, LlamaModel
    base = dataclasses.replace(
        LlamaConfig.debug(vocab_size=128, max_seq_len=64),
        dtype=jnp.float32)
    toks = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8] * 8], jnp.int32)
    tgts = jnp.roll(toks, -1, axis=1)
    losses = {}
    for impl in ("ring", "xla", "flash"):
        cfg = dataclasses.replace(base, attention_impl=impl,
                                  flash_block_q=32, flash_block_k=32)
        m = LlamaModel(cfg)
        p = m.init(jax.random.key(0))
        losses[impl] = float(m.loss(p, toks, tgts))
    assert abs(losses["xla"] - losses["ring"]) < 1e-4, losses
    assert abs(losses["flash"] - losses["ring"]) < 1e-3, losses
