"""Cluster-wide continuous profiling + the lock-contention
observatory: sampler units (collapse format, thread attribution,
burst vs continuous, prune monotonicity), the daemon's profile.flush
retry seam, MeteredLock wait/hold histograms, queue-dwell gauges, and
the 2-node federation path (daemon profiles at the head,
cluster_profile's merged speedscope view, lock/queue/arena metrics in
the federated exposition). See docs/observability.md "Profiling &
contention"."""

import json
import threading
import time
from collections import Counter

import pytest

from ray_tpu.util import profiling


# ---------------------------------------------------------------------------
# sampler units
# ---------------------------------------------------------------------------

def test_collapse_format_and_thread_attribution():
    """A burst attributes a parked thread's stack to its NAME, frames
    collapsed leaf-last as ``thread;file:func;...`` (FlameGraph
    format)."""
    stop = threading.Event()

    def _parked_beacon():
        stop.wait(10.0)

    t = threading.Thread(target=_parked_beacon, daemon=True,
                         name="prof-beacon")
    t.start()
    try:
        rec = profiling.burst_record("unit", duration_s=0.2, hz=50.0)
    finally:
        stop.set()
        t.join()
    assert rec["proc"] == "unit" and rec["mode"] == "burst"
    assert rec["samples"] > 0 and rec["counts"]
    beacon = [s for s in rec["counts"] if s.startswith("prof-beacon;")]
    assert beacon, f"no stack attributed to the beacon: {rec['counts']}"
    # leaf-last ordering: the parked function is the innermost frame
    assert any("test_profiling.py:_parked_beacon" in s.split(";")[-2]
               or "_parked_beacon" in s for s in beacon)
    for stack in rec["counts"]:
        for tok in stack.split(";")[1:]:
            assert ":" in tok or tok == profiling.PRUNED_STACK


def test_prune_caps_stacks_and_preserves_total_weight():
    counts = Counter({f"t;f.py:fn{i}": i + 1
                      for i in range(profiling.MAX_STACKS + 500)})
    total = sum(counts.values())
    profiling._prune(counts)
    assert len(counts) <= profiling.MAX_STACKS
    assert sum(counts.values()) == total    # weight folded, not lost
    assert counts[profiling.PRUNED_STACK] > 0
    # pruning again is monotonic: totals still preserved
    profiling._prune(counts)
    assert sum(counts.values()) == total


def test_continuous_sampler_snapshot_cumulative():
    s = profiling.ContinuousSampler("unit-cont", hz=100.0).start()
    try:
        deadline = time.monotonic() + 5.0
        snap = None
        while snap is None and time.monotonic() < deadline:
            snap = s.snapshot()
            time.sleep(0.02)
        assert snap is not None, "sampler never produced a snapshot"
        assert snap["mode"] == "continuous" and snap["hz"] == 100.0
        first_total = sum(snap["counts"].values())
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            snap2 = s.snapshot()
            if snap2["samples"] > snap["samples"]:
                break
            time.sleep(0.02)
        assert snap2["samples"] > snap["samples"]
        assert sum(snap2["counts"].values()) >= first_total  # cumulative
    finally:
        s.stop()


def test_process_sampler_gating_and_idempotence():
    profiling.stop_process_sampler()
    try:
        # hz <= 0 (the profiling_hz default) leaves sampling OFF
        assert profiling.start_process_sampler("unit", hz=0.0) is None
        assert profiling.process_profile() is None
        s1 = profiling.start_process_sampler("unit", hz=50.0)
        s2 = profiling.start_process_sampler("unit", hz=200.0)
        assert s1 is not None and s2 is s1      # idempotent
    finally:
        profiling.stop_process_sampler()
    assert profiling.process_profile() is None


def test_ingest_profile_tolerant_and_node_profile_merges():
    before = {r["proc"] for r in profiling.remote_profiles()}
    # bad payloads are dropped silently (result hot path)
    profiling.ingest_profile(None)
    profiling.ingest_profile({"counts": {}})            # no proc
    profiling.ingest_profile({"proc": "w", "counts": 3})  # bad counts
    assert {r["proc"] for r in profiling.remote_profiles()} == before
    rec = {"proc": "worker:test-ingest", "pid": 1, "mode": "continuous",
           "hz": 5.0, "samples": 3, "counts": {"t;a.py:f": 3}}
    profiling.ingest_profile(rec)
    try:
        node = profiling.node_profile()
        assert node is not None
        mine = [r for r in node["procs"]
                if r["proc"] == "worker:test-ingest"]
        assert mine == [rec]
        # a later record REPLACES the earlier one (cumulative shipping)
        rec2 = dict(rec, samples=9, counts={"t;a.py:f": 9})
        profiling.ingest_profile(rec2)
        assert [r for r in profiling.remote_profiles()
                if r["proc"] == "worker:test-ingest"] == [rec2]
    finally:
        with profiling._REMOTE_LOCK:
            profiling._REMOTE.pop("worker:test-ingest", None)


def test_speedscope_document_and_merged_collapsed():
    records = [
        {"proc": "driver", "pid": 1, "mode": "burst", "samples": 4,
         "counts": {"main;a.py:f;a.py:g": 3, "main;a.py:f": 1}},
        {"proc": "worker:2", "pid": 2, "mode": "continuous",
         "samples": 2, "counts": {"main;a.py:f": 2}},
    ]
    doc = profiling.speedscope_document(records)
    assert doc["$schema"].startswith("https://www.speedscope.app/")
    assert len(doc["profiles"]) == 2        # one lane per process
    names = {p["name"] for p in doc["profiles"]}
    assert any(n.startswith("driver (burst") for n in names)
    assert any(n.startswith("worker:2 (continuous") for n in names)
    frames = [f["name"] for f in doc["shared"]["frames"]]
    assert frames.count("a.py:f") == 1      # shared frame table dedupes
    for p in doc["profiles"]:
        assert p["type"] == "sampled"
        assert len(p["samples"]) == len(p["weights"])
        assert p["endValue"] == sum(p["weights"])
        for sample in p["samples"]:
            assert all(0 <= i < len(frames) for i in sample)
    # heaviest stack first per lane; collapsed lines carry the proc
    lines = profiling.merged_collapsed(records).splitlines()
    assert lines[0] == "driver;main;a.py:f;a.py:g 3"
    assert "worker:2;main;a.py:f 2" in lines


# ---------------------------------------------------------------------------
# profile.flush seam (unit: deterministic drop -> retry discipline)
# ---------------------------------------------------------------------------

def test_gate_profile_flush_drop_then_retry():
    """The daemon's heartbeat gate: off-cadence -> None; a drop arm
    nulls the payload (so the caller's push stamp never advances and
    the FULL cumulative snapshot re-sends next period); disarmed, the
    same snapshot flows again."""
    from ray_tpu._private import failpoints as fp
    from ray_tpu._private.daemon import _gate_profile_flush

    rec = {"proc": "worker:gate-test", "pid": 1, "mode": "continuous",
           "hz": 5.0, "samples": 1, "counts": {"t;a.py:f": 1}}
    profiling.ingest_profile(rec)
    try:
        now = time.monotonic()
        assert _gate_profile_flush(last_push=now, now=now) is None
        payload = _gate_profile_flush(last_push=now - 10.0, now=now)
        assert payload is not None
        assert any(r["proc"] == "worker:gate-test"
                   for r in payload["procs"])
        fp.activate("profile.flush=drop:p=1")
        try:
            assert _gate_profile_flush(last_push=now - 10.0,
                                       now=now) is None
            assert fp.fire_count("profile.flush") >= 1
        finally:
            fp.reset()
        retried = _gate_profile_flush(last_push=now - 10.0, now=now)
        assert retried is not None      # same cumulative data, re-sent
        assert any(r["proc"] == "worker:gate-test"
                   for r in retried["procs"])
    finally:
        with profiling._REMOTE_LOCK:
            profiling._REMOTE.pop("worker:gate-test", None)


# ---------------------------------------------------------------------------
# lock-contention observatory (units)
# ---------------------------------------------------------------------------

@pytest.fixture
def _fresh_config():
    """Env flips in these tests must not leak through the cfg() cache
    (a cached Config resolved while RAY_TPU_LOCK_METRICS was set would
    keep metering on after monkeypatch undoes the env)."""
    from ray_tpu._private import config as _config
    _config.reset()
    yield
    _config.reset()


def test_metered_lock_wait_hold_and_contended(monkeypatch, _fresh_config):
    monkeypatch.setenv("RAY_TPU_LOCK_METRICS", "1")
    monkeypatch.delenv("RAY_TPU_LOCK_SANITIZER", raising=False)
    from ray_tpu._private import lock_sanitizer as ls

    lock = ls.tracked_lock("unit.meter", reentrant=False)
    assert isinstance(lock, ls.MeteredLock)
    with lock:
        time.sleep(0.002)               # measurable hold
    held = threading.Event()

    def holder():
        with lock:
            held.set()
            time.sleep(0.05)

    t = threading.Thread(target=holder)
    t.start()
    held.wait(5.0)
    with lock:                          # contended: waits ~50ms
        pass
    t.join()
    assert lock.contended >= 1
    assert lock.wait_total >= 2 and lock.hold_total >= 2
    assert lock.wait_sum > 0.01         # the blocked acquire's wait

    entries = {e["name"]: e for e in ls.lock_metric_entries()}
    wait = entries["ray_tpu_lock_wait_seconds"]
    assert wait["kind"] == "histogram"
    assert wait["boundaries"] == list(ls.METER_BOUNDS)
    rows = {tuple(map(tuple, labels)): (counts, s, total)
            for labels, counts, s, total in wait["hist"]}
    counts, wsum, total = rows[(("lock", "unit.meter"),)]
    assert sum(counts) == total and wsum > 0.01
    assert "ray_tpu_lock_hold_seconds" in entries
    cont = entries["ray_tpu_lock_contended_total"]
    assert any(labels == [["lock", "unit.meter"]] and v >= 1
               for labels, v in cont["samples"])
    # the entries ride the shared registry snapshot (federation path)
    from ray_tpu.util.metrics import export_snapshot
    assert any(e["name"] == "ray_tpu_lock_wait_seconds"
               for e in export_snapshot())


def test_metered_rlock_reentrant_measures_outermost(monkeypatch,
                                                    _fresh_config):
    monkeypatch.setenv("RAY_TPU_LOCK_METRICS", "1")
    monkeypatch.delenv("RAY_TPU_LOCK_SANITIZER", raising=False)
    from ray_tpu._private import lock_sanitizer as ls

    lock = ls.tracked_lock("unit.meter.rlock", reentrant=True)
    assert isinstance(lock, ls.MeteredLock)
    with lock:
        with lock:                      # inner acquire: depth only
            pass
        time.sleep(0.002)
    # one outermost acquire -> exactly one wait + one hold observation
    assert lock.wait_total == 1 and lock.hold_total == 1
    assert lock.hold_sum >= 0.002


def test_tracked_lock_stays_plain_without_opt_in(monkeypatch,
                                                 _fresh_config):
    monkeypatch.delenv("RAY_TPU_LOCK_METRICS", raising=False)
    monkeypatch.delenv("RAY_TPU_LOCK_SANITIZER", raising=False)
    from ray_tpu._private import lock_sanitizer as ls
    assert type(ls.tracked_lock("unit.plain", reentrant=False)) \
        is type(threading.Lock())


def test_queue_dwell_gauge_in_snapshot():
    from ray_tpu.util import metrics
    metrics.note_queue_dwell("unit.test_queue", 0.25)
    entries = [e for e in metrics.export_snapshot()
               if e["name"] == "ray_tpu_queue_dwell_seconds"]
    assert len(entries) == 1 and entries[0]["kind"] == "gauge"
    samples = {tuple(map(tuple, labels)): v
               for labels, v in entries[0]["samples"]}
    assert samples[(("queue", "unit.test_queue"),)] == 0.25


# ---------------------------------------------------------------------------
# 2-node federation e2e: daemon profiles at the head over heartbeats
# (surviving the profile.flush drop arm), cluster_profile's merged
# speedscope view, lock/queue/arena metrics in the federated /metrics
# ---------------------------------------------------------------------------

@pytest.fixture
def profiled_daemon_cluster(monkeypatch):
    import ray_tpu
    monkeypatch.setenv("RAY_TPU_PROFILING_HZ", "20")
    monkeypatch.setenv("RAY_TPU_LOCK_METRICS", "1")
    # each daemon drops its first 2 profile flushes: federation below
    # only passes because the un-advanced push stamp re-sends them
    monkeypatch.setenv("RAY_TPU_FAILPOINTS", "profile.flush=drop:max=2")
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()
    profiling.stop_process_sampler()


def _run_batched_workload(n=40):
    import ray_tpu

    @ray_tpu.remote(num_returns=2)
    def duo(i):
        return i, i + 1

    refs = [duo.remote(i) for i in range(n)]
    ray_tpu.get([r for ab in refs for r in ab])


def test_profiles_federate_to_head_and_merged_speedscope(
        profiled_daemon_cluster, tmp_path):
    rt = profiled_daemon_cluster
    backend = rt.cluster_backend
    node_hexes = {h.node_id.hex() for h in backend.daemons.values()}

    # 1) continuous profiles from BOTH daemons reach the head on
    # heartbeats — despite each daemon dropping its first 2 flushes
    deadline = time.monotonic() + 30.0
    nodes = {}
    while time.monotonic() < deadline:
        _run_batched_workload(10)
        fed = backend.head.profile_get()
        nodes = {nid: p for nid, p in (fed.get("nodes") or {}).items()
                 if p and p.get("procs")}
        if set(nodes) >= node_hexes:
            break
        time.sleep(0.3)
    assert set(nodes) >= node_hexes, (
        f"head saw profiles from {set(nodes)}, wanted {node_hexes}")
    for nid in node_hexes:
        procs = {r["proc"] for r in nodes[nid]["procs"]}
        assert any(p.startswith("daemon:") for p in procs), procs
    # driver sampler runs too (profiling_hz picked up at init)
    assert profiling.process_profile() is not None

    # 2) the `ray-tpu profile --all` backend: burst fan-out + merge —
    # lanes for driver, both daemons, and at least one worker
    from ray_tpu.util.state import cluster_profile
    out_path = str(tmp_path / "prof.json")
    out = cluster_profile(duration_s=0.5, path=out_path)
    procs = {r["proc"] for r in out["records"]}
    assert "driver" in procs
    assert sum(1 for p in procs if p.startswith("daemon:")) >= 2, procs
    assert any(p.startswith("worker:") for p in procs), procs
    doc = out["speedscope"]
    assert len(doc["profiles"]) == len(out["records"]) >= 4
    assert doc["shared"]["frames"]
    with open(out_path) as f:
        on_disk = json.load(f)
    assert on_disk["$schema"] == doc["$schema"]
    assert out["collapsed"]


def test_contention_and_object_plane_metrics_federate(
        profiled_daemon_cluster):
    """Acceptance: ray_tpu_lock_wait_seconds appears in the federated
    exposition with a tracked-lock label under a queued burst; queue
    dwell + arena-slot-ref + push gauges federate alongside."""
    from ray_tpu.util.metrics import cluster_prometheus_text

    rt = profiled_daemon_cluster
    assert rt is not None
    wanted = ("ray_tpu_lock_wait_seconds_bucket",
              "ray_tpu_lock_hold_seconds_bucket",
              "ray_tpu_queue_dwell_seconds",
              "ray_tpu_arena_slot_refs",
              "ray_tpu_push_inflight")
    deadline = time.monotonic() + 30.0
    text = ""
    while time.monotonic() < deadline:
        _run_batched_workload(20)
        text = cluster_prometheus_text()
        if all(w in text for w in wanted):
            break
        time.sleep(0.3)
    for w in wanted:
        assert w in text, f"{w} missing from the federated exposition"
    wait_lines = [ln for ln in text.splitlines()
                  if ln.startswith("ray_tpu_lock_wait_seconds_bucket")
                  and 'lock="' in ln]
    assert wait_lines, "no tracked-lock label on the wait histogram"
    dwell = [ln for ln in text.splitlines()
             if ln.startswith("ray_tpu_queue_dwell_seconds")
             and 'queue="' in ln]
    assert any('queue="rpc.lane"' in ln or 'queue="node.dispatch"' in ln
               or 'queue="daemon.reply_pump"' in ln for ln in dwell)
    assert 'state="held"' in text and 'state="refs"' in text
