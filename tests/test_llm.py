"""LLM engine: KV-cache correctness, continuous batching, serving."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import (ByteTokenizer, ContinuousBatchingEngine, LLMConfig,
                         SamplingParams, build_llm_app)
from ray_tpu.models.llama import LlamaConfig, LlamaModel


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.debug(vocab_size=512, max_seq_len=128)
    model = LlamaModel(cfg)
    params = model.init(jax.random.key(0))
    return model, params


def test_kv_cache_matches_full_forward(tiny_model):
    """Greedy decode with the KV cache must equal argmax of the full
    (uncached) forward at every step."""
    model, params = tiny_model
    prompt = [1, 7, 42, 99, 3]
    engine = ContinuousBatchingEngine(model, params, max_slots=2,
                                      max_seq=64,
                                      prefill_buckets=(8, 16))
    req = engine.generate([prompt],
                          SamplingParams(max_tokens=8))[0]
    assert len(req.output) == 8

    # uncached greedy reference
    seq = list(prompt)
    expect = []
    for _ in range(8):
        logits = model.apply(params, jnp.asarray([seq], jnp.int32))
        tok = int(jnp.argmax(logits[0, -1]))
        expect.append(tok)
        seq.append(tok)
    assert req.output == expect


def test_continuous_batching_multiple_requests(tiny_model):
    model, params = tiny_model
    engine = ContinuousBatchingEngine(model, params, max_slots=4,
                                      max_seq=64, prefill_buckets=(8, 16))
    prompts = [[i + 1, i + 2, i + 3] for i in range(6)]  # > max_slots
    reqs = engine.generate(prompts, SamplingParams(max_tokens=5))
    assert all(len(r.output) == 5 for r in reqs)
    assert all(r.finish_reason == "length" for r in reqs)
    assert engine.stats["requests"] == 6
    # batched decode: fewer decode steps than 6 requests x 4 tokens
    assert engine.stats["decode_steps"] < 6 * 5


def test_batched_results_match_single_results(tiny_model):
    """Continuous batching must not change greedy outputs."""
    model, params = tiny_model
    prompts = [[5, 6, 7], [200, 201], [50, 51, 52, 53]]
    solo = []
    for p in prompts:
        eng = ContinuousBatchingEngine(model, params, max_slots=1,
                                       max_seq=64, prefill_buckets=(8,))
        solo.append(eng.generate([p], SamplingParams(max_tokens=6))[0]
                    .output)
    eng = ContinuousBatchingEngine(model, params, max_slots=4,
                                   max_seq=64, prefill_buckets=(8,))
    batched = [r.output for r in
               eng.generate(prompts, SamplingParams(max_tokens=6))]
    assert batched == solo


def test_streaming_and_ttft(tiny_model):
    model, params = tiny_model
    engine = ContinuousBatchingEngine(model, params, max_slots=2,
                                      max_seq=64, prefill_buckets=(8,))
    req = engine.submit([1, 2, 3], SamplingParams(max_tokens=4))
    got = []
    t = threading.Thread(target=lambda: got.extend(req.iter_tokens()))
    t.start()
    while engine.has_work():
        engine.step()
    t.join(timeout=10)
    assert got == req.output
    assert req.ttft_s is not None and req.ttft_s >= 0


def test_temperature_sampling_differs(tiny_model):
    model, params = tiny_model
    engine = ContinuousBatchingEngine(model, params, max_slots=2,
                                      max_seq=64, prefill_buckets=(8,))
    r1 = engine.generate([[1, 2, 3]],
                         SamplingParams(max_tokens=16,
                                        temperature=2.0))[0]
    r2 = engine.generate([[1, 2, 3]],
                         SamplingParams(max_tokens=16,
                                        temperature=2.0))[0]
    assert r1.output != r2.output  # different rng draws


def test_stop_tokens(tiny_model):
    model, params = tiny_model
    engine = ContinuousBatchingEngine(model, params, max_slots=1,
                                      max_seq=64, prefill_buckets=(8,))
    probe = engine.generate([[9, 8, 7]],
                            SamplingParams(max_tokens=6))[0]
    stop_tok = probe.output[2]
    req = engine.generate([[9, 8, 7]],
                          SamplingParams(max_tokens=6,
                                         stop_token_ids=(stop_tok,)))[0]
    assert req.finish_reason == "stop"
    assert req.output[-1] == stop_tok
    assert len(req.output) == 3


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    ids = tok.encode("hello TPU")
    assert ids[0] == ByteTokenizer.BOS
    assert tok.decode(ids) == "hello TPU"


def test_llm_serve_app(ray_start_regular):
    from ray_tpu import serve
    try:
        app = build_llm_app(LLMConfig(max_slots=2, max_seq=128))
        handle = serve.run(app)
        out = handle.remote({"prompt": "hi", "max_tokens": 4}).result(
            timeout=120)
        assert out["usage"]["completion_tokens"] == 4
        assert out["finish_reason"] == "length"
        assert isinstance(out["text"], str)
        stats = handle.stats.remote().result()
        assert stats["requests"] == 1
    finally:
        serve.shutdown()


def test_prefix_router_affinity_and_balance():
    from ray_tpu.llm.prefix_router import PrefixAwareRouter

    router = PrefixAwareRouter(4, block_size=4)
    prompt_a = list(range(100, 116))
    r1 = router.route(prompt_a)
    router.on_finished(r1)
    # same prefix → same replica
    assert router.route(prompt_a + [1, 2]) == r1
    router.on_finished(r1)
    # distinct prompts spread across replicas
    seen = set()
    for i in range(40):
        prompt = [i * 1000 + j for j in range(16)]
        r = router.route(prompt)
        seen.add(r)
        router.on_finished(r)
    assert len(seen) >= 3


def test_prefill_decode_disagg_matches_colocated(tiny_model, ray_start_regular):
    """1p1d disaggregated serving produces the same greedy tokens as a
    colocated engine."""
    from ray_tpu import serve
    from ray_tpu.llm.disagg import build_pd_disagg_app
    from ray_tpu.llm.serving import LLMConfig

    model, params = tiny_model
    prompt_ids = [1, 7, 42, 99, 3]
    colocated = ContinuousBatchingEngine(model, params, max_slots=1,
                                         max_seq=128,
                                         prefill_buckets=(8,))
    expect = colocated.generate([prompt_ids],
                                SamplingParams(max_tokens=6))[0].output

    try:
        app = build_pd_disagg_app(LLMConfig(max_slots=2, max_seq=128))
        handle = serve.run(app)
        out = handle.remote({"prompt": prompt_ids,
                             "max_tokens": 6}).result(timeout=300)
        assert out["token_ids"] == expect
        assert out["finish_reason"] == "length"
    finally:
        serve.shutdown()
