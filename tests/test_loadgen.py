"""loadgen: arrival determinism, percentile/goodput math, report
schema, open-loop semantics, and the CLI against a debug-model app."""

import json
import os
import random
import subprocess
import sys
import time

import pytest

from ray_tpu.loadgen import (SLO, LatencyRecorder, LengthSampler,
                             LoadSpec, RequestRecord, arrival_times,
                             build_payloads, percentile, run_load)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# arrival schedules + length distributions
# ---------------------------------------------------------------------------

def test_poisson_schedule_deterministic_for_seed():
    a = arrival_times("poisson", 50, 4, seed=7)
    assert a == arrival_times("poisson", 50, 4, seed=7)
    assert a != arrival_times("poisson", 50, 4, seed=8)
    assert a == sorted(a)
    assert all(0 <= t < 4 for t in a)
    # E[n] = rate * duration = 200, sd ~14: loose 4-sigma bounds
    assert 140 < len(a) < 260


def test_constant_schedule_exact_spacing():
    assert arrival_times("constant", 10, 1.0) == [
        i / 10 for i in range(10)]


def test_arrival_validation():
    with pytest.raises(ValueError, match="unknown arrival"):
        arrival_times("bursty", 1, 1)
    with pytest.raises(ValueError, match="rate"):
        arrival_times("poisson", 0, 1)
    with pytest.raises(ValueError, match="duration"):
        arrival_times("poisson", 1, 0)


def test_length_sampler_forms():
    r = random.Random(0)
    assert LengthSampler.parse(32).sample(r) == 32
    assert LengthSampler.parse("16").sample(r) == 16
    uni = LengthSampler.parse("uniform:4:9")
    assert {uni.sample(r) for _ in range(300)} == set(range(4, 10))
    lgn = LengthSampler.parse("lognormal:64:0.5")
    vals = sorted(lgn.sample(r) for _ in range(301))
    assert all(v >= 1 for v in vals)
    assert 40 < vals[150] < 100          # median ~64
    with pytest.raises(ValueError):
        LengthSampler.parse("uniform:9:4")
    with pytest.raises(ValueError):
        LengthSampler.parse("zipf:1:2")


def test_payloads_deterministic_with_shared_prefix():
    spec = LoadSpec(prompt_len="uniform:4:8", output_len=5,
                    prefix_len=6, seed=9)
    a = build_payloads(spec, 20)
    assert a == build_payloads(spec, 20)
    prefix = a[0]["prompt"][:6]
    assert all(p["prompt"][:6] == prefix for p in a)
    assert all(4 <= len(p["prompt"]) - 6 <= 8 for p in a)
    assert all(p["max_tokens"] == 5 for p in a)


# ---------------------------------------------------------------------------
# percentile / goodput math (hand-checkable synthetic trace)
# ---------------------------------------------------------------------------

def test_percentile_nearest_rank():
    vals = [float(i) for i in range(1, 11)]
    assert percentile(vals, 50) == 5.0
    assert percentile(vals, 90) == 9.0
    assert percentile(vals, 99) == 10.0
    assert percentile(vals, 100) == 10.0
    assert percentile([3.0], 99) == 3.0
    assert percentile([], 50) == 0.0


def test_recorder_summary_math_on_synthetic_trace():
    rec = LatencyRecorder()
    # request i (1..4): ttft = 0.1*i, e2e = 0.2*i, 3 output tokens
    # => tpot = (e2e - ttft) / 2 = 0.05*i
    for i in range(1, 5):
        rec.add(RequestRecord(scheduled_at=0.0, sent_at=1.0,
                              first_token_at=1.0 + i * 0.1,
                              finished_at=1.0 + i * 0.2,
                              output_tokens=3))
    rec.add(RequestRecord(scheduled_at=0.0, sent_at=1.0, error="boom"))
    rep = rec.summary(slo=SLO(ttft_s=0.25, e2e_s=1.0), wall_s=2.0)
    assert rep["requests"] == {"total": 5, "completed": 4, "errors": 1}
    assert rep["requests_per_second"] == 2.0
    assert rep["output_tokens"] == 12
    assert rep["ttft_s"]["p50"] == pytest.approx(0.2)
    assert rep["ttft_s"]["p99"] == pytest.approx(0.4)
    assert rep["e2e_s"]["max"] == pytest.approx(0.8)
    assert rep["tpot_s"]["p50"] == pytest.approx(0.1)
    good = rep["goodput"]
    assert good["completed_within_slo"] == 2     # ttft 0.1, 0.2 pass
    assert good["fraction"] == 0.5
    assert good["requests_per_second"] == 1.0
    assert rep["error_samples"] == ["boom"]


def test_slo_unbounded_dimensions():
    ok = RequestRecord(scheduled_at=0, sent_at=0, first_token_at=5.0,
                       finished_at=9.0, output_tokens=1)
    assert SLO().met_by(ok)                      # no bounds: any done
    assert not SLO(e2e_s=1.0).met_by(ok)
    assert SLO(e2e_s=10.0).met_by(ok)
    err = RequestRecord(scheduled_at=0, sent_at=0, error="x")
    assert not SLO().met_by(err)                 # errors never count


# ---------------------------------------------------------------------------
# run_load: report schema + open-loop semantics (no cluster needed)
# ---------------------------------------------------------------------------

def test_run_load_report_schema_and_open_loop_lateness():
    """One slow client vs a 100/s constant schedule: open loop keeps
    the arrivals on schedule and books the lag as queue time."""

    def slow_target(payload, rec, t0):
        time.sleep(0.05)
        now = time.perf_counter() - t0
        rec.first_token_at = now
        rec.finished_at = now
        rec.output_tokens = 1

    spec = LoadSpec(rate=100, duration_s=0.2, clients=1,
                    arrival="constant", seed=0, slo=SLO(ttft_s=5.0))
    rep = run_load(slow_target, spec)
    for key in ("requests", "wall_s", "requests_per_second", "ttft_s",
                "tpot_s", "e2e_s", "queue_s", "goodput", "spec",
                "target", "scheduled_requests"):
        assert key in rep, key
    assert rep["scheduled_requests"] == 20
    assert rep["requests"]["completed"] == 20
    # 20 requests x 50ms serial vs a 0.2s window: the tail waited
    assert rep["queue_s"]["max"] > 0.5
    json.dumps(rep)          # JSON-serializable end to end


def test_run_load_records_target_errors():
    def flaky(payload, rec, t0):
        raise RuntimeError("nope")

    spec = LoadSpec(rate=50, duration_s=0.1, clients=2,
                    arrival="constant", seed=0)
    rep = run_load(flaky, spec)
    assert rep["requests"]["total"] == 5
    assert rep["requests"]["errors"] == 5
    assert rep["goodput"]["completed_within_slo"] == 0
    assert any("nope" in s for s in rep["error_samples"])


def test_handle_target_stream_enforces_timeout():
    """A wedged streaming replica must surface as a counted timeout
    error, not an eternal client hang: the per-request deadline bounds
    every chunk wait (regression — timeout_s was unary-only)."""
    from ray_tpu.loadgen import HandleTarget

    class _SlowGen:
        def next(self, timeout=None):
            # honors the per-chunk budget the target hands down, but
            # the stream never finishes
            time.sleep(min(timeout or 0.05, 0.05) + 0.02)
            return "tok"

    class _FakeHandle:
        def options(self, **kw):
            return self

        def remote(self, payload):
            return _SlowGen()

    target = HandleTarget(_FakeHandle(), stream=True, timeout_s=0.1)
    rec = RequestRecord(scheduled_at=0.0)
    with pytest.raises(TimeoutError):
        target({}, rec, time.perf_counter())

    spec = LoadSpec(rate=30, duration_s=0.1, clients=2,
                    arrival="constant", seed=0, timeout_s=0.1,
                    drain_timeout_s=5.0)
    rep = run_load(target, spec)
    assert rep["requests"]["errors"] == rep["requests"]["total"] > 0
    assert any("timeout" in s.lower() for s in rep["error_samples"])


# ---------------------------------------------------------------------------
# CLI smoke against a self-hosted debug-model serve app
# ---------------------------------------------------------------------------

def test_ray_tpu_cli_dispatches_loadgen_after_global_flags():
    """`ray-tpu --num-nodes 2 loadgen …` must reach the loadgen CLI
    (regression: only a LEADING `loadgen` token was passed through,
    and the dispatch dict had no entry — KeyError traceback)."""
    from ray_tpu.scripts import cli

    for argv in (["loadgen", "--help"],
                 ["--num-nodes", "2", "loadgen", "--help"]):
        with pytest.raises(SystemExit) as ei:
            cli.main(argv)
        assert ei.value.code == 0, argv


def test_cli_smoke_debug_app(tmp_path):
    out_json = tmp_path / "report.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.loadgen", "--clients", "4",
         "--rate", "15", "--duration", "1.5", "--prompt-len", "8",
         "--output-len", "4", "--replicas", "2", "--seed", "1",
         "--json", str(out_json)],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=REPO_ROOT)
    assert proc.returncode == 0, (proc.stdout[-500:], proc.stderr[-800:])
    assert "== loadgen report ==" in proc.stdout
    assert "goodput" in proc.stdout
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    rep = json.loads(lines[-1])
    assert rep["requests"]["completed"] > 0
    assert rep["requests"]["errors"] == 0
    assert rep["ttft_s"]["p50"] > 0
    assert rep["spec"]["clients"] == 4
    disk = json.loads(out_json.read_text())
    assert disk["requests"] == rep["requests"]
