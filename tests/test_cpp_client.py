"""Native C++ wire-protocol client (reference: the `cpp/` API tier —
a native program speaking to the cluster without Python in the loop)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def daemon_cluster():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


def test_cpp_client_kv_and_objects(daemon_cluster):
    from ray_tpu.cpp_client import CppClient

    rt = daemon_cluster
    backend = rt.cluster_backend
    head_addr = ("127.0.0.1", backend._head_port)
    daemon = list(backend.daemons.values())[0]

    # head KV: write from C++, read from Python and back
    cpp_head = CppClient(head_addr)
    try:
        cpp_head.kv_put(b"cpp-key", b"written-by-cpp")
        assert backend.head.kv_get(b"cpp-key") == b"written-by-cpp"
        backend.head.kv_put(b"py-key", b"written-by-python")
        assert cpp_head.kv_get(b"py-key") == b"written-by-python"
        assert cpp_head.kv_get(b"absent") is None
    finally:
        cpp_head.close()

    # daemon object plane: cross-language object round trip (incl. a
    # blob large enough to land in the C++ shm arena)
    cpp = CppClient(daemon.addr)
    try:
        assert cpp.ping() == daemon.proc.pid
        blob = np.arange(100_000, dtype=np.int64).tobytes()  # ~800KB
        cpp.put_object(b"cpp-oid", blob)
        assert daemon.get_object_blob(b"cpp-oid") == blob
        daemon.put_object_blob(b"py-oid", b"x" * 300_000)
        got = cpp.get_object(b"py-oid")
        assert got == b"x" * 300_000
        assert cpp.get_object(b"missing-oid") is None
    finally:
        cpp.close()
