"""Native C++ wire-protocol client (reference: the `cpp/` API tier —
a native program speaking to the cluster without Python in the loop)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def daemon_cluster():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


def test_cpp_client_kv_and_objects(daemon_cluster):
    from ray_tpu.cpp_client import CppClient

    rt = daemon_cluster
    backend = rt.cluster_backend
    head_addr = ("127.0.0.1", backend._head_port)
    daemon = list(backend.daemons.values())[0]

    # head KV: write from C++, read from Python and back
    cpp_head = CppClient(head_addr)
    try:
        cpp_head.kv_put(b"cpp-key", b"written-by-cpp")
        assert backend.head.kv_get(b"cpp-key") == b"written-by-cpp"
        backend.head.kv_put(b"py-key", b"written-by-python")
        assert cpp_head.kv_get(b"py-key") == b"written-by-python"
        assert cpp_head.kv_get(b"absent") is None
    finally:
        cpp_head.close()

    # daemon object plane: cross-language object round trip (incl. a
    # blob large enough to land in the C++ shm arena)
    cpp = CppClient(daemon.addr)
    try:
        assert cpp.ping() == daemon.proc.pid
        blob = np.arange(100_000, dtype=np.int64).tobytes()  # ~800KB
        cpp.put_object(b"cpp-oid", blob)
        assert daemon.get_object_blob(b"cpp-oid") == blob
        daemon.put_object_blob(b"py-oid", b"x" * 300_000)
        got = cpp.get_object(b"py-oid")
        assert got == b"x" * 300_000
        assert cpp.get_object(b"missing-oid") is None
    finally:
        cpp.close()


def test_cpp_submits_python_task(daemon_cluster):
    """C++ -> Python task: function exported by name, msgpack args in,
    msgpack result out, executed on a pooled worker process."""
    from ray_tpu import xlang
    from ray_tpu.cpp_client import CppClient

    # local def: cloudpickle serializes it by VALUE, so the daemon's
    # workers need no importable test module
    def _scale(x, factor):
        return [v * factor for v in x]

    def _iota(n):
        return list(range(n))

    rt = daemon_cluster
    daemon = list(rt.cluster_backend.daemons.values())[0]
    xlang.export_task("scale", _scale)
    xlang.export_task("iota", _iota)
    cpp = CppClient(daemon.addr)
    try:
        assert cpp.submit_task("scale", [1, 2, 3], 10) == [10, 20, 30]
        # >= 65536 elements exercises the array32 wire encoding
        big = cpp.submit_task("iota", 70_000)
        assert len(big) == 70_000 and big[-1] == 69_999
        with pytest.raises(RuntimeError, match="no exported"):
            cpp.submit_task("nope", 1)
    finally:
        cpp.close()


def test_cpp_drives_python_actor(daemon_cluster):
    """C++ -> Python actor: create by exported class name, stateful
    method calls in order, errors surfaced as app-level failures."""
    from ray_tpu import xlang
    from ray_tpu.cpp_client import CppClient

    class _Counter:
        """Exported to C++ by name; state lives on a pool worker."""

        def __init__(self, start):
            self.value = int(start)

        def add(self, n):
            self.value += int(n)
            return self.value

        def get(self):
            return self.value

        def boom(self):
            raise ValueError("counter exploded")

    rt = daemon_cluster
    daemon = list(rt.cluster_backend.daemons.values())[0]
    xlang.export_actor_class("Counter", _Counter)
    cpp = CppClient(daemon.addr)
    try:
        cpp.create_actor("Counter", "c1", 100)
        assert cpp.call_actor("c1", "add", 5) == 105
        assert cpp.call_actor("c1", "add", 7) == 112
        assert cpp.call_actor("c1", "get") == 112
        with pytest.raises(RuntimeError, match="exploded"):
            cpp.call_actor("c1", "boom")
        # state survives an error
        assert cpp.call_actor("c1", "get") == 112
        with pytest.raises(RuntimeError, match="no xlang actor"):
            cpp.call_actor("ghost", "get")
        with pytest.raises(RuntimeError, match="no exported"):
            cpp.create_actor("NoSuchClass", "c2")
    finally:
        cpp.close()
