"""Native C++ wire-protocol client (reference: the `cpp/` API tier —
a native program speaking to the cluster without Python in the loop)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def daemon_cluster():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


def test_cpp_client_kv_and_objects(daemon_cluster):
    from ray_tpu.cpp_client import CppClient

    rt = daemon_cluster
    backend = rt.cluster_backend
    head_addr = ("127.0.0.1", backend._head_port)
    daemon = list(backend.daemons.values())[0]

    # head KV: write from C++, read from Python and back
    cpp_head = CppClient(head_addr)
    try:
        cpp_head.kv_put(b"cpp-key", b"written-by-cpp")
        assert backend.head.kv_get(b"cpp-key") == b"written-by-cpp"
        backend.head.kv_put(b"py-key", b"written-by-python")
        assert cpp_head.kv_get(b"py-key") == b"written-by-python"
        assert cpp_head.kv_get(b"absent") is None
    finally:
        cpp_head.close()

    # daemon object plane: cross-language object round trip (incl. a
    # blob large enough to land in the C++ shm arena)
    cpp = CppClient(daemon.addr)
    try:
        assert cpp.ping() == daemon.proc.pid
        blob = np.arange(100_000, dtype=np.int64).tobytes()  # ~800KB
        cpp.put_object(b"cpp-oid", blob)
        assert daemon.get_object_blob(b"cpp-oid") == blob
        daemon.put_object_blob(b"py-oid", b"x" * 300_000)
        got = cpp.get_object(b"py-oid")
        assert got == b"x" * 300_000
        assert cpp.get_object(b"missing-oid") is None
    finally:
        cpp.close()


def test_cpp_submits_python_task(daemon_cluster):
    """C++ -> Python task: function exported by name, msgpack args in,
    msgpack result out, executed on a pooled worker process."""
    from ray_tpu import xlang
    from ray_tpu.cpp_client import CppClient

    # local def: cloudpickle serializes it by VALUE, so the daemon's
    # workers need no importable test module
    def _scale(x, factor):
        return [v * factor for v in x]

    def _iota(n):
        return list(range(n))

    rt = daemon_cluster
    daemon = list(rt.cluster_backend.daemons.values())[0]
    xlang.export_task("scale", _scale)
    xlang.export_task("iota", _iota)
    cpp = CppClient(daemon.addr)
    try:
        assert cpp.submit_task("scale", [1, 2, 3], 10) == [10, 20, 30]
        # >= 65536 elements exercises the array32 wire encoding
        big = cpp.submit_task("iota", 70_000)
        assert len(big) == 70_000 and big[-1] == 69_999
        with pytest.raises(RuntimeError, match="no exported"):
            cpp.submit_task("nope", 1)
    finally:
        cpp.close()


def test_cpp_drives_python_actor(daemon_cluster):
    """C++ -> Python actor: create by exported class name, stateful
    method calls in order, errors surfaced as app-level failures."""
    from ray_tpu import xlang
    from ray_tpu.cpp_client import CppClient

    class _Counter:
        """Exported to C++ by name; state lives on a pool worker."""

        def __init__(self, start):
            self.value = int(start)

        def add(self, n):
            self.value += int(n)
            return self.value

        def get(self):
            return self.value

        def boom(self):
            raise ValueError("counter exploded")

    rt = daemon_cluster
    daemon = list(rt.cluster_backend.daemons.values())[0]
    xlang.export_actor_class("Counter", _Counter)
    cpp = CppClient(daemon.addr)
    try:
        cpp.create_actor("Counter", "c1", 100)
        assert cpp.call_actor("c1", "add", 5) == 105
        assert cpp.call_actor("c1", "add", 7) == 112
        assert cpp.call_actor("c1", "get") == 112
        with pytest.raises(RuntimeError, match="exploded"):
            cpp.call_actor("c1", "boom")
        # state survives an error
        assert cpp.call_actor("c1", "get") == 112
        with pytest.raises(RuntimeError, match="no xlang actor"):
            cpp.call_actor("ghost", "get")
        with pytest.raises(RuntimeError, match="no exported"):
            cpp.create_actor("NoSuchClass", "c2")
    finally:
        cpp.close()


def test_embedded_cpp_api_program(daemon_cluster, tmp_path):
    """A NATIVE C++ program (native/api_demo.cc over the header API
    native/ray_tpu_api.h — the `cpp/include/ray/api.h` role) drives the
    cluster end-to-end: KV, objects, by-name tasks and a stateful named
    actor, with typed msgpack marshalling and no Python in its process."""
    import os
    import subprocess

    from ray_tpu import xlang

    rt = daemon_cluster
    backend = rt.cluster_backend

    def add(a, b):
        return a + b

    def greet(who):
        return f"hello {who}"

    class Counter:
        def __init__(self, start):
            self.n = start

        def inc(self, k):
            self.n += k
            return self.n

    xlang.export_task("add", add)
    xlang.export_task("greet", greet)
    xlang.export_actor_class("Counter", Counter)

    native_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native")
    demo = str(tmp_path / "api_demo")
    subprocess.run(["g++", "-O2", "-std=c++17", "-Wall", "-o", demo,
                    os.path.join(native_dir, "api_demo.cc"), "-ldl"],
                   check=True, timeout=120)

    daemon = list(backend.daemons.values())[0]
    lib = os.path.join(native_dir, "libray_tpu_cpp_client.so")
    out = subprocess.run(
        [demo, "127.0.0.1", str(backend._head_port),
         daemon.addr[0], str(daemon.addr[1]), lib],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    got = dict(line.split("=", 1)
               for line in out.stdout.strip().splitlines())
    assert got["KV"] == "embedded-value"
    assert int(got["PING"]) == daemon.proc.pid
    assert int(got["OBJ"]) == 300000
    assert int(got["ADD"]) == 42
    assert got["GREET"] == "hello embedded"
    assert int(got["COUNT1"]) == 101
    assert int(got["COUNT2"]) == 106
    assert got["MISSING_OK"] == "0"
    assert got["DONE"] == "1"
