"""Two-process multi-host end-to-end (VERDICT r2 #4).

The single-machine stand-in for a v5p pod: two OS processes, each owning
4 virtual CPU devices, rendezvous through a real head process's KV, form
ONE 8-device global mesh via ``jax.distributed``, and run JaxTrainer.fit
with per-step collectives crossing the process boundary (reference
capability: ``python/ray/cluster_utils.py:135`` multi-node fixture +
``train/torch/config.py:66`` rendezvous).
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_global_mesh_train(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"

    # a real head process provides the rendezvous KV
    from ray_tpu._private.cluster import _spawn
    head_proc, head_port = _spawn("ray_tpu._private.head", [])
    coord_port = _free_port()
    procs = []
    outs = []
    try:
        for pid in range(2):
            out = tmp_path / f"host{pid}.json"
            outs.append(out)
            procs.append(subprocess.Popen(
                [sys.executable,
                 os.path.join(repo, "tests", "multihost_host_runner.py"),
                 "--process-id", str(pid),
                 "--num-processes", "2",
                 "--head", f"127.0.0.1:{head_port}",
                 "--coordinator-port", str(coord_port),
                 "--out", str(out)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        deadline = time.monotonic() + 240
        for proc in procs:
            budget = max(5.0, deadline - time.monotonic())
            try:
                proc.wait(timeout=budget)
            except subprocess.TimeoutExpired:
                for p in procs:
                    p.kill()
                pytest.fail("multihost runner timed out")
        for proc in procs:
            if proc.returncode != 0:
                pytest.fail(
                    f"runner rc={proc.returncode}\n"
                    f"stdout: {proc.stdout.read()[-2000:]}\n"
                    f"stderr: {proc.stderr.read()[-4000:]}")
        results = [json.load(open(o)) for o in outs]
        # both hosts saw the 8-device global mesh
        assert [r["global_devices"] for r in results] == [8, 8]
        # SPMD lockstep: identical program + identical data -> identical
        # loss on both hosts (the collectives actually synchronized)
        assert results[0]["loss"] == pytest.approx(results[1]["loss"])
        assert results[0]["loss"] > 0
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        head_proc.kill()


def test_elastic_reform_from_checkpoint(tmp_path):
    """Elastic re-form in the multi-host rendezvous path (VERDICT r4
    weak #4): generation 1 = two hosts over one 8-device global mesh,
    checkpointing; generation 2 = ONE surviving host, NEW rendezvous
    run id, restores the checkpoint and keeps training on its 4-device
    mesh (the same capacity-shrink contract the elastic Trainer applies
    within one host)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"

    from ray_tpu._private.cluster import _spawn
    head_proc, head_port = _spawn("ray_tpu._private.head", [])
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    procs = []

    def run_generation(n_procs, run_id, restore):
        nonlocal procs
        coord_port = _free_port()
        outs = []
        procs = []
        for pid in range(n_procs):
            out = tmp_path / f"{run_id}-host{pid}.json"
            outs.append(out)
            cmd = [sys.executable,
                   os.path.join(repo, "tests",
                                "multihost_host_runner.py"),
                   "--process-id", str(pid),
                   "--num-processes", str(n_procs),
                   "--head", f"127.0.0.1:{head_port}",
                   "--coordinator-port", str(coord_port),
                   "--run-id", run_id,
                   "--checkpoint-dir", str(ckpt),
                   "--out", str(out)]
            if restore:
                cmd.append("--restore")
            procs.append(subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
        deadline = time.monotonic() + 300
        for proc in procs:
            budget = max(5.0, deadline - time.monotonic())
            try:
                proc.wait(timeout=budget)
            except subprocess.TimeoutExpired:
                for p in procs:
                    p.kill()
                pytest.fail(f"gen {run_id} timed out\n"
                            f"stderr: {proc.stderr.read()[-4000:]}")
            if proc.returncode != 0:
                pytest.fail(f"gen {run_id} rc={proc.returncode}\n"
                            f"stderr: {proc.stderr.read()[-4000:]}")
        return [json.load(open(o)) for o in outs]

    try:
        gen1 = run_generation(2, "mh-gen1", restore=False)
        assert [r["global_devices"] for r in gen1] == [8, 8]
        assert (ckpt / "params.pkl").exists()
        # capacity lost: the survivor re-forms alone and RESUMES
        gen2 = run_generation(1, "mh-gen2", restore=True)
        assert gen2[0]["global_devices"] == 4
        # restored params train on: loss finite and below the fresh
        # 2-step loss of gen1 (training actually continued)
        assert gen2[0]["loss"] > 0
        assert gen2[0]["loss"] < gen1[0]["loss"]
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        head_proc.kill()
