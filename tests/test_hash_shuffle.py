"""Streaming hash shuffle: aggregator actors, tagged sides, reaping.

Reference capability: `python/ray/data/_internal/execution/operators/
hash_shuffle.py:339` (stateful aggregating actors fed by streaming
partition shards).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.data.block import block_from_rows, concat_blocks
from ray_tpu.data.hash_shuffle import run_streaming_shuffle


def _mk_block(vals):
    return block_from_rows([{"v": int(v)} for v in vals])


# Closures, not module functions: cloudpickle ships them BY VALUE, so
# daemons-mode workers need no importable test module.
def _make_mod_partition():
    def _mod_partition(block, n):
        import numpy as np
        out = [block.take(np.nonzero(
            block.column("v").to_numpy(zero_copy_only=False)
            % n == p)[0]) for p in range(n)]
        return out if n > 1 else out[0]
    return _mod_partition


_mod_partition = _make_mod_partition()


def _make_slow_mod_partition():
    inner = _make_mod_partition()

    def _slow_mod_partition(block, n, delay_s):
        import time
        time.sleep(delay_s)
        return inner(block, n)
    return _slow_mod_partition


_slow_mod_partition = _make_slow_mod_partition()


def _make_fin_rows():
    def _fin_rows(shards):
        from ray_tpu.data.block import concat_blocks
        return concat_blocks(shards.get("d", []))
    return _fin_rows


_fin_rows = _make_fin_rows()


def test_streaming_shuffle_partitions_correctly(ray_start_regular):
    refs = [ray_tpu.put(_mk_block(range(i * 10, i * 10 + 10)))
            for i in range(4)]
    outs = run_streaming_shuffle([("d", refs, _mod_partition, (3,))],
                                 3, _fin_rows, lambda p: ())
    blocks = ray_tpu.get(outs, timeout=120)
    for p, b in enumerate(blocks):
        vals = b.column("v").to_numpy(zero_copy_only=False)
        assert all(v % 3 == p for v in vals)
    total = sum(b.num_rows for b in blocks)
    assert total == 40


def test_streaming_shuffle_staggered_maps(ray_start_regular):
    """Finalize must wait for every shard even when partition tasks
    finish wildly out of order (explicit dataflow deps, not actor
    submission order)."""
    refs = [ray_tpu.put(_mk_block(range(i * 8, i * 8 + 8)))
            for i in range(5)]
    delays = [0.4, 0.0, 0.3, 0.05, 0.2]
    sides = [("d", [r], _slow_mod_partition, (2, d))
             for r, d in zip(refs, delays)]
    outs = run_streaming_shuffle(sides, 2, _fin_rows, lambda p: ())
    blocks = ray_tpu.get(outs, timeout=120)
    assert sum(b.num_rows for b in blocks) == 40
    evens = blocks[0].column("v").to_numpy(zero_copy_only=False)
    assert len(evens) == 20 and all(v % 2 == 0 for v in evens)


def test_streaming_shuffle_two_tagged_sides(ray_start_regular):
    """Join-style: two sides land in the same aggregators, separated
    by tag at finalize."""
    left = [ray_tpu.put(_mk_block([0, 1, 2, 3]))]
    right = [ray_tpu.put(_mk_block([2, 3, 4, 5]))]

    def fin(shards):
        l = concat_blocks(shards.get("l", []))
        r = concat_blocks(shards.get("r", []))
        return {"left": l.num_rows, "right": r.num_rows}

    outs = run_streaming_shuffle(
        [("l", left, _mod_partition, (2,)),
         ("r", right, _mod_partition, (2,))],
        2, fin, lambda p: ())
    got = ray_tpu.get(outs, timeout=120)
    assert got[0] == {"left": 2, "right": 2}   # evens both sides
    assert got[1] == {"left": 2, "right": 2}


def test_aggregator_actors_reaped(ray_start_regular):
    """Once outputs materialize, the per-shuffle actors die."""
    from ray_tpu.util.state.api import list_actors
    refs = [ray_tpu.put(_mk_block(range(6)))]
    before = len([a for a in list_actors()
                  if a.get("state") == "ALIVE"])
    outs = run_streaming_shuffle([("d", refs, _mod_partition, (2,))],
                                 2, _fin_rows, lambda p: ())
    ray_tpu.get(outs, timeout=120)
    deadline = time.time() + 20
    while time.time() < deadline:
        alive = len([a for a in list_actors()
                     if a.get("state") == "ALIVE"])
        if alive <= before:
            break
        time.sleep(0.2)
    assert alive <= before, f"aggregators leaked: {alive} > {before}"
