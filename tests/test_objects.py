"""Object store + reference counting tests.

Reference: python/ray/tests/test_object_*.py, test_reference_counting*.py.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private.object_store import LocalObjectStore
from ray_tpu.exceptions import OutOfMemoryError


def test_refcount_frees_on_del(ray_start_regular):
    rt = ray_start_regular
    ref = ray_tpu.put(np.ones((500, 500)))
    oid = ref.id
    assert rt.refcounter.ref_count(oid) >= 1
    del ref
    gc.collect()
    time.sleep(0.1)
    assert rt.refcounter.ref_count(oid) == 0
    assert not rt.memory_store.contains(oid)


def test_refcount_pinned_by_pending_task(ray_start_regular):
    rt = ray_start_regular

    @ray_tpu.remote
    def slow(x):
        time.sleep(0.5)
        return x

    ref = ray_tpu.put(123)
    oid = ref.id
    out = slow.remote(ref)
    del ref
    gc.collect()
    # Task argument pin keeps it alive while the task runs.
    assert ray_tpu.get(out) == 123


def test_nested_ref_containment(ray_start_regular):
    rt = ray_start_regular
    inner = ray_tpu.put("inner-value")
    outer = ray_tpu.put([inner])
    inner_id = inner.id
    del inner
    gc.collect()
    time.sleep(0.05)
    # Containment pin: inner survives because outer holds it.
    assert rt.refcounter.ref_count(inner_id) >= 1
    got = ray_tpu.get(outer)
    assert ray_tpu.get(got[0]) == "inner-value"


def test_store_eviction_spill_roundtrip(tmp_path):
    store = LocalObjectStore(NodeID.from_random(), capacity_bytes=1_000_000,
                             spill_dir=str(tmp_path))
    oids = []
    for i in range(10):
        oid = ObjectID.from_random()
        store.put(oid, np.full((200, 200), i))  # 320KB each
        oids.append(oid)
    assert store.stats["spills"] > 0
    # Every object still readable (restored from disk transparently).
    for i, oid in enumerate(oids):
        assert store.get(oid)[0][0] == i


def test_store_capacity_error():
    store = LocalObjectStore(NodeID.from_random(), capacity_bytes=1000)
    with pytest.raises(OutOfMemoryError):
        store.put(ObjectID.from_random(), np.ones(10_000))


def test_object_immutability(ray_start_regular):
    arr = np.zeros(5)
    ref = ray_tpu.put(arr)
    got = ray_tpu.get(ref)
    with pytest.raises(ValueError):
        got[0] = 1


def test_shared_value_across_consumers(ray_start_regular):
    big = np.random.rand(400, 400)
    ref = ray_tpu.put(big)

    @ray_tpu.remote
    def reader(x):
        return float(x.sum())

    outs = ray_tpu.get([reader.remote(ref) for _ in range(4)])
    assert all(abs(o - big.sum()) < 1e-6 for o in outs)


def test_store_restore_does_not_respill_itself(tmp_path):
    """Regression: _restore cleared spilled_path BEFORE the pressure
    scan, so _ensure_space could pick the very entry being restored,
    re-spill it, and hand the caller value=None while _used
    double-counted it. The entry is now pinned across the scan: with a
    spillable neighbor the restore succeeds; with everything pinned the
    infeasible restore raises OutOfMemoryError LOUDLY instead of
    silently corrupting the read."""
    from ray_tpu.exceptions import OutOfMemoryError

    store = LocalObjectStore(NodeID.from_random(), capacity_bytes=100_000,
                             spill_dir=str(tmp_path))
    a, b = ObjectID.from_random(), ObjectID.from_random()
    store.put(a, np.full(60_000, 1, dtype=np.uint8))
    store.put(b, np.full(60_000, 2, dtype=np.uint8))     # spills a
    assert store.stats["spills"] >= 1
    # feasible: the scan spills b (unpinned), never the restoring a
    val = store.get(a)
    assert val is not None and val[0] == 1
    # infeasible: b pinned, a shielded -> loud OOM, not value=None
    store2 = LocalObjectStore(NodeID.from_random(),
                              capacity_bytes=100_000,
                              spill_dir=str(tmp_path / "s2"))
    store2.put(a, np.full(60_000, 1, dtype=np.uint8))
    store2.put(b, np.full(60_000, 2, dtype=np.uint8))
    with store2._lock:
        store2._entries[b].pinned += 1
    with pytest.raises(OutOfMemoryError):
        store2.get(a)
    # the failed restore consumed NOTHING: accounting intact, spill
    # file intact, and the restore succeeds once pressure drops
    with store2._lock:
        assert store2._used == 60_000          # b only; a uncounted
        assert store2._entries[a].spilled_path is not None
        store2._entries[b].pinned -= 1
    val = store2.get(a)
    assert val is not None and val[0] == 1
    with store2._lock:
        assert store2._used == 60_000          # b spilled, a resident
