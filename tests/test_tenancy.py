"""Multi-tenant fair-share: ledger math, admission verdicts, isolation.

Covers the three layers of docs/multitenancy.md:

- :class:`FairShareLedger` unit math — dominant shares, weighted
  deficit ordering over whole same-shape groups, hard-cap clamping;
- admission verdicts end to end — QUEUED work is delayed never lost
  and resumes when quota frees, REJECTED surfaces as the typed
  :class:`AdmissionRejectedError`, verdict counts ride /metrics;
- the isolation acceptance A/B — with fair-share ON a light tenant's
  p99 stays within 3x its solo latency while a heavy tenant saturates
  the cluster; with fair-share OFF the same contention starves it.
"""

import threading
import time
import types

import pytest

import ray_tpu
from ray_tpu._private import failpoints as fp
from ray_tpu._private.ids import JobID
from ray_tpu.exceptions import AdmissionRejectedError
from ray_tpu.tenancy import (ADMITTED, QUEUED, FairShareLedger, JobQuota,
                             TenancyManager, job_context)
from ray_tpu.tenancy.context import canonical_job
from ray_tpu.tenancy.policy import DEFICIT_CAP


@pytest.fixture(autouse=True)
def _reset_failpoints():
    yield
    fp.reset()


# ---------------------------------------------------------------------------
# ledger math (pure unit tests, fixed capacity)
# ---------------------------------------------------------------------------

CAP = {"CPU": 10.0, "memory": 100.0}


def test_dominant_share_is_max_axis_ratio():
    led = FairShareLedger(CAP)
    led.note_admitted("a", {"CPU": 2.0, "memory": 5.0}, 1)
    # max(2/10, 5/100) = 0.2 — CPU dominates
    assert led.dominant_share("a") == pytest.approx(0.2)
    led.note_admitted("a", {"memory": 45.0}, 1)
    # memory now dominates: 50/100
    assert led.dominant_share("a") == pytest.approx(0.5)
    led.note_done("a", {"memory": 45.0})
    assert led.dominant_share("a") == pytest.approx(0.2)


def test_dominant_cost_of_unknown_resource_is_nonzero():
    led = FairShareLedger(CAP)
    # deficits must still be spendable for demands off the capacity map
    assert led.dominant_cost({"accelerator_x": 1.0}) > 0.0


def test_weighted_deficit_accrual_and_ordering():
    led = FairShareLedger(CAP)
    led.ensure("heavy", weight=3.0)
    led.ensure("light", weight=1.0)
    items = [(("heavy", ("s",)), 4), (("light", ("s",)), 4)]
    led.order(items)
    snap = led.snapshot()
    # one round splits QUANTUM by weight share: 0.75 vs 0.25
    assert snap["heavy"]["deficit"] == pytest.approx(0.75)
    assert snap["light"]["deficit"] == pytest.approx(0.25)
    # the higher-deficit job's group comes back first, whole
    assert led.order(items)[0][0] == "heavy"
    # launching spends deficit: once heavy's launches outrun its 3x
    # weighted accrual it falls behind light on the next round
    led.note_admitted("heavy", {"CPU": 2.0}, 12)  # spends 12 * 0.2
    assert led.order(items)[0][0] == "light"


def test_deficit_is_capped_both_ways():
    led = FairShareLedger(CAP)
    items = [(("solo", ("s",)), 1)]
    for _ in range(50):
        led.order(items)    # solo job accrues the full quantum/round
    assert led.snapshot()["solo"]["deficit"] <= DEFICIT_CAP
    led.note_admitted("solo", {"CPU": 10.0}, 30)
    assert led.snapshot()["solo"]["deficit"] >= -DEFICIT_CAP


def test_order_admits_whole_groups_fifo_within_job():
    led = FairShareLedger(CAP)
    led.ensure("a", weight=1.0)
    led.ensure("b", weight=1.0)
    # two same-shape groups per job: blocks stay contiguous per job and
    # FIFO within a job (stable sort), never interleaved task-at-a-time
    items = [(("a", ("x",)), 2), (("b", ("y",)), 2),
             (("a", ("z",)), 1), (("b", ("w",)), 1)]
    keys = led.order(items)
    by_job = [job for job, _shape in keys]
    assert by_job in (["a", "a", "b", "b"], ["b", "b", "a", "a"])
    a_shapes = [shape for job, shape in keys if job == "a"]
    assert a_shapes == [("x",), ("z",)]


def test_queue_empty_job_forfeits_deficit():
    led = FairShareLedger(CAP)
    led.order([(("a", ("s",)), 1)])
    assert led.snapshot()["a"]["deficit"] > 0
    led.observe_queued("node-1", {"a": 0})
    assert led.snapshot()["a"]["deficit"] == 0.0


def test_admit_cap_clamps_group_to_headroom():
    led = FairShareLedger(CAP)
    led.set_quota("a", JobQuota(hard={"CPU": 4.0}))
    led.note_admitted("a", {"CPU": 1.0}, 1)
    # 3 CPUs of headroom left for 1-CPU tasks
    assert led.admit_cap("a", {"CPU": 1.0}, 10) == 3
    assert led.admit_cap("a", {"CPU": 2.0}, 10) == 1
    led.note_admitted("a", {"CPU": 1.0}, 3)
    assert led.admit_cap("a", {"CPU": 1.0}, 10) == 0
    # completions free headroom again
    led.note_done("a", {"CPU": 1.0})
    assert led.admit_cap("a", {"CPU": 1.0}, 10) == 1
    # uncapped jobs are never clamped
    assert led.admit_cap("other", {"CPU": 1.0}, 10) == 10


def test_over_hard_cap_and_soft_cap_checks():
    led = FairShareLedger(CAP)
    led.set_quota("a", JobQuota(hard={"CPU": 2.0}, soft={"memory": 10.0}))
    assert not led.over_hard_cap("a", {"CPU": 1.0})
    led.note_admitted("a", {"CPU": 2.0, "memory": 20.0}, 1)
    assert led.over_hard_cap("a", {"CPU": 1.0})
    assert led.at_hard_cap("a")
    assert led.over_soft_cap("a")           # memory 20 > soft 10
    led.note_done("a", {"CPU": 2.0, "memory": 20.0})
    assert not led.at_hard_cap("a")
    assert not led.over_soft_cap("a")


def test_object_bytes_quota_axis():
    led = FairShareLedger(CAP)
    led.set_quota("a", JobQuota(hard={"object_store_bytes": 100.0}))
    led.note_object_bytes("a", 150.0)
    assert led.over_hard_cap("a", {"CPU": 1.0})
    assert led.admit_cap("a", {"CPU": 1.0}, 5) == 0
    led.note_object_bytes("a", -100.0)
    assert not led.over_hard_cap("a", {"CPU": 1.0})


def test_quota_rejects_unknown_resource_axis():
    with pytest.raises(ValueError):
        JobQuota(hard={"GPUs": 1.0})


# ---------------------------------------------------------------------------
# admission manager (no cluster: fake specs, fixed capacity)
# ---------------------------------------------------------------------------

def _spec(job: str, resources=None):
    jid = JobID(job.encode().ljust(8, b"\0")[:8])
    return types.SimpleNamespace(job_id=jid, resources=resources
                                 or {"CPU": 1.0}), jid.hex()


def _manager(queue_max=4):
    return TenancyManager(runtime=None, enabled=True,
                          capacity_fn=lambda: dict(CAP),
                          default_weight=1.0, queue_max=queue_max)


def test_admit_verdicts_and_typed_rejection():
    ten = _manager(queue_max=2)
    spec, job = _spec("j1")
    assert ten.admit(spec) == ADMITTED
    ten.note_admitted(job, spec.resources, 1)       # pending -> 0
    ten.set_quota(job, hard={"CPU": 1.0})           # now at the cap
    assert ten.admit(spec) == QUEUED                # pending 1
    assert ten.admit(spec) == QUEUED                # pending 2 == max
    with pytest.raises(AdmissionRejectedError):
        ten.admit(spec)
    # dispatch retires the queued submits' inflight demand and
    # completions free the cap: back to ADMITTED. (The verdict folds
    # in submitted-not-yet-dispatched demand, so queued work must
    # actually dispatch — not merely have older tasks complete —
    # before the job reads as under cap again.)
    ten.note_admitted(job, spec.resources, 2)
    for _ in range(3):
        ten.note_done(job, spec.resources)
    assert ten.admit(spec) == ADMITTED


def test_admission_verdict_seam_drop_fails_open_error_degrades():
    ten = _manager()
    spec, job = _spec("j2")
    ten.set_quota(job, hard={"CPU": 0.5})           # every submit over cap
    assert ten.admit(spec) == QUEUED
    fp.activate("admission.verdict=drop")
    assert ten.admit(spec) == ADMITTED              # decision lost: open
    fp.reset()
    fp.activate("admission.verdict=error(RuntimeError)")
    under_spec, _ = _spec("j3")                     # within caps...
    assert ten.admit(under_spec) == QUEUED          # ...but path failed
    assert fp.fire_count("admission.verdict") == 1


def test_quota_sync_seam_drop_keeps_records_dirty():
    calls = []

    class FakeHead:
        def tenancy_set(self, job, rec):
            calls.append(("set", job, rec))

        def tenancy_report(self, jobs):
            calls.append(("report", jobs))

    backend = types.SimpleNamespace(head=FakeHead(), daemons={})
    ten = _manager()
    ten.set_quota("aaaa", hard={"CPU": 2.0}, weight=2.0)
    fp.activate("tenancy.quota_sync=drop")
    ten.maybe_sync(backend)
    assert calls == []                  # tick skipped, nothing sent
    fp.reset()
    ten.maybe_sync(backend)             # dirty record survived the drop
    canon, _ = canonical_job("aaaa")    # short names hash to stable hex
    assert ("set", canon,
            {"quota": {"hard": {"CPU": 2.0}, "soft": {}},
             "weight": 2.0, "name": "aaaa"}) in calls
    # clean now: another tick sends no records (reports may still ride)
    n_sets = sum(1 for c in calls if c[0] == "set")
    ten.maybe_sync(backend)
    assert sum(1 for c in calls if c[0] == "set") == n_sets


def test_quota_sync_skips_daemons_without_capability():
    sent = []

    class FakeClient:
        def call(self, method, **kw):
            sent.append(method)

    old = types.SimpleNamespace(client=FakeClient())    # no hello bit
    new = types.SimpleNamespace(client=FakeClient(),
                                _tenancy_supported=True)
    backend = types.SimpleNamespace(
        head=types.SimpleNamespace(
            tenancy_set=lambda job, rec: None,
            tenancy_report=lambda jobs: None),
        daemons={"old": old, "new": new})
    ten = _manager()
    ten.set_quota("bbbb", hard={"CPU": 1.0})
    ten.maybe_sync(backend)
    # only the daemon that advertised "tenancy" in its hello reply got
    # the job table; the old one fell back to unconditional admission
    assert sent == ["tenancy_sync"]


# ---------------------------------------------------------------------------
# end-to-end verdicts on a live cluster
# ---------------------------------------------------------------------------

def test_queued_is_delayed_never_lost_and_resumes():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      _system_config={"fairshare": True})
    rt.tenancy.set_quota("capped", hard={"CPU": 1.0})

    @ray_tpu.remote
    def work(i):
        time.sleep(0.05)
        return i

    with job_context("capped"):
        refs = [work.remote(i) for i in range(6)]
    # 1-CPU cap on a 2-CPU box: tasks run one at a time, over-cap ones
    # sit QUEUED at the dispatch gate — and every result still arrives
    assert sorted(ray_tpu.get(refs, timeout=60)) == list(range(6))
    from ray_tpu.util import metrics
    text = metrics.prometheus_text()
    assert "ray_tpu_admission_total" in text
    assert 'verdict="queued"' in text
    assert 'verdict="admitted"' in text


def test_rejected_is_typed_and_queue_resumes_after_quota_raise():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      _system_config={"fairshare": True,
                                      "admission_queue_max": 3})
    rt.tenancy.set_quota("strict", hard={"CPU": 0.0})

    @ray_tpu.remote
    def work(i):
        return i * 10

    refs = []
    with job_context("strict"):
        for i in range(3):
            refs.append(work.remote(i))     # QUEUED x3 fills the bound
        with pytest.raises(AdmissionRejectedError):
            work.remote(99)
    # raising the quota lets the held backlog drain: delayed, not lost
    rt.tenancy.set_quota("strict", hard={"CPU": 2.0})
    assert sorted(ray_tpu.get(refs, timeout=60)) == [0, 10, 20]
    from ray_tpu.util import metrics
    text = metrics.prometheus_text()
    assert 'verdict="rejected"' in text


def test_job_gauges_and_jobs_view():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      _system_config={"fairshare": True})

    @ray_tpu.remote
    def work(i):
        return i

    with job_context("viewer", weight=2.0) as jid:
        assert ray_tpu.get([work.remote(i) for i in range(4)],
                           timeout=30) == list(range(4))
    view = rt.tenancy.jobs_view()
    assert view[jid.hex()]["weight"] == 2.0
    assert view[jid.hex()]["name"] == "viewer"
    from ray_tpu.util import metrics
    text = metrics.prometheus_text()
    assert "ray_tpu_job_running_tasks" in text
    assert "ray_tpu_job_queued_tasks" in text


def test_fairshare_off_means_no_admission_and_plain_dispatch():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2})
    assert rt.tenancy.enabled is False
    for node in rt.nodes():
        assert node.tenancy is None     # untenanted dispatch path
    rt.tenancy.set_quota("ignored", hard={"CPU": 0.0})

    @ray_tpu.remote
    def work(i):
        return i

    with job_context("ignored"):
        # quota is recorded but NOT enforced: everything runs
        assert ray_tpu.get([work.remote(i) for i in range(4)],
                           timeout=30) == list(range(4))


def test_child_tasks_inherit_job_id():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      _system_config={"fairshare": True})

    @ray_tpu.remote
    def leaf():
        from ray_tpu._private import runtime_context
        ctx = runtime_context._ctx.get()
        return ctx.job_id.hex() if ctx and ctx.job_id else None

    @ray_tpu.remote
    def parent():
        return ray_tpu.get(leaf.remote())

    with job_context("lineage") as jid:
        child_job = ray_tpu.get(parent.remote(), timeout=30)
    assert child_job == jid.hex()


# ---------------------------------------------------------------------------
# the isolation acceptance A/B
# ---------------------------------------------------------------------------

def _light_latencies(n=12, sleep_s=0.02):
    """Submit n sequential light-job tasks, returning per-task latency."""

    @ray_tpu.remote
    def light():
        time.sleep(sleep_s)
        return None

    lats = []
    with job_context("light", weight=1.0):
        for _ in range(n):
            t0 = time.perf_counter()
            ray_tpu.get(light.remote(), timeout=120)
            lats.append(time.perf_counter() - t0)
    return lats


def _heavy_backlog(n=100, sleep_s=0.02):
    """Pre-queue n heavy-job tasks WITHOUT consuming the results."""

    @ray_tpu.remote
    def heavy():
        time.sleep(sleep_s)
        return None

    with job_context("heavy", weight=1.0):
        return [heavy.remote() for _ in range(n)]


def _p99(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(0.99 * len(xs)))]


def test_isolation_fair_share_on_vs_off():
    """The headline A/B (ISSUE acceptance): a heavy tenant pre-queues a
    deep backlog on a 2-CPU cluster, then a light tenant submits
    latency-sensitive tasks one at a time.

    OFF: both tenants share one FIFO shape bucket — each light task
    waits behind the heavy backlog (~1s on this sizing), far past 3x
    its solo latency. ON: (job, shape)-keyed buckets + deficit
    ordering let the light job's singleton groups cut ahead, keeping
    its p99 within 3x of max(solo p99, 150ms)."""
    # solo baseline: the light job alone on the cluster
    ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                 _system_config={"fairshare": True})
    solo_p99 = _p99(_light_latencies())
    ray_tpu.shutdown()

    # fair-share ON under contention
    ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                 _system_config={"fairshare": True})
    heavy_refs = _heavy_backlog()
    on_p99 = _p99(_light_latencies())
    ray_tpu.get(heavy_refs, timeout=120)    # heavy work still completes
    ray_tpu.shutdown()

    # fair-share OFF under the same contention
    ray_tpu.init(num_nodes=1, resources={"CPU": 2})
    heavy_refs = _heavy_backlog()
    off_p99 = _p99(_light_latencies())
    ray_tpu.get(heavy_refs, timeout=120)
    ray_tpu.shutdown()

    bound = 3.0 * max(solo_p99, 0.15)
    assert on_p99 <= bound, (
        f"fair-share ON failed isolation: light p99 {on_p99:.3f}s vs "
        f"bound {bound:.3f}s (solo {solo_p99:.3f}s)")
    # OFF must reproduce the starvation the subsystem exists to fix —
    # if it doesn't, this test is not exercising real contention
    assert off_p99 > 3.0 * solo_p99, (
        f"fair-share OFF did not starve the light job: p99 "
        f"{off_p99:.3f}s vs solo {solo_p99:.3f}s — contention too weak")
    assert off_p99 > on_p99, (off_p99, on_p99)


def test_concurrent_multi_job_submits_are_race_free():
    """Two driver threads submitting under different job contexts while
    the dispatcher runs: no lost tasks, no mixed attribution."""
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      _system_config={"fairshare": True})

    @ray_tpu.remote
    def work(i):
        return i

    results = {}

    def run(job, n):
        with job_context(job):
            results[job] = ray_tpu.get(
                [work.remote(i) for i in range(n)], timeout=60)

    threads = [threading.Thread(target=run, args=(f"racer-{k}", 40))
               for k in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results["racer-0"] == list(range(40))
    assert results["racer-1"] == list(range(40))
    view = rt.tenancy.jobs_view()
    names = {row.get("name") for row in view.values()}
    assert {"racer-0", "racer-1"} <= names
