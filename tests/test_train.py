"""Train library: JaxTrainer, session, checkpoints, failure recovery."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (Checkpoint, CheckpointManager, FailureConfig,
                           JaxTrainer, RunConfig, ScalingConfig)


def test_checkpoint_pytree_roundtrip(tmp_path):
    tree = {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "meta": {"step": 7, "lr": 0.1}}
    ckpt = Checkpoint.from_pytree(tree, str(tmp_path / "ck"))
    back = ckpt.to_pytree()
    np.testing.assert_array_equal(back["w"], np.arange(6).reshape(2, 3))
    assert back["meta"]["step"] == 7


def test_checkpoint_manager_topk(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "run"), num_to_keep=2,
                            score_attribute="acc")
    for i, acc in enumerate([0.1, 0.9, 0.5]):
        ck = Checkpoint.from_pytree({"i": jnp.asarray(i)},
                                    str(tmp_path / f"src{i}"))
        mgr.register(ck, {"acc": acc})
    kept = sorted(d for d in os.listdir(tmp_path / "run")
                  if d.startswith("checkpoint_"))
    assert len(kept) == 2
    best = mgr.best_checkpoint().to_pytree()
    assert int(best["i"]) == 1  # acc=0.9


def test_jax_trainer_basic(ray_start_cluster, tmp_path):
    def train_fn(config):
        ctx = train.get_context()
        assert ctx.get_world_size() == 2
        for step in range(3):
            metrics = {"step": step, "rank": ctx.get_world_rank(),
                       "loss": 1.0 / (step + 1)}
            if ctx.get_world_rank() == 0:
                ck = Checkpoint.from_pytree({"step": jnp.asarray(step)})
                train.report(metrics, checkpoint=ck)
            else:
                train.report(metrics)

    trainer = JaxTrainer(
        train_fn, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="basic", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert result.checkpoint is not None
    assert int(result.checkpoint.to_pytree()["step"]) == 2
    # both ranks reported 3 times
    assert len(result.metrics_history) == 6


def test_jax_trainer_trains_real_model(ray_start_regular, tmp_path):
    """End-to-end: MLP actually learns inside the trainer."""
    def train_fn(config):
        import optax
        from ray_tpu.models import MLPConfig, MLPModel
        from ray_tpu.train.spmd import make_train_step
        model = MLPModel(MLPConfig(in_dim=8, hidden=(16,), num_classes=2))
        ts = make_train_step(model, optimizer=optax.adam(1e-2))
        params, opt = ts.init_fn(jax.random.key(0))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(32, 8)), jnp.float32)
        y = (np.asarray(x)[:, 0] > 0).astype(np.int32)
        y = jnp.asarray(y)
        for step in range(40):
            params, opt, m = ts.step_fn(params, opt, (x, y))
        acc = float(model.accuracy(params, x, y))
        train.report({"acc": acc},
                     checkpoint=Checkpoint.from_pytree(params))

    result = JaxTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="mlp", storage_path=str(tmp_path))).fit()
    assert result.error is None
    assert result.metrics["acc"] > 0.9
    assert result.checkpoint is not None


def test_jax_trainer_failure_retry(ray_start_cluster, tmp_path):
    """Worker crash → group restarted from latest checkpoint."""
    marker = tmp_path / "crashed_once"

    def train_fn(config):
        ctx = train.get_context()
        start = 0
        prev = train.get_checkpoint()
        if prev is not None:
            start = int(prev.to_pytree()["step"]) + 1
        for step in range(start, 4):
            if step == 2 and not marker.exists():
                marker.write_text("x")
                raise RuntimeError("injected failure at step 2")
            ck = (Checkpoint.from_pytree({"step": jnp.asarray(step)})
                  if ctx.get_world_rank() == 0 else None)
            train.report({"step": step}, checkpoint=ck)

    result = JaxTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="retry", storage_path=str(tmp_path / "run"),
            failure_config=FailureConfig(max_failures=1))).fit()
    assert result.error is None
    assert result.metrics["step"] == 3
    # resumed from step 2, not from scratch: rank-0 history has 1 entry per
    # step 0,1 then 2,3 after resume
    rank0_steps = [e["metrics"]["step"] for e in result.metrics_history
                   if e["rank"] == 0]
    assert rank0_steps == [0, 1, 2, 3]


def test_jax_trainer_failure_exhausted(ray_start_regular, tmp_path):
    def train_fn(config):
        raise ValueError("always fails")

    result = JaxTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="fail", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=0))).fit()
    assert result.error is not None


def test_dataset_shard_sequence_split(ray_start_regular, tmp_path):
    def train_fn(config):
        shard = train.get_dataset_shard("train")
        train.report({"n": len(list(shard))})

    result = JaxTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ds", storage_path=str(tmp_path)),
        datasets={"train": list(range(10))}).fit()
    counts = sorted(e["metrics"]["n"] for e in result.metrics_history)
    assert counts == [5, 5]


def test_worker_group_collectives(ray_start_cluster, tmp_path):
    from ray_tpu.train import collective as col

    def train_fn(config):
        ctx = train.get_context()
        rank = ctx.get_world_rank()
        col.barrier()
        got = col.broadcast_from_rank_zero(
            {"seed": 42} if rank == 0 else None)
        total = col.allreduce(rank + 1)           # 1 + 2 + 3 = 6
        ranks = col.allgather(rank)
        train.report({"bcast": got["seed"], "sum": total,
                      "ranks": ranks})

    result = JaxTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=3),
        run_config=RunConfig(name="coll",
                             storage_path=str(tmp_path))).fit()
    assert result.error is None
    for e in result.metrics_history:
        assert e["metrics"]["bcast"] == 42
        assert e["metrics"]["sum"] == 6
        assert e["metrics"]["ranks"] == [0, 1, 2]


def test_async_checkpointer_overlaps_and_roundtrips(tmp_path):
    """AsyncCheckpointer: save returns before the disk write lands;
    wait_until_finished makes the files trustworthy; contents equal the
    sync path."""
    from ray_tpu.train import AsyncCheckpointer

    tree = {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            "b16": jnp.ones(16, jnp.bfloat16), "step": 7}
    ck = AsyncCheckpointer()
    try:
        ckpt = ck.save(tree, str(tmp_path / "a"))
        ck.wait_until_finished(timeout=30)
        back = ckpt.to_pytree()
        np.testing.assert_array_equal(np.asarray(back["w"]),
                                      np.asarray(tree["w"]))
        assert str(back["b16"].dtype) == "bfloat16"
        assert back["step"] == 7
        # a second pending save doesn't block the caller
        import time as _t
        t0 = _t.perf_counter()
        ck.save(tree, str(tmp_path / "b"))
        assert _t.perf_counter() - t0 < 5
        ck.wait_until_finished(timeout=30)
        assert (tmp_path / "b" / "leaves.npz").exists()
    finally:
        ck.close()


def test_async_checkpointer_error_handling(tmp_path):
    """A failed write surfaces on result() and once on
    wait_until_finished, without poisoning later successful saves."""
    from ray_tpu.train import AsyncCheckpointer

    ck = AsyncCheckpointer()
    try:
        blocked = tmp_path / "blocked"
        blocked.write_text("a file where a directory must go")
        bad = ck.save({"w": jnp.ones(4)}, str(blocked / "sub"))
        with pytest.raises(Exception):
            bad.result(timeout=30)
        with pytest.raises(Exception):
            ck.wait_until_finished(timeout=30)
        # error cleared: a later good save is not poisoned
        good = ck.save({"w": jnp.ones(4)}, str(tmp_path / "good"))
        ck.wait_until_finished(timeout=30)
        assert good.result().path
        np.testing.assert_array_equal(
            np.asarray(good.to_pytree()["w"]), np.ones(4))
    finally:
        ck.close()
