"""S3 filesystem over the data seam, tested against an in-process mock
S3 server (reference test pattern: data/tests/mock_s3_server.py —
exercise the real wire protocol without network egress)."""

import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import ray_tpu


class _MockS3Handler(BaseHTTPRequestHandler):
    store = {}   # "bucket/key" -> bytes

    def log_message(self, *args):
        pass

    def _path_key(self):
        return urllib.parse.unquote(self.path.split("?")[0]).lstrip("/")

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        self.store[self._path_key()] = self.rfile.read(length)
        self.send_response(200)
        self.end_headers()

    def do_HEAD(self):
        if self._path_key() in self.store:
            self.send_response(200)
        else:
            self.send_response(404)
        self.end_headers()

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        q = urllib.parse.parse_qs(parsed.query)
        key = self._path_key()
        if "list-type" in q:   # ListObjectsV2
            bucket = key
            prefix = q.get("prefix", [""])[0]
            delim = q.get("delimiter", [""])[0]
            keys, prefixes = [], set()
            for full in sorted(self.store):
                b, _, k = full.partition("/")
                if b != bucket or not k.startswith(prefix):
                    continue
                rest = k[len(prefix):]
                if delim and delim in rest:
                    prefixes.add(prefix + rest.split(delim)[0] + delim)
                else:
                    keys.append(k)
            ns = 'xmlns="http://s3.amazonaws.com/doc/2006-03-01/"'
            body = f'<ListBucketResult {ns}>'
            for k in keys:
                body += f"<Contents><Key>{k}</Key></Contents>"
            for p in sorted(prefixes):
                body += (f"<CommonPrefixes><Prefix>{p}</Prefix>"
                         f"</CommonPrefixes>")
            body += "</ListBucketResult>"
            blob = body.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)
            return
        blob = self.store.get(key)
        if blob is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        self.wfile.write(blob)


@pytest.fixture
def mock_s3():
    _MockS3Handler.store = {}
    server = ThreadingHTTPServer(("127.0.0.1", 0), _MockS3Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    from ray_tpu.data.s3_filesystem import enable_s3
    fs = enable_s3(
        endpoint_url=f"http://127.0.0.1:{server.server_address[1]}",
        access_key="test", secret_key="secret")
    yield fs
    server.shutdown()
    from ray_tpu.data.filesystem import _REGISTRY
    _REGISTRY.pop("s3", None)


def test_s3_roundtrip_and_listing(mock_s3):
    fs = mock_s3
    with fs.open_output("bkt/dir/a.txt") as f:
        f.write(b"alpha")
    with fs.open_output("bkt/dir/b.txt") as f:
        f.write(b"beta")
    with fs.open_output("bkt/other/c.txt") as f:
        f.write(b"gamma")

    assert fs.open_input("bkt/dir/a.txt").read() == b"alpha"
    assert fs.exists("bkt/dir/a.txt")
    assert not fs.exists("bkt/dir/zzz.txt")
    assert fs.isdir("bkt/dir")
    assert not fs.isdir("bkt/dir/a.txt")
    assert fs.listdir("bkt/dir") == ["bkt/dir/a.txt", "bkt/dir/b.txt"]
    assert fs.listdir("bkt") == ["bkt/dir", "bkt/other"]
    assert fs.glob("bkt/dir/*.txt") == ["bkt/dir/a.txt", "bkt/dir/b.txt"]
    with pytest.raises(FileNotFoundError):
        fs.open_input("bkt/nope")


def test_s3_dataset_roundtrip(mock_s3, ray_start_regular):
    """End to end through ray_tpu.data: write + read parquet on s3://."""
    from ray_tpu import data

    ds = data.from_items([{"x": i, "y": float(i * i)} for i in range(50)])
    ds.write_parquet("s3://bkt/ds")
    back = data.read_parquet("s3://bkt/ds")
    rows = sorted(back.take_all(), key=lambda r: r["x"])
    assert len(rows) == 50
    assert rows[7] == {"x": 7, "y": 49.0}


def test_s3_csv_roundtrip(mock_s3, ray_start_regular):
    from ray_tpu import data

    ds = data.from_items([{"a": i} for i in range(10)])
    ds.write_csv("s3://bkt/csvs")
    back = data.read_csv("s3://bkt/csvs")
    assert sorted(r["a"] for r in back.take_all()) == list(range(10))


def test_s3_writer_abort_on_exception(mock_s3):
    """An exception inside the with-block must not upload partial bytes."""
    fs = mock_s3
    with pytest.raises(RuntimeError, match="boom"):
        with fs.open_output("bkt/bad.bin") as f:
            f.write(b"partial")
            raise RuntimeError("boom")
    assert not fs.exists("bkt/bad.bin")


@pytest.fixture
def mock_gs():
    """GCS interop XML API speaks the same protocol as S3 — the same
    mock serves both schemes."""
    _MockS3Handler.store = {}
    server = ThreadingHTTPServer(("127.0.0.1", 0), _MockS3Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    from ray_tpu.data.s3_filesystem import enable_gs
    fs = enable_gs(
        endpoint_url=f"http://127.0.0.1:{server.server_address[1]}",
        access_key="gtest", secret_key="gsecret")
    yield fs
    server.shutdown()
    from ray_tpu.data.filesystem import _REGISTRY
    _REGISTRY.pop("gs", None)
    _REGISTRY.pop("gcs", None)


def test_gs_dataset_roundtrip(mock_gs, ray_start_regular):
    """gs:// paths flow through every reader/writer via the seam."""
    from ray_tpu import data

    ds = data.from_items([{"a": i, "b": i * i} for i in range(12)])
    ds.write_parquet("gs://bkt/ds")
    back = data.read_parquet("gs://bkt/ds/*.parquet")
    rows = sorted(back.take_all(), key=lambda r: r["a"])
    assert [r["b"] for r in rows] == [i * i for i in range(12)]
    # gcs:// alias resolves to the same filesystem
    from ray_tpu.data.filesystem import resolve_filesystem
    assert resolve_filesystem("gcs://x/y")[0] is mock_gs
