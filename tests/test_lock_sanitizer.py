"""Lock-order sanitizer (SURVEY §5.2: the reference's TSAN-in-CI role).
"""

import threading
import warnings

import pytest

from ray_tpu._private.lock_sanitizer import (GRAPH, LockOrderViolation,
                                             TrackedLock, tracked_lock)


@pytest.fixture(autouse=True)
def _fresh_graph():
    GRAPH.reset()
    yield
    GRAPH.reset()


def test_consistent_order_is_clean():
    a, b = TrackedLock("a"), TrackedLock("b")
    with warnings.catch_warnings():
        warnings.simplefilter("error", LockOrderViolation)
        for _ in range(3):
            with a:
                with b:
                    pass
    assert GRAPH.violations == []


def test_inversion_detected_across_threads():
    a, b = TrackedLock("a"), TrackedLock("b")
    with a:
        with b:
            pass

    def reversed_order():
        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            with b:
                with a:         # a->b then b->a: cycle
                    pass

    t = threading.Thread(target=reversed_order)
    t.start()
    t.join()
    assert len(GRAPH.violations) == 1
    assert "lock-order inversion" in GRAPH.violations[0]
    assert "'b' -> 'a'" in GRAPH.violations[0]


def test_transitive_cycle_detected():
    a, b, c = TrackedLock("a"), TrackedLock("b"), TrackedLock("c")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        with c:
            with a:             # a->b->c then c->a
                pass
    assert len(GRAPH.violations) == 1


def test_reentrant_and_disabled_paths():
    a = TrackedLock("a")
    with a:
        with a:                 # re-entrant: no self-edge, no violation
            pass
    assert GRAPH.violations == []
    # disabled -> plain RLock, zero tracking
    lock = tracked_lock("plain")
    assert not isinstance(lock, TrackedLock)


def test_runtime_locks_tracked_when_enabled(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCK_SANITIZER", "1")
    import ray_tpu
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4})
    try:
        @ray_tpu.remote
        def f(x):
            return x * 2

        assert ray_tpu.get([f.remote(i) for i in range(20)]) == \
            [i * 2 for i in range(20)]
        refs = [ray_tpu.put(b"x" * 10_000) for _ in range(20)]
        ray_tpu.get(refs)
        # core task/object flow must be inversion-free under the tracker
        assert GRAPH.violations == []
    finally:
        ray_tpu.shutdown()


def test_disabled_path_is_zero_overhead(monkeypatch):
    """Regression guard for tracked_lock adoption across the runtime's
    hot paths (cluster/daemon/head/node/worker/fast_lane): with the
    sanitizer off, tracked_lock must return the PLAIN threading
    primitive — the exact C-level type, no Python wrapper whose
    acquire/release would tax every ledger operation."""
    monkeypatch.delenv("RAY_TPU_LOCK_SANITIZER", raising=False)
    plain = tracked_lock("zero.overhead", reentrant=False)
    assert type(plain) is type(threading.Lock())
    reent = tracked_lock("zero.overhead.r")     # reentrant default
    assert type(reent) is type(threading.RLock())
    # and the enabled path really does wrap (the inverse guard, so a
    # future refactor can't silently disable tracking)
    monkeypatch.setenv("RAY_TPU_LOCK_SANITIZER", "1")
    assert isinstance(tracked_lock("zero.overhead.on"), TrackedLock)
