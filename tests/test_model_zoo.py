"""GPT-2, ViT, MoE model families."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.models import (GPT2Config, GPT2Model, MoEConfig, MoEModel,
                            ViTConfig, ViTModel)
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.train.spmd import make_train_step, shard_batch


def test_gpt2_forward_and_training():
    cfg = GPT2Config.debug()
    model = GPT2Model(cfg)
    ts = make_train_step(model, optimizer=optax.adam(1e-3))
    params, opt = ts.init_fn(jax.random.key(0))
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32)), jnp.int32)
    losses = []
    for _ in range(8):
        params, opt, m = ts.step_fn(params, opt, (toks,
                                                  jnp.roll(toks, -1, 1)))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_gpt2_causality():
    cfg = GPT2Config.debug()
    model = GPT2Model(cfg)
    params = model.init(jax.random.key(0))
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 12].set(9)
    l1, l2 = model.apply(params, t1), model.apply(params, t2)
    np.testing.assert_allclose(np.asarray(l1[0, :12]),
                               np.asarray(l2[0, :12]), atol=1e-4)


def test_vit_forward_and_training():
    cfg = ViTConfig.debug()
    model = ViTModel(cfg)
    ts = make_train_step(model, optimizer=optax.adam(1e-3))
    params, opt = ts.init_fn(jax.random.key(0))
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)
    logits = model.apply(params, imgs)
    assert logits.shape == (8, 10)
    losses = []
    for _ in range(10):
        params, opt, m = ts.step_fn(params, opt, (imgs, labels))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_moe_forward_loss_and_training():
    cfg = MoEConfig.debug_moe()
    model = MoEModel(cfg)
    ts = make_train_step(model, optimizer=optax.adam(1e-3))
    params, opt = ts.init_fn(jax.random.key(0))
    assert "e_gate" in params["layers"] and "w_gate" not in params["layers"]
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32)), jnp.int32)
    losses = []
    for _ in range(8):
        params, opt, m = ts.step_fn(params, opt, (toks,
                                                  jnp.roll(toks, -1, 1)))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)


def test_moe_expert_parallel_sharded_step():
    """MoE trains over an ep=4 mesh axis; experts sharded."""
    spec = MeshSpec.auto(8, ep=4)
    mesh = build_mesh(spec, jax.devices()[:8])
    cfg = MoEConfig.debug_moe(num_experts=4)
    model = MoEModel(cfg, mesh=mesh)
    ts = make_train_step(model, mesh=mesh)
    params, opt = ts.init_fn(jax.random.key(0))
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)
    batch = shard_batch((toks, jnp.roll(toks, -1, 1)), ts)
    params, opt, m = ts.step_fn(params, opt, batch)
    assert np.isfinite(float(m["loss"]))
    # expert weights actually sharded over ep
    sh = jax.tree.leaves(ts.param_shardings)
    e_gate_sharding = ts.param_shardings["layers"]["e_gate"]
    assert "ep" in str(e_gate_sharding.spec)


def test_moe_router_balance_loss_positive():
    cfg = MoEConfig.debug_moe()
    model = MoEModel(cfg)
    params = model.init(jax.random.key(0))
    toks = jnp.zeros((1, 16), jnp.int32)
    logits, aux = model.apply_with_aux(params, toks)
    assert float(aux) > 0
    assert logits.shape == (1, 16, cfg.vocab_size)
