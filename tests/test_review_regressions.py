"""Regression tests for code-review findings on the core runtime."""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc


def test_wait_caps_ready_at_num_returns(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    refs = [f.remote() for _ in range(5)]
    ray_tpu.get(refs)  # all done
    ready, not_ready = ray_tpu.wait(refs, num_returns=1)
    assert len(ready) == 1
    assert len(not_ready) == 4


def test_method_decorator_num_returns(ray_start_regular):
    @ray_tpu.remote
    class A:
        @ray_tpu.method(num_returns=2)
        def pair(self):
            return "x", "y"

    a = A.remote()
    r1, r2 = a.pair.remote()
    assert ray_tpu.get([r1, r2]) == ["x", "y"]


def test_max_retries_minus_one_unlimited(ray_start_regular, tmp_path):
    # Out-of-band attempt counter: tasks run in worker processes behind a
    # serialization boundary, so driver-closure mutation must not leak.
    marker = str(tmp_path)

    @ray_tpu.remote(max_retries=-1, retry_exceptions=True)
    def flaky():
        import os
        n = len(os.listdir(marker))
        open(os.path.join(marker, str(n)), "w").close()
        if n + 1 < 6:
            raise RuntimeError("transient")
        return "ok"

    assert ray_tpu.get(flaky.remote()) == "ok"
    import os
    assert len(os.listdir(marker)) == 6


def test_kill_during_init_not_resurrected(ray_start_regular):
    @ray_tpu.remote
    class SlowInit:
        def __init__(self):
            time.sleep(1.0)

        def ping(self):
            return "pong"

    a = SlowInit.remote()
    time.sleep(0.1)
    ray_tpu.kill(a)
    with pytest.raises((exc.ActorDiedError, exc.ActorError)):
        ray_tpu.get(a.ping.remote(), timeout=15)
    # Resources freed: an 8-CPU task can still run.
    rt = ray_tpu._private.worker.global_runtime()
    time.sleep(1.2)  # let __init__ finish and the kill path release
    avail = ray_tpu.available_resources()
    assert avail.get("CPU") == 8.0


def test_actor_task_replay_on_node_death(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_restarts=1, max_task_retries=2)
    class Slow:
        def work(self):
            time.sleep(1.0)
            return "done"

        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = Slow.remote()
    node_hex = ray_tpu.get(a.node.remote())
    ref = a.work.remote()  # will be in flight when the node dies
    time.sleep(0.2)
    victim = next(n for n in rt.nodes() if n.node_id.hex() == node_hex)
    rt.remove_node(victim)
    # Replayed on the restarted incarnation, not crashed on func=None.
    assert ray_tpu.get(ref, timeout=30) == "done"


def test_failed_tasks_do_not_leak(ray_start_regular):
    rt = ray_tpu._private.worker.global_runtime()

    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("x")

    refs = [boom.remote() for _ in range(10)]
    for r in refs:
        with pytest.raises(ValueError):
            ray_tpu.get(r)
    time.sleep(0.1)
    with rt._tasks_lock:
        assert len(rt._tasks) == 0


def test_generator_retry_keeps_stream_binding(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=2)
    def gen():
        for i in range(3):
            yield i

    it = gen.remote()
    out = [ray_tpu.get(r) for r in it]
    assert out == [0, 1, 2]


def test_checkpoint_bfloat16_roundtrip(tmp_path):
    """ml_dtypes leaves (bf16 — the common TPU param dtype) must round-trip
    with their dtype intact, not as raw void (ADVICE r1 medium)."""
    import jax.numpy as jnp
    import ml_dtypes
    import numpy as np
    from ray_tpu.train.checkpoint import Checkpoint

    tree = {"w": jnp.ones((3, 4), jnp.bfloat16),
            "b": np.zeros((2,), np.float32),
            "e": np.float8_e4m3fn(1.5) if hasattr(np, "float8_e4m3fn")
                 else np.asarray(1.5, ml_dtypes.bfloat16),
            "step": 7}
    ckpt = Checkpoint.from_pytree(tree, str(tmp_path / "c"))
    back = ckpt.to_pytree()
    assert back["w"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        np.asarray(back["w"], np.float32), np.ones((3, 4), np.float32))
    assert back["b"].dtype == np.float32
    assert back["step"] == 7


def test_checkpoint_manager_latest_after_evict(tmp_path):
    """latest_checkpoint() must track registration order even after
    score-based eviction (ADVICE r1 low: in-place sort broke it)."""
    from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "root"), num_to_keep=2,
                            score_attribute="acc", score_order="max")
    paths = []
    for i, acc in enumerate([0.9, 0.1, 0.5]):
        d = tmp_path / f"src{i}"
        d.mkdir()
        (d / "x.txt").write_text(str(i))
        paths.append(mgr.register(Checkpoint(str(d)), {"acc": acc}))
    # acc=0.1 (2nd) evicted; latest must be the 3rd registered, best the 1st
    assert mgr.latest_checkpoint().path == paths[2]
    assert mgr.best_checkpoint().path == paths[0]


def test_stable_hash_partition_deterministic():
    """join/aggregate partitioning must not depend on PYTHONHASHSEED
    (ADVICE r1 low)."""
    import subprocess
    import sys

    code = ("from ray_tpu.data.execution import _stable_hash;"
            "print([_stable_hash(x) for x in"
            " ['key-a', b'key-b', 42, 3.5, True, None]])")
    outs = {
        subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            env={**__import__('os').environ, "PYTHONHASHSEED": seed,
                 "JAX_PLATFORMS": "cpu"},
        ).stdout.strip()
        for seed in ("0", "1", "12345")}
    assert len(outs) == 1 and "[" in next(iter(outs))


# ---------------------------------------------------------------------------
# round-6 fault-path regressions (ISSUE 1), driven through failpoints
# ---------------------------------------------------------------------------

@pytest.fixture
def _failpoints_reset():
    from ray_tpu._private import failpoints as fp
    yield fp
    fp.reset()


def test_lane_gen_fallback_runs_body_once(ray_start_regular, tmp_path):
    """A plain function with a side effect that RETURNS a generator
    object must run its body exactly once per attempt. The old
    KIND_GEN_FALLBACK protocol re-ran it classically after the lane
    execution (side effects doubled); the worker now drains the
    generator in place and ships KIND_GEN_LIST."""
    marker = str(tmp_path / "ran.log")

    @ray_tpu.remote
    def gen_side_effect():
        with open(marker, "a") as fh:
            fh.write("x")
        return (i * 10 for i in range(4))

    # a plain task returning a generator resolves to the streaming
    # sentinel; drain the stream it names end to end
    from ray_tpu._private.worker import _StreamingGeneratorSentinel
    from ray_tpu.remote_function import ObjectRefGenerator

    sentinel = ray_tpu.get(gen_side_effect.remote())
    assert isinstance(sentinel, _StreamingGeneratorSentinel)
    out = [ray_tpu.get(r) for r in ObjectRefGenerator(sentinel.task_id)]
    assert out == [0, 10, 20, 30]
    import os
    assert os.path.exists(marker)
    with open(marker) as fh:
        assert fh.read() == "x", "generator-returning body ran twice"


def test_oom_check_fast_lane_scoping():
    """daemon.handle_oom_check: the un-attributed-kill fallback must be
    claimed ONLY for fast-lane crashes — a classic segfault inside the
    attribution window must not steal (and consume) the lane crash's
    OOM entry."""
    import time as _time

    from ray_tpu._private.daemon import DaemonService
    from ray_tpu._private.memory_monitor import MemoryMonitor

    svc = DaemonService.__new__(DaemonService)   # handler needs only
    mon = MemoryMonitor.__new__(MemoryMonitor)   # these attributes
    mon.kills = 1
    mon.oom_killed_tasks = set()
    mon.kill_log = [(1234, _time.time(), False)]  # one unattributed kill
    svc.memory_monitor = mon

    # classic crash: fallback NOT taken, entry NOT consumed
    out = DaemonService.handle_oom_check(
        svc, None, None, {"task_id": "feedface", "fast_lane": False})
    assert out["oom"] is False
    assert mon.kill_log[0][2] is False, "classic crash consumed the entry"

    # the lane crash that the kill actually explains still claims it
    out = DaemonService.handle_oom_check(
        svc, None, None, {"task_id": "", "fast_lane": True})
    assert out["oom"] is True
    assert mon.kill_log[0][2] is True
    # and one kill explains exactly one crash
    out = DaemonService.handle_oom_check(
        svc, None, None, {"task_id": "", "fast_lane": True})
    assert out["oom"] is False


class _FakeLane:
    def __init__(self, dead=False):
        self.dead = dead
        self.cancelled = []

    def cancel(self, rid, force=False):
        self.cancelled.append((rid, force))


def test_cancel_uses_submitting_lane_generation_daemon_handle():
    """DaemonHandle.cancel_task must send the cancel to the lane CLIENT
    the task was submitted on. After a lane death + reconnect the new
    client's rid counter restarts at 1 — a stale rid sent there would
    kill an unrelated task."""
    import threading

    from ray_tpu._private.cluster import DaemonHandle
    from ray_tpu._private.ids import TaskID

    handle = DaemonHandle.__new__(DaemonHandle)
    handle._fast_lock = threading.Lock()
    old_lane, new_lane = _FakeLane(), _FakeLane()
    task_id = TaskID.from_random()
    handle._fast_rids = {task_id.hex(): (old_lane, 7)}
    handle._fast = new_lane                      # reconnected client
    assert handle.cancel_task(task_id, force=False) is True
    assert old_lane.cancelled == [(7, False)]
    assert new_lane.cancelled == []              # never the new client

    # dead submitting lane: no cancel bytes anywhere, no crash
    old_lane.dead = True
    old_lane.cancelled.clear()
    handle._fast_rids = {task_id.hex(): (old_lane, 7)}
    assert handle.cancel_task(task_id, force=True) is True
    assert old_lane.cancelled == [] and new_lane.cancelled == []


def test_cancel_uses_submitting_lane_generation_process_router(
        monkeypatch):
    """Same generation rule for the driver-local lane
    (ProcessRouter.cancel_task)."""
    monkeypatch.setenv("RAY_TPU_PROCESS_WORKERS", "0")
    from ray_tpu._private.ids import TaskID
    from ray_tpu._private.worker_process import ProcessRouter

    router = ProcessRouter(runtime=None)
    old_lane, new_lane = _FakeLane(), _FakeLane()
    task_id = TaskID.from_random()
    router._fast_rids = {task_id.hex(): (old_lane, 3)}
    router._fast = new_lane
    assert router.cancel_task(task_id, force=True) is True
    assert old_lane.cancelled == [(3, True)]
    assert new_lane.cancelled == []


def test_fast_lane_ping_slot_leak(_failpoints_reset):
    """FastLaneClient.ping send failure: pending slot popped, lane
    marked dead, typed FastLaneError raised (not a raw OSError leaking
    into daemon stats paths)."""
    import socket

    from ray_tpu._private import fast_lane as fle

    fp = _failpoints_reset
    srv = socket.create_server(("127.0.0.1", 0))
    client = fle.FastLaneClient(srv.getsockname())
    try:
        # the fast_lane.submit seam fires inside _submit_op AFTER the
        # pending slot is installed: ping() now routes through it, so a
        # revert of the pop-on-send-failure cleanup fails this assert
        fp.activate("fast_lane.submit=error(OSError)")
        with pytest.raises(fle.FastLaneError):
            client.ping(timeout=0.5)
        assert client.dead and not client._pending
    finally:
        client.close()
        srv.close()
