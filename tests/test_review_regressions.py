"""Regression tests for code-review findings on the core runtime."""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc


def test_wait_caps_ready_at_num_returns(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    refs = [f.remote() for _ in range(5)]
    ray_tpu.get(refs)  # all done
    ready, not_ready = ray_tpu.wait(refs, num_returns=1)
    assert len(ready) == 1
    assert len(not_ready) == 4


def test_method_decorator_num_returns(ray_start_regular):
    @ray_tpu.remote
    class A:
        @ray_tpu.method(num_returns=2)
        def pair(self):
            return "x", "y"

    a = A.remote()
    r1, r2 = a.pair.remote()
    assert ray_tpu.get([r1, r2]) == ["x", "y"]


def test_max_retries_minus_one_unlimited(ray_start_regular, tmp_path):
    # Out-of-band attempt counter: tasks run in worker processes behind a
    # serialization boundary, so driver-closure mutation must not leak.
    marker = str(tmp_path)

    @ray_tpu.remote(max_retries=-1, retry_exceptions=True)
    def flaky():
        import os
        n = len(os.listdir(marker))
        open(os.path.join(marker, str(n)), "w").close()
        if n + 1 < 6:
            raise RuntimeError("transient")
        return "ok"

    assert ray_tpu.get(flaky.remote()) == "ok"
    import os
    assert len(os.listdir(marker)) == 6


def test_kill_during_init_not_resurrected(ray_start_regular):
    @ray_tpu.remote
    class SlowInit:
        def __init__(self):
            time.sleep(1.0)

        def ping(self):
            return "pong"

    a = SlowInit.remote()
    time.sleep(0.1)
    ray_tpu.kill(a)
    with pytest.raises((exc.ActorDiedError, exc.ActorError)):
        ray_tpu.get(a.ping.remote(), timeout=15)
    # Resources freed: an 8-CPU task can still run.
    rt = ray_tpu._private.worker.global_runtime()
    time.sleep(1.2)  # let __init__ finish and the kill path release
    avail = ray_tpu.available_resources()
    assert avail.get("CPU") == 8.0


def test_actor_task_replay_on_node_death(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_restarts=1, max_task_retries=2)
    class Slow:
        def work(self):
            time.sleep(1.0)
            return "done"

        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = Slow.remote()
    node_hex = ray_tpu.get(a.node.remote())
    ref = a.work.remote()  # will be in flight when the node dies
    time.sleep(0.2)
    victim = next(n for n in rt.nodes() if n.node_id.hex() == node_hex)
    rt.remove_node(victim)
    # Replayed on the restarted incarnation, not crashed on func=None.
    assert ray_tpu.get(ref, timeout=30) == "done"


def test_failed_tasks_do_not_leak(ray_start_regular):
    rt = ray_tpu._private.worker.global_runtime()

    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("x")

    refs = [boom.remote() for _ in range(10)]
    for r in refs:
        with pytest.raises(ValueError):
            ray_tpu.get(r)
    time.sleep(0.1)
    with rt._tasks_lock:
        assert len(rt._tasks) == 0


def test_generator_retry_keeps_stream_binding(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=2)
    def gen():
        for i in range(3):
            yield i

    it = gen.remote()
    out = [ray_tpu.get(r) for r in it]
    assert out == [0, 1, 2]


def test_checkpoint_bfloat16_roundtrip(tmp_path):
    """ml_dtypes leaves (bf16 — the common TPU param dtype) must round-trip
    with their dtype intact, not as raw void (ADVICE r1 medium)."""
    import jax.numpy as jnp
    import ml_dtypes
    import numpy as np
    from ray_tpu.train.checkpoint import Checkpoint

    tree = {"w": jnp.ones((3, 4), jnp.bfloat16),
            "b": np.zeros((2,), np.float32),
            "e": np.float8_e4m3fn(1.5) if hasattr(np, "float8_e4m3fn")
                 else np.asarray(1.5, ml_dtypes.bfloat16),
            "step": 7}
    ckpt = Checkpoint.from_pytree(tree, str(tmp_path / "c"))
    back = ckpt.to_pytree()
    assert back["w"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        np.asarray(back["w"], np.float32), np.ones((3, 4), np.float32))
    assert back["b"].dtype == np.float32
    assert back["step"] == 7


def test_checkpoint_manager_latest_after_evict(tmp_path):
    """latest_checkpoint() must track registration order even after
    score-based eviction (ADVICE r1 low: in-place sort broke it)."""
    from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "root"), num_to_keep=2,
                            score_attribute="acc", score_order="max")
    paths = []
    for i, acc in enumerate([0.9, 0.1, 0.5]):
        d = tmp_path / f"src{i}"
        d.mkdir()
        (d / "x.txt").write_text(str(i))
        paths.append(mgr.register(Checkpoint(str(d)), {"acc": acc}))
    # acc=0.1 (2nd) evicted; latest must be the 3rd registered, best the 1st
    assert mgr.latest_checkpoint().path == paths[2]
    assert mgr.best_checkpoint().path == paths[0]


def test_stable_hash_partition_deterministic():
    """join/aggregate partitioning must not depend on PYTHONHASHSEED
    (ADVICE r1 low)."""
    import subprocess
    import sys

    code = ("from ray_tpu.data.execution import _stable_hash;"
            "print([_stable_hash(x) for x in"
            " ['key-a', b'key-b', 42, 3.5, True, None]])")
    outs = {
        subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            env={**__import__('os').environ, "PYTHONHASHSEED": seed,
                 "JAX_PLATFORMS": "cpu"},
        ).stdout.strip()
        for seed in ("0", "1", "12345")}
    assert len(outs) == 1 and "[" in next(iter(outs))


# ---------------------------------------------------------------------------
# round-6 fault-path regressions (ISSUE 1), driven through failpoints
# ---------------------------------------------------------------------------

@pytest.fixture
def _failpoints_reset():
    from ray_tpu._private import failpoints as fp
    yield fp
    fp.reset()


def test_lane_gen_fallback_runs_body_once(ray_start_regular, tmp_path):
    """A plain function with a side effect that RETURNS a generator
    object must run its body exactly once per attempt. The old
    KIND_GEN_FALLBACK protocol re-ran it classically after the lane
    execution (side effects doubled); the worker now drains the
    generator in place and ships KIND_GEN_LIST."""
    marker = str(tmp_path / "ran.log")

    @ray_tpu.remote
    def gen_side_effect():
        with open(marker, "a") as fh:
            fh.write("x")
        return (i * 10 for i in range(4))

    # a plain task returning a generator resolves to the streaming
    # sentinel; drain the stream it names end to end
    from ray_tpu._private.worker import _StreamingGeneratorSentinel
    from ray_tpu.remote_function import ObjectRefGenerator

    sentinel = ray_tpu.get(gen_side_effect.remote())
    assert isinstance(sentinel, _StreamingGeneratorSentinel)
    out = [ray_tpu.get(r) for r in ObjectRefGenerator(sentinel.task_id)]
    assert out == [0, 10, 20, 30]
    import os
    assert os.path.exists(marker)
    with open(marker) as fh:
        assert fh.read() == "x", "generator-returning body ran twice"


def test_oom_check_fast_lane_scoping():
    """daemon.handle_oom_check: the un-attributed-kill fallback must be
    claimed ONLY for fast-lane crashes — a classic segfault inside the
    attribution window must not steal (and consume) the lane crash's
    OOM entry."""
    import time as _time

    from ray_tpu._private.daemon import DaemonService
    from ray_tpu._private.memory_monitor import MemoryMonitor

    svc = DaemonService.__new__(DaemonService)   # handler needs only
    mon = MemoryMonitor.__new__(MemoryMonitor)   # these attributes
    mon.kills = 1
    mon.oom_killed_tasks = set()
    mon.kill_log = [(1234, _time.time(), False)]  # one unattributed kill
    svc.memory_monitor = mon

    # classic crash: fallback NOT taken, entry NOT consumed
    out = DaemonService.handle_oom_check(
        svc, None, None, {"task_id": "feedface", "fast_lane": False})
    assert out["oom"] is False
    assert mon.kill_log[0][2] is False, "classic crash consumed the entry"

    # the lane crash that the kill actually explains still claims it
    out = DaemonService.handle_oom_check(
        svc, None, None, {"task_id": "", "fast_lane": True})
    assert out["oom"] is True
    assert mon.kill_log[0][2] is True
    # and one kill explains exactly one crash
    out = DaemonService.handle_oom_check(
        svc, None, None, {"task_id": "", "fast_lane": True})
    assert out["oom"] is False


class _FakeLane:
    def __init__(self, dead=False):
        self.dead = dead
        self.cancelled = []

    def cancel(self, rid, force=False):
        self.cancelled.append((rid, force))


def test_cancel_uses_submitting_lane_generation_daemon_handle():
    """DaemonHandle.cancel_task must send the cancel to the lane CLIENT
    the task was submitted on. After a lane death + reconnect the new
    client's rid counter restarts at 1 — a stale rid sent there would
    kill an unrelated task."""
    import threading

    from ray_tpu._private.cluster import DaemonHandle
    from ray_tpu._private.ids import TaskID

    handle = DaemonHandle.__new__(DaemonHandle)
    handle._fast_lock = threading.Lock()
    old_lane, new_lane = _FakeLane(), _FakeLane()
    task_id = TaskID.from_random()
    handle._fast_rids = {task_id.hex(): (old_lane, 7)}
    handle._fast = new_lane                      # reconnected client
    assert handle.cancel_task(task_id, force=False) is True
    assert old_lane.cancelled == [(7, False)]
    assert new_lane.cancelled == []              # never the new client

    # dead submitting lane: no cancel bytes anywhere, no crash
    old_lane.dead = True
    old_lane.cancelled.clear()
    handle._fast_rids = {task_id.hex(): (old_lane, 7)}
    assert handle.cancel_task(task_id, force=True) is True
    assert old_lane.cancelled == [] and new_lane.cancelled == []


def test_cancel_uses_submitting_lane_generation_process_router(
        monkeypatch):
    """Same generation rule for the driver-local lane
    (ProcessRouter.cancel_task)."""
    monkeypatch.setenv("RAY_TPU_PROCESS_WORKERS", "0")
    from ray_tpu._private.ids import TaskID
    from ray_tpu._private.worker_process import ProcessRouter

    router = ProcessRouter(runtime=None)
    old_lane, new_lane = _FakeLane(), _FakeLane()
    task_id = TaskID.from_random()
    router._fast_rids = {task_id.hex(): (old_lane, 3)}
    router._fast = new_lane
    assert router.cancel_task(task_id, force=True) is True
    assert old_lane.cancelled == [(3, True)]
    assert new_lane.cancelled == []


def test_fast_lane_ping_slot_leak(_failpoints_reset):
    """FastLaneClient.ping send failure: pending slot popped, lane
    marked dead, typed FastLaneError raised (not a raw OSError leaking
    into daemon stats paths)."""
    import socket

    from ray_tpu._private import fast_lane as fle

    fp = _failpoints_reset
    srv = socket.create_server(("127.0.0.1", 0))
    client = fle.FastLaneClient(srv.getsockname())
    try:
        # the fast_lane.submit seam fires inside _submit_op AFTER the
        # pending slot is installed: ping() now routes through it, so a
        # revert of the pop-on-send-failure cleanup fails this assert
        fp.activate("fast_lane.submit=error(OSError)")
        with pytest.raises(fle.FastLaneError):
            client.ping(timeout=0.5)
        assert client.dead and not client._pending
    finally:
        client.close()
        srv.close()


def _lane_client_with_sendall(srv, sendall_fn):
    """FastLaneClient against a dummy server, with sendall intercepted
    (socket objects reject attribute assignment, so proxy the sock)."""
    from ray_tpu._private import fast_lane as fle

    client = fle.FastLaneClient(srv.getsockname())

    class _SockProxy:
        def __init__(self, sock):
            self._s = sock

        def __getattr__(self, name):
            return getattr(self._s, name)

        def sendall(self, data):
            return sendall_fn(self._s, data)

    client._sock = _SockProxy(client._sock)
    return client


def test_send_stage_later_pass_failure_is_post_submit():
    """Flat-combining send stage: a flusher whose OWN frame already hit
    the wire must not see a LATER pass's send failure as a submit
    error — submit() returns and the failed slot surfaces via wait()
    ("died mid-call" -> retry accounting). Raising there made the
    classic fallback re-run a task the daemon was already executing."""
    import socket

    from ray_tpu._private import fast_lane as fle

    srv = socket.create_server(("127.0.0.1", 0))
    calls = []
    holder = {}

    def sendall(sock, data):
        calls.append(bytes(data))
        if len(calls) == 1:
            # a thread staging mid-pass sees _send_flushing True and
            # returns: the SAME drain's pass 2 flushes it — and fails
            with holder["c"]._stage_lock:
                holder["c"]._send_stage.append(
                    (b"other-thread-frame", None))
            return sock.sendall(data)
        raise OSError("connection lost mid-burst")

    client = holder["c"] = _lane_client_with_sendall(srv, sendall)
    try:
        rid, slot = client.submit(b"payload")   # must NOT raise
        assert len(calls) == 2 and client.dead
        with pytest.raises(fle.FastLaneError):
            client.wait(slot, timeout=0.5)
    finally:
        client.close()
        srv.close()


def test_send_stage_sole_frame_failure_still_raises_to_submitter():
    """Sole-frame failed write: sendall raising guarantees the daemon
    can't hold a complete frame, so submit() raises and the classic
    fallback stays safe. An own frame sharing a failed MULTI-frame
    write (its bytes may have reached the daemon) must not raise."""
    import socket

    from ray_tpu._private import fast_lane as fle

    srv = socket.create_server(("127.0.0.1", 0))

    def sendall_fail(sock, data):
        raise OSError("connection lost")

    client = _lane_client_with_sendall(srv, sendall_fail)
    try:
        with pytest.raises(fle.FastLaneError):
            client.submit(b"payload")
        assert client.dead and not client._pending
    finally:
        client.close()
        srv.close()

    srv = socket.create_server(("127.0.0.1", 0))
    client = _lane_client_with_sendall(srv, sendall_fail)
    try:
        with client._stage_lock:        # rides the first write too
            client._send_stage.append((b"earlier-staged-frame", None))
        rid, slot = client.submit(b"payload")   # must NOT raise
        assert client.dead
        with pytest.raises(fle.FastLaneError):
            client.wait(slot, timeout=0.5)
    finally:
        client.close()
        srv.close()


def test_send_stage_unwritten_frame_resolves_unsubmitted():
    """A frame still STAGED when another thread's flush fails provably
    never reached the wire: its slot must resolve FastLaneUnsubmitted
    (callers take the classic path retry-free), not lane death (which
    consumes a retry — with max_retries=0 a never-submitted task
    failed permanently)."""
    import socket
    import threading

    from ray_tpu._private import fast_lane as fle

    srv = socket.create_server(("127.0.0.1", 0))
    holder = {}

    def sendall(sock, data):
        # stage a second submitter's frame (with a live pending slot)
        # mid-write, then fail the write: the staged frame was never
        # part of any sendall
        c = holder["c"]
        rid2 = next(c._rids)
        slot2 = [threading.Event(), None, None]
        with c._plock:
            c._pending[rid2] = slot2
        with c._stage_lock:
            c._send_stage.append((b"unwritten-frame", rid2))
        holder["slot2"] = slot2
        raise OSError("connection lost")

    client = holder["c"] = _lane_client_with_sendall(srv, sendall)
    try:
        with pytest.raises(fle.FastLaneError):
            client.submit(b"payload")   # sole own frame: submit raises
        with pytest.raises(fle.FastLaneUnsubmitted):
            client.wait(holder["slot2"], timeout=0.5)
        assert client.dead and not client._pending
    finally:
        client.close()
        srv.close()


def test_drain_steals_pool_pending_when_backlog_empty():
    """Ample ledger capacity admits every queued task straight into the
    exec-pool queue (node backlog EMPTY); graceful drain must still
    steal the unstarted specs back retry-free. The backlog-gated drain
    pass skipped the pool queue entirely: the specs burned down
    serially on the tiny pool, the deadline escalated, and
    max_retries=0 tasks that never started failed permanently."""
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 64},
                      _system_config={"exec_pool_size": 2})
    try:
        @ray_tpu.remote(max_retries=0)
        def slowish(i):
            time.sleep(0.4)
            return i

        refs = [slowish.remote(i) for i in range(40)]
        time.sleep(0.3)     # dispatch admits everything into the pools
        victim = ray_tpu.nodes()[0]["NodeID"]
        # deadline far below the serial burn-down time of the pool
        # queue (20 specs x 0.4s / 2 threads = 4s): without handback
        # the drain escalates and the never-started specs are lost
        ray_tpu.drain_node(victim, deadline_s=3.0)
        assert ray_tpu.get(refs, timeout=120) == list(range(40))
        assert rt.stats["tasks_retried"] == 0
    finally:
        ray_tpu.shutdown()


def test_nbytes_of_accounts_big_ints():
    """int is arbitrary-precision: the trivial-type fast path must not
    book a multi-MB int as 32 bytes (store _used stayed near zero, so
    eviction/spill and OOM thresholds never fired)."""
    import sys as _sys

    from ray_tpu._private.object_store import _nbytes_of

    big = 10 ** 10000
    assert _nbytes_of(big) >= _sys.getsizeof(big) > 4000
    assert _nbytes_of(5) <= 64
    assert _nbytes_of(None) == 32
    assert _nbytes_of(1.5) == 32
    assert _nbytes_of(True) == 32


def test_stream_terminations_gated_on_term_pump():
    """Coalesced stream terminations are a driver-advertised capability
    (entry flag ``term_pump``): a driver that did not set it (an older
    release on a persistent daemon) must get the classic per-task
    termination push — coalescing it would strand that driver's
    stream consumer forever."""
    from ray_tpu._private.daemon import _BatchTaskConn

    class FakePump:
        def __init__(self):
            self.added = []

        def add(self, conn, out):
            self.added.append(out)

    class FakeService:
        epoch = 0

        def __init__(self):
            self._batch_pump = FakePump()

    class FakeConn:
        closed = False

        def __init__(self):
            self.pushed = []

        def push(self, method, **kw):
            self.pushed.append((method, kw))

    svc, conn = FakeService(), FakeConn()
    old = _BatchTaskConn(svc, conn, "t1", ("t1", 0))    # no term_pump
    old.push("task_stream_end", task="t1")
    assert conn.pushed == [("task_stream_end", {"task": "t1"})]
    assert svc._batch_pump.added == []
    new = _BatchTaskConn(svc, conn, "t2", ("t2", 0), term_pump=True)
    new.push("task_stream_end", task="t2")
    assert [o["stream"] for o in svc._batch_pump.added] == [
        "task_stream_end"]
    assert len(conn.pushed) == 1    # still only the ungated driver's


def test_send_stage_large_frame_keeps_fifo_order():
    """A >SEND_CONCAT_MAX payload must ride the send stage in FIFO
    position (as a two-part entry), not bypass it: the old direct
    write under the wire lock could overtake the SAME thread's earlier
    staged small frame, executing two calls to one actor in reverse
    submission order."""
    import socket
    import threading

    from ray_tpu._private.fast_lane import (
        _SEND_CONCAT_MAX, FastLaneClient)

    srv = socket.create_server(("127.0.0.1", 0))
    writes = []

    def sendall(sock, data):
        writes.append(bytes(data))

    client = _lane_client_with_sendall(srv, sendall)
    try:
        # park a small frame in the stage (flusher "active" elsewhere)
        with client._stage_lock:
            client._send_flushing = True
        client._send(0x01, b"", b"small-first", rid=None)
        assert not writes              # staged, not written
        with client._stage_lock:
            client._send_flushing = False
        # the SAME thread now sends a large frame: it must flush the
        # earlier small frame first, in order
        big = b"x" * (_SEND_CONCAT_MAX + 1)
        client._send(0x02, b"", big, rid=None)
        blob = b"".join(writes)
        assert blob.index(b"small-first") < blob.index(b"x" * 64)
        # the big payload was written whole, never concat-copied into
        # a joined batch buffer (its sendall is the payload alone)
        assert big in writes
    finally:
        client.close()
        srv.close()


# ---------------------------------------------------------------------------
# raylint v2 findings on the real runtime (ISSUE 14): each fix below is
# pinned by running the flagging pass over the REAL module — a revert
# reintroduces the exact finding the pass was built to catch.
# ---------------------------------------------------------------------------

def _raylint_ctx(*relpaths):
    import os

    from tools.raylint.core import Context, load_modules

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(root, p) for p in relpaths]
    return Context(modules=load_modules(paths, root), repo_root=root)


def test_actor_executor_retains_loop_tasks():
    """node.py ActorExecutor loop: create_task() results must be
    retained (asyncio keeps only weak refs — an unretained handle()
    task can be GC'd mid-await, dropping the actor call). The fix
    routes every spawn through an inflight set; reverting it brings
    back the pass's fire-and-forget finding."""
    from tools.raylint import async_discipline

    findings = async_discipline.run(_raylint_ctx("ray_tpu/_private/node.py"))
    assert [f.key for f in findings
            if f.key.startswith("fire-and-forget")] == []


def test_http_proxy_stream_submits_off_loop():
    """http_proxy._stream: handle.options(...).remote() is a full rpc
    round trip (lease + push) and must run on the stream pool, never
    directly on the event loop. Reverting the run_in_executor offload
    brings back the blocking-call finding."""
    from tools.raylint import async_discipline

    findings = async_discipline.run(
        _raylint_ctx("ray_tpu/serve/http_proxy.py"))
    assert [f.key for f in findings
            if f.key.startswith("blocking:_stream")] == []


def test_retry_metrics_documented():
    """Every ray_tpu_retry* series emitted by _private/retry.py must
    appear in docs/observability.md (the doc IS the operator contract;
    the drift was three undocumented retry counters)."""
    from tools.raylint import metric_registry

    ctx = _raylint_ctx("ray_tpu/_private/retry.py")
    assert metric_registry.run(ctx) == []
    doc = ctx.observability_doc()
    for name in ("ray_tpu_retries_total",
                 "ray_tpu_retry_backoff_seconds_total",
                 "ray_tpu_retry_exhausted_total"):
        assert name in doc


def test_exec_pool_handback_gate_skips_bounced():
    """The drain pass runs only while the pool queue holds specs it
    could still hand back: bounced-back specs (nowhere else fits) must
    not re-trigger steal/requeue churn every dispatch tick."""
    from ray_tpu._private.node import _ExecPool

    class Spec:
        def __init__(self, bounced):
            if bounced:
                self._drain_bounced = True

    pool = _ExecPool(1, lambda s: None, name="t")
    with pool._cv:      # keep workers from draining the queue
        pool._q.extend([Spec(True), Spec(True)])
    assert not pool.has_handback_pending()
    with pool._cv:
        pool._q.append(Spec(False))
    assert pool.has_handback_pending()
