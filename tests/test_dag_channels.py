"""Compiled-graph channels (reference: compiled_dag_node.py:809 —
pre-allocated channels, per-call execution skips the scheduler;
mutable-object channel role experimental_mutable_object_manager.h:44)."""

import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


@ray_tpu.remote(_in_process=True)
class Stage:
    def __init__(self, add, delay=0.0):
        self.add = add
        self.delay = delay
        self.calls = 0

    def work(self, x):
        self.calls += 1
        if self.delay:
            time.sleep(self.delay)
        return x + self.add

    def count(self):
        return self.calls


def test_channel_mode_engages_and_is_correct(ray_start_regular):
    a, b = Stage.remote(1), Stage.remote(10)
    with InputNode() as inp:
        dag = b.work.bind(a.work.bind(inp))
    compiled = dag.experimental_compile()
    assert compiled._executors is not None  # channel mode, not fallback
    assert ray_tpu.get(compiled.execute(5)) == 16
    assert ray_tpu.get(compiled.execute(100)) == 111
    # ops went through the real actor instances, in order
    assert ray_tpu.get(a.count.remote()) == 2
    compiled.teardown()
    with pytest.raises(RuntimeError):
        compiled.execute(1)


def test_channel_mode_pipelines_across_stages(ray_start_regular):
    """Two executions in flight overlap stage-wise: total wall clock is
    well under 2x the sequential path (the point of compiled channels)."""
    a, b = Stage.remote(0, delay=0.3), Stage.remote(0, delay=0.3)
    with InputNode() as inp:
        dag = b.work.bind(a.work.bind(inp))
    compiled = dag.experimental_compile()
    assert compiled._executors is not None
    t0 = time.monotonic()
    r1 = compiled.execute(1)
    r2 = compiled.execute(2)
    assert ray_tpu.get([r1, r2]) == [1, 2]
    elapsed = time.monotonic() - t0
    # sequential would be 4 x 0.3 = 1.2s; pipelined ~3 x 0.3 = 0.9s
    assert elapsed < 1.15, elapsed


def test_channel_mode_error_propagates(ray_start_regular):
    @ray_tpu.remote(_in_process=True)
    class Bad:
        def boom(self, x):
            raise ValueError("channel boom")

    bad = Bad.remote()
    with InputNode() as inp:
        dag = bad.boom.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled._executors is not None
    with pytest.raises(ValueError, match="channel boom"):
        ray_tpu.get(compiled.execute(1))


def test_multi_output_channels(ray_start_regular):
    a, b = Stage.remote(1), Stage.remote(2)
    with InputNode() as inp:
        dag = MultiOutputNode([a.work.bind(inp), b.work.bind(inp)])
    compiled = dag.experimental_compile()
    assert compiled._executors is not None
    assert ray_tpu.get(compiled.execute(10)) == [11, 12]


def test_task_node_falls_back_to_dynamic(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    with InputNode() as inp:
        dag = double.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled._executors is None   # fallback path
    assert ray_tpu.get(compiled.execute(4)) == 8


def test_process_actor_falls_back(ray_start_regular):
    @ray_tpu.remote
    class P:
        def m(self, x):
            return x + 1

    p = P.remote()
    ray_tpu.get(p.m.remote(0))   # ensure created
    with InputNode() as inp:
        dag = p.m.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled._executors is None
    assert ray_tpu.get(compiled.execute(1)) == 2
