"""Unit tests for crash-safe object-plane reclamation: the per-client
grant ledger, ``reclaim_client``, and the heartbeat orphan sweep
(docs/object_plane.md "Crash reclamation").

These run at the ObjectTable / native-store layer — no cluster boot —
so they stay in the tier-1 sweep. The end-to-end SIGKILL campaign
(worker mid-view, writer mid-direct-put, external attacher, and the
``arena.grant_reclaim`` / ``arena.reservation_sweep`` failpoint
backstops) lives in the chaos tier: tests/test_chaos.py.
"""

import os
import time
import uuid

import pytest

from ray_tpu.native_store import available

needs_native = pytest.mark.skipif(
    not available(), reason="native store unavailable (no compiler)")

pytestmark = needs_native

CAP = 4 * 1024 * 1024


@pytest.fixture
def table():
    from ray_tpu._private.daemon import ObjectTable
    t = ObjectTable(f"rtpu_t_{os.getpid()}_{uuid.uuid4().hex[:8]}",
                    CAP, sweep=False)
    if t._shm is None:
        t.close()
        pytest.skip("arena creation failed on this box")
    try:
        yield t
    finally:
        t.close()


def _pinned(table, oid=b"obj-1", n=200_000):
    """Store a pinned arena entry (blob above the inline threshold)."""
    table.put(oid, b"x" * n)
    return oid


def _refs(table, slot):
    return int(table._shm.ext_refs(slot))


# ---------------------------------------------------------------------------
# the satellite regression: reclaim frees deferred deletes via reap,
# NOT at daemon restart
# ---------------------------------------------------------------------------

def test_reclaim_frees_deferred_delete_without_restart(table):
    """A deferred-deleted entry whose LAST external ref is dropped by
    reclaim_client is freed by the reap reclaim itself runs — the bytes
    are re-allocatable immediately, no daemon restart involved."""
    oid = _pinned(table, n=int(CAP * 0.6))   # > half: two can't coexist
    meta = table.get_ext_meta(oid, "w:4242:1")
    assert meta is not None
    slot = meta[4]
    assert _refs(table, slot) == 1

    # delete while the grant pins it: deferred, bytes still held
    table.delete(oid)
    used_before = table.used_bytes()
    assert used_before >= int(CAP * 0.6)
    assert table.reserve(b"probe", int(CAP * 0.6)) is None  # no room

    dropped, aborted = table.reclaim_client("w:4242:1")
    assert dropped == 1 and aborted == 0
    assert _refs(table, slot) == 0
    # reclaim's own reap freed the deferred entry: same-size reserve
    # now fits in the same (still-running) table
    off = table.reserve(b"probe2", int(CAP * 0.6))
    assert off is not None


def test_double_reclaim_is_idempotent(table):
    """A second death signal for the same client finds an empty ledger:
    nothing is dropped twice (a re-drop would steal refs a later holder
    of the recycled slot legitimately owns)."""
    oid = _pinned(table)
    slot = table.get_ext_meta(oid, "w:7:1")[4]
    # a second, LIVE client shares the slot
    assert table.get_ext_meta(oid, "w:8:1")[4] == slot
    assert _refs(table, slot) == 2

    assert table.reclaim_client("w:7:1") == (1, 0)
    assert _refs(table, slot) == 1          # the live holder's ref
    assert table.reclaim_client("w:7:1") == (0, 0)   # idempotent
    assert _refs(table, slot) == 1
    assert table.reclaim_client("w:8:1") == (1, 0)
    assert _refs(table, slot) == 0


# ---------------------------------------------------------------------------
# the safe bound: ledgers over-count, reclaim never steals
# ---------------------------------------------------------------------------

def test_reclaim_never_steals_a_live_clients_ref(table):
    """The dead client already released one grant with a silent local
    atomic (its ledger over-counts): reclaim drops only
    observed - other clients' charges, leaving the live reader's ref."""
    oid = _pinned(table)
    slot = table.get_ext_meta(oid, "w:100:1")[4]    # dead-to-be
    table.get_ext_meta(oid, "w:100:1")              # granted twice
    table.get_ext_meta(oid, "w:200:1")              # live co-holder
    assert _refs(table, slot) == 3
    table._shm.ext_release(slot)    # dead client's silent release
    assert _refs(table, slot) == 2

    # ledger says 2, but only 1 is really theirs (2 observed - 1 other)
    assert table.reclaim_client("w:100:1") == (1, 0)
    assert _refs(table, slot) == 1  # live holder keeps reading


def test_reclaim_aborts_unsealed_reservations(table):
    """A writer that dies between reserve and seal: reclaim aborts its
    reservation and the key is clean for reuse."""
    assert table.reserve(b"res-1", 1 << 20, "w:55:1") is not None
    assert table.reserve(b"res-2", 1 << 20, "w:55:1") is not None
    used = table.used_bytes()
    assert used >= 2 << 20

    dropped, aborted = table.reclaim_client("w:55:1")
    assert aborted == 2 and dropped == 0
    assert table.used_bytes() < used
    assert table.reserve(b"res-1", 1 << 20, "w:56:1") is not None


def test_sealed_reservation_is_not_reclaimed(table):
    """Seal clears the reservation charge: the writer dying AFTER seal
    must not drop the sealed entry."""
    off = table.reserve(b"sealed", 300_000, "w:9:1")
    assert off is not None
    assert table.seal(b"sealed")
    assert table.reclaim_client("w:9:1") == (0, 0)
    assert table.get_blob(b"sealed") is not None


# ---------------------------------------------------------------------------
# the heartbeat sweep: stale reservations + ledger-drift true-up
# ---------------------------------------------------------------------------

def test_stale_reservations_respect_ttl(table):
    table.reserve(b"old", 4096, "c:abc")
    assert table.stale_reservations(ttl=0.0) == [b"old"]
    assert table.stale_reservations(ttl=3600.0) == []
    time.sleep(0.02)
    assert table.stale_reservations(ttl=0.01) == [b"old"]
    table.abort_reserve(b"old")
    assert table.stale_reservations(ttl=0.0) == []


def test_sweep_force_drops_holderless_refs(table):
    """A slot with outstanding refs but NO ledger holder carries only
    refs of already-reclaimed clients (reclaim's bound left a residue):
    the sweep forces them to zero."""
    oid = _pinned(table)
    slot = table.get_ext_meta(oid, "w:1:1")[4]
    # an untagged incref (legacy path / pre-ledger client): invisible
    # to the ledger, so reclaim's safe bound strands it...
    table._shm.get_ext(oid)
    assert _refs(table, slot) == 2
    assert table.reclaim_client("w:1:1") == (1, 0)
    assert _refs(table, slot) == 1
    # ...until the orphan sweep sees refs with no registered holder
    assert table.sweep_orphan_slots() == 1
    assert _refs(table, slot) == 0


def test_sweep_clamps_single_holder_overcount(table):
    """Silent local releases shrink observed refs below the single
    holder's charge: the sweep clamps the ledger down (preserving the
    ledger >= actual invariant) without dropping the live ref."""
    oid = _pinned(table)
    slot = table.get_ext_meta(oid, "w:3:1")[4]
    table.get_ext_meta(oid, "w:3:1")
    table.get_ext_meta(oid, "w:3:1")
    assert _refs(table, slot) == 3
    table._shm.ext_release(slot)     # two silent client-side releases
    table._shm.ext_release(slot)
    assert _refs(table, slot) == 1

    assert table.sweep_orphan_slots() == 0      # clamp, not drop
    assert _refs(table, slot) == 1
    assert table._ext_slots["w:3:1"][slot] == 1
    # the clamped ledger now reclaims EXACTLY what the client holds
    assert table.reclaim_client("w:3:1") == (1, 0)
    assert _refs(table, slot) == 0


def test_sweep_does_not_touch_live_grants(table):
    oid = _pinned(table)
    slot = table.get_ext_meta(oid, "w:11:1")[4]
    assert table.sweep_orphan_slots() == 0
    assert _refs(table, slot) == 1


# ---------------------------------------------------------------------------
# observability: attribution rows + zero-ref pruning
# ---------------------------------------------------------------------------

def test_slot_ref_stats_attribution_and_pruning(table):
    oid = _pinned(table)
    slot = table.get_ext_meta(oid, "w:21:1")[4]
    table.get_ext_meta(oid, "w:21:1")
    table.get_ext_meta(oid, "c:deadbeef")

    stats = table.slot_ref_stats(attribution=True)
    assert stats["refs"] == 3 and stats["held"] == 1
    rows = {r["client"]: r for r in stats["clients"]}
    assert rows["w:21:1"]["granted"] == 2
    assert rows["c:deadbeef"]["granted"] == 1

    # tagged owner-side releases retire the charges with the refs
    table.ext_release(slot, "w:21:1")
    table.ext_release(slot, "w:21:1")
    table.ext_release(slot, "c:deadbeef")
    stats = table.slot_ref_stats(attribution=True)
    assert stats == {"held": 0, "refs": 0, "clients": []}
    # fully-released slots leave tracking entirely
    assert table._slot_owners == {} and table._ext_slots == {}


def test_ledger_clients_lists_grants_and_reservations(table):
    oid = _pinned(table)
    table.get_ext_meta(oid, "w:31:1")
    table.reserve(b"r", 4096, "c:conn1")
    assert table.ledger_clients() == ["c:conn1", "w:31:1"]


# ---------------------------------------------------------------------------
# native bulk op
# ---------------------------------------------------------------------------

def test_ext_release_n_floors_at_zero(table):
    """rtpu_ext_release_n drops at most what the slot holds and reports
    what it actually dropped (CAS loop floored at zero)."""
    oid = _pinned(table)
    slot = table.get_ext_meta(oid, "w:41:1")[4]
    table.get_ext_meta(oid, "w:41:1")
    assert _refs(table, slot) == 2
    assert table._shm.ext_release_n(slot, 5) == 2   # clamped
    assert _refs(table, slot) == 0
    assert table._shm.ext_release_n(slot, 1) == 0   # already zero
    assert _refs(table, slot) == 0
