"""The BASELINE target-config examples must run end-to-end."""

import sys

import pytest

sys.path.insert(0, "/root/repo/examples")


def test_mnist_mlp_example(ray_start_regular):
    import train_mnist_mlp
    result = train_mnist_mlp.main()
    assert result.error is None
    epochs = {e["metrics"]["epoch"] for e in result.metrics_history}
    assert 1 in epochs  # both epochs ran


def test_gpt2_dp_example():
    import train_gpt2_dp
    loss = train_gpt2_dp.main(debug=True, steps=3)
    assert loss > 0


def test_llama_fsdp_example():
    import train_llama_fsdp
    loss = train_llama_fsdp.main(debug=True, steps=2)
    assert loss > 0


def test_vit_streaming_example(ray_start_regular):
    import data_vit_streaming
    result = data_vit_streaming.main()
    assert result.error is None


def test_serve_llama_example(ray_start_regular):
    import serve_llama
    out = serve_llama.main()
    assert out["usage"]["completion_tokens"] == 8


def test_serve_llama_example_load_mode(ray_start_regular):
    """--load runs a short open-loop burst through ray_tpu.loadgen and
    returns the SLO report (the single-request demo stays default)."""
    import serve_llama
    rep = serve_llama.main(["--load", "--rate", "6",
                            "--duration", "1.5", "--clients", "4"])
    assert rep["requests"]["errors"] == 0
    assert rep["requests"]["completed"] == rep["scheduled_requests"] > 0
    assert rep["goodput"]["slo"] == {"ttft_s": 1.0, "e2e_s": 5.0}


def test_compiled_dag_pipeline_example():
    # pinned local: the example demonstrates (and asserts) the
    # driver-pool shm-channel mode, which correctly degrades to the
    # dynamic path under the daemons topology
    import ray_tpu
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 8},
                      cluster="local")
    try:
        import compiled_dag_pipeline
        outs = compiled_dag_pipeline.main(rounds=20)
        assert len(outs) == 20
        assert all(isinstance(o, float) for o in outs)
    finally:
        ray_tpu.shutdown()


def test_full_stack_pipeline_example(ray_start_regular):
    """Data -> Train -> Tune (TPE) -> Serve/HTTP in one runtime."""
    import full_stack_pipeline

    out = full_stack_pipeline.main(samples=256, trials=3)
    assert abs(out["w"] - 3.0) < 0.5
    assert abs(out["b"] - 1.0) < 0.5
    expected = out["w"] * 0.5 + out["b"]
    assert abs(out["served_prediction"] - expected) < 1e-6
