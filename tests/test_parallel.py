"""Mesh / topology / pipeline / collective tests (8 virtual CPU devices)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import (MeshSpec, TpuTopology, build_mesh,
                              logical_to_spec, mesh_from_string,
                              named_sharding, pipelined, shard_constraint)


def test_devices_are_virtual_8():
    assert len(jax.devices()) == 8


def test_mesh_spec_auto():
    spec = MeshSpec.auto(8, tp=2, sp=2)
    assert spec.dp == 2 and spec.num_devices == 8
    with pytest.raises(ValueError):
        MeshSpec.auto(8, tp=3)


def test_build_mesh_axes():
    mesh = build_mesh(MeshSpec.auto(8, tp=2))
    assert set(mesh.axis_names) == {"pp", "dp", "fsdp", "sp", "tp", "ep"}
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] == 4


def test_mesh_from_string():
    mesh = mesh_from_string("dp=2,tp=2,sp=2")
    assert mesh.shape["sp"] == 2


def test_logical_rules():
    spec = logical_to_spec(("batch", "seq", "embed"))
    assert spec == P(("dp", "fsdp"), "sp", None)
    with pytest.raises(KeyError):
        logical_to_spec(("nonexistent_axis",))


def test_sharded_matmul_psum_equivalence():
    """TP matmul over the mesh matches single-device result."""
    mesh = build_mesh(MeshSpec.auto(8, tp=2))
    x = jnp.arange(16 * 32, dtype=jnp.float32).reshape(16, 32) / 100
    w = jnp.ones((32, 64), jnp.float32) * 0.01

    @jax.jit
    def f(x, w):
        x = shard_constraint(x, mesh, "batch", None)
        w = shard_constraint(w, mesh, None, "mlp")
        y = x @ w
        return shard_constraint(y, mesh, "batch", "mlp")

    np.testing.assert_allclose(np.asarray(f(x, w)), np.asarray(x @ w),
                               rtol=1e-6)


def test_pipeline_matches_sequential():
    """GPipe SPMD schedule == running stages sequentially."""
    mesh = build_mesh(MeshSpec.auto(8, pp=4))
    n_stages, num_micro = 4, 8
    dim = 16
    key = jax.random.PRNGKey(0)
    ws = jax.random.normal(key, (n_stages, dim, dim)) * 0.3

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    batch = jax.random.normal(jax.random.PRNGKey(1), (32, dim))
    run = pipelined(stage_fn, mesh, num_microbatches=num_micro)
    out = jax.jit(run)(ws, batch)

    expected = batch
    for i in range(n_stages):
        expected = stage_fn(ws[i], expected)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_grad_flows():
    mesh = build_mesh(MeshSpec.auto(8, pp=2))
    n_stages, num_micro, dim = 2, 4, 8
    ws = jax.random.normal(jax.random.PRNGKey(0), (n_stages, dim, dim)) * 0.3

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    batch = jax.random.normal(jax.random.PRNGKey(1), (32, dim))
    run = pipelined(stage_fn, mesh, num_microbatches=num_micro)

    def loss(ws):
        return jnp.mean(run(ws, batch) ** 2)

    g = jax.jit(jax.grad(loss))(ws)
    assert g.shape == ws.shape
    assert float(jnp.abs(g).sum()) > 0

    def loss_seq(ws):
        x = batch
        for i in range(n_stages):
            x = stage_fn(ws[i], x)
        return jnp.mean(x ** 2)

    g_seq = jax.grad(loss_seq)(ws)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_seq),
                               rtol=1e-4, atol=1e-5)


# -- topology ---------------------------------------------------------------

def test_topology_basics():
    topo = TpuTopology("v5p", "4x4x4")
    assert topo.num_chips == 64
    assert topo.num_hosts == 16
    assert topo.chips_per_host == 4


def test_topology_subslice_allocation():
    topo = TpuTopology("v5p", "4x4x4")
    sub = topo.allocate(8)
    assert sub is not None and sub.num_chips == 8
    # cube-like preference: 2x2x2
    assert sorted(sub.shape) == [2, 2, 2]
    a = topo.allocate(32)
    b = topo.allocate(16)
    assert a is not None and b is not None
    assert topo.allocate(32) is None  # only 8 chips left
    topo.free(a)
    assert topo.allocate(32) is not None


def test_topology_v5e_2d():
    topo = TpuTopology("v5e", "4x4")
    assert topo.num_chips == 16
    assert topo.num_hosts == 4
    sub = topo.allocate(4)
    assert sub.num_chips == 4
    with pytest.raises(ValueError):
        TpuTopology("v5e", "4x4x4")  # wrong dimensionality


def test_topology_host_mapping():
    topo = TpuTopology("v5e", "4x4")
    hosts = {topo.host_of(c.coords) for c in topo.chips()}
    assert hosts == {0, 1, 2, 3}
    sub = topo.allocate(4)  # one host block 2x2
    assert len(topo.hosts_of_subslice(sub)) == 1


# -- host-level collectives -------------------------------------------------

def test_collective_allreduce(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import collective as col

    @ray_tpu.remote
    class Worker:
        def __init__(self, rank, world):
            col.init_collective_group(world, rank, group_name="g1")
            self.rank = rank

        def allreduce(self):
            out = col.allreduce(np.full(4, self.rank + 1.0),
                                group_name="g1")
            return out

        def gather_bcast(self):
            gathered = col.allgather(np.array([self.rank]), group_name="g1")
            bc = col.broadcast(np.array([self.rank * 10]), src_rank=1,
                               group_name="g1")
            return gathered, bc

    workers = [Worker.remote(i, 3) for i in range(3)]
    outs = ray_tpu.get([w.allreduce.remote() for w in workers])
    for o in outs:
        np.testing.assert_array_equal(o, np.full(4, 6.0))  # 1+2+3
    results = ray_tpu.get([w.gather_bcast.remote() for w in workers])
    gathered, bc = results[0]
    assert [int(g[0]) for g in gathered] == [0, 1, 2]
    assert int(bc[0]) == 10


def test_collective_send_recv(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import collective as col

    @ray_tpu.remote
    class P2P:
        def __init__(self, rank):
            col.init_collective_group(2, rank, group_name="p2p")
            self.rank = rank

        def run(self):
            if self.rank == 0:
                col.send(np.array([42.0]), dst_rank=1, group_name="p2p")
                return None
            return col.recv(src_rank=0, group_name="p2p")

    a, b = P2P.remote(0), P2P.remote(1)
    r0, r1 = ray_tpu.get([a.run.remote(), b.run.remote()])
    assert int(r1[0]) == 42


def test_multihost_env_parsing(monkeypatch):
    """Pod-topology env contract resolves (coordinator, world, rank)."""
    from ray_tpu.parallel import multihost

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-a,host-b,host-c")
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    topo = multihost.pod_topology_from_env()
    assert topo == (f"host-a:{multihost.COORDINATOR_PORT}", 3, 1)

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "solo")
    assert multihost.pod_topology_from_env() is None


def test_multihost_single_process_noop():
    from ray_tpu.parallel import multihost

    assert multihost.initialize_multihost() is False  # no pod env: no-op


def test_multihost_kv_rendezvous(ray_start_regular):
    import ray_tpu
    from ray_tpu.parallel import multihost

    rt = ray_tpu._private.worker.global_runtime()
    addr, world, rank = multihost.rendezvous_via_kv(rt.gcs, 2, 0)
    assert addr.endswith(f":{multihost.COORDINATOR_PORT}") and rank == 0
    addr2, world2, rank2 = multihost.rendezvous_via_kv(rt.gcs, 2, 1)
    assert addr2 == addr and rank2 == 1
