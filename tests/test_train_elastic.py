"""Elastic Train resize: worker failure → re-form a SMALLER mesh on the
surviving capacity from the latest checkpoint.

Reference capability: `python/ray/train/v2/_internal/execution/
scaling_policy/scaling_policy.py` (ResizeDecision after failures) —
the SURVEY §7 hard part "elastically re-form a smaller mesh".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (Checkpoint, FailureConfig, JaxTrainer,
                           RunConfig, ScalingConfig)
from ray_tpu.train.scaling_policy import (ElasticScalingPolicy,
                                          FixedScalingPolicy,
                                          resolve_policy)


def test_resolve_policy_defaults():
    sc = ScalingConfig(num_workers=3)
    pol = resolve_policy(sc, None)
    assert isinstance(pol, FixedScalingPolicy)
    assert pol.initial_size() == 3
    assert pol.on_recovery(3, {"CPU": 1}, 1).num_workers == 3

    pol = resolve_policy(ScalingConfig(num_workers=4, elastic=(1, 4)), None)
    assert isinstance(pol, ElasticScalingPolicy)
    assert pol.initial_size() == 4


def test_elastic_policy_clamps(ray_start_regular):
    # 8 CPUs available, 3 per worker -> 2 placeable; clamped to [1, 4]
    pol = ElasticScalingPolicy(1, 4, wait_s=0.5)
    d = pol.on_recovery(4, {"CPU": 3.0}, 1)
    assert d.num_workers == 2
    # capacity below min: times out waiting and returns min (the retry
    # then fails placement and counts against FailureConfig)
    d = pol.on_recovery(4, {"CPU": 100.0}, 1)
    assert d.num_workers == 1


def test_unplaceable_gang_fails_not_hangs(ray_start_regular, tmp_path):
    """A gang the cluster can never place must FAIL within the
    placement timeout and count against FailureConfig — not hang in
    pg.ready() forever."""
    def train_fn(config):
        train.report({"x": 1})

    res = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 9.0},
            placement_timeout_s=3.0),
        run_config=RunConfig(
            name="noplace", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1))).fit()
    assert res.error is not None
    assert "unplaceable" in res.error


def test_elastic_resize_on_worker_failure(ray_start_regular, tmp_path):
    """Kill 1 of 2 workers mid-run: the gang fails, the policy re-forms
    at the surviving world=1, and training completes from the latest
    checkpoint with strictly-continuous loss, params re-sharded onto
    the smaller (1-device) mesh.

    The resize decision itself is deterministic here (capacity-probing
    ElasticScalingPolicy math is covered by test_elastic_policy_clamps
    against real available_resources)."""

    class _LoseOneWorker(ElasticScalingPolicy):
        def on_recovery(self, current_size, resources_per_worker,
                        attempt):
            from ray_tpu.train.scaling_policy import ResizeDecision
            return ResizeDecision(max(self.min_workers,
                                      current_size - 1))

    def train_fn(config):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        ctx = train.get_context()
        ws = ctx.get_world_size()
        # dp mesh sized to the CURRENT world: after the resize the same
        # checkpointed params re-shard onto the 1-device mesh
        mesh = build_mesh(MeshSpec(dp=ws), jax.devices()[:ws])
        start = 0
        w = jnp.arange(8, dtype=jnp.float32) + 1.0
        prev = train.get_checkpoint()
        if prev is not None:
            state = prev.to_pytree()
            start = int(state["step"]) + 1
            w = jnp.asarray(state["w"])
        w = jax.device_put(w, NamedSharding(mesh, P("dp")))
        for step in range(start, 6):
            # every rank of the FIRST attempt (no checkpoint yet) dies
            # at step 3 — deterministic, no cross-rank marker races
            if step == 3 and prev is None:
                raise RuntimeError("worker lost")
            w = w * 0.8
            loss = float(jnp.sum(w * w))
            ck = None
            if ctx.get_world_rank() == 0:
                ck = Checkpoint.from_pytree(
                    {"step": jnp.asarray(step), "w": np.asarray(w)})
            train.report({"step": step, "loss": loss, "world": ws},
                         checkpoint=ck)

    result = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        scaling_policy=_LoseOneWorker(1, 2),
        run_config=RunConfig(
            name="elastic", storage_path=str(tmp_path / "run"),
            failure_config=FailureConfig(max_failures=1))).fit()

    assert result.error is None
    rank0 = [e["metrics"] for e in result.metrics_history
             if e["rank"] == 0]
    steps = [m["step"] for m in rank0]
    worlds = [m["world"] for m in rank0]
    losses = [m["loss"] for m in rank0]
    # steps 0..2 at world=2, then 3..5 at world=1 — no restart from 0
    assert steps == [0, 1, 2, 3, 4, 5]
    assert worlds == [2, 2, 2, 1, 1, 1]
    # loss continuous across the resize: strictly decreasing throughout
    assert all(b < a for a, b in zip(losses, losses[1:]))
    assert result.metrics["world"] == 1
