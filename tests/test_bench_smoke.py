"""bench.py harness smoke: the CPU paths must keep emitting valid
JSON lines (the driver runs these on real hardware — a harness
regression would silently cost the round its headline numbers)."""

import json
import os
import subprocess
import sys

import numpy as np


def test_serving_bench_cpu_smoke():
    """The BENCH_SERVE row is an OPEN-LOOP loadgen run through the full
    Serve data plane; the stable ``serving.*`` keys are the contract
    the driver greps across rounds."""
    from ray_tpu.llm.bench import run_serving_bench

    out = run_serving_bench()
    assert out["metric"] == "llm_serve_requests_per_second"
    assert out["value"] > 0
    s = out["serving"]
    assert s["requests_per_second"] > 0
    assert s["open_loop"] is True and s["replicas"] == 2
    assert s["errors"] == 0 and s["completed"] > 0
    assert np.isfinite(s["ttft_p50_s"]) and s["ttft_p50_s"] > 0
    assert s["ttft_p99_s"] >= s["ttft_p50_s"]
    assert s["e2e_p99_s"] >= s["e2e_p50_s"] >= s["ttft_p50_s"]
    assert 0.0 <= s["goodput_fraction"] <= 1.0
    # CPU fallback must be stamped LOUDLY in every section
    assert out["platform"] == "cpu" and out["tpu_fallback"] is True
    assert out["detail"]["spec"]["stream"] is True


def test_train_bench_child_cpu_smoke():
    """The --child CPU fallback end-to-end in a fresh process (what the
    driver's last-resort path runs)."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--child"],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode == 0, proc.stderr[-500:]
    line = [l for l in proc.stdout.splitlines() if l.strip()][-1]
    out = json.loads(line)
    assert out["metric"] == "llama_train_tokens_per_sec_per_chip"
    assert out["value"] > 0
    assert out["detail"]["config"] == "debug"
    assert out["platform"] == "cpu" and out["tpu_fallback"] is True
    cp = out.get("control_plane")
    if cp is not None:      # platform stamped into EVERY section
        assert cp["platform"] == "cpu" and cp["tpu_fallback"] is True
        # the drain-side rate rides every BENCH json (ROADMAP item 4:
        # the trajectory files track the bottleneck being fixed)
        assert "drain_tasks_per_second" in cp
        assert "tasks_per_second" in cp
        # object-plane throughput rows (ROADMAP item 1: put_get_1MiB is
        # the zero-copy plane's headline; 64KiB/16MiB bracket it)
        obj = out.get("objects")
        assert obj is not None
        for k in ("put_get_64KiB_mbps", "put_get_1MiB_mbps",
                  "put_get_16MiB_mbps"):
            assert k in obj
        # fair-share rows (docs/multitenancy.md): the two-tenant probe
        # runs with fairshare admission on and its keys are the
        # contract the driver greps across rounds
        mt = out.get("multitenancy")
        assert mt is not None
        assert "fairness_index" in mt
        assert "isolation_p99_ratio" in mt
        assert 0.0 <= mt["fairness_index"] <= 1.0
        if mt["fairness_index"] > 0:        # probe succeeded
            assert mt["fairshare_enabled"] is True
            assert mt["isolation_p99_ratio"] >= 1.0
