"""bench.py harness smoke: the CPU paths must keep emitting valid
JSON lines (the driver runs these on real hardware — a harness
regression would silently cost the round its headline numbers)."""

import json
import os
import subprocess
import sys

import numpy as np


def test_serving_bench_cpu_smoke():
    from ray_tpu.llm.bench import run_serving_bench

    out = run_serving_bench()
    assert out["metric"] == "llm_serve_output_tokens_per_sec"
    assert out["value"] > 0
    d = out["detail"]
    assert d["requests"] == 6 and d["output_tokens"] > 0
    assert d["prefix_prefills"] >= 1          # prefix phase exercised
    assert d["prefix_tokens_reused"] > 0
    assert np.isfinite(d["ttft_prefix_hit_p50_ms"])


def test_train_bench_child_cpu_smoke():
    """The --child CPU fallback end-to-end in a fresh process (what the
    driver's last-resort path runs)."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--child"],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode == 0, proc.stderr[-500:]
    line = [l for l in proc.stdout.splitlines() if l.strip()][-1]
    out = json.loads(line)
    assert out["metric"] == "llama_train_tokens_per_sec_per_chip"
    assert out["value"] > 0
    assert out["detail"]["config"] == "debug"
