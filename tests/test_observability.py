"""Export events (SURVEY #14: structured lifecycle events, reference
export_*.proto + _private/event/export_event_logger.py)."""

# ---------------------------------------------------------------------------
# Export events (reference: export_*.proto + export_event_logger.py)
# ---------------------------------------------------------------------------

def test_export_events_lifecycle(tmp_path):
    import ray_tpu
    from ray_tpu._private.export_events import (get_export_logger,
                                                reset_export_logger)

    reset_export_logger()
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      _system_config={"export_events": True})
    try:
        @ray_tpu.remote
        def f():
            return 1

        @ray_tpu.remote
        class A:
            def ping(self):
                return "pong"

        assert ray_tpu.get(f.remote()) == 1
        a = A.remote()
        assert ray_tpu.get(a.ping.remote()) == "pong"
        ray_tpu.kill(a)

        from ray_tpu.util.placement_group import (placement_group,
                                                  remove_placement_group)
        pg = placement_group([{"CPU": 1}])
        ray_tpu.get(pg.ready())
        remove_placement_group(pg)

        logger = get_export_logger()
        tasks = logger.read("TASK")
        assert any(e["state"] == "FINISHED" for e in tasks)
        assert all("task_id" in e and "timestamp" in e for e in tasks)
        actors = logger.read("ACTOR")
        states = {e["state"] for e in actors}
        assert any("ALIVE" in s for s in states)
        assert any("DEAD" in s for s in states)
        nodes = logger.read("NODE")
        assert any(e["state"] == "ALIVE" for e in nodes)
        pgs = logger.read("PLACEMENT_GROUP")
        assert {e["state"] for e in pgs} >= {"CREATED", "REMOVED"}
    finally:
        ray_tpu.shutdown()
        reset_export_logger()


def test_export_events_disabled_by_default(tmp_path):
    import ray_tpu
    from ray_tpu._private.export_events import (get_export_logger,
                                                reset_export_logger)

    reset_export_logger()
    ray_tpu.init(num_nodes=1, resources={"CPU": 2})
    try:
        @ray_tpu.remote
        def f():
            return 1

        assert ray_tpu.get(f.remote()) == 1
        logger = get_export_logger()
        assert logger.read("TASK") == []   # flag off: no writes
    finally:
        ray_tpu.shutdown()
        reset_export_logger()


def test_worker_metrics_flow_to_driver(ray_start_regular):
    """User metrics created inside pool workers surface on the driver's
    Prometheus endpoint (reference: worker -> agent -> exporter flow);
    counters merge across workers, histograms merge bucket counts."""
    import time

    import ray_tpu
    from ray_tpu.util.metrics import prometheus_text

    @ray_tpu.remote
    def work(i):
        from ray_tpu.util.metrics import Counter, Histogram
        Counter("xproc_events", "events").inc(5)
        Histogram("xproc_lat", "lat", boundaries=(1, 10)).observe(i)
        return 1

    assert ray_tpu.get([work.remote(i) for i in range(3)],
                       timeout=60) == [1, 1, 1]
    deadline = time.time() + 20
    while time.time() < deadline:
        text = prometheus_text()
        lines = [l for l in text.splitlines()
                 if l.startswith("xproc_events ")]
        if lines and lines[0].endswith("15.0"):
            break
        time.sleep(0.2)
    assert lines and lines[0].endswith("15.0"), lines
    assert "xproc_lat_count 3" in text
    # don't pollute later tests' prometheus_text in this process
    from ray_tpu.util.metrics import clear_registry
    clear_registry()
