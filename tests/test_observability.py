"""Observability: export events (reference export_*.proto +
export_event_logger.py), the task-event/span buffer, cluster-wide task
tracing (per-phase spans flushed to the head over heartbeats), metrics
federation, and the dashboard HTTP endpoints."""

import json
import time
import urllib.error
import urllib.request

import pytest

# ---------------------------------------------------------------------------
# Export events (reference: export_*.proto + export_event_logger.py)
# ---------------------------------------------------------------------------

def test_export_events_lifecycle(tmp_path):
    import ray_tpu
    from ray_tpu._private.export_events import (get_export_logger,
                                                reset_export_logger)

    reset_export_logger()
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      _system_config={"export_events": True})
    try:
        @ray_tpu.remote
        def f():
            return 1

        @ray_tpu.remote
        class A:
            def ping(self):
                return "pong"

        assert ray_tpu.get(f.remote()) == 1
        a = A.remote()
        assert ray_tpu.get(a.ping.remote()) == "pong"
        ray_tpu.kill(a)

        from ray_tpu.util.placement_group import (placement_group,
                                                  remove_placement_group)
        pg = placement_group([{"CPU": 1}])
        ray_tpu.get(pg.ready())
        remove_placement_group(pg)

        logger = get_export_logger()
        tasks = logger.read("TASK")
        assert any(e["state"] == "FINISHED" for e in tasks)
        assert all("task_id" in e and "timestamp" in e for e in tasks)
        actors = logger.read("ACTOR")
        states = {e["state"] for e in actors}
        assert any("ALIVE" in s for s in states)
        assert any("DEAD" in s for s in states)
        nodes = logger.read("NODE")
        assert any(e["state"] == "ALIVE" for e in nodes)
        pgs = logger.read("PLACEMENT_GROUP")
        assert {e["state"] for e in pgs} >= {"CREATED", "REMOVED"}
    finally:
        ray_tpu.shutdown()
        reset_export_logger()


def test_export_events_disabled_by_default(tmp_path):
    import ray_tpu
    from ray_tpu._private.export_events import (get_export_logger,
                                                reset_export_logger)

    reset_export_logger()
    ray_tpu.init(num_nodes=1, resources={"CPU": 2})
    try:
        @ray_tpu.remote
        def f():
            return 1

        assert ray_tpu.get(f.remote()) == 1
        logger = get_export_logger()
        assert logger.read("TASK") == []   # flag off: no writes
    finally:
        ray_tpu.shutdown()
        reset_export_logger()


# ---------------------------------------------------------------------------
# task-event buffer (reference: task_event_buffer.cc)
# ---------------------------------------------------------------------------

def test_event_buffer_extend_and_from_events():
    from ray_tpu._private.events import TaskEventBuffer

    src = TaskEventBuffer()
    src.record(task_id="t1", name="a", event="RUNNING")
    src.record(task_id="t1", name="a", event="FINISHED")
    buf = TaskEventBuffer.from_events(src.events())
    assert [e["task_id"] for e in buf.events()] == ["t1", "t1"]
    # extend re-assigns seqs locally so cursors stay monotonic
    buf.extend([{"task_id": "t2", "name": "b", "event": "RUNNING",
                 "wall_ts": time.time(), "seq": 999}])
    seqs = [e["seq"] for e in buf.events()]
    assert seqs == sorted(seqs) and seqs[-1] == 3
    assert buf.events()[-1]["task_id"] == "t2"


def test_events_after_tail_indexed():
    from ray_tpu._private.events import TaskEventBuffer

    buf = TaskEventBuffer(capacity=10)
    for i in range(25):
        buf.record(task_id=f"t{i}", name="n", event="RUNNING")
    # only the last 10 survive the ring (seqs 16..25)
    assert [e["seq"] for e in buf.events_after(20)] == [21, 22, 23, 24, 25]
    assert [e["seq"] for e in buf.events_after(0)] == list(range(16, 26))
    assert buf.events_after(25) == []
    assert buf.events_after(99) == []


def test_chrome_trace_retry_pairing():
    """A retry's second RUNNING supersedes the dead attempt's start, so
    FINISHED pairs with the retry's own start — never the stale one."""
    from ray_tpu._private.events import TaskEventBuffer

    buf = TaskEventBuffer()
    buf.record(task_id="t1", name="f", event="RUNNING", node_id="aa" * 8)
    buf.record(task_id="t1", name="f", event="RETRY")
    buf.record(task_id="t1", name="f", event="RUNNING", node_id="bb" * 8)
    buf.record(task_id="t1", name="f", event="FINISHED",
               node_id="bb" * 8)
    # second task: two RUNNINGs with NO retry marker (lost transition)
    buf.record(task_id="t2", name="g", event="RUNNING")
    buf.record(task_id="t2", name="g", event="RUNNING")
    buf.record(task_id="t2", name="g", event="FINISHED")
    events = buf.events()
    trace = buf.chrome_trace()
    t1 = [s for s in trace if s["tid"] == "t1"]
    assert len(t1) == 1
    second_running = [e for e in events if e["task_id"] == "t1"
                      and e["event"] == "RUNNING"][1]
    assert t1[0]["ts"] == second_running["ts_us"]
    t2 = [s for s in trace if s["tid"] == "t2"]
    assert len(t2) == 1


def test_merged_chrome_trace_lanes():
    from ray_tpu._private.events import merged_chrome_trace

    now = time.time()
    events = [
        {"task_id": "t1", "name": "f", "event": "SPAN", "phase": "submit",
         "proc": "driver", "wall_ts": now, "start_wall": now - 0.01,
         "dur_s": 0.01},
        {"task_id": "t1", "name": "f", "event": "SPAN",
         "phase": "dispatch", "proc": "daemon:aabbccdd",
         "wall_ts": now + 0.02, "start_wall": now + 0.01, "dur_s": 0.01},
        {"task_id": "t1", "name": "f", "event": "SPAN", "phase": "exec",
         "proc": "worker:123", "wall_ts": now + 0.05,
         "start_wall": now + 0.02, "dur_s": 0.03},
    ]
    trace = merged_chrome_trace(events)
    lanes = {s["pid"] for s in trace}
    assert lanes == {"driver", "daemon:aabbccdd", "worker:123"}
    by_phase = {s["args"]["phase"]: s["ts"] for s in trace}
    assert by_phase["submit"] <= by_phase["dispatch"] <= by_phase["exec"]


# ---------------------------------------------------------------------------
# Prometheus exposition (reference: metrics_agent.py)
# ---------------------------------------------------------------------------

def test_prometheus_label_escaping():
    """Label values with quotes/backslashes/newlines must be escaped per
    the exposition spec — a task name containing `\"` used to corrupt
    the scrape."""
    from ray_tpu.util import metrics

    metrics.clear_registry()
    try:
        c = metrics.Counter("esc_test_total", "escaping", ("name",))
        c.inc(1, tags={"name": 'he said "hi"\nback\\slash'})
        text = metrics.prometheus_text()
        assert ('esc_test_total{name="he said \\"hi\\"\\nback'
                '\\\\slash"} 1.0') in text
        # still a parseable single line
        line = [ln for ln in text.splitlines()
                if ln.startswith("esc_test_total{")]
        assert len(line) == 1
    finally:
        metrics.clear_registry()


def test_render_prometheus_federated_labels():
    """Snapshots from several processes merge into one exposition with a
    single TYPE block per metric and per-source node_id labels."""
    from ray_tpu.util import metrics

    metrics.clear_registry()
    try:
        metrics.Counter("fed_reqs_total", "reqs").inc(2)
        local = metrics.export_snapshot()
        remote = [{"name": "fed_reqs_total", "kind": "counter",
                   "description": "reqs", "samples": [[[], 5.0]]}]
        text = metrics.render_prometheus(
            [({}, local), ({"node_id": "aa" * 16}, remote)])
        assert text.count("# TYPE fed_reqs_total counter") == 1
        assert "fed_reqs_total 2.0" in text
        assert f'fed_reqs_total{{node_id="{"aa" * 16}"}} 5.0' in text
    finally:
        metrics.clear_registry()


def test_worker_metrics_flow_to_driver(ray_start_regular):
    """User metrics created inside pool workers surface on the driver's
    Prometheus endpoint (reference: worker -> agent -> exporter flow);
    counters merge across workers, histograms merge bucket counts."""
    import time

    import ray_tpu
    from ray_tpu.util.metrics import prometheus_text

    @ray_tpu.remote
    def work(i):
        from ray_tpu.util.metrics import Counter, Histogram
        Counter("xproc_events", "events").inc(5)
        Histogram("xproc_lat", "lat", boundaries=(1, 10)).observe(i)
        return 1

    assert ray_tpu.get([work.remote(i) for i in range(3)],
                       timeout=60) == [1, 1, 1]
    deadline = time.time() + 20
    while time.time() < deadline:
        text = prometheus_text()
        lines = [l for l in text.splitlines()
                 if l.startswith("xproc_events ")]
        if lines and lines[0].endswith("15.0"):
            break
        time.sleep(0.2)
    assert lines and lines[0].endswith("15.0"), lines
    assert "xproc_lat_count 3" in text
    # don't pollute later tests' prometheus_text in this process
    from ray_tpu.util.metrics import clear_registry
    clear_registry()


# ---------------------------------------------------------------------------
# cluster-wide tracing + federation (2-node daemon topology; reference:
# task_event_buffer.cc flush -> gcs_task_manager, metrics agent federation)
# ---------------------------------------------------------------------------

@pytest.fixture
def daemon_cluster():
    import ray_tpu
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


def _run_batched_workload(n=40):
    """num_returns=2 keeps tasks OFF the fast lane, so they ride the
    classic batched submit path (coalescer -> push_task_batch)."""
    import ray_tpu

    @ray_tpu.remote(num_returns=2)
    def duo(i):
        return i, i + 1

    refs = [duo.remote(i) for i in range(n)]
    ray_tpu.get([r for ab in refs for r in ab])


def _head_span_events(backend, phases, deadline_s=20.0):
    """Poll the head store until spans for every wanted phase landed
    (daemon flushes piggyback on ~0.2s heartbeats)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        events = backend.head.task_events_get()
        spans = [e for e in events if e.get("event") == "SPAN"]
        got = {e.get("phase") for e in spans}
        if phases <= got:
            return events
        time.sleep(0.2)
    raise AssertionError(
        f"head store never saw phases {phases - got}; got {got}")


def test_spans_flush_to_head_and_breakdown(daemon_cluster):
    """End-to-end trace: driver submit/linger/queue/result spans,
    daemon dispatch spans, worker exec spans all reach the head;
    task_breakdown returns the six-phase vector; the merged chrome
    trace has one lane per process with monotonic phase ordering."""
    rt = daemon_cluster
    backend = rt.cluster_backend
    _run_batched_workload()
    backend._flush_task_events()
    events = _head_span_events(
        backend, {"submit", "queue", "dispatch", "exec", "result"})

    spans = [e for e in events if e.get("event") == "SPAN"]
    # daemon + worker lanes carry the daemon's node ids
    node_hexes = {h.node_id.hex() for h in backend.daemons.values()}
    dispatch = [e for e in spans if e["phase"] == "dispatch"]
    assert {e["node_id"] for e in dispatch} <= node_hexes
    assert all(e["proc"].startswith("daemon:") for e in dispatch)
    execs = [e for e in spans if e["phase"] == "exec"]
    assert any(e["proc"].startswith("worker:") for e in execs)
    # clock correction was applied on ingestion
    assert all("clock_off" in e for e in dispatch)

    # a task that has driver+daemon+worker spans -> full breakdown
    by_task = {}
    for e in spans:
        by_task.setdefault(e["task_id"], set()).add(e["phase"])
    full = [t for t, ph in by_task.items()
            if {"submit", "dispatch", "exec"} <= ph]
    assert full, f"no task with cross-process spans: {by_task}"
    from ray_tpu.util.state import task_breakdown
    bd = task_breakdown(full[0])
    assert set(bd) == {"submit", "linger", "queue", "dispatch", "exec",
                       "result_flush", "result_ingest", "result"}
    assert bd["exec"] > 0.0 and bd["dispatch"] > 0.0

    # merged chrome trace: one lane per process, monotonic ordering
    from ray_tpu.util.state import cluster_timeline
    trace = cluster_timeline()
    task_slices = [s for s in trace
                   if s.get("args", {}).get("task_id") == full[0]]
    lanes = {s["pid"] for s in task_slices}
    assert "driver" in lanes
    assert any(p.startswith("daemon:") for p in lanes)
    assert any(p.startswith("worker:") for p in lanes)
    ts = {s["args"]["phase"]: s["ts"] for s in task_slices
          if s["args"].get("phase")}
    slack = 2000.0  # µs of clock-estimate tolerance (same host: ~0)
    assert ts["submit"] <= ts["dispatch"] + slack
    assert ts["dispatch"] <= ts["exec"] + slack


def test_cluster_metrics_federation(daemon_cluster):
    """Dashboard /metrics is CLUSTER-wide: phase histograms carry
    node_id labels for both daemons, and daemon-process metrics (rpc
    server counters) federate to the driver via head heartbeats."""
    rt = daemon_cluster
    backend = rt.cluster_backend
    _run_batched_workload()
    from ray_tpu.util.metrics import cluster_prometheus_text
    node_hexes = {h.node_id.hex() for h in backend.daemons.values()}
    deadline = time.monotonic() + 25
    ok = False
    while time.monotonic() < deadline and not ok:
        text = cluster_prometheus_text()
        ok = ("ray_tpu_task_phase_seconds_bucket" in text
              and all(f'node_id="{h}"' in text for h in node_hexes)
              and "ray_tpu_rpc_server_requests_total" in text)
        if not ok:
            time.sleep(0.3)
    assert "ray_tpu_task_phase_seconds_bucket" in text
    for h in node_hexes:
        assert f'node_id="{h}"' in text
    # federated from the DAEMON processes (heartbeat snapshots): their
    # rpc server counters appear node_id-labeled
    assert "ray_tpu_rpc_server_requests_total" in text
    lines = [ln for ln in text.splitlines()
             if ln.startswith("ray_tpu_rpc_server_requests_total")
             and "node_id=" in ln]
    assert lines, "daemon rpc counters did not federate"
    # exactly one TYPE block per metric even with federated sources
    assert text.count("# TYPE ray_tpu_task_phase_seconds histogram") == 1


def test_dashboard_endpoints_live_cluster(daemon_cluster):
    """Dashboard HTTP surface against a live 2-node cluster:
    /api/timeline, /api/cluster_status, /api/metrics, /metrics, and the
    unknown-path 404."""
    from ray_tpu.dashboard.server import start_dashboard, stop_dashboard

    rt = daemon_cluster
    _run_batched_workload(10)
    rt.cluster_backend._flush_task_events()
    host, port = start_dashboard(port=0)
    base = f"http://{host}:{port}"
    try:
        with urllib.request.urlopen(f"{base}/api/timeline",
                                    timeout=30) as r:
            trace = json.loads(r.read())
        assert isinstance(trace, list) and trace
        assert any(s.get("ph") == "X" for s in trace)

        with urllib.request.urlopen(f"{base}/api/cluster_status",
                                    timeout=30) as r:
            status = json.loads(r.read())
        assert "cluster_resources" in status and "stats" in status
        assert status["task_summary"].get("FINISHED", 0) >= 1

        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            assert r.status == 200
            text = r.read().decode()
        assert "ray_tpu_task_phase_seconds" in text
        assert "ray_tpu_tasks_finished" in text

        with urllib.request.urlopen(f"{base}/api/metrics",
                                    timeout=30) as r:
            payload = json.loads(r.read())
        assert any(row["name"] == "ray_tpu_task_phase_seconds"
                   for row in payload["metrics"])

        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/api/no_such_thing",
                                   timeout=30)
        assert err.value.code == 404
    finally:
        stop_dashboard()


def test_trace_flush_failpoint_retries(daemon_cluster):
    """trace.flush drop arm: a lost driver flush keeps its cursor, so
    the next interval re-sends the same batch (no span ever lost)."""
    from ray_tpu._private import failpoints as _fp

    rt = daemon_cluster
    backend = rt.cluster_backend
    _run_batched_workload(6)
    cursor_before = backend._task_event_cursor
    _fp.activate("trace.flush=drop:p=1")
    try:
        backend._flush_task_events()
        assert backend._task_event_cursor == cursor_before
    finally:
        _fp.reset()
    backend._flush_task_events()
    assert backend._task_event_cursor > cursor_before
    events = backend.head.task_events_get()
    assert any(e.get("event") == "FINISHED" for e in events)
