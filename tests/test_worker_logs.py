"""Worker log capture + tail-to-driver (VERDICT r2 #9; reference:
``python/ray/_private/log_monitor.py``, ``worker.py:2164
print_worker_logs``)."""

import re
import time

import pytest

import ray_tpu


def _wait_for(capfd, pattern: str, timeout: float = 10.0) -> str:
    """Accumulate captured driver output until pattern appears."""
    buf = ""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out, err = capfd.readouterr()
        buf += out + err
        if re.search(pattern, buf):
            return buf
        time.sleep(0.2)
    return buf


def test_worker_print_appears_on_driver(ray_start_regular, capfd):
    @ray_tpu.remote
    def talker():
        print("hello from the worker side")
        return 1

    assert ray_tpu.get(talker.remote()) == 1
    buf = _wait_for(capfd, r"\(worker .*pid=\d+\) hello from the worker")
    m = re.search(r"\(worker .*pid=(\d+)\) hello from the worker side", buf)
    assert m, f"worker line not surfaced on driver: {buf[-800:]!r}"
    import os
    assert int(m.group(1)) != os.getpid()  # a real worker process's pid


def test_worker_stderr_appears_on_driver(ray_start_regular, capfd):
    @ray_tpu.remote
    def warner():
        import sys
        print("trouble brewing", file=sys.stderr)
        return 2

    assert ray_tpu.get(warner.remote()) == 2
    buf = _wait_for(capfd, r"\(worker .*pid=\d+\) trouble brewing")
    assert re.search(r"\(worker .*pid=\d+\) trouble brewing", buf), \
        buf[-800:]


def test_worker_logs_daemons_mode(capfd):
    """Cross-process: a daemon-hosted worker's print crosses the wire to
    the driver with a node + pid prefix."""
    ray_tpu.init(num_nodes=1, resources={"CPU": 2}, cluster="daemons")
    try:
        @ray_tpu.remote
        def talker():
            print("daemon worker speaking")
            return 3

        assert ray_tpu.get(talker.remote()) == 3
        buf = _wait_for(
            capfd, r"\(worker node=\w+ pid=\d+\) daemon worker speaking",
            timeout=15.0)
        assert re.search(
            r"\(worker node=\w+ pid=\d+\) daemon worker speaking", buf), \
            buf[-800:]
    finally:
        ray_tpu.shutdown()
