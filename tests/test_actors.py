"""Actor tests (reference: python/ray/tests/test_actor*.py)."""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def inc(self, by=1):
        self.n += by
        return self.n

    def read(self):
        return self.n


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote(5)) == 6
    assert ray_tpu.get(c.read.remote()) == 6


def test_actor_init_args(ray_start_regular):
    c = Counter.remote(100)
    assert ray_tpu.get(c.read.remote()) == 100


def test_actor_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(50)]
    # FIFO per-caller ordering: results are 1..50 in submission order.
    assert ray_tpu.get(refs) == list(range(1, 51))


def test_actor_method_error(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def boom(self):
            raise ValueError("actor method error")

        def ok(self):
            return "fine"

    b = Bad.remote()
    with pytest.raises(ValueError, match="actor method error"):
        ray_tpu.get(b.boom.remote())
    # Actor survives method errors.
    assert ray_tpu.get(b.ok.remote()) == "fine"


def test_actor_init_failure(ray_start_regular):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("init failed")

        def m(self):
            return 1

    b = Broken.remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.m.remote(), timeout=10)


def test_named_actor(ray_start_regular):
    Counter.options(name="counter1").remote(7)
    h = ray_tpu.get_actor("counter1")
    assert ray_tpu.get(h.read.remote()) == 7


def test_named_actor_get_if_exists(ray_start_regular):
    h1 = Counter.options(name="c", get_if_exists=True).remote(1)
    h2 = Counter.options(name="c", get_if_exists=True).remote(999)
    ray_tpu.get(h1.inc.remote())
    assert ray_tpu.get(h2.read.remote()) == 2  # same actor


def test_named_actor_duplicate_raises(ray_start_regular):
    Counter.options(name="dup").remote()
    time.sleep(0.2)
    with pytest.raises(ValueError):
        Counter.options(name="dup").remote()


def test_get_actor_missing(ray_start_regular):
    with pytest.raises(ValueError):
        ray_tpu.get_actor("nope")


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    ray_tpu.get(c.inc.remote())
    ray_tpu.kill(c)
    with pytest.raises((exc.ActorDiedError, exc.ActorError)):
        ray_tpu.get(c.inc.remote())


def test_actor_handle_serialization(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    def use(handle):
        return ray_tpu.get(handle.inc.remote(10))

    assert ray_tpu.get(use.remote(c)) == 10
    assert ray_tpu.get(c.read.remote()) == 10


def test_actor_max_concurrency(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Sleeper:
        def nap(self):
            time.sleep(0.3)
            return 1

    s = Sleeper.remote()
    t0 = time.monotonic()
    ray_tpu.get([s.nap.remote() for _ in range(4)])
    elapsed = time.monotonic() - t0
    assert elapsed < 1.0  # ran concurrently, not 1.2s serial


def test_async_actor(ray_start_regular):
    @ray_tpu.remote(max_concurrency=8)
    class AsyncActor:
        async def work(self, i):
            await asyncio.sleep(0.2)
            return i * 2

    a = AsyncActor.remote()
    t0 = time.monotonic()
    out = ray_tpu.get([a.work.remote(i) for i in range(8)])
    elapsed = time.monotonic() - t0
    assert out == [i * 2 for i in range(8)]
    assert elapsed < 1.0  # concurrent awaits


def test_exit_actor(ray_start_regular):
    @ray_tpu.remote
    class Quitter:
        def quit(self):
            ray_tpu.exit_actor()

        def m(self):
            return 1

    q = Quitter.remote()
    ray_tpu.get(q.quit.remote())
    with pytest.raises((exc.ActorDiedError, exc.ActorError)):
        ray_tpu.get(q.m.remote())


def test_actor_resource_accounting(ray_start_regular):
    @ray_tpu.remote(num_cpus=4)
    class Big:
        def ping(self):
            return "pong"

    b1 = Big.remote()
    b2 = Big.remote()
    assert ray_tpu.get(b1.ping.remote()) == "pong"
    assert ray_tpu.get(b2.ping.remote()) == "pong"
    avail = ray_tpu.available_resources()
    assert avail.get("CPU", 0) == 0  # 2 actors x 4 CPUs on an 8-CPU node


def test_detached_actor_namespace(ray_start_regular):
    Counter.options(name="d1", lifetime="detached",
                    namespace="other").remote(3)
    h = ray_tpu.get_actor("d1", namespace="other")
    assert ray_tpu.get(h.read.remote()) == 3


def test_concurrency_groups(ray_start_regular):
    """Named groups get isolated thread pools with their own limits
    (reference: concurrency_group_manager.h, SURVEY.md §8.4)."""
    import threading
    import time

    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        def __init__(self):
            self.events = []
            self.lock = threading.Lock()

        def io_task(self, i):
            with self.lock:
                self.events.append(("io_start", i))
            time.sleep(0.2)
            with self.lock:
                self.events.append(("io_end", i))
            return i

        def compute_task(self, i):
            time.sleep(0.05)
            return i

        def get_events(self):
            with self.lock:
                return list(self.events)

    w = Worker.remote()
    t0 = time.time()
    io_refs = [w.io_task.options(concurrency_group="io").remote(i)
               for i in range(2)]
    comp_refs = [w.compute_task.options(
        concurrency_group="compute").remote(i) for i in range(2)]
    assert sorted(ray_tpu.get(io_refs)) == [0, 1]
    io_time = time.time() - t0
    # 2 io tasks of 0.2s overlapped in the io group (limit 2)
    assert io_time < 0.39
    assert sorted(ray_tpu.get(comp_refs)) == [0, 1]
    events = ray_tpu.get(w.get_events.remote())
    starts = [e for e in events if e[0] == "io_start"]
    assert len(starts) == 2
