"""Failpoint registry + unified RetryPolicy: unit tier (fast, tier-1).

The seeded chaos schedules that drive whole-cluster fault replays live
in tests/test_chaos.py (`-m chaos`); here we pin the registry contract
(arms, determinism, hit log, spec grammar), the RetryPolicy schedule
(full jitter, budgets, Prometheus counters), and the cheap wired-seam
behaviors that don't need a daemon cluster.
"""

import random
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import failpoints as fp
from ray_tpu._private import rpc
from ray_tpu._private.retry import RetryPolicy, record_retry


@pytest.fixture(autouse=True)
def _reset_failpoints():
    yield
    fp.reset()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_inactive_registry_is_noop():
    assert not fp.ENABLED
    assert fp.fire("anything.at.all") is None
    assert fp.hit_count("anything.at.all") == 0


def test_spec_parsing_arms():
    fp.activate("a.b=drop:every=2:max=2;c.d=delay(1);"
                "e.f=error(OSError):after=1;g.h=return(42)")
    desc = fp.describe()
    assert desc["a.b"]["action"] == "drop" and desc["a.b"]["every"] == 2
    assert desc["c.d"]["action"] == "delay" and desc["c.d"]["arg"] == 1.0
    # exception names resolve lazily at fire() time (import-order safe)
    assert desc["e.f"]["arg"] == "OSError" and desc["e.f"]["after"] == 1
    assert desc["g.h"]["action"] == "return" and desc["g.h"]["arg"] == 42


def test_every_and_max_arms():
    fp.activate("s=drop:every=2:max=2")
    outcomes = [fp.fire("s") for _ in range(8)]
    assert [o is fp.DROP for o in outcomes] == [
        False, True, False, True, False, False, False, False]
    assert fp.hit_count("s") == 8
    assert fp.fire_count("s") == 2


def test_after_arm_skips_first_hits():
    fp.activate("s=drop:after=3")
    outcomes = [fp.fire("s") is fp.DROP for _ in range(5)]
    assert outcomes == [False, False, False, True, True]


def test_error_arm_raises_resolved_class():
    fp.activate("s=error(RpcError)")
    with pytest.raises(rpc.RpcError):
        fp.fire("s")
    fp.activate("t=error()")
    with pytest.raises(fp.FailpointError):
        fp.fire("t")


def test_return_arm_short_circuits():
    fp.configure("s", "return", arg={"x": 1})
    out = fp.fire("s")
    assert isinstance(out, fp.Return) and out.value == {"x": 1}


def test_seeded_probability_is_deterministic():
    fp.activate("s=drop:p=0.5", seed=321)
    first = [fp.fire("s") is fp.DROP for _ in range(32)]
    fp.activate("s=drop:p=0.5", seed=321)
    replay = [fp.fire("s") is fp.DROP for _ in range(32)]
    assert first == replay
    assert any(first) and not all(first)   # it's actually probabilistic
    fp.activate("s=drop:p=0.5", seed=99)
    other = [fp.fire("s") is fp.DROP for _ in range(32)]
    assert other != first                  # seed changes the schedule


def test_per_arm_rng_isolation():
    """One arm's probability draws must not perturb another's: the
    per-seam schedule replays identically whether or not other seams'
    hits interleave (per-arm RNG derived from (seed, name))."""
    fp.activate("a=drop:p=0.5;b=drop:p=0.5", seed=77)
    a_alone = [fp.fire("a") is fp.DROP for _ in range(20)]
    fp.activate("a=drop:p=0.5;b=drop:p=0.5", seed=77)
    a_interleaved = []
    for _ in range(20):
        a_interleaved.append(fp.fire("a") is fp.DROP)
        fp.fire("b")
    assert a_interleaved == a_alone


def test_hit_log_carries_context():
    fp.activate("s=delay(0)")
    fp.fire("s", method="kv_put")
    fp.fire("s", method="publish")
    log = fp.hit_log("s")
    assert [e["method"] for e in log] == ["kv_put", "publish"]
    assert [e["fire"] for e in log] == [1, 2]


def test_hit_log_thread_safety():
    fp.activate("s=delay(0)")
    n_threads, per_thread = 8, 50

    def worker():
        for _ in range(per_thread):
            fp.fire("s")

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert fp.hit_count("s") == n_threads * per_thread
    assert len(fp.hit_log("s")) == n_threads * per_thread


def test_malformed_specs_rejected():
    with pytest.raises(ValueError):
        fp.parse_spec("no_equals_sign")
    with pytest.raises(ValueError):
        fp.parse_spec("a=explode")
    with pytest.raises(ValueError):
        fp.parse_spec("a=drop:bogus=1")
    # unknown exception names parse (resolution is lazy so runtime
    # error classes work from env activation at import time) but fail
    # LOUDLY at the seam
    fp.activate("a=error(NoSuchExceptionClass)")
    with pytest.raises(ValueError):
        fp.fire("a")


def test_error_arm_resolves_at_fire_time_not_import_time():
    """Env activation runs while rpc.py/fast_lane.py are mid-import;
    specs naming their error classes must not crash the process then
    (regression: parse-time _resolve_exc raised ValueError and killed
    every process at startup)."""
    import os
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable, "-c",
         "import ray_tpu._private.rpc as rpc\n"
         "from ray_tpu._private import failpoints as fp\n"
         "assert fp.ENABLED\n"
         "try:\n"
         "    fp.fire('rpc.client.send')\n"
         "except rpc.RpcError:\n"
         "    print('RESOLVED_OK')\n"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "RAY_TPU_FAILPOINTS": "rpc.client.send=error(RpcError)"})
    assert "RESOLVED_OK" in out.stdout, (out.stdout, out.stderr)


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def test_retry_policy_attempt_budget():
    calls = []

    def boom():
        calls.append(1)
        raise OSError("down")

    policy = RetryPolicy(max_attempts=4, base_s=0.0, max_backoff_s=0.0)
    with pytest.raises(OSError):
        policy.run(boom, loop="t.budget", retry_on=(OSError,))
    assert len(calls) == 4     # the LAST exception re-raises


def test_retry_policy_overall_deadline():
    t0 = time.monotonic()
    policy = RetryPolicy(deadline_s=0.15, base_s=0.02,
                         max_backoff_s=0.05)
    with pytest.raises(OSError):
        policy.run(lambda: (_ for _ in ()).throw(OSError("x")),
                   loop="t.deadline", retry_on=(OSError,))
    assert time.monotonic() - t0 < 2.0


def test_retry_policy_full_jitter_bounds_and_determinism():
    policy = RetryPolicy(base_s=0.05, max_backoff_s=0.4)
    rng = random.Random(7)
    seq = [policy.backoff_s(i, rng) for i in range(10)]
    for i, s in enumerate(seq):
        assert 0.0 <= s <= min(0.4, 0.05 * 2 ** i)
    # same rng seed => same jitter draws
    rng2 = random.Random(7)
    assert seq == [RetryPolicy(base_s=0.05, max_backoff_s=0.4).backoff_s(
        i, rng2) for i in range(10)]
    # huge attempt numbers must not overflow float pow
    assert policy.backoff_s(10_000) <= 0.4


def test_retry_policy_succeeds_midway_and_counts():
    from ray_tpu.util import metrics
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise OSError("flap")
        return "ok"

    policy = RetryPolicy(max_attempts=10, base_s=0.0, max_backoff_s=0.0)
    assert policy.run(flaky, loop="t.flaky", retry_on=(OSError,)) == "ok"
    counter = metrics.registry()["ray_tpu_retries_total"]
    samples = dict(counter.samples())
    assert samples[(("loop", "t.flaky"),)] == 2.0


def test_retry_policy_non_retryable_escapes_immediately():
    calls = []

    def wrong():
        calls.append(1)
        raise ValueError("not transient")

    policy = RetryPolicy(max_attempts=5, base_s=0.0)
    with pytest.raises(ValueError):
        policy.run(wrong, loop="t.escape", retry_on=(OSError,))
    assert len(calls) == 1


def test_retry_policy_abort_hook():
    stop = threading.Event()
    stop.set()
    policy = RetryPolicy(max_attempts=100, base_s=0.0)
    calls = []

    def boom():
        calls.append(1)
        raise OSError("x")

    with pytest.raises(OSError):
        policy.run(boom, loop="t.abort", retry_on=(OSError,),
                   abort=stop.is_set)
    assert len(calls) == 1


def test_record_retry_exports_prometheus_text():
    from ray_tpu.util import metrics
    record_retry("t.prom", 0.123)
    text = metrics.prometheus_text()
    assert "ray_tpu_retries_total" in text
    assert 'loop="t.prom"' in text
    assert "ray_tpu_retry_backoff_seconds_total" in text


# ---------------------------------------------------------------------------
# wired seams (cheap: no daemon cluster)
# ---------------------------------------------------------------------------

def test_rpc_server_recv_drop_times_out_then_recovers():
    """A dropped request vanishes on the wire: the caller times out,
    a retry goes through, and the hit log shows exactly one drop."""

    class Svc:
        def handle_echo(self, conn, rid, msg):
            return {"v": msg["v"]}

    rpc.declare("echo", "v")
    server = rpc.Server(Svc()).start()
    client = rpc.Client(server.addr, timeout=0.3)
    try:
        assert client.call("echo", v=1)["v"] == 1
        fp.activate("rpc.server.recv=drop:max=1")
        with pytest.raises(rpc.RpcError):
            client.call("echo", v=2)
        # convergence: the next attempt is not dropped
        assert client.call("echo", v=3)["v"] == 3
        assert fp.fire_count("rpc.server.recv") == 1
    finally:
        client.close()
        server.stop()


def test_rpc_client_send_drop_with_retry_policy_converges():
    class Svc:
        def handle_echo(self, conn, rid, msg):
            return {"v": msg["v"]}

    rpc.declare("echo", "v")
    server = rpc.Server(Svc()).start()
    client = rpc.Client(server.addr, timeout=0.2)
    try:
        fp.activate("rpc.client.send=drop:max=2")
        policy = RetryPolicy(max_attempts=5, base_s=0.0)
        out = policy.run(lambda: client.call("echo", v=7),
                         loop="t.rpc_drop", retry_on=(rpc.RpcError,))
        assert out["v"] == 7
        # exactly the configured drops fired before convergence
        assert fp.fire_count("rpc.client.send") == 2
        assert fp.hit_count("rpc.client.send") == 3
    finally:
        client.close()
        server.stop()


def test_worker_retry_seam_fires(ray_start_regular):
    """The worker.retry failpoint observes every task retry (wiring
    smoke for the retry seam + hit log assertions)."""
    fp.activate("worker.retry=delay(0)")
    state_dir = ray_start_regular.session_dir

    @ray_tpu.remote(max_retries=2, retry_exceptions=True)
    def flaky():
        import os
        marker = os.path.join(state_dir, "flaky_ran")
        if not os.path.exists(marker):
            open(marker, "w").close()
            raise RuntimeError("first attempt fails")
        return "done"

    assert ray_tpu.get(flaky.remote()) == "done"
    assert fp.fire_count("worker.retry") == 1
    log = fp.hit_log("worker.retry")
    assert log[0]["attempt"] == 0


def test_worker_retry_error_arm_fails_task(ray_start_regular):
    """An error arm on worker.retry converts a retryable failure into a
    terminal typed error (retry suppression)."""
    from ray_tpu import exceptions as exc
    fp.activate("worker.retry=error()")

    @ray_tpu.remote(max_retries=5, retry_exceptions=True)
    def always_fails():
        raise RuntimeError("app error")

    with pytest.raises(exc.TaskError):
        ray_tpu.get(always_fails.remote())
    assert fp.fire_count("worker.retry") == 1


def test_fast_lane_ping_send_failure_is_typed_and_slot_free():
    """Regression (fast_lane.py ping): a send failure must pop the
    pending slot, mark the lane dead, and raise FastLaneError — not
    leak the slot and surface a raw OSError."""
    import socket

    from ray_tpu._private import fast_lane as fle

    # a real listener so the client connects; we never accept frames
    srv = socket.create_server(("127.0.0.1", 0))
    client = fle.FastLaneClient(srv.getsockname())
    try:
        # arm the seam INSIDE _submit_op, which fires after the pending
        # slot is installed — so this actually exercises the
        # pop-on-send-failure cleanup (the bug leaked that slot)
        fp.activate("fast_lane.submit=error(OSError)")
        with pytest.raises(fle.FastLaneError):
            client.ping(timeout=0.5)
        assert client.dead
        assert not client._pending      # no leaked slot
        # a dead lane refuses further ops with the typed error
        with pytest.raises(fle.FastLaneError):
            client.submit(b"x")
    finally:
        client.close()
        srv.close()


def test_fast_lane_submit_failure_pops_slot():
    import socket

    from ray_tpu._private import fast_lane as fle

    srv = socket.create_server(("127.0.0.1", 0))
    client = fle.FastLaneClient(srv.getsockname())
    try:
        fp.activate("fast_lane.submit=error(OSError)")
        with pytest.raises(fle.FastLaneError):
            client.submit(b"payload")
        assert client.dead
        assert not client._pending
    finally:
        client.close()
        srv.close()


def test_config_flag_activation(monkeypatch):
    """ray_tpu.init activates failpoints from the config flag."""
    fp.reset()
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      _system_config={
                          "failpoints": "test.flag_seam=delay(0)",
                          "failpoints_seed": 5})
    try:
        assert fp.ENABLED
        fp.fire("test.flag_seam")
        assert fp.hit_count("test.flag_seam") == 1
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# seam registry coverage (raylint failpoint-registry contract: every
# wired seam name is unique, documented, and exercised here or in a
# deeper suite)
# ---------------------------------------------------------------------------

# The canonical seam catalogue. raylint cross-checks every fire() call
# site in ray_tpu/ against docs/fault_tolerance.md AND tests/ — adding
# a seam without updating this list (or another test) fails CI.
WIRED_SEAMS = [
    "rpc.client.send",
    "rpc.client.recv",
    "rpc.server.recv",
    "fast_lane.submit",
    "fast_lane.ping",
    "fast_lane.reconnect",
    "cluster.lane_reconnect",
    "cluster.cancel",
    "daemon.lease",
    "daemon.push_task",
    "daemon.pull_transfer",
    "daemon.oom_check",
    "head.kv_put",
    "head.pubsub_publish",
    "head.respawn",
    "worker.retry",
    "worker.generator_stream",
    "drain.announce",
    "drain.migrate_object",
    "drain.deadline",
    "daemon.push_transfer",
    "shm.attach",
    "shm.seal",
    "batch.submit_flush",
    "batch.free_flush",
    "batch.result_flush",
    "trace.flush",
    "profile.flush",
    "admission.verdict",
    "tenancy.quota_sync",
    "arena.grant_reclaim",
    "arena.reservation_sweep",
    "net.link_drop",
    "net.partition_heal",
    "arena.spill",
    "arena.restore",
    "pressure.level",
]


def test_every_wired_seam_activates_and_fires():
    """One spec string arming EVERY wired seam parses, arms each name
    independently, and fires deterministically — a renamed seam that
    drifts from the catalogue shows up here (and in raylint) instead of
    silently never firing in a chaos schedule."""
    fp.activate(";".join(f"{name}=delay(0)" for name in WIRED_SEAMS))
    desc = fp.describe()
    assert sorted(desc) == sorted(WIRED_SEAMS)
    for name in WIRED_SEAMS:
        assert desc[name]["action"] == "delay"
        assert fp.fire(name) is None        # delay(0): benign arm
        assert fp.hit_count(name) == 1, name
        assert fp.fire_count(name) == 1, name


def test_rpc_client_recv_drop_loses_reply_then_recovers():
    """rpc.client.recv seam: a dropped incoming reply frame leaves the
    caller waiting (timeout), and the connection recovers afterwards."""

    class Svc:
        def handle_echo2(self, conn, rid, msg):
            return {"v": msg["v"]}

    rpc.declare("echo2", "v")
    server = rpc.Server(Svc()).start()
    client = rpc.Client(server.addr, timeout=0.3)
    try:
        assert client.call("echo2", v=1)["v"] == 1
        fp.activate("rpc.client.recv=drop:max=1")
        with pytest.raises(rpc.RpcError):
            client.call("echo2", v=2)
        assert client.call("echo2", v=3)["v"] == 3
        assert fp.fire_count("rpc.client.recv") == 1
    finally:
        client.close()
        server.stop()


def test_fast_lane_ping_drop_marks_lane_dead():
    """fast_lane.ping seam: the drop arm surfaces as the typed
    FastLaneError and marks the lane dead (health probes must never
    leak raw OSErrors into daemon stats paths)."""
    import socket

    from ray_tpu._private import fast_lane as fle

    srv = socket.create_server(("127.0.0.1", 0))
    client = fle.FastLaneClient(srv.getsockname())
    try:
        fp.activate("fast_lane.ping=drop")
        with pytest.raises(fle.FastLaneError):
            client.ping(timeout=0.5)
        assert client.dead
        assert fp.fire_count("fast_lane.ping") == 1
    finally:
        client.close()
        srv.close()


def test_head_pubsub_publish_drop_starves_the_log():
    """head.pubsub_publish seam: a dropped publish never reaches the
    channel log (subscribers starve); the next publish lands."""
    from ray_tpu._private.head import HeadService

    svc = HeadService()
    try:
        fp.activate("head.pubsub_publish=drop:max=1")
        svc._publish("t", {"kind": "lost"})
        svc._publish("t", {"kind": "kept"})
        with svc._lock:
            kinds = [e["kind"] for e in svc._events.get("t", [])]
        assert kinds == ["kept"]
        # both publishes HIT the seam; only the first FIRED (max=1)
        assert fp.hit_count("head.pubsub_publish") == 2
        assert fp.fire_count("head.pubsub_publish") == 1
    finally:
        svc._stop.set()


def test_drain_announce_drop_loses_the_notice():
    """drain.announce seam: the drop arm means the self-announced drain
    never reaches the head (the crash path is the backstop); without
    the arm the same announce lands as a DRAINING membership state."""
    from ray_tpu._private.daemon import PreemptionWatcher
    from ray_tpu._private.head import HeadService

    import types

    svc = HeadService()
    server = rpc.Server(svc).start()
    try:
        # register through the handler directly: an rpc.Client would
        # mark the node dead on disconnect (conn.meta fencing)
        svc.handle_register_node(
            types.SimpleNamespace(meta={}, link=lambda *a: None), 1,
            {"node_id": "n1", "resources": {}, "labels": {},
             "addr": ["127.0.0.1", 1]})

        fp.activate("drain.announce=drop")
        w = PreemptionWatcher("n1", server.addr, deadline_s=30.0)
        w.notify("preempted")
        w._announce()
        with svc._lock:
            assert not svc._nodes["n1"].draining   # notice lost
        assert fp.fire_count("drain.announce") == 1

        fp.reset()
        w2 = PreemptionWatcher("n1", server.addr, deadline_s=30.0)
        w2.notify("preempted")
        w2._announce()
        with svc._lock:
            assert svc._nodes["n1"].draining       # notice landed
    finally:
        svc._stop.set()
        server.stop()
