"""Memory monitor + worker-killing policy, Serve long-poll push, and
controller crash recovery.

Reference capabilities: ``common/memory_monitor.h:52`` +
``raylet/worker_killing_policy*.h`` (OOM defense),
``serve/_private/long_poll.py:70,222`` (push config propagation),
``serve/tests/test_controller_crashes.py`` (controller recovery).
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc


def _rt():
    return ray_tpu._private.worker.global_runtime()


def test_memory_monitor_kills_and_retries(ray_start_regular, tmp_path):
    """A task that blows past the memory limit is SIGKILLed by the
    monitor and retried; with retries exhausted it fails with
    OutOfMemoryError."""
    rt = _rt()
    mon = rt.memory_monitor
    mon.interval_s = 0.1
    if not mon._thread.is_alive():
        mon.start()
    baseline = mon.usage_bytes()
    mon.set_limit(baseline + 150 * 1024 * 1024)  # headroom: ~150MB
    try:
        @ray_tpu.remote(max_retries=1)
        def hog():
            import numpy as np
            import time as _t
            blob = np.ones(400 * 1024 * 1024 // 8)  # ~400MB
            _t.sleep(20)
            return blob.sum()

        with pytest.raises(exc.OutOfMemoryError):
            ray_tpu.get(hog.remote(), timeout=60)
        # local topology: the driver's monitor killed; daemons
        # topology: each NODE's monitor polices its own workers (the
        # raylet role) and reports kills over the wire
        kills = mon.kills
        backend = getattr(rt, "cluster_backend", None)
        if backend is not None:
            for h in backend.daemons.values():
                kills += h.client.call("oom_check", task_id="",
                                       fast_lane=False)["kills"]
        assert kills >= 1
    finally:
        mon.set_limit(1 << 62)


def test_memory_monitor_policy_prefers_retriable():
    from ray_tpu._private.memory_monitor import (_Candidate,
                                                 GroupByOwnerPolicy,
                                                 RetriableFIFOPolicy)

    cands = [
        _Candidate(1, "task", task_id="a", retriable=False, started_at=5),
        _Candidate(2, "task", task_id="b", retriable=True, started_at=3),
        _Candidate(3, "task", task_id="c", retriable=True, started_at=4),
        _Candidate(4, "actor", actor_id="x", retriable=True,
                   started_at=9),
    ]
    # newest RETRIABLE TASK first, not the non-retriable or the actor
    assert RetriableFIFOPolicy().pick(cands).task_id == "c"
    # group-by-owner: the biggest owner group gets trimmed
    grouped = [
        _Candidate(1, "task", task_id="a", retriable=True, started_at=1,
                   owner_key="flood"),
        _Candidate(2, "task", task_id="b", retriable=True, started_at=2,
                   owner_key="flood"),
        _Candidate(3, "task", task_id="c", retriable=True, started_at=9,
                   owner_key="singleton"),
    ]
    assert GroupByOwnerPolicy().pick(grouped).owner_key == "flood"


def test_serve_long_poll_pushes_membership(ray_start_regular):
    """Scaling a deployment is pushed to handles without any request
    traffic (no poll-on-interval staleness window)."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind())
    assert handle.remote("hi").result() == "hi"
    controller = ray_tpu.get_actor("serve_controller")
    ray_tpu.get(controller.set_target_replicas.remote("Echo", 2))
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with handle._lock:
            if len(handle._replicas) == 2:
                break
        time.sleep(0.05)
    else:
        pytest.fail("membership change was never pushed to the handle")
    serve.shutdown()


def test_serve_controller_crash_recovery(ray_start_regular):
    """Kill the controller actor: a fresh incarnation restores the
    deployment specs from the KV checkpoint and RE-BINDS the still-live
    named replicas (stateful replica keeps its state)."""
    from ray_tpu import serve
    from ray_tpu.serve.api import _get_controller

    @serve.deployment(num_replicas=1)
    class Counter:
        def __init__(self):
            self.n = 0

        def __call__(self, _):
            self.n += 1
            return self.n

    handle = serve.run(Counter.bind())
    assert handle.remote(None).result() == 1
    controller = ray_tpu.get_actor("serve_controller")
    ray_tpu.kill(controller)
    time.sleep(0.3)

    # next controller touch recreates it; recovery re-binds the replica
    new_controller = _get_controller(create=True)
    from ray_tpu.serve.router import DeploymentHandle

    h2 = DeploymentHandle("Counter", new_controller)
    deadline = time.monotonic() + 20
    result = None
    while time.monotonic() < deadline:
        try:
            result = h2.remote(None).result(timeout=10)
            break
        except Exception:
            time.sleep(0.2)
    # state preserved => the SAME replica was adopted, not restarted
    assert result == 2
    serve.shutdown()


def test_serve_grpc_proxy(ray_start_regular):
    """gRPC ingress plane (reference: serve/_private/proxy.py gRPCProxy):
    a real grpc.Server routing to deployment handles."""
    from ray_tpu import serve
    from ray_tpu.serve.grpc_proxy import (GrpcServeClient,
                                          start_grpc_proxy,
                                          stop_grpc_proxy)

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

        def shout(self, s):
            return s.upper()

    serve.run(Doubler.bind())
    port = start_grpc_proxy()
    client = GrpcServeClient(f"127.0.0.1:{port}")
    try:
        assert client.healthz()
        assert client.predict(21) == 42
        assert client.predict("abc", method="shout") == "ABC"
        assert "default" in client.list_applications()
        with pytest.raises(RuntimeError):
            client.predict(1, application="missing")
    finally:
        client.close()
        stop_grpc_proxy()
        serve.shutdown()


def test_serve_grpc_streaming(ray_start_regular):
    """gRPC server-streaming Predict: chunks arrive as the replica
    produces them (the second streaming ingress next to HTTP SSE)."""
    import time as _time

    from ray_tpu import serve
    from ray_tpu.serve.grpc_proxy import (GrpcServeClient,
                                          start_grpc_proxy,
                                          stop_grpc_proxy)

    @serve.deployment
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                _time.sleep(0.2)
                yield {"i": i}

    serve.run(Streamer.bind())
    port = start_grpc_proxy()
    client = GrpcServeClient(f"127.0.0.1:{port}")
    try:
        t0 = _time.monotonic()
        chunks = []
        t_first = None
        for chunk in client.predict_stream(4):
            if t_first is None:
                t_first = _time.monotonic() - t0
            chunks.append(chunk)
        t_all = _time.monotonic() - t0
        assert [c["i"] for c in chunks] == [0, 1, 2, 3]
        assert t_first < t_all - 0.3, (t_first, t_all)
    finally:
        client.close()
        stop_grpc_proxy()
        serve.shutdown()


# ---------------------------------------------------------------------------
# Graceful drain: head membership state machine + HeadClient fixes
# ---------------------------------------------------------------------------

class _FakeConn:
    """Just enough Connection for direct HeadService handler calls."""

    def __init__(self):
        self.meta = {}
        self.replies = []

    def reply(self, rid, **kw):
        self.replies.append((rid, kw))

    def link(self, *a, **kw):
        pass


def _register(svc, node_id="n1", port=7001):
    return svc.handle_register_node(_FakeConn(), 1, {
        "node_id": node_id, "resources": {"CPU": 4.0}, "labels": {},
        "addr": ["127.0.0.1", port]})


def test_head_drain_state_and_deadline_escalation(tmp_path):
    """drain_node moves the node to alive+DRAINING (publishing
    node_drain, NOT node_death); the health loop escalates into the
    death path once the deadline expires."""
    from ray_tpu._private.head import HeadService

    svc = HeadService()
    try:
        assert _register(svc)["ok"]
        out = svc.handle_drain_node(_FakeConn(), 2, {
            "node_id": "n1", "deadline_s": 0.2, "reason": "preempt"})
        assert out["ok"]
        view = svc.handle_list_nodes(_FakeConn(), 3, {})["nodes"][0]
        assert view["alive"] and view["draining"]
        assert view["drain_reason"] == "preempt"
        events = svc._events.get("node", [])
        assert any(e.get("kind") == "drain" for e in events)
        assert not any(e.get("kind") == "death" for e in events)
        # deadline passes -> the monitor escalates to death
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            view = svc.handle_list_nodes(_FakeConn(), 4, {})["nodes"][0]
            if not view["alive"]:
                break
            time.sleep(0.05)
        assert not view["alive"]
        assert view["reason"] == "drain deadline expired"
        assert any(e.get("kind") == "death" and e.get("was_draining")
                   for e in svc._events["node"])
    finally:
        svc._stop.set()


def test_head_drain_survives_restart(tmp_path):
    """With --state-path, a drain outlives a head restart: when the
    draining daemon re-registers at the fresh head, the DRAINING state
    (and its remaining deadline) re-attaches and is re-announced."""
    from ray_tpu._private.head import HeadService

    state = str(tmp_path / "head_state.db")
    svc = HeadService(state_path=state)
    try:
        _register(svc)
        svc.handle_drain_node(_FakeConn(), 2, {
            "node_id": "n1", "deadline_s": 60.0, "reason": "maint"})
    finally:
        svc._stop.set()
    svc._store._db.close()

    svc2 = HeadService(state_path=state)    # the respawned head
    try:
        # membership is not persisted (daemons re-register themselves)
        assert svc2.handle_list_nodes(_FakeConn(), 1, {})["nodes"] == []
        out = _register(svc2)
        assert out["ok"] and out["draining"]
        view = svc2.handle_list_nodes(_FakeConn(), 2, {})["nodes"][0]
        assert view["draining"] and view["drain_reason"] == "maint"
        assert 0 < view["drain_deadline_s"] <= 60.0
        # the drain event is re-announced for (re)subscribed drivers
        assert any(e.get("kind") == "drain"
                   for e in svc2._events.get("node", []))
    finally:
        svc2._stop.set()


def test_head_rejects_zombie_reregistration():
    """A node_id we declared dead may not re-register with stale state —
    the register reply mirrors the heartbeat {"dead": True} contract."""
    from ray_tpu._private.head import HeadService

    svc = HeadService()
    try:
        _register(svc)
        svc._mark_dead("n1", "missed heartbeats")
        out = _register(svc)
        assert out.get("dead") and not out.get("ok")
        view = svc.handle_list_nodes(_FakeConn(), 9, {})["nodes"][0]
        assert not view["alive"]
        # a FRESH node id still registers fine
        assert _register(svc, node_id="n2", port=7002)["ok"]
    finally:
        svc._stop.set()


def test_head_client_publish_survives_head_restart():
    """HeadClient.publish rides the reconnect/retry path: with a
    reconnect window it survives the head process being replaced
    (the old direct client.call failed mid-restart)."""
    import threading

    from ray_tpu._private import rpc
    from ray_tpu._private.head import HeadClient, HeadService

    svc = HeadService()
    server = rpc.Server(svc, host="127.0.0.1", port=0).start()
    port = server.addr[1]
    client = HeadClient(("127.0.0.1", port), reconnect_window=10.0)
    try:
        client.publish("chan", {"n": 1})
        server.stop()
        svc._stop.set()

        svc2 = HeadService()
        holder = {}

        def restart():
            time.sleep(0.4)
            holder["server"] = rpc.Server(
                svc2, host="127.0.0.1", port=port).start()

        t = threading.Thread(target=restart, daemon=True)
        t.start()
        client.publish("chan", {"n": 2})     # rides the redial window
        t.join()
        assert svc2._events["chan"] == [{"n": 2}]
    finally:
        client.close()
        svc2._stop.set()
        holder["server"].stop()


def test_head_client_close_joins_subscriber_threads():
    """close() closes the per-channel subscriber connections and joins
    the threads (no leaked sockets / parked long-polls)."""
    from ray_tpu._private import rpc
    from ray_tpu._private.head import HeadClient, HeadService

    svc = HeadService()
    server = rpc.Server(svc, host="127.0.0.1", port=0).start()
    client = HeadClient(server.addr, reconnect_window=5.0)
    try:
        seen = []
        client.subscribe("events", seen.append)
        client.publish("events", {"x": 1})
        deadline = time.monotonic() + 5
        while not seen and time.monotonic() < deadline:
            time.sleep(0.02)
        assert seen == [{"x": 1}]
        client.close()
        for t in client._sub_threads:
            t.join(timeout=3.0)
            assert not t.is_alive(), "subscriber thread leaked"
        assert all(c.dead for c in client._sub_clients) \
            or not client._sub_clients
    finally:
        svc._stop.set()
        server.stop()
