"""Utility tests: ActorPool, Queue (reference: ray.util)."""

import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Queue


@ray_tpu.remote
class Doubler:
    def double(self, x):
        return 2 * x


def test_actor_pool_map(ray_start_regular):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(6)))
    assert out == [0, 2, 4, 6, 8, 10]


def test_actor_pool_unordered(ray_start_regular):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = sorted(pool.map_unordered(lambda a, v: a.double.remote(v),
                                    range(6)))
    assert out == [0, 2, 4, 6, 8, 10]


def test_queue_basic(ray_start_regular):
    q = Queue(maxsize=4)
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.get() == "a"
    assert q.get() == "b"
    assert q.empty()


def test_queue_nowait_errors(ray_start_regular):
    from ray_tpu.util.queue import Empty, Full
    q = Queue(maxsize=1)
    q.put_nowait(1)
    with pytest.raises(Full):
        q.put_nowait(2)
    assert q.get_nowait() == 1
    with pytest.raises(Empty):
        q.get_nowait()


def test_queue_cross_task(ray_start_regular):
    q = Queue()

    @ray_tpu.remote
    def producer(queue, n):
        for i in range(n):
            queue.put(i)
        return "done"

    @ray_tpu.remote
    def consumer(queue, n):
        return [queue.get() for _ in range(n)]

    p = producer.remote(q, 5)
    c = consumer.remote(q, 5)
    assert ray_tpu.get(c) == [0, 1, 2, 3, 4]
    assert ray_tpu.get(p) == "done"
