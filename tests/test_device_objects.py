"""Cross-process device (HBM) objects: sharding-preserving transfer.

Reference capability: `python/ray/experimental/gpu_object_manager/
gpu_object_manager.py:18` — device tensors crossing process boundaries.
"""

import numpy as np
import pytest

import ray_tpu


def _mesh_2x2():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                ("dp", "tp"))


def test_sharded_array_wire_roundtrip_preserves_sharding():
    """The wire format must carry NamedSharding meta — jax's built-in
    reducer collapses it to one device."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu._private.serialization import SerializationContext
    mesh = _mesh_2x2()
    x = jax.device_put(jnp.arange(32.0).reshape(8, 4),
                       NamedSharding(mesh, P("dp", "tp")))
    ctx = SerializationContext()
    back = ctx.deserialize(ctx.serialize(x))
    assert isinstance(back.sharding, NamedSharding)
    assert back.sharding.mesh.axis_names == ("dp", "tp")
    assert tuple(back.sharding.spec) == ("dp", "tp")
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


def test_replicated_spec_and_plain_array_roundtrip():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu._private.serialization import SerializationContext
    ctx = SerializationContext()
    # replicated over the mesh
    mesh = _mesh_2x2()
    r = jax.device_put(jnp.ones((4, 4)), NamedSharding(mesh, P()))
    back = ctx.deserialize(ctx.serialize(r))
    assert isinstance(back.sharding, NamedSharding)
    assert tuple(back.sharding.spec) == ()
    # plain single-device array still round-trips
    p = ctx.deserialize(ctx.serialize(jnp.arange(6)))
    np.testing.assert_array_equal(np.asarray(p), np.arange(6))
    # bf16 payloads survive (ml_dtypes numpy on the host leg)
    b = ctx.deserialize(ctx.serialize(jnp.ones(8, jnp.bfloat16)))
    assert str(b.dtype) == "bfloat16"


def test_device_array_through_real_daemon():
    """A daemon-hosted actor (separate OS process) returns device
    arrays; the driver gets live jax.Arrays back — including a sharded
    one rematerialized on the driver's mesh — and can send one as an
    argument the other way."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons")
    try:
        @ray_tpu.remote
        class DeviceHost:
            def make(self, n):
                import jax.numpy as jnp
                return jnp.arange(float(n)) * 2.0

            def make_sharded(self):
                import jax
                import jax.numpy as jnp
                import numpy as np
                from jax.sharding import (Mesh, NamedSharding,
                                          PartitionSpec as P)
                mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                            ("dp", "tp"))
                return jax.device_put(
                    jnp.arange(32.0).reshape(8, 4),
                    NamedSharding(mesh, P("dp", "tp")))

            def total(self, arr):
                # consumes a device array shipped driver -> worker
                import jax.numpy as jnp
                return float(jnp.sum(arr))

        h = DeviceHost.options(num_cpus=1).remote()
        out = ray_tpu.get(h.make.remote(5), timeout=120)
        assert isinstance(out, jax.Array)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.arange(5.0) * 2.0)

        sharded = ray_tpu.get(h.make_sharded.remote(), timeout=120)
        assert isinstance(sharded.sharding, NamedSharding)
        assert sharded.sharding.mesh.axis_names == ("dp", "tp")
        np.testing.assert_array_equal(
            np.asarray(sharded), np.arange(32.0).reshape(8, 4))

        # driver -> worker direction
        arg = jax.device_put(jnp.ones(16),
                             NamedSharding(_mesh_2x2(), P("dp")))
        assert ray_tpu.get(h.total.remote(arg), timeout=120) == 16.0
    finally:
        ray_tpu.shutdown()


def test_driver_local_zero_copy_fast_path(ray_start_regular):
    """In-process consumers get the LIVE device array (HBM tier) —
    the exact same buffer, no serialization."""
    import jax.numpy as jnp

    x = jnp.arange(8.0)
    ref = ray_tpu.put(x)
    got = ray_tpu.get(ref)
    assert got is x                     # zero-copy: identity preserved
