"""pip runtime-env materialization (offline wheelhouse).

Reference capability: `python/ray/_private/runtime_env/pip.py` — tasks
declaring ``runtime_env={"pip": ...}`` run with those packages
importable.
"""

import os
import zipfile

import pytest

import ray_tpu
from ray_tpu._private.runtime_env_pip import env_dir_for, materialize_pip


def _make_wheel(wheelhouse: str, name: str, version: str,
                source: str) -> str:
    """Hand-roll a minimal pure-python wheel (a wheel is a zip with
    dist-info metadata) so the test needs no network and no build
    backend."""
    os.makedirs(wheelhouse, exist_ok=True)
    whl = os.path.join(wheelhouse, f"{name}-{version}-py3-none-any.whl")
    info = f"{name}-{version}.dist-info"
    with zipfile.ZipFile(whl, "w") as z:
        z.writestr(f"{name}/__init__.py", source)
        z.writestr(f"{info}/METADATA",
                   f"Metadata-Version: 2.1\nName: {name}\n"
                   f"Version: {version}\n")
        z.writestr(f"{info}/WHEEL",
                   "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib:"
                   " true\nTag: py3-none-any\n")
        z.writestr(f"{info}/RECORD", "")
    return whl


@pytest.fixture
def wheelhouse(tmp_path):
    wh = str(tmp_path / "wheels")
    _make_wheel(wh, "rtpu_demo_pkg", "1.0",
                "MAGIC = 'from-the-wheel'\n\n"
                "def double(x):\n    return x * 2\n")
    return wh


def test_materialize_pip_offline(wheelhouse):
    spec = {"packages": ["rtpu_demo_pkg"], "find_links": wheelhouse}
    env_dir = materialize_pip(spec)
    assert os.path.isdir(os.path.join(env_dir, "rtpu_demo_pkg"))
    # cached: second call is a no-op returning the same dir
    assert materialize_pip(spec) == env_dir == env_dir_for(spec)


def test_task_imports_pip_package(ray_start_regular, wheelhouse):
    """A task declaring the pip env imports the wheel's package; the
    driver process does NOT have it importable outside the env."""
    with pytest.raises(ImportError):
        import rtpu_demo_pkg  # noqa: F401

    @ray_tpu.remote(runtime_env={"pip": {
        "packages": ["rtpu_demo_pkg"], "find_links": wheelhouse}})
    def use_pkg(x):
        import rtpu_demo_pkg
        return rtpu_demo_pkg.MAGIC, rtpu_demo_pkg.double(x)

    magic, doubled = ray_tpu.get(use_pkg.remote(21), timeout=120)
    assert magic == "from-the-wheel"
    assert doubled == 42


def test_pip_failure_is_loud(ray_start_regular, tmp_path):
    """A package that cannot be materialized fails the task with pip's
    error — never a silent no-op."""
    @ray_tpu.remote(runtime_env={"pip": {
        "packages": ["definitely-not-a-real-pkg-xyz"],
        "find_links": str(tmp_path / "empty")}})
    def f():
        return 1

    with pytest.raises(Exception, match="pip runtime_env"):
        ray_tpu.get(f.remote(), timeout=120)


def test_uv_spec_materializes_like_pip(ray_start_regular, wheelhouse):
    """uv package specs ride the same installer (reference: uv.py
    agent; no uv binary in this image)."""
    @ray_tpu.remote(runtime_env={"uv": {
        "packages": ["rtpu_demo_pkg"], "find_links": wheelhouse}})
    def use():
        import rtpu_demo_pkg
        return rtpu_demo_pkg.MAGIC

    assert ray_tpu.get(use.remote(), timeout=120) == "from-the-wheel"
