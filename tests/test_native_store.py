"""Native shared-memory object store (C++ tier)."""

import os

import numpy as np
import pytest

from ray_tpu.native_store import ShmObjectStore, ShmStoreFull, available

pytestmark = pytest.mark.skipif(not available(),
                                reason="no native toolchain")


@pytest.fixture
def store():
    s = ShmObjectStore(f"rtpu_test_{os.getpid()}", 1 << 20)  # 1 MiB
    yield s
    s.close()


def test_put_get_zero_copy(store):
    data = np.arange(1000, dtype=np.float64)
    store.put(b"obj1", data.tobytes())
    view = store.get_view(b"obj1")
    back = np.frombuffer(view, np.float64)
    np.testing.assert_array_equal(back, data)
    assert not view.flags.writeable
    assert store.num_objects() == 1
    assert store.used_bytes() == data.nbytes
    store.release(b"obj1")


def test_duplicate_put_rejected(store):
    store.put(b"x", b"abc")
    with pytest.raises(KeyError):
        store.put(b"x", b"def")


def test_delete_frees_space(store):
    store.put(b"a", b"\x00" * 1000)
    used = store.used_bytes()
    store.delete(b"a")
    assert store.used_bytes() == used - 1000
    assert not store.contains(b"a")


def test_lru_eviction_under_pressure(store):
    # fill the 1 MiB store with 5 x 200 KiB objects (1,024,000 bytes);
    # a sixth requires evicting the least-recently-released object (o0).
    blob = b"\x01" * (200 * 1024)
    for i in range(5):
        store.put(f"o{i}".encode(), blob)
    # bump recency of o1..o4, leaving o0 as the LRU victim
    for i in range(1, 5):
        store.get_view(f"o{i}".encode())
        store.release(f"o{i}".encode())
    store.put(b"new", blob)
    assert store.contains(b"new")
    assert not store.contains(b"o0")
    assert all(store.contains(f"o{i}".encode()) for i in range(1, 5))


def test_pinned_objects_not_evicted(store):
    # hold refs on everything -> nothing evictable -> create must fail
    blob = b"\x02" * (300 * 1024)
    for name in (b"a", b"b", b"c"):
        store.put(name, blob)
        store.get_view(name)          # pin
    with pytest.raises(ShmStoreFull):
        store.put(b"d", blob)
    assert all(store.contains(n) for n in (b"a", b"b", b"c"))
    for name in (b"a", b"b", b"c"):
        store.release(name)
    store.put(b"d", blob)             # now eviction can proceed
    assert store.contains(b"d")


def test_free_list_coalescing(store):
    # allocate three adjacent objects, free the middle then the first;
    # a object larger than any single freed chunk must still fit after
    # coalescing.
    third = (1 << 20) // 3 - 64
    for name in (b"a", b"b", b"c"):
        store.put(name, b"\x03" * third)
    store.delete(b"a")
    store.delete(b"b")
    store.put(b"big", b"\x04" * (2 * third))  # needs coalesced a+b
    assert store.contains(b"big")


def test_deferred_delete_while_view_held(store):
    """delete() with outstanding get refs must NOT free the range (the
    reference's plasma keeps client buffers valid for their lifetime);
    deallocation happens at the last release (ADVICE r1 high)."""
    data = np.arange(4096, dtype=np.float64)
    store.put(b"live", data.tobytes())
    view = store.get_view(b"live")           # incref
    store.delete(b"live")                    # deferred: ref outstanding
    # Unreachable for new gets...
    with pytest.raises(KeyError):
        store.get_view(b"live")
    # ...but the held view must still read intact data, even after the
    # allocator is pressured to reuse space.
    filler = np.zeros(8192, np.uint8)
    for i in range(20):
        try:
            store.put(b"f%d" % i, filler.tobytes())
        except ShmStoreFull:
            break
    np.testing.assert_array_equal(np.frombuffer(view, np.float64), data)
    before = store.used_bytes()
    store.release(b"live")                   # last ref: frees now
    assert store.used_bytes() == before - data.nbytes


def test_deferred_deleted_object_not_evictable(store):
    data = np.ones(1024, np.float64)
    store.put(b"zombie", data.tobytes())
    view = store.get_view(b"zombie")
    store.delete(b"zombie")
    # LRU eviction must skip the zombie (readers hold it).
    freed = store.evict(1 << 30)
    np.testing.assert_array_equal(np.frombuffer(view, np.float64), data)
    store.release(b"zombie")


def test_pinned_delete_frees_space(store):
    """delete() of a pinned object (the production LocalObjectStore path)
    must consume the creator's pin ref and free the range — not leak a
    permanent zombie."""
    data = np.arange(2048, dtype=np.float64)
    store.put(b"pinned", data.tobytes(), pin=True)
    before = store.used_bytes()
    store.delete(b"pinned")
    assert store.used_bytes() == before - data.nbytes
    # Pinned + outstanding view: deferred until the view's release.
    store.put(b"pinned2", data.tobytes(), pin=True)
    view = store.get_view(b"pinned2")
    store.delete(b"pinned2")
    np.testing.assert_array_equal(np.frombuffer(view, np.float64), data)
    before = store.used_bytes()
    store.release(b"pinned2")
    assert store.used_bytes() == before - data.nbytes
