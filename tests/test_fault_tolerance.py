"""Fault tolerance: node death, lineage reconstruction, actor restart.

Reference: python/ray/tests/test_reconstruction*.py, test_actor_failures.py,
cluster fixture cluster_utils.py:135.
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import worker as worker_mod


def _node_of(rt, ref):
    """Find which node store holds an object."""
    for node in rt.nodes():
        if node.store.contains(ref.id):
            return node
    return None


def test_object_lost_reconstruction(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=3)
    def big():
        import numpy as np
        return np.ones((1000, 1000))  # 8MB -> node store, not inline

    ref = big.remote()
    ray_tpu.get(ref)
    victim = _node_of(rt, ref)
    assert victim is not None
    rt.remove_node(victim)
    # Value was lost with the node; get() must reconstruct via lineage.
    val = ray_tpu.get(ref, timeout=30)
    assert val.shape == (1000, 1000)
    assert rt.stats["objects_reconstructed"] >= 1


def test_put_object_lost_is_unrecoverable(ray_start_cluster):
    rt = ray_start_cluster
    import numpy as np
    big_val = np.ones((1000, 1000))
    ref = rt.put(big_val)  # driver put: no lineage
    # Force it onto a node store (puts are inline only if small; this is 8MB
    # but driver puts go to memory store by default — emulate a node-stored
    # put via a task instead).
    # put() values live in the owner memory store -> survive node death.
    victim = rt.nodes()[0]
    rt.remove_node(victim)
    assert ray_tpu.get(ref).shape == (1000, 1000)


def test_chained_reconstruction(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=5)
    def step(x):
        import numpy as np
        return x + np.ones((600, 600))

    @ray_tpu.remote(max_retries=5)
    def base():
        import numpy as np
        return np.zeros((600, 600))

    a = base.remote()
    b = step.remote(a)
    c = step.remote(b)
    ray_tpu.get(c)
    # Kill every node that holds any of the intermediate values.
    for ref in (a, b, c):
        n = _node_of(rt, ref)
        if n is not None and n.alive:
            rt.remove_node(n)
    assert ray_tpu.get(c, timeout=60)[0][0] == 2.0


def test_task_retry_on_node_death(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=3)
    def slow():
        time.sleep(1.0)
        return "done"

    ref = slow.remote()
    time.sleep(0.2)
    # Find the node running it and kill it mid-flight.
    with rt._tasks_lock:
        inflight = [t for t in rt._tasks.values()
                    if t.spec.name.endswith("slow")]
    assert inflight
    node = rt.get_node(inflight[0].node_id)
    rt.remove_node(node)
    assert ray_tpu.get(ref, timeout=30) == "done"
    assert rt.stats["tasks_retried"] >= 1


def test_actor_restart_on_node_death(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_restarts=2, max_task_retries=2)
    class Stateful:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = Stateful.remote()
    assert ray_tpu.get(a.inc.remote()) == 1
    node_hex = ray_tpu.get(a.node.remote())
    victim = rt.get_node(
        next(n.node_id for n in rt.nodes() if n.node_id.hex() == node_hex))
    rt.remove_node(victim)
    # Actor restarts on another node; state resets (fresh __init__).
    val = ray_tpu.get(a.inc.remote(), timeout=30)
    assert val == 1
    assert rt.stats["actor_restarts"] == 1


def test_actor_no_restart_when_limit_zero(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_restarts=0)
    class Fragile:
        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = Fragile.remote()
    node_hex = ray_tpu.get(a.node.remote())
    victim = next(n for n in rt.nodes() if n.node_id.hex() == node_hex)
    rt.remove_node(victim)
    with pytest.raises((exc.ActorDiedError, exc.ActorError)):
        ray_tpu.get(a.node.remote(), timeout=10)


def test_retries_exhausted_gives_error(ray_start_regular):
    rt = ray_start_regular

    @ray_tpu.remote(max_retries=0)
    def big():
        import numpy as np
        return np.ones((500, 500))

    ref = big.remote()
    ray_tpu.get(ref)
    node = _node_of(rt, ref)
    if node is None:
        pytest.skip("value was inlined")
    rt.remove_node(node)
    with pytest.raises(exc.ObjectLostError):
        ray_tpu.get(ref, timeout=10)
