"""Fault tolerance: node death, lineage reconstruction, actor restart.

Reference: python/ray/tests/test_reconstruction*.py, test_actor_failures.py,
cluster fixture cluster_utils.py:135.
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import worker as worker_mod


def _node_of(rt, ref):
    """Find which node store holds an object."""
    for node in rt.nodes():
        if node.store.contains(ref.id):
            return node
    return None


def test_object_lost_reconstruction(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=3)
    def big():
        import numpy as np
        return np.ones((1000, 1000))  # 8MB -> node store, not inline

    ref = big.remote()
    ray_tpu.get(ref)
    victim = _node_of(rt, ref)
    assert victim is not None
    rt.remove_node(victim)
    # Value was lost with the node; get() must reconstruct via lineage.
    val = ray_tpu.get(ref, timeout=30)
    assert val.shape == (1000, 1000)
    assert rt.stats["objects_reconstructed"] >= 1


def test_put_object_lost_is_unrecoverable(ray_start_cluster):
    rt = ray_start_cluster
    import numpy as np
    big_val = np.ones((1000, 1000))
    ref = rt.put(big_val)  # driver put: no lineage
    # Force it onto a node store (puts are inline only if small; this is 8MB
    # but driver puts go to memory store by default — emulate a node-stored
    # put via a task instead).
    # put() values live in the owner memory store -> survive node death.
    victim = rt.nodes()[0]
    rt.remove_node(victim)
    assert ray_tpu.get(ref).shape == (1000, 1000)


def test_chained_reconstruction(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=5)
    def step(x):
        import numpy as np
        return x + np.ones((600, 600))

    @ray_tpu.remote(max_retries=5)
    def base():
        import numpy as np
        return np.zeros((600, 600))

    a = base.remote()
    b = step.remote(a)
    c = step.remote(b)
    ray_tpu.get(c)
    # Kill every node that holds any of the intermediate values.
    for ref in (a, b, c):
        n = _node_of(rt, ref)
        if n is not None and n.alive:
            rt.remove_node(n)
    assert ray_tpu.get(c, timeout=60)[0][0] == 2.0


def test_task_retry_on_node_death(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=3)
    def slow():
        time.sleep(1.0)
        return "done"

    ref = slow.remote()
    time.sleep(0.2)
    # Find the node running it and kill it mid-flight.
    with rt._tasks_lock:
        inflight = [t for t in rt._tasks.values()
                    if t.spec.name.endswith("slow")]
    assert inflight
    node = rt.get_node(inflight[0].node_id)
    rt.remove_node(node)
    assert ray_tpu.get(ref, timeout=30) == "done"
    assert rt.stats["tasks_retried"] >= 1


def test_actor_restart_on_node_death(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_restarts=2, max_task_retries=2)
    class Stateful:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = Stateful.remote()
    assert ray_tpu.get(a.inc.remote()) == 1
    node_hex = ray_tpu.get(a.node.remote())
    victim = rt.get_node(
        next(n.node_id for n in rt.nodes() if n.node_id.hex() == node_hex))
    rt.remove_node(victim)
    # Actor restarts on another node; state resets (fresh __init__).
    val = ray_tpu.get(a.inc.remote(), timeout=30)
    assert val == 1
    assert rt.stats["actor_restarts"] == 1


def test_actor_no_restart_when_limit_zero(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote(max_restarts=0)
    class Fragile:
        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = Fragile.remote()
    node_hex = ray_tpu.get(a.node.remote())
    victim = next(n for n in rt.nodes() if n.node_id.hex() == node_hex)
    rt.remove_node(victim)
    with pytest.raises((exc.ActorDiedError, exc.ActorError)):
        ray_tpu.get(a.node.remote(), timeout=10)


def test_retries_exhausted_gives_error(ray_start_regular):
    rt = ray_start_regular

    @ray_tpu.remote(max_retries=0)
    def big():
        import numpy as np
        return np.ones((500, 500))

    ref = big.remote()
    ray_tpu.get(ref)
    node = _node_of(rt, ref)
    if node is None:
        pytest.skip("value was inlined")
    rt.remove_node(node)
    with pytest.raises(exc.ObjectLostError):
        ray_tpu.get(ref, timeout=10)


# ---------------------------------------------------------------------------
# Graceful drain (preemption-aware planned node departure)
# ---------------------------------------------------------------------------

def _wait_node_gone(rt, node_id, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if rt.get_node(node_id) is None:
            return True
        time.sleep(0.05)
    return False


def test_drain_migrates_objects_without_reconstruction(ray_start_cluster):
    """A clean drain copies the node's primary object replicas off it
    BEFORE departure: the value survives with objects_reconstructed
    still 0 (a hard node kill would have paid a lineage re-execution)."""
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=3)
    def big():
        import numpy as np
        return np.ones((1000, 1000))  # 8MB -> node store, not inline

    ref = big.remote()
    ray_tpu.get(ref)
    victim = _node_of(rt, ref)
    assert victim is not None
    assert ray_tpu.drain_node(victim.node_id.hex(), deadline_s=15,
                              reason="unit test")
    assert victim.draining
    assert _wait_node_gone(rt, victim.node_id)
    assert ray_tpu.get(ref, timeout=10).shape == (1000, 1000)
    assert rt.stats["objects_reconstructed"] == 0
    assert rt.stats["drain_objects_migrated"] >= 1
    assert rt.stats["drains_total"] == 1
    assert rt.stats["drain_escalations_total"] == 0


def test_drain_restarts_actors_elsewhere_pending_replayed(
        ray_start_cluster):
    """A drained node's actors come back ALIVE on surviving nodes with
    their pending tasks REPLAYED (not failed) — even with
    max_restarts=0 / max_task_retries=0, because a planned migration is
    not a failure and must not consume fault budgets."""
    rt = ray_start_cluster

    @ray_tpu.remote(max_restarts=0, max_task_retries=0)
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = Counter.remote()
    assert ray_tpu.get(a.inc.remote()) == 1
    victim_hex = ray_tpu.get(a.node.remote())
    victim = next(n for n in rt.nodes() if n.node_id.hex() == victim_hex)
    pending = [a.inc.remote() for _ in range(3)]
    assert ray_tpu.drain_node(victim_hex, deadline_s=15, reason="drill")
    # pending tasks complete (replayed on whichever incarnation runs
    # them) instead of failing with ActorDiedError
    assert ray_tpu.get(pending, timeout=30)
    assert _wait_node_gone(rt, victim.node_id)
    # the actor is ALIVE on a surviving node
    from ray_tpu._private.gcs import ActorState
    info = rt.gcs.get_actor_info(a._ray_actor_id)
    assert info.state == ActorState.ALIVE
    assert info.node_id != victim.node_id
    assert ray_tpu.get(a.node.remote(), timeout=10) != victim_hex
    # planned move: the restart budget is untouched
    assert info.num_restarts == 0
    assert rt.stats["drain_actors_migrated"] >= 1


def test_drain_resubmits_queued_tasks_without_retry(ray_start_cluster):
    """Queued-but-unstarted tasks on the draining node reschedule onto
    other nodes without consuming a retry (max_retries=0 still
    completes)."""
    rt = ray_start_cluster

    @ray_tpu.remote(num_cpus=4, max_retries=0)
    def task(i):
        time.sleep(0.4)
        return i

    # pin a deep backlog onto one node: each task takes the whole node
    victim = rt.alive_nodes()[0]
    strat = ray_tpu.NodeAffinitySchedulingStrategy(
        victim.node_id.hex(), soft=True)
    refs = [task.options(scheduling_strategy=strat).remote(i)
            for i in range(6)]
    time.sleep(0.2)     # let the first task start + backlog build
    assert ray_tpu.drain_node(victim.node_id.hex(), deadline_s=20,
                              reason="downscale")
    assert sorted(ray_tpu.get(refs, timeout=30)) == list(range(6))
    assert rt.stats["tasks_retried"] == 0
    assert _wait_node_gone(rt, victim.node_id)


def test_drain_deadline_escalates_to_node_death(ray_start_cluster):
    """A drain whose deadline expires with work still running escalates
    into the ordinary node-death path: the task retries elsewhere via
    the existing machinery and the escalation is counted."""
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=3)
    def slow():
        time.sleep(2.0)
        return "done"

    ref = slow.remote()
    time.sleep(0.3)
    with rt._tasks_lock:
        inflight = [t for t in rt._tasks.values()
                    if t.spec.name.endswith("slow")]
    assert inflight
    victim = rt.get_node(inflight[0].node_id)
    assert ray_tpu.drain_node(victim.node_id.hex(), deadline_s=0.3,
                              reason="spot reclaim")
    assert ray_tpu.get(ref, timeout=30) == "done"
    assert rt.stats["drain_escalations_total"] >= 1
    assert rt.stats["tasks_retried"] >= 1
    assert _wait_node_gone(rt, victim.node_id)


def test_drain_under_combined_load_integration(ray_start_cluster):
    """The acceptance scenario: a node under active task+actor load is
    drained — in-flight work completes or resubmits, its actors come
    back ALIVE elsewhere with pending tasks replayed, objects migrate
    (ray_tpu_drain_objects_migrated > 0) and objects_reconstructed
    stays 0 on the clean-drain path."""
    rt = ray_start_cluster

    @ray_tpu.remote(max_retries=3)
    def produce(i):
        import numpy as np
        return np.full((600, 600), i)

    @ray_tpu.remote(max_retries=3)
    def chew(x):
        time.sleep(0.2)
        return float(x[0][0])

    @ray_tpu.remote(max_restarts=0, max_task_retries=0)
    class Stateful:
        def __init__(self):
            self.seen = 0

        def hit(self):
            self.seen += 1
            time.sleep(0.05)
            return self.seen

        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    blobs = [produce.remote(i) for i in range(8)]
    ray_tpu.get(blobs)
    actors = [Stateful.remote() for _ in range(4)]
    for a in actors:
        ray_tpu.get(a.hit.remote())
    victim = _node_of(rt, blobs[0]) or rt.alive_nodes()[0]
    victim_hex = victim.node_id.hex()
    on_victim = [a for a in actors
                 if ray_tpu.get(a.node.remote()) == victim_hex]

    downstream = [chew.remote(b) for b in blobs]
    actor_pending = [a.hit.remote() for a in actors for _ in range(2)]
    assert ray_tpu.drain_node(victim_hex, deadline_s=20,
                              reason="preemption notice")
    # every in-flight piece of work completes or resubmits
    assert ray_tpu.get(downstream, timeout=40) == list(range(8))
    assert all(v >= 1 for v in ray_tpu.get(actor_pending, timeout=40))
    assert _wait_node_gone(rt, victim.node_id, timeout=30)
    # actors that lived on the victim are ALIVE on surviving nodes
    from ray_tpu._private.gcs import ActorState
    for a in on_victim:
        info = rt.gcs.get_actor_info(a._ray_actor_id)
        assert info.state == ActorState.ALIVE
        assert ray_tpu.get(a.node.remote(), timeout=10) != victim_hex
    # the blobs survived the departure without lineage re-execution
    assert ray_tpu.get(blobs, timeout=20)[3][0][0] == 3
    assert rt.stats["objects_reconstructed"] == 0
    assert rt.stats["drain_objects_migrated"] > 0
    assert rt.stats["drain_escalations_total"] == 0


def test_drain_excluded_from_new_placements(ray_start_cluster):
    """While DRAINING, the scheduler routes new tasks to other nodes."""
    rt = ray_start_cluster
    victim = rt.alive_nodes()[0]
    victim.draining = True      # flag only: no migration machinery
    try:
        @ray_tpu.remote
        def where():
            return ray_tpu.get_runtime_context().get_node_id()

        spots = ray_tpu.get([where.remote() for _ in range(12)],
                            timeout=20)
        assert victim.node_id.hex() not in spots
    finally:
        victim.draining = False


def test_drain_label_selector_never_widens(ray_start_regular):
    """A hard label selector is honored even when its only match is
    draining: the task runs on the draining matching node rather than
    leaking onto a non-matching one (or failing outright)."""
    rt = ray_start_regular
    labeled = rt.add_node({"CPU": 2}, labels={"accel": "tpu"})
    labeled.draining = True     # flag only: no migration machinery
    try:
        from ray_tpu.util.scheduling_strategies import (
            NodeLabelSchedulingStrategy)

        @ray_tpu.remote(
            scheduling_strategy=NodeLabelSchedulingStrategy(
                hard={"accel": "tpu"}))
        def where():
            return ray_tpu.get_runtime_context().get_node_id()

        assert ray_tpu.get(where.remote(),
                           timeout=15) == labeled.node_id.hex()
    finally:
        labeled.draining = False
        rt.remove_node(labeled)
