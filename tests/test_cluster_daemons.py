"""Daemon-cluster mode: head + node-daemon OS processes on the wire.

Reference capabilities exercised end to end: separately spawnable GCS/
raylet processes with a typed RPC contract (``gcs_service.proto``,
``node_manager.proto`` lease protocol + PG 2PC), cross-process shm object
transfer (``plasma``), daemon⇄daemon object pull
(``object_manager.cc:247``), active health checking with pubsub death
broadcast (``gcs_health_check_manager.h``), and chaos recovery — SIGKILL
of a daemon process triggers task retry + actor restart WITHOUT any
test-side ``remove_node()`` call.

The same public test suites (test_core_tasks / test_actors /
test_placement_group) pass unmodified against this backend with
``RAY_TPU_CLUSTER=daemons`` (see ``tests/conftest.py``); here we cover
the cluster-only behaviors.
"""

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc


@pytest.fixture
def daemon_cluster():
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


def _daemon_handles(rt):
    return list(rt.cluster_backend.daemons.values())


def test_processes_exist(daemon_cluster):
    rt = daemon_cluster
    backend = rt.cluster_backend
    assert backend.head_proc.poll() is None  # head process alive
    handles = _daemon_handles(rt)
    assert len(handles) == 2
    for handle in handles:
        assert handle.proc.poll() is None
        out = handle.client.call("daemon_ping")
        assert out["pid"] == handle.proc.pid


def test_tasks_execute_in_daemon_workers(daemon_cluster):
    rt = daemon_cluster
    daemon_pids = {h.proc.pid for h in _daemon_handles(rt)}

    @ray_tpu.remote
    def tree():
        import os
        return os.getpid(), os.getppid()

    results = ray_tpu.get([tree.remote() for _ in range(8)])
    driver = os.getpid()
    for pid, ppid in results:
        assert pid != driver
        assert pid not in daemon_pids  # a worker, not the daemon itself


def test_large_result_via_shm_arena(daemon_cluster):
    """>100KiB results stay in the daemon's object table (C++ shm arena)
    and are fetched cross-process on get()."""

    @ray_tpu.remote
    def big():
        return np.arange(200_000)  # ~1.6MB

    ref = big.remote()
    out = ray_tpu.get(ref)
    assert out.shape == (200_000,) and out[-1] == 199_999
    # the blob lives remotely: some daemon's store holds bytes
    used = [h.client.call("daemon_stats")["store_used"]
            for h in _daemon_handles(daemon_cluster)]
    assert max(used) > 100_000


def test_inter_daemon_pull(daemon_cluster):
    """Object plane: daemon B pulls an object it doesn't have from daemon
    A (ObjectManager::Pull)."""
    rt = daemon_cluster
    a, b = _daemon_handles(rt)
    blob = b"x" * 300_000
    a.put_object_blob(b"oid-pull-test", blob)
    assert b.pull_object(b"oid-pull-test", a.addr)
    got = b.get_object_blob(b"oid-pull-test")
    assert got == blob


def test_nested_ops_reach_owner(daemon_cluster):
    """Worker-initiated core ops flow daemon→driver (CoreWorkerService)."""

    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer():
        refs = [inner.remote(i) for i in range(4)]
        return sum(ray_tpu.get(refs))

    assert ray_tpu.get(outer.remote()) == 10


def test_head_kv(daemon_cluster):
    head = daemon_cluster.cluster_backend.head
    assert head.kv_put(b"k1", b"v1")
    assert head.kv_get(b"k1") == b"v1"
    assert head.kv_keys(b"k") == [b"k1"]
    head.kv_del(b"k1")
    assert head.kv_get(b"k1") is None


def test_chaos_sigkill_daemon_task_retry(daemon_cluster, tmp_path):
    """SIGKILL a daemon process mid-task: the head's health check (or the
    driver's first-hand RPC failure) marks the node dead and the task is
    retried elsewhere — no remove_node() anywhere."""
    rt = daemon_cluster
    marker = str(tmp_path)

    @ray_tpu.remote(max_retries=2)
    def slow():
        import os as _os
        import time as _time
        n = len(_os.listdir(marker))
        open(os.path.join(marker, str(n)), "w").close()
        if n == 0:
            _time.sleep(30)
        return "recovered"

    ref = slow.remote()
    deadline = time.monotonic() + 10
    victim = None
    while victim is None and time.monotonic() < deadline:
        for handle in _daemon_handles(rt):
            if handle.client.call("daemon_stats")["running"] > 0:
                victim = handle
                break
        time.sleep(0.05)
    assert victim is not None, "task never started on a daemon"
    os.kill(victim.proc.pid, signal.SIGKILL)
    assert ray_tpu.get(ref, timeout=60) == "recovered"
    # the dead daemon is gone from the alive set
    assert victim.node_id not in {n.node_id for n in rt.alive_nodes()}


def test_chaos_sigkill_daemon_actor_restart(daemon_cluster):
    """SIGKILL the daemon hosting an actor: max_restarts replays the
    actor on a surviving daemon."""
    rt = daemon_cluster

    @ray_tpu.remote(max_restarts=1, max_task_retries=2)
    class Svc:
        def pid(self):
            return os.getpid()

    a = Svc.remote()
    pid1 = ray_tpu.get(a.pid.remote())
    victim = None
    for handle in _daemon_handles(rt):
        if handle.client.call("daemon_stats")["actors"] > 0:
            victim = handle
            break
    assert victim is not None
    os.kill(victim.proc.pid, signal.SIGKILL)
    # generous budget: under full-suite load on a small host the death
    # detection (~1.5s) + creation replay + cold worker pool can take a
    # while; the property under test is recovery, not latency
    deadline = time.monotonic() + 90
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2 = ray_tpu.get(a.pid.remote(), timeout=15)
            break
        except (exc.ActorError, exc.ActorUnavailableError,
                exc.TaskError, exc.GetTimeoutError):
            time.sleep(0.3)
    assert pid2 is not None and pid2 != pid1


def test_head_health_check_marks_dead(daemon_cluster):
    """Heartbeat-miss detection: kill a daemon while IDLE (the driver has
    no in-flight RPC to observe the failure first-hand) — the head's
    health monitor must notice and broadcast the death."""
    rt = daemon_cluster
    victim = _daemon_handles(rt)[0]
    os.kill(victim.proc.pid, signal.SIGKILL)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        alive = {n.node_id for n in rt.alive_nodes()}
        if victim.node_id not in alive:
            break
        time.sleep(0.1)
    else:
        pytest.fail("head never marked the killed daemon dead")


def test_pg_2pc_bundles_on_daemons(daemon_cluster):
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="SPREAD")
    assert pg.wait(15)
    # both daemons should hold a committed bundle record
    states = []
    for handle in _daemon_handles(daemon_cluster):
        out = handle.client.call("daemon_stats")
        states.append(out)
    nodes = {b.node_id for b in pg.bundles}
    assert len(nodes) == 2
