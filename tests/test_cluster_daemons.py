"""Daemon-cluster mode: head + node-daemon OS processes on the wire.

Reference capabilities exercised end to end: separately spawnable GCS/
raylet processes with a typed RPC contract (``gcs_service.proto``,
``node_manager.proto`` lease protocol + PG 2PC), cross-process shm object
transfer (``plasma``), daemon⇄daemon object pull
(``object_manager.cc:247``), active health checking with pubsub death
broadcast (``gcs_health_check_manager.h``), and chaos recovery — SIGKILL
of a daemon process triggers task retry + actor restart WITHOUT any
test-side ``remove_node()`` call.

The same public test suites (test_core_tasks / test_actors /
test_placement_group) pass unmodified against this backend with
``RAY_TPU_CLUSTER=daemons`` (see ``tests/conftest.py``); here we cover
the cluster-only behaviors.
"""

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc


@pytest.fixture
def daemon_cluster():
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


def _daemon_handles(rt):
    return list(rt.cluster_backend.daemons.values())


def test_processes_exist(daemon_cluster):
    rt = daemon_cluster
    backend = rt.cluster_backend
    assert backend.head_proc.poll() is None  # head process alive
    handles = _daemon_handles(rt)
    assert len(handles) == 2
    for handle in handles:
        assert handle.proc.poll() is None
        out = handle.client.call("daemon_ping")
        assert out["pid"] == handle.proc.pid


def test_tasks_execute_in_daemon_workers(daemon_cluster):
    rt = daemon_cluster
    daemon_pids = {h.proc.pid for h in _daemon_handles(rt)}

    @ray_tpu.remote
    def tree():
        import os
        return os.getpid(), os.getppid()

    results = ray_tpu.get([tree.remote() for _ in range(8)])
    driver = os.getpid()
    for pid, ppid in results:
        assert pid != driver
        assert pid not in daemon_pids  # a worker, not the daemon itself


def test_large_result_via_shm_arena(daemon_cluster):
    """>100KiB results stay in the daemon's object table (C++ shm arena)
    and are fetched cross-process on get()."""

    @ray_tpu.remote
    def big():
        return np.arange(200_000)  # ~1.6MB

    ref = big.remote()
    out = ray_tpu.get(ref)
    assert out.shape == (200_000,) and out[-1] == 199_999
    # the blob lives remotely: some daemon's store holds bytes
    used = [h.client.call("daemon_stats")["store_used"]
            for h in _daemon_handles(daemon_cluster)]
    assert max(used) > 100_000


def test_inter_daemon_pull(daemon_cluster):
    """Object plane: daemon B pulls an object it doesn't have from daemon
    A (ObjectManager::Pull)."""
    rt = daemon_cluster
    a, b = _daemon_handles(rt)
    blob = b"x" * 300_000
    a.put_object_blob(b"oid-pull-test", blob)
    assert b.pull_object(b"oid-pull-test", a.addr)
    got = b.get_object_blob(b"oid-pull-test")
    assert got == blob


def test_nested_ops_reach_owner(daemon_cluster):
    """Worker-initiated core ops flow daemon→driver (CoreWorkerService)."""

    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer():
        refs = [inner.remote(i) for i in range(4)]
        return sum(ray_tpu.get(refs))

    assert ray_tpu.get(outer.remote()) == 10


def test_head_kv(daemon_cluster):
    head = daemon_cluster.cluster_backend.head
    assert head.kv_put(b"k1", b"v1")
    assert head.kv_get(b"k1") == b"v1"
    assert head.kv_keys(b"k") == [b"k1"]
    head.kv_del(b"k1")
    assert head.kv_get(b"k1") is None


def test_chaos_sigkill_daemon_task_retry(daemon_cluster, tmp_path):
    """SIGKILL a daemon process mid-task: the head's health check (or the
    driver's first-hand RPC failure) marks the node dead and the task is
    retried elsewhere — no remove_node() anywhere."""
    rt = daemon_cluster
    marker = str(tmp_path)

    @ray_tpu.remote(max_retries=2)
    def slow():
        import os as _os
        import time as _time
        n = len(_os.listdir(marker))
        open(os.path.join(marker, str(n)), "w").close()
        if n == 0:
            _time.sleep(30)
        return "recovered"

    ref = slow.remote()
    deadline = time.monotonic() + 10
    victim = None
    while victim is None and time.monotonic() < deadline:
        for handle in _daemon_handles(rt):
            if handle.client.call("daemon_stats")["running"] > 0:
                victim = handle
                break
        time.sleep(0.05)
    assert victim is not None, "task never started on a daemon"
    os.kill(victim.proc.pid, signal.SIGKILL)
    assert ray_tpu.get(ref, timeout=60) == "recovered"
    # the dead daemon is gone from the alive set
    assert victim.node_id not in {n.node_id for n in rt.alive_nodes()}


def test_chaos_sigkill_daemon_actor_restart(daemon_cluster):
    """SIGKILL the daemon hosting an actor: max_restarts replays the
    actor on a surviving daemon."""
    rt = daemon_cluster

    @ray_tpu.remote(max_restarts=1, max_task_retries=2)
    class Svc:
        def pid(self):
            return os.getpid()

    a = Svc.remote()
    pid1 = ray_tpu.get(a.pid.remote())
    victim = None
    for handle in _daemon_handles(rt):
        if handle.client.call("daemon_stats")["actors"] > 0:
            victim = handle
            break
    assert victim is not None
    os.kill(victim.proc.pid, signal.SIGKILL)
    # generous budget: under full-suite load on a small host the death
    # detection (~1.5s) + creation replay + cold worker pool can take a
    # while; the property under test is recovery, not latency
    deadline = time.monotonic() + 90
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2 = ray_tpu.get(a.pid.remote(), timeout=15)
            break
        except (exc.ActorError, exc.ActorUnavailableError,
                exc.TaskError, exc.GetTimeoutError):
            time.sleep(0.3)
    assert pid2 is not None and pid2 != pid1


def test_head_health_check_marks_dead(daemon_cluster):
    """Heartbeat-miss detection: kill a daemon while IDLE (the driver has
    no in-flight RPC to observe the failure first-hand) — the head's
    health monitor must notice and broadcast the death."""
    rt = daemon_cluster
    victim = _daemon_handles(rt)[0]
    os.kill(victim.proc.pid, signal.SIGKILL)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        alive = {n.node_id for n in rt.alive_nodes()}
        if victim.node_id not in alive:
            break
        time.sleep(0.1)
    else:
        pytest.fail("head never marked the killed daemon dead")


def test_pg_2pc_bundles_on_daemons(daemon_cluster):
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="SPREAD")
    assert pg.wait(15)
    # both daemons should hold a committed bundle record
    states = []
    for handle in _daemon_handles(daemon_cluster):
        out = handle.client.call("daemon_stats")
        states.append(out)
    nodes = {b.node_id for b in pg.bundles}
    assert len(nodes) == 2


def test_chaos_sigkill_head_cluster_survives(daemon_cluster):
    """Head FT (reference: GCS restart + Redis reload, gcs_init_data.h):
    SIGKILL the head mid-run; the supervisor respawns it on the same port
    with the sqlite state, daemons re-register, KV survives, and task
    submission keeps working."""
    rt = daemon_cluster
    backend = rt.cluster_backend

    backend.head.kv_put(b"pre-crash", b"survives", namespace=b"t")
    old_pid = backend.head_proc.pid
    os.kill(old_pid, signal.SIGKILL)

    # supervisor respawns the head on the same port
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if (backend.head_proc.pid != old_pid
                and backend.head_proc.poll() is None):
            break
        time.sleep(0.1)
    assert backend.head_proc.pid != old_pid, "head was not respawned"

    # persisted KV reloaded by the restarted head
    assert backend.head.kv_get(b"pre-crash", namespace=b"t") == b"survives"
    backend.head.kv_put(b"post-crash", b"ok", namespace=b"t")
    assert backend.head.kv_get(b"post-crash", namespace=b"t") == b"ok"

    # daemons survived the outage and re-registered: membership rebuilt
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        alive = [n for n in backend.head.list_nodes() if n["alive"]]
        if len(alive) == 2:
            break
        time.sleep(0.1)
    assert len(alive) == 2, f"daemons did not re-register: {alive}"
    for handle in _daemon_handles(rt):
        assert handle.proc.poll() is None  # no daemon died with the head

    # the cluster still executes tasks end to end
    @ray_tpu.remote
    def after():
        return "recovered"

    assert ray_tpu.get(after.remote(), timeout=30) == "recovered"


# ---------------------------------------------------------------------------
# Object-manager depth (reference: object_manager.cc:247,354 chunked
# pull/push, pull_manager.h priority, push_manager.h dedup,
# ownership_object_directory.h)
# ---------------------------------------------------------------------------

def test_chunked_64mib_pull(daemon_cluster):
    """A 64 MiB object moves daemon→daemon in PULL_CHUNK pieces, not one
    monolithic RPC frame."""
    rt = daemon_cluster
    a, b = _daemon_handles(rt)
    blob = bytes(bytearray(64 * 1024 * 1024))          # 64 MiB
    a.put_object_blob(b"oid-big", blob)
    before = b.client.call("daemon_stats")["pull_stats"]
    assert b.pull_object(b"oid-big", a.addr, priority=0)
    after = b.client.call("daemon_stats")["pull_stats"]
    assert after["bytes_pulled"] - before["bytes_pulled"] == len(blob)
    assert after["chunks_transferred"] - before["chunks_transferred"] >= 16
    got = b.get_object_blob(b"oid-big")
    assert got == blob


def test_concurrent_pull_dedup(monkeypatch):
    """N concurrent pulls of one object collapse onto one transfer (the
    push-dedup role): bytes cross the wire once."""
    import threading as th
    # tiny chunks -> the transfer spans many RPCs, so all concurrent
    # pulls deterministically arrive while it is in flight
    monkeypatch.setenv("RAY_TPU_PULL_CHUNK", str(64 * 1024))
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    try:
        a, b = _daemon_handles(rt)
        blob = bytes(bytearray(8 * 1024 * 1024))
        a.put_object_blob(b"oid-dedup", blob)
        results = []
        from ray_tpu._private.rpc import Client

        def pull():
            # separate connection per puller: the server processes one
            # connection's requests sequentially, so sharing one would
            # serialize the pulls instead of racing them
            cli = Client(b.addr, timeout=120.0)
            try:
                out = cli.call("pull_object", oid=b"oid-dedup",
                               from_addr=list(a.addr), priority=2)
                results.append(out.get("ok", False))
            finally:
                cli.close()

        threads = [th.Thread(target=pull) for _ in range(6)]
        before = b.client.call("daemon_stats")["pull_stats"]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        after = b.client.call("daemon_stats")["pull_stats"]
        assert all(results)
        started = after["pulls_started"] - before["pulls_started"]
        deduped = after["pulls_deduped"] - before["pulls_deduped"]
        # one transfer, everyone else joined it; one copy of the bytes
        assert started == 1
        assert deduped == 5
        assert after["bytes_pulled"] - before["bytes_pulled"] == len(blob)
    finally:
        ray_tpu.shutdown()


def test_pull_via_owner_directory(daemon_cluster):
    """pull_object with no location hint resolves through the owner's
    object directory (ownership_object_directory.h role)."""
    rt = daemon_cluster
    a, b = _daemon_handles(rt)

    # Create an owned object on daemon A through the normal task path so
    # the owner's location metadata knows about it.
    @ray_tpu.remote
    def big():
        return np.arange(150_000)   # >100KiB: stays in the daemon table

    ref = big.remote()
    ray_tpu.get(ref)  # ensure finished + registered
    holder = None
    key = None
    for handle in (a, b):
        node = rt.get_node(handle.node_id)
        for oid in node.store.object_ids():
            holder = handle
            key = node.store._meta[oid][0]
            break
        if key:
            break
    assert key is not None
    other = b if holder is a else a
    assert not other.client.call("get_object", oid=key,
                                 prefer_shm=False).get("blob")
    assert other.pull_object(key, from_addr=None, priority=1)
    assert other.get_object_blob(key) is not None


def test_pull_priority_ordering():
    """Unit test: queued pulls are served get > wait > task-args
    (pull_manager.h:38-51)."""
    from ray_tpu._private.daemon import (PULL_PRIORITY_GET,
                                         PULL_PRIORITY_TASK_ARGS,
                                         PULL_PRIORITY_WAIT, PullManager)

    order = []
    gate = __import__("threading").Event()

    class FakeObjects:
        def contains(self, oid):
            return False

        def put(self, oid, blob):
            pass

    class FakePeer:
        def call(self, method, **kw):
            if method == "object_meta":
                gate.wait(5)             # hold transfers until all queued
                order.append(kw["oid"])
                return {"size": 1}
            return {"blob": b"x"}

    pm = PullManager(FakeObjects(), lambda addr: FakePeer(),
                     num_workers=1)
    # first pull occupies the single worker at the gate; the rest queue
    p0 = pm.request(b"warm", ("h", 1), PULL_PRIORITY_TASK_ARGS)
    time.sleep(0.2)
    p1 = pm.request(b"args", ("h", 1), PULL_PRIORITY_TASK_ARGS)
    p2 = pm.request(b"get", ("h", 1), PULL_PRIORITY_GET)
    p3 = pm.request(b"wait", ("h", 1), PULL_PRIORITY_WAIT)
    gate.set()
    for p in (p0, p1, p2, p3):
        assert p.event.wait(10)
    assert order[0] == b"warm"
    assert order[1:] == [b"get", b"wait", b"args"]


def test_resource_view_gossip(daemon_cluster):
    """Syncer role (ray_syncer.h:83): the driver gossips true per-node
    availability to the head; list_nodes and the transient 'resources'
    channel expose the live view (heartbeat static values don't clobber
    fresh gossip)."""
    rt = daemon_cluster
    backend = rt.cluster_backend
    events = []
    backend.head.subscribe("resources", events.append)

    @ray_tpu.remote(num_cpus=3)
    def hold():
        time.sleep(3.0)   # longer than the 2s gossip-freshness window:
        return 1          # steady load must not revert to static values

    ref = hold.remote()
    deadline = time.monotonic() + 1.0
    seen_during = None
    while time.monotonic() < deadline:
        per_node = {n["node_id"]: n["available"].get("CPU", 0)
                    for n in backend.head.list_nodes() if n["alive"]}
        if min(per_node.values()) <= 1:
            seen_during = per_node
            break
        time.sleep(0.05)
    assert seen_during is not None, "gossiped availability never dropped"
    # steady load past the freshness window: the view must NOT revert
    time.sleep(1.5)
    per_node = {n["node_id"]: n["available"].get("CPU", 0)
                for n in backend.head.list_nodes() if n["alive"]}
    assert min(per_node.values()) <= 1, (
        f"steady load reverted to static availability: {per_node}")
    ray_tpu.get(ref)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        per_node = {n["node_id"]: n["available"].get("CPU", 0)
                    for n in backend.head.list_nodes() if n["alive"]}
        if all(v == 4 for v in per_node.values()):
            break
        time.sleep(0.05)
    assert all(v == 4 for v in per_node.values()), per_node
    assert events and "available" in events[-1]


def test_per_task_borrow_release(daemon_cluster):
    """Refs the owner pins on a worker task's behalf (nested put) release
    when THAT task finishes — a long-lived daemon must not pin dead
    tasks' objects (reference: per-task borrows, reference_count.h:73)."""
    rt = daemon_cluster
    from ray_tpu._private.ids import ObjectID

    @ray_tpu.remote
    def put_and_drop():
        ref = ray_tpu.put(np.arange(1000))
        return ref.id.hex()      # the hex only: no live ref escapes

    @ray_tpu.remote
    def put_and_return():
        return ray_tpu.put(np.arange(1000))

    # dropped borrow: freed once the task is done (the dropped handle
    # can sit in a reply-closure cycle, so nudge the cyclic collector)
    import gc
    oid_hex = ray_tpu.get(put_and_drop.remote())
    deadline = time.monotonic() + 5.0
    oid = ObjectID.from_hex(oid_hex)
    while time.monotonic() < deadline and rt.refcounter.ref_count(oid):
        gc.collect()
        time.sleep(0.05)
    assert rt.refcounter.ref_count(oid) == 0
    svc = rt.cluster_backend.owner_service
    assert svc.holder.num_keys() == 0, "holder leaked task keys"

    # returned borrow: containment in the result keeps it alive
    inner = ray_tpu.get(put_and_return.remote())
    assert list(ray_tpu.get(inner)[:3]) == [0, 1, 2]


def test_actor_borrow_released_on_death(daemon_cluster):
    """Actor-lifetime borrows persist across its tasks, then release on
    actor death."""
    rt = daemon_cluster
    from ray_tpu._private.ids import ObjectID

    @ray_tpu.remote
    class Holder:
        def make(self):
            self.ref = ray_tpu.put(np.arange(500))
            return self.ref.id.hex()

        def read(self):
            return int(ray_tpu.get(self.ref)[1])

    h = Holder.remote()
    oid = ObjectID.from_hex(ray_tpu.get(h.make.remote()))
    assert ray_tpu.get(h.read.remote()) == 1   # alive across actor tasks
    assert rt.refcounter.ref_count(oid) > 0
    ray_tpu.kill(h)
    import gc
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and rt.refcounter.ref_count(oid):
        gc.collect()
        time.sleep(0.05)
    assert rt.refcounter.ref_count(oid) == 0


def test_head_task_event_store(daemon_cluster):
    """Task state transitions buffer at the HEAD (reference:
    gcs_task_manager.h:94) so list/timeline queries outlive the driver's
    in-process buffer."""
    rt = daemon_cluster
    backend = rt.cluster_backend

    @ray_tpu.remote
    def marked():
        return 1

    ray_tpu.get([marked.remote() for _ in range(5)])
    backend._flush_task_events()
    # wipe the driver-side buffer: reads below must come from the head
    rt.task_events.clear()
    events = backend.head.task_events_get()
    finished = [e for e in events
                if e["event"] == "FINISHED" and "marked" in e["name"]]
    assert len(finished) == 5, events
    assert all(e["job_id"] == rt.job_id.hex() for e in finished)
    # exact-name server-side filter round trip
    exact = backend.head.task_events_get(name=finished[0]["name"])
    assert len([e for e in exact if e["event"] == "FINISHED"]) == 5
    # job filter excludes other jobs
    assert backend.head.task_events_get(job_id="deadbeef") == []


def test_post_mortem_state_from_head(daemon_cluster):
    """list_tasks_from_head / timeline_from_head answer from the head
    store alone — the post-driver-exit introspection path."""
    rt = daemon_cluster
    backend = rt.cluster_backend

    @ray_tpu.remote
    def traced():
        return 1

    ray_tpu.get([traced.remote() for _ in range(3)])
    backend._flush_task_events()
    addr = f"{backend.head.addr[0]}:{backend.head.addr[1]}"
    from ray_tpu.util.state.api import (list_tasks_from_head,
                                        timeline_from_head)
    rows = list_tasks_from_head(addr)
    done = [r for r in rows
            if "traced" in r["name"] and r["state"] == "FINISHED"]
    assert len(done) == 3
    trace = timeline_from_head(addr)
    assert isinstance(trace, list)


def test_peer_resource_gossip(daemon_cluster):
    """Daemon-to-daemon anti-entropy (reference: ray_syncer.h:83 bidi
    gossip): each daemon's view converges to contain EVERY node's load
    entry via peer exchange, and the head's membership view gains
    gossip_load entries pushed by ~one node per interval."""
    rt = daemon_cluster
    handles = _daemon_handles(rt)
    all_ids = {h.node_id.hex() for h in handles}
    deadline = time.monotonic() + 15
    converged = False
    while time.monotonic() < deadline and not converged:
        views = [set(h.client.call("syncer_view")["view"])
                 for h in handles]
        converged = all(all_ids <= v for v in views)
        if not converged:
            time.sleep(0.2)
    assert converged, f"gossip never converged: {views}"
    # entries carry real load fields
    view = handles[0].client.call("syncer_view")["view"]
    entry = view[handles[1].node_id.hex()]
    assert {"running", "store_used", "fast_queued"} <= set(entry["load"])
    # the head picked up gossip entries without per-node reports
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        nodes = rt.cluster_backend.head.list_nodes()
        if any("gossip_load" in n for n in nodes):
            break
        time.sleep(0.2)
    assert any("gossip_load" in n for n in nodes), nodes


def test_per_node_agent_endpoints(daemon_cluster):
    """Each daemon serves its own observability HTTP endpoint
    (reference: dashboard/agent.py per-node agent): /api/stats,
    /api/profile/cpu (stack-sample flamegraph data), /metrics."""
    import json as _json
    import urllib.request

    rt = daemon_cluster
    for h in _daemon_handles(rt):
        port = h.client.call("daemon_stats")["agent_port"]
        assert port, "agent not started"
        base = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(f"{base}/api/stats",
                                    timeout=30) as r:
            stats = _json.loads(r.read())
        assert stats["node_id"] == h.node_id.hex()
        assert stats["pid"] == h.proc.pid
        with urllib.request.urlopen(
                f"{base}/api/profile/cpu?duration=0.3",
                timeout=30) as r:
            prof = _json.loads(r.read())
        assert "collapsed" in prof and prof["samples"] > 0
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            assert r.status == 200


def test_autoscaler_provisions_real_daemon_process(daemon_cluster):
    """ProcessHostProvider end to end: unmet demand drives the
    reconciler to SPAWN a real node-daemon OS process which registers
    at the head and becomes schedulable; idle drain terminates it
    (reference: autoscaler node providers actually creating hosts)."""
    import time as _t

    from ray_tpu.autoscaler_v2 import (InstanceStatus,
                                       ProcessHostProvider, Reconciler)
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    rt = daemon_cluster
    before = {n.node_id for n in rt.alive_nodes()}
    provider = ProcessHostProvider(rt)
    rec = Reconciler(rt, provider, idle_timeout_s=0.5)

    # 2 daemons x CPU:4 fully... demand a CPU:16 host (cpu-host type)
    pg = placement_group([{"CPU": 16}], strategy="PACK")
    assert not pg.wait(0.5)
    deadline = _t.monotonic() + 60
    while _t.monotonic() < deadline:
        rec.reconcile()
        if pg.wait(0.5):
            break
    assert pg.wait(5), "real daemon never provisioned"
    rec.reconcile()   # promote ALLOCATED -> RAY_RUNNING post-join
    new_nodes = {n.node_id for n in rt.alive_nodes()} - before
    assert len(new_nodes) == 1
    running = rec.instance_manager.list(InstanceStatus.RAY_RUNNING)
    assert running and running[0].node_type == "cpu-host"

    # a task actually lands on the provisioned daemon process
    @ray_tpu.remote(num_cpus=9)   # only fits the new CPU:16 host
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    assert where.remote() is not None

    remove_placement_group(pg)
    deadline = _t.monotonic() + 30
    while _t.monotonic() < deadline:
        rec.reconcile()
        if rec.instance_manager.list(InstanceStatus.TERMINATED):
            break
        _t.sleep(0.2)
    assert rec.instance_manager.list(InstanceStatus.TERMINATED)
