"""Cluster lifecycle CLI (VERDICT r2 #8; reference: scripts.py:676
`ray start` / stop): stand a cluster up from a shell, join it from two
successive drivers, tear it down."""

import json
import os
import subprocess
import sys
import time

import pytest

import ray_tpu


def _cli(*argv, timeout=60):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", *argv],
        capture_output=True, text=True, timeout=timeout, env=env)


def test_cli_start_join_two_drivers_stop(tmp_path):
    out = _cli("start", "--head", "--num-daemons", "2",
               "--resources", json.dumps({"CPU": 4}))
    assert out.returncode == 0, out.stderr[-2000:]
    address = None
    for line in out.stdout.splitlines():
        if "started at" in line:
            address = line.split("started at")[1].split()[0]
    assert address, out.stdout

    try:
        # cluster-status without a runtime sees both daemons
        st = _cli("cluster-status", "--address", address)
        assert st.returncode == 0, st.stderr[-2000:]
        nodes = json.loads(st.stdout)["nodes"]
        assert len([n for n in nodes if n["alive"]]) == 2

        daemon_pids = set()

        # driver 1 joins, runs work, disconnects
        rt = ray_tpu.init(address=address)
        try:
            handles = list(rt.cluster_backend.daemons.values())
            assert len(handles) == 2
            assert all(h.proc is None for h in handles)  # not ours

            @ray_tpu.remote
            def who():
                return os.getpid()

            pids = set(ray_tpu.get([who.remote() for _ in range(4)]))
            assert os.getpid() not in pids
            daemon_pids = {
                h.client.call("daemon_ping")["pid"] for h in handles}
        finally:
            ray_tpu.shutdown()

        # daemons survived driver 1's exit (persist mode)
        time.sleep(1.0)
        st = _cli("cluster-status", "--address", address)
        alive = [n for n in json.loads(st.stdout)["nodes"] if n["alive"]]
        assert len(alive) == 2, "daemons died with the first driver"

        # driver 2 joins the SAME daemons and runs work
        rt = ray_tpu.init(address=address)
        try:
            handles = list(rt.cluster_backend.daemons.values())
            pids2 = {h.client.call("daemon_ping")["pid"]
                     for h in handles}
            assert pids2 == daemon_pids  # same processes, not respawns

            @ray_tpu.remote
            class Counter:
                def __init__(self):
                    self.n = 0

                def bump(self):
                    self.n += 1
                    return self.n

            c = Counter.remote()
            assert ray_tpu.get(c.bump.remote()) == 1
            assert ray_tpu.get(c.bump.remote()) == 2
        finally:
            ray_tpu.shutdown()
    finally:
        stop = _cli("stop", "--address", address)
    assert stop.returncode == 0, stop.stderr[-2000:]
    # daemons + head actually gone
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        st = _cli("cluster-status", "--address", address)
        if st.returncode != 0:
            break
        time.sleep(0.3)
    assert st.returncode != 0 or not [
        n for n in json.loads(st.stdout)["nodes"] if n["alive"]]
