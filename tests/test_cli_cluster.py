"""Cluster lifecycle CLI (VERDICT r2 #8; reference: scripts.py:676
`ray start` / stop): stand a cluster up from a shell, join it from two
successive drivers, tear it down."""

import json
import os
import subprocess
import sys
import time

import pytest

import ray_tpu


def _cli(*argv, timeout=60):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", *argv],
        capture_output=True, text=True, timeout=timeout, env=env)


def test_cli_start_join_two_drivers_stop(tmp_path):
    out = _cli("start", "--head", "--num-daemons", "2",
               "--resources", json.dumps({"CPU": 4}))
    assert out.returncode == 0, out.stderr[-2000:]
    address = None
    for line in out.stdout.splitlines():
        if "started at" in line:
            address = line.split("started at")[1].split()[0]
    assert address, out.stdout

    try:
        # cluster-status without a runtime sees both daemons
        st = _cli("cluster-status", "--address", address)
        assert st.returncode == 0, st.stderr[-2000:]
        nodes = json.loads(st.stdout)["nodes"]
        assert len([n for n in nodes if n["alive"]]) == 2

        daemon_pids = set()

        # driver 1 joins, runs work, disconnects
        rt = ray_tpu.init(address=address)
        try:
            handles = list(rt.cluster_backend.daemons.values())
            assert len(handles) == 2
            assert all(h.proc is None for h in handles)  # not ours

            @ray_tpu.remote
            def who():
                return os.getpid()

            pids = set(ray_tpu.get([who.remote() for _ in range(4)]))
            assert os.getpid() not in pids
            daemon_pids = {
                h.client.call("daemon_ping")["pid"] for h in handles}
        finally:
            ray_tpu.shutdown()

        # daemons survived driver 1's exit (persist mode)
        time.sleep(1.0)
        st = _cli("cluster-status", "--address", address)
        alive = [n for n in json.loads(st.stdout)["nodes"] if n["alive"]]
        assert len(alive) == 2, "daemons died with the first driver"

        # driver 2 joins the SAME daemons and runs work
        rt = ray_tpu.init(address=address)
        try:
            handles = list(rt.cluster_backend.daemons.values())
            pids2 = {h.client.call("daemon_ping")["pid"]
                     for h in handles}
            assert pids2 == daemon_pids  # same processes, not respawns

            @ray_tpu.remote
            class Counter:
                def __init__(self):
                    self.n = 0

                def bump(self):
                    self.n += 1
                    return self.n

            c = Counter.remote()
            assert ray_tpu.get(c.bump.remote()) == 1
            assert ray_tpu.get(c.bump.remote()) == 2
        finally:
            ray_tpu.shutdown()
    finally:
        stop = _cli("stop", "--address", address)
    assert stop.returncode == 0, stop.stderr[-2000:]
    # daemons + head actually gone
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        st = _cli("cluster-status", "--address", address)
        if st.returncode != 0:
            break
        time.sleep(0.3)
    assert st.returncode != 0 or not [
        n for n in json.loads(st.stdout)["nodes"] if n["alive"]]


def test_ray_tpu_up_down_subprocess_provider(tmp_path):
    """`ray-tpu up` with the subprocess provider creates a REAL head +
    worker-daemon cluster a driver can join; `down` terminates it
    (reference: `ray up` over autoscaler commands + NodeUpdater)."""
    import ray_tpu
    from ray_tpu import cluster_launcher as cl

    config = tmp_path / "cluster.yaml"
    config.write_text(
        "cluster_name: up-test\n"
        "provider:\n  type: subprocess\n"
        "head:\n  resources: {CPU: 2}\n"
        "worker:\n  resources: {CPU: 2}\n  count: 2\n")
    state = cl.up(str(config))
    try:
        assert cl.wait_for_nodes(state["address"], 2, timeout=60)
        rt = ray_tpu.init(address=state["address"])

        @ray_tpu.remote
        def pid():
            import os
            return os.getpid()

        pids = set(ray_tpu.get([pid.remote() for _ in range(4)],
                               timeout=120))
        assert pids and all(p != __import__("os").getpid()
                            for p in pids)
        ray_tpu.shutdown()
        # idempotent: a second `up` adds nothing
        state2 = cl.up(str(config))
        assert sum(1 for n in state2["nodes"]
                   if n["kind"] == "worker") == 2
    finally:
        n = cl.down(str(config))
    assert n == 3    # head + 2 workers


def test_ssh_provider_command_shape(tmp_path):
    """SshProvider builds correct bootstrap command lines (the
    NodeUpdater contract); run=False returns without executing."""
    from ray_tpu.cluster_launcher import SshProvider

    p = SshProvider(user="tpu", hosts=["h1", "h2"], key="/k",
                    repo="/srv/ray_tpu", run=False)
    head = p.create_head({"resources": {"CPU": 4}})
    assert head["address"] == "h1:6379"
    assert head["command"][:3] == ["ssh", "-o", "StrictHostKeyChecking=no"]
    assert "tpu@h1" in head["command"]
    w1 = p.create_worker("h1:6379", {"resources": {"CPU": 4, "TPU": 4}})
    w2 = p.create_worker("h1:6379", {"resources": {"CPU": 4}})
    assert w1["host"] == "h1" and w2["host"] == "h2"  # round robin
    remote = w1["command"][-1]
    assert "--head h1:6379" in remote
    assert "--host 0.0.0.0" in remote
    assert '"TPU": 4' in remote


def test_ray_tpu_attach_runs_command_against_cluster(tmp_path):
    """`ray-tpu attach <cmd>` exports RAY_TPU_ADDRESS so a bare
    ray_tpu.init() inside the command joins the running cluster
    (reference: `ray attach` + RAY_ADDRESS)."""
    import subprocess
    import sys as _sys

    from ray_tpu import cluster_launcher as cl

    config = tmp_path / "cluster.yaml"
    config.write_text(
        "cluster_name: attach-test\n"
        "provider:\n  type: subprocess\n"
        "head:\n  resources: {CPU: 2}\n"
        "worker:\n  resources: {CPU: 2}\n  count: 1\n")
    state = cl.up(str(config))
    try:
        assert cl.wait_for_nodes(state["address"], 1, timeout=60)
        repo = __import__("os").path.dirname(__import__("os").path.dirname(
            __import__("os").path.abspath(__file__)))
        script = ("import ray_tpu; rt = ray_tpu.init(); "
                  "print('NODES', len(rt.alive_nodes())); "
                  "ray_tpu.shutdown()")
        env = dict(__import__("os").environ)
        env["JAX_PLATFORMS"] = "cpu"     # the attached child must not
        env.pop("PALLAS_AXON_POOL_IPS", None)   # grab the accelerator
        out = subprocess.run(
            [_sys.executable, "-m", "ray_tpu.scripts.cli", "attach",
             "--cluster", state["address"], "--",
             _sys.executable, "-c", script],
            capture_output=True, text=True, timeout=120,
            cwd=repo, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "NODES 1" in out.stdout, out.stdout
    finally:
        cl.down(str(config))
