"""Control-plane batching & pipelined dispatch.

Covers the batching seams added by the submit coalescer
(``push_task_batch``), the zero-ref free buffer, the shared wire
helpers, and the pick_node feasibility cache:

- batched submit is semantically transparent (results, streams,
  multi-return, errors identical to the per-task protocol);
- a dropped/errored batch flush (``batch.submit_flush`` failpoint)
  retries idempotently — no double execution, per-actor ordering
  preserved;
- the free buffer coalesces, retries on ``batch.free_flush`` faults,
  and flushes synchronously (shutdown/drain contract);
- the feasibility cache invalidates on node add / remove / drain.
"""

import time

import pytest

import ray_tpu
from ray_tpu._private import failpoints as fp
from ray_tpu._private.ids import TaskID
from ray_tpu._private.task_spec import TaskKind, TaskSpec


@pytest.fixture(autouse=True)
def _reset_failpoints():
    yield
    fp.reset()


@pytest.fixture
def daemon_cluster():
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


# Two returns force the classic wire path (the native fast lane only
# carries single-return plain tasks), which is exactly the path the
# submit coalescer batches.
@ray_tpu.remote(num_returns=2)
def pair(x):
    return x, x + 1


# ---------------------------------------------------------------------------
# batched submit: transparency
# ---------------------------------------------------------------------------

def test_batched_submit_transparent():
    """Every classic-path submission rides push_task_batch frames and
    completes with identical semantics."""
    ray_tpu.init(num_nodes=2, resources={"CPU": 4}, cluster="daemons",
                 # generous linger: concurrent submissions coalesce
                 # deterministically even on a loaded 2-core box
                 _system_config={"submit_linger_us": 5000})
    try:
        fp.configure("batch.submit_flush", "delay", 0)   # pure observer
        refs = [pair.remote(i) for i in range(40)]
        flat = [r for ab in refs for r in ab]
        out = ray_tpu.get(flat)
        assert out == [v for i in range(40) for v in (i, i + 1)]
        log = fp.hit_log("batch.submit_flush")
        assert log, "no batch flush fired: coalescer not engaged"
        assert sum(e["n"] for e in log) == 40   # all rode the coalescer
        # at least one frame actually coalesced multiple tasks
        assert max(e["n"] for e in log) > 1
    finally:
        ray_tpu.shutdown()


def test_batched_submit_stream_and_error(daemon_cluster):
    @ray_tpu.remote
    def gen(n):
        for i in range(n):
            yield i

    @ray_tpu.remote(num_returns=2, max_retries=0)
    def boom():
        raise ValueError("nope")

    assert [ray_tpu.get(r) for r in gen.remote(4)] == [0, 1, 2, 3]
    a, _b = boom.remote()
    with pytest.raises(ValueError, match="nope"):
        ray_tpu.get(a)


def test_batching_can_be_disabled():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons",
                      _system_config={"submit_batch": False})
    try:
        fp.configure("batch.submit_flush", "delay", 0)
        refs = pair.remote(7)
        assert ray_tpu.get(list(refs)) == [7, 8]
        assert fp.hit_count("batch.submit_flush") == 0
        for handle in rt.cluster_backend.daemons.values():
            assert handle._submit_coalescer() is None
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# batched submit: fault injection / idempotency
# ---------------------------------------------------------------------------

def test_dropped_batch_flush_retries_exactly_once(daemon_cluster,
                                                  tmp_path):
    """Every second flush attempt is 'lost in transit'; the coalescer
    resends and the daemon dedupes by task id — each task body runs
    exactly once."""
    marker = tmp_path / "runs.txt"

    @ray_tpu.remote(num_returns=2)
    def record(i, path):
        with open(path, "a") as fh:
            fh.write(f"{i}\n")
        return i, -i

    fp.configure("batch.submit_flush", "drop", every=2)
    refs = [record.remote(i, str(marker)) for i in range(30)]
    out = ray_tpu.get([r for ab in refs for r in ab])
    assert out == [v for i in range(30) for v in (i, -i)]
    assert fp.fire_count("batch.submit_flush") > 0, "no drop injected"
    lines = sorted(int(x) for x in marker.read_text().split())
    assert lines == list(range(30))     # exactly once each


def test_errored_batch_flush_retries(daemon_cluster):
    fp.configure("batch.submit_flush", "error", every=3)
    refs = [pair.remote(i) for i in range(12)]
    assert ray_tpu.get([a for a, _ in (r for r in refs)]) == list(range(12))
    assert fp.fire_count("batch.submit_flush") > 0


def test_batched_retry_reexecutes_not_replays(tmp_path):
    """A task RETRY reuses the task id; the daemon's duplicate-frame
    dedupe must key on (task, attempt) — replaying the first attempt's
    recorded 'crashed' outcome would burn every retry without ever
    re-running the body."""
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons")
    try:
        marker = tmp_path / "attempts.txt"

        @ray_tpu.remote(num_returns=2, max_retries=3)
        def crash_once(path):
            import os
            with open(path, "a") as fh:
                fh.write("x")
            if len(open(path).read()) == 1:
                os._exit(1)     # worker crash on the FIRST attempt only
            return "ok", "ok2"

        a, b = crash_once.remote(str(marker))
        assert ray_tpu.get([a, b], timeout=60) == ["ok", "ok2"]
        # first attempt crashed, retry actually EXECUTED (two runs)
        assert marker.read_text() == "xx"
        assert rt.stats["tasks_retried"] >= 1
    finally:
        ray_tpu.shutdown()


def test_sub_batch_frees_flush_within_bound(daemon_cluster):
    """A trickle of frees far below free_batch_max still leaves within
    the free_flush_ms bound — the flusher must wake on every append,
    not only on a full batch."""
    rt = daemon_cluster
    handle = next(iter(rt.cluster_backend.daemons.values()))
    for round_no in range(2):       # 2nd round hits the idle-parked loop
        key = b"tst:trickle" + bytes([round_no])
        handle.put_object_blob(key, b"w" * 2048)
        before = _store_used(handle)
        handle.queue_free(key)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if _store_used(handle) < before:
                break
            time.sleep(0.05)
        assert _store_used(handle) < before, (
            f"round {round_no}: single queued free never flushed")


def test_actor_ordering_preserved_under_batch_faults(daemon_cluster):
    """Actor calls keep strict submission order while the batched plain
    task path is dropping/retrying flushes around them."""
    @ray_tpu.remote
    class Log:
        def __init__(self):
            self.seen = []

        def add(self, i):
            self.seen.append(i)
            return i

        def all(self):
            return list(self.seen)

    fp.configure("batch.submit_flush", "drop", every=2)
    log = Log.remote()
    noise = [pair.remote(i) for i in range(10)]
    calls = [log.add.remote(i) for i in range(25)]
    ray_tpu.get(calls)
    ray_tpu.get([r for ab in noise for r in ab])
    assert ray_tpu.get(log.all.remote()) == list(range(25))


# ---------------------------------------------------------------------------
# coalesced frees
# ---------------------------------------------------------------------------

def _store_used(handle):
    return handle.client.call("daemon_stats", timeout=10.0)["store_used"]


def test_free_buffer_coalesces_and_flushes(daemon_cluster):
    rt = daemon_cluster
    handle = next(iter(rt.cluster_backend.daemons.values()))
    fp.configure("batch.free_flush", "delay", 0)     # observer
    keys = []
    for i in range(8):
        key = b"tst:" + bytes([i]) * 8
        handle.put_object_blob(key, b"x" * 4096)
        keys.append(key)
    before = _store_used(handle)
    assert before >= 8 * 4096
    for key in keys:
        handle.queue_free(key)
    handle.flush_frees()        # synchronous drain (shutdown contract)
    assert _store_used(handle) < before
    log = fp.hit_log("batch.free_flush")
    assert log and sum(e["n"] for e in log) == 8
    # size-bounded coalescing: 8 queued frees left in ≤ a few frames,
    # not one RPC per oid
    assert len(log) < 8


def test_free_flush_fault_retries_idempotently(daemon_cluster):
    rt = daemon_cluster
    handle = next(iter(rt.cluster_backend.daemons.values()))
    key = b"tst:retry"
    handle.put_object_blob(key, b"y" * 4096)
    before = _store_used(handle)
    fp.configure("batch.free_flush", "error", max_fires=1)
    handle.queue_free(key)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if _store_used(handle) < before:
            break
        time.sleep(0.05)
    assert _store_used(handle) < before, "free lost to injected fault"
    assert fp.fire_count("batch.free_flush") == 1


def test_zero_ref_frees_are_batched(daemon_cluster):
    """End to end: dropping many result refs coalesces their frees
    instead of firing one single-oid RPC per object."""
    rt = daemon_cluster

    @ray_tpu.remote(num_returns=2)
    def big(i):
        return b"z" * 200_000, i      # > inline: lives in daemon store

    fp.configure("batch.free_flush", "delay", 0)
    refs = [big.remote(i) for i in range(10)]
    ray_tpu.get([b for _a, b in (r for r in refs)])
    del refs
    import gc
    gc.collect()
    for handle in rt.cluster_backend.daemons.values():
        handle.flush_frees()
    log = fp.hit_log("batch.free_flush")
    freed = sum(e["n"] for e in log)
    assert freed >= 10
    assert len(log) < freed     # coalesced: fewer frames than oids


# ---------------------------------------------------------------------------
# pick_node feasibility cache
# ---------------------------------------------------------------------------

def _spec(resources):
    return TaskSpec(task_id=TaskID.from_random(), kind=TaskKind.NORMAL,
                    name="t", func=None, resources=resources)


def test_feasibility_cache_hit_same_shape():
    rt = ray_tpu.init(num_nodes=3, resources={"CPU": 4})
    sched = rt.scheduler
    nodes = rt.nodes()
    sched.pick_node(_spec({"CPU": 1}), nodes)
    key = (("CPU", 1.0),)
    assert key in sched._feas_cache
    assert len(sched._feas_cache[key]) == 3
    # identical specs in a burst reuse the cached candidate set
    epoch_before = sched._feas_epoch
    for _ in range(10):
        sched.pick_node(_spec({"CPU": 1}), nodes)
    assert sched._feas_epoch == epoch_before


def test_feasibility_cache_drain_invalidation():
    """DRAINING nodes leave the cached candidate set immediately
    (regression vs PR 2 drain semantics)."""
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4})
    sched = rt.scheduler
    nodes = rt.nodes()
    for _ in range(5):
        sched.pick_node(_spec({"CPU": 1}), nodes)
    victim = nodes[0]
    rt.begin_node_drain(victim, deadline_s=30.0, reason="test")
    for _ in range(20):
        picked = sched.pick_node(_spec({"CPU": 1}), nodes)
        assert picked.node_id != victim.node_id


def test_feasibility_cache_add_remove_invalidation():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2})
    sched = rt.scheduler
    from ray_tpu._private.scheduler import SchedulingError
    with pytest.raises(SchedulingError):
        sched.pick_node(_spec({"CPU": 8}), rt.nodes())
    # negative result is cached for the shape...
    with pytest.raises(SchedulingError):
        sched.pick_node(_spec({"CPU": 8}), rt.nodes())
    # ...until membership changes: an added node invalidates it
    big = rt.add_node({"CPU": 16})
    assert sched.pick_node(
        _spec({"CPU": 8}), rt.nodes()).node_id == big.node_id
    rt.remove_node(big)
    with pytest.raises(SchedulingError):
        sched.pick_node(_spec({"CPU": 8}), rt.nodes())


def test_pg_capacity_change_invalidates_cache():
    """Placement-group bundle capacity rides the same epoch: add_total
    must invalidate cached infeasibility."""
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2})
    sched = rt.scheduler
    from ray_tpu._private.scheduler import SchedulingError
    with pytest.raises(SchedulingError):
        sched.pick_node(_spec({"widget": 1}), rt.nodes())
    rt.nodes()[0].ledger.add_total({"widget": 2})
    assert sched.pick_node(_spec({"widget": 1}), rt.nodes()) is not None


# ---------------------------------------------------------------------------
# ledger batch admission + shared wire helpers
# ---------------------------------------------------------------------------

def test_try_acquire_many():
    from ray_tpu._private.node import ResourceLedger
    led = ResourceLedger({"CPU": 4, "TPU": 2})
    assert led.try_acquire_many({"CPU": 1}, 10) == 4
    assert led.try_acquire_many({"CPU": 1}, 10) == 0
    led.release({"CPU": 4})
    assert led.try_acquire_many({"CPU": 2, "TPU": 1}, 5) == 2
    assert led.available() == {"CPU": 0.0, "TPU": 0.0}
    led.release({"CPU": 4, "TPU": 2})
    assert led.try_acquire_many({}, 7) == 7     # zero-demand shape
    assert led.try_acquire_many({"CPU": 0.5}, 3) == 3
    assert led.available()["CPU"] == pytest.approx(2.5)


# ---------------------------------------------------------------------------
# drain-side result pipeline (batched completion delivery)
# ---------------------------------------------------------------------------

class _FakeConn:
    """Stand-in for a daemon Connection on the reply pump: records every
    pushed frame; hashable (dict key in the pump buffer)."""

    def __init__(self):
        self.frames = []
        self.closed = False

    def push(self, method, **kw):
        assert method == "task_batch_done"
        self.frames.append(kw["outcomes"])


def test_result_pump_drop_requeues_and_resends():
    """batch.result_flush drop arm: a lost task_batch_done frame's
    entries requeue in order and leave on the next pump pass — nothing
    is dropped, nothing is duplicated."""
    from ray_tpu._private.daemon import _BatchReplyPump
    fp.configure("batch.result_flush", "drop", every=2)
    pump = _BatchReplyPump()
    conn = _FakeConn()
    for i in range(40):
        pump.add(conn, {"task": f"t{i}", "outcome": "ok"})
        if i % 10 == 9:
            time.sleep(0.02)    # several pump passes → several frames
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if sum(len(f) for f in conn.frames) >= 40:
            break
        time.sleep(0.02)
    got = [out["task"] for frame in conn.frames for out in frame]
    assert sorted(got) == sorted(f"t{i}" for i in range(40)), (
        "drop arm lost or duplicated completions")
    assert fp.fire_count("batch.result_flush") > 0, "no drop injected"


def test_result_pump_zero_linger_drops_still_deliver():
    """result_linger_us=0 is documented ('flush immediately'); with the
    drop arm armed the retry path must still converge — the failure
    backoff floor keeps the pump off a busy-spin while resending."""
    from ray_tpu._private.config import apply_system_config
    from ray_tpu._private.daemon import _BatchReplyPump
    apply_system_config({"result_linger_us": 0})
    try:
        fp.configure("batch.result_flush", "drop", every=2)
        pump = _BatchReplyPump()
        assert pump.linger_s == 0.0
        conn = _FakeConn()
        for i in range(10):
            pump.add(conn, {"task": f"z{i}", "outcome": "ok"})
            time.sleep(0.005)   # several passes → several drop fires
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if sum(len(f) for f in conn.frames) >= 10:
                break
            time.sleep(0.02)
        got = [o["task"] for f in conn.frames for o in f]
        assert sorted(got) == sorted(f"z{i}" for i in range(10))
        assert fp.fire_count("batch.result_flush") > 0
    finally:
        apply_system_config(None)


def test_result_pump_error_arm_is_a_loss_not_a_crash():
    """The error arm at the flush seam behaves like a transport loss:
    the pump thread survives and the entries still arrive."""
    from ray_tpu._private.daemon import _BatchReplyPump
    fp.configure("batch.result_flush", "error", max_fires=1)
    pump = _BatchReplyPump()
    conn = _FakeConn()
    pump.add(conn, {"task": "only", "outcome": "ok"})
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if conn.frames:
            break
        time.sleep(0.02)
    assert [o["task"] for f in conn.frames for o in f] == ["only"]
    assert fp.fire_count("batch.result_flush") == 1


def test_ingest_batch_out_of_order_and_duplicates():
    """Driver-side ingest idempotency: a resent frame arriving after
    (or interleaved with) its successor wakes each waiter exactly once;
    duplicates find no slot and are dropped silently."""
    import queue
    import threading
    from types import SimpleNamespace

    from ray_tpu._private.cluster import DaemonHandle
    h = DaemonHandle.__new__(DaemonHandle)
    h._bw_lock = threading.Lock()
    h._slock = threading.Lock()
    h.dead = False
    h._fence_supported = False
    slots = {name: [threading.Event(), None] for name in ("t1", "t2", "t3")}
    h._batch_waiters = dict(slots)
    stream = SimpleNamespace(q=queue.Queue())
    h._streams = {"s1": stream}

    # frame 2 arrives FIRST (out of order), carrying t2+t3 and a stream
    # termination
    h._ingest_batch([{"task": "t2", "outcome": "ok", "v": 2},
                     {"task": "t3", "outcome": "ok", "v": 3},
                     {"task": "s1", "stream": "task_stream_end"}])
    assert slots["t2"][0].is_set() and slots["t2"][1]["v"] == 2
    assert slots["t3"][0].is_set() and slots["t3"][1]["v"] == 3
    assert not slots["t1"][0].is_set()
    assert stream.q.get_nowait()["m"] == "task_stream_end"

    # the resent frame 1 lands late: t1 completes now; the duplicate t2
    # (and a duplicate stream end) are no-ops
    h._ingest_batch([{"task": "t1", "outcome": "ok", "v": 1},
                     {"task": "t2", "outcome": "ok", "v": 99},
                     {"task": "s1", "stream": "task_stream_end"}])
    assert slots["t1"][0].is_set() and slots["t1"][1]["v"] == 1
    assert slots["t2"][1]["v"] == 2, "duplicate overwrote the outcome"
    assert h._batch_waiters == {}


def test_result_flush_drop_end_to_end_exactly_once(tmp_path):
    """Daemon-side batch.result_flush drop arm (env-armed so the
    spawned daemon inherits it): completions are 'lost in transit'
    every other frame, the pump resends, and every task body still runs
    exactly once with every result delivered."""
    import os
    marker = tmp_path / "runs.txt"
    os.environ["RAY_TPU_FAILPOINTS"] = "batch.result_flush=drop:every=2"
    try:
        ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                     cluster="daemons")

        @ray_tpu.remote(num_returns=2)
        def record(i, path):
            with open(path, "a") as fh:
                fh.write(f"{i}\n")
            return i, -i

        refs = [record.remote(i, str(marker)) for i in range(30)]
        out = ray_tpu.get([r for ab in refs for r in ab], timeout=120)
        assert out == [v for i in range(30) for v in (i, -i)]
        lines = sorted(int(x) for x in marker.read_text().split())
        assert lines == list(range(30))     # exactly once each
    finally:
        os.environ.pop("RAY_TPU_FAILPOINTS", None)
        ray_tpu.shutdown()


def test_mixed_classic_and_batched_submitters_one_daemon(daemon_cluster):
    """Both completion entry points share one daemon's dedupe tables
    and one reply pump: a thread of batched (push_task_batch)
    submissions races classic via_pump submissions on the SAME daemon —
    every result lands, none twice."""
    import threading
    rt = daemon_cluster
    handles = list(rt.cluster_backend.daemons.values())
    assert all(h._result_batch for h in handles), (
        "daemon hello did not advertise result_batch")

    batched_out = {}

    def batched_submitter():
        refs = [pair.remote(i) for i in range(20)]
        batched_out["v"] = ray_tpu.get([r for ab in refs for r in ab])

    t = threading.Thread(target=batched_submitter)
    t.start()
    # classic path on the same daemons: flip batch support off so new
    # submissions take the per-task submit_task RPC, whose completion
    # rides the shared task_batch_done pump (via_pump)
    for h in handles:
        h._batch_supported = False
    try:
        refs = [pair.remote(100 + i) for i in range(20)]
        classic = ray_tpu.get([r for ab in refs for r in ab], timeout=60)
    finally:
        for h in handles:
            h._batch_supported = True
    t.join(timeout=60)
    assert not t.is_alive()
    assert classic == [v for i in range(100, 120) for v in (i, i + 1)]
    assert batched_out["v"] == [v for i in range(20) for v in (i, i + 1)]


def test_classic_submit_rides_result_pump():
    """submit_batch=False still gets coalesced completion delivery:
    the daemon acks via_pump submissions immediately and the outcome
    returns on a task_batch_done frame."""
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons",
                      _system_config={"submit_batch": False})
    try:
        handle = next(iter(rt.cluster_backend.daemons.values()))
        assert handle._submit_coalescer() is None   # batching disabled
        assert handle._result_batch                 # pump still on
        refs = [pair.remote(i) for i in range(15)]
        assert ray_tpu.get([r for ab in refs for r in ab],
                           timeout=60) == [
            v for i in range(15) for v in (i, i + 1)]
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# coalesced ledger release (release_many)
# ---------------------------------------------------------------------------

def test_release_many_matches_n_single_releases():
    from ray_tpu._private.node import ResourceLedger
    a = ResourceLedger({"CPU": 8.0, "TPU": 4.0})
    b = ResourceLedger({"CPU": 8.0, "TPU": 4.0})
    for led in (a, b):
        assert led.try_acquire_many({"CPU": 1.0}, 6) == 6
        assert led.try_acquire_many({"CPU": 0.5, "TPU": 2.0}, 2) == 2
    a.release_many([({"CPU": 1.0}, 6), ({"CPU": 0.5, "TPU": 2.0}, 2)])
    for _ in range(6):
        b.release({"CPU": 1.0})
    for _ in range(2):
        b.release({"CPU": 0.5, "TPU": 2.0})
    assert a.available() == b.available()
    assert a.available() == {"CPU": 8.0, "TPU": 4.0}


def test_release_many_clamps_at_total_like_release():
    from ray_tpu._private.node import ResourceLedger
    led = ResourceLedger({"CPU": 2.0})
    # over-release (e.g. a shape released twice across a retry seam)
    # clamps at capacity exactly like the single-release path
    led.release_many([({"CPU": 5.0}, 3)])
    assert led.available() == {"CPU": 2.0}
    single = ResourceLedger({"CPU": 2.0})
    single.release({"CPU": 15.0})
    assert led.available() == single.available()


def test_release_many_wakes_dispatch_waiter():
    """release_many must notify the ledger condition — a dispatch loop
    parked in wait_for_change wakes when a batch of completions lands."""
    import threading
    from ray_tpu._private.node import ResourceLedger
    led = ResourceLedger({"CPU": 1.0})
    assert led.try_acquire_many({"CPU": 1.0}, 1) == 1
    woke = threading.Event()

    def waiter():
        led.wait_for_change(5.0)
        woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    led.release_many([({"CPU": 1.0}, 1)])
    assert woke.wait(2.0), "release_many never notified the condition"
    t.join()


def test_recv_exact_shared_implementation():
    """One recv helper for rpc + fast_lane; recv_into semantics and the
    two-phase large-frame send survive a round trip."""
    import socket
    import threading

    from ray_tpu._private import fast_lane, rpc
    assert fast_lane._recv_exact is rpc.recv_exact

    a, b = socket.socketpair()
    lock = threading.Lock()
    big = b"q" * (rpc.SEND_CONCAT_MAX + 1000)
    sender = threading.Thread(
        target=lambda: (rpc.send_frame_bytes(a, b"small", lock),
                        rpc.send_frame_bytes(a, big, lock)))
    sender.start()
    import struct
    (n1,) = struct.unpack("!I", rpc.recv_exact(b, 4))
    assert bytes(rpc.recv_exact(b, n1)) == b"small"
    (n2,) = struct.unpack("!I", rpc.recv_exact(b, 4))
    assert bytes(rpc.recv_exact(b, n2)) == big
    sender.join()
    a.close()
    with pytest.raises(OSError):    # EOF surfaces as ConnectionError
        rpc.recv_exact(b, 1)
    b.close()
