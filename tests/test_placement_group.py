"""Placement group tests (reference: python/ray/tests/test_placement_group*)."""

import time

import pytest

import ray_tpu
from ray_tpu.util import (placement_group, placement_group_table,
                          remove_placement_group)


@ray_tpu.remote
def where():
    return ray_tpu.get_runtime_context().get_node_id()


def test_pg_create_ready(ray_start_cluster):
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="PACK")
    assert pg.wait(10)
    assert pg.is_ready()
    assert all(n is not None for n in pg.bundle_nodes())


def test_pg_ready_ref(ray_start_cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    got = ray_tpu.get(pg.ready(), timeout=10)
    assert got.is_ready()


def test_strict_pack_same_node(ray_start_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}, {"CPU": 1}],
                         strategy="STRICT_PACK")
    assert pg.wait(10)
    nodes = {n.hex() for n in pg.bundle_nodes()}
    assert len(nodes) == 1


def test_strict_spread_distinct_nodes(ray_start_cluster):
    pg = placement_group([{"CPU": 1}] * 4, strategy="STRICT_SPREAD")
    assert pg.wait(10)
    nodes = {n.hex() for n in pg.bundle_nodes()}
    assert len(nodes) == 4


def test_strict_spread_infeasible_pending(ray_start_cluster):
    # 5 bundles, only 4 nodes -> cannot place.
    pg = placement_group([{"CPU": 1}] * 5, strategy="STRICT_SPREAD")
    assert not pg.wait(1.0)
    assert pg.state in ("PENDING", "RESCHEDULING")


def test_task_into_pg_bundle(ray_start_cluster):
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_SPREAD")
    assert pg.wait(10)
    strat = ray_tpu.PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=1)
    got = ray_tpu.get(where.options(
        scheduling_strategy=strat, num_cpus=1).remote())
    assert got == pg.bundle_nodes()[1].hex()


def test_pg_bundle_capacity_enforced(ray_start_cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(10)
    strat = ray_tpu.PlacementGroupSchedulingStrategy(placement_group=pg)

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=strat)
    def hold():
        time.sleep(0.4)
        return time.monotonic()

    t0 = time.monotonic()
    # Two 1-CPU tasks into a 1-CPU bundle -> must serialize.
    times = ray_tpu.get([hold.remote(), hold.remote()])
    assert max(times) - t0 >= 0.75


def test_pg_task_demand_exceeding_bundle_fails(ray_start_cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(10)
    strat = ray_tpu.PlacementGroupSchedulingStrategy(placement_group=pg)
    with pytest.raises(Exception):
        ray_tpu.get(where.options(scheduling_strategy=strat,
                                  num_cpus=4).remote(), timeout=10)


def test_actor_in_pg(ray_start_cluster):
    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=2, scheduling_strategy=
                    ray_tpu.PlacementGroupSchedulingStrategy(
                        placement_group=pg,
                        placement_group_bundle_index=0))
    class Pinned:
        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = Pinned.remote()
    assert ray_tpu.get(a.node.remote()) == pg.bundle_nodes()[0].hex()


def test_remove_pg_frees_resources(ray_start_cluster):
    rt = ray_start_cluster
    before = ray_tpu.available_resources()["CPU"]
    pg = placement_group([{"CPU": 4}, {"CPU": 4}], strategy="SPREAD")
    assert pg.wait(10)
    during = ray_tpu.available_resources()["CPU"]
    assert during == before - 8
    remove_placement_group(pg)
    time.sleep(0.2)
    after = ray_tpu.available_resources()["CPU"]
    assert after == before


def test_pg_reschedules_after_node_death(ray_start_cluster):
    rt = ray_start_cluster
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_SPREAD")
    assert pg.wait(10)
    victim_id = pg.bundle_nodes()[0]
    victim = rt.get_node(victim_id)
    rt.remove_node(victim)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if pg.is_ready() and all(
                n is not None and n != victim_id
                for n in pg.bundle_nodes()):
            break
        time.sleep(0.1)
    assert pg.is_ready()
    assert victim_id not in pg.bundle_nodes()


def test_pg_table(ray_start_cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK", name="mypg")
    pg.wait(10)
    table = placement_group_table()
    assert pg.id.hex() in table
    assert table[pg.id.hex()]["name"] == "mypg"
    assert table[pg.id.hex()]["state"] == "CREATED"


def test_pending_pg_places_when_resources_free(ray_start_cluster):
    # Fill the cluster, create a PG that can't fit, then free resources.
    @ray_tpu.remote(num_cpus=4)
    def hog():
        time.sleep(1.0)

    hogs = [hog.remote() for _ in range(4)]  # consumes all 16 CPUs
    time.sleep(0.2)
    pg = placement_group([{"CPU": 4}], strategy="PACK")
    assert not pg.wait(0.3)  # can't place yet
    ray_tpu.get(hogs)
    assert pg.wait(10)


def test_pg_task_retry_after_node_death(ray_start_cluster):
    """Retry of a PG task re-matches bundles (scoped-resource regression)."""
    rt = ray_start_cluster
    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.wait(10)
    strat = ray_tpu.PlacementGroupSchedulingStrategy(placement_group=pg)

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=strat, max_retries=3)
    def slow():
        time.sleep(1.0)
        return "ok"

    ref = slow.remote()
    time.sleep(0.3)
    victim = rt.get_node(pg.bundle_nodes()[0])
    rt.remove_node(victim)
    assert ray_tpu.get(ref, timeout=30) == "ok"


def test_capture_child_tasks(ray_start_cluster):
    pg = placement_group([{"CPU": 3}], strategy="PACK")
    assert pg.wait(10)
    strat = ray_tpu.PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_capture_child_tasks=True)

    @ray_tpu.remote(num_cpus=1)
    def child():
        return ray_tpu.get_runtime_context().get_node_id()

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=strat)
    def parent():
        from ray_tpu.util.placement_group import get_current_placement_group
        me = ray_tpu.get_runtime_context().get_node_id()
        kid = ray_tpu.get(child.remote())
        return me, kid, get_current_placement_group() is not None

    me, kid, has_pg = ray_tpu.get(parent.remote())
    assert me == kid  # child captured into the same bundle's node
    assert has_pg


def test_bundle_index_out_of_range(ray_start_cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(10)
    strat = ray_tpu.PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=5)
    with pytest.raises(ValueError):
        ray_tpu.get(where.options(scheduling_strategy=strat).remote(),
                    timeout=10)
