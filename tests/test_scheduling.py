"""Scheduling policy tests (reference: raylet/scheduling policy suite)."""

import time
from collections import Counter as Histogram

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private.scheduler import SchedulingError


@ray_tpu.remote
def where():
    return ray_tpu.get_runtime_context().get_node_id()


def test_spread_strategy(ray_start_cluster):
    refs = [where.options(scheduling_strategy="SPREAD").remote()
            for _ in range(16)]
    hist = Histogram(ray_tpu.get(refs))
    assert len(hist) == 4  # all 4 nodes used
    assert max(hist.values()) <= 6  # roughly even


def test_node_affinity_hard(ray_start_cluster):
    rt = ray_start_cluster
    target = rt.nodes()[2]
    strat = ray_tpu.NodeAffinitySchedulingStrategy(
        node_id=target.node_id.hex(), soft=False)
    got = ray_tpu.get(where.options(scheduling_strategy=strat).remote())
    assert got == target.node_id.hex()


def test_node_affinity_dead_node_fails(ray_start_cluster):
    rt = ray_start_cluster
    victim = rt.nodes()[3]
    rt.remove_node(victim)
    strat = ray_tpu.NodeAffinitySchedulingStrategy(
        node_id=victim.node_id.hex(), soft=False)
    with pytest.raises(SchedulingError):
        ray_tpu.get(where.options(scheduling_strategy=strat).remote(),
                    timeout=10)


def test_node_affinity_soft_falls_back(ray_start_cluster):
    rt = ray_start_cluster
    victim = rt.nodes()[3]
    victim_hex = victim.node_id.hex()
    rt.remove_node(victim)
    strat = ray_tpu.NodeAffinitySchedulingStrategy(node_id=victim_hex,
                                                   soft=True)
    got = ray_tpu.get(where.options(scheduling_strategy=strat).remote(),
                      timeout=10)
    assert got != victim_hex


def test_custom_resources():
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4})
    special = rt.add_node({"CPU": 2, "special": 1.0})

    @ray_tpu.remote(resources={"special": 1})
    def on_special():
        return ray_tpu.get_runtime_context().get_node_id()

    assert ray_tpu.get(on_special.remote()) == special.node_id.hex()


def test_infeasible_task_errors(ray_start_regular):
    @ray_tpu.remote(num_cpus=1000)
    def huge():
        return 1

    with pytest.raises(Exception):
        ray_tpu.get(huge.remote(), timeout=10)


def test_label_scheduling():
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4})
    gpuish = rt.add_node({"CPU": 4}, labels={"tier": "accel"})
    strat = ray_tpu.NodeLabelSchedulingStrategy(hard={"tier": "accel"})
    got = ray_tpu.get(where.options(scheduling_strategy=strat).remote())
    assert got == gpuish.node_id.hex()


def test_resource_queueing(ray_start_regular):
    # 8 CPUs; 4 tasks of 4 CPUs must run in two waves.
    @ray_tpu.remote(num_cpus=4)
    def hold():
        time.sleep(0.3)
        return time.monotonic()

    t0 = time.monotonic()
    times = ray_tpu.get([hold.remote() for _ in range(4)])
    assert max(times) - t0 >= 0.55  # two waves of 0.3s


def test_fractional_resources(ray_start_regular):
    @ray_tpu.remote(num_cpus=0.5)
    def half():
        return 1

    assert sum(ray_tpu.get([half.remote() for _ in range(16)])) == 16


def test_locality_preference(ray_start_cluster):
    rt = ray_start_cluster

    @ray_tpu.remote
    def produce():
        import numpy as np
        return np.ones((800, 800))  # big enough for node store

    @ray_tpu.remote
    def consume(x):
        return ray_tpu.get_runtime_context().get_node_id()

    data = produce.remote()
    ray_tpu.get(data)
    holder = None
    for node in rt.nodes():
        if node.store.contains(data.id):
            holder = node
            break
    assert holder is not None
    # Sequential submissions (idle cluster each time): locality bias wins.
    consumer_nodes = [ray_tpu.get(consume.remote(data)) for _ in range(6)]
    assert Histogram(consumer_nodes)[holder.node_id.hex()] >= 5
