"""Zero-copy object plane: worker shm-arena attach, tiered store,
push-dedup transfer (ray_tpu/objectplane/, docs/object_plane.md).

Covers the plane's contract at three levels:

- native: process-shared slot refcounts block LRU eviction while any
  attached process holds a view; reserve/seal are idempotent;
- store: explicit (host-shm | device-hbm | spilled) tiers with
  occupancy accounting; stale-segment sweep on (re)start;
- cluster e2e (daemons topology): same-node consumers see read-only
  arena-backed views (no pickle on the raw hot path, mutation raises),
  direct puts move only a seal message, classic and attached consumers
  coexist on one daemon, and everything degrades to the per-RPC path
  when the arena is unavailable.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.native_store import available

needs_native = pytest.mark.skipif(
    not available(), reason="native shm store unavailable (no g++)")


# ---------------------------------------------------------------------------
# native: shared-slot ref/release protocol
# ---------------------------------------------------------------------------

@needs_native
def test_ext_ref_blocks_eviction_then_release_frees():
    from ray_tpu.native_store import ShmObjectStore, ShmStoreFull
    store = ShmObjectStore(f"rtpu_tst_ext_{os.getpid()}", 1 << 20)
    try:
        store.put(b"a", b"\x07" * (600 * 1024))
        store.put(b"b", b"\x08" * (300 * 1024))
        # an ATTACHED process's view: ext slot ref taken on its behalf
        off, size, slot = store.get_ext(b"a")
        att = ShmObjectStore.attach(store.name)
        try:
            view = att.view_range(off, size)
            assert bytes(view[:4]) == b"\x07" * 4
            assert store.ext_refs(slot) == 1
            # pressure needing a's bytes: b is evictable, but freeing
            # it cannot make 600 KiB contiguous — and a is PINNED by
            # the attached client's slot ref, so the create must fail
            # instead of unmapping bytes the client still views
            with pytest.raises(ShmStoreFull):
                store.put(b"c", b"\x01" * (600 * 1024))
            assert store.contains(b"a")
            assert bytes(view[:4]) == b"\x07" * 4   # still mapped
            del view
            att.ext_release(slot)
            assert store.ext_refs(slot) == 0
            store.put(b"c", b"\x01" * (600 * 1024))  # now evictable
            assert not store.contains(b"a")
            assert store.contains(b"c")
        finally:
            att.close()
    finally:
        store.close(unlink=True)


@needs_native
def test_deferred_delete_waits_for_ext_release_then_reaps():
    from ray_tpu.native_store import ShmObjectStore
    store = ShmObjectStore(f"rtpu_tst_reap_{os.getpid()}", 1 << 20)
    try:
        store.put(b"x", b"abc" * 1000, pin=True)
        _off, _size, slot = store.get_ext(b"x")
        store.delete(b"x")              # readers outstanding: deferred
        assert store.num_objects() == 1
        assert store.reap() == 0        # still ext-referenced
        store.ext_release(slot)
        assert store.reap() == 3000     # last ref gone: bytes freed
        assert store.num_objects() == 0
    finally:
        store.close(unlink=True)


@needs_native
def test_view_slot_ref_survives_derived_views():
    """Review regression: numpy collapses view base chains, so a slice
    of the reshaped array bases on the frombuffer BASE — the slot ref
    must release only when the base dies (i.e. when NO view of the
    bytes survives), not when the derived array is dropped."""
    import gc

    from ray_tpu.native_store import ShmObjectStore
    from ray_tpu.objectplane.arena import WorkerArena
    store = ShmObjectStore(f"rtpu_tst_deriv_{os.getpid()}", 1 << 20)
    try:
        store.put(b"a", np.arange(1024, dtype=np.float32).tobytes(),
                  pin=True)
        off, size, slot = store.get_ext(b"a")
        wa = WorkerArena(store.name, 0)
        view = wa.view(off, size, slot, dtype="<f4", shape=(1024,))
        sl = view[10:20]
        del view
        gc.collect()
        assert store.ext_refs(slot) == 1    # slice still maps the bytes
        assert float(sl[0]) == 10.0
        del sl
        gc.collect()
        assert store.ext_refs(slot) == 0    # last view gone: released
    finally:
        store.close(unlink=True)


@needs_native
def test_reserve_write_seal_idempotent_via_attach():
    from ray_tpu.native_store import ShmObjectStore
    store = ShmObjectStore(f"rtpu_tst_rsv_{os.getpid()}", 1 << 20)
    try:
        off = store.reserve(b"k", 8)
        assert store.reserve(b"k", 8) == off    # retried reserve
        att = ShmObjectStore.attach(store.name)
        try:
            att.write_range(off, b"12345678")
        finally:
            att.close()
        store.seal(b"k", pin=True)
        store.seal(b"k", pin=True)              # retried seal
        o, s = store.get_ref(b"k")
        assert bytes(store.read_range(o, s)) == b"12345678"
        store.release(b"k")
    finally:
        store.close(unlink=True)


# ---------------------------------------------------------------------------
# stale-segment hygiene (daemon restart)
# ---------------------------------------------------------------------------

@needs_native
def test_object_table_sweeps_stale_segments_of_same_node():
    """A SIGKILL'd daemon leaks its arena in /dev/shm; the successor of
    the SAME node sweeps it before creating a fresh one (and never
    touches other nodes' segments)."""
    from ray_tpu._private.daemon import ObjectTable
    from ray_tpu.native_store import ShmObjectStore
    name = f"rtpu_tstsweep{os.getpid() % 10_000:04d}"
    other = f"rtpu_tstother{os.getpid() % 10_000:04d}"
    # "crashed daemon": segment left behind, name never unlinked
    leaked = ShmObjectStore(name, 1 << 20)
    leaked.put(b"stale", b"old-bytes")
    leaked.close(unlink=False)
    bystander = ShmObjectStore(other, 1 << 20)
    try:
        assert os.path.exists(f"/dev/shm/{name}")
        table = ObjectTable(name, 1 << 20)      # the restarted daemon
        try:
            # fresh arena: the stale object is gone, the segment exists
            assert table._shm is not None
            assert not table._shm.contains(b"stale")
            assert os.path.exists(f"/dev/shm/{name}")
            # the other node's live segment was not swept
            assert os.path.exists(f"/dev/shm/{other}")
        finally:
            table.close()
    finally:
        bystander.close(unlink=True)


def test_sweep_stale_segments_scoped_to_prefix(tmp_path):
    from ray_tpu.objectplane.arena import sweep_stale_segments
    assert sweep_stale_segments("") == []     # no prefix: never sweeps


# ---------------------------------------------------------------------------
# tier model
# ---------------------------------------------------------------------------

def test_local_store_tier_accounting_spill_restore(tmp_path):
    from ray_tpu._private.ids import NodeID, ObjectID
    from ray_tpu._private.object_store import LocalObjectStore
    store = LocalObjectStore(NodeID.from_random(), 4000,
                             spill_dir=str(tmp_path))
    store._native = None    # force the pure-python host tier
    a, b = ObjectID.from_random(), ObjectID.from_random()
    store.put(a, b"x" * 3000, nbytes=3000)
    assert store.tier_bytes().get("host-shm") == 3000
    store.put(b, b"y" * 3000, nbytes=3000)      # pressure: a spills
    tiers = store.tier_bytes()
    assert tiers.get("spilled") == 3000
    assert tiers.get("host-shm") == 3000
    assert store.get(a) == b"x" * 3000          # restore flips it back
    tiers = store.tier_bytes()
    assert tiers.get("spilled", 0) in (0, 3000)  # b may have spilled
    store.delete(a)
    store.delete(b)
    tiers = store.tier_bytes()
    assert tiers.get("host-shm", 0) == 0
    assert tiers.get("spilled", 0) == 0
    store.close()


@needs_native
def test_arena_spill_restore_tier_accounting(tmp_path):
    """Arena spill is a tier transition, not a loss: spilled occupancy
    (spilled_bytes / spill_stats) and the spill/restore byte counters
    move in lockstep with the data, and a restore returns every byte to
    host-shm (docs/object_plane.md "Arena spill")."""
    import uuid

    from ray_tpu._private.daemon import ObjectTable
    from ray_tpu.util.metrics import Counter

    def _total(name):
        return sum(v for _, v in Counter(name, "").samples())

    table = ObjectTable(f"rtpu_sp_{os.getpid()}_{uuid.uuid4().hex[:8]}",
                        1 << 22, sweep=False, spill_dir=str(tmp_path))
    if table._shm is None:
        table.close()
        pytest.skip("arena creation failed on this box")
    try:
        n = 300_000
        spilled0 = _total("ray_tpu_arena_spilled_bytes_total")
        restored0 = _total("ray_tpu_arena_restored_bytes_total")
        table.put(b"obj", b"s" * n)
        used_resident = table.used_bytes()
        assert table.spilled_bytes() == 0

        assert table.spill_to_fraction(0.0) == 1
        assert table.spilled_bytes() == n
        stats = table.spill_stats()
        assert stats["spills"] == 1
        assert stats["spilled_bytes"] == n
        assert stats["spilled_now_count"] == 1
        assert _total("ray_tpu_arena_spilled_bytes_total") - spilled0 == n
        # the arena side actually freed (deferred-delete + reap ran)
        assert table.used_bytes() < used_resident
        # directory answers stay exact while parked on disk
        assert table.contains(b"obj")
        assert table.nbytes_of(b"obj") == n

        assert table.get_blob(b"obj") == b"s" * n       # restores
        assert table.spilled_bytes() == 0
        stats = table.spill_stats()
        assert stats["restores"] == 1
        assert stats["restored_bytes"] == n
        assert stats["spilled_now_count"] == 0
        assert _total("ray_tpu_arena_restored_bytes_total") \
            - restored0 == n
    finally:
        table.close()


# ---------------------------------------------------------------------------
# cluster e2e (daemons topology)
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _reset_failpoints():
    """Env-armed failpoints activate in-process at init; never leak an
    armed registry into later test files."""
    yield
    from ray_tpu._private import failpoints as _fp
    _fp.reset()


@pytest.fixture
def daemon_cluster():
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


@ray_tpu.remote
def _produce(n):
    return np.full(n // 8, 7.0)


@ray_tpu.remote
def _consume(refs):
    got = ray_tpu.get(refs)[0]
    mutated = False
    try:
        got[0] = 1.0
        mutated = True
    except (ValueError, TypeError):
        pass
    from ray_tpu.objectplane.arena import arena_stats
    return {"sum": float(got.sum()), "mutated": mutated,
            "writeable": bool(got.flags.writeable)
            if hasattr(got, "flags") else None,
            "stats": arena_stats()}


@needs_native
def test_same_node_consumer_gets_arena_backed_readonly_view(
        daemon_cluster):
    """The satellite contract: a same-node consumer's buffer is backed
    by the shared arena (zero-copy — the arena's zero_copy_gets counter
    moved in THAT worker), the view is read-only, and mutation raises."""
    r = _produce.remote(1 << 21)
    ray_tpu.get(r)      # result landed (stored in the daemon)
    out = ray_tpu.get(_consume.remote([r]))
    assert out["sum"] == 7.0 * (1 << 18)
    assert out["mutated"] is False
    assert out["writeable"] is False
    assert out["stats"]["attached"] == 1
    assert out["stats"]["zero_copy_gets"] >= 1


@needs_native
def test_worker_direct_put_roundtrip_and_driver_get(daemon_cluster):
    """Worker direct put: payload written in place, only the seal +
    registration messages cross the wire; the driver reads the same
    object back (zero-copy view on this same-host box)."""

    @ray_tpu.remote
    def put_big():
        a = np.arange(512 * 1024, dtype=np.float32)     # 2 MiB
        ref = ray_tpu.put(a)
        from ray_tpu.objectplane.arena import arena_stats
        return ref, arena_stats()

    ref, stats = ray_tpu.get(put_big.remote())
    assert stats["direct_puts"] >= 1
    got = ray_tpu.get(ref)
    assert got.dtype == np.float32 and got.shape == (512 * 1024,)
    assert float(got[12345]) == 12345.0
    assert got.flags.writeable is False     # raw-tier view/frombuffer


@needs_native
def test_multi_return_not_aliased_to_tuple_blob(daemon_cluster):
    """Review regression: a multi-return task's stored blob holds the
    WHOLE tuple; the daemon's oid index must not alias ref0 to it, or
    a same-node consumer would read (r0, r1) as r0's value."""

    @ray_tpu.remote(num_returns=2)
    def duo(n):
        return np.full(n // 8, 1.0), np.full(n // 8, 2.0)

    r0, r1 = duo.remote(1 << 21)
    ray_tpu.get([r0, r1])

    @ray_tpu.remote
    def consume(refs):
        v = ray_tpu.get(refs)[0]
        assert not isinstance(v, tuple), "aliased to the tuple blob"
        return float(v.sum())

    assert ray_tpu.get(consume.remote([r0])) == float(1 << 18)
    assert ray_tpu.get(consume.remote([r1])) == 2.0 * (1 << 18)


@needs_native
def test_mixed_classic_and_attached_consumers_one_daemon(
        daemon_cluster):
    """Classic (plane-disabled) and attached consumers coexist on one
    daemon: both read the same stored object correctly."""

    @ray_tpu.remote
    def consume_classic(refs):
        from ray_tpu.objectplane import arena as _arena
        _arena.set_disabled(True)
        try:
            got = ray_tpu.get(refs)[0]
            return float(got.sum())
        finally:
            _arena.set_disabled(False)

    r = _produce.remote(1 << 21)
    ray_tpu.get(r)
    classic = ray_tpu.get(consume_classic.remote([r]))
    attached = ray_tpu.get(_consume.remote([r]))
    assert classic == attached["sum"] == 7.0 * (1 << 18)


@needs_native
def test_push_object_dedupes_and_lands_copy(daemon_cluster):
    """PushManager contract: a driver-directed push lands the object on
    the peer; a second push of the same object dedupes against the copy
    the destination now holds (directory/receiver probe)."""
    rt = daemon_cluster
    h_a, h_b = list(rt.cluster_backend.daemons.values())
    key = b"put:pushtest-0001"
    h_a.put_object_blob(key, b"z" * (2 << 20))
    out = h_a.push_object(key, h_b.addr)
    assert out["ok"] and not out.get("skipped")
    assert h_b.client.call("object_meta", oid=key)["size"] == 2 << 20
    out2 = h_a.push_object(key, h_b.addr)
    assert out2["ok"] and out2.get("skipped")
    stats = h_a.client.call("daemon_stats")["push_stats"]
    assert stats["pushes_started"] >= 2
    assert stats["pushes_skipped_held"] >= 1
    assert stats["bytes_pushed"] >= 2 << 20


@needs_native
def test_remote_store_direct_put_tiers_and_zero_copy_get(
        daemon_cluster):
    """Driver-side direct put of a large contiguous array: raw tier,
    host-shm occupancy accounted, zero-copy read-only view back."""
    rt = daemon_cluster
    from ray_tpu._private.ids import ObjectID
    node = next(n for n in rt.nodes()
                if getattr(n, "daemon", None) is not None)
    store = node.store
    if not store.daemon.objectplane:
        pytest.skip("daemon has no native arena")
    oid = ObjectID.from_random()
    arr = np.arange(256 * 1024, dtype=np.float32)       # 1 MiB
    before = store.stats["direct_puts"]
    store.put(oid, arr)
    assert store.stats["direct_puts"] == before + 1
    assert store.tier_bytes().get("host-shm", 0) >= arr.nbytes
    got = store.get(oid)
    assert got.flags.writeable is False
    assert (got == arr).all()
    del got
    store.delete(oid)


class _FakeObjects:
    def __init__(self):
        self.stored = {}

    def contains(self, oid):
        return oid in self.stored

    def put(self, oid, blob):
        self.stored[oid] = blob


def test_push_receiver_distinct_range_accounting_and_raw_meta():
    """Review regressions: (1) duplicate/overlapping chunks from two
    concurrent senders must not count toward completion twice (a buffer
    with holes would enter the table); (2) raw-tier (dtype, shape)
    metadata travels with the push so the receiver's oid index serves
    the copy as views, not pickle-lookalike bytes."""
    from ray_tpu.objectplane.push import PushReceiver
    objs = _FakeObjects()
    regs = {}
    rx = PushReceiver(objs, register_oid=lambda ref, key, raw=None:
                      regs.__setitem__(ref, (key, raw)))
    rx.chunk(b"o", 0, 8, b"1234")
    out = rx.chunk(b"o", 0, 8, b"1234")     # duplicate offset
    assert not out.get("have")
    assert b"o" not in objs.stored          # 4+4 dup != complete
    rx.chunk(b"o", 4, 8, b"5678", ref=b"r", raw=("<f4", (2,)))
    assert objs.stored[b"o"] == b"12345678"
    assert regs[b"r"] == (b"o", ("<f4", (2,)))


def test_push_receiver_have_short_circuits():
    from ray_tpu.objectplane.push import PushReceiver
    objs = _FakeObjects()
    objs.stored[b"o"] = b"already"
    rx = PushReceiver(objs)
    assert rx.chunk(b"o", 0, 7, b"already").get("have") is True


def test_push_receiver_interval_merge_handles_misaligned_senders():
    """Review regression: completion is the UNION of covered intervals,
    not a sum of per-offset lengths — two senders with different chunk
    sizes must not 'complete' a buffer that still has a hole."""
    from ray_tpu.objectplane.push import PushReceiver
    objs = _FakeObjects()
    rx = PushReceiver(objs)
    rx.chunk(b"m", 0, 10, b"aaaa")      # covers 0-4
    rx.chunk(b"m", 4, 10, b"bbbb")      # covers 4-8
    rx.chunk(b"m", 0, 10, b"aaaaaa")    # covers 0-6 (overlap): union
    #                                     is still only 0-8, but the
    #                                     naive sum would be 14 >= 10
    assert b"m" not in objs.stored      # bytes 8-10 never arrived
    rx.chunk(b"m", 8, 10, b"cc")
    assert objs.stored[b"m"] == b"aaaaaabbcc"


def test_push_receiver_sweep_expires_abandoned_partials():
    from ray_tpu.objectplane.push import PushReceiver
    objs = _FakeObjects()
    rx = PushReceiver(objs)
    rx.chunk(b"s", 0, 100, b"x")        # partial: sender then "dies"
    assert rx.sweep(max_age_s=-1.0) == 1
    assert rx.stats["pending_expired"] == 1
    rx.chunk(b"s", 0, 4, b"full")       # a fresh transfer still works
    assert objs.stored[b"s"] == b"full"


# ---------------------------------------------------------------------------
# graceful degradation
# ---------------------------------------------------------------------------

def test_plane_disabled_falls_back_to_rpc_path():
    """objectplane_attach=False (and equally: no native build) keeps
    every object op on the classic per-RPC path — no-compiler boxes
    stay green."""
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      cluster="daemons",
                      _system_config={"objectplane_attach": False})
    try:
        for h in rt.cluster_backend.daemons.values():
            assert h.objectplane is False

        @ray_tpu.remote
        def round_trip(n):
            a = np.ones(n // 4, dtype=np.float32)
            ref = ray_tpu.put(a)
            return float(ray_tpu.get([ref])[0].sum())

        assert ray_tpu.get(round_trip.remote(1 << 20)) == float(1 << 18)
    finally:
        ray_tpu.shutdown()


def test_shm_attach_failpoint_drops_to_rpc_fallback(monkeypatch):
    """shm.attach drop arm: the worker's mapping fails -> the plane
    disables for that process and object ops fall back per-RPC; the
    task still succeeds (never task failure)."""
    monkeypatch.setenv("RAY_TPU_FAILPOINTS", "shm.attach=drop")
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      cluster="daemons")
    try:
        @ray_tpu.remote
        def round_trip(n):
            a = np.ones(n // 4, dtype=np.float32)
            ref = ray_tpu.put(a)
            total = float(ray_tpu.get([ref])[0].sum())
            from ray_tpu.objectplane.arena import arena_stats
            return total, arena_stats()

        total, stats = ray_tpu.get(round_trip.remote(1 << 20))
        assert total == float(1 << 18)
        if stats:   # arena configured: the attach must have failed
            assert stats["attached"] == 0
            assert stats["attach_failures"] >= 1
            assert stats["zero_copy_gets"] == 0
    finally:
        ray_tpu.shutdown()


@needs_native
def test_shm_seal_drop_retries_idempotently(monkeypatch):
    """shm.seal drop arm: the first seal message is lost; the writer
    resends and the retried seal lands the SAME entry (exactly-once
    object, correct bytes)."""
    monkeypatch.setenv("RAY_TPU_FAILPOINTS", "shm.seal=drop:max=1")
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      cluster="daemons")
    try:
        @ray_tpu.remote
        def put_and_read(n):
            a = np.arange(n // 4, dtype=np.float32)
            ref = ray_tpu.put(a)
            got = ray_tpu.get([ref])[0]
            from ray_tpu.objectplane.arena import arena_stats
            return float(got[n // 8]), arena_stats()

        v, stats = ray_tpu.get(put_and_read.remote(1 << 20))
        assert v == float(1 << 17)
        if stats.get("attached"):
            assert stats["direct_puts"] >= 1    # direct path survived
    finally:
        ray_tpu.shutdown()
