"""Core task/object API tests (reference: python/ray/tests/test_basic*.py)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc


def test_put_get(ray_start_regular):
    ref = ray_tpu.put(42)
    assert ray_tpu.get(ref) == 42
    ref2 = ray_tpu.put({"a": [1, 2, 3]})
    assert ray_tpu.get(ref2) == {"a": [1, 2, 3]}


def test_put_numpy_zero_copy_readonly(ray_start_regular):
    arr = np.arange(10)
    ref = ray_tpu.put(arr)
    got = ray_tpu.get(ref)
    np.testing.assert_array_equal(got, arr)
    with pytest.raises(ValueError):
        got[0] = 99  # store values are immutable


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_with_ref_args(ray_start_regular):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    x = ray_tpu.put(10)
    y = add.remote(x, 5)
    z = add.remote(y, y)
    assert ray_tpu.get(z) == 30


def test_task_chain_parallel(ray_start_regular):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(20)]
    assert ray_tpu.get(refs) == [i * i for i in range(20)]


def test_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagation(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("bad")

    with pytest.raises(ValueError, match="bad"):
        ray_tpu.get(boom.remote())


def test_error_propagates_through_dependency(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise KeyError("first")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(exc.TaskError):
        ray_tpu.get(consume.remote(boom.remote()))


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return 1

    with pytest.raises(exc.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.2)


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        time.sleep(10)
        return 2

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([f, s], num_returns=1, timeout=5)
    assert ready == [f]
    assert not_ready == [s]


def test_wait_timeout_empty(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(10)

    ready, not_ready = ray_tpu.wait([slow.remote()], num_returns=1,
                                    timeout=0.1)
    assert ready == []
    assert len(not_ready) == 1


def test_options_override(ray_start_regular):
    @ray_tpu.remote(num_cpus=1)
    def f():
        return ray_tpu.get_runtime_context().get_assigned_resources()

    res = ray_tpu.get(f.options(num_cpus=2).remote())
    assert res.get("CPU") == 2


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(1)) == 12


def test_nested_refs_in_containers(ray_start_regular):
    @ray_tpu.remote
    def make():
        return 7

    @ray_tpu.remote
    def consume(refs):
        return sum(ray_tpu.get(r) for r in refs)

    refs = [make.remote() for _ in range(3)]
    # Passing refs inside a list does NOT auto-resolve (parity with ray).
    assert ray_tpu.get(consume.remote(refs)) == 21


def test_retry_on_app_error(ray_start_regular, tmp_path):
    # Attempts counted out-of-band (a file): tasks execute in worker
    # processes behind a serialization boundary, so driver-closure
    # mutation must NOT be visible (reference semantics).
    counter = str(tmp_path / "attempts")

    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def flaky():
        import os
        n = len(os.listdir(os.path.dirname(counter)))
        open(f"{counter}.{n}", "w").close()
        if n + 1 < 3:
            raise RuntimeError("transient")
        return "ok"

    assert ray_tpu.get(flaky.remote()) == "ok"
    import os
    assert len(os.listdir(tmp_path)) == 3


def test_retry_exceptions_allowlist(ray_start_regular, tmp_path):
    counter = str(tmp_path / "attempts")

    @ray_tpu.remote(max_retries=5, retry_exceptions=[KeyError])
    def flaky():
        import os
        n = len(os.listdir(os.path.dirname(counter)))
        open(f"{counter}.{n}", "w").close()
        raise ValueError("not retryable")

    with pytest.raises(ValueError):
        ray_tpu.get(flaky.remote())
    import os
    assert len(os.listdir(tmp_path)) == 1


def test_cancel_pending(ray_start_regular):
    @ray_tpu.remote(num_cpus=8)
    def hog():
        time.sleep(3)
        return 1

    @ray_tpu.remote(num_cpus=8)
    def queued():
        return 2

    h = hog.remote()
    q = queued.remote()  # stuck behind hog
    time.sleep(0.1)
    ray_tpu.cancel(q)
    with pytest.raises(exc.TaskCancelledError):
        ray_tpu.get(q)
    assert ray_tpu.get(h) == 1


def test_runtime_context(ray_start_regular):
    @ray_tpu.remote
    def ctx():
        c = ray_tpu.get_runtime_context()
        return c.get_task_id(), c.get_node_id(), c.get_job_id()

    task_id, node_id, job_id = ray_tpu.get(ctx.remote())
    assert task_id and node_id and job_id


def test_cluster_resources(ray_start_regular):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 8


def test_large_object_roundtrip(ray_start_regular):
    arr = np.random.rand(1000, 1000)  # 8 MB -> node store

    @ray_tpu.remote
    def identity(x):
        return x

    got = ray_tpu.get(identity.remote(arr))
    np.testing.assert_array_equal(got, arr)


def test_streaming_generator(ray_start_regular):
    @ray_tpu.remote
    def gen(n):
        for i in range(n):
            yield i * 10

    out = [ray_tpu.get(ref) for ref in gen.remote(5)]
    assert out == [0, 10, 20, 30, 40]


def test_streaming_generator_early_consumption(ray_start_regular):
    @ray_tpu.remote
    def gen():
        yield "first"
        time.sleep(3)
        yield "second"

    it = gen.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(next(it))
    assert first == "first"
    assert time.monotonic() - t0 < 2.0  # did not wait for task completion


def test_generator_error_propagates(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def gen():
        yield 1
        raise RuntimeError("mid-stream failure")

    it = gen.remote()
    assert ray_tpu.get(next(it)) == 1
    with pytest.raises(Exception, match="mid-stream"):
        next(it)
        next(it)
