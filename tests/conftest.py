"""Test fixtures.

JAX is forced onto a virtual 8-device CPU platform (the reference's
"multi-node cluster in one machine" fixture idea, cluster_utils.py:135,
applied to SPMD: XLA_FLAGS=--xla_force_host_platform_device_count=8).
Must run before the first jax import in the test process.
"""

from ray_tpu._private.platform import force_cpu_platform

force_cpu_platform(8)

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    """Boot a 1-node runtime per test (reference: conftest.py:588)."""
    import ray_tpu
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 8})
    yield rt
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Boot a 4-node virtual cluster (reference: conftest.py:678)."""
    import ray_tpu
    rt = ray_tpu.init(num_nodes=4, resources={"CPU": 4})
    yield rt
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _cleanup_runtime():
    yield
    import ray_tpu
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


def pytest_sessionfinish(session, exitstatus):
    """Lock-sanitizer gate (tools/run_chaos.sh sanitized stage): the
    -W error escalation only fails tests whose inversion fires on the
    MAIN thread; most runtime locks are acquired on daemon threads,
    where a raised LockOrderViolation dies with the thread. The graph
    records every violation regardless of thread — fail the session on
    any of them when the sanitizer is armed."""
    import os
    if os.environ.get("RAY_TPU_LOCK_SANITIZER") != "1":
        return
    try:
        from ray_tpu._private.lock_sanitizer import GRAPH
    except Exception:
        return
    if GRAPH.violations and exitstatus == 0:
        reporter = session.config.pluginmanager.get_plugin(
            "terminalreporter")
        if reporter is not None:
            reporter.write_line(
                f"lock sanitizer: {len(GRAPH.violations)} lock-order "
                f"violation(s) recorded on runtime threads:", red=True)
            for v in GRAPH.violations:
                reporter.write_line(v, red=True)
        # pytest.exit from sessionfinish is the sanctioned way to force
        # the process exit code (wrap_session catches exit.Exception
        # and adopts its returncode; plain session.exitstatus
        # assignment does not stick here)
        pytest.exit("lock-order violations recorded", returncode=1)
