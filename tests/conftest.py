"""Test fixtures.

JAX is forced onto a virtual 8-device CPU platform (the reference's
"multi-node cluster in one machine" fixture idea, cluster_utils.py:135,
applied to SPMD: XLA_FLAGS=--xla_force_host_platform_device_count=8).
Must run before the first jax import in the test process.
"""

from ray_tpu._private.platform import force_cpu_platform

force_cpu_platform(8)

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    """Boot a 1-node runtime per test (reference: conftest.py:588)."""
    import ray_tpu
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 8})
    yield rt
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Boot a 4-node virtual cluster (reference: conftest.py:678)."""
    import ray_tpu
    rt = ray_tpu.init(num_nodes=4, resources={"CPU": 4})
    yield rt
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _cleanup_runtime():
    yield
    import ray_tpu
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
