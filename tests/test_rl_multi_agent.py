"""Multi-agent RL: env API, per-policy batching, shared-policy training.

Reference capability: `rllib/env/multi_agent_env.py` +
`multi_agent_env_runner.py` + AlgorithmConfig.multi_agent().
"""

import numpy as np
import pytest

from ray_tpu.rl import AlgorithmConfig, MultiAgentCartPole
from ray_tpu.rl.multi_agent import MultiAgentEnvRunner


def test_multi_agent_env_api():
    env = MultiAgentCartPole(num_agents=3, seed=0, max_steps=10)
    obs, _ = env.reset()
    assert set(obs) == {"agent_0", "agent_1", "agent_2"}
    obs, rew, term, trunc, _ = env.step({a: 0 for a in obs})
    assert set(rew) == {"agent_0", "agent_1", "agent_2"}
    assert term["__all__"] is False
    # run to the end: each agent terminates individually, then __all__
    for _ in range(30):
        if term["__all__"]:
            break
        obs, rew, term, trunc, _ = env.step({a: 0 for a in obs})
    assert term["__all__"] is True
    # done agents stop appearing in obs
    assert obs == {} or all(not env._done[a] for a in obs)


def test_runner_groups_fragments_by_policy():
    from ray_tpu.rl.ppo import ActorCriticPolicy

    factories = {
        "even": lambda: ActorCriticPolicy(4, 2, seed=0),
        "odd": lambda: ActorCriticPolicy(4, 2, seed=1),
    }

    def mapping(aid):
        return "even" if int(aid.split("_")[1]) % 2 == 0 else "odd"

    runner = MultiAgentEnvRunner(
        lambda seed=0: MultiAgentCartPole(4, seed=seed, max_steps=50),
        factories, mapping, seed=3)
    out = runner.sample(40)
    assert set(out) == {"even", "odd"}
    assert len(out["even"]) == 2 and len(out["odd"]) == 2
    for frags in out.values():
        for f in frags:
            n = len(f["rewards"])
            assert 0 < n <= 40
            assert f["obs"].shape == (n, 4)
            assert f["next_obs_last"].shape == (4,)
            assert f["logp"].shape == (n,)


def test_multi_agent_ppo_trains(ray_start_regular):
    from ray_tpu.rl import register_env

    register_env("MultiCartPole-2",
                 lambda seed=0: MultiAgentCartPole(2, seed=seed,
                                                  max_steps=100))
    algo = (AlgorithmConfig(algo="PPO", seed=0)
            .environment("MultiCartPole-2")
            .env_runners(2, rollout_fragment_length=128)
            .training(minibatch_size=64, epochs=2)
            .multi_agent(
                policies={"p0": None, "p1": None},
                policy_mapping_fn=lambda aid: (
                    "p0" if aid == "agent_0" else "p1"))
            .build())
    try:
        m = None
        for _ in range(3):
            m = algo.train()
        # both policies actually updated and reported
        assert any(k.startswith("p0/") for k in m)
        assert any(k.startswith("p1/") for k in m)
        assert m["training_iteration"] == 3
        assert m["num_episodes"] > 0
        assert np.isfinite(m["episode_return_mean"])
    finally:
        algo.stop()


def test_all_done_without_per_agent_flags():
    """Envs ending via __all__ only (shared time limit) must not let
    trajectories bootstrap across the reset, and episode returns must
    flush per episode."""
    from ray_tpu.rl.ppo import ActorCriticPolicy

    class SharedLimitEnv:
        n_actions = 2
        obs_dim = 4
        agent_ids = ["a", "b"]

        def __init__(self, seed=0):
            self.t = 0

        def reset(self, seed=None):
            self.t = 0
            return {a: np.zeros(4, np.float32)
                    for a in self.agent_ids}, {}

        def step(self, actions):
            self.t += 1
            obs = {a: np.zeros(4, np.float32) for a in self.agent_ids}
            rew = {a: 1.0 for a in actions}
            over = self.t >= 5
            return obs, rew, {"__all__": over}, {"__all__": False}, {}

    runner = MultiAgentEnvRunner(
        lambda seed=0: SharedLimitEnv(),
        {"p": lambda: ActorCriticPolicy(4, 2, seed=0)},
        lambda a: "p", seed=0)
    out = runner.sample(10)
    for frag in out["p"]:
        assert list(np.nonzero(frag["dones"])[0]) == [4, 9]
    # 2 agents x 2 completed episodes of return 5 each
    assert sorted(runner.episode_returns()) == [5.0] * 4


def test_mapping_validated_at_build(ray_start_regular):
    from ray_tpu.rl import register_env

    register_env("MultiCartPole-2v",
                 lambda seed=0: MultiAgentCartPole(2, seed=seed))
    cfg = (AlgorithmConfig(algo="PPO")
           .environment("MultiCartPole-2v")
           .multi_agent(policies={"p0": None},
                        policy_mapping_fn=lambda aid: aid))
    with pytest.raises(ValueError, match="not in policies"):
        cfg.build()


def test_multi_agent_impala_async_path(ray_start_regular):
    """Multi-agent IMPALA: fragments stream through the async
    actor-learner loop, one V-trace learner per policy."""
    from ray_tpu.rl import register_env

    register_env("MultiCartPole-2a",
                 lambda seed=0: MultiAgentCartPole(2, seed=seed,
                                                  max_steps=80))
    algo = (AlgorithmConfig(algo="IMPALA", seed=0)
            .environment("MultiCartPole-2a")
            .env_runners(2, rollout_fragment_length=64)
            .multi_agent(
                policies={"p0": None, "p1": None},
                policy_mapping_fn=lambda aid: (
                    "p0" if aid == "agent_0" else "p1"))
            .build())
    try:
        m = None
        for _ in range(2):
            m = algo.train()
        assert any(k.startswith("p0/") for k in m)
        assert any(k.startswith("p1/") for k in m)
        assert m["training_iteration"] == 2
        assert np.isfinite(m["episode_return_mean"]) or \
            m["num_episodes"] == 0
    finally:
        algo.stop()


def test_tuned_examples_registry_builds(ray_start_regular):
    """Every tuned example's config must at least build and train one
    iteration (reference: tuned_examples as runnable contracts)."""
    from ray_tpu.rl.tuned_examples import TUNED
    assert len(TUNED) >= 6
    for name, ex in TUNED.items():
        algo = ex.make_config().build()
        try:
            m = algo.train()
            assert m["training_iteration"] == 1, name
        finally:
            algo.stop()


def test_tuned_example_contract_runs(ray_start_regular):
    """One fast contract end-to-end: PPO improves toward its target."""
    from ray_tpu.rl.tuned_examples import run
    m = run("ppo-cartpole", max_iterations=6, target_return=60.0)
    assert m["best_return"] > 20.0
    assert m["training_iteration"] >= 1
