"""Compiled DAGs + runtime environments."""

import os

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.runtime_env import RuntimeEnv


def test_function_dag_execute(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def inc(x):
        return x + 1

    with InputNode() as inp:
        dag = inc.bind(double.bind(inp))
    assert ray_tpu.get(dag.execute(5)) == 11
    assert ray_tpu.get(dag.execute(10)) == 21


def test_actor_dag_and_compile(ray_start_regular):
    @ray_tpu.remote
    class Adder:
        def __init__(self, c):
            self.c = c

        def add(self, x):
            return x + self.c

    a = Adder.remote(100)
    b = Adder.remote(1000)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    assert ray_tpu.get(compiled.execute(5)) == 1105
    assert ray_tpu.get(compiled.execute(6)) == 1106
    compiled.teardown()
    with pytest.raises(RuntimeError):
        compiled.execute(1)


def test_multi_output_dag(ray_start_regular):
    @ray_tpu.remote
    def square(x):
        return x * x

    @ray_tpu.remote
    def cube(x):
        return x ** 3

    with InputNode() as inp:
        dag = MultiOutputNode([square.bind(inp), cube.bind(inp)])
    refs = dag.execute(3)
    assert ray_tpu.get(refs) == [9, 27]


def test_dag_diamond(ray_start_regular):
    @ray_tpu.remote
    def left(x):
        return x + 1

    @ray_tpu.remote
    def right(x):
        return x * 10

    @ray_tpu.remote
    def join(a, b):
        return a + b

    with InputNode() as inp:
        dag = join.bind(left.bind(inp), right.bind(inp))
    assert ray_tpu.get(dag.execute(4)) == 45


def test_runtime_env_env_vars(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_TEST_VAR": "42"}})
    def read_env():
        return os.environ.get("RTPU_TEST_VAR")

    assert ray_tpu.get(read_env.remote()) == "42"
    assert os.environ.get("RTPU_TEST_VAR") is None  # restored


def test_runtime_env_actor(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_ACTOR_VAR": "x1"}})
    class EnvActor:
        def __init__(self):
            self.seen = os.environ.get("RTPU_ACTOR_VAR")

        def get(self):
            return self.seen

    a = EnvActor.remote()
    assert ray_tpu.get(a.get.remote()) == "x1"


def test_runtime_env_validation():
    with pytest.raises(ValueError):
        RuntimeEnv(bogus_field=1)
    with pytest.raises(TypeError):
        RuntimeEnv(env_vars={"A": 1})
    env = RuntimeEnv(env_vars={"A": "1"}, working_dir=".")
    assert env["env_vars"] == {"A": "1"}


def test_runtime_env_py_modules(ray_start_regular, tmp_path):
    mod_dir = tmp_path / "mods"
    mod_dir.mkdir()
    (mod_dir / "rtpu_testmod.py").write_text("VALUE = 'imported'\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod_dir)]})
    def use_module():
        import rtpu_testmod
        return rtpu_testmod.VALUE

    assert ray_tpu.get(use_module.remote()) == "imported"
