"""Ray-Client-equivalent: remote driver over socket."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.client import connect, serve_cluster


@pytest.fixture
def client(ray_start_regular):
    server = serve_cluster(port=0)
    c = connect(f"{server.address[0]}:{server.address[1]}")
    yield c
    c.close()
    server.shutdown()


def test_client_put_get(client):
    ref = client.put({"a": np.arange(5)})
    back = client.get(ref)
    np.testing.assert_array_equal(back["a"], np.arange(5))


def test_client_tasks(client):
    def square(x):
        return x * x

    f = client.remote(square)
    refs = [f.remote(i) for i in range(5)]
    assert client.get(refs) == [0, 1, 4, 9, 16]


def test_client_task_with_ref_arg(client):
    def add(a, b):
        return a + b

    f = client.remote(add)
    r1 = client.put(10)
    assert client.get(f.remote(r1, 5)) == 15


def test_client_actors(client):
    class Counter:
        def __init__(self, start):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

    A = client.remote(Counter)
    a = A.remote(100)
    assert client.get(a.inc.remote()) == 101
    assert client.get(a.inc.remote(9)) == 110
    client.kill(a)


def test_client_wait_and_resources(client):
    def fast():
        return 1

    f = client.remote(fast)
    refs = [f.remote() for _ in range(4)]
    ready, rest = client.wait(refs, num_returns=4, timeout=30)
    assert len(ready) == 4 and not rest
    assert client.cluster_resources().get("CPU", 0) > 0


def test_client_error_propagation(client):
    def boom():
        raise ValueError("kaboom")

    f = client.remote(boom)
    with pytest.raises(RuntimeError, match="kaboom"):
        client.get(f.remote())
