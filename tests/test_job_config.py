"""JobConfig: job-default runtime env + code search path.

Reference capability: `python/ray/job_config.py` serialized at driver
connect (`_private/worker.py:2347`).
"""

import pytest

import ray_tpu
from ray_tpu.job_config import JobConfig


def test_job_default_runtime_env_and_code_search_path(tmp_path):
    mod_dir = tmp_path / "jobmods"
    mod_dir.mkdir()
    (mod_dir / "jobcfg_mod.py").write_text("VALUE = 'from-search-path'\n")

    jc = JobConfig(
        runtime_env={"env_vars": {"JOBCFG_FLAG": "on"}},
        metadata={"team": "tpu"},
        code_search_path=[str(mod_dir)])
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4}, job_config=jc)
    try:
        @ray_tpu.remote
        def probe():
            import os

            import jobcfg_mod
            return os.environ.get("JOBCFG_FLAG"), jobcfg_mod.VALUE

        flag, val = ray_tpu.get(probe.remote(), timeout=60)
        assert flag == "on"                 # job-default env applied
        assert val == "from-search-path"

        @ray_tpu.remote(runtime_env={"env_vars": {"JOBCFG_FLAG": "own"}})
        def own_env():
            import os
            return os.environ.get("JOBCFG_FLAG")

        # a task's OWN runtime env wins over the job default
        assert ray_tpu.get(own_env.remote(), timeout=60) == "own"
        assert rt.job_config.metadata == {"team": "tpu"}
    finally:
        ray_tpu.shutdown()


def test_job_config_validation():
    with pytest.raises(ValueError, match="default_actor_lifetime"):
        JobConfig(default_actor_lifetime="bogus")
    with pytest.raises(ValueError):
        JobConfig(runtime_env={"not_a_field": 1})
    jc = JobConfig()
    assert jc.serialize()["metadata"] == {}
