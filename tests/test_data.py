"""Data layer: datasets, transforms, shuffles, groupby, iteration."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.data import Count, Max, Mean, Min, Sum


@pytest.fixture
def ray4(ray_start_regular):
    return ray_start_regular


def test_range_count_take(ray4):
    ds = rdata.range(100)
    assert ds.count() == 100
    assert [r["id"] for r in ds.take(5)] == [0, 1, 2, 3, 4]


def test_from_items_and_map(ray4):
    ds = rdata.from_items([1, 2, 3, 4]).map(
        lambda r: {"item": r["item"] * 10})
    assert sorted(r["item"] for r in ds.take_all()) == [10, 20, 30, 40]


def test_map_batches_numpy(ray4):
    ds = rdata.range(10).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    rows = ds.take_all()
    assert all(r["sq"] == r["id"] ** 2 for r in rows)


def test_fused_map_chain_streams(ray4):
    ds = (rdata.range(100, parallelism=10)
          .map(lambda r: {"id": r["id"] + 1})
          .filter(lambda r: r["id"] % 2 == 0)
          .map_batches(lambda b: {"id": b["id"] * 2}))
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == [x * 2 for x in range(2, 101, 2)]


def test_flat_map(ray4):
    ds = rdata.from_items([1, 2]).flat_map(
        lambda r: [{"v": r["item"]}, {"v": r["item"] * 100}])
    assert sorted(r["v"] for r in ds.take_all()) == [1, 2, 100, 200]


def test_limit_streaming(ray4):
    ds = rdata.range(1000, parallelism=20).limit(37)
    assert ds.count() == 37


def test_repartition(ray4):
    ds = rdata.range(100, parallelism=10).repartition(3)
    assert ds.num_blocks() == 3
    assert ds.count() == 100


def test_random_shuffle_preserves_multiset(ray4):
    ds = rdata.range(50).random_shuffle(seed=42)
    vals = [r["id"] for r in ds.take_all()]
    assert sorted(vals) == list(range(50))
    assert vals != list(range(50))  # overwhelmingly likely


def test_sort(ray4):
    rng = np.random.default_rng(0)
    items = [{"k": int(x)} for x in rng.permutation(100)]
    ds = rdata.from_items(items, parallelism=7).sort("k")
    assert [r["k"] for r in ds.take_all()] == list(range(100))
    ds2 = rdata.from_items(items, parallelism=7).sort("k", descending=True)
    assert [r["k"] for r in ds2.take_all()] == list(range(99, -1, -1))


def test_union_zip(ray4):
    a = rdata.from_items([{"x": 1}, {"x": 2}])
    b = rdata.from_items([{"x": 3}])
    assert sorted(r["x"] for r in a.union(b).take_all()) == [1, 2, 3]
    c = rdata.from_items([{"y": 10}, {"y": 20}])
    z = a.zip(c).take_all()
    assert z == [{"x": 1, "y": 10}, {"x": 2, "y": 20}]


def test_groupby_aggregate(ray4):
    items = [{"k": i % 3, "v": i} for i in range(30)]
    ds = rdata.from_items(items, parallelism=5)
    out = ds.groupby("k").aggregate(Sum("v"), Count()).take_all()
    by_k = {r["k"]: r for r in out}
    assert by_k[0]["sum(v)"] == sum(i for i in range(30) if i % 3 == 0)
    assert by_k[1]["count()"] == 10


def test_groupby_map_groups(ray4):
    items = [{"k": i % 2, "v": float(i)} for i in range(10)]
    ds = rdata.from_items(items, parallelism=3)
    out = ds.groupby("k").map_groups(
        lambda b: {"k": b["k"][:1], "vmax": [b["v"].max()]}).take_all()
    assert {r["k"]: r["vmax"] for r in out} == {0: 8.0, 1: 9.0}


def test_scalar_aggregates(ray4):
    ds = rdata.range(10)
    assert ds.sum("id") == 45
    assert ds.min("id") == 0
    assert ds.max("id") == 9
    assert ds.mean("id") == 4.5


def test_iter_batches_sizes(ray4):
    ds = rdata.range(25, parallelism=4)
    batches = list(ds.iter_batches(batch_size=10))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [10, 10, 5]
    batches = list(ds.iter_batches(batch_size=10, drop_last=True))
    assert [len(b["id"]) for b in batches] == [10, 10]


def test_iter_batches_local_shuffle(ray4):
    ds = rdata.range(40, parallelism=4)
    vals = []
    for b in ds.iter_batches(batch_size=10, local_shuffle_buffer_size=20,
                             local_shuffle_seed=0):
        vals.extend(b["id"].tolist())
    assert sorted(vals) == list(range(40))
    assert vals != list(range(40))


def test_actor_pool_map_batches(ray4):
    class AddConst:
        def __init__(self):
            self.c = 1000

        def __call__(self, batch):
            return {"id": batch["id"] + self.c}

    ds = rdata.range(20, parallelism=4).map_batches(AddConst, concurrency=2)
    assert sorted(r["id"] for r in ds.take_all()) == \
        list(range(1000, 1020))


def test_split_and_streaming_split(ray4):
    ds = rdata.range(20, parallelism=4)
    parts = ds.split(2)
    assert sum(p.count() for p in parts) == 20
    its = ds.streaming_split(2)
    seen = []
    for it in its:
        for b in it.iter_batches(batch_size=None):
            seen.extend(b["id"].tolist())
    assert sorted(seen) == list(range(20))


def test_parquet_roundtrip(ray4, tmp_path):
    ds = rdata.range(50, parallelism=3)
    ds.write_parquet(str(tmp_path / "pq"))
    back = rdata.read_parquet(str(tmp_path / "pq"))
    assert sorted(r["id"] for r in back.take_all()) == list(range(50))


def test_csv_and_text(ray4, tmp_path):
    ds = rdata.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}])
    ds.write_csv(str(tmp_path / "csv"))
    back = rdata.read_csv(str(tmp_path / "csv"))
    assert sorted(r["a"] for r in back.take_all()) == [1, 2]
    (tmp_path / "t.txt").write_text("hello\nworld\n")
    txt = rdata.read_text(str(tmp_path / "t.txt"))
    assert [r["text"] for r in txt.take_all()] == ["hello", "world"]


def test_column_ops(ray4):
    ds = rdata.range(5).add_column("double", lambda b: b["id"] * 2)
    assert ds.take(1)[0]["double"] == 0
    assert set(ds.select_columns(["double"]).columns()) == {"double"}
    renamed = ds.rename_columns({"double": "d2"})
    assert "d2" in renamed.columns()
    dropped = ds.drop_columns(["double"])
    assert dropped.columns() == ["id"]


def test_to_jax_device_iterator(ray4):
    ds = rdata.range(32, parallelism=2)
    batches = list(ds.to_jax(batch_size=16))
    assert len(batches) == 2
    import jax
    assert isinstance(batches[0]["id"], jax.Array)


def test_dataset_feeds_trainer(ray4, tmp_path):
    """Data → Train ingest path (reference SURVEY.md §8.13)."""
    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ds = rdata.range(40, parallelism=4)

    def train_fn(config):
        shard = train.get_dataset_shard("train")
        total = 0
        for batch in shard.iter_batches(batch_size=5):
            total += int(batch["id"].sum())
        train.report({"total": total})

    result = JaxTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ingest", storage_path=str(tmp_path)),
        datasets={"train": ds}).fit()
    assert result.error is None
    assert sum(e["metrics"]["total"]
               for e in result.metrics_history) == sum(range(40))


def test_hash_join(ray4):
    left = rdata.from_items(
        [{"k": i % 5, "lv": i} for i in range(20)], parallelism=4)
    right = rdata.from_items(
        [{"k": k, "rv": k * 100} for k in range(3)], parallelism=2)
    joined = left.join(right, on="k").take_all()
    assert all(r["rv"] == r["k"] * 100 for r in joined)
    assert len(joined) == 12  # k in {0,1,2}: 4 left rows each x 1 right
    outer = left.join(right, on="k", how="left outer").take_all()
    assert len(outer) == 20
