"""Serve: deployments, routing, composition, batching, autoscaling, HTTP."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cluster(ray_start_regular):
    yield ray_start_regular
    serve.shutdown()


def test_function_deployment(serve_cluster):
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    assert handle.remote("hi").result() == {"echo": "hi"}


def test_class_deployment_with_state(serve_cluster):
    @serve.deployment(num_replicas=1)
    class Counter:
        def __init__(self, start):
            self.n = start

        def __call__(self):
            self.n += 1
            return self.n

        def peek(self):
            return self.n

    handle = serve.run(Counter.bind(10))
    assert handle.remote().result() == 11
    assert handle.remote().result() == 12
    assert handle.peek.remote().result() == 12


def test_multi_replica_round_robin(serve_cluster):
    import os
    import threading

    @serve.deployment(num_replicas=3)
    class Who:
        def __init__(self):
            self.id = id(self)

        def __call__(self):
            return self.id

    handle = serve.run(Who.bind())
    seen = {handle.remote().result() for _ in range(30)}
    assert len(seen) >= 2  # p2c spreads over replicas


def test_model_composition(serve_cluster):
    @serve.deployment
    class Preprocessor:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Model:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            doubled = self.pre.remote(x).result()
            return doubled + 1

    handle = serve.run(Model.bind(Preprocessor.bind()))
    assert handle.remote(5).result() == 11


def test_dynamic_batching(serve_cluster):
    @serve.deployment(max_ongoing_requests=16)
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        def handle(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        def __call__(self, x):
            return self.handle(x)

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind())
    resps = [handle.remote(i) for i in range(8)]
    assert sorted(r.result() for r in resps) == [i * 10 for i in range(8)]
    sizes = handle.sizes.remote().result()
    assert max(sizes) > 1  # batching actually happened


def test_reconfigure_user_config(serve_cluster):
    @serve.deployment(user_config={"k": 1})
    class Cfg:
        def __init__(self):
            self.k = None

        def reconfigure(self, cfg):
            self.k = cfg["k"]

        def __call__(self):
            return self.k

    handle = serve.run(Cfg.bind())
    assert handle.remote().result() == 1
    controller = ray_tpu.get_actor("serve_controller")
    ray_tpu.get(controller.reconfigure_deployment.remote("Cfg", {"k": 9}))
    assert handle.remote().result() == 9


def test_replica_failure_recovery(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Fragile:
        def __call__(self):
            return "ok"

    handle = serve.run(Fragile.bind())
    assert handle.remote().result() == "ok"
    controller = ray_tpu.get_actor("serve_controller")
    replicas = ray_tpu.get(
        controller.get_replicas.remote("Fragile"))["replicas"]
    ray_tpu.kill(replicas[0])
    deadline = time.time() + 15
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote())["Fragile"]
        if st["num_replicas"] == 2 and st["version"] >= 2:
            break
        time.sleep(0.3)
    st = ray_tpu.get(controller.status.remote())["Fragile"]
    assert st["num_replicas"] == 2
    # traffic still works after recovery
    handle._refresh(force=True)
    assert handle.remote().result() == "ok"


def test_push_metrics_feed_controller(serve_cluster):
    """Replicas PUSH their metrics (reference: autoscaling_state.py) —
    the controller's cache fills without it ever polling, and a killed
    replica's zombie reporter cannot keep its slot looking healthy."""

    @serve.deployment(num_replicas=2)
    class Svc:
        def __call__(self):
            return "ok"

    handle = serve.run(Svc.bind())
    assert handle.remote().result() == "ok"
    controller = ray_tpu.get_actor("serve_controller")

    deadline = time.time() + 10
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote())["Svc"]
        if st["metrics_fresh"] == 2:
            break
        time.sleep(0.2)
    assert st["metrics_fresh"] == 2, st

    # kill one replica: its Replica instance (and reporter thread) lives
    # on in-process, but its reports must be rejected/stopped so the
    # slot goes stale, the death is detected, and a replacement lands
    replicas = ray_tpu.get(controller.get_replicas.remote("Svc"))["replicas"]
    dead_id = replicas[0]._actor_id
    ray_tpu.kill(replicas[0])
    deadline = time.time() + 20
    recovered = False
    while time.time() < deadline:
        reps = ray_tpu.get(controller.get_replicas.remote("Svc"))
        ids = [r._actor_id for r in reps["replicas"]]
        if len(ids) == 2 and dead_id not in ids:
            recovered = True
            break
        time.sleep(0.3)
    assert recovered, "dead replica was never replaced"
    handle._refresh(force=True)
    assert handle.remote().result() == "ok"


def test_autoscaling_up(serve_cluster):
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0})
    class Slow:
        def __call__(self):
            time.sleep(1.0)
            return "done"

    handle = serve.run(Slow.bind())
    resps = [handle.remote() for _ in range(6)]
    controller = ray_tpu.get_actor("serve_controller")
    deadline = time.time() + 10
    scaled = False
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote())["Slow"]
        if st["target_replicas"] > 1:
            scaled = True
            break
        time.sleep(0.2)
    assert scaled
    for r in resps:
        assert r.result(timeout=30) == "done"


def test_http_proxy(serve_cluster):
    @serve.deployment
    def app(payload):
        return {"got": payload}

    serve.run(app.bind())
    port = serve.start_http_proxy(port=0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=json.dumps({"a": 1}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert body == {"got": {"a": 1}}


def test_serve_status_and_delete(serve_cluster):
    @serve.deployment(num_replicas=2)
    def f(x):
        return x

    serve.run(f.bind())
    st = serve.status()
    assert st["f"]["num_replicas"] == 2
    serve.delete("default")
    assert "f" not in serve.status()


def test_local_testing_mode():
    """No cluster needed: the app graph runs in-process."""
    @serve.deployment
    class Pre:
        def __call__(self, x):
            return x + 1

    @serve.deployment(user_config={"scale": 10})
    class Model:
        def __init__(self, pre):
            self.pre = pre
            self.scale = 1

        def reconfigure(self, cfg):
            self.scale = cfg["scale"]

        def __call__(self, x):
            return self.pre.remote(x).result() * self.scale

    handle = serve.run(Model.bind(Pre.bind()), local_testing_mode=True)
    assert handle.remote(4).result() == 50


# ---------------------------------------------------------------------------
# Streaming responses (reference: replica.py:1028 handle_request_streaming,
# proxy.py:1009 streaming proxy path)
# ---------------------------------------------------------------------------

def test_streaming_handle_first_chunk_before_completion(serve_cluster):
    """The defining property of streaming: chunk 1 is consumable BEFORE
    the replica's generator has finished producing."""

    @serve.deployment
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                time.sleep(0.2)
                yield {"i": i}

    handle = serve.run(Streamer.bind())
    t0 = time.monotonic()
    gen = handle.options(stream=True).remote(5)
    first = next(iter(gen))
    t_first = time.monotonic() - t0
    rest = list(gen)
    t_all = time.monotonic() - t0
    assert first == {"i": 0}
    assert [c["i"] for c in rest] == [1, 2, 3, 4]
    # 5 chunks x 0.2s ~= 1.0s total; the first must arrive well before
    assert t_first < t_all - 0.3, (t_first, t_all)


def test_streaming_non_generator_degrades_to_single_chunk(serve_cluster):
    @serve.deployment
    def plain(x):
        return {"just": x}

    handle = serve.run(plain.bind())
    chunks = list(handle.options(stream=True).remote("one"))
    assert chunks == [{"just": "one"}]


def test_http_proxy_sse_streaming(serve_cluster):
    """SSE through the HTTP proxy: first data: event readable before the
    generator completes (a real TTFT)."""

    @serve.deployment
    class SSEApp:
        def __call__(self, payload):
            n = int(payload.get("n", 3)) if isinstance(payload, dict) else 3
            for i in range(n):
                time.sleep(0.25)
                yield {"chunk": i}

    serve.run(SSEApp.bind())
    port = serve.start_http_proxy(port=0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"n": 4}).encode(),
        headers={"Content-Type": "application/json",
                 "Accept": "text/event-stream"})
    t0 = time.monotonic()
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers["Content-Type"] == "text/event-stream"
        events = []
        t_first = None
        for raw in resp:
            line = raw.decode().strip()
            if not line.startswith("data: "):
                continue
            if t_first is None:
                t_first = time.monotonic() - t0
            body = line[len("data: "):]
            if body == "[DONE]":
                break
            events.append(json.loads(body))
    t_all = time.monotonic() - t0
    assert [e["chunk"] for e in events] == [0, 1, 2, 3]
    assert t_first < t_all - 0.3, (t_first, t_all)


def test_llm_serve_token_streaming(serve_cluster):
    """LLM serving streams engine tokens chunk-by-chunk through Serve."""
    from ray_tpu.llm.serving import LLMConfig, build_llm_app

    app = build_llm_app(LLMConfig(max_slots=2, max_seq=128))
    handle = serve.run(app)
    gen = handle.options(stream=True).remote(
        {"prompt": "hello", "max_tokens": 8, "stream": True})
    chunks = list(gen)
    assert chunks[-1].get("done") is True
    deltas = [c for c in chunks if "delta" in c]
    assert 1 <= len(deltas) <= 8
    assert chunks[-1]["usage"]["completion_tokens"] == len(deltas)


# ---------------------------------------------------------------------------
# Declarative app config (reference: serve/schema.py + `serve deploy`)
# ---------------------------------------------------------------------------

def test_run_config_from_yaml(serve_cluster, tmp_path):
    cfg = tmp_path / "app.yaml"
    cfg.write_text("""
applications:
  - name: default
    import_path: tests.serve_app_fixture:app
    deployments:
      - name: Scaler
        num_replicas: 2
        user_config: {factor: 5}
""")
    handles = serve.run_config(str(cfg))
    assert set(handles) == {"default"}
    # user_config override applied via reconfigure
    assert handles["default"].remote(10).result(timeout=30) == 50
    st = serve.status()
    dep = st["Scaler"]
    assert dep["target_replicas"] == 2


def test_run_config_builder_with_args(serve_cluster):
    handles = serve.run_config({
        "applications": [{
            "name": "built",
            "import_path": "tests.serve_app_fixture:build_app",
            "args": {"factor": 7},
        }]})
    assert handles["built"].remote(3).result(timeout=30) == 21


def test_run_config_rejects_bad_entries(serve_cluster, tmp_path):
    with pytest.raises(ValueError, match="applications"):
        serve.run_config({"nope": []})
    with pytest.raises(ValueError, match="unknown deployment option"):
        serve.run_config({"applications": [{
            "import_path": "tests.serve_app_fixture:app",
            "deployments": [{"name": "Scaler", "bogus_knob": 1}]}]})


def test_run_config_validation_errors(serve_cluster):
    import pytest as _pytest

    # typo'd deployment name must raise, not silently no-op
    with _pytest.raises(ValueError, match="match no deployment"):
        serve.run_config({"applications": [{
            "import_path": "tests.serve_app_fixture:app",
            "deployments": [{"name": "Sclaer", "num_replicas": 9}]}]})
    # override entry without a name
    with _pytest.raises(ValueError, match="missing 'name'"):
        serve.run_config({"applications": [{
            "import_path": "tests.serve_app_fixture:app",
            "deployments": [{"num_replicas": 2}]}]})
    # internal fields are not part of the declarative surface
    with _pytest.raises(ValueError, match="unknown deployment option"):
        serve.run_config({"applications": [{
            "import_path": "tests.serve_app_fixture:app",
            "deployments": [{"name": "Scaler",
                             "func_or_class": "x:y"}]}]})
    # duplicate app names shadow routes
    with _pytest.raises(ValueError, match="duplicate application"):
        serve.run_config({"applications": [
            {"import_path": "tests.serve_app_fixture:app"},
            {"import_path": "tests.serve_app_fixture:app"}]})
    # a typo'd path is a file error, not a schema error
    with _pytest.raises(FileNotFoundError):
        serve.run_config("/nonexistent/app.yaml")


def test_http_proxy_ingress_backpressure(serve_cluster):
    """The asyncio ingress sheds load with 503 + Retry-After once
    max_ongoing_requests is hit (reference: proxy backpressure), instead
    of queueing unboundedly."""
    import http.client
    import threading as _threading

    @serve.deployment(max_ongoing_requests=16)
    def slow(payload):
        time.sleep(1.0)
        return {"ok": True}

    serve.run(slow.bind())
    port = serve.start_http_proxy(port=0, max_ongoing_requests=2)
    codes = []
    lock = _threading.Lock()

    def hit():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.request("POST", "/", body=json.dumps({}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            with lock:
                codes.append((resp.status,
                              resp.getheader("Retry-After")))
            resp.read()
        finally:
            conn.close()

    threads = [_threading.Thread(target=hit) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    status_codes = [c for c, _ in codes]
    assert status_codes.count(200) >= 2
    assert 503 in status_codes, codes
    assert any(ra == "1" for c, ra in codes if c == 503)


def test_http_proxy_keep_alive(serve_cluster):
    """Two requests ride ONE connection (HTTP/1.1 keep-alive)."""
    import http.client

    @serve.deployment
    def echo2(payload):
        return {"got": payload}

    serve.run(echo2.bind())
    port = serve.start_http_proxy(port=0)
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        for i in range(2):
            conn.request("POST", "/", body=json.dumps({"i": i}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert json.loads(resp.read()) == {"got": {"i": i}}
    finally:
        conn.close()
    proxy = serve.api._http_server
    assert proxy.stats["requests"] >= 2


# ---------------------------------------------------------------------------
# Queue-depth routing + metrics-driven autoscaling (ISSUE 9: the serving
# tier must be measurable and reactive under open-loop load)
# ---------------------------------------------------------------------------

def test_router_rng_not_in_lockstep():
    """Every handle family seeds its P2C rng from urandom: a FIXED seed
    marched independent client processes through identical replica
    pairs (the herd all picks the same victim); ``seed=`` keeps tests
    deterministic."""
    from ray_tpu.serve.router import _HandleState

    s1 = _HandleState("d", None)
    s2 = _HandleState("d", None)
    assert [s1.rng.random() for _ in range(8)] != \
           [s2.rng.random() for _ in range(8)]
    a = _HandleState("d", None, seed=5)
    b = _HandleState("d", None, seed=5)
    assert [a.rng.random() for _ in range(8)] == \
           [b.rng.random() for _ in range(8)]


def test_replica_reports_callable_queue_depth(serve_cluster):
    """The optional ``queue_depth()`` protocol (an LLM engine's waiting
    queue) rides the existing report_metrics push into the
    controller's depth view."""

    @serve.deployment(num_replicas=1)
    class WithBacklog:
        def queue_depth(self):
            return 7

        def __call__(self):
            return "ok"

    handle = serve.run(WithBacklog.bind())
    assert handle.remote().result() == "ok"
    controller = ray_tpu.get_actor("serve_controller")
    deadline = time.time() + 10
    d = {}
    while time.time() < deadline:
        d = ray_tpu.get(controller.get_depths.remote("WithBacklog"))
        if d["depths"] and d["depths"][0] >= 7:
            break
        time.sleep(0.2)
    assert d["depths"] and d["depths"][0] >= 7, d


def test_depth_snapshot_published_on_long_poll(serve_cluster):
    """Routers learn depths from the ``depths::<name>`` long-poll key,
    versioned against the membership snapshot they score."""

    @serve.deployment(num_replicas=2)
    def echo3(x):
        return x

    handle = serve.run(echo3.bind())
    assert handle.remote(1).result() == 1
    controller = ray_tpu.get_actor("serve_controller")
    deadline = time.time() + 10
    snap = {}
    while time.time() < deadline:
        snap = ray_tpu.get(controller.listen_for_change.remote(
            {"depths::echo3": -1}))
        if snap.get("depths::echo3"):
            break
    entry = snap["depths::echo3"]["snapshot"]
    assert len(entry["depths"]) == 2
    d = ray_tpu.get(controller.get_depths.remote("echo3"))
    assert entry["version"] <= d["version"]
    # the depth gauge landed in the (in-process) controller's registry
    from ray_tpu.util.metrics import registry
    assert "ray_tpu_serve_replica_depth" in registry()


def test_stalled_replica_stops_receiving_new_requests(
        serve_cluster, tmp_path):
    """The ISSUE 9 routing criterion: once a replica's REPORTED depth
    rises (here: a wedged engine reporting queue backlog through the
    ``queue_depth()`` protocol), a FRESH handle — an independent client
    with no local in-flight knowledge, which a fixed-seed local-only
    router could never steer — routes around it."""
    claim = str(tmp_path / "slow.claim")

    @serve.deployment(num_replicas=2, max_ongoing_requests=8)
    class HalfStalled:
        def __init__(self, claim_path):
            import os as _os
            try:
                fd = _os.open(claim_path,
                              _os.O_CREAT | _os.O_EXCL | _os.O_WRONLY)
                _os.close(fd)
                self.role = "slow"      # first replica claims the stall
            except FileExistsError:
                self.role = "fast"

        def queue_depth(self):
            # the stalled replica's engine backlog keeps growing; the
            # healthy one stays empty
            return 50 if self.role == "slow" else 0

        def __call__(self):
            if self.role == "slow":
                time.sleep(8.0)
            return self.role

    serve.run(HalfStalled.bind(claim))
    controller = ray_tpu.get_actor("serve_controller")
    deadline = time.time() + 10
    d = {"depths": []}
    while time.time() < deadline:
        d = ray_tpu.get(controller.get_depths.remote("HalfStalled"))
        if d["depths"] and max(d["depths"]) >= 50:
            break
        time.sleep(0.2)
    assert d["depths"] and max(d["depths"]) >= 50, d

    # an INDEPENDENT client: fresh handle, empty local in-flight table
    fresh = serve.get_deployment_handle("HalfStalled")
    fresh._state.ensure_long_poll()
    fresh._refresh()
    deadline = time.time() + 10
    while time.time() < deadline:
        with fresh._state.lock:
            if fresh._state.depths and max(fresh._state.depths) >= 50:
                break
        time.sleep(0.1)
    with fresh._state.lock:
        assert fresh._state.depths, "depth snapshot never arrived"
    served = [fresh.remote().result(timeout=4) for _ in range(12)]
    assert served == ["fast"] * 12, served


def test_downscale_drains_in_flight_requests(serve_cluster):
    """Scale-down must not burn in-flight work: routers stop picking
    the victim at the membership publish, the kill waits for its
    reported load to drain."""

    @serve.deployment(num_replicas=2, graceful_shutdown_timeout_s=20.0)
    class Slow:
        def __call__(self, t=2.0):
            time.sleep(t)
            return "done"

    handle = serve.run(Slow.bind())
    resps = [handle.remote(2.0) for _ in range(4)]
    time.sleep(0.3)          # land on both replicas
    controller = ray_tpu.get_actor("serve_controller")
    ray_tpu.get(controller.set_target_replicas.remote("Slow", 1))
    assert [r.result(timeout=30) for r in resps] == ["done"] * 4
    deadline = time.time() + 15
    st = {}
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote())["Slow"]
        if st["num_replicas"] == 1:
            break
        time.sleep(0.2)
    assert st["num_replicas"] == 1, st


def test_autoscale_one_to_n_to_one_under_open_loop_load(serve_cluster):
    """ISSUE 9 acceptance: a loadgen run visibly drives 1->N replica
    scale-up, and load-off decays back to min without burning any
    in-flight request."""
    import threading as _th

    from ray_tpu.loadgen import SLO, HandleTarget, LoadSpec, run_load

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0,
        "upscale_delay_s": 0.0, "downscale_delay_s": 0.5,
        "downscale_queue_guard_s": 0.0},
        max_ongoing_requests=8, graceful_shutdown_timeout_s=15.0)
    def slowish(payload):
        time.sleep(0.4)
        return {"ok": True}

    handle = serve.run(slowish.bind())
    controller = ray_tpu.get_actor("serve_controller")

    peak = {"target": 1, "replicas": 1}
    stop = _th.Event()

    def watch():
        while not stop.is_set():
            st = ray_tpu.get(controller.status.remote())["slowish"]
            peak["target"] = max(peak["target"], st["target_replicas"])
            peak["replicas"] = max(peak["replicas"], st["num_replicas"])
            time.sleep(0.2)

    watcher = _th.Thread(target=watch, daemon=True)
    watcher.start()
    try:
        spec = LoadSpec(rate=12, duration_s=5, clients=16,
                        arrival="constant", stream=False, seed=0,
                        slo=SLO(e2e_s=60.0), timeout_s=60,
                        drain_timeout_s=120)
        report = run_load(
            HandleTarget(handle, stream=False, timeout_s=60), spec)
    finally:
        stop.set()
        watcher.join(timeout=5)
    # load on -> replicas grew toward max
    assert peak["target"] >= 2, peak
    assert peak["replicas"] >= 2, peak
    # no request burned by scale churn
    assert report["requests"]["errors"] == 0, report.get("error_samples")
    assert report["requests"]["completed"] == report["scheduled_requests"]
    # load off -> decay to min without killing anything mid-flight
    deadline = time.time() + 25
    st = {}
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote())["slowish"]
        if st["target_replicas"] == 1 and st["num_replicas"] == 1:
            break
        time.sleep(0.3)
    assert st["target_replicas"] == 1 and st["num_replicas"] == 1, st
    # autoscale introspection surfaced the decision inputs
    assert "total_load" in st["autoscale"] and "desired" in st["autoscale"]


def test_depth_gauge_series_cleared_on_downscale(serve_cluster):
    """A downscaled slot's ray_tpu_serve_replica_depth series must
    disappear from the registry, not report its last depth forever."""
    from ray_tpu.util.metrics import registry

    @serve.deployment(num_replicas=2)
    def echo4(x):
        return x

    handle = serve.run(echo4.bind())
    assert handle.remote(1).result() == 1

    def gauge_slots():
        g = registry().get("ray_tpu_serve_replica_depth")
        if g is None:
            return set()
        return {dict(key).get("slot") for key, _v in g.samples()
                if dict(key).get("deployment") == "echo4"}

    deadline = time.time() + 10
    while time.time() < deadline and len(gauge_slots()) < 2:
        time.sleep(0.2)
    assert len(gauge_slots()) == 2, gauge_slots()

    controller = ray_tpu.get_actor("serve_controller")
    ray_tpu.get(controller.set_target_replicas.remote("echo4", 1))
    deadline = time.time() + 15
    while time.time() < deadline and len(gauge_slots()) != 1:
        time.sleep(0.2)
    assert len(gauge_slots()) == 1, gauge_slots()


def test_idle_deployment_downscales_despite_cluster_pressure(
        serve_cluster):
    """The federated queue-pressure guard is CLUSTER-wide: it must not
    veto downscale of a deployment that itself reports zero load, or an
    unrelated batch sweep pins every idle serve app at peak."""
    import threading as _th

    from ray_tpu.util.metrics import Histogram

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 2,
        "target_ongoing_requests": 1.0,
        "upscale_delay_s": 0.0, "downscale_delay_s": 0.2,
        "downscale_queue_guard_s": 0.5})
    def idleapp(x):
        return x

    handle = serve.run(idleapp.bind())
    assert handle.remote(1).result() == 1
    controller = ray_tpu.get_actor("serve_controller")
    ray_tpu.get(controller.set_target_replicas.remote("idleapp", 2))

    # an unrelated workload keeps the cluster-wide queue-phase mean
    # far above the guard for the whole window
    stop = _th.Event()

    def pressure():
        h = Histogram("ray_tpu_task_phase_seconds",
                      "task phase seconds")
        while not stop.is_set():
            h.observe(2.0, tags={"phase": "queue"})
            time.sleep(0.05)

    t = _th.Thread(target=pressure, daemon=True)
    t.start()
    try:
        deadline = time.time() + 20
        st = {}
        while time.time() < deadline:
            st = ray_tpu.get(controller.status.remote())["idleapp"]
            if st["target_replicas"] == 1 and st["num_replicas"] == 1:
                break
            time.sleep(0.3)
    finally:
        stop.set()
        t.join(timeout=5)
    assert st["target_replicas"] == 1 and st["num_replicas"] == 1, st
