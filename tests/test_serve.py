"""Serve: deployments, routing, composition, batching, autoscaling, HTTP."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cluster(ray_start_regular):
    yield ray_start_regular
    serve.shutdown()


def test_function_deployment(serve_cluster):
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    assert handle.remote("hi").result() == {"echo": "hi"}


def test_class_deployment_with_state(serve_cluster):
    @serve.deployment(num_replicas=1)
    class Counter:
        def __init__(self, start):
            self.n = start

        def __call__(self):
            self.n += 1
            return self.n

        def peek(self):
            return self.n

    handle = serve.run(Counter.bind(10))
    assert handle.remote().result() == 11
    assert handle.remote().result() == 12
    assert handle.peek.remote().result() == 12


def test_multi_replica_round_robin(serve_cluster):
    import os
    import threading

    @serve.deployment(num_replicas=3)
    class Who:
        def __init__(self):
            self.id = id(self)

        def __call__(self):
            return self.id

    handle = serve.run(Who.bind())
    seen = {handle.remote().result() for _ in range(30)}
    assert len(seen) >= 2  # p2c spreads over replicas


def test_model_composition(serve_cluster):
    @serve.deployment
    class Preprocessor:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Model:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            doubled = self.pre.remote(x).result()
            return doubled + 1

    handle = serve.run(Model.bind(Preprocessor.bind()))
    assert handle.remote(5).result() == 11


def test_dynamic_batching(serve_cluster):
    @serve.deployment(max_ongoing_requests=16)
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        def handle(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        def __call__(self, x):
            return self.handle(x)

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind())
    resps = [handle.remote(i) for i in range(8)]
    assert sorted(r.result() for r in resps) == [i * 10 for i in range(8)]
    sizes = handle.sizes.remote().result()
    assert max(sizes) > 1  # batching actually happened


def test_reconfigure_user_config(serve_cluster):
    @serve.deployment(user_config={"k": 1})
    class Cfg:
        def __init__(self):
            self.k = None

        def reconfigure(self, cfg):
            self.k = cfg["k"]

        def __call__(self):
            return self.k

    handle = serve.run(Cfg.bind())
    assert handle.remote().result() == 1
    controller = ray_tpu.get_actor("serve_controller")
    ray_tpu.get(controller.reconfigure_deployment.remote("Cfg", {"k": 9}))
    assert handle.remote().result() == 9


def test_replica_failure_recovery(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Fragile:
        def __call__(self):
            return "ok"

    handle = serve.run(Fragile.bind())
    assert handle.remote().result() == "ok"
    controller = ray_tpu.get_actor("serve_controller")
    replicas = ray_tpu.get(
        controller.get_replicas.remote("Fragile"))["replicas"]
    ray_tpu.kill(replicas[0])
    deadline = time.time() + 15
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote())["Fragile"]
        if st["num_replicas"] == 2 and st["version"] >= 2:
            break
        time.sleep(0.3)
    st = ray_tpu.get(controller.status.remote())["Fragile"]
    assert st["num_replicas"] == 2
    # traffic still works after recovery
    handle._refresh(force=True)
    assert handle.remote().result() == "ok"


def test_push_metrics_feed_controller(serve_cluster):
    """Replicas PUSH their metrics (reference: autoscaling_state.py) —
    the controller's cache fills without it ever polling, and a killed
    replica's zombie reporter cannot keep its slot looking healthy."""

    @serve.deployment(num_replicas=2)
    class Svc:
        def __call__(self):
            return "ok"

    handle = serve.run(Svc.bind())
    assert handle.remote().result() == "ok"
    controller = ray_tpu.get_actor("serve_controller")

    deadline = time.time() + 10
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote())["Svc"]
        if st["metrics_fresh"] == 2:
            break
        time.sleep(0.2)
    assert st["metrics_fresh"] == 2, st

    # kill one replica: its Replica instance (and reporter thread) lives
    # on in-process, but its reports must be rejected/stopped so the
    # slot goes stale, the death is detected, and a replacement lands
    replicas = ray_tpu.get(controller.get_replicas.remote("Svc"))["replicas"]
    dead_id = replicas[0]._actor_id
    ray_tpu.kill(replicas[0])
    deadline = time.time() + 20
    recovered = False
    while time.time() < deadline:
        reps = ray_tpu.get(controller.get_replicas.remote("Svc"))
        ids = [r._actor_id for r in reps["replicas"]]
        if len(ids) == 2 and dead_id not in ids:
            recovered = True
            break
        time.sleep(0.3)
    assert recovered, "dead replica was never replaced"
    handle._refresh(force=True)
    assert handle.remote().result() == "ok"


def test_autoscaling_up(serve_cluster):
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0})
    class Slow:
        def __call__(self):
            time.sleep(1.0)
            return "done"

    handle = serve.run(Slow.bind())
    resps = [handle.remote() for _ in range(6)]
    controller = ray_tpu.get_actor("serve_controller")
    deadline = time.time() + 10
    scaled = False
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote())["Slow"]
        if st["target_replicas"] > 1:
            scaled = True
            break
        time.sleep(0.2)
    assert scaled
    for r in resps:
        assert r.result(timeout=30) == "done"


def test_http_proxy(serve_cluster):
    @serve.deployment
    def app(payload):
        return {"got": payload}

    serve.run(app.bind())
    port = serve.start_http_proxy(port=0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=json.dumps({"a": 1}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert body == {"got": {"a": 1}}


def test_serve_status_and_delete(serve_cluster):
    @serve.deployment(num_replicas=2)
    def f(x):
        return x

    serve.run(f.bind())
    st = serve.status()
    assert st["f"]["num_replicas"] == 2
    serve.delete("default")
    assert "f" not in serve.status()


def test_local_testing_mode():
    """No cluster needed: the app graph runs in-process."""
    @serve.deployment
    class Pre:
        def __call__(self, x):
            return x + 1

    @serve.deployment(user_config={"scale": 10})
    class Model:
        def __init__(self, pre):
            self.pre = pre
            self.scale = 1

        def reconfigure(self, cfg):
            self.scale = cfg["scale"]

        def __call__(self, x):
            return self.pre.remote(x).result() * self.scale

    handle = serve.run(Model.bind(Pre.bind()), local_testing_mode=True)
    assert handle.remote(4).result() == 50


# ---------------------------------------------------------------------------
# Streaming responses (reference: replica.py:1028 handle_request_streaming,
# proxy.py:1009 streaming proxy path)
# ---------------------------------------------------------------------------

def test_streaming_handle_first_chunk_before_completion(serve_cluster):
    """The defining property of streaming: chunk 1 is consumable BEFORE
    the replica's generator has finished producing."""

    @serve.deployment
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                time.sleep(0.2)
                yield {"i": i}

    handle = serve.run(Streamer.bind())
    t0 = time.monotonic()
    gen = handle.options(stream=True).remote(5)
    first = next(iter(gen))
    t_first = time.monotonic() - t0
    rest = list(gen)
    t_all = time.monotonic() - t0
    assert first == {"i": 0}
    assert [c["i"] for c in rest] == [1, 2, 3, 4]
    # 5 chunks x 0.2s ~= 1.0s total; the first must arrive well before
    assert t_first < t_all - 0.3, (t_first, t_all)


def test_streaming_non_generator_degrades_to_single_chunk(serve_cluster):
    @serve.deployment
    def plain(x):
        return {"just": x}

    handle = serve.run(plain.bind())
    chunks = list(handle.options(stream=True).remote("one"))
    assert chunks == [{"just": "one"}]


def test_http_proxy_sse_streaming(serve_cluster):
    """SSE through the HTTP proxy: first data: event readable before the
    generator completes (a real TTFT)."""

    @serve.deployment
    class SSEApp:
        def __call__(self, payload):
            n = int(payload.get("n", 3)) if isinstance(payload, dict) else 3
            for i in range(n):
                time.sleep(0.25)
                yield {"chunk": i}

    serve.run(SSEApp.bind())
    port = serve.start_http_proxy(port=0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"n": 4}).encode(),
        headers={"Content-Type": "application/json",
                 "Accept": "text/event-stream"})
    t0 = time.monotonic()
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers["Content-Type"] == "text/event-stream"
        events = []
        t_first = None
        for raw in resp:
            line = raw.decode().strip()
            if not line.startswith("data: "):
                continue
            if t_first is None:
                t_first = time.monotonic() - t0
            body = line[len("data: "):]
            if body == "[DONE]":
                break
            events.append(json.loads(body))
    t_all = time.monotonic() - t0
    assert [e["chunk"] for e in events] == [0, 1, 2, 3]
    assert t_first < t_all - 0.3, (t_first, t_all)


def test_llm_serve_token_streaming(serve_cluster):
    """LLM serving streams engine tokens chunk-by-chunk through Serve."""
    from ray_tpu.llm.serving import LLMConfig, build_llm_app

    app = build_llm_app(LLMConfig(max_slots=2, max_seq=128))
    handle = serve.run(app)
    gen = handle.options(stream=True).remote(
        {"prompt": "hello", "max_tokens": 8, "stream": True})
    chunks = list(gen)
    assert chunks[-1].get("done") is True
    deltas = [c for c in chunks if "delta" in c]
    assert 1 <= len(deltas) <= 8
    assert chunks[-1]["usage"]["completion_tokens"] == len(deltas)


# ---------------------------------------------------------------------------
# Declarative app config (reference: serve/schema.py + `serve deploy`)
# ---------------------------------------------------------------------------

def test_run_config_from_yaml(serve_cluster, tmp_path):
    cfg = tmp_path / "app.yaml"
    cfg.write_text("""
applications:
  - name: default
    import_path: tests.serve_app_fixture:app
    deployments:
      - name: Scaler
        num_replicas: 2
        user_config: {factor: 5}
""")
    handles = serve.run_config(str(cfg))
    assert set(handles) == {"default"}
    # user_config override applied via reconfigure
    assert handles["default"].remote(10).result(timeout=30) == 50
    st = serve.status()
    dep = st["Scaler"]
    assert dep["target_replicas"] == 2


def test_run_config_builder_with_args(serve_cluster):
    handles = serve.run_config({
        "applications": [{
            "name": "built",
            "import_path": "tests.serve_app_fixture:build_app",
            "args": {"factor": 7},
        }]})
    assert handles["built"].remote(3).result(timeout=30) == 21


def test_run_config_rejects_bad_entries(serve_cluster, tmp_path):
    with pytest.raises(ValueError, match="applications"):
        serve.run_config({"nope": []})
    with pytest.raises(ValueError, match="unknown deployment option"):
        serve.run_config({"applications": [{
            "import_path": "tests.serve_app_fixture:app",
            "deployments": [{"name": "Scaler", "bogus_knob": 1}]}]})


def test_run_config_validation_errors(serve_cluster):
    import pytest as _pytest

    # typo'd deployment name must raise, not silently no-op
    with _pytest.raises(ValueError, match="match no deployment"):
        serve.run_config({"applications": [{
            "import_path": "tests.serve_app_fixture:app",
            "deployments": [{"name": "Sclaer", "num_replicas": 9}]}]})
    # override entry without a name
    with _pytest.raises(ValueError, match="missing 'name'"):
        serve.run_config({"applications": [{
            "import_path": "tests.serve_app_fixture:app",
            "deployments": [{"num_replicas": 2}]}]})
    # internal fields are not part of the declarative surface
    with _pytest.raises(ValueError, match="unknown deployment option"):
        serve.run_config({"applications": [{
            "import_path": "tests.serve_app_fixture:app",
            "deployments": [{"name": "Scaler",
                             "func_or_class": "x:y"}]}]})
    # duplicate app names shadow routes
    with _pytest.raises(ValueError, match="duplicate application"):
        serve.run_config({"applications": [
            {"import_path": "tests.serve_app_fixture:app"},
            {"import_path": "tests.serve_app_fixture:app"}]})
    # a typo'd path is a file error, not a schema error
    with _pytest.raises(FileNotFoundError):
        serve.run_config("/nonexistent/app.yaml")


def test_http_proxy_ingress_backpressure(serve_cluster):
    """The asyncio ingress sheds load with 503 + Retry-After once
    max_ongoing_requests is hit (reference: proxy backpressure), instead
    of queueing unboundedly."""
    import http.client
    import threading as _threading

    @serve.deployment(max_ongoing_requests=16)
    def slow(payload):
        time.sleep(1.0)
        return {"ok": True}

    serve.run(slow.bind())
    port = serve.start_http_proxy(port=0, max_ongoing_requests=2)
    codes = []
    lock = _threading.Lock()

    def hit():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.request("POST", "/", body=json.dumps({}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            with lock:
                codes.append((resp.status,
                              resp.getheader("Retry-After")))
            resp.read()
        finally:
            conn.close()

    threads = [_threading.Thread(target=hit) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    status_codes = [c for c, _ in codes]
    assert status_codes.count(200) >= 2
    assert 503 in status_codes, codes
    assert any(ra == "1" for c, ra in codes if c == 503)


def test_http_proxy_keep_alive(serve_cluster):
    """Two requests ride ONE connection (HTTP/1.1 keep-alive)."""
    import http.client

    @serve.deployment
    def echo2(payload):
        return {"got": payload}

    serve.run(echo2.bind())
    port = serve.start_http_proxy(port=0)
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        for i in range(2):
            conn.request("POST", "/", body=json.dumps({"i": i}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert json.loads(resp.read()) == {"got": {"i": i}}
    finally:
        conn.close()
    proxy = serve.api._http_server
    assert proxy.stats["requests"] >= 2
