"""Tier-1 units for graceful degradation under memory pressure
(docs/fault_tolerance.md "Memory pressure & graceful degradation"):

- watermark parsing and the pure level-fusion rule;
- the PressureController's failpoint seam (``pressure.level``) and
  relief transitions;
- arena spill candidacy (pins always win, the spill-dir budget bounds
  disk), restore idempotency and the failpoint-armed degrade-to-disk
  read path;
- hard-level typed rejection of NEW puts and the driver's RetryPolicy
  ride to success after relief;
- tenant-preferring OOM preemption order (TenantAwarePolicy);
- pick_node soft-exclusion of hard-pressure nodes (DRAINING's peer).

The ballast-driven end-to-end campaign lives in the chaos tier:
tests/test_chaos.py::test_chaos_memory_*.
"""

import os
import time
import uuid

import pytest

import ray_tpu
from ray_tpu._private import failpoints as fp
from ray_tpu._private.pressure import (
    HOST_SOFT_MARGIN,
    PressureController,
    compute_level,
    parse_watermarks,
)
from ray_tpu.native_store import available

needs_native = pytest.mark.skipif(
    not available(), reason="native store unavailable (no compiler)")

CAP = 4 * 1024 * 1024
BIG = 512 * 1024        # comfortably above the inline threshold


@pytest.fixture(autouse=True)
def _reset_failpoints():
    yield
    fp.reset()


# ---------------------------------------------------------------------------
# watermark math (pure)
# ---------------------------------------------------------------------------

def test_parse_watermarks():
    assert parse_watermarks("0.70,0.85") == (0.70, 0.85)
    assert parse_watermarks("0.5,0.5") == (0.5, 0.5)
    # malformed input falls back to the defaults, never disables
    for bad in ("", "nope", "0.9", "0.9,0.2", "0,1", "1.2,1.5"):
        assert parse_watermarks(bad) == (0.70, 0.85)


def test_compute_level_fusion():
    wm = dict(wm_soft=0.70, wm_hard=0.85, host_threshold=0.95)
    assert compute_level(0.0, 0.0, 0.0, **wm) == "ok"
    # host RSS inside the soft margin of the kill threshold -> soft
    assert compute_level(0.95 - HOST_SOFT_MARGIN, 0.0, 0.0, **wm) == "soft"
    # arena over its soft watermark -> soft
    assert compute_level(0.0, 0.70, 0.0, **wm) == "soft"
    # host at the kill threshold -> hard (the monitor is about to shoot)
    assert compute_level(0.95, 0.0, 0.0, **wm) == "hard"
    # arena at the hard watermark -> hard
    assert compute_level(0.0, 0.85, 0.0, **wm) == "hard"
    # arena soft-full while the spill budget is exhausted: nowhere left
    # to degrade to -> hard
    assert compute_level(0.0, 0.70, 1.0, **wm) == "hard"
    # exhausted budget alone (arena comfortable) is NOT pressure
    assert compute_level(0.0, 0.10, 1.0, **wm) == "ok"


# ---------------------------------------------------------------------------
# PressureController: failpoint seam, transitions, relief
# ---------------------------------------------------------------------------

class _StubObjects:
    """Just enough ObjectTable surface for a controller."""

    capacity = 100
    spill_budget = 0
    _shm = None

    def __init__(self):
        self.spill_calls = 0

    def spilled_bytes(self):
        return 0

    def spill_to_fraction(self, target):
        self.spill_calls += 1
        return 0


def test_controller_failpoint_override_then_relief():
    """``pressure.level=return(hard):max=2`` forces two hard ticks (the
    chaos-script idiom — no real ballast); the third tick recomputes
    from the (quiet) fractions and relieves back to ok. Every non-ok
    tick runs a proactive spill pass; transitions invoke on_level."""
    objects = _StubObjects()
    seen = []
    ctl = PressureController(objects, monitor=None, tick_s=60.0,
                             watermarks="0.70,0.85", host_threshold=0.95,
                             on_level=lambda old, new: seen.append((old,
                                                                    new)))
    fp.activate("pressure.level=return(hard):max=2")
    assert ctl.tick() == "hard"
    assert ctl.tick() == "hard"
    assert objects.spill_calls == 2     # proactive degradation ran
    assert ctl.tick() == "ok"           # arm exhausted: relief
    assert seen == [("ok", "hard"), ("hard", "ok")]


def test_controller_drop_arm_skips_tick():
    objects = _StubObjects()
    ctl = PressureController(objects, monitor=None, tick_s=60.0)
    ctl.level = "soft"
    fp.activate("pressure.level=drop")
    assert ctl.tick() == "soft"         # tick skipped: level unchanged
    assert objects.spill_calls == 0


def test_controller_fractions_arena_signal():
    """Arena occupancy feeds the fusion directly (no monitor, no
    budget): past the hard watermark the level goes hard and the
    proactive pass runs."""

    class _FullArena:
        def used_bytes(self):
            return 90

    objects = _StubObjects()
    objects._shm = _FullArena()
    ctl = PressureController(objects, monitor=None, tick_s=60.0,
                             watermarks="0.70,0.85", host_threshold=0.95)
    assert ctl.fractions() == (0.0, 0.90, 0.0)
    assert ctl.tick() == "hard"
    assert objects.spill_calls == 1


# ---------------------------------------------------------------------------
# arena spill: pins win, budget bounds disk, restore idempotency
# ---------------------------------------------------------------------------

@pytest.fixture
def table(tmp_path):
    from ray_tpu._private.daemon import ObjectTable
    t = ObjectTable(f"rtpu_p_{os.getpid()}_{uuid.uuid4().hex[:8]}",
                    CAP, sweep=False, spill_dir=str(tmp_path))
    if t._shm is None:
        t.close()
        pytest.skip("arena creation failed on this box")
    try:
        yield t
    finally:
        t.close()


@needs_native
def test_spill_skips_pinned(table):
    """An entry with an outstanding external slot ref (a held zero-copy
    view) is NEVER spilled — a full-sweep pass parks the cold unpinned
    entry and leaves the pinned one resident, bytes intact."""
    table.put(b"pinned", b"p" * BIG)
    table.put(b"cold", b"c" * BIG)
    meta = table.get_ext_meta(b"pinned", "w:1:1")
    assert meta is not None
    table.spill_to_fraction(0.0)
    stats = table.spill_stats()
    assert stats["spill_skipped_pinned"] >= 1
    assert b"pinned" not in table._spilled
    assert b"cold" in table._spilled
    assert table.get_blob(b"pinned") == b"p" * BIG
    # release the view: the next pass may park it
    table.ext_release(meta[4], "w:1:1")
    table.spill_to_fraction(0.0)
    assert b"pinned" in table._spilled
    assert table.get_blob(b"pinned") == b"p" * BIG   # restores


@needs_native
def test_spill_budget_bounds_disk(table):
    """The spill-dir budget stops the pass: disk consumption never
    exceeds arena_spill_budget_bytes (the level goes hard instead —
    compute_level's budget-exhausted clause)."""
    table.spill_budget = BIG + 1024     # room for exactly one entry
    table.put(b"one", b"1" * BIG)
    table.put(b"two", b"2" * BIG)
    table.spill_to_fraction(0.0)
    assert table.spilled_bytes() <= table.spill_budget
    assert table.spill_stats()["spilled_now_count"] == 1


@needs_native
def test_restore_idempotent_and_failpoint_degrades_to_disk_read(table):
    """arena.restore drop arm: the restore attempt fails but the READ
    still succeeds straight off the spill file (reads never miss); the
    next read retries the restore and flips the tier back. A repeated
    restore of a resident entry is a no-op success."""
    table.put(b"obj", b"z" * BIG)
    table.spill_to_fraction(0.0)
    assert b"obj" in table._spilled
    path = table._spilled[b"obj"][0]
    assert os.path.exists(path)

    fp.activate("arena.restore=drop:max=1")
    assert table.get_blob(b"obj") == b"z" * BIG     # disk-read degrade
    assert b"obj" in table._spilled                 # still parked
    assert table.spill_stats()["restore_failed"] == 1
    assert os.path.exists(path)     # failed attempt consumed nothing

    assert table.get_blob(b"obj") == b"z" * BIG     # retried restore
    assert b"obj" not in table._spilled
    stats = table.spill_stats()
    assert stats["restores"] == 1
    assert stats["restored_bytes"] == BIG
    assert not os.path.exists(path)     # file consumed AFTER the land
    assert table.restore(b"obj") is True            # idempotent
    assert table.spill_stats()["restores"] == 1


@needs_native
def test_spill_failpoint_keeps_entry_resident(table):
    """arena.spill drop arm: the attempt fails, the entry stays at tier
    host-shm, and a later (disarmed) pass parks it."""
    table.put(b"obj", b"q" * BIG)
    fp.activate("arena.spill=drop")
    assert table.spill_to_fraction(0.0) == 0
    assert b"obj" not in table._spilled
    assert table.get_blob(b"obj") == b"q" * BIG
    fp.reset()
    assert table.spill_to_fraction(0.0) == 1
    assert b"obj" in table._spilled


# ---------------------------------------------------------------------------
# hard-level backpressure: typed rejection, retry succeeds after relief
# ---------------------------------------------------------------------------

def test_hard_rejection_is_typed_and_retry_succeeds(monkeypatch):
    """While the daemon's level is hard (failpoint-forced — no real
    ballast), a NEW driver put is rejected with the retriable
    MemoryPressureError; reads still pass. The public ``ray_tpu.put``
    rides RetryPolicy through the remaining hard ticks and lands after
    relief."""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu.exceptions import MemoryPressureError
    monkeypatch.setenv("RAY_TPU_MEMORY_PRESSURE", "1")
    monkeypatch.setenv("RAY_TPU_PRESSURE_TICK_S", "0.05")
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      cluster="daemons")
    try:
        node = rt.nodes()[0]
        handle = next(iter(rt.cluster_backend.daemons.values()))
        pre = ObjectID.from_random()
        node.store.put(pre, b"stored-before-pressure", nbytes=22)

        # the per-node chaos hook (net_chaos's failpoint twin) forces
        # ~2s of hard pressure in THE daemon, then relieves
        handle.client.call(
            "fail_points", spec="pressure.level=return(hard):max=40",
            seed=0)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if handle.client.call("daemon_stats")["pressure"] == "hard":
                break
            time.sleep(0.02)
        else:
            pytest.fail("daemon never reached hard pressure")

        # typed rejection of a NEW put at the transport layer
        with pytest.raises(MemoryPressureError):
            handle.put_object_blob(b"put:rejected", b"x" * 1024)
        # reads always pass under pressure
        assert node.store.get(pre) == b"stored-before-pressure"

        # the store-level put retries until the hard arm exhausts (40
        # ticks x 50ms = ~2s << the 30s policy deadline) and then lands
        oid = ObjectID.from_random()
        node.store.put(oid, b"y" * 2048, nbytes=2048)
        assert node.store.get(oid) == b"y" * 2048
        while time.monotonic() < deadline:
            if handle.client.call("daemon_stats")["pressure"] == "ok":
                break
            time.sleep(0.02)
        else:
            pytest.fail("pressure never relieved after arm exhaustion")
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# tenant-preferring preemption order
# ---------------------------------------------------------------------------

def test_tenant_aware_policy_prefers_over_quota():
    from ray_tpu._private.memory_monitor import (
        RetriableFIFOPolicy,
        TenantAwarePolicy,
        _Candidate,
    )
    cands = [
        _Candidate(1, "task", task_id="a", started_at=5.0,
                   owner_key="job-greedy"),
        _Candidate(2, "task", task_id="b", started_at=9.0,
                   owner_key="job-quiet"),
        _Candidate(3, "task", task_id="c", started_at=7.0,
                   owner_key="job-greedy"),
    ]
    over = set()
    policy = TenantAwarePolicy(RetriableFIFOPolicy(), lambda: over)

    # no tenant over quota: plain host-pressure order (newest first)
    assert policy.pick(cands).task_id == "b"
    assert policy.last_reason == "host"

    # the over-quota tenant's workers go first — newest WITHIN the
    # preferred pool, even though a newer innocent task exists
    over = {"job-greedy"}
    assert policy.pick(cands).task_id == "c"
    assert policy.last_reason == "tenant_quota"

    # no over-quota worker running here: the full pool backstops
    over = {"job-absent"}
    assert policy.pick(cands).task_id == "b"
    assert policy.last_reason == "host"


# ---------------------------------------------------------------------------
# pressure-aware placement
# ---------------------------------------------------------------------------

def _spec(resources):
    from ray_tpu._private.ids import TaskID
    from ray_tpu._private.task_spec import TaskKind, TaskSpec
    return TaskSpec(task_id=TaskID.from_random(), kind=TaskKind.NORMAL,
                    name="t", func=None, resources=resources)


def test_pick_node_soft_excludes_hard_pressure():
    """A hard-pressure node leaves the candidate set like a DRAINING
    one (including cached feasibility sets); when EVERY feasible node
    is pressured the scheduler still places — a pressured node beats a
    failing task."""
    from ray_tpu._private.scheduler import bump_cluster_epoch
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4})
    sched = rt.scheduler
    nodes = rt.nodes()
    for _ in range(5):
        sched.pick_node(_spec({"CPU": 1}), nodes)   # warm the cache

    def _set_level(node, level):
        # what DaemonHandle._on_node_pressure does on a level push:
        # flip the Node and invalidate cached feasibility
        node.pressure_level = level
        bump_cluster_epoch()

    victim = nodes[0]
    _set_level(victim, "hard")
    for _ in range(20):
        assert sched.pick_node(
            _spec({"CPU": 1}), nodes).node_id != victim.node_id
    key = (("CPU", 1.0),)
    # soft pressure does NOT exclude (only hard sheds load): the victim
    # is back in the cached candidate set
    _set_level(victim, "soft")
    sched.pick_node(_spec({"CPU": 1}), nodes)
    assert victim.node_id in {n.node_id for n in sched._feas_cache[key]}
    # all-pressured fallback: placement still succeeds
    for n in nodes:
        _set_level(n, "hard")
    assert sched.pick_node(_spec({"CPU": 1}), nodes) is not None
    # relief restores the full candidate set
    for n in nodes:
        _set_level(n, "ok")
    sched.pick_node(_spec({"CPU": 1}), nodes)
    assert len(sched._feas_cache[key]) == 2
