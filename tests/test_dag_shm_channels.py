"""Compiled DAG cross-process shm channels.

Reference capability: accelerated-DAG mutable-object channels
(`python/ray/experimental/channel/shared_memory_channel.py` +
`compiled_dag_node.py` _do_exec_tasks) — after compile, values flow
worker->worker through pre-allocated shared memory with ZERO per-execute
RPCs or object-store traffic.
"""

import threading

import pytest

import ray_tpu
from ray_tpu.dag import InputNode
from ray_tpu.dag.shm_channel import ChannelFull, ShmChannel


# ---------------------------------------------------------------------------
# channel protocol
# ---------------------------------------------------------------------------

def test_channel_roundtrip_and_backpressure():
    ch = ShmChannel(create=True, capacity=4096)
    try:
        reader = ShmChannel(name=ch.name)
        ch.write("ok", {"x": 1})
        assert reader.read() == ("ok", {"x": 1})
        # depth-1 backpressure: a second write blocks until consumed
        ch.write("ok", 2)
        done = threading.Event()

        def writer():
            ch.write("ok", 3, timeout=30)
            done.set()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        assert not done.wait(0.3)          # blocked on unconsumed slot
        assert reader.read() == ("ok", 2)
        assert done.wait(10)
        assert reader.read() == ("ok", 3)
        reader.close()
    finally:
        ch.close()
        ch.unlink()


def test_channel_capacity_guard():
    ch = ShmChannel(create=True, capacity=128)
    try:
        with pytest.raises(ChannelFull, match="buffer_size_bytes"):
            ch.write("ok", b"x" * 1024)
    finally:
        ch.close()
        ch.unlink()


# ---------------------------------------------------------------------------
# compiled DAG over process workers
# ---------------------------------------------------------------------------

@ray_tpu.remote
class Stage:
    def __init__(self, add):
        self.add = add
        self.calls = 0

    def f(self, x):
        self.calls += 1
        return x + self.add

    def mix(self, x, y):
        self.calls += 1
        return x * 100 + y

    def boom(self, x):
        raise RuntimeError("stage exploded")

    def calls_seen(self):
        return self.calls


@pytest.fixture
def local_pool_runtime():
    """The shm-channel fast path binds DRIVER-POOL actor workers (the
    channels are wired through the driver's WorkerClient dag ops). In
    the daemons topology actors live in daemon-hosted workers and
    compile falls back to the dynamic path — correct behavior, covered
    by test_daemons_mode_falls_back_to_dynamic below — so the channel
    tests pin the local topology explicitly."""
    import ray_tpu
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 8},
                      cluster="local")
    yield rt
    ray_tpu.shutdown()


def test_cross_process_pipeline(local_pool_runtime):
    """Two process-worker actors pipeline through shm channels; per
    execute() NO RPC reaches either worker (call counters frozen)."""
    a, b = Stage.remote(1), Stage.remote(10)
    ray_tpu.get([a.calls_seen.remote(), b.calls_seen.remote()])
    with InputNode() as inp:
        dag = b.f.bind(a.f.bind(inp))
    c = dag.experimental_compile()
    assert c._proc is not None, "process-channel mode did not engage"

    from ray_tpu._private import worker
    rt = worker.global_runtime()
    clients = [rt._actor_executors[x._actor_id].instance._client
               for x in (a, b)]
    calls_before = [cl.calls for cl in clients]
    for i in range(5):
        assert ray_tpu.get(c.execute(i), timeout=60) == i + 11
    assert [cl.calls for cl in clients] == calls_before   # zero RPCs
    # actor state advanced exactly once per execute (no free-running)
    assert ray_tpu.get(a.calls_seen.remote()) == 5
    assert ray_tpu.get(b.calls_seen.remote()) == 5
    c.teardown()
    # workers survive teardown and still serve normal calls
    assert ray_tpu.get(a.f.remote(100)) == 101


def test_fan_out_and_constants(local_pool_runtime):
    """One upstream feeding two consumers plus a mixed-arg stage."""
    from ray_tpu.dag import MultiOutputNode
    a, b, c2 = Stage.remote(1), Stage.remote(2), Stage.remote(0)
    with InputNode() as inp:
        up = a.f.bind(inp)
        dag = MultiOutputNode([b.f.bind(up), c2.mix.bind(up, inp)])
    c = dag.experimental_compile()
    assert c._proc is not None
    out = ray_tpu.get(c.execute(5), timeout=60)
    assert out == [8, 605]            # (5+1)+2 and (5+1)*100+5
    out = ray_tpu.get(c.execute(7), timeout=60)
    assert out == [10, 807]
    c.teardown()


def test_pipelined_rounds_in_order(local_pool_runtime):
    """Back-to-back execute() calls resolve in round order through the
    single ordered finisher (no racing readers on the channels)."""
    a, b = Stage.remote(1), Stage.remote(10)
    with InputNode() as inp:
        dag = b.f.bind(a.f.bind(inp))
    c = dag.experimental_compile()
    assert c._proc is not None
    refs = [c.execute(i) for i in range(4)]
    assert [ray_tpu.get(r, timeout=60) for r in refs] == [11, 12, 13, 14]
    c.teardown()


def test_superseding_compile_and_gc(local_pool_runtime):
    """Recompiling over the same actors supersedes the old loop; GC of
    the STALE CompiledDAG must not kill the new binding."""
    import gc

    a = Stage.remote(5)
    with InputNode() as inp:
        dag = a.f.bind(inp)
    c1 = dag.experimental_compile()
    assert c1._proc is not None
    assert ray_tpu.get(c1.execute(1), timeout=60) == 6
    c2 = dag.experimental_compile()     # supersedes c1's worker loop
    del c1
    gc.collect()                         # stale teardown: generation-scoped no-op
    assert ray_tpu.get(c2.execute(2), timeout=60) == 7
    assert ray_tpu.get(c2.execute(3), timeout=60) == 8
    c2.teardown()


def test_dead_stage_worker_fails_round_promptly(local_pool_runtime):
    """SIGKILL a stage's worker process mid-DAG: the pending round must
    fail with ActorDiedError within seconds, not a 300s channel
    timeout."""
    import os
    import signal
    import time

    from ray_tpu._private import worker as _w

    a, b = Stage.remote(1), Stage.remote(10)
    with InputNode() as inp:
        dag = b.f.bind(a.f.bind(inp))
    c = dag.experimental_compile()
    assert c._proc is not None
    assert ray_tpu.get(c.execute(1), timeout=60) == 12

    rt = _w.global_runtime()
    client = rt._actor_executors[a._actor_id].instance._client
    os.kill(client.proc.pid, signal.SIGKILL)
    t0 = time.time()
    ref = c.execute(2)
    with pytest.raises(Exception, match="died"):
        ray_tpu.get(ref, timeout=120)
    assert time.time() - t0 < 60          # prompt, not channel-timeout
    c.teardown()


def test_stage_error_propagates(local_pool_runtime):
    a, b = Stage.remote(1), Stage.remote(2)
    with InputNode() as inp:
        dag = b.f.bind(a.boom.bind(inp))
    c = dag.experimental_compile()
    assert c._proc is not None
    with pytest.raises(Exception, match="stage exploded"):
        ray_tpu.get(c.execute(1), timeout=60)
    c.teardown()


def test_daemons_mode_falls_back_to_dynamic():
    """Under the wire topology the compiled DAG cannot pre-wire driver
    shm channels to daemon-hosted workers; compile must DEGRADE to the
    dynamic execution path and still produce correct results."""
    import ray_tpu
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons")
    try:
        a, b = Stage.remote(1), Stage.remote(10)
        with InputNode() as inp:
            dag = b.f.bind(a.f.bind(inp))
        c = dag.experimental_compile()
        assert ray_tpu.get(c.execute(5)) == (5 + 1) + 10
        assert ray_tpu.get(c.execute(7)) == 18
    finally:
        ray_tpu.shutdown()
