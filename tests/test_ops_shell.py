"""Ops shell: state API, metrics, dashboard, jobs, autoscaler, CLI,
timeline."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import metrics as rt_metrics
from ray_tpu.util import state as state_api


def test_state_api_lists(ray_start_cluster):
    @ray_tpu.remote
    def f():
        return 1

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.options(name="state_test_actor").remote()
    ray_tpu.get(a.ping.remote())
    ray_tpu.get([f.remote() for _ in range(5)])

    nodes = state_api.list_nodes()
    assert len(nodes) == 4 and all(n["alive"] for n in nodes)
    actors = state_api.list_actors()
    assert any(x["name"] == "state_test_actor" for x in actors)
    tasks = state_api.list_tasks()
    assert len(tasks) >= 5
    summary = state_api.summarize_tasks()
    assert sum(summary.values()) == len(tasks)


def test_timeline_chrome_trace(ray_start_regular, tmp_path):
    @ray_tpu.remote
    def work():
        time.sleep(0.01)
        return 1

    ray_tpu.get([work.remote() for _ in range(3)])
    trace = state_api.timeline()
    named = [s for s in trace if "work" in s["name"]]
    assert len(named) >= 3
    assert all(s["ph"] == "X" and s["dur"] > 0 for s in named)
    path = state_api.timeline(str(tmp_path / "trace.json"))
    assert json.load(open(path))


def test_metrics_prometheus_text(ray_start_regular):
    rt_metrics.clear_registry()
    c = rt_metrics.Counter("my_requests", "test counter", ("route",))
    c.inc(3, tags={"route": "/a"})
    g = rt_metrics.Gauge("my_depth", "test gauge")
    g.set(7.5)
    h = rt_metrics.Histogram("my_lat", "test hist", boundaries=(1, 10))
    h.observe(0.5)
    h.observe(5)
    h.observe(50)
    text = rt_metrics.prometheus_text()
    assert 'my_requests{route="/a"} 3.0' in text
    assert "my_depth 7.5" in text
    assert "my_lat_count 3" in text
    assert "ray_tpu_tasks_finished" in text


def test_dashboard_endpoints(ray_start_regular):
    from ray_tpu.dashboard.server import start_dashboard, stop_dashboard

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    host, port = start_dashboard(port=0)
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://{host}:{port}{path}", timeout=10) as r:
                return r.read().decode()
        nodes = json.loads(get("/api/nodes"))
        assert len(nodes) == 1
        status = json.loads(get("/api/cluster_status"))
        assert status["stats"]["tasks_finished"] >= 1
        assert "ray_tpu_tasks_finished" in get("/metrics")
        assert json.loads(get("/api/timeline"))
        from ray_tpu._private.config import cfg
        config = json.loads(get("/api/config"))
        assert config["pull_chunk"]["value"] == cfg().pull_chunk
        assert "source" in config["memory_monitor"]
        # library observability endpoints (reference: dashboard
        # serve/train/data modules)
        from ray_tpu import data as _data
        (_data.from_items([{"x": i} for i in range(6)])
         .map(lambda r: r).take_all())   # executor path records stats
        ds_stats = json.loads(get("/api/data"))
        assert ds_stats["datasets"], "dataset stats not surfaced"
        train = json.loads(get("/api/train"))
        assert "train_runs" in train
        serve_state = json.loads(get("/api/serve"))
        assert "applications" in serve_state or serve_state == {}
    finally:
        stop_dashboard()


def test_job_submission(ray_start_regular):
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint="echo hello_from_job && echo line2")
    status = client.wait_until_finished(job_id, timeout=30)
    assert status == "SUCCEEDED"
    logs = client.get_job_logs(job_id)
    assert "hello_from_job" in logs and "line2" in logs

    bad = client.submit_job(entrypoint="exit 3")
    assert client.wait_until_finished(bad, timeout=30) == "FAILED"
    assert client.get_job_info(bad).returncode == 3
    assert len(client.list_jobs()) == 2


def test_autoscaler_scales_up_and_down(ray_start_regular):
    from ray_tpu._private import worker as _worker
    from ray_tpu.autoscaler import FakeNodeProvider, StandardAutoscaler

    rt = _worker.global_runtime()
    provider = FakeNodeProvider(rt, {"CPU": 4})
    scaler = StandardAutoscaler(rt, provider, min_nodes=1, max_nodes=4,
                                idle_timeout_s=0.5)

    # more parallel work than one 8-CPU node can run
    @ray_tpu.remote(num_cpus=4)
    def slow():
        time.sleep(1.5)
        return 1

    refs = [slow.remote() for _ in range(6)]  # 24 CPUs of demand
    time.sleep(0.2)
    scaler.update()
    assert scaler.stats["launched"] >= 1
    scaler.update()
    launched = scaler.stats["launched"]
    assert launched >= 2
    assert ray_tpu.get(refs, timeout=60) == [1] * 6
    # idle: scale back down
    deadline = time.time() + 15
    while time.time() < deadline and provider.non_terminated_nodes():
        scaler.update()
        time.sleep(0.3)
    assert not provider.non_terminated_nodes()


def test_cli_status_and_summary(ray_start_regular, capsys):
    from ray_tpu.scripts.cli import main

    assert main(["status"]) == 0
    out = capsys.readouterr().out
    assert "cluster_resources" in out
    assert main(["summary"]) == 0


def test_microbenchmark_harness(ray_start_regular):
    from ray_tpu._private.perf import run_microbenchmarks

    results = run_microbenchmarks(duration_s=0.3)
    names = {r["name"] for r in results}
    assert "tasks_per_second" in names
    assert all(r["throughput_per_s"] > 0 for r in results)


def test_debug_state_and_loop_instrumentation(ray_start_regular):
    from ray_tpu._private import worker as _worker

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(5)])
    rt = _worker.global_runtime()
    state = rt.debug_state()
    assert "loop=" in state and "tasks_launched" in state
    node = rt.nodes()[0]
    assert node.loop_stats["tasks_launched"] >= 5
    assert node.loop_stats["max_queue_lag_ms"] >= 0


def test_tracing_spans(ray_start_regular):
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def traced_work():
        return 1

    tracing.enable_tracing()
    try:
        ray_tpu.get([traced_work.remote() for _ in range(3)])
        spans = tracing.get_spans()
        named = [s for s in spans if "traced_work" in s["name"]]
        assert len(named) >= 3
        assert all(s["end_ns"] > s["start_ns"] for s in named)
        assert tracing.chrome_trace()
    finally:
        tracing.disable_tracing()
        tracing.clear_spans()


def test_gcs_kv_snapshot_restore(ray_start_regular, tmp_path):
    from ray_tpu._private import worker as _worker

    rt = _worker.global_runtime()
    rt.gcs.kv_put(b"cfg", b"value1")
    path = rt.gcs.snapshot(str(tmp_path / "gcs.snap"))

    rt.gcs.kv_del(b"cfg")
    assert rt.gcs.kv_get(b"cfg") is None
    rt.gcs.restore(path)
    assert rt.gcs.kv_get(b"cfg") == b"value1"


def test_tqdm_ray(capsys):
    from ray_tpu.experimental.tqdm_ray import tqdm

    out = []
    for x in tqdm(range(5), desc="test", flush_period_s=0):
        out.append(x)
    assert out == [0, 1, 2, 3, 4]


def test_iter_torch_batches(ray_start_regular):
    from ray_tpu import data as rdata
    import torch

    ds = rdata.range(20, parallelism=2)
    batches = list(ds.iter_torch_batches(batch_size=10))
    assert len(batches) == 2
    assert isinstance(batches[0]["id"], torch.Tensor)
    vals = sorted(int(x) for b in batches for x in b["id"])
    assert vals == list(range(20))


# ---------------------------------------------------------------------------
# Autoscaler v2: GCS-state reconciler + instance lifecycle (VERDICT r1 #10)
# ---------------------------------------------------------------------------

def test_autoscaler_v2_scales_up_for_tpu_demand(ray_start_regular):
    """A pending PG demanding a TPU slice drives the reconciler to
    provision the smallest covering slice type, with the full instance
    state machine recorded."""
    import ray_tpu
    from ray_tpu.autoscaler_v2 import (InstanceStatus, Reconciler,
                                       RuntimeBackedTpuProvider)
    from ray_tpu.util.placement_group import placement_group

    rt = ray_tpu._private.worker.global_runtime()
    provider = RuntimeBackedTpuProvider(rt)
    rec = Reconciler(rt, provider, idle_timeout_s=0.2)

    pg = placement_group([{"TPU": 4}], strategy="PACK")  # unschedulable now
    assert not pg.wait(0.5)
    for _ in range(4):
        rec.reconcile()
    assert pg.wait(10), "slice node never provisioned"
    running = rec.instance_manager.list(InstanceStatus.RAY_RUNNING)
    assert len(running) == 1
    assert running[0].node_type == "v5e-4"  # smallest covering slice
    assert "QUEUED->REQUESTED" in running[0].history[0]

    # release the PG: the instance drains and terminates
    from ray_tpu.util.placement_group import remove_placement_group
    remove_placement_group(pg)
    import time as _t
    deadline = _t.monotonic() + 15
    while _t.monotonic() < deadline:
        rec.reconcile()
        if rec.instance_manager.list(InstanceStatus.TERMINATED):
            break
        _t.sleep(0.1)
    dead = rec.instance_manager.list(InstanceStatus.TERMINATED)
    assert len(dead) == 1
    assert rec.stats["terminated"] == 1


def test_autoscaler_v2_gke_provider_is_explicit_stub():
    from ray_tpu.autoscaler_v2 import GkeTpuProvider
    import pytest as _pytest

    provider = GkeTpuProvider(project="p", zone="z", cluster="c")
    with _pytest.raises(NotImplementedError, match="zero-egress|GKE|API"):
        provider.launch("v5e-4")


def test_dashboard_web_ui_and_profiling(ray_start_regular):
    """The dashboard serves an HTML UI at / and on-demand profiling
    endpoints (py-spy/memray role, stdlib sampling — SURVEY §5.1)."""
    import json
    import threading
    import time
    import urllib.request

    from ray_tpu.dashboard.server import start_dashboard, stop_dashboard

    host, port = start_dashboard(port=0)
    base = f"http://{host}:{port}"
    try:
        html = urllib.request.urlopen(f"{base}/").read().decode()
        assert "<html>" in html and "ray_tpu" in html

        # keep a thread busy so the sampler sees a stack
        stop = threading.Event()

        def burn():
            while not stop.is_set():
                sum(i * i for i in range(2000))

        t = threading.Thread(target=burn, daemon=True, name="burner")
        t.start()
        prof = json.loads(urllib.request.urlopen(
            f"{base}/api/profile/cpu?duration=0.5").read())
        stop.set()
        assert prof["samples"] > 10
        assert any("burn" in row["frame"] or "burner" in stack
                   for row in prof["top"]
                   for stack in [""]) or any(
                       "burner" in line for line in prof["collapsed"])

        mem1 = json.loads(urllib.request.urlopen(
            f"{base}/api/profile/memory").read())
        blob = [bytearray(1024 * 1024) for _ in range(4)]
        mem2 = json.loads(urllib.request.urlopen(
            f"{base}/api/profile/memory").read())
        assert mem2.get("total_traced_bytes", 0) > 0
        del blob
    finally:
        stop_dashboard()
