"""Chaos tier: seeded failpoint schedules replayed across topologies.

Run with ``pytest -m chaos`` (or ``tools/run_chaos.sh``, which sweeps
the seeds across both the in-process and ``RAY_TPU_CLUSTER=daemons``
topologies). Every test here is ALSO marked slow so the tier-1 sweep
(``-m 'not slow'``) never pays for cluster boots + fault windows.

Each schedule is deterministic for a given seed: probabilistic arms
draw from the registry's seeded RNG, hit-count arms count per seam, and
every assertion on fault counts reads the registry's thread-safe hit
log — never timing heuristics.
"""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import failpoints as fp
from ray_tpu._private import rpc
from ray_tpu._private.retry import RetryPolicy

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

SEEDS = [101, 202, 303]


@pytest.fixture(autouse=True)
def _reset_failpoints():
    yield
    fp.reset()


# ---------------------------------------------------------------------------
# in-process topology: strict exact-count replays
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_every_nth_rpc_drop_converges(seed):
    """Every-Nth-request drop on a live RPC server: every call converges
    under RetryPolicy and the drop count is exact (no background
    traffic shares this in-process server)."""

    class Svc:
        def __init__(self):
            self.served = 0

        def handle_bump(self, conn, rid, msg):
            self.served += 1
            return {"n": self.served}

    rpc.declare("bump", "k")
    svc = Svc()
    server = rpc.Server(svc).start()
    client = rpc.Client(server.addr, timeout=0.25)
    fp.activate("rpc.server.recv=drop:every=3", seed=seed)
    policy = RetryPolicy(max_attempts=6, base_s=0.005,
                         max_backoff_s=0.02)
    try:
        for k in range(12):
            policy.run(lambda: client.call("bump", k=k),
                       loop="chaos.rpc_drop", retry_on=(rpc.RpcError,))
        # 12 successes with every 3rd arrival dropped: the 12th success
        # lands on arrival 17 (drops at 3,6,9,12,15) => 17 hits, 5 drops
        assert svc.served == 12
        assert fp.fire_count("rpc.server.recv") == 5
        assert fp.hit_count("rpc.server.recv") == 17
        drops = fp.hit_log("rpc.server.recv")
        assert [e["fire"] for e in drops] == list(range(1, 6))
        assert all(e["method"] == "bump" for e in drops)
    finally:
        client.close()
        server.stop()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_probabilistic_drop_is_seed_deterministic(seed):
    """The same seed replays the same probabilistic fault schedule —
    run the identical workload twice and compare the hit logs."""

    def run_once():
        fp.activate("chaos.coin=drop:p=0.5", seed=seed)
        outcomes = [fp.fire("chaos.coin") is fp.DROP for _ in range(40)]
        fired = fp.fire_count("chaos.coin")
        return outcomes, fired

    first, fired1 = run_once()
    second, fired2 = run_once()
    assert first == second and fired1 == fired2
    assert 0 < fired1 < 40


def test_chaos_stream_error_mid_generator(ray_start_regular):
    """A failpoint killing the stream after 2 items surfaces as a typed
    error on the consumer, never a hang or a silent truncation."""
    fp.activate("worker.generator_stream=error():after=2")

    @ray_tpu.remote(max_retries=0)
    def gen():
        yield from range(5)

    it = gen.remote()
    assert ray_tpu.get(next(it)) == 0
    assert ray_tpu.get(next(it)) == 1
    with pytest.raises(Exception):
        for _ in range(3):
            ray_tpu.get(next(it))
    assert fp.fire_count("worker.generator_stream") == 1


# ---------------------------------------------------------------------------
# daemons topology: whole-cluster seeded schedules
# ---------------------------------------------------------------------------

@pytest.fixture
def daemon_cluster():
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    yield rt
    ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_seeded_schedule_daemons(seed, daemon_cluster):
    """The acceptance schedule: every-Nth lane-submit fault + one head
    kill mid-KV-traffic + retried tasks — converges to success for
    every seed, with exact fault counts from the registry log and
    retry counters visible in the Prometheus registry."""
    rt = daemon_cluster
    fp.activate("fast_lane.submit=error(OSError):every=3:max=5",
                seed=seed)

    @ray_tpu.remote
    def f(x):
        return x * 3

    out = ray_tpu.get([f.remote(i) for i in range(30)])
    assert out == [i * 3 for i in range(30)]

    # head respawn mid-put: kill the head, keep writing through the
    # redial window, and verify the persisted KV survived the restart
    backend = rt.cluster_backend
    backend.head.kv_put(b"chaos:key", b"v0")
    backend.head_proc.kill()
    backend.head.kv_put(b"chaos:key", b"v1")     # rides the redial
    assert backend.head.kv_get(b"chaos:key") == b"v1"

    # the cluster still runs tasks after the respawn
    out = ray_tpu.get([f.remote(i) for i in range(10)])
    assert out == [i * 3 for i in range(10)]

    # exact fault accounting from the registry log
    assert fp.fire_count("fast_lane.submit") == 5
    lane_log = fp.hit_log("fast_lane.submit")
    assert [e["fire"] for e in lane_log] == [1, 2, 3, 4, 5]

    # migrated retry loops surface in the Prometheus exposition
    from ray_tpu.util import metrics
    text = metrics.prometheus_text()
    assert "ray_tpu_retries_total" in text
    assert 'loop="head.redial"' in text


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_generator_body_exactly_once(seed, daemon_cluster,
                                           tmp_path):
    """Exactly-once-per-attempt: a PLAIN function with a side effect
    that returns a generator object must run its body once per attempt
    even while lane submits are failing over to the classic path
    (regression for the KIND_GEN_FALLBACK double-run)."""
    fp.activate("fast_lane.submit=error(OSError):p=0.4", seed=seed)
    marker_dir = str(tmp_path)

    @ray_tpu.remote
    def gen_with_side_effect(i):
        with open(os.path.join(marker_dir, f"{i}.ran"), "a") as fh:
            fh.write("x")
        return (j * 2 for j in range(3))

    refs = [gen_with_side_effect.remote(i) for i in range(12)]
    for r in refs:
        ray_tpu.get(r)
    for i in range(12):
        with open(os.path.join(marker_dir, f"{i}.ran")) as fh:
            assert fh.read() == "x", f"task {i} body ran != once"
    # the schedule actually exercised both paths
    assert 0 < fp.fire_count("fast_lane.submit") < fp.hit_count(
        "fast_lane.submit")


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_lane_death_mid_stream_daemons(seed, daemon_cluster):
    """Kill a daemon mid-stream: the consumer gets a typed error or the
    retried stream completes — never a wedge (deterministic per seed
    because the kill lands between two acked items)."""
    rt = daemon_cluster

    @ray_tpu.remote(max_retries=2)
    def slow_gen():
        for i in range(6):
            time.sleep(0.05)
            yield i

    it = slow_gen.remote()
    assert ray_tpu.get(next(it)) == 0
    # node death under a streaming task -> lineage replay skips acked
    # items (deterministic streams) or surfaces NodeDiedError
    victim = list(rt.cluster_backend.daemons.values())[0]
    try:
        rest = []
        mid_kill = {"done": False}

        def killer():
            victim.sigkill()
            mid_kill["done"] = True

        t = threading.Thread(target=killer)
        t.start()
        try:
            for ref in it:
                rest.append(ray_tpu.get(ref, timeout=30))
        except (exc.RayTpuError, exc.TaskError):
            pass        # typed error (incl. get timeout) is accepted
        t.join()
        assert mid_kill["done"]
        # convergence: whatever survived is a prefix-consistent stream
        assert rest == list(range(1, 1 + len(rest)))
    finally:
        # the second daemon keeps the cluster serviceable (generous
        # timeout: this tier runs on loaded CI boxes mid node-death)
        @ray_tpu.remote(max_retries=2)
        def ping():
            return "up"

        assert ray_tpu.get(ping.remote(), timeout=90) == "up"


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_push_task_delay_schedule(seed):
    """Env-activated schedule reaches SPAWNED daemon processes: delay
    arms on the daemon's push path slow leases without losing tasks."""
    os.environ["RAY_TPU_FAILPOINTS"] = (
        "daemon.push_task=delay(30):every=2")
    os.environ["RAY_TPU_FAILPOINTS_SEED"] = str(seed)
    try:
        rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                          cluster="daemons")
        try:
            @ray_tpu.remote(num_returns="streaming")
            def gen():
                yield from range(4)

            # streaming tasks ride the classic push path (the delayed
            # seam); the stream must still arrive complete and ordered
            assert [ray_tpu.get(r) for r in gen.remote()] == [0, 1, 2, 3]
        finally:
            ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_FAILPOINTS", None)
        os.environ.pop("RAY_TPU_FAILPOINTS_SEED", None)


# ---------------------------------------------------------------------------
# graceful drain under chaos: migration faults + crashes racing the drain
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_drain_migration_faults_fall_back_to_lineage(seed):
    """Seeded error arm on drain.migrate_object: objects whose
    migration is injected to fail still survive the departure — lineage
    reconstruction covers exactly what migration could not move, and
    every get() converges."""
    import numpy as np

    rt = ray_tpu.init(num_nodes=4, resources={"CPU": 4})
    try:
        @ray_tpu.remote(max_retries=5)
        def blob(i):
            return np.full((600, 600), i)

        refs = [blob.remote(i) for i in range(8)]
        ray_tpu.get(refs)
        victim = next(n for n in rt.nodes()
                      if any(n.store.contains(r.id) for r in refs))
        n_victim = sum(1 for r in refs if victim.store.contains(r.id))

        fp.activate("drain.migrate_object=error:p=0.5", seed=seed)
        assert rt.drain_node(victim.node_id, deadline_s=20,
                             reason="chaos")
        deadline = time.monotonic() + 25
        while (rt.get_node(victim.node_id) is not None
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert rt.get_node(victim.node_id) is None

        vals = ray_tpu.get(refs, timeout=60)
        assert all(vals[i][0][0] == i for i in range(8))
        # accounting: every sole copy either migrated (counted once —
        # retried copies are location-deduped) or was lost with the
        # node and lazily reconstructed by the get() above
        moved = rt.stats["drain_objects_migrated"]
        rebuilt = rt.stats["objects_reconstructed"]
        assert moved + rebuilt == n_victim, (moved, rebuilt, n_victim)
        # each sole copy reached the failpoint at least once
        assert fp.hit_count("drain.migrate_object") >= n_victim
    finally:
        ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_drain_races_worker_crashes_daemons(seed, daemon_cluster):
    """Drain one daemon while seeded lane faults crash/deny submits
    across the cluster: every task converges (completed, resubmitted
    off the draining node, or retried through the crash machinery) and
    the drained node leaves — drain and chaos never wedge each other."""
    rt = daemon_cluster
    fp.activate("fast_lane.submit=error(OSError):every=4:max=6",
                seed=seed)

    @ray_tpu.remote(max_retries=3)
    def work(i):
        time.sleep(0.02)
        return i * 7

    refs = [work.remote(i) for i in range(24)]
    victim = rt.alive_nodes()[0]
    assert rt.drain_node(victim.node_id, deadline_s=10, reason="chaos")
    refs += [work.remote(i) for i in range(24, 36)]

    out = ray_tpu.get(refs, timeout=120)
    assert out == [i * 7 for i in range(36)]
    deadline = time.monotonic() + 30
    while (rt.get_node(victim.node_id) is not None
           and time.monotonic() < deadline):
        time.sleep(0.1)
    assert rt.get_node(victim.node_id) is None
    # the surviving node keeps serving
    assert ray_tpu.get(work.remote(99), timeout=60) == 693
    # head membership reflects the drained departure
    views = {n["node_id"]: n
             for n in rt.cluster_backend.head.list_nodes()}
    assert not views[victim.node_id.hex()]["alive"]


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_drain_exec_pool_inflight_vs_pending(seed, tmp_path):
    """Drain a node whose sized exec pool is saturated (PR 10 pooled
    execution): pooled IN-FLIGHT tasks finish where they run, admitted-
    but-unstarted specs still in the pool queue are stolen back and
    handed to the scheduler WITHOUT consuming a retry (max_retries=0
    throughout — a burned retry would fail the task), and every body
    runs exactly once, under seeded lane-submit delay noise. Topology
    comes from the run_chaos.sh sweep (in-process + daemons)."""
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 8},
                      # pool far smaller than the ledger's admission
                      # width: admitted specs QUEUE in the pool, so the
                      # drain finds both in-flight and pending work
                      _system_config={"exec_pool_size": 2})
    try:
        fp.activate("fast_lane.submit=delay(10):p=0.25", seed=seed)
        marker_dir = str(tmp_path)

        @ray_tpu.remote(max_retries=0)
        def slow(i):
            with open(os.path.join(marker_dir, f"{i}.ran"), "a") as fh:
                fh.write("x")
            time.sleep(0.2)
            return i * 5

        refs = [slow.remote(i) for i in range(16)]
        time.sleep(0.15)    # let admission fill the pools mid-flood
        victim = rt.alive_nodes()[0]
        assert rt.drain_node(victim.node_id, deadline_s=30,
                             reason="chaos")
        out = ray_tpu.get(refs, timeout=120)
        assert out == [i * 5 for i in range(16)]
        # exactly once each: the pool-queue handback resubmits specs
        # that never started — a double run (or a retry-burning failure)
        # shows up as a doubled marker / missing result
        for i in range(16):
            with open(os.path.join(marker_dir, f"{i}.ran")) as fh:
                assert fh.read() == "x", f"task {i} body ran != once"
        assert rt.stats["tasks_retried"] == 0
        # clean drain: the node left via completion, not escalation
        deadline = time.monotonic() + 30
        while (rt.get_node(victim.node_id) is not None
               and time.monotonic() < deadline):
            time.sleep(0.1)
        assert rt.get_node(victim.node_id) is None
        assert rt.stats["drain_escalations_total"] == 0
        # the survivor keeps serving pooled work
        assert ray_tpu.get(slow.remote(99), timeout=60) == 495
    finally:
        ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_drain_deadline_races_escalation_daemons(seed,
                                                      daemon_cluster):
    """A drain whose window closes mid-load escalates into the node-
    death path while the driver's own timer races the head's: the
    escalation runs exactly once, tasks recover via retries, and the
    cluster converges."""
    rt = daemon_cluster

    @ray_tpu.remote(max_retries=3)
    def slow(i):
        time.sleep(0.5)
        return i

    refs = [slow.remote(i) for i in range(8)]
    time.sleep(0.2)
    victim = rt.alive_nodes()[0]
    fp.activate("drain.deadline=delay(25)", seed=seed)
    assert rt.drain_node(victim.node_id, deadline_s=0.3, reason="chaos")
    assert sorted(ray_tpu.get(refs, timeout=120)) == list(range(8))
    deadline = time.monotonic() + 30
    while (rt.get_node(victim.node_id) is not None
           and time.monotonic() < deadline):
        time.sleep(0.1)
    assert rt.get_node(victim.node_id) is None
    # the escalation was counted once (driver timer or head deadline —
    # whichever won; the loser found the node already gone)
    assert rt.stats["drain_escalations_total"] == 1


# ---------------------------------------------------------------------------
# multi-tenant fair-share under fault injection
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS[:1])
def test_chaos_quota_exceeded_job_degrades_others_unharmed(seed):
    """A tenant that blows through its CPU quota while the
    ``admission.verdict`` seam is erroring degrades gracefully (its
    submits fall back to QUEUED — delayed, never lost) and the
    well-behaved tenant on the same cluster is unharmed: every task
    from BOTH jobs completes and the seam's hit log shows the faults
    actually fired."""
    from ray_tpu.tenancy import job_context

    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      _system_config={"fairshare": True})
    try:
        # the greedy tenant gets a 1-CPU hard cap on a 2-CPU cluster
        rt.tenancy.set_quota("greedy", hard={"CPU": 1.0})

        @ray_tpu.remote
        def work(i):
            time.sleep(0.02)
            return i

        # every 2nd admission decision errors: those submits must
        # degrade to QUEUED (dispatch gate re-decides), not crash
        fp.activate("admission.verdict=error(RuntimeError):every=2:max=20",
                    seed=seed)
        with job_context("greedy"):
            greedy_refs = [work.remote(i) for i in range(20)]
        with job_context("polite"):
            polite_refs = [work.remote(i) for i in range(10)]
        fired = fp.fire_count("admission.verdict")
        assert fired > 0     # the schedule actually cut the seam
        # the polite job is unharmed: all results arrive
        assert sorted(ray_tpu.get(polite_refs, timeout=60)) == \
            list(range(10))
        # the degraded job is delayed, never lost: all results arrive
        # even though half its verdicts came from the error arm and its
        # quota held it to 1 CPU throughout
        assert sorted(ray_tpu.get(greedy_refs, timeout=120)) == \
            list(range(20))
        assert fp.hit_count("admission.verdict") >= fired
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# process-death reclamation campaign: SIGKILL'd clients leak nothing
# ---------------------------------------------------------------------------
# The object-plane crash-safety contract (docs/object_plane.md "Crash
# reclamation"): every slot ref / reservation charged to a client that
# dies — worker SIGKILL mid-view, writer SIGKILL between reserve and
# seal, external attacher SIGKILL holding live grants — is reclaimed by
# the SAME daemon (death signal or heartbeat sweep), the leak gauge
# returns to zero, and the evicted bytes become re-allocatable. No
# daemon restart, no task failures attributable to reclamation.

def _first_daemon(rt):
    return list(rt.cluster_backend.daemons.values())[0]


def _slot_refs(handle):
    return handle.client.call("daemon_stats")["slot_refs"]


def _wait_refs_zero(handle, timeout=20.0):
    """Poll the per-client attributed leak gauge until every externally
    granted slot ref has been reclaimed (registry truth, not timing)."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        last = _slot_refs(handle)
        if last["refs"] == 0:
            return last
        time.sleep(0.1)
    raise AssertionError(f"slot refs never reclaimed: {last}")


def _wait_store_used(handle, at_most, timeout=20.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        last = handle.client.call("daemon_stats")["store_used"]
        if last <= at_most:
            return last
        time.sleep(0.1)
    raise AssertionError(f"store_used stuck at {last} > {at_most}")


def _needs_arena(handle):
    if not handle.objectplane:
        pytest.skip("no native arena on this box (dict-only store)")


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_sigkill_worker_mid_view_reclaims_grants(seed):
    """SIGKILL an actor's worker while it holds a live zero-copy view:
    the worker-pipe EOF funnels into reclaim_client, the leak gauge
    (ray_tpu_arena_slot_refs{state=refs}) returns to zero, and the
    freed bytes are re-allocatable — without a daemon restart."""
    import signal
    import numpy as np

    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons")
    try:
        handle = _first_daemon(rt)
        _needs_arena(handle)
        daemon_pid = handle.proc.pid

        # produced WORKER-side so the result direct-puts into the
        # daemon's arena raw-tier (c-contiguous, > direct_put_min_
        # bytes): the consumer's get is then a zero-copy view whose
        # finalizer is the ONLY releaser — exactly what a SIGKILL
        # strands. (A driver-side put stays in the driver's store and
        # the consumer would get shipped bytes, not a slot grant.)
        nbytes = 96 * 1024 * 8                          # 768 KiB

        @ray_tpu.remote
        def produce(n):
            return np.arange(n, dtype=np.float64)

        ref = produce.remote(96 * 1024)

        @ray_tpu.remote
        class Holder:
            def hold(self, refs):
                import os as _os
                self.view = ray_tpu.get(refs)[0]
                return _os.getpid(), float(self.view[7])

        h = Holder.remote()
        victim_pid, v = ray_tpu.get(h.hold.remote([ref]), timeout=60)
        assert v == 7.0
        before = _slot_refs(handle)
        assert before["refs"] >= 1, before
        # attribution names a live worker client holding the grant
        workers = [c for c in before["clients"]
                   if c["client"].startswith("w:")]
        assert workers and any(c["alive"] for c in workers), before

        os.kill(victim_pid, signal.SIGKILL)
        after = _wait_refs_zero(handle)
        assert after["refs"] == 0 and after["clients"] == []

        # the daemon never restarted
        assert handle.proc.poll() is None
        assert handle.proc.pid == daemon_pid

        # freed bytes are re-allocatable: drop the driver ref, then the
        # deferred delete (its last ext ref died with the worker) frees
        # on reap and the same-size reservation succeeds
        del ref
        import gc
        gc.collect()
        handle.flush_frees()
        out = None
        deadline = time.monotonic() + 20
        while out is None and time.monotonic() < deadline:
            out = handle.arena_reserve(b"chaos:realloc:%d" % seed, nbytes)
            if out is None:
                time.sleep(0.1)
        assert out is not None and "off" in out, "bytes not re-allocatable"
        handle.free_objects([b"chaos:realloc:%d" % seed])

        # zero task failures attributable to reclamation
        @ray_tpu.remote
        def ping():
            return "up"

        assert ray_tpu.get(ping.remote(), timeout=60) == "up"
    finally:
        ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_sigkill_worker_mid_direct_put_aborts_reservation(seed):
    """SIGKILL a worker between reserve and seal (the direct-put write
    window): the death signal aborts the unsealed reservation and the
    reserved bytes return to the arena — no TTL wait, no restart."""
    import signal

    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                      cluster="daemons")
    try:
        handle = _first_daemon(rt)
        _needs_arena(handle)
        baseline = handle.client.call("daemon_stats")["store_used"]

        @ray_tpu.remote
        def reserve_and_stall(seed):
            # reserve arena space exactly like a direct put, then return
            # WITHOUT sealing: the daemon now carries an unsealed
            # reservation charged to this worker's identity
            import os as _os
            from ray_tpu._private import worker as worker_mod
            st = worker_mod._global_runtime._state
            key = b"chaos:stall:%d:%d" % (seed, _os.getpid())
            out = st.call_host("shm_put_reserve", key=key, size=1 << 20)
            assert isinstance(out, dict) and "off" in out, out
            return _os.getpid()

        victim_pid = ray_tpu.get(reserve_and_stall.remote(seed),
                                 timeout=60)
        used = handle.client.call("daemon_stats")["store_used"]
        assert used >= baseline + (1 << 20), (used, baseline)

        os.kill(victim_pid, signal.SIGKILL)
        # pipe EOF -> reclaim_client aborts the reservation; the bytes
        # come back without any daemon restart (small slack: stored
        # task results share the same table)
        _wait_store_used(handle, baseline + 64 * 1024)
        assert handle.proc.poll() is None

        # the same key reserves cleanly afterwards (the abort deleted
        # the unsealed entry, it did not poison the key)
        out = handle.arena_reserve(b"chaos:stall:again:%d" % seed, 1 << 20)
        assert out is not None and "off" in out
        handle.free_objects([b"chaos:stall:again:%d" % seed])

        @ray_tpu.remote
        def ping():
            return "ok"

        assert ray_tpu.get(ping.remote(), timeout=60) == "ok"
    finally:
        ray_tpu.shutdown()


def _external_attacher_script():
    """Source for a subprocess that plays a driver-like external
    attacher: grabs a slot grant + an unsealed reservation over raw
    RPC, reports READY, then blocks until SIGKILLed."""
    return r"""
import sys, time
host, port, oid_hex = sys.argv[1], int(sys.argv[2]), sys.argv[3]
from ray_tpu._private import rpc
rpc.declare("get_object", "oid", "prefer_shm")
rpc.declare("create_object", "oid", "size")
c = rpc.Client((host, port), timeout=10)
out = c.call("get_object", oid=bytes.fromhex(oid_hex),
             prefer_shm=True, slot_ok=True)
assert out.get("slot") is not None, out
res = c.call("create_object", oid=b"chaos:ext:res", size=256 * 1024)
assert res.get("ok"), res
print("READY", flush=True)
time.sleep(120)
"""


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_sigkill_external_client_holding_views(seed):
    """SIGKILL an external attacher (driver-protocol client) that holds
    a slot grant on a deferred-deleted object PLUS an unsealed
    reservation: the RPC disconnect reclaims both, the deferred delete
    frees on the very next reap (NOT at daemon restart), and an
    allocation that could not fit while the leak lived succeeds."""
    import subprocess
    import sys

    # arena sized so the seeded blob + a leaked copy cannot coexist:
    # re-reserving the blob's size FAILS while the dead client's grant
    # pins the deferred delete, and SUCCEEDS once reclaimed
    rt = ray_tpu.init(num_nodes=1, resources={"CPU": 2},
                      cluster="daemons",
                      object_store_memory=4 * 1024 * 1024)
    try:
        handle = _first_daemon(rt)
        _needs_arena(handle)
        daemon_pid = handle.proc.pid
        key = b"chaos:ext:%d" % seed
        blob = os.urandom(int(2.5 * 1024 * 1024))
        handle.put_object_blob(key, blob)

        proc = subprocess.Popen(
            [sys.executable, "-c", _external_attacher_script(),
             handle.addr[0], str(handle.addr[1]), key.hex()],
            stdout=subprocess.PIPE, text=True)
        try:
            assert proc.stdout.readline().strip() == "READY"
            before = _slot_refs(handle)
            assert before["refs"] >= 1, before
            assert any(c["client"].startswith("c:")
                       for c in before["clients"]), before

            # delete while the external grant pins it: deferred delete,
            # bytes still held -> a same-size reservation cannot fit
            handle.free_objects([key])
            assert handle.arena_reserve(b"chaos:ext:probe", len(blob)) \
                is None, "leak did not pin the arena (test inert)"

            proc.kill()     # SIGKILL: no release, no goodbye
            proc.wait(timeout=10)

            # conn EOF -> on_disconnect -> reclaim_client: grant dropped,
            # reservation aborted, reap frees the deferred delete
            after = _wait_refs_zero(handle)
            assert after["refs"] == 0
            out = None
            deadline = time.monotonic() + 20
            while out is None and time.monotonic() < deadline:
                out = handle.arena_reserve(b"chaos:ext:re", len(blob))
                if out is None:
                    time.sleep(0.1)
            assert out is not None and "off" in out, \
                "deferred delete not freed by reap after reclaim"
            handle.free_objects([b"chaos:ext:re"])

            # same daemon process throughout — reclamation, not restart
            assert handle.proc.poll() is None
            assert handle.proc.pid == daemon_pid
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)

        @ray_tpu.remote
        def ping():
            return 1

        assert ray_tpu.get(ping.remote(), timeout=60) == 1
    finally:
        ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_sweep_backstop_when_event_reclaim_dropped(seed):
    """arena.grant_reclaim drop arm (env-activated so it arms the
    SPAWNED daemon): the death-signal reclaim is LOST once; the
    heartbeat orphan sweep must still converge the leak gauge to zero
    (dead-pid ledger reclaim), and the reclaimed grants surface on the
    federated ray_tpu_arena_grants_reclaimed_total counter."""
    import signal
    import numpy as np

    os.environ["RAY_TPU_FAILPOINTS"] = "arena.grant_reclaim=drop:max=1"
    os.environ["RAY_TPU_FAILPOINTS_SEED"] = str(seed)
    os.environ["RAY_TPU_ARENA_RESERVE_TTL_S"] = "1"
    try:
        rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                          cluster="daemons")
        try:
            handle = _first_daemon(rt)
            _needs_arena(handle)

            @ray_tpu.remote
            def produce(n):
                return np.arange(n, dtype=np.float64)

            ref = produce.remote(96 * 1024)   # lands raw in the arena

            @ray_tpu.remote
            class Holder:
                def hold(self, refs):
                    import os as _os
                    self.view = ray_tpu.get(refs)[0]
                    return _os.getpid()

            h = Holder.remote()
            victim_pid = ray_tpu.get(h.hold.remote([ref]), timeout=60)
            assert _slot_refs(handle)["refs"] >= 1

            os.kill(victim_pid, signal.SIGKILL)
            # event path suppressed by the drop arm (max=1); the sweep
            # (every daemon heartbeat) finds the dead pid in the ledger
            # and reclaims through the now-exhausted seam
            after = _wait_refs_zero(handle, timeout=30.0)
            assert after["refs"] == 0
            assert handle.proc.poll() is None

            # a driver-side reservation never sealed: the TTL sweep
            # (RAY_TPU_ARENA_RESERVE_TTL_S=1) aborts it while this
            # connection stays OPEN — stale-reservation path, not the
            # disconnect path
            used0 = handle.client.call("daemon_stats")["store_used"]
            out = handle.arena_reserve(b"chaos:ttl:%d" % seed, 512 * 1024)
            assert out is not None and "off" in out
            _wait_store_used(handle, used0 + 64 * 1024, timeout=30.0)

            # federated accounting: the daemon's reclaim counters reach
            # the driver's cluster view (per-node rows)
            from ray_tpu.util import metrics
            deadline = time.monotonic() + 30
            names = set()
            while time.monotonic() < deadline:
                names = {(r["name"],
                          dict(r.get("labels") or {}).get("reason"))
                         for r in metrics.cluster_metrics_json()["metrics"]}
                if ("ray_tpu_arena_grants_reclaimed_total",
                        "sweep") in names and \
                   ("ray_tpu_arena_stale_reservations_total",
                        None) in names:
                    break
                time.sleep(0.25)
            assert ("ray_tpu_arena_grants_reclaimed_total",
                    "sweep") in names, sorted(names)
            assert ("ray_tpu_arena_stale_reservations_total",
                    None) in names, sorted(names)
        finally:
            ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_FAILPOINTS", None)
        os.environ.pop("RAY_TPU_FAILPOINTS_SEED", None)
        os.environ.pop("RAY_TPU_ARENA_RESERVE_TTL_S", None)


# ---------------------------------------------------------------------------
# network partitions: one-way splits, death-mark + heal fencing, flapping
# links, partition racing a graceful drain (docs/fault_tolerance.md
# "Partitions, epochs & fencing"). These boot their own daemons cluster
# (the run_chaos.sh `network` tier sweeps the surrounding driver
# topology env, like the process-kill tier).
# ---------------------------------------------------------------------------

from ray_tpu._private import netchaos as nc  # noqa: E402


def _fenced_results_total(kind=None):
    """Sum of ray_tpu_fenced_results_total in THIS driver's registry
    (optionally one kind) — the fencing layer is driver-side."""
    from ray_tpu.util import metrics
    total = 0.0
    for line in metrics.prometheus_text().splitlines():
        if not line.startswith("ray_tpu_fenced_results_total"):
            continue
        if kind is not None and f'kind="{kind}"' not in line:
            continue
        total += float(line.rsplit(" ", 1)[1])
    return total


@pytest.mark.parametrize("seed", SEEDS)
def test_netchaos_partition_one_way_driver_daemon_mid_burst(seed):
    """One-way driver->daemon partition opened MID-BURST against one
    node (requests vanish; replies and result pushes still flow). The
    wedge-proof contract: the bounded batch-flush deadline surfaces the
    silent link as a typed RpcError -> node death -> retries on the
    survivor; lane submits swallowed by the partition unwedge through
    the same death mark. Every task converges exactly once."""
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons",
                      _system_config={"control_call_timeout_s": 1.5})
    try:
        victim = _first_daemon(rt)
        vh = victim.node_id.hex()

        @ray_tpu.remote(max_retries=5, num_returns=2)
        def pair(i):
            time.sleep(0.05)
            return i, i * 11

        @ray_tpu.remote(max_retries=5)
        def plain(i):
            time.sleep(0.05)
            return i * 7

        # pre-partition traffic on both planes (pump + fast lane)
        pre = [pair.remote(i) for i in range(6)]
        pre_plain = [plain.remote(i) for i in range(6)]
        time.sleep(0.3)
        # one-way split: everything the driver sends toward the victim
        # (control client AND its node-scoped lane) is dropped
        nc.activate(f"driver>daemon@{vh}=partition;"
                    f"driver>daemon@lane:{vh}=partition", seed=seed)
        post = [pair.remote(i) for i in range(6, 14)]
        post_plain = [plain.remote(i) for i in range(6, 14)]

        flat = [r for pr in pre + post for r in pr]
        vals = ray_tpu.get(flat, timeout=120)
        assert vals == [x for i in range(14) for x in (i, i * 11)]
        assert ray_tpu.get(pre_plain + post_plain, timeout=120) == [
            i * 7 for i in range(14)]

        # the partition actually ate frames, deterministically logged
        assert nc.injected_count("drop") > 0
        dropped_links = {e["policy"] for e in nc.hit_log()}
        assert f"driver>daemon@{vh}" in dropped_links
        # typed-timeout contract: the victim was declared dead (flush
        # deadline -> RpcError -> mark_dead), never a wedged thread
        assert victim.dead
        # the survivor keeps serving new work
        assert ray_tpu.get(plain.remote(99), timeout=60) == 693
    finally:
        nc.reset()
        ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_netchaos_partition_death_mark_then_heal_fences_results(seed):
    """Daemon<->head partition long enough for the head's liveness
    timer to death-mark the node, then heal. In-flight work finishes on
    the superseded node and its late result pushes arrive at the driver
    AFTER the death mark: the fence rejects them (counter > 0), the
    retried attempts complete exactly once on the survivor, and the
    fenced daemon exits via the {"dead": True} re-register contract."""
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    try:
        victim = _first_daemon(rt)
        vh = victim.node_id.hex()
        fenced_before = _fenced_results_total()

        @ray_tpu.remote(max_retries=5, num_returns=2)
        def slow(i):
            time.sleep(2.5)
            return i, i * 13

        refs = [slow.remote(i) for i in range(8)]
        time.sleep(0.4)     # let the burst dispatch across both nodes
        # partition THIS daemon's head link (programmatic per-node
        # activation inside the spawned process); window outlives the
        # node_dead_after_s liveness deadline, then heals
        out = victim.client.call(
            "net_chaos", spec="daemon>head=partition:dur=3500",
            seed=seed, timeout=5.0)
        assert out["active"]

        vals = ray_tpu.get([r for pr in refs for r in pr], timeout=120)
        # exactly once per ref: each task resolves to ONE value even
        # though the superseded node also ran (and pushed) it
        assert vals == [x for i in range(8) for x in (i, i * 13)]
        # the dead-marked node's work was re-run through retries
        assert rt.stats["tasks_retried"] >= 1
        # the fence engaged: stamped frames from the superseded
        # incarnation were rejected, not double-delivered
        deadline = time.monotonic() + 30
        while (_fenced_results_total() <= fenced_before
               and time.monotonic() < deadline):
            time.sleep(0.1)
        assert _fenced_results_total() > fenced_before
        # heal: the partition window closes, the daemon re-registers,
        # learns it was fenced, and drains via the dead-exit contract
        victim.proc.wait(timeout=40)
        views = {n["node_id"]: n
                 for n in rt.cluster_backend.head.list_nodes()}
        assert not views[vh]["alive"]

        @ray_tpu.remote
        def ping():
            return "up"

        assert ray_tpu.get(ping.remote(), timeout=60) == "up"
    finally:
        nc.reset()
        ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_netchaos_flapping_link_under_queued_drain(seed):
    """Flapping latency bursts (150ms impaired / 150ms clean, seeded
    jitter) on one node's control link + lane while that node drains
    with a queue of admitted work: every queued task converges exactly
    once, the drained node departs, and the flap's off-transitions fire
    the net.partition_heal seam."""
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    try:
        fp.activate("net.partition_heal=delay(0);net.link_drop=delay(0)",
                    seed=seed)
        victim = _first_daemon(rt)
        vh = victim.node_id.hex()

        @ray_tpu.remote(max_retries=2)
        def work(i):
            time.sleep(0.15)
            return i * 9

        # queue depth > cluster CPU: the drain finds admitted-but-
        # unstarted work behind the stuttering link
        refs = [work.remote(i) for i in range(12)]
        # 200ms impaired / 100ms clean: the second half of the burst and
        # the drain's migration chatter cross the flap's on-phase
        nc.activate(f"driver>daemon@{vh}=lat=120:jitter=40:"
                    f"flap=200/100:sym;"
                    f"driver>daemon@lane:{vh}=lat=120:flap=200/100",
                    seed=seed)
        refs += [work.remote(i) for i in range(12, 24)]
        time.sleep(0.1)
        assert rt.drain_node(victim.node_id, deadline_s=20,
                             reason="netchaos-flap")
        assert ray_tpu.get(refs, timeout=120) == [
            i * 9 for i in range(24)]
        deadline = time.monotonic() + 30
        while (rt.get_node(victim.node_id) is not None
               and time.monotonic() < deadline):
            time.sleep(0.1)
        assert rt.get_node(victim.node_id) is None
        # the stutter really ran: seeded delays were injected and at
        # least one impaired->clear flap transition reported a heal
        assert nc.injected_count("delay") > 0
        assert fp.fire_count("net.partition_heal") >= 1
        assert ray_tpu.get(work.remote(50), timeout=60) == 450
    finally:
        nc.reset()
        ray_tpu.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_netchaos_partition_during_graceful_drain(seed):
    """A daemon<->head partition opens DURING a graceful drain of that
    node. Whichever side wins the race — the drain finishing its
    migration, or the head's liveness timer escalating to node death —
    every task converges exactly once, the node departs, and the
    partitioned daemon process exits instead of lingering as a zombie."""
    rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                      cluster="daemons")
    try:
        victim = _first_daemon(rt)
        vh = victim.node_id.hex()

        @ray_tpu.remote(max_retries=5)
        def work(i):
            time.sleep(0.6)
            return i * 4

        refs = [work.remote(i) for i in range(24)]
        time.sleep(0.2)
        assert rt.drain_node(victim.node_id, deadline_s=15,
                             reason="netchaos-drain")
        # permanent split from the head, mid-drain: heartbeats vanish
        victim.client.call("net_chaos", spec="daemon>head=partition",
                           seed=seed, timeout=5.0)

        assert ray_tpu.get(refs, timeout=120) == [
            i * 4 for i in range(24)]
        deadline = time.monotonic() + 30
        while (rt.get_node(victim.node_id) is not None
               and time.monotonic() < deadline):
            time.sleep(0.1)
        assert rt.get_node(victim.node_id) is None
        views = {n["node_id"]: n
                 for n in rt.cluster_backend.head.list_nodes()}
        assert not views[vh]["alive"]
        # drained OR death-marked, the daemon process must EXIT (clean
        # drain completion, or the fenced re-register dead reply)
        victim.proc.wait(timeout=40)
        assert ray_tpu.get(work.remote(99), timeout=60) == 396
    finally:
        nc.reset()
        ray_tpu.shutdown()

# ---------------------------------------------------------------------------
# memory pressure: graceful degradation under OOM chaos
# (docs/fault_tolerance.md "Memory pressure & graceful degradation").
# Ballast scenarios — worker host-memory ballast, arena overfill, and
# both at once — assert the degradation ladder end to end: zero lost
# tasks, spill/restore counters rise, a held zero-copy view is NEVER
# spilled out from under its reader, slot refs return to zero, and the
# node converges back to level ok after relief. The run_chaos.sh
# `memory` tier sweeps these over both driver topologies.
# ---------------------------------------------------------------------------

def _wait_pressure(handle, level, timeout=20.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        last = handle.client.call("daemon_stats")["pressure"]
        if last == level:
            return
        time.sleep(0.05)
    raise AssertionError(f"pressure stuck at {last!r}, wanted {level!r}")


def _spill_stats(handle):
    return handle.client.call("daemon_stats")["spill"]


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_memory_arena_fill_spills_restores_pins_hold(seed,
                                                           tmp_path):
    """Arena overfill ballast: blob puts far past the arena's capacity
    all land (spill_for makes room off cold entries instead of failing
    over), every byte reads back exactly (restore on demand), the entry
    under a HELD zero-copy view is never spilled, and after relief the
    grants reclaim to zero and the node returns to level ok."""
    import numpy as np

    os.environ["RAY_TPU_MEMORY_PRESSURE"] = "1"
    os.environ["RAY_TPU_PRESSURE_TICK_S"] = "0.1"
    os.environ["RAY_TPU_ARENA_SPILL_DIR"] = str(tmp_path)
    try:
        rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                          cluster="daemons",
                          object_store_memory=4 * 1024 * 1024)
        try:
            handle = _first_daemon(rt)
            _needs_arena(handle)
            daemon_pid = handle.proc.pid

            # a worker-produced raw-tier entry, pinned by a held view:
            # it must survive every spill pass bit-for-bit
            @ray_tpu.remote
            def produce(n):
                return np.arange(n, dtype=np.float64)

            ref = produce.remote(96 * 1024)             # 768 KiB

            @ray_tpu.remote
            class Holder:
                def hold(self, refs):
                    self.view = ray_tpu.get(refs)[0]
                    return float(self.view[7])

                def check(self):
                    return float(self.view[7]), float(self.view[-1])

                def drop(self):
                    del self.view
                    return True

            h = Holder.remote()
            assert ray_tpu.get(h.hold.remote([ref]), timeout=60) == 7.0
            assert _slot_refs(handle)["refs"] >= 1

            # ballast: 8 MiB of puts through a 4 MiB arena — every one
            # must land (spill_for + the typed-backpressure retry ride)
            from ray_tpu.exceptions import MemoryPressureError
            rng = __import__("random").Random(seed)
            blobs = {}
            for i in range(8):
                key = b"chaos:mem:%d:%d" % (seed, i)
                blobs[key] = bytes([rng.randrange(256)]) * (1 << 20)
                RetryPolicy.default(deadline_s=60.0).run(
                    lambda k=key: handle.put_object_blob(k, blobs[k]),
                    loop="chaos.mem_put",
                    retry_on=(MemoryPressureError,))
            stats = _spill_stats(handle)
            assert stats["spills"] >= 1, stats
            assert stats["spilled_now_bytes"] > 0, stats
            # the pass walked past the pinned entry, never spilled it
            assert stats["spill_skipped_pinned"] >= 1, stats

            # reads never miss: every ballast byte restores (or serves
            # off its spill file) exactly
            for key, blob in blobs.items():
                got = handle.get_object_blob(key)
                assert got == blob, f"{key} corrupted"
            assert _spill_stats(handle)["restores"] >= 1

            # the held view stayed valid AND exact through the storm
            v7, vlast = ray_tpu.get(h.check.remote(), timeout=60)
            assert (v7, vlast) == (7.0, float(96 * 1024 - 1))

            # relief: drop the view + ballast, grants reclaim to zero,
            # the level converges back to ok, the daemon never restarted
            assert ray_tpu.get(h.drop.remote(), timeout=60) is True
            del ref
            import gc
            gc.collect()
            handle.flush_frees()
            handle.free_objects(list(blobs))
            _wait_refs_zero(handle)
            _wait_pressure(handle, "ok")
            assert handle.proc.poll() is None
            assert handle.proc.pid == daemon_pid

            @ray_tpu.remote
            def ping():
                return "up"

            assert ray_tpu.get(ping.remote(), timeout=60) == "up"
        finally:
            ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_MEMORY_PRESSURE", None)
        os.environ.pop("RAY_TPU_PRESSURE_TICK_S", None)
        os.environ.pop("RAY_TPU_ARENA_SPILL_DIR", None)


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_memory_worker_ballast_oom_preemption(seed):
    """Worker host-memory ballast: a hog task blows the (lowered)
    memory limit, the node's monitor SIGKILLs it, and with retries
    exhausted it surfaces as the typed retriable OutOfMemoryError —
    while every innocent task converges (zero lost tasks) and the
    preemption lands on the federated
    ray_tpu_oom_preemptions_total{reason} counter."""
    os.environ["RAY_TPU_MEMORY_PRESSURE"] = "1"
    os.environ["RAY_TPU_MEMORY_MONITOR_INTERVAL"] = "0.1"
    try:
        rt = ray_tpu.init(num_nodes=1, resources={"CPU": 4},
                          cluster="daemons")
        try:
            mon = rt.memory_monitor
            mon.interval_s = 0.1
            if not mon._thread.is_alive():
                mon.start()
            baseline = mon.usage_bytes()
            mon.set_limit(baseline + 150 * 1024 * 1024)

            @ray_tpu.remote(max_retries=1)
            def hog(s):
                import numpy as np
                import time as _t
                blob = np.ones(400 * 1024 * 1024 // 8)   # ~400 MB
                _t.sleep(20)
                return blob.sum() + s

            # innocents carry generous retries: the RetriableFIFO
            # policy shoots the NEWEST retriable task, so any light
            # task scheduled after the hog's (re)start can catch a
            # stray bullet — it must retry through, never get lost
            @ray_tpu.remote(max_retries=8)
            def light(i):
                time.sleep(0.05)
                return i * 5

            light_refs = [light.remote(i) for i in range(16)]
            hog_ref = hog.remote(seed)

            with pytest.raises(exc.OutOfMemoryError):
                ray_tpu.get(hog_ref, timeout=120)
            # zero lost tasks: every innocent task converges exactly
            assert ray_tpu.get(light_refs, timeout=120) == [
                i * 5 for i in range(16)]

            kills = mon.kills
            backend = getattr(rt, "cluster_backend", None)
            if backend is not None:
                for h in backend.daemons.values():
                    kills += h.client.call("oom_check", task_id="",
                                           fast_lane=False)["kills"]
            assert kills >= 1

            # the preemption federates with its reason tag
            from ray_tpu.util import metrics
            deadline = time.monotonic() + 30
            reasons = set()
            while time.monotonic() < deadline:
                reasons = {
                    dict(r.get("labels") or {}).get("reason")
                    for r in metrics.cluster_metrics_json()["metrics"]
                    if r["name"] == "ray_tpu_oom_preemptions_total"}
                if reasons:
                    break
                time.sleep(0.25)
            assert "host" in reasons or "tenant_quota" in reasons, reasons

            # post-relief convergence: limit restored, the cluster runs
            mon.set_limit(1 << 62)
            assert ray_tpu.get(light.remote(99), timeout=60) == 495
        finally:
            ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_MEMORY_PRESSURE", None)
        os.environ.pop("RAY_TPU_MEMORY_MONITOR_INTERVAL", None)


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_memory_combined_hard_window_backpressure(seed, tmp_path):
    """Both ballasts at once: a forced host-hard window (the
    pressure.level seam, armed per-node through the fail_points hook —
    the deterministic stand-in for RSS ballast) OVER an arena overfill.
    While hard: the level propagates to the driver's Node view (so
    pick_node soft-excludes the victim), NEW puts reject with the typed
    retriable error, and reads still pass. Store-level puts ride
    RetryPolicy through the window; after relief every task and byte
    has converged and the level returns to ok."""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu.exceptions import MemoryPressureError

    os.environ["RAY_TPU_MEMORY_PRESSURE"] = "1"
    os.environ["RAY_TPU_PRESSURE_TICK_S"] = "0.1"
    os.environ["RAY_TPU_ARENA_SPILL_DIR"] = str(tmp_path)
    try:
        rt = ray_tpu.init(num_nodes=2, resources={"CPU": 4},
                          cluster="daemons",
                          object_store_memory=4 * 1024 * 1024)
        try:
            victim = _first_daemon(rt)
            _needs_arena(victim)
            node = rt.get_node(victim.node_id)

            # a pre-pressure object on the victim: reads must pass
            # through the whole hard window
            pre = ObjectID.from_random()
            node.store.put(pre, b"pre-pressure", nbytes=12)

            # ~4s of forced hard pressure on the victim only
            out = victim.client.call(
                "fail_points",
                spec="pressure.level=return(hard):max=40",
                seed=seed, timeout=5.0)
            assert out["active"]
            _wait_pressure(victim, "hard")

            # the level rode the push/gossip to the driver's Node view
            deadline = time.monotonic() + 10
            while (getattr(node, "pressure_level", "ok") != "hard"
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert node.pressure_level == "hard"

            # typed rejection of a NEW put; reads still pass
            with pytest.raises(MemoryPressureError):
                victim.put_object_blob(b"chaos:rejected", b"x" * 1024)
            assert node.store.get(pre) == b"pre-pressure"

            # tasks submitted DURING the window all converge (the other
            # node takes them; the fallback would run them regardless)
            @ray_tpu.remote(max_retries=2)
            def work(i):
                return i * 6

            refs = [work.remote(i) for i in range(12)]

            # arena overfill through the window: store-level puts ride
            # RetryPolicy across the hard ticks, then spill keeps every
            # one landing
            rng = __import__("random").Random(seed)
            oids = {}
            for i in range(6):
                oid = ObjectID.from_random()
                blob = bytes([rng.randrange(256)]) * (1 << 20)
                oids[oid] = blob
                node.store.put(oid, blob, nbytes=len(blob))
            assert ray_tpu.get(refs, timeout=120) == [
                i * 6 for i in range(12)]
            for oid, blob in oids.items():
                assert node.store.get(oid) == blob
            stats = _spill_stats(victim)
            assert stats["spills"] >= 1, stats

            # relief: the arm exhausts, the level converges to ok and
            # the driver's view follows
            _wait_pressure(victim, "ok", timeout=30.0)
            deadline = time.monotonic() + 10
            while (node.pressure_level != "ok"
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert node.pressure_level == "ok"
            assert ray_tpu.get(work.remote(50), timeout=60) == 300
        finally:
            ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_MEMORY_PRESSURE", None)
        os.environ.pop("RAY_TPU_PRESSURE_TICK_S", None)
        os.environ.pop("RAY_TPU_ARENA_SPILL_DIR", None)
